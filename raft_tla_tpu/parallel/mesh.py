"""Multi-device BFS: frontier data parallelism + fingerprint-ownership
partitioning (SURVEY §2.14).

The reference's engine-level parallelism is TLC's multi-worker BFS with
a partitioned fingerprint table (`-workers 8`).  The TPU-native
counterpart implemented here:

- the frontier, level buffer, parent arrays and the visited/level key
  sets all carry a leading device axis and live sharded over a 1-D
  ``jax.sharding.Mesh`` (``shard_map`` over axis "d");
- each device expands its frontier shard and fingerprints its enabled
  candidates (compute data parallelism);
- every candidate is then routed to its OWNER device — owner = low
  bits of the fingerprint — via ``jax.lax.all_to_all`` over ICI; the
  owner claim-inserts into its shard of the open-addressing visited
  table (engine/bfs._probe_insert: membership + first-seen dedup +
  insert in one probe walk), and appends fresh states to its level
  shard.  The dedup authority therefore lives on device and is
  partitioned by hash, exactly like TLC's worker-local fingerprint
  table partitions, with the all-to-all exchange riding ICI instead
  of shared memory;
- because ownership is hash-uniform, the next frontier (the level
  buffer, swapped in place) is automatically load-balanced.

Global state ids are assigned device-major per level: device d's rows
get ids ``g_base + prefix[d] + row`` where ``prefix`` is the exclusive
cumsum of the per-device level counts (computed on device with an
``all_gather``).  The host reads ONE packed per-level scalar matrix.

Determinism (cf. TLC's multi-worker mode, improved — VERDICT r3 #6):
the surviving representative among equal-VIEW-fingerprint candidates
(whose non-VIEW history counters feed constraint pruning and scenario
predicates downstream) is CONTENT-CANONICAL — the lexicographic
minimum of the packed non-VIEW lanes over the whole level's candidate
multiset, implemented as a per-window min-content reduction plus
replace-if-smaller on same-level duplicate hits (`lrow` slot map).
Because the min is over the level's candidate multiset — which is
itself determined by the previous level's rows — the reachable set and
all counts are, by induction, a pure function of the model, identical
for EVERY mesh size, chunk size and all_to_all window packing
(tests/test_sharded.py::test_sharded_reference_cfg_full_constraints
pins D=4 ≡ D=8 at depth 16 under the full counter-dependent
constraint set).  TLC's multi-worker mode is run-to-run
nondeterministic here; our single-device engines keep TLC's
SEQUENTIAL first-seen policy (= the oracle).  The two policies may in
principle pick different representatives — measured on the reference
cfg micro-bounds at depth 16, content-min agrees with the oracle
exactly (82,771 distinct; the arrival-rank scheme it replaced
measured 82,751) — and each is deterministic and explores a sound
constraint semantics.  Witness provenance is mesh-invariant too
(VERDICT r4 #9): among equal-content candidates the canonical min
extends to (parent fingerprint, lane) — the parent's FINGERPRINT, not
its global id, because gids are assigned device-major and therefore
differ across mesh shapes while the fingerprint is a pure function of
the parent's content.  A violation trace reproduced on D=4 is
action-by-action identical to the D=8 trace
(tests/test_sharded.py::test_sharded_trace_mesh_invariant).
"""

from __future__ import annotations

import time
from functools import partial
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:                                    # jax >= 0.6
    from jax import shard_map

    def _shard_map(f, mesh, in_specs, out_specs):
        return shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)
except ImportError:                     # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

    def _shard_map(f, mesh, in_specs, out_specs):
        return shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)

from ..config import ModelConfig
from ..obs import NULL_OBS
from ..engine import driver
from ..engine.bfs import (CheckResult, Engine, U32MAX, Violation, _cat,
                          _take, ckpt_archives, ckpt_carry, ckpt_read,
                          ckpt_result, ckpt_write)
from ..engine.host_table import insert_np
from ..ops.codec import C_OVERFLOW
from ..resil.chaos import chaos_point

# sharded checkpoint format gate (shared with MultiHostEngine):
# format 2 added the content-canonical lrow table (round 4); format 3
# added the mesh-invariant provenance lpfp table (round 5); format 4
# replaced the pg_off arithmetic with the gids table and added
# trip_base (round 5, the spill-composed engine).  Older checkpoints
# fail here with a version message instead of a missing-leaf error
# deep in ckpt_carry.
_SHARDED_CKPT_FORMAT = 4
_SHARDED_FMT = ("ckpt_format", _SHARDED_CKPT_FORMAT,
                "the carry replaced pg_off with the gids table and "
                "gained trip_base")

# warn-once latch for uneven user chunk overrides (per process, like
# any stacklevel warning filter — the mesh size doesn't change mid-run)
_warned_uneven_chunk = False


def _round_chunk_to_devices(chunk: int, n_devices: int) -> int:
    """Round ``chunk`` up to the next multiple of the mesh size.

    The mesh engines shard the frontier chunk/D rows per device, so
    the per-device row count must divide evenly.  Defaults (512, 2048)
    already divide every power-of-two pod slice; a user override that
    doesn't is rounded up (never down — capacities are sized FROM the
    chunk) with a one-time warning naming both numbers."""
    d = max(1, int(n_devices))
    rem = int(chunk) % d
    if rem == 0:
        return int(chunk)
    rounded = int(chunk) + (d - rem)
    global _warned_uneven_chunk
    if not _warned_uneven_chunk:
        _warned_uneven_chunk = True
        import warnings
        warnings.warn(
            f"chunk {chunk} is not a multiple of the {d}-device mesh; "
            f"rounded up to {rounded} ({rounded // d} frontier rows "
            "per device)", stacklevel=3)
    return rounded


class ShardedEngine(Engine):
    """Engine whose full BFS runs sharded over a device mesh with
    hash-ownership-partitioned visited/level key sets.

    chunk — GLOBAL frontier states expanded per step (chunk/D per
    device); rounded up to a multiple of the mesh size
    (_round_chunk_to_devices — uneven overrides warn once)."""

    def __init__(self, cfg: ModelConfig, devices=None, chunk: int = 512,
                 store_states: bool = True,
                 lcap: int = 1 << 14, vcap: int = 1 << 17,
                 fcap: Optional[int] = None, scap: Optional[int] = None,
                 burst: bool = True,
                 burst_levels: Optional[int] = None,
                 guard_matmul: bool = True,
                 dedup_kernel: str = "auto",
                 delta_matmul: bool = True,
                 fam_density=None,
                 sym_canon: str = "auto"):
        devices = devices if devices is not None else jax.devices()
        self.mesh = Mesh(np.array(devices), axis_names=("d",))
        self.D = len(devices)
        # pod-size-aware chunk: the frontier shards chunk/D rows per
        # device, so chunk rounds UP to the next multiple of the mesh
        # size instead of asserting — the default chunk then does the
        # right thing on any pod slice; an uneven user override warns
        # once (it was a deliberate number that no longer holds)
        chunk = _round_chunk_to_devices(chunk, self.D)
        self.BL = chunk // self.D              # frontier rows per device
        super().__init__(cfg, chunk=chunk, store_states=store_states,
                         lcap=lcap, vcap=vcap, fcap=fcap, burst=burst,
                         burst_levels=burst_levels,
                         guard_matmul=guard_matmul,
                         dedup_kernel=dedup_kernel,
                         delta_matmul=delta_matmul,
                         fam_density=fam_density,
                         sym_canon=sym_canon)
        # the sharded step computes full per-candidate fingerprints: the
        # incremental per-action path (engine/fingerprint) is not wired
        # into _local_step yet, so make the inherited flag's inertness
        # explicit rather than silently carrying it as True
        self.incremental_fp = False
        # per-device capacities.  VB (table shard slots) power of two
        # for mask indexing.
        self.FC = max(256, (self.FCAP + self.D - 1) // self.D)
        self.VB = 1 << max(12, int(np.ceil(np.log2(
            max(vcap // self.D, 2)))))
        # send capacity per (src, dst) pair; hash-uniform routing puts
        # ~FC/D candidates per destination — 4x headroom, growable
        self.SC = int(scap) if scap else max(256, 4 * self.FC // self.D)
        # the level shard must hold the D*SC receive window on top of
        # its usable capacity
        self.LB = self._round_lb(max(lcap // self.D, 4 * self.FC,
                                     2 * self.D * self.SC))
        # per-family materialization caps are per-DEVICE (chunk/D rows)
        self.FAM_CAPS = tuple(self.expander.default_fam_caps(
            self.BL, self.fam_density))
        # step-atomic trip discipline: off here (whole-level journal
        # replay); the spill-composed subclass turns it on
        self._step_atomic = False
        # in-burst frontier policy: this engine keeps constraint-pruned
        # rows in place under fmask (prune-not-expand, engine/bfs);
        # the spill-composed subclass compacts them away at each burst
        # level commit, because its HOST path drops pruned rows before
        # re-upload — the window packing (and so the level shards' row
        # order and gid assignment) must match the un-bursted path
        # exactly
        self._burst_compact_frontier = False
        # appended rows' fingerprints ride the level shard (lkey) only
        # when the spill-composed subclass runs its host-partitioned
        # table: they feed the per-device partition sweep + cache
        # reseed (parallel/spill_mesh; engine/host_table)
        self._track_keys = False
        self._level_jit = jax.jit(self._sharded_level_call,
                                  donate_argnums=0, static_argnums=1)
        # fused K-level driver (_shard_burst): the level program's body
        # inside one more while_loop, one stats matrix back per burst
        self._burst_mesh_jit = jax.jit(self._sharded_burst_call,
                                       donate_argnums=0,
                                       static_argnums=1)

    def _round_lb(self, n: int) -> int:
        b = self.BL
        return ((int(n) + b - 1) // b) * b

    # -----------------------------------------------------------------
    def _sharded_level_call(self, carry, fam_caps):
        specs = jax.tree_util.tree_map(lambda _: P("d"), carry)
        # scal is all-gathered on device and comes back REPLICATED so
        # every controller process can read the whole [D, 10+n_fams]
        # matrix without touching non-addressable shards (multi-host
        # safe)
        out_specs = (specs, dict(inv_ok=P("d"), scal=P(None)))
        return _shard_map(
            lambda c: self._shard_level(c, fam_caps), self.mesh,
            (specs,), out_specs)(carry)

    def _shard_level(self, carry, fam_caps):
        """Whole BFS level in one device call: while any device still
        has frontier rows and no device overflowed, run lock-step chunk
        steps (the all_to_all inside needs every device participating —
        drained shards keep stepping with all-invalid rows), then
        finalize.  The seed level (n_front=0 everywhere) skips straight
        to the finalize, so this is the ONLY shard_map program the
        engine compiles."""
        c = jax.tree_util.tree_map(lambda x: x[0], carry)

        def cond(c):
            more = c["base"] < c["n_front"]
            bad = c["ovf"] | c["fovf"] | c["sovf"] | c["hovf"]
            flags = jax.lax.all_gather(jnp.stack([more, bad]), "d")
            return flags[:, 0].any() & ~flags[:, 1].any()

        c = lax.while_loop(cond, lambda cc: self._local_step(cc, fam_caps),
                           c)
        new_c, out = self._local_finalize(c)
        return (jax.tree_util.tree_map(lambda x: x[None], new_c),
                dict(inv_ok=out["inv_ok"][None], scal=out["scal"]))

    # -----------------------------------------------------------------
    # per-device chunk step (runs inside _shard_level's while_loop; all
    # leaves are the local shard, device axis stripped)
    # -----------------------------------------------------------------

    def _local_step(self, c, fam_caps):
        B, A, W, D = self.BL, self.A, self.W, self.D
        # capacities derive from carry shapes so growth always retraces
        # (fam_caps rides as a static jit arg instead)
        FC = c["cidx"].shape[0]
        SC = c["sscr"].shape[0]
        LB = c["fmask"].shape[0]
        N = B * A
        M = D * SC                     # received candidates per step
        base = c["base"]
        # frontier shards are stored narrow; widen the chunk for kernels
        sv = self.ir.widen({k: lax.dynamic_slice_in_dim(v, base, B)
                    for k, v in c["front"].items()})
        fmask = lax.dynamic_slice_in_dim(c["fmask"], base, B)
        # guard-first expansion (engine/bfs chunk-step twin).  The
        # expander APIs are batch-LAST; this engine keeps its shard
        # buffers batch-major and transposes at the boundary (the
        # virtual-CPU test mesh doesn't care about TPU tiling).
        svT = {k: jnp.moveaxis(v, 0, -1) for k, v in sv.items()}
        derT = self.expander.derived_batch_T(svT)
        ok = lax.optimization_barrier(self.expander.guards_T(svT, derT))
        valid = ((base + jnp.arange(B, dtype=jnp.int32)) <
                 c["n_front"]) & fmask
        okf = (ok & valid[:, None]).reshape(N)

        # compact enabled lanes, materialize, fingerprint them
        idx = jnp.arange(N, dtype=jnp.int32)
        epos = jnp.where(okf, jnp.cumsum(okf.astype(jnp.int32)) - 1, FC)
        n_e = okf.sum(dtype=jnp.int32)
        eidx = lax.optimization_barrier(
            jnp.full((FC,), N, jnp.int32).at[epos].set(idx, mode="drop"))
        cand_T, famx = self.expander.materialize(
            svT, derT, okf, epos, FC, fam_caps)
        cand_c = lax.optimization_barrier(
            {k: jnp.moveaxis(v, -1, 0) for k, v in cand_T.items()})
        famx = jnp.maximum(c["famx"], famx)
        fovf = c["fovf"] | (n_e > FC) | \
            jnp.any(famx > jnp.asarray(fam_caps, jnp.int32))
        elive = jnp.arange(FC, dtype=jnp.int32) < n_e
        take = jnp.clip(eidx, 0, N - 1)
        if self.act_names:
            par_c = {k: v[take // A] for k, v in sv.items()}
            act = jax.vmap(self._act_ok)(par_c, cand_c)
            elive = elive & act
        gen_inc = elive.sum(dtype=jnp.int32)
        fp = lax.optimization_barrier(
            self.fpr.fingerprint_batch(cand_c))            # [FC, W]
        # parent global ids come from the per-row gids table (the
        # commit finalize refreshes it; the spill-composed engine
        # uploads host-compacted frontiers where arithmetic ids are
        # impossible)
        pgid = c["gids"][base + take // A]
        lane = take % A
        # parent fingerprints, for mesh-invariant provenance (module
        # docstring): the canonical tiebreak among equal-content
        # candidates must not use pgid — global ids are device-major
        # and mesh-shape dependent — so the parent's content hash rides
        # along instead (B extra hashes per step vs FC candidate ones)
        pfp_par = self.fpr.fingerprint_batch(sv)           # [B, W]
        pfp = pfp_par[take // A]                           # [FC, W]

        # ---- route to owner device (hash-ownership, SURVEY §2.14) ----
        owner = jnp.where(elive, (fp[:, W - 1] % D).astype(jnp.int32), D)
        slot = jnp.arange(FC, dtype=jnp.int32)
        o_s, slot_s = lax.optimization_barrier(
            lax.sort((owner, slot), num_keys=2))
        counts = jnp.sum(o_s[None, :] == jnp.arange(D)[:, None],
                         axis=1)                            # [D]
        starts = jnp.cumsum(counts) - counts
        rank = jnp.arange(FC, dtype=jnp.int32) - \
            starts[jnp.clip(o_s, 0, D - 1)]
        live_s = o_s < D
        sovf = c["sovf"] | jnp.any(live_s & (rank >= SC))
        dest = jnp.where(live_s & (rank < SC),
                         o_s * SC + jnp.clip(rank, 0, SC - 1), M)
        # inverse map: send slot -> local candidate slot
        sidx = lax.optimization_barrier(
            jnp.full((M,), FC, jnp.int32).at[dest].set(
                slot_s, mode="drop"))
        sfill = jnp.zeros((M,), bool).at[dest].set(live_s, mode="drop")
        stake = jnp.clip(sidx, 0, FC - 1)
        send_key = tuple(jnp.where(sfill, fp[stake, w], U32MAX)
                         for w in range(W))
        # rows ride the ICI all_to_all in storage dtypes (2-3x fewer
        # interconnect bytes than the kernels' int32 rows)
        send_row = self.ir.narrow(self.lay, {k: v[stake]
                                     for k, v in cand_c.items()})
        send_pgid = jnp.where(sfill, pgid[stake], -1)
        send_lane = jnp.where(sfill, lane[stake], -1)
        send_pfp = jnp.where(sfill[:, None], pfp[stake], U32MAX)
        (send_key, send_row, send_pgid, send_lane, send_pfp) = \
            lax.optimization_barrier(
                (send_key, send_row, send_pgid, send_lane, send_pfp))

        a2a = partial(lax.all_to_all, axis_name="d", split_axis=0,
                      concat_axis=0, tiled=True)
        recv_key = tuple(a2a(kw) for kw in send_key)        # [M] each
        recv_row = {k: a2a(v) for k, v in send_row.items()}
        recv_pgid = a2a(send_pgid)
        recv_lane = a2a(send_lane)
        recv_pfp = a2a(send_pfp)                            # [M, W]

        # ---- owner-side dedup: claim-insert into the table shard ----
        VB = c["vis"][0].shape[0]
        recv_live = jnp.zeros(M, bool)
        for w in range(W):
            recv_live = recv_live | (recv_key[w] != U32MAX)
        # include the CURRENT step's fovf/sovf (not just prior-step
        # flags): a step that overflowed its compaction or send buffer
        # is doomed to replay, so its claim-inserts are wasted writes
        if self._step_atomic:
            # spill-composed mode (parallel/spill_mesh): a tripping
            # step must commit on NO device — the host resumes from
            # the tripped step after spilling/growing, and there is no
            # whole-level journal rollback once shard contents have
            # spilled to host.  One tiny all_gather makes the
            # pre-insert trip decision global.
            pre_bad = jax.lax.all_gather(fovf | sovf, "d").any()
        else:
            pre_bad = fovf | sovf
        gate = ~(c["ovf"] | pre_bad | c["hovf"])

        # ---- content-canonical survivor, stage 1 (VERDICT r3 #6) ----
        # The admitted representative among equal-fingerprint candidates
        # is the one with the lexicographically SMALLEST non-VIEW
        # content (history counters + feature lanes), not the first
        # arrival: stage 1 reduces each receive window to one
        # min-content candidate per key (sort by key, then content);
        # stage 2 after the append replaces a row admitted by an
        # earlier window of the SAME level when a smaller-content
        # duplicate arrives.  Together they make the survivor the
        # content-min over the whole level's candidate multiset — see
        # the module docstring's determinism contract.
        def content_words(rows_nv):
            ws = []
            for k in self.ir.nonview_keys:
                v = rows_nv[k].astype(jnp.int32).reshape(M, -1)
                for ci in range(v.shape[1]):
                    ws.append(v[:, ci].astype(jnp.uint32)
                              ^ jnp.uint32(0x80000000))
            return ws

        cwords = content_words(recv_row)
        # provenance words extend the canonical key (module docstring):
        # among equal (key, content) candidates the rep is the one with
        # the smallest (parent fingerprint, lane) — mesh-invariant,
        # unlike arrival order.  -1 lanes cast to 0xFFFFFFFF and sort
        # last, so invalid rows never win a run.
        pwords = [recv_pfp[:, w] for w in range(W)] + \
            [recv_lane.astype(jnp.uint32)]
        ops = list(recv_key) + cwords + pwords + \
            [jnp.arange(M, dtype=jnp.uint32)]
        srt = lax.sort(tuple(ops), num_keys=len(ops))
        s_idx = srt[-1].astype(jnp.int32)
        same_prev = jnp.ones((M - 1,), bool)
        for w in range(W):
            same_prev = same_prev & (srt[w][1:] == srt[w][:-1])
        first_run = jnp.concatenate([jnp.ones((1,), bool), ~same_prev])
        rep = jnp.zeros((M,), bool).at[s_idx].set(first_run)
        live_rep = recv_live & rep & gate

        ranks = jnp.arange(M, dtype=jnp.uint32)
        table, claims, fresh, pos, hv = self._probe_insert(
            c["vis"], c["claims"], recv_key, live_rep, ranks)
        hovf = c["hovf"] | hv
        n_fresh = fresh.sum(dtype=jnp.int32)
        ovf_now = c["n_lvl"] + n_fresh > LB - M
        if self._step_atomic:
            # spill-composed mode: revert on EVERY device when ANY
            # device tripped, so the tripped step commits nowhere and
            # the host can resume from trip_base exactly
            bad_now = pre_bad | jax.lax.all_gather(ovf_now | hv,
                                                   "d").any()
            stepped = ~(c["ovf"] | c["fovf"] | c["sovf"] | c["hovf"])
            trip_base = jnp.where(stepped & bad_now, base,
                                  c["trip_base"])
        else:
            # classic mode: local revert; the whole-level journal
            # rollback at finalize handles cross-device consistency
            bad_now = ovf_now
            trip_base = c["trip_base"]
        # level shard would overflow: revert this step's inserts and
        # skip the append (the level replays; see engine/bfs)
        ridx2 = jnp.where(fresh & bad_now, pos, VB)
        table = tuple(table[w].at[ridx2].set(U32MAX, mode="drop")
                      for w in range(W))
        fresh = fresh & ~bad_now
        n_fresh = jnp.where(bad_now, 0, n_fresh)
        ovf = c["ovf"] | ovf_now
        if self._step_atomic:
            # a tripped step replays from trip_base: count its
            # generated successors only when it commits
            n_gen = c["n_gen"] + jnp.where(gate & ~bad_now, gen_inc, 0)
        else:
            # classic mode: the whole-level replay resets n_gen at the
            # finalize, so the unconditional count is exact
            n_gen = c["n_gen"] + gen_inc

        ridx = jnp.arange(M, dtype=jnp.int32)
        lpos = jnp.where(fresh,
                         jnp.cumsum(fresh.astype(jnp.int32)) - 1, M)
        lidx = lax.optimization_barrier(
            jnp.zeros((M,), jnp.int32).at[lpos].set(ridx, mode="drop"))

        start = jnp.minimum(c["n_lvl"], LB - M)
        rows = lax.optimization_barrier(
            {k: recv_row[k][lidx] for k in recv_row})   # narrow
        # invariants/constraints for every window row: the appended
        # block reads them through lidx; stage-2 replacements read
        # their own lane (counter-reading scenario predicates must
        # re-evaluate on the surviving representative's content)
        inv_all, con_all = lax.optimization_barrier(
            self._phase2_impl(self.ir.widen(recv_row)))
        inv, con = inv_all[lidx], con_all[lidx]
        lvl = {k: lax.dynamic_update_slice_in_dim(v, rows[k], start, 0)
               for k, v in c["lvl"].items()}
        extra = {}
        if self._track_keys:
            # the appended rows' dedup keys (stage-2 replacements swap
            # content behind the SAME key, so no update there)
            rkey = jnp.stack(recv_key, axis=-1)            # [M, W]
            extra["lkey"] = lax.dynamic_update_slice(
                c["lkey"], rkey[lidx], (start, 0))
        lpar = lax.dynamic_update_slice_in_dim(
            c["lpar"], recv_pgid[lidx], start, 0)
        llane = lax.dynamic_update_slice_in_dim(
            c["llane"], recv_lane[lidx], start, 0)
        lpfp = lax.dynamic_update_slice(
            c["lpfp"], recv_pfp[lidx], (start, 0))
        jslot = lax.dynamic_update_slice_in_dim(
            c["jslot"], pos[lidx], start, 0)
        linv = lax.dynamic_update_slice(c["linv"], inv, (start, 0))
        lcon = lax.dynamic_update_slice_in_dim(c["lcon"], con, start, 0)

        # ---- content-canonical survivor, stage 2: replace-if-smaller
        # for duplicates of keys admitted by an EARLIER window of this
        # level.  lrow maps table slot -> level row for this level's
        # inserts (reset to -1 at every level boundary/replay).  Rows
        # are disjoint across lanes (one rep per key per window), so
        # the scatters race-free; a replaced row keeps its jslot.
        # The comparison key is (content, parent fp, lane) — the same
        # extended canonical key stage 1 uses, so the level-wide min
        # covers provenance too (mesh-invariant witness traces).
        lrow = c["lrow"].at[jnp.where(fresh, pos, VB)].set(
            (start + lpos).astype(jnp.int32), mode="drop")
        dup = live_rep & ~fresh & ~bad_now
        tgt = lrow[jnp.clip(pos, 0, VB - 1)]
        dup = dup & (tgt >= 0)
        tgt_c = jnp.clip(tgt, 0, LB - 1)
        swords = content_words({k: lvl[k][tgt_c] for k in lvl}) + \
            [lpfp[tgt_c, w] for w in range(W)] + \
            [llane[tgt_c].astype(jnp.uint32)]
        cand_words = cwords + pwords
        less = jnp.zeros((M,), bool)
        eq = jnp.ones((M,), bool)
        for cw, sw in zip(cand_words, swords):
            less = less | (eq & (cw < sw))
            eq = eq & (cw == sw)
        repl = dup & less
        widx2 = jnp.where(repl, tgt_c, LB)
        lvl = {k: v.at[widx2].set(recv_row[k], mode="drop")
               for k, v in lvl.items()}
        lpar = lpar.at[widx2].set(recv_pgid, mode="drop")
        llane = llane.at[widx2].set(recv_lane, mode="drop")
        lpfp = lpfp.at[widx2].set(recv_pfp, mode="drop")
        linv = linv.at[widx2].set(inv_all, mode="drop")
        lcon = lcon.at[widx2].set(con_all, mode="drop")
        return dict(c, vis=table, claims=claims, lvl=lvl, lpar=lpar,
                    llane=llane, lpfp=lpfp, jslot=jslot, linv=linv,
                    lcon=lcon, lrow=lrow, **extra,
                    n_lvl=jnp.minimum(c["n_lvl"] + n_fresh, LB - M),
                    n_gen=n_gen, ovf=ovf, fovf=fovf, sovf=sovf,
                    hovf=hovf, famx=famx, trip_base=trip_base,
                    base=base + B)

    # -----------------------------------------------------------------

    def _local_finalize(self, c):
        LB = c["fmask"].shape[0]
        VB = c["vis"][0].shape[0]
        n_lvl = c["n_lvl"]
        bad_local = c["ovf"] | c["fovf"] | c["sovf"] | c["hovf"]
        # any device overflowing aborts the level everywhere
        bad = jax.lax.all_gather(bad_local, "d").any()
        validrow = jnp.arange(LB, dtype=jnp.int32) < n_lvl
        inv_ok = (c["linv"] | ~validrow[:, None]
                  if self.inv_names else c["linv"])
        con = c["lcon"]
        n_viol = (~inv_ok).sum(dtype=jnp.int32)
        faults = ((c["lvl"]["ctr"][:, C_OVERFLOW] > 0) &
                  validrow).sum(dtype=jnp.int32)

        # device-major global ids for this level
        nl_vec = jax.lax.all_gather(n_lvl, "d")             # [D]
        prefix = jnp.cumsum(nl_vec) - nl_vec
        d_idx = jax.lax.axis_index("d")
        total = nl_vec.sum()

        def commit(c):
            # the level's keys are already in the table shard; the
            # swapped-in frontier rows' global ids are device-major
            # arithmetic, materialized into the gids table here so the
            # step can read ids uniformly (host-compacted frontiers in
            # the spill-composed engine upload theirs instead)
            fmask = con & validrow
            gids = c["g_off"] + prefix[d_idx] + \
                jnp.arange(LB, dtype=jnp.int32)
            return (c["lvl"], c["front"], fmask, n_lvl, c["vis"],
                    gids, c["g_off"] + total)

        def abandon(c):
            # roll the table shard back via the journal (engine/bfs
            # _probe_insert rollback note)
            cidx = jnp.where(validrow, c["jslot"], VB)
            vis = tuple(c["vis"][w].at[cidx].set(U32MAX, mode="drop")
                        for w in range(self.W))
            return (c["front"], c["lvl"], c["fmask"], c["n_front"],
                    vis, c["gids"], c["g_off"])

        front, lvl, fmask, n_front, vis, gids, g_next = lax.cond(
            bad, abandon, commit, c)
        # [D, 10+n_fams] replicated via all_gather so every controller
        # process reads the full matrix (multi-host safe; out_specs
        # P(None)); the famx tail drives per-family cap growth
        scal = jax.lax.all_gather(jnp.concatenate([jnp.stack([
            n_lvl, n_viol, faults, n_front,
            c["ovf"].astype(jnp.int32), c["fovf"].astype(jnp.int32),
            c["n_gen"], (con & validrow).sum(dtype=jnp.int32),
            c["sovf"].astype(jnp.int32), c["hovf"].astype(jnp.int32)]),
            c["famx"]]), "d")
        new_c = dict(c, vis=vis, front=front, lvl=lvl,
                     fmask=fmask, n_front=n_front,
                     n_lvl=jnp.int32(0), n_gen=jnp.int32(0),
                     ovf=jnp.bool_(False), fovf=jnp.bool_(False),
                     sovf=jnp.bool_(False), hovf=jnp.bool_(False),
                     famx=jnp.zeros_like(c["famx"]),
                     # slot->level-row map is per-level (commit moves to
                     # the next level; abandon replays this one)
                     lrow=jnp.full_like(c["lrow"], -1),
                     trip_base=jnp.int32(-1),
                     base=jnp.int32(0), gids=gids, g_off=g_next)
        return new_c, dict(inv_ok=inv_ok, scal=scal)

    # -----------------------------------------------------------------
    # fused K-level driver (the mesh twin of engine/bfs._burst_core):
    # the _shard_level body — lock-step chunk steps over all_to_all —
    # becomes the body of ONE MORE while_loop, committing one level per
    # iteration inside the SAME shard_map program, with the per-level
    # all_gather id-assignment kept in-loop and ONE packed
    # [D, L_MAX+1, n_scalars] stats matrix read back per burst.  A
    # shard_map dispatch + scalar sync per level is "genuinely
    # expensive" (bfs.py finalize note) — this removes all but one of
    # them for runs of small levels.
    #
    # Archive discipline: per-level parent/lane/state/inv rows are
    # copied into [L_MAX, KBd]-wide ring buffers, KBd =
    # min(_burst_chunks * BL, LB) rows per shard; a level whose shard
    # outgrows KBd — or that trips ANY overflow — is abandoned via the
    # whole-level journal rollback (_local_finalize's abandon,
    # replicated here) and replayed by the per-level path.  The
    # loop-carried state adds only the ring archives on top of what
    # _shard_level already loop-carries.
    # -----------------------------------------------------------------

    def _mesh_burst_width(self) -> int:
        """Per-shard burst ring rows (the host entry gate compares the
        per-device frontier max against this)."""
        return min(self._burst_chunks * self.BL, self.LB)

    def _sharded_burst_call(self, carry, fam_caps, levels_left,
                            states_cap):
        specs = jax.tree_util.tree_map(lambda _: P("d"), carry)
        st_specs = {k: P("d") for k in carry["lvl"]}
        out_specs = (specs, dict(stats=P(None), par=P("d"),
                                 lane=P("d"), st=st_specs,
                                 inv=P("d")))
        return _shard_map(
            lambda c, ll, sc: self._shard_burst(c, fam_caps, ll, sc),
            self.mesh, (specs, P(), P()), out_specs)(
                carry, levels_left, states_cap)

    def _shard_burst(self, carry, fam_caps, levels_left, states_cap):
        c0 = jax.tree_util.tree_map(lambda x: x[0], carry)
        LB = c0["fmask"].shape[0]
        VB = c0["vis"][0].shape[0]
        KBd = self._mesh_burst_width()
        L_MAX = self.burst_levels
        n_inv = len(self.inv_names)
        d_idx = jax.lax.axis_index("d")

        st = dict(
            c=c0, li=jnp.int32(0), done=jnp.int32(0),
            bail=jnp.bool_(False), viol=jnp.bool_(False),
            stats=jnp.zeros((L_MAX, self._BS_N), jnp.int32),
            opar=jnp.full((L_MAX, KBd), -1, jnp.int32),
            olane=jnp.full((L_MAX, KBd), -1, jnp.int32),
            ost={k: jnp.zeros((L_MAX, KBd) + v.shape[1:], v.dtype)
                 for k, v in c0["lvl"].items()},
            oinv=jnp.ones((L_MAX, KBd, n_inv), bool),
        )

        def cond(st):
            # every operand is replicated (derived from all_gathers),
            # so the decision is uniform across the mesh
            more = jax.lax.all_gather(st["c"]["n_front"] > 0,
                                      "d").any()
            return (~st["bail"] & ~st["viol"]
                    & (st["li"] < levels_left) & more
                    & (st["done"] < states_cap))

        def body(st):
            def chunk_cond(cc):
                more = cc["base"] < cc["n_front"]
                bad = cc["ovf"] | cc["fovf"] | cc["sovf"] | cc["hovf"]
                flags = jax.lax.all_gather(jnp.stack([more, bad]), "d")
                return flags[:, 0].any() & ~flags[:, 1].any()

            c = lax.while_loop(
                chunk_cond, lambda cc: self._local_step(cc, fam_caps),
                st["c"])
            n_lvl = c["n_lvl"]
            bad = jax.lax.all_gather(
                c["ovf"] | c["fovf"] | c["sovf"] | c["hovf"] |
                (n_lvl > KBd), "d").any()
            validrow = jnp.arange(LB, dtype=jnp.int32) < n_lvl
            inv_ok = (c["linv"] | ~validrow[:, None]
                      if n_inv else c["linv"])
            con = c["lcon"]
            n_viol = (~inv_ok).sum(dtype=jnp.int32)
            faults = ((c["lvl"]["ctr"][:, C_OVERFLOW] > 0) &
                      validrow).sum(dtype=jnp.int32)
            n_expand = (con & validrow).sum(dtype=jnp.int32)
            nl_vec = jax.lax.all_gather(n_lvl, "d")
            prefix = jnp.cumsum(nl_vec) - nl_vec
            total = nl_vec.sum()
            viol_g = jax.lax.all_gather(n_viol, "d").sum() > 0
            gen_l = c["n_gen"]
            li = st["li"]

            def commit(op):
                c, opar, olane, ost, oinv = op
                opar = lax.dynamic_update_slice(
                    opar, c["lpar"][:KBd][None], (li, 0))
                olane = lax.dynamic_update_slice(
                    olane, c["llane"][:KBd][None], (li, 0))
                ost = {k: lax.dynamic_update_slice(
                           ost[k], c["lvl"][k][:KBd][None],
                           (li,) + (0,) * (ost[k].ndim - 1))
                       for k in ost}
                oinv = lax.dynamic_update_slice(
                    oinv, inv_ok[:KBd][None], (li, 0, 0))
                gids_all = c["g_off"] + prefix[d_idx] + \
                    jnp.arange(LB, dtype=jnp.int32)
                if self._burst_compact_frontier:
                    # spill-composed mode: drop pruned rows from the
                    # next frontier on device, exactly as the host does
                    # between levels (archives above keep ALL rows) —
                    # the window packing, and so every later level's
                    # row order and gids, must match the un-bursted
                    # path bit-for-bit
                    keep = con & validrow
                    n_keep = keep.sum(dtype=jnp.int32)
                    kpos = jnp.where(
                        keep,
                        jnp.cumsum(keep.astype(jnp.int32)) - 1, LB)
                    kidx = jnp.zeros((LB,), jnp.int32).at[kpos].set(
                        jnp.arange(LB, dtype=jnp.int32), mode="drop")
                    front = {k: c["lvl"][k][kidx] for k in c["lvl"]}
                    inrange = jnp.arange(LB, dtype=jnp.int32) < n_keep
                    gids = jnp.where(inrange, gids_all[kidx], -1)
                    fmask = inrange
                    n_front = n_keep
                else:
                    front = c["lvl"]
                    gids = gids_all
                    fmask = con & validrow
                    n_front = n_lvl
                new_c = dict(c, front=front, lvl=c["front"],
                             fmask=fmask, n_front=n_front, gids=gids,
                             g_off=c["g_off"] + total,
                             n_lvl=jnp.int32(0), n_gen=jnp.int32(0),
                             famx=jnp.zeros_like(c["famx"]),
                             lrow=jnp.full_like(c["lrow"], -1),
                             trip_base=jnp.int32(-1),
                             base=jnp.int32(0))
                return new_c, opar, olane, ost, oinv

            def abandon(op):
                # whole-level journal rollback on every shard (the
                # burst never spills mid-level, so the journal is the
                # exact record of this level's inserts — the per-level
                # path replays the level from the intact frontier)
                c, opar, olane, ost, oinv = op
                cidx = jnp.where(validrow, c["jslot"], VB)
                vis = tuple(
                    c["vis"][w].at[cidx].set(U32MAX, mode="drop")
                    for w in range(self.W))
                new_c = dict(c, vis=vis,
                             n_lvl=jnp.int32(0), n_gen=jnp.int32(0),
                             ovf=jnp.bool_(False),
                             fovf=jnp.bool_(False),
                             sovf=jnp.bool_(False),
                             hovf=jnp.bool_(False),
                             famx=jnp.zeros_like(c["famx"]),
                             lrow=jnp.full_like(c["lrow"], -1),
                             trip_base=jnp.int32(-1),
                             base=jnp.int32(0))
                return new_c, opar, olane, ost, oinv

            c2, opar, olane, ost, oinv = lax.cond(
                bad, abandon, commit,
                (c, st["opar"], st["olane"], st["ost"], st["oinv"]))
            row = jnp.where(
                bad, jnp.zeros((self._BS_N,), jnp.int32),
                jnp.stack([n_lvl, n_viol, faults, n_expand, gen_l,
                           jnp.int32(0), jnp.int32(0), jnp.int32(0)]))
            new = dict(st, c=c2, opar=opar, olane=olane, ost=ost,
                       oinv=oinv)
            new["stats"] = lax.dynamic_update_slice(
                st["stats"], row[None], (li, 0))
            new["li"] = li + (~bad).astype(jnp.int32)
            new["bail"] = st["bail"] | bad
            new["viol"] = st["viol"] | (~bad & viol_g)
            new["done"] = st["done"] + jnp.where(bad, 0, total)
            return new

        st = lax.while_loop(cond, body, st)
        meta = jnp.stack([st["li"], st["bail"].astype(jnp.int32),
                          st["c"]["n_front"],
                          st["viol"].astype(jnp.int32), st["done"],
                          jnp.int32(0), jnp.int32(0), jnp.int32(0)])
        stats = jnp.concatenate([st["stats"], meta[None]], axis=0)
        sg = jax.lax.all_gather(stats, "d")     # [D, L_MAX+1, NS]
        return (jax.tree_util.tree_map(lambda x: x[None], st["c"]),
                dict(stats=sg, par=st["opar"][None],
                     lane=st["olane"][None],
                     st={k: v[None] for k, v in st["ost"].items()},
                     inv=st["oinv"][None]))

    # -----------------------------------------------------------------

    def _fresh_sharded_carry(self):
        D, LB, VB, FC = self.D, self.LB, self.VB, self.FC
        one = self.ir.narrow(self.lay, self.ir.encode(
            self.lay, *self.ir.init_state(self.cfg)))
        zeros = {k: jnp.zeros((D, LB) + v.shape, dtype=v.dtype)
                 for k, v in one.items()}
        n_inv = len(self.inv_names)
        extra = {}
        if self._track_keys:
            extra["lkey"] = jnp.full((D, LB, self.W), U32MAX)
        return dict(
            **extra,
            vis=tuple(jnp.full((D, VB), U32MAX) for _ in range(self.W)),
            claims=jnp.full((D, VB), U32MAX),
            # table slot -> this-level row (content-canonical stage 2)
            lrow=jnp.full((D, VB), -1, jnp.int32),
            jslot=jnp.full((D, LB), -1, jnp.int32),
            linv=jnp.ones((D, LB, n_inv), bool),
            lcon=jnp.ones((D, LB), bool),
            lvl=zeros,
            lpar=jnp.full((D, LB), -1, jnp.int32),
            llane=jnp.full((D, LB), -1, jnp.int32),
            # per-row parent fingerprint: the mesh-invariant half of
            # the provenance key (stage-2 comparisons read it back)
            lpfp=jnp.full((D, LB, self.W), U32MAX),
            # per-frontier-row global ids (refreshed by the commit
            # finalize; uploaded by the spill-composed engine)
            gids=jnp.full((D, LB), -1, jnp.int32),
            trip_base=jnp.full((D,), -1, jnp.int32),
            cidx=jnp.zeros((D, FC), jnp.int32),
            # shape anchor for SC: jit caches on input avals, and SC
            # otherwise only shapes internal send/recv buffers — an SC
            # growth would silently cache-hit the stale trace
            sscr=jnp.zeros((D, self.SC), jnp.int32),
            n_lvl=jnp.zeros((D,), jnp.int32),
            n_gen=jnp.zeros((D,), jnp.int32),
            famx=jnp.zeros((D, len(self.expander.families)), jnp.int32),
            base=jnp.zeros((D,), jnp.int32),
            g_off=jnp.zeros((D,), jnp.int32),
            ovf=jnp.zeros((D,), bool),
            fovf=jnp.zeros((D,), bool),
            sovf=jnp.zeros((D,), bool),
            hovf=jnp.zeros((D,), bool),
            front={k: jnp.zeros_like(v) for k, v in zeros.items()},
            fmask=jnp.zeros((D, LB), bool),
            n_front=jnp.zeros((D,), jnp.int32),
        )

    # -----------------------------------------------------------------

    def check(self, max_depth: int = 10 ** 9, max_states: int = 10 ** 9,
              stop_on_violation: bool = False,
              seed_states: Optional[List] = None,
              checkpoint_path: Optional[str] = None,
              checkpoint_every: int = 1,
              resume_from: Optional[str] = None,
              resume_image=None,
              verbose: bool = False, obs=None) -> CheckResult:
        """``resume_image`` — a ``resil.portable.PortableImage``
        extracted from ANY engine family's checkpoint: the visited key
        set and frontier rows are re-partitioned onto THIS mesh by
        hash ownership, so a checkpoint written on a different device
        count (or by the spill/classic engines) resumes here
        (ROADMAP item-2 elastic resume)."""
        obs = self._obs = obs if obs is not None else NULL_OBS
        t0 = time.perf_counter()
        lay = self.lay
        D, W = self.D, self.W
        if resume_from is not None and resume_image is not None:
            raise ValueError(
                "resume_from and resume_image are mutually exclusive")
        if resume_from is not None:
            carry, res, meta = self._load_checkpoint(resume_from)
            n_states = meta["n_states"]
            n_vis = np.asarray(meta["n_vis"], dtype=np.int64)
            depth = meta["depth"]
            n_front = meta["n_front"]
            resumed = True
        elif resume_image is not None:
            (carry, res, n_states, n_vis, depth,
             n_front) = self._resume_portable(resume_image)
            resumed = True
        else:
            # shared root admission (engine/bfs._dedup_roots), then
            # this engine's extra step: hash-ownership routing
            roots, rk, pin_interiors = self._dedup_roots(seed_states)
            per_dev: List[List[int]] = [[] for _ in range(D)]
            for r in range(len(rk)):
                per_dev[int(rk[r, W - 1]) % D].append(r)
            # grow the level shard until the most-loaded device's seeds
            # fit with the receive-window margin (punctuated-search
            # seed sets can be thousands of states, hash-skewed across
            # devices)
            max_seed = max(len(p) for p in per_dev)
            while self.LB - self.D * self.SC < 2 * max_seed:
                self.LB = self._round_lb(2 * self.LB)
            while max_seed + self.LB > self._LOAD_MAX * self.VB:
                self.VB *= 4

            res = CheckResult(distinct_states=0,
                              generated_states=len(rk), depth=0)
            # replicated computation: every controller checks the same
            # interiors and takes identical violation counts
            self._check_pin_interiors(pin_interiors, res)
            self._states = []
            self._parents = []
            self._lanes = []
            self._arch_segs = []

            # root invariants/constraints (levels get theirs in the
            # step)
            inv_r, con_r = (np.asarray(a) for a in self._phase2(
                {k: jnp.asarray(v) for k, v in roots.items()}))

            carry_np = self._fresh_sharded_carry_host()
            nl = np.zeros((D,), np.int32)
            for d in range(D):
                for r, i in enumerate(per_dev[d]):
                    for k in roots:
                        carry_np["lvl"][k][d, r] = roots[k][i]
                    carry_np["lpar"][d, r] = -1
                    carry_np["llane"][d, r] = -1
                    carry_np["linv"][d, r] = inv_r[i]
                    carry_np["lcon"][d, r] = con_r[i]
                nl[d] = len(per_dev[d])
            # root global ids, device-major (the finalize commit swaps
            # lvl->front and recomputes gids the same way; seeding them
            # here keeps the seed finalize's abandon-path gids sane)
            pref = np.cumsum(nl) - nl
            for d in range(D):
                carry_np["gids"][d, :nl[d]] = pref[d] + \
                    np.arange(nl[d], dtype=np.int32)
                rkd = rk[per_dev[d]]                       # [n, W]
                # host-side probe placement into the empty table shard
                slots = self._host_probe_assign(rkd, vcap=self.VB)
                for r, sl in enumerate(slots):
                    for w in range(W):
                        carry_np["vis"][w][d, sl] = rkd[r, w]
                    carry_np["jslot"][d, r] = sl
            carry_np["n_lvl"] = nl
            carry = self._to_device(carry_np)

            n_states = 0
            n_vis = np.zeros((D,), np.int64)
            depth = 0
            resumed = False
        self._stamp_mode(res)

        def run_finalize(carry):
            # seed carries have n_front=0 everywhere, so the level
            # program skips straight to its finalize — no separate
            # finalize-only shard_map compile
            carry, out = self._level_jit(carry, self.FAM_CAPS)
            return carry, out, np.asarray(out["scal"])  # [D, 10+n_fams]

        def grow_table_if_needed(carry, min_add=0):
            # pessimistic per-shard load bound, checked between levels
            # (min_add: a burst can admit up to burst_levels ring-widths
            # per shard before its next host sync)
            need = int(n_vis.max()) + max(self.LB, min_add)
            if need > self._LOAD_MAX * self.VB:
                while need > self._LOAD_MAX * self.VB:
                    self.VB *= 4
                carry = self._rehash_sharded(carry)
            return carry

        def local_rows(arr):
            """[(d, np_row)] for the addressable device rows of a
            P('d')-sharded [D, ...] array — all rows on one host, only
            this process's rows under multi-controller."""
            rows = []
            for s in arr.addressable_shards:
                ix = s.index[0]
                d = (ix.start or 0) if isinstance(ix, slice) else ix
                rows.append((int(d), np.asarray(s.data)[0]))
            return sorted(rows, key=lambda t: t[0])

        def harvest(carry, out, scal):
            nonlocal n_states
            nl = scal[:, 0]
            n_lvl = int(nl.sum())
            res.distinct_states += n_lvl
            res.overflow_faults += int(scal[:, 2].sum())
            res.generated_states += int(scal[:, 6].sum())
            # global count from the replicated matrix: identical on
            # every controller (the violations LIST is shard-local)
            res.violations_global += int(scal[:, 1].sum())
            prefix = np.cumsum(nl) - nl
            rows = None
            if self.store_states or scal[:, 1].sum():
                # one device->host transfer of the front buffer, shared
                # by the state archive and violation decoding
                rows = {k: dict(local_rows(v))
                        for k, v in carry["front"].items()}
            if self.store_states:
                # archives cover this controller's shards (= everything
                # on one host; under MultiHostEngine each controller
                # archives its own devices and _arch_segs records which
                # (device, count) segments its per-level concatenation
                # holds, so per-controller archive files can be merged
                # device-major into the global id order at trace time)
                pars = local_rows(carry["lpar"])
                lns = dict(local_rows(carry["llane"]))
                self._parents.append(np.concatenate(
                    [row[:nl[d]] for d, row in pars]))
                self._lanes.append(np.concatenate(
                    [lns[d][:nl[d]] for d, _ in pars]))
                self._states.append(
                    {k: np.concatenate([rows[k][d][:nl[d]]
                                        for d, _ in pars])
                     for k in rows})
                self._arch_segs.append(
                    [(int(d), int(nl[d])) for d, _ in pars])
            if scal[:, 1].sum():
                inv_shards = local_rows(out["inv_ok"])
                for d, inv_ok in inv_shards:
                    for j, nm in enumerate(self.inv_names):
                        for s in np.nonzero(~inv_ok[:nl[d], j])[0]:
                            vsv, vh = self.ir.decode(lay, _take(
                                {k: rows[k][d] for k in rows}, s))
                            res.violations.append(Violation(
                                nm, n_states + int(prefix[d]) + int(s),
                                state=vsv, hist=vh))
            n_states += n_lvl
            for d in range(D):
                n_vis[d] += nl[d]
            # global state ids are device int32; fail loud, not wrap
            driver.guard_id_space(n_states)
            return int(scal[:, 3].max())

        if not resumed:
            carry, out, scal = run_finalize(carry)
            n_front = harvest(carry, out, scal)
        # decide from the REPLICATED count: every controller takes the
        # same branch (a process-local decision would deadlock the
        # mesh collectives under multi-controller runs)
        if stop_on_violation and res.violations_global:
            res.seconds = time.perf_counter() - t0
            return res

        # burst_ok: a burst that committed levels then bailed keeps the
        # bailing level's frontier intact — re-entering would replay
        # the identical lock-step chunks and bail again (one wasted
        # shard_map round trip); skip the burst for that one level
        burst_ok = True
        while n_front and depth < max_depth and \
                res.distinct_states < max_states:
            # chaos site: dispatch-time device/tunnel error at the
            # level boundary (resil/chaos) — before any device work,
            # so the last checkpoint stays the exact resume point
            chaos_point("dispatch")
            kbd = self._mesh_burst_width()
            if self.burst and burst_ok and n_front <= kbd:
                # fused K-level burst: ONE shard_map dispatch + ONE
                # stats readback for up to burst_levels small levels
                # (_shard_burst).  nlev == 0 means the first level
                # bailed — fall through to the per-level path below.
                t1 = time.perf_counter()
                with obs.span("burst_dispatch"):
                    carry = grow_table_if_needed(
                        carry, min_add=self.burst_levels * kbd)
                    lv_left = min(self.burst_levels, max_depth - depth)
                    st_cap = max(1,
                                 min(max_states - res.distinct_states,
                                     2 ** 31 - 1))
                    carry, bout = self._burst_mesh_jit(
                        carry, self.FAM_CAPS, jnp.int32(lv_left),
                        jnp.int32(st_cap))
                    stats = np.asarray(bout["stats"])  # [D,L_MAX+1,NS]
                nlev = int(stats[0, -1, 0])
                bailed = bool(stats[0, -1, 1])
                res.burst_dispatches += 1
                res.burst_bailouts += int(bailed)
                if nlev:
                    burst_ok = not bailed
                    d0 = depth
                    viol_any = bool(stats[0, -1, 3])
                    _hv_span = obs.span("harvest")
                    _hv_span.__enter__()
                    par_rows = lane_rows = st_rows = inv_rows = None
                    if self.store_states or viol_any:
                        par_rows = dict(local_rows(bout["par"]))
                        lane_rows = dict(local_rows(bout["lane"]))
                        st_rows = {k: dict(local_rows(v))
                                   for k, v in bout["st"].items()}
                        inv_rows = dict(local_rows(bout["inv"]))

                    def _stats(li):
                        return (int(stats[:, li, 0].sum()),
                                int(stats[:, li, 1].sum()),
                                int(stats[:, li, 2].sum()),
                                int(stats[:, li, 3].sum()),
                                int(stats[:, li, 4].sum()))

                    def _arch(li, _n_lvl):
                        if not self.store_states:
                            return
                        nl = stats[:, li, 0]
                        ds = sorted(par_rows)
                        self._parents.append(np.concatenate(
                            [par_rows[d][li, :nl[d]] for d in ds]))
                        self._lanes.append(np.concatenate(
                            [lane_rows[d][li, :nl[d]] for d in ds]))
                        self._states.append(
                            {k: np.concatenate(
                                [st_rows[k][d][li, :nl[d]]
                                 for d in ds]) for k in st_rows})
                        self._arch_segs.append(
                            [(int(d), int(nl[d])) for d in ds])

                    def _viol(li, _n_lvl, gid_base):
                        nl = stats[:, li, 0]
                        prefix = np.cumsum(nl) - nl
                        for d in sorted(inv_rows):
                            inv_ok = inv_rows[d]
                            for j, nm in enumerate(self.inv_names):
                                for s in np.nonzero(
                                        ~inv_ok[li, :nl[d], j])[0]:
                                    vsv, vh = self.ir.decode(
                                        lay, _take(
                                        {k: st_rows[k][d][li]
                                         for k in st_rows}, s))
                                    res.violations.append(
                                        Violation(
                                            nm, gid_base +
                                            int(prefix[d]) + int(s),
                                            state=vsv, hist=vh))

                    def _vis(li, _n_lvl):
                        for d in range(D):
                            n_vis[d] += stats[d, li, 0]

                    depth, n_states = driver.harvest_fused_levels(
                        res, nlev, _stats, depth, n_states,
                        archive=_arch, violations=_viol,
                        visited=_vis)
                    _hv_span.__exit__(None, None, None)
                    n_front = int(stats[:, -1, 2].max())
                    if checkpoint_path is not None and \
                            driver.ckpt_due_after_burst(
                                depth, d0, checkpoint_every):
                        self._save_checkpoint(checkpoint_path, carry,
                                              res, depth, n_states,
                                              n_vis, n_front)
                    obs.dispatch(kind="burst", depth=depth,
                                 frontier=n_front,
                                 metrics=res.metrics.as_dict())
                    if stop_on_violation and res.violations_global:
                        break
                    if verbose:
                        print(f"burst: {nlev} levels to depth {depth} "
                              f"(total {res.distinct_states}), "
                              f"frontier(max/dev) {n_front}, "
                              f"{time.perf_counter() - t1:.2f}s")
                    continue
            burst_ok = True        # re-arm after a per-level level
            depth += 1
            _lvl_span = obs.span("level_dispatch")
            _lvl_span.__enter__()
            carry = grow_table_if_needed(carry)
            while True:
                carry, out = self._level_jit(carry, self.FAM_CAPS)
                scal = np.asarray(out["scal"])
                ovf = bool(scal[:, 4].any())
                fovf = bool(scal[:, 5].any())
                sovf = bool(scal[:, 8].any())
                hovf = bool(scal[:, 9].any())
                if not (ovf or fovf or sovf or hovf):
                    break
                old_caps = (self.LB, self.FC, self.SC, self.FAM_CAPS)
                if fovf:
                    famx = scal[:, 10:10 + len(self.FAM_CAPS)].max(axis=0)
                    caps = list(self.FAM_CAPS)
                    fam_over = False
                    for fi, fam in enumerate(self.expander.families):
                        hard = fam.n_lanes * self.BL
                        while caps[fi] < hard and famx[fi] > caps[fi]:
                            caps[fi] = min(2 * caps[fi], hard)
                            fam_over = True
                    self.FAM_CAPS = tuple(caps)
                    if not fam_over:
                        self.FC *= 4
                if sovf or (fovf and self.FC != old_caps[1]):
                    self.SC = max(4 * self.SC, 4 * self.FC // self.D)
                if ovf or self.LB < max(4 * self.FC,
                                        2 * self.D * self.SC):
                    self.LB = self._round_lb(
                        max((4 * self.LB) if ovf else self.LB,
                            4 * self.FC, 2 * self.D * self.SC))
                if hovf:
                    self.VB *= 4
                    carry = self._rehash_sharded(carry)
                if verbose:
                    print(f"level {depth}: overflow "
                          f"(ovf={ovf} fovf={fovf} sovf={sovf} "
                          f"hovf={hovf}), LB={self.LB} FC={self.FC} "
                          f"SC={self.SC} VB={self.VB}")
                if (self.LB, self.FC, self.SC) != old_caps[:3]:
                    carry = self._grow_sharded(carry)
                    # the replayed level can add up to the NEW LB keys
                    # per shard: re-check the table load bound
                    carry = grow_table_if_needed(carry)
            _lvl_span.__exit__(None, None, None)
            with obs.span("harvest"):
                n_front = harvest(carry, out, scal)
            depth = driver.gate_level_depth(
                res, depth, int(scal[:, 0].sum()),
                int(scal[:, 6].sum()), int(scal[:, 7].sum()))
            if checkpoint_path is not None and \
                    driver.ckpt_due_at_level(depth, checkpoint_every):
                self._save_checkpoint(checkpoint_path, carry, res,
                                      depth, n_states, n_vis, n_front)
            obs.dispatch(kind="level", depth=depth, frontier=n_front,
                         metrics=res.metrics.as_dict())
            if stop_on_violation and res.violations_global:
                break
            if verbose:
                print(f"depth {depth}: +{int(scal[:, 0].sum())} states "
                      f"(total {res.distinct_states}), "
                      f"frontier(max/dev) {n_front}")
        res.depth = depth
        res.seconds = time.perf_counter() - t0
        return res

    def _to_device(self, carry_np):
        """Host carry pytree -> device arrays.  MultiHostEngine
        overrides this to build globally-sharded arrays."""
        return jax.tree_util.tree_map(jnp.asarray, carry_np)

    def _fresh_sharded_carry_host(self):
        """Host-side (numpy) fresh carry, for seeding mutation before
        _to_device."""
        return jax.tree_util.tree_map(
            lambda x: np.array(x), self._fresh_sharded_carry())

    def _grow_sharded(self, carry):
        """Re-home the carry in bigger per-device buffers (frontier and
        the visited table survive; the level buffer resets — the level
        replays).  Table growth goes through _rehash_sharded first."""
        D = self.D
        old = carry
        assert old["vis"][0].shape[1] == self.VB, \
            "grow the table via _rehash_sharded first"
        new = self._fresh_sharded_carry()
        new["vis"] = old["vis"]
        new["claims"] = old["claims"]
        olb = old["fmask"].shape[1]
        pad = self.LB - olb
        new["front"] = {k: jnp.concatenate(
            [old["front"][k],
             jnp.zeros((D, pad) + v.shape[2:], v.dtype)], axis=1)
            for k, v in old["front"].items()}
        new["fmask"] = jnp.concatenate(
            [old["fmask"], jnp.zeros((D, pad), bool)], axis=1)
        new["n_front"] = old["n_front"]
        new["g_off"] = old["g_off"]
        # gids ride with the frontier rows they describe
        olb2 = old["gids"].shape[1]
        new["gids"] = jnp.concatenate(
            [old["gids"], jnp.full((D, self.LB - olb2), -1,
                                   jnp.int32)], axis=1)
        return new

    # ------------------------------------------------------------------
    # checkpoint / resume (sharded layout; single-controller — the
    # _save_checkpoint entry fails fast under multiple controllers;
    # MultiHostEngine overrides both methods with per-controller shard
    # files).  Same wavefront semantics as
    # engine/bfs: written at level boundaries, resume lands on
    # bit-identical counts.
    # ------------------------------------------------------------------

    def _save_checkpoint(self, path, carry, res, depth, n_states,
                         n_vis, n_front):
        if jax.process_count() > 1:
            # fail fast, not hours in: this serializer np.asarray's the
            # whole carry, which a multi-controller run cannot address
            raise NotImplementedError(
                "ShardedEngine checkpoints are single-controller; use "
                "MultiHostEngine (per-controller shard files) for "
                "multi-process runs")
        with self._obs.span("checkpoint"):
            ckpt_write(path, carry, self.store_states, self._parents,
                       self._lanes, self._states, res, dict(
                           sharded=True,
                           ckpt_format=_SHARDED_CKPT_FORMAT, D=self.D,
                           chunk=self.chunk,
                           LB=self.LB, VB=self.VB, FC=self.FC,
                           SC=self.SC,
                           fam_caps=list(self.FAM_CAPS),
                           depth=depth, n_states=n_states,
                           n_vis=[int(x) for x in n_vis],
                           n_front=int(n_front),
                           spec=self.ir.name,
                           sym_canon=self.fpr.sym_canon,
                           ir_fingerprint=self.ir.fingerprint(),
                           cfg=repr(self.cfg)),
                       keep=self.ckpt_keep)

    def _resume_portable(self, img):
        """Rebuild a level-boundary carry from a PortableImage: route
        visited keys and frontier rows to their owner devices
        (``key[W-1] % D`` — pure content, so any source shape / device
        count re-partitions here), build per-device table images with
        the host insert twin, and seed the gids table from the image.
        Constraint-pruned rows are dropped (they are never expanded;
        gids are explicit here, so no placeholder rows are needed)."""
        from ..resil.portable import validate_image
        D, W = self.D, self.W
        validate_image(img, self.ir.name, repr(self.cfg), W)
        self._restore_portable_archives(img)
        self._arch_segs = [[(0, len(p))] for p in self._parents]
        keys = img.keys
        owner = (keys[:, W - 1].astype(np.int64)) % D
        n_vis = np.bincount(owner, minlength=D).astype(np.int64)
        rows, gids = img.expandable()
        if gids.shape[0]:
            b = {k: jnp.asarray(v)
                 for k, v in self.ir.widen(rows).items()}
            fkeys = np.asarray(self._rootfp_jit(b)).astype(np.uint32)
            fowner = (fkeys[:, W - 1].astype(np.int64)) % D
        else:
            fowner = np.zeros((0,), np.int64)
        per_dev = [np.nonzero(fowner == d)[0] for d in range(D)]
        max_rows = max((len(p) for p in per_dev), default=0)
        # grow LB FIRST, then size the table against the final LB —
        # the same order as root admission: the load bound reserves
        # headroom for a whole level (up to LB keys), so sizing VB
        # against a stale smaller LB could leave the shard past its
        # probe budget right after resume
        while self.LB - self.D * self.SC < 2 * max(max_rows, 1):
            self.LB = self._round_lb(2 * self.LB)
        while int(n_vis.max()) + self.LB > self._LOAD_MAX * self.VB:
            self.VB *= 4
        carry_np = self._fresh_sharded_carry_host()
        for d in range(D):
            kd = keys[owner == d]
            if kd.shape[0]:
                tbl = np.full((W, self.VB), np.uint32(0xFFFFFFFF),
                              np.uint32)
                insert_np(tbl, kd.astype(np.uint32))
                for w in range(W):
                    carry_np["vis"][w][d] = tbl[w]
            idx = per_dev[d]
            n = len(idx)
            if n:
                for k in rows:
                    carry_np["front"][k][d, :n] = rows[k][idx]
                carry_np["gids"][d, :n] = gids[idx]
                carry_np["fmask"][d, :n] = True
            carry_np["n_front"][d] = n
        carry_np["g_off"][:] = np.int32(img.n_states)
        carry = self._to_device(carry_np)
        return (carry, img.fresh_result(), img.n_states, n_vis, img.depth,
                max_rows)

    def _load_checkpoint(self, path):
        from ..engine.bfs import CheckpointError
        if jax.process_count() > 1:
            raise NotImplementedError(
                "ShardedEngine checkpoints are single-controller; use "
                "MultiHostEngine (per-controller shard files) for "
                "multi-process runs")
        z, meta = ckpt_read(path, repr(self.cfg), self.chunk,
                            ("D", "LB", "VB", "FC", "SC", "fam_caps"),
                            sharded=True, expected_format=_SHARDED_FMT,
                            spec_name=self.ir.name,
                            sym_canon=self.fpr.sym_canon)
        if meta["D"] != self.D:
            raise CheckpointError(
                f"checkpoint was written on a {meta['D']}-device mesh; "
                f"this engine has {self.D} devices (shard ownership is "
                "mesh-size dependent)")
        self.LB, self.VB, self.FC, self.SC = (
            meta["LB"], meta["VB"], meta["FC"], meta["SC"])
        self.FAM_CAPS = tuple(int(c) for c in meta["fam_caps"])
        template = jax.eval_shape(lambda: self._fresh_sharded_carry())
        carry = ckpt_carry(path, z, template, self._to_device)
        self._parents, self._lanes, self._states = ckpt_archives(
            z, meta, template, self.store_states)
        # segment metadata is not checkpointed (only the MultiHostEngine
        # archive merge needs it, and that engine rejects store_states +
        # checkpointing); single-host trace() never reads it
        self._arch_segs = [[(0, len(p))] for p in self._parents]
        res = ckpt_result(z, meta)
        z.close()             # all arrays extracted; don't leak the fd
        return carry, res, meta

    def _rehash_sharded(self, carry):
        """Per-shard device rehash into self.VB-slot tables (sharded
        twin of Engine._rehash_tables)."""
        old_vb = int(carry["vis"][0].shape[1])
        new_vb = self.VB

        def local(table):
            t = tuple(x[0] for x in table)
            allones = jnp.ones((old_vb,), bool)
            for w in range(self.W):
                allones &= t[w] == U32MAX
            new = tuple(jnp.full((new_vb,), U32MAX)
                        for _ in range(self.W))
            ncl = jnp.full((new_vb,), U32MAX)
            ranks = jnp.arange(old_vb, dtype=jnp.uint32)
            # lax path unconditionally: a rehash probes a whole table
            # shard at once, not the per-candidate hot loop
            new, ncl, _f, _p, hv = self._probe_insert_lax(
                new, ncl, t, ~allones, ranks)
            # replicated so every controller can read it (multi-host)
            hv_all = jax.lax.all_gather(hv, "d").any()
            return (tuple(x[None] for x in new), ncl[None], hv_all)

        fn = _shard_map(
            local, self.mesh,
            (tuple(P("d") for _ in range(self.W)),),
            (tuple(P("d") for _ in range(self.W)), P("d"), P()))
        vis, claims, hv = jax.jit(fn)(carry["vis"])
        if bool(np.asarray(hv).any()):
            raise RuntimeError("sharded rehash did not converge — "
                               "table pathologically full; raise vcap")
        # lrow is slot-indexed: resize with the table (it is only ever
        # non-sentinel mid-level, and a rehash either sits between
        # levels or aborts the level into a replay)
        return dict(carry, vis=vis, claims=claims,
                    lrow=jnp.full((self.D, new_vb), -1, jnp.int32))

    # ------------------------------------------------------------------
    # collective demo kept for the driver dry run
    # ------------------------------------------------------------------

    def device_fingerprint_gather(self, svb: Dict[str, jnp.ndarray]):
        """shard_map the expansion and all_gather the fingerprint
        blocks over ICI, returning globally-assembled [B, A, streams]
        fingerprints — proves the collective path compiles+executes."""
        def local(svb_local):
            _ok, _cand, fp = self._phase1_impl(svb_local)
            return jax.lax.all_gather(fp, "d", tiled=True)

        fn = _shard_map(
            local, self.mesh,
            ({k: P("d") for k in self.ir.all_keys},), P(None))
        return fn(svb)
