"""Multi-device frontier sharding (SURVEY §2.14).

The reference's engine-level parallelism is TLC's multi-worker BFS over
shared memory (`-workers 8`); the TPU-native counterpart is **data
parallelism over the frontier axis**: the per-level candidate expansion
(engine/bfs phase 1: expand + fingerprint) is compiled once over a
1-D ``jax.sharding.Mesh`` with the batch axis sharded, so each device
expands its slice of the frontier.  A ``jax.lax.all_gather`` over the
mesh axis exchanges the per-device fingerprint blocks (the ICI ride that
replaces TLC's shared fingerprint table) so every device — and the host
after one transfer — sees the full candidate fingerprint set.

Fingerprint-ownership partitioning (hash-prefix → device, all-to-all
exchange, device-resident visited set) is the planned next step; the
host-side sorted set remains the dedup authority for now (SURVEY §7.2
L6 lands in stages).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import ModelConfig
from ..engine.bfs import Engine


class ShardedEngine(Engine):
    """Engine whose phase-1 (expand + fingerprint) runs sharded over a
    device mesh.  chunk must be a multiple of the mesh size."""

    def __init__(self, cfg: ModelConfig, devices=None, chunk: int = 512,
                 store_states: bool = True):
        devices = devices if devices is not None else jax.devices()
        self.mesh = Mesh(np.array(devices), axis_names=("frontier",))
        self.n_dev = len(devices)
        assert chunk % self.n_dev == 0, \
            f"chunk {chunk} not divisible by {self.n_dev} devices"
        super().__init__(cfg, chunk=chunk, store_states=store_states)
        shard = NamedSharding(self.mesh, P("frontier"))
        self._shard = shard
        self._phase1 = jax.jit(
            self._phase1_sharded,
            in_shardings=({k: shard for k in self._state_keys()},),
            out_shardings=(shard, {k: shard for k in self._state_keys()},
                           shard))

    def _state_keys(self):
        from ..ops.codec import ALL_KEYS
        return ALL_KEYS

    def _phase1_sharded(self, svb):
        ok, cand, fp = self._phase1_impl(svb)
        return ok, cand, fp

    def device_fingerprint_gather(self, svb: Dict[str, jnp.ndarray]):
        """The explicit-collective path: shard_map the expansion and
        all_gather the fingerprint blocks over ICI, returning the
        globally-assembled [B, A, streams] fingerprints.  Used by the
        multi-chip dry run to prove the collective compiles + executes."""
        from jax.experimental.shard_map import shard_map

        def local(svb_local):
            _ok, _cand, fp = self._phase1_impl(svb_local)
            return jax.lax.all_gather(fp, "frontier", tiled=True)

        fn = shard_map(
            local, mesh=self.mesh,
            in_specs=({k: P("frontier") for k in self._state_keys()},),
            out_specs=P(None),
            check_rep=False)
        return fn(svb)
