"""Multi-device BFS: frontier data parallelism + fingerprint-ownership
partitioning (SURVEY §2.14).

The reference's engine-level parallelism is TLC's multi-worker BFS with
a partitioned fingerprint table (`-workers 8`).  The TPU-native
counterpart implemented here:

- the frontier, level buffer, parent arrays and the visited/level key
  sets all carry a leading device axis and live sharded over a 1-D
  ``jax.sharding.Mesh`` (``shard_map`` over axis "d");
- each device expands its frontier shard and fingerprints its enabled
  candidates (compute data parallelism);
- every candidate is then routed to its OWNER device — owner = low
  bits of the fingerprint — via ``jax.lax.all_to_all`` over ICI; the
  owner probes its visited/level shards, dedups, and appends fresh
  states to its level shard.  The dedup authority therefore lives on
  device and is partitioned by hash, exactly like TLC's worker-local
  fingerprint table partitions, with the all-to-all exchange riding
  ICI instead of shared memory;
- because ownership is hash-uniform, the next frontier (the level
  buffer, swapped in place) is automatically load-balanced.

Global state ids are assigned device-major per level: device d's rows
get ids ``g_base + prefix[d] + row`` where ``prefix`` is the exclusive
cumsum of the per-device level counts (computed on device with an
``all_gather``).  The host reads ONE packed per-level scalar matrix.

Determinism caveat (shared with TLC's multi-worker mode): when two
candidates have equal VIEW fingerprints but different non-VIEW history
counters, WHICH concrete state survives depends on arrival order.
Under ``VIEW``-insensitive constraint sets the reachable set is
unaffected; with counter-dependent constraints (BoundedTimeouts etc.)
multi-worker TLC has the same nondeterminism.  The sharded differential
test therefore runs a counter-free constraint set.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:                                    # jax >= 0.6
    from jax import shard_map

    def _shard_map(f, mesh, in_specs, out_specs):
        return shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)
except ImportError:                     # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

    def _shard_map(f, mesh, in_specs, out_specs):
        return shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)

from ..config import ModelConfig
from ..engine.bfs import (CheckResult, Engine, U32MAX, Violation, _cat,
                          _take)
from ..models.raft import init_state
from ..ops.codec import C_OVERFLOW, decode, encode


class ShardedEngine(Engine):
    """Engine whose full BFS runs sharded over a device mesh with
    hash-ownership-partitioned visited/level key sets.

    chunk — GLOBAL frontier states expanded per step (chunk/D per
    device); must be a multiple of the mesh size."""

    def __init__(self, cfg: ModelConfig, devices=None, chunk: int = 512,
                 store_states: bool = True,
                 lcap: int = 1 << 14, vcap: int = 1 << 17,
                 fcap: Optional[int] = None, scap: Optional[int] = None):
        devices = devices if devices is not None else jax.devices()
        self.mesh = Mesh(np.array(devices), axis_names=("d",))
        self.D = len(devices)
        assert chunk % self.D == 0, \
            f"chunk {chunk} not divisible by {self.D} devices"
        self.BL = chunk // self.D              # frontier rows per device
        super().__init__(cfg, chunk=chunk, store_states=store_states,
                         lcap=lcap, vcap=vcap, fcap=fcap)
        # per-device capacities
        self.FC = max(256, (self.FCAP + self.D - 1) // self.D)
        self.VB = max(1 << 12, vcap // self.D)
        # send capacity per (src, dst) pair; hash-uniform routing puts
        # ~FC/D candidates per destination — 4x headroom, growable
        self.SC = int(scap) if scap else max(256, 4 * self.FC // self.D)
        # the level shard must hold the D*SC receive window on top of
        # its usable capacity
        self.LB = self._round_lb(max(lcap // self.D, 4 * self.FC,
                                     2 * self.D * self.SC))
        self._set_tb()
        self._step_jit = jax.jit(self._sharded_step_call,
                                 donate_argnums=0)
        self._fin_jit = jax.jit(self._sharded_fin_call, donate_argnums=0)

    def _round_lb(self, n: int) -> int:
        b = self.BL
        return ((int(n) + b - 1) // b) * b

    def _set_tb(self):
        # the tail must hold a full per-step receive window (n_fresh
        # can reach M = D*SC); a too-small tail would silently drop
        # keys in _sorted_insert and re-admit duplicate states
        self.TB = min(max(8 * self.FC, self.D * self.SC), self.LB)

    # -----------------------------------------------------------------
    def _sharded_step_call(self, carry):
        specs = jax.tree_util.tree_map(lambda _: P("d"), carry)
        return _shard_map(self._shard_step, self.mesh,
                          (specs,), specs)(carry)

    def _sharded_fin_call(self, carry):
        specs = jax.tree_util.tree_map(lambda _: P("d"), carry)
        out_specs = (specs, dict(inv_ok=P("d"), scal=P("d")))
        return _shard_map(self._shard_finalize, self.mesh,
                          (specs,), out_specs)(carry)

    # -----------------------------------------------------------------
    # per-device chunk step (runs inside shard_map; leading axis of
    # every leaf is the local shard, size 1 in the device dimension)
    # -----------------------------------------------------------------

    def _shard_step(self, carry):
        c = jax.tree_util.tree_map(lambda x: x[0], carry)
        c = self._local_step(c)
        return jax.tree_util.tree_map(lambda x: x[None], c)

    def _local_step(self, c):
        B, A, W, D = self.BL, self.A, self.W, self.D
        # capacities derive from carry shapes so growth always retraces
        FC = c["cidx"].shape[0]
        SC = c["sscr"].shape[0]
        LB = c["fmask"].shape[0]
        N = B * A
        M = D * SC                     # received candidates per step
        base = c["base"]
        sv = {k: lax.dynamic_slice_in_dim(v, base, B)
              for k, v in c["front"].items()}
        fmask = lax.dynamic_slice_in_dim(c["fmask"], base, B)
        ok, cand = lax.optimization_barrier(
            self.expander._expand_impl(sv))
        if self.act_names:
            act = jax.vmap(lambda p, crow: jax.vmap(
                lambda cc: self._act_ok(p, cc))(crow))(sv, cand)
            ok = ok & act
        valid = ((base + jnp.arange(B, dtype=jnp.int32)) <
                 c["n_front"]) & fmask
        okf = (ok & valid[:, None]).reshape(N)
        n_gen = c["n_gen"] + okf.sum(dtype=jnp.int32)

        # compact enabled lanes, fingerprint them
        idx = jnp.arange(N, dtype=jnp.int32)
        epos = jnp.where(okf, jnp.cumsum(okf.astype(jnp.int32)) - 1, FC)
        n_e = okf.sum(dtype=jnp.int32)
        fovf = c["fovf"] | (n_e > FC)
        eidx = lax.optimization_barrier(
            jnp.full((FC,), N, jnp.int32).at[epos].set(idx, mode="drop"))
        elive = jnp.arange(FC, dtype=jnp.int32) < n_e
        take = jnp.clip(eidx, 0, N - 1)
        cand_c = lax.optimization_barrier(
            {k: v.reshape((N,) + v.shape[2:])[take]
             for k, v in cand.items()})
        fp = lax.optimization_barrier(
            jax.vmap(self.fpr.fingerprint)(cand_c))        # [FC, W]
        pgid = c["pg_off"] + base + take // A
        lane = take % A

        # ---- route to owner device (hash-ownership, SURVEY §2.14) ----
        owner = jnp.where(elive, (fp[:, W - 1] % D).astype(jnp.int32), D)
        slot = jnp.arange(FC, dtype=jnp.int32)
        o_s, slot_s = lax.optimization_barrier(
            lax.sort((owner, slot), num_keys=2))
        counts = jnp.sum(o_s[None, :] == jnp.arange(D)[:, None],
                         axis=1)                            # [D]
        starts = jnp.cumsum(counts) - counts
        rank = jnp.arange(FC, dtype=jnp.int32) - \
            starts[jnp.clip(o_s, 0, D - 1)]
        live_s = o_s < D
        sovf = c["sovf"] | jnp.any(live_s & (rank >= SC))
        dest = jnp.where(live_s & (rank < SC),
                         o_s * SC + jnp.clip(rank, 0, SC - 1), M)
        # inverse map: send slot -> local candidate slot
        sidx = lax.optimization_barrier(
            jnp.full((M,), FC, jnp.int32).at[dest].set(
                slot_s, mode="drop"))
        sfill = jnp.zeros((M,), bool).at[dest].set(live_s, mode="drop")
        stake = jnp.clip(sidx, 0, FC - 1)
        send_key = tuple(jnp.where(sfill, fp[stake, w], U32MAX)
                         for w in range(W))
        send_row = {k: v[stake] for k, v in cand_c.items()}
        send_pgid = jnp.where(sfill, pgid[stake], -1)
        send_lane = jnp.where(sfill, lane[stake], -1)
        (send_key, send_row, send_pgid, send_lane) = \
            lax.optimization_barrier(
                (send_key, send_row, send_pgid, send_lane))

        a2a = partial(lax.all_to_all, axis_name="d", split_axis=0,
                      concat_axis=0, tiled=True)
        recv_key = tuple(a2a(kw) for kw in send_key)        # [M] each
        recv_row = {k: a2a(v) for k, v in send_row.items()}
        recv_pgid = a2a(send_pgid)
        recv_lane = a2a(send_lane)

        # ---- owner-side dedup (first-seen in arrival-slot order) ----
        ridx = jnp.arange(M, dtype=jnp.int32)
        sorted_ops = lax.optimization_barrier(
            lax.sort(recv_key + (ridx,), num_keys=W + 1))
        sk, srid = sorted_ops[:W], sorted_ops[W]
        diff = jnp.zeros(M, bool).at[0].set(True)
        for w in range(W):
            diff = diff | jnp.concatenate(
                [jnp.ones(1, bool), sk[w][1:] != sk[w][:-1]])
        is_sent = jnp.ones(M, bool)
        for w in range(W):
            is_sent = is_sent & (sk[w] == U32MAX)
        surv = diff & ~is_sent
        surv = surv & ~self._member(c["vis"], sk)
        surv = surv & ~self._member(c["lvlk"], sk)
        surv = surv & ~self._member(c["ltail"], sk)

        fresh = jnp.zeros(M, bool).at[srid].set(surv)
        n_fresh = fresh.sum(dtype=jnp.int32)
        lpos = jnp.where(fresh,
                         jnp.cumsum(fresh.astype(jnp.int32)) - 1, M)
        lidx, lkey = lax.optimization_barrier((
            jnp.zeros((M,), jnp.int32).at[lpos].set(ridx, mode="drop"),
            tuple(jnp.full((M,), U32MAX).at[lpos].set(
                recv_key[w], mode="drop") for w in range(W))))

        start = jnp.minimum(c["n_lvl"], LB - M)
        ovf = c["ovf"] | (c["n_lvl"] + n_fresh > LB - M)
        lvl = {k: lax.dynamic_update_slice_in_dim(
            v, recv_row[k][lidx], start, 0)
            for k, v in c["lvl"].items()}
        lpar = lax.dynamic_update_slice_in_dim(
            c["lpar"], recv_pgid[lidx], start, 0)
        llane = lax.dynamic_update_slice_in_dim(
            c["llane"], recv_lane[lidx], start, 0)

        TB = c["ltail"][0].shape[0]
        ovf = ovf | (n_fresh > TB)     # belt: TB >= M should hold
        spill = c["n_tail"] + n_fresh > TB

        def do_spill(ops):
            lvlk, ltail = ops
            return (self._sorted_insert(lvlk, ltail, LB),
                    tuple(jnp.full((TB,), U32MAX) for _ in range(W)))

        lvlk, ltail = lax.cond(spill, do_spill, lambda o: o,
                               (c["lvlk"], c["ltail"]))
        n_tail = jnp.where(spill, 0, c["n_tail"]) + n_fresh
        ltail = self._sorted_insert(ltail, lkey, TB)
        return dict(c, lvl=lvl, lpar=lpar, llane=llane, lvlk=lvlk,
                    ltail=ltail, n_tail=n_tail,
                    n_lvl=jnp.minimum(c["n_lvl"] + n_fresh, LB - M),
                    n_gen=n_gen, ovf=ovf, fovf=fovf, sovf=sovf,
                    base=base + B)

    # -----------------------------------------------------------------

    def _shard_finalize(self, carry):
        c = jax.tree_util.tree_map(lambda x: x[0], carry)
        LB = c["fmask"].shape[0]
        VB = c["vis"][0].shape[0]
        n_lvl = c["n_lvl"]
        bad_local = c["ovf"] | c["fovf"] | c["sovf"]
        # any device overflowing aborts the level everywhere
        bad = jax.lax.all_gather(bad_local, "d").any()
        validrow = jnp.arange(LB, dtype=jnp.int32) < n_lvl
        inv, con = lax.optimization_barrier(
            self._phase2_impl(c["lvl"]))
        inv_ok = inv | ~validrow[:, None] if self.inv_names else inv
        n_viol = (~inv_ok).sum(dtype=jnp.int32)
        faults = ((c["lvl"]["ctr"][:, C_OVERFLOW] > 0) &
                  validrow).sum(dtype=jnp.int32)

        # device-major global ids for this level
        nl_vec = jax.lax.all_gather(n_lvl, "d")             # [D]
        prefix = jnp.cumsum(nl_vec) - nl_vec
        d_idx = jax.lax.axis_index("d")
        total = nl_vec.sum()

        def commit(c):
            fmask = con & validrow
            ins = tuple(jnp.concatenate([c["lvlk"][w], c["ltail"][w]])
                        for w in range(self.W))
            vis = self._sorted_insert(c["vis"], ins, VB)
            return (c["lvl"], c["front"], fmask, n_lvl, vis,
                    c["g_off"] + prefix[d_idx], c["g_off"] + total)

        def abandon(c):
            return (c["front"], c["lvl"], c["fmask"], c["n_front"],
                    c["vis"], c["pg_off"], c["g_off"])

        front, lvl, fmask, n_front, vis, pg_off, g_next = lax.cond(
            bad, abandon, commit, c)
        lvlk = tuple(jnp.full((LB,), U32MAX) for _ in range(self.W))
        ltail = tuple(jnp.full((c["ltail"][0].shape[0],), U32MAX)
                      for _ in range(self.W))
        scal = jnp.stack([
            n_lvl, n_viol, faults, n_front,
            c["ovf"].astype(jnp.int32), c["fovf"].astype(jnp.int32),
            c["n_gen"], (con & validrow).sum(dtype=jnp.int32),
            c["sovf"].astype(jnp.int32)])
        new_c = dict(c, vis=vis, lvlk=lvlk, ltail=ltail,
                     n_tail=jnp.int32(0), front=front, lvl=lvl,
                     fmask=fmask, n_front=n_front,
                     n_lvl=jnp.int32(0), n_gen=jnp.int32(0),
                     ovf=jnp.bool_(False), fovf=jnp.bool_(False),
                     sovf=jnp.bool_(False),
                     base=jnp.int32(0), pg_off=pg_off, g_off=g_next)
        out = dict(inv_ok=inv_ok, scal=scal)
        return (jax.tree_util.tree_map(lambda x: x[None], new_c),
                jax.tree_util.tree_map(lambda x: x[None], out))

    # -----------------------------------------------------------------

    def _fresh_sharded_carry(self):
        D, LB, VB, TB, FC = self.D, self.LB, self.VB, self.TB, self.FC
        one = encode(self.lay, *init_state(self.cfg))
        zeros = {k: jnp.zeros((D, LB) + v.shape, dtype=v.dtype)
                 for k, v in one.items()}
        return dict(
            vis=tuple(jnp.full((D, VB), U32MAX) for _ in range(self.W)),
            lvlk=tuple(jnp.full((D, LB), U32MAX) for _ in range(self.W)),
            ltail=tuple(jnp.full((D, TB), U32MAX)
                        for _ in range(self.W)),
            n_tail=jnp.zeros((D,), jnp.int32),
            lvl=zeros,
            lpar=jnp.full((D, LB), -1, jnp.int32),
            llane=jnp.full((D, LB), -1, jnp.int32),
            cidx=jnp.zeros((D, FC), jnp.int32),
            # shape anchor for SC: jit caches on input avals, and SC
            # otherwise only shapes internal send/recv buffers — an SC
            # growth would silently cache-hit the stale trace
            sscr=jnp.zeros((D, self.SC), jnp.int32),
            n_lvl=jnp.zeros((D,), jnp.int32),
            n_gen=jnp.zeros((D,), jnp.int32),
            base=jnp.zeros((D,), jnp.int32),
            g_off=jnp.zeros((D,), jnp.int32),
            pg_off=jnp.zeros((D,), jnp.int32),
            ovf=jnp.zeros((D,), bool),
            fovf=jnp.zeros((D,), bool),
            sovf=jnp.zeros((D,), bool),
            front={k: jnp.zeros_like(v) for k, v in zeros.items()},
            fmask=jnp.zeros((D, LB), bool),
            n_front=jnp.zeros((D,), jnp.int32),
        )

    # -----------------------------------------------------------------

    def check(self, max_depth: int = 10 ** 9, max_states: int = 10 ** 9,
              stop_on_violation: bool = False,
              seed_states: Optional[List] = None,
              checkpoint_path: Optional[str] = None,
              checkpoint_every: int = 1,
              resume_from: Optional[str] = None,
              verbose: bool = False) -> CheckResult:
        if checkpoint_path or resume_from:
            raise NotImplementedError(
                "checkpoint/resume is single-device only for now "
                "(the sharded carry layout needs its own serializer)")
        t0 = time.time()
        lay = self.lay
        D, W, LB = self.D, self.W, self.LB
        init_list = (seed_states if seed_states is not None
                     else [init_state(self.cfg)])
        init_arrs = _cat([
            {k: np.asarray(v)[None] for k, v in s.items()}
            if isinstance(s, dict) else
            {k: v[None] for k, v in encode(lay, *s).items()}
            for s in init_list])
        rootsb = {k: jnp.asarray(v) for k, v in init_arrs.items()}
        root_fp = np.asarray(self._rootfp_jit(rootsb)).astype(np.uint32)
        # host-side dedup of seeds + ownership routing
        keys = [tuple(int(root_fp[i, w]) for w in range(W))
                for i in range(root_fp.shape[0])]
        seen = {}
        for i, k in enumerate(keys):
            seen.setdefault(k, i)
        per_dev: List[List[int]] = [[] for _ in range(D)]
        for k, i in sorted(seen.items(), key=lambda kv: kv[1]):
            per_dev[int(k[W - 1]) % D].append(i)
        # grow the level shard until the most-loaded device's seeds fit
        # with the receive-window margin (punctuated-search seed sets
        # can be thousands of states, hash-skewed across devices)
        max_seed = max(len(p) for p in per_dev)
        while self.LB - self.D * self.SC < 2 * max_seed:
            self.LB = self._round_lb(2 * self.LB)
        self._set_tb()
        LB = self.LB

        res = CheckResult(distinct_states=0,
                          generated_states=len(seen), depth=0)
        self._states = []
        self._parents = []
        self._lanes = []

        carry_np = jax.tree_util.tree_map(
            lambda x: np.array(x), self._fresh_sharded_carry())
        nl = np.zeros((D,), np.int32)
        for d in range(D):
            for r, i in enumerate(per_dev[d]):
                for k in init_arrs:
                    carry_np["lvl"][k][d, r] = init_arrs[k][i]
                carry_np["lpar"][d, r] = -1
                carry_np["llane"][d, r] = -1
            nl[d] = len(per_dev[d])
            rk = root_fp[per_dev[d]]                       # [n, W]
            order = np.lexsort(tuple(rk[:, w]
                                     for w in range(W - 1, -1, -1)))
            for w in range(W):
                col = np.full((LB,), 0xFFFFFFFF, np.uint32)
                col[:len(order)] = rk[order, w]
                carry_np["lvlk"][w][d] = col
        carry_np["n_lvl"] = nl
        carry = jax.tree_util.tree_map(jnp.asarray, carry_np)

        n_states = 0
        n_vis = np.zeros((D,), np.int64)
        depth = 0

        def run_finalize(carry):
            need = int(n_vis.max()) + self.LB
            if need > self.VB:
                while self.VB < need:
                    self.VB *= 4
                carry = dict(carry)
                carry["vis"] = tuple(
                    jnp.concatenate(
                        [carry["vis"][w],
                         jnp.full((D, self.VB -
                                   carry["vis"][w].shape[1]), U32MAX)],
                        axis=1)
                    for w in range(W))
            carry, out = self._fin_jit(carry)
            return carry, out, np.asarray(out["scal"])     # [D, 9]

        def harvest(carry, out, scal):
            nonlocal n_states
            nl = scal[:, 0]
            n_lvl = int(nl.sum())
            res.distinct_states += n_lvl
            res.overflow_faults += int(scal[:, 2].sum())
            res.generated_states += int(scal[:, 6].sum())
            prefix = np.cumsum(nl) - nl
            if self.store_states:
                pars = np.asarray(carry["lpar"])
                lns = np.asarray(carry["llane"])
                self._parents.append(np.concatenate(
                    [pars[d, :nl[d]] for d in range(D)]))
                self._lanes.append(np.concatenate(
                    [lns[d, :nl[d]] for d in range(D)]))
                rows = {k: np.asarray(v)
                        for k, v in carry["front"].items()}
                self._states.append(
                    {k: np.concatenate([rows[k][d, :nl[d]]
                                        for d in range(D)])
                     for k in rows})
            if scal[:, 1].sum():
                inv_ok = np.asarray(out["inv_ok"])
                rows = {k: np.asarray(v)
                        for k, v in carry["front"].items()}
                for d in range(D):
                    for j, nm in enumerate(self.inv_names):
                        for s in np.nonzero(~inv_ok[d, :nl[d], j])[0]:
                            vsv, vh = decode(lay, _take(
                                {k: rows[k][d] for k in rows}, s))
                            res.violations.append(Violation(
                                nm, n_states + int(prefix[d]) + int(s),
                                state=vsv, hist=vh))
            n_states += n_lvl
            for d in range(D):
                n_vis[d] += nl[d]
            # global state ids are device int32; fail loud, not wrap
            if n_states >= 2 ** 31 - 1:
                raise RuntimeError(
                    "state-id space exhausted (2^31 ids): run exceeds "
                    "the engine's int32 global-id width")
            return int(scal[:, 3].max())

        carry, out, scal = run_finalize(carry)
        n_front = harvest(carry, out, scal)
        if stop_on_violation and res.violations:
            res.seconds = time.time() - t0
            return res

        while n_front and depth < max_depth and \
                res.distinct_states < max_states:
            depth += 1
            while True:
                n_chunks = (n_front + self.BL - 1) // self.BL
                for _ in range(n_chunks):
                    carry = self._step_jit(carry)
                carry, out, scal = run_finalize(carry)
                ovf = bool(scal[:, 4].any())
                fovf = bool(scal[:, 5].any())
                sovf = bool(scal[:, 8].any())
                if not (ovf or fovf or sovf):
                    break
                if fovf:
                    self.FC *= 4
                if sovf or fovf:
                    self.SC = max(4 * self.SC, 4 * self.FC // self.D)
                if ovf or self.LB < max(4 * self.FC,
                                        2 * self.D * self.SC):
                    self.LB = self._round_lb(
                        max((4 * self.LB) if ovf else self.LB,
                            4 * self.FC, 2 * self.D * self.SC))
                self._set_tb()
                if verbose:
                    print(f"level {depth}: overflow "
                          f"(ovf={ovf} fovf={fovf} sovf={sovf}), "
                          f"LB={self.LB} FC={self.FC} SC={self.SC}")
                carry = self._grow_sharded(carry)
            n_front = harvest(carry, out, scal)
            if int(scal[:, 0].sum()) == 0 and int(scal[:, 6].sum()) == 0:
                depth -= 1
            else:
                res.level_sizes.append(int(scal[:, 7].sum()))
            if stop_on_violation and res.violations:
                break
            if verbose:
                print(f"depth {depth}: +{int(scal[:, 0].sum())} states "
                      f"(total {res.distinct_states}), "
                      f"frontier(max/dev) {n_front}")
        res.depth = depth
        res.seconds = time.time() - t0
        return res

    def _grow_sharded(self, carry):
        """Re-home the carry in bigger per-device buffers (frontier and
        visited survive; the level buffer resets — the level replays)."""
        D, W = self.D, self.W
        old = carry
        new = self._fresh_sharded_carry()
        ovb = old["vis"][0].shape[1]           # .shape: no transfer
        new["vis"] = tuple(
            jnp.concatenate(
                [old["vis"][w],
                 jnp.full((D, self.VB - ovb), U32MAX)], axis=1)
            if self.VB > ovb else old["vis"][w]
            for w in range(W))
        olb = old["fmask"].shape[1]
        pad = self.LB - olb
        new["front"] = {k: jnp.concatenate(
            [old["front"][k],
             jnp.zeros((D, pad) + v.shape[2:], v.dtype)], axis=1)
            for k, v in old["front"].items()}
        new["fmask"] = jnp.concatenate(
            [old["fmask"], jnp.zeros((D, pad), bool)], axis=1)
        new["lvlk"] = tuple(jnp.full((D, self.LB), U32MAX)
                            for _ in range(W))
        new["n_front"] = old["n_front"]
        new["g_off"] = old["g_off"]
        new["pg_off"] = old["pg_off"]
        return new

    # ------------------------------------------------------------------
    # collective demo kept for the driver dry run
    # ------------------------------------------------------------------

    def device_fingerprint_gather(self, svb: Dict[str, jnp.ndarray]):
        """shard_map the expansion and all_gather the fingerprint
        blocks over ICI, returning globally-assembled [B, A, streams]
        fingerprints — proves the collective path compiles+executes."""
        def local(svb_local):
            _ok, _cand, fp = self._phase1_impl(svb_local)
            return jax.lax.all_gather(fp, "d", tiled=True)

        from ..ops.codec import ALL_KEYS
        fn = _shard_map(
            local, self.mesh,
            ({k: P("d") for k in ALL_KEYS},), P(None))
        return fn(svb)
