"""Multi-host (DCN) scale-out for the sharded BFS (SURVEY §2.14, §7.2
L7: "then multi-host over DCN").

The reference's engine-level counterpart is TLC's multi-worker BFS run
as distributed TLC; here the ShardedEngine's hash-ownership mesh simply
spans every host's chips: one controller process per host calls the
same jit'd shard_map program (multi-controller SPMD), the all_to_all
candidate exchange and the replicated per-level scalar matrix ride ICI
inside a host and DCN across hosts, and each controller only ever
touches its own addressable shards (mesh.py's `local_rows` /
replicated-scal design).

Bring-up:

    # on every host (coordinator = host 0), BEFORE any jax use:
    from raft_tla_tpu.parallel.multihost import init_distributed
    init_distributed("host0:9911", num_processes=4, process_id=rank)
    eng = MultiHostEngine(cfg, chunk=1024, lcap=..., vcap=...)
    res = eng.check()   # counts + violations_global identical on every
                        # host; res.violations holds only THIS host's
                        # shard-local decoded violations

Verified in-repo by tests/test_multihost.py: two controller processes
x two virtual CPU devices each (gloo collectives — the CPU stand-in
for DCN) land on oracle-identical counts.

Constraints vs the single-host ShardedEngine:
- `store_states=True` needs `trace_dir=` — a directory every
  controller can reach (TLC's distributed workers write worker-local
  ``states/`` files to shared storage the same way).  Each controller
  archives its own device shards per level; ``trace()`` on any
  controller merges the per-controller files device-major (the global
  id order) and replays the full witness chain, so a violation found
  at mesh scale has a trace without a single-host re-run
  (tests/test_multihost.py::test_multihost_violation_trace).  Without
  a trace_dir, violations still print decoded states shard-locally
  (``Violation.state``).  store_states composes with checkpointing
  (round 14): every controller's checkpoint shard carries its own
  archive rows + device segmentation, so a resumed run's final
  trace_dir merge equals an uninterrupted run's bit-exact.
- Level/send/compaction capacities (lcap/fcap/scap) GROW mid-run like
  the single-host engine's: every controller takes the identical
  growth branch from the replicated scalar matrix and re-homes its
  shards into identically-shaped new global arrays in lockstep
  (mesh.py `_grow_sharded` runs as SPMD ops on the P("d") arrays).
  The visited table grows the same way (`_rehash_sharded`).  Proven
  under 2 controllers by
  tests/test_multihost.py::test_multihost_midrun_growth — pre-sizing
  is a performance choice (growth replays the level), not a limit.
"""

from __future__ import annotations


import jax

def init_distributed(coordinator_address: str, num_processes: int,
                     process_id: int):
    """Initialize the JAX distributed runtime for a multi-controller
    run.  On CPU (tests / DCN rehearsal) also selects the gloo
    collectives backend; for multiple virtual CPU devices per process
    the caller must have set
    ``XLA_FLAGS=--xla_force_host_platform_device_count=N`` before the
    interpreter started (the axon sitecustomize initializes backends
    too early for an in-process os.environ write to take effect)."""
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass                    # non-CPU backend: collectives are native
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)




def __getattr__(name):
    # lazy: importing the engine initializes the XLA backend, which
    # must happen AFTER jax.distributed.initialize / init_distributed
    if name == "MultiHostEngine":
        from .multihost_engine import MultiHostEngine
        return MultiHostEngine
    raise AttributeError(name)
