"""MultiHostEngine implementation — import via
raft_tla_tpu.parallel.multihost (lazily, AFTER init_distributed)."""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..config import ModelConfig
from .mesh import ShardedEngine


class MultiHostEngine(ShardedEngine):
    """ShardedEngine whose mesh spans every process's devices."""

    def __init__(self, cfg: ModelConfig, chunk: int = 512,
                 store_states: bool = False, **kw):
        if store_states:
            raise ValueError(
                "MultiHostEngine requires store_states=False (the "
                "trace archive cannot span hosts); reproduce traces "
                "with the single-host engine")
        kw.pop("devices", None)
        super().__init__(cfg, devices=jax.devices(), chunk=chunk,
                         store_states=False, **kw)

    def check(self, *args, **kw):
        if kw.get("checkpoint_path") or kw.get("resume_from"):
            raise NotImplementedError(
                "checkpoint/resume is not supported by MultiHostEngine "
                "(a multi-host checkpoint would need per-controller "
                "shard files); use ShardedEngine on one controller")
        return super().check(*args, **kw)

    # -- global-array plumbing -----------------------------------------

    def _to_device(self, carry_np):
        """Every controller holds the full logical carry in host
        memory (cheap at checker scale) and serves its local shards."""
        def leaf(x):
            x = np.asarray(x)
            sharding = NamedSharding(self.mesh, P("d"))
            return jax.make_array_from_callback(
                x.shape, sharding, lambda idx: x[idx])
        return jax.tree_util.tree_map(leaf, carry_np)

    def _fresh_sharded_carry_host(self):
        # the base builder makes process-local arrays — fine as a host
        # template (np.array on addressable arrays)
        return jax.tree_util.tree_map(
            np.array, ShardedEngine._fresh_sharded_carry(self))

    def _fresh_sharded_carry(self):
        return self._to_device(self._fresh_sharded_carry_host())

    def _grow_sharded(self, carry):
        raise RuntimeError(
            "buffer overflow in a multi-host run: pre-size "
            "lcap/vcap/fcap/scap (mid-run growth would rebuild global "
            "arrays, which is not supported across controllers)")
