"""MultiHostEngine implementation — import via
raft_tla_tpu.parallel.multihost (lazily, AFTER init_distributed)."""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..config import ModelConfig
from ..engine.bfs import CheckpointError, ckpt_carry, ckpt_read, \
    ckpt_result, ckpt_write
from .mesh import ShardedEngine


class MultiHostEngine(ShardedEngine):
    """ShardedEngine whose mesh spans every process's devices.

    Checkpoints are per-controller shard files (``<path>.proc<k>``):
    each controller writes only its addressable device rows, and resume
    rebuilds the global arrays with every controller serving its own
    rows (TLC's distributed mode checkpoints analogously — worker-local
    state files).  Mid-run capacity growth works too: the growth
    decision comes from the replicated scal matrix, so every controller
    re-homes its shards into identically-shaped new global arrays in
    lockstep."""

    def __init__(self, cfg: ModelConfig, chunk: int = 512,
                 store_states: bool = False, **kw):
        if store_states:
            raise ValueError(
                "MultiHostEngine requires store_states=False (the "
                "trace archive cannot span hosts); reproduce traces "
                "with the single-host engine")
        kw.pop("devices", None)
        super().__init__(cfg, devices=jax.devices(), chunk=chunk,
                         store_states=False, **kw)

    # -- global-array plumbing -----------------------------------------

    def _to_device(self, carry_np):
        """Every controller holds the full logical carry in host
        memory (cheap at checker scale) and serves its local shards."""
        def leaf(x):
            x = np.asarray(x)
            sharding = NamedSharding(self.mesh, P("d"))
            return jax.make_array_from_callback(
                x.shape, sharding, lambda idx: x[idx])
        return jax.tree_util.tree_map(leaf, carry_np)

    def _fresh_sharded_carry_host(self):
        # the base builder makes process-local arrays — fine as a host
        # template (np.array on addressable arrays)
        return jax.tree_util.tree_map(
            np.array, ShardedEngine._fresh_sharded_carry(self))

    def _fresh_sharded_carry(self):
        return self._to_device(self._fresh_sharded_carry_host())

    # _grow_sharded: the base implementation is global-array-safe (the
    # concats/zeros run as SPMD ops on P("d") arrays and every
    # controller takes the identical growth branch from the replicated
    # scal matrix), so mid-run growth needs no multi-host override.

    # -- per-controller checkpoint shards ------------------------------

    def _proc_path(self, path):
        return f"{path}.proc{jax.process_index()}"

    def _local_block(self, leaf):
        """Addressable [d, ...] rows of a P('d') global array as
        (device_indices, stacked numpy block)."""
        rows = []
        for s in leaf.addressable_shards:
            ix = s.index[0]
            d = (ix.start or 0) if isinstance(ix, slice) else ix
            rows.append((int(d), np.asarray(s.data)[0]))
        rows.sort(key=lambda t: t[0])
        return [d for d, _ in rows], np.stack([r for _, r in rows])

    def _save_checkpoint(self, path, carry, res, depth, n_states,
                         n_vis, n_front):
        d_idx = None
        blocks = []
        for _kp, leaf in jax.tree_util.tree_flatten_with_path(carry)[0]:
            ds, blk = self._local_block(leaf)
            d_idx = ds
            blocks.append(blk)
        # a carry-shaped pytree of the local blocks keeps ckpt leaf
        # names in lockstep with the fresh-carry template at load time
        carry_local = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(carry), blocks)
        ckpt_write(self._proc_path(path), carry_local, False, [], [],
                   [], res, dict(
                       sharded=True, multihost=True,
                       D=self.D, n_proc=jax.process_count(),
                       proc=jax.process_index(), d_idx=d_idx,
                       chunk=self.chunk, LB=self.LB, VB=self.VB,
                       FC=self.FC, SC=self.SC,
                       fam_caps=list(self.FAM_CAPS),
                       depth=depth, n_states=n_states,
                       n_vis=[int(x) for x in n_vis],
                       n_front=int(n_front), cfg=repr(self.cfg)))

    def _load_checkpoint(self, path):
        z, meta = ckpt_read(self._proc_path(path), repr(self.cfg),
                            self.chunk,
                            ("D", "n_proc", "proc", "d_idx", "LB", "VB",
                             "FC", "SC", "fam_caps"), sharded=True)
        if meta["n_proc"] != jax.process_count() or \
                meta["D"] != self.D:
            raise CheckpointError(
                f"checkpoint was written by {meta['n_proc']} "
                f"controllers x {meta['D']} devices; this run has "
                f"{jax.process_count()} controllers x {self.D}")
        if meta["proc"] != jax.process_index():
            raise CheckpointError(
                f"{self._proc_path(path)} belongs to controller "
                f"{meta['proc']}")
        self.LB, self.VB, self.FC, self.SC = (
            meta["LB"], meta["VB"], meta["FC"], meta["SC"])
        self.FAM_CAPS = tuple(int(c) for c in meta["fam_caps"])
        d_of = {int(d): r for r, d in enumerate(meta["d_idx"])}
        template = jax.eval_shape(
            lambda: ShardedEngine._fresh_sharded_carry(self))

        def to_global(block):
            # each controller serves only its own device rows; the
            # callback is never invoked for non-addressable shards
            sharding = NamedSharding(self.mesh, P("d"))
            shape = (self.D,) + block.shape[1:]

            def cb(idx, block=block):
                ix = idx[0]
                d = (ix.start or 0) if isinstance(ix, slice) else ix
                return block[d_of[int(d)]][None]
            return jax.make_array_from_callback(shape, sharding, cb)

        carry = ckpt_carry(self._proc_path(path), z, template, to_global)
        self._parents, self._lanes, self._states = [], [], []
        res = ckpt_result(z, meta)
        z.close()
        return carry, res, meta
