"""MultiHostEngine implementation — import via
raft_tla_tpu.parallel.multihost (lazily, AFTER init_distributed)."""

from __future__ import annotations

import os
import time

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..config import ModelConfig
from ..engine.bfs import CheckpointError, ckpt_archives, ckpt_carry, \
    ckpt_read, ckpt_result, ckpt_write
from .mesh import ShardedEngine, _SHARDED_CKPT_FORMAT


class MultiHostEngine(ShardedEngine):
    """ShardedEngine whose mesh spans every process's devices.

    Checkpoints are per-controller shard files (``<path>.proc<k>``):
    each controller writes only its addressable device rows, and resume
    rebuilds the global arrays with every controller serving its own
    rows (TLC's distributed mode checkpoints analogously — worker-local
    state files).  Mid-run capacity growth works too: the growth
    decision comes from the replicated scal matrix, so every controller
    re-homes its shards into identically-shaped new global arrays in
    lockstep.

    Trace archives (``store_states=True``) follow the same worker-local
    pattern: pass ``trace_dir=`` (a directory every controller can
    reach — TLC's distributed workers likewise write worker-local
    ``states/`` files to shared storage) and each controller writes its
    device shards of the per-level parent/lane/state arrays to
    ``trace_arch.proc<k>.npz`` when ``check()`` finishes.  ``trace()``
    on ANY controller then merges the files device-major (global ids
    are assigned device-major per level, so the merge reproduces the
    single-host archive exactly) and replays the parent chain — a
    violation found at mesh scale has a witness trace without a
    single-host re-run."""

    def __init__(self, cfg: ModelConfig, chunk: int = 512,
                 store_states: bool = False, trace_dir: str = None, **kw):
        if store_states and trace_dir is None:
            raise ValueError(
                "store_states under MultiHostEngine needs trace_dir= — "
                "a directory shared by every controller — so the "
                "per-controller archive shards can be merged at trace "
                "time")
        self.trace_dir = trace_dir
        self._arch_merged = False
        kw.pop("devices", None)
        super().__init__(cfg, devices=jax.devices(), chunk=chunk,
                         store_states=store_states, **kw)

    # -- per-controller trace archives ---------------------------------

    def check(self, *args, **kw):
        res = super().check(*args, **kw)
        if self.store_states:
            self._write_trace_archive(res)
        return res

    def _arch_path(self, k: int) -> str:
        return os.path.join(self.trace_dir, f"trace_arch.proc{k}.npz")

    def _run_stamp(self, res):
        """Identifies THIS run's archives: every controller computes the
        same stamp (the counts are replicated across controllers), while
        a stale file left in a reused trace_dir by a DIFFERENT run
        mismatches and keeps the merge polling instead of silently
        mixing shards.  (A rerun of the identical model on the identical
        mesh stamps identically — and, the engine being deterministic,
        writes identical archives, so the merge stays correct.)

        Counts are chunk-independent, but per-level archive ROW ORDER
        (global-id assignment) is not: it depends on the chunk/window
        packing (chunk, SC) and the buffer capacities that shape the
        spill boundaries (LB, FC).  Those parameters are therefore part
        of the stamp — a same-model run with different packing must not
        match — along with an archive-format version token so a future
        layout change can never silently merge old shards."""
        return (f"arch-v2|{self.cfg!r}|D={self.D}"
                f"|np={jax.process_count()}"
                f"|chunk={self.chunk}|SC={self.SC}|LB={self.LB}"
                f"|FC={self.FC}"
                f"|depth={res.depth}|distinct={res.distinct_states}"
                f"|generated={res.generated_states}")

    def _write_trace_archive(self, res):
        os.makedirs(self.trace_dir, exist_ok=True)
        payload = {"n_proc": np.int64(jax.process_count()),
                   "n_levels": np.int64(len(self._parents)),
                   "stamp": np.array(self._run_stamp(res))}
        for L in range(len(self._parents)):
            payload[f"par{L}"] = self._parents[L]
            payload[f"lane{L}"] = self._lanes[L]
            payload[f"segs{L}"] = np.asarray(
                self._arch_segs[L], np.int64).reshape(-1, 2)
            for k, v in self._states[L].items():
                payload[f"st{L}_{k}"] = v
        # write-then-rename so a reader polling for the file never
        # opens a half-written archive
        tmp = self._arch_path(jax.process_index()) + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **payload)
        os.replace(tmp, self._arch_path(jax.process_index()))
        self._arch_merged = False

    def _ensure_merged(self, timeout_s: float = 120.0):
        """Merge every controller's archive file into full per-level
        arrays (device-major = global id order), in place of the local
        shard archives.  Polls briefly for files other controllers may
        still be writing."""
        if self._arch_merged:
            return
        n_proc = jax.process_count()
        deadline = time.perf_counter() + timeout_s
        # this controller's own file carries the current run's stamp;
        # other controllers' files must match it (a reused trace_dir
        # can hold a previous run's archives until every controller of
        # THIS run finishes writing — poll, don't mix)
        own = np.load(self._arch_path(jax.process_index()))
        want_stamp = str(own["stamp"])
        own.close()
        files = []
        for k in range(n_proc):
            while True:
                if os.path.exists(self._arch_path(k)):
                    f = np.load(self._arch_path(k))
                    if "stamp" in f and str(f["stamp"]) == want_stamp:
                        files.append(f)
                        break
                    f.close()
                if time.perf_counter() > deadline:
                    raise FileNotFoundError(
                        f"{self._arch_path(k)}: no archive with this "
                        f"run's stamp within {timeout_s}s — did "
                        f"controller {k}'s check() finish, or is "
                        "trace_dir shared with a different run?")
                time.sleep(0.2)
        n_levels = int(files[0]["n_levels"])
        parents, lanes, states = [], [], []
        for L in range(n_levels):
            blocks = {}                       # device -> (file, off, n)
            for f in files:
                off = 0
                for d, n in f[f"segs{L}"]:
                    blocks[int(d)] = (f, off, int(n))
                    off += int(n)
            assert sorted(blocks) == list(range(self.D)), \
                (sorted(blocks), self.D)
            keys = [k[len(f"st{L}_"):] for k in files[0].files
                    if k.startswith(f"st{L}_")]

            def merged(name):
                return np.concatenate(
                    [blocks[d][0][name][blocks[d][1]:
                                        blocks[d][1] + blocks[d][2]]
                     for d in range(self.D)])

            parents.append(merged(f"par{L}"))
            lanes.append(merged(f"lane{L}"))
            states.append({k: merged(f"st{L}_{k}") for k in keys})
        for f in files:
            f.close()
        self._parents, self._lanes, self._states = parents, lanes, states
        self._arch_merged = True

    def trace(self, gid: int):
        self._ensure_merged()
        return super().trace(gid)

    def get_state_arrays(self, gid: int):
        self._ensure_merged()
        return super().get_state_arrays(gid)

    # -- global-array plumbing -----------------------------------------

    def _to_device(self, carry_np):
        """Every controller holds the full logical carry in host
        memory (cheap at checker scale) and serves its local shards."""
        def leaf(x):
            x = np.asarray(x)
            sharding = NamedSharding(self.mesh, P("d"))
            return jax.make_array_from_callback(
                x.shape, sharding, lambda idx: x[idx])
        return jax.tree_util.tree_map(leaf, carry_np)

    def _fresh_sharded_carry_host(self):
        # the base builder makes process-local arrays — fine as a host
        # template (np.array on addressable arrays)
        return jax.tree_util.tree_map(
            np.array, ShardedEngine._fresh_sharded_carry(self))

    def _fresh_sharded_carry(self):
        return self._to_device(self._fresh_sharded_carry_host())

    # _grow_sharded: the base implementation is global-array-safe (the
    # concats/zeros run as SPMD ops on P("d") arrays and every
    # controller takes the identical growth branch from the replicated
    # scal matrix), so mid-run growth needs no multi-host override.

    # -- per-controller checkpoint shards ------------------------------

    def _proc_path(self, path):
        return f"{path}.proc{jax.process_index()}"

    def _local_block(self, leaf):
        """Addressable [d, ...] rows of a P('d') global array as
        (device_indices, stacked numpy block)."""
        rows = []
        for s in leaf.addressable_shards:
            ix = s.index[0]
            d = (ix.start or 0) if isinstance(ix, slice) else ix
            rows.append((int(d), np.asarray(s.data)[0]))
        rows.sort(key=lambda t: t[0])
        return [d for d, _ in rows], np.stack([r for _, r in rows])

    def _save_checkpoint(self, path, carry, res, depth, n_states,
                         n_vis, n_front):
        d_idx = None
        blocks = []
        for _kp, leaf in jax.tree_util.tree_flatten_with_path(carry)[0]:
            ds, blk = self._local_block(leaf)
            d_idx = ds
            blocks.append(blk)
        # a carry-shaped pytree of the local blocks keeps ckpt leaf
        # names in lockstep with the fresh-carry template at load time
        carry_local = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(carry), blocks)
        # store_states × checkpoint (round 14): each controller's
        # checkpoint shard carries its OWN per-level archive rows
        # (exactly what _write_trace_archive would shard out at run
        # end) plus the device segmentation in meta, so a resumed run
        # keeps appending and the final trace_dir merge reproduces an
        # uninterrupted run's archive bit-exact
        ckpt_write(self._proc_path(path), carry_local,
                   self.store_states, self._parents, self._lanes,
                   self._states, res, dict(
                       sharded=True, ckpt_format=_SHARDED_CKPT_FORMAT, multihost=True,
                       arch_segs=[[[int(d), int(n)] for d, n in segs]
                                  for segs in self._arch_segs],
                       D=self.D, n_proc=jax.process_count(),
                       proc=jax.process_index(), d_idx=d_idx,
                       chunk=self.chunk, LB=self.LB, VB=self.VB,
                       FC=self.FC, SC=self.SC,
                       fam_caps=list(self.FAM_CAPS),
                       depth=depth, n_states=n_states,
                       n_vis=[int(x) for x in n_vis],
                       n_front=int(n_front),
                       spec=self.ir.name,
                       sym_canon=self.fpr.sym_canon,
                       ir_fingerprint=self.ir.fingerprint(),
                       cfg=repr(self.cfg)))

    def _load_checkpoint(self, path):
        from .mesh import _SHARDED_FMT
        z, meta = ckpt_read(self._proc_path(path), repr(self.cfg),
                            self.chunk,
                            ("D", "n_proc", "proc", "d_idx", "LB", "VB",
                             "FC", "SC", "fam_caps"), sharded=True,
                            expected_format=_SHARDED_FMT,
                            spec_name=self.ir.name,
                            sym_canon=self.fpr.sym_canon)
        if meta["n_proc"] != jax.process_count() or \
                meta["D"] != self.D:
            raise CheckpointError(
                f"checkpoint was written by {meta['n_proc']} "
                f"controllers x {meta['D']} devices; this run has "
                f"{jax.process_count()} controllers x {self.D}")
        if meta["proc"] != jax.process_index():
            raise CheckpointError(
                f"{self._proc_path(path)} belongs to controller "
                f"{meta['proc']}")
        self.LB, self.VB, self.FC, self.SC = (
            meta["LB"], meta["VB"], meta["FC"], meta["SC"])
        self.FAM_CAPS = tuple(int(c) for c in meta["fam_caps"])
        d_of = {int(d): r for r, d in enumerate(meta["d_idx"])}
        template = jax.eval_shape(
            lambda: ShardedEngine._fresh_sharded_carry(self))

        def to_global(block):
            # each controller serves only its own device rows; the
            # callback is never invoked for non-addressable shards
            sharding = NamedSharding(self.mesh, P("d"))
            shape = (self.D,) + block.shape[1:]

            def cb(idx, block=block):
                ix = idx[0]
                d = (ix.start or 0) if isinstance(ix, slice) else ix
                return block[d_of[int(d)]][None]
            return jax.make_array_from_callback(shape, sharding, cb)

        carry = ckpt_carry(self._proc_path(path), z, template, to_global)
        # restore this controller's archive shards (round 14: the
        # store_states × checkpoint combination works — the shard file
        # carries its controller's per-level rows; ckpt_archives'
        # compatibility gates apply unchanged)
        self._parents, self._lanes, self._states = ckpt_archives(
            z, meta, template, self.store_states)
        if self.store_states and meta["store_states"]:
            self._arch_segs = [[(int(d), int(n)) for d, n in segs]
                               for segs in meta["arch_segs"]]
            self._arch_merged = False
        else:
            self._arch_segs = []
        res = ckpt_result(z, meta)
        z.close()
        return carry, res, meta
