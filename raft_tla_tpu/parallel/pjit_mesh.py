"""Pod-scale pjit frontier: the WHOLE BFS state under named shardings
(ROADMAP item 2 — the last big perf ceiling).

Every other engine tops out at one host's devices: the classic engine
is single-chip, shard_map/pmap meshes span one controller's devices
(MultiHostEngine spans hosts but hand-routes its exchange through
``all_to_all`` inside shard_map).  This engine instead puts the full
logical BFS state — frontier rows, visited-table partitions, gid
cursors, level buffers, per-level archive staging — under
``NamedSharding``s on a ``jax.make_mesh`` spanning ALL hosts' devices,
and lets the compiler partition the UNCHANGED single-logical-program
engine:

- the carry pytree's shardings come from **rule-matched PartitionSpec
  trees** (``match_partition_rules`` — SNIPPETS.md's pjit
  shard/gather exemplar): visited-table words shard on the SLOT axis,
  frontier/level rows on the batch-last axis, scalars replicate;
- ``make_shard_and_gather_fns`` builds the boundary movers: shard fns
  re-partition host/checkpoint arrays onto the mesh, gather fns pull
  replicated host copies for the harvest/archive/checkpoint paths
  (every controller receives the full row set, so archives and
  violation decodes are controller-replicated — the
  store_states × checkpoint combination works here from day one);
- the **hash-ownership exchange is a sharding-constraint-mediated
  collective inside ONE jit program**: a candidate's claim-scatter
  into the slot-sharded table (engine/bfs._probe_insert_lax) IS the
  routing step the shard_map engines spell as an explicit
  ``all_to_all`` — ``with_sharding_constraint`` pins the table's named
  sharding and GSPMD emits the cross-device (ICI within a host, DCN
  across hosts) collectives;
- every host-read output (the packed scal vector, burst stats and
  ring archives) is declared REPLICATED in ``out_shardings``, so the
  per-level sync is one small all-gather and ``np.asarray`` works on
  every controller.

Because the engine's program is the classic Engine's — same chunk
order, same probe/claim discipline, same finalize — counts, level
sizes, global ids, archives and witness traces are bit-identical to
the single-device engine and therefore to the oracle
(tests/test_pjit.py pins it in-process on a 1-device mesh and under 2
controller processes × 2 virtual CPU devices with gloo collectives —
the DCN stand-in).

Resume rides the round-12 portable-image contract both ways: any
engine family's checkpoint loads through ``resume_image=`` (the key
SET re-inserts into the slot-sharded table — membership is a set
property — and the gid-ordered frontier rows re-partition onto the
batch axis), and this engine's checkpoints are written in the CLASSIC
engine format (gathered to host, proc-0 publish), so they resume on
the classic/spill/mesh engines through the same portable loader.

The ceiling this moves (BASELINE.md round 14): the visited table and
frontier scale with AGGREGATE pod HBM (+ host RAM via the spill
engines for the archive side), not one chip — the "run configs #1-#2
to exhaustion" substrate.

Multi-controller bring-up mirrors parallel/multihost: call
``init_distributed`` (or ``jax.distributed.initialize``) on every
host BEFORE constructing the engine, then build with
``devices=jax.devices()`` (the default) so the mesh spans the pod.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..config import ModelConfig
from ..engine.bfs import CheckpointError, Engine, U32MAX


# ---------------------------------------------------------------------------
# rule-matched PartitionSpec trees + shard/gather fns (the SNIPPETS.md
# pjit exemplar, adapted: rules are regexes over the carry's "|"-joined
# key paths; a rule names an AXIS KIND rather than a literal spec so
# one rule covers leaves of different ranks)
# ---------------------------------------------------------------------------

# kind -> how the leaf shards over the 1-D "d" mesh axis:
#   "slots" — dim 0 (the visited-table slot axis / 1-D row arrays)
#   "rows"  — the LAST axis (batch-last frontier/level state arrays)
#   "rep"   — replicated (scalars, shape anchors, counters)
# and over the 2-D ("jobs", "state") serving mesh (serve/batch round
# 17 — the batched wave carry leads every leaf with the [J] job axis):
#   "jobs"       — P("jobs") on dim 0 only (cursors, per-job rows)
#   "jobs_slots" — [J, VCAP, ...]: the table slot axis (dim 1) shards
#                  the "state" mesh axis — the dedup probe/claim
#                  scatter becomes a state-axis in-program collective
#   "jobs_rows"  — [J, ..., KB]: batch-last ring/level/archive rows
#                  shard the "state" mesh axis on the LAST dim
CARRY_RULES = [
    (r"^vis\|", "slots"),
    (r"^claims$", "slots"),
    (r"^(front|lvl)\|", "rows"),
    (r"^linv$", "rows"),
    (r"^(lpar|llane|jslot|lcon|fmask)$", "slots"),
    (r".*", "rep"),
]


def _leaf_path_name(key_path) -> str:
    return "|".join(str(getattr(p, "key", getattr(p, "idx", p)))
                    for p in key_path)


def _spec_for(kind: str, ndim: int) -> P:
    if kind == "rep" or ndim == 0:
        return P()
    if kind == "slots":
        return P(*(("d",) + (None,) * (ndim - 1)))
    if kind == "jobs" or (kind.startswith("jobs_") and ndim == 1):
        return P(*(("jobs",) + (None,) * (ndim - 1)))
    if kind == "jobs_slots":
        return P(*(("jobs", "state") + (None,) * (ndim - 2)))
    if kind == "jobs_rows":
        return P(*(("jobs",) + (None,) * (ndim - 2) + ("state",)))
    assert kind == "rows", kind
    return P(*((None,) * (ndim - 1) + ("d",)))


def match_partition_rules(rules, tree):
    """Pytree of (ShapeDtypeStruct or array) -> pytree of PartitionSpec
    by first-regex-match over the "|"-joined key path (the exemplar's
    ``match_partition_rules``, axis-kind flavored).  Every leaf must
    match some rule — the catch-all replicates."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    specs = []
    for kp, leaf in flat:
        name = _leaf_path_name(kp)
        for rx, kind in rules:
            if re.search(rx, name):
                specs.append(_spec_for(kind, np.ndim(leaf)
                                       if not hasattr(leaf, "ndim")
                                       else leaf.ndim))
                break
        else:                                   # pragma: no cover
            raise ValueError(f"no partition rule matched {name!r}")
    return jax.tree_util.tree_unflatten(treedef, specs)


def make_shard_and_gather_fns(shardings, mesh):
    """(shard_fns, gather_fns) pytrees for a sharding pytree — the
    exemplar's boundary movers.  A shard fn re-partitions a host (or
    differently-sharded) array onto its named sharding via a jitted
    identity with ``out_shardings``; a gather fn pulls a REPLICATED
    host copy (every controller's ``np.asarray`` then reads its local
    replica — multi-controller safe)."""
    rep = jax.jit(lambda x: x,
                  out_shardings=NamedSharding(mesh, P()))

    def make_shard_fn(sh):
        return jax.jit(lambda x: x, out_shardings=sh)

    def gather_fn(x):
        return np.asarray(rep(x))

    return (jax.tree_util.tree_map(make_shard_fn, shardings),
            jax.tree_util.tree_map(lambda _sh: gather_fn, shardings))


class PjitShardedEngine(Engine):
    """The classic Engine with its whole state pjit-sharded over a
    (possibly multi-host) device mesh.

    devices — the mesh's devices; defaults to ``jax.devices()``, which
    under a multi-controller run (``multihost.init_distributed``)
    spans every process's devices.  chunk is rounded up to a multiple
    of the device count (mesh._round_chunk_to_devices — an uneven
    override warns once; uneven shardings would compile but waste
    tiles on every step).

    Program identity: the compiled step/finalize/burst are the classic
    engine's traces — partitioning changes WHERE integer ops run,
    never their results — so every count, gid and trace is
    bit-identical to the single-device engine (and the oracle)."""

    def __init__(self, cfg: ModelConfig, devices=None, **kw):
        devices = list(devices) if devices is not None else jax.devices()
        self.mesh = jax.make_mesh((len(devices),), ("d",),
                                  devices=devices)
        self.D = len(devices)
        from .mesh import _round_chunk_to_devices
        kw = dict(kw, chunk=_round_chunk_to_devices(
            kw.get("chunk", 512), self.D))
        super().__init__(cfg, **kw)
        # the Pallas probe kernel is a single-device program; the lax
        # claim walk is the pjit program (its table scatter is the
        # ownership exchange) — keep the kernel off regardless of the
        # dedup_kernel flag
        self._dedup_pallas = False
        self._rep_sh = NamedSharding(self.mesh, P())
        self._table_sh = NamedSharding(self.mesh, P("d"))
        # rule-matched spec tree over the carry template (structure
        # only; shardings are shape-free, so one tree serves every
        # capacity growth)
        template = jax.eval_shape(
            lambda: Engine._fresh_carry(self, self.LCAP, self.VCAP))
        self._carry_specs = match_partition_rules(CARRY_RULES, template)
        self._carry_sh = jax.tree_util.tree_map(
            lambda spec: NamedSharding(self.mesh, spec),
            self._carry_specs,
            is_leaf=lambda x: isinstance(x, P))
        self._shard_fns, self._gather_fns = make_shard_and_gather_fns(
            self._carry_sh, self.mesh)
        self._state_keys = list(template["front"].keys())
        rep = self._rep_sh
        n_rep = {k: rep for k in self._state_keys}
        # re-jit the drivers' entry points with explicit out_shardings:
        # the carry stays under its named shardings call after call;
        # everything the host reads comes back replicated
        self._step_jit = jax.jit(self._chunk_step_impl,
                                 donate_argnums=0, static_argnums=1,
                                 out_shardings=self._carry_sh)
        self._fin_jit = jax.jit(
            self._finalize_impl, donate_argnums=0,
            out_shardings=(self._carry_sh,
                           dict(inv_ok=rep, scal=rep)))
        self._burst_jit = jax.jit(
            self._burst_impl, donate_argnums=0, static_argnums=1,
            out_shardings=(self._carry_sh,
                           dict(stats=rep, par=rep, lane=rep,
                                st=n_rep, inv=rep)))
        self._shard_carry = jax.jit(lambda c: c,
                                    out_shardings=self._carry_sh)
        self._gather_rep = jax.jit(lambda x: x, out_shardings=rep)
        self._fresh_jit_cache = {}
        self._seed_table_cache = {}

    # -- sharded state construction -----------------------------------

    def _fresh_carry(self, lcap: int, vcap: int,
                     fcap: Optional[int] = None,
                     ocap: Optional[int] = None):
        """The base builder, jitted with the carry's out_shardings so
        every buffer is BORN under its named sharding (no host-side
        materialization of the multi-GB state — the pod-scale point)."""
        key = (lcap, vcap, fcap, ocap)
        fn = self._fresh_jit_cache.get(key)
        if fn is None:
            fn = jax.jit(
                lambda: Engine._fresh_carry(self, lcap, vcap, fcap,
                                            ocap),
                out_shardings=self._carry_sh)
            self._fresh_jit_cache[key] = fn
        return fn()

    def _fetch(self, x) -> np.ndarray:
        """Harvest-path reads gather to a replicated array first, so
        ``np.asarray`` sees an addressable replica on EVERY controller
        (the base engines' process-local asarray would fail on
        non-addressable shards)."""
        return np.asarray(self._gather_rep(x))

    def _probe_insert(self, table, claims, keys, live, ranks):
        """The dedup claim walk with the table pinned to its slot
        sharding: the winners' key scatter is the hash-ownership
        exchange, mediated by this constraint as an in-program GSPMD
        collective (module docstring) — no Pallas kernel, no
        all_to_all, no host hop."""
        table = jax.lax.with_sharding_constraint(
            table, tuple(self._table_sh for _ in table))
        claims = jax.lax.with_sharding_constraint(claims,
                                                  self._table_sh)
        return self._probe_insert_lax(table, claims, keys, live, ranks)

    # -- checkpoint / resume ------------------------------------------
    #
    # Checkpoints are written in the CLASSIC engine format: the carry
    # gathers to host (one replicated copy per controller) and process
    # 0 publishes.  That makes the file portable BOTH ways — the
    # classic/spill/mesh engines resume it through the round-12
    # portable loader, and this engine resumes any of theirs via
    # resume_image (engine/bfs Engine._resume_portable) with the carry
    # re-partitioned onto the mesh by _commit_carry below.
    # ------------------------------------------------------------------

    def _gather_carry_host(self, carry):
        flat, treedef = jax.tree_util.tree_flatten(carry)
        gf = jax.tree_util.tree_leaves(self._gather_fns)
        return jax.tree_util.tree_unflatten(
            treedef, [g(x) for g, x in zip(gf, flat)])

    def _save_checkpoint(self, path, carry, res, depth, n_states,
                         n_vis, n_front):
        host = self._gather_carry_host(carry)
        if jax.process_index() == 0:
            Engine._save_checkpoint(self, path, host, res, depth,
                                    n_states, n_vis, n_front)

    def _load_checkpoint(self, path):
        carry, res, meta = Engine._load_checkpoint(self, path)
        return self._commit_carry(carry), res, meta

    def _commit_carry(self, carry):
        """Host/local carry -> the mesh's named shardings (the shard
        half of the exemplar, whole-tree)."""
        return self._shard_carry(carry)
