"""Pmapped random-walker fleets: sim/walker.SimEngine across the mesh.

Walkers are embarrassingly parallel — no frontier exchange, no dedup
routing — so the mesh story is a plain ``jax.pmap`` of the single-device
dispatch program (one persistent ``lax.while_loop`` per device) with
periodic host-side stats reduction between dispatches:

- the fleet of W walkers splits evenly into D per-device cohorts;
  walker GLOBAL ids (d * W/D + i) key the ``jax.random`` streams, so a
  fixed seed replays bit-identical trajectories regardless of the mesh
  shape (the single-device engine with the same W produces the same
  walks — tests/test_sim.py pins this);
- each device keeps its own novelty Bloom filter; the host ORs them at
  harvest (Bloom union is exact for membership, so the estimated
  distinct coverage is computed over the union);
- per dispatch the host syncs one [D, ST_LEN] stats matrix and the hit
  flags; any device's hit ends the fleet (its while_loop exits early,
  the others complete their dispatch quota).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import ModelConfig
from ..sim.walker import (ST_HIT, ST_ITERS, ST_STEPS, SimEngine,
                          SimResult, dispatch_counters)


class ShardedSimEngine:
    """D-device walker fleet.  ``walkers`` is the MESH-TOTAL fleet
    width, split evenly across devices (must divide)."""

    def __init__(self, cfg: ModelConfig, walkers: int = 1024,
                 devices: Optional[List] = None, **kw):
        self.devices = list(devices) if devices else jax.local_devices()
        self.D = len(self.devices)
        if walkers % self.D:
            raise ValueError(
                f"walkers={walkers} must divide across {self.D} devices")
        self.Wd = walkers // self.D
        self.W = walkers
        self.sim = SimEngine(cfg, walkers=self.Wd, **kw)
        self._pdisp = jax.pmap(self.sim._dispatch_impl,
                               static_broadcasted_argnums=(1, 2),
                               devices=self.devices)

    def fresh_carry(self) -> Dict:
        carries = []
        for d in range(self.D):
            self.sim.wid_base = d * self.Wd
            carries.append(self.sim.fresh_carry())
        self.sim.wid_base = 0
        return jax.device_put_sharded(
            [jax.tree_util.tree_map(np.asarray, c) for c in carries],
            self.devices)

    def run(self, steps: int, steps_per_dispatch: int = 256,
            stop_on_hit: bool = True, verbose: bool = False,
            obs=None) -> SimResult:
        from ..obs import NULL_OBS
        obs = obs if obs is not None else NULL_OBS
        t0 = time.perf_counter()
        root_hit = self.sim._check_root()
        if root_hit is not None and stop_on_hit:
            res = self._harvest(self.fresh_carry(),
                                time.perf_counter() - t0)
            res.hits.insert(0, root_hit)
            return res
        st = self.fresh_carry()
        done = 0
        while done < steps:
            k = min(steps_per_dispatch, steps - done)
            with obs.span("sim_dispatch"):
                st = self._pdisp(st, int(k), bool(stop_on_hit))
                stats = np.asarray(st["stats"])       # [D, ST_LEN]
            done = int(stats[:, ST_ITERS].max())
            if obs.enabled:
                obs.dispatch(
                    kind="sim", depth=done, frontier=self.W,
                    states=int(stats[:, ST_STEPS].sum()),
                    metrics=dispatch_counters(stats, self.W))
            if verbose:
                print(f"fleet: {done} iters, "
                      f"{int(stats[:, ST_STEPS].sum())} walker-steps "
                      f"across {self.D} devices", flush=True)
            if stop_on_hit and stats[:, ST_HIT].any():
                break
        res = self._harvest(st, time.perf_counter() - t0)
        if root_hit is not None:
            res.hits.insert(0, root_hit)
        return res

    def _harvest(self, st: Dict, seconds: float) -> SimResult:
        """Shared stats/hit assembly (sim/walker build_result +
        harvest_hits) over the [D, ...] device axis; the Bloom union is
        exact for membership, so the coverage estimate covers the whole
        fleet."""
        stats = np.asarray(st["stats"])           # [D, ST_LEN]
        bloom = np.asarray(st["bloom"])           # [D, M]
        union_bits = int(bloom.any(axis=0).sum())
        res = self.sim.build_result(stats, union_bits, self.W, seconds)
        hit = np.asarray(st["hit"])               # [D, Wd]
        if hit.any():
            traj = np.asarray(st["traj"])         # [D, R, Wd]
            hdep = np.asarray(st["hit_depth"])
            hinv = np.asarray(st["hit_inv"])
            for d in range(self.D):
                if hit[d].any():
                    self.sim.harvest_hits(res, hit[d], traj[d],
                                          hdep[d], hinv[d],
                                          d * self.Wd)
        return res

    def decode_hit(self, h: WalkerHit) -> WalkerHit:
        return self.sim.decode_hit(h)
