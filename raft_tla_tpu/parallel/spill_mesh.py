"""Spill-composed sharded BFS: the mesh scale-out story and the host-
spill depth story in ONE engine (VERDICT r4 #5).

The classic ShardedEngine (parallel/mesh) keeps each device's frontier
and level shard device-resident, so a real mesh hits the same per-chip
level-buffer wall the single-device SpillEngine (engine/spill) broke;
and the SpillEngine is single-device.  TLC's distributed mode has one
story for both — every worker spills its local queue to disk.  This
engine is that composition, TPU-shaped:

- per-device visited-table shards stay device-resident (hash-ownership
  dedup over ``all_to_all`` exactly as in parallel/mesh — ownership is
  fingerprint-derived, which is ALSO the spill partition key, so
  routing is unchanged);
- each device's FRONTIER lives in host RAM as per-device blocks and
  streams through its [D, LB] shard in segments (quantized H2D);
- each device's LEVEL shard spills to host when full and at level
  ends (quantized D2H), becoming the next per-device frontier blocks;
- trips are STEP-ATOMIC (mesh._local_step's _step_atomic mode): a
  step that overflows any shard commits on NO device — one small
  all_gather makes the trip decision global — so the host can spill /
  grow and resume from the tripped step exactly.  The whole-level
  journal replay of the classic engine is impossible here: earlier
  shard contents have already left the device.

Survivor policy: stage-1 content-canonical reduction per receive
window is unchanged; the stage-2 replace-if-smaller map (lrow) only
reaches rows still ON the device, so the canonical min is per SPILL
EPOCH (first-epoch-seen across epochs).  When no mid-level spill
occurs this engine is bit-identical to ShardedEngine; with mid-level
spills counts remain fully deterministic for a fixed (mesh, seg)
configuration, and on VIEW-only constraint sets (where the
representative's non-VIEW content cannot affect reachability) counts
equal the oracle exactly regardless of spill timing
(tests/test_spill_mesh.py forces spills every few steps and pins
oracle parity).  Constraint semantics stay prune-not-expand: pruned
rows are counted, checked and dropped host-side (engine/spill's
policy, differentially tested).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..config import ModelConfig
from ..engine.bfs import (CheckResult, CheckpointError, U32MAX,
                          Violation, ckpt_read, ckpt_result,
                          ckpt_write)
from ..obs import NULL_OBS
from ..engine import driver
from ..engine.host_table import HostPartitionedTable, insert_np
from ..engine.spill import SpillEngine
from ..ops.codec import C_OVERFLOW
from ..resil.chaos import chaos_point
from .mesh import P, ShardedEngine, _shard_map

# summary row layout ([D, Z_LEN + n_fams] int32, replicated)
(Z_NLVL, Z_NGEN, Z_OVF, Z_FOVF, Z_SOVF, Z_HOVF, Z_TRIP,
 Z_LEN) = range(8)


class SpilledShardedEngine(ShardedEngine):
    """ShardedEngine whose level/frontier shards stream through host
    RAM (module docstring).  ``lcap`` is the MESH-TOTAL level
    capacity, split evenly across devices (LB = lcap/D rows per shard,
    floored by the receive-window bound) — the same convention as
    ShardedEngine; everything else follows it too."""

    def __init__(self, cfg: ModelConfig, devices=None, chunk: int = 512,
                 store_states: bool = False, host_table: bool = False,
                 partitions: int = 4, part_cap: int = 1 << 12,
                 dev_keys: Optional[int] = None,
                 archive_dir: Optional[str] = None, **kw):
        # the parent engines' store machinery is bypassed (this check()
        # owns level assembly), so init with store OFF and compose the
        # trace archive from the spilled blocks instead (ROADMAP open
        # item: mesh-scale witnesses): every harvested block appends a
        # part in gid order, flushed per level into engine/archive
        # memmaps (archive_dir) or the in-RAM lists — Engine.trace /
        # get_state walk either backing unchanged.
        super().__init__(cfg, devices=devices, chunk=chunk,
                         store_states=False, **kw)
        self.store_states = bool(store_states)
        self.archive_dir = archive_dir
        self._cur_parts: List[dict] = []
        # host-partitioned visited table, mesh composition
        # (engine/host_table): hash-ownership routes a key to its owner
        # device (fingerprint stream W-1 mod D) exactly as before, and
        # each device's authoritative visited set moves to a
        # PER-DEVICE prefix-partitioned host table (stream 0 top bits
        # — an independent axis, so the two partitionings compose).
        # The table shard becomes a bounded per-device cache, complete
        # over the running level, reseeded from the frontier at level
        # boundaries; level keys meet the host partitions once per
        # level, per device, in the engine's deterministic
        # (spill-event, device) order, so counts are exactly those of
        # the un-composed engine.
        self.host_table = bool(host_table)
        self._track_keys = self.host_table
        self.partitions = int(partitions)
        self.part_cap = int(part_cap)
        self.VB0 = self.VB
        self.dev_keys = (int(dev_keys) if dev_keys
                         else int(self._LOAD_MAX * self.VB))
        self.hpts = None               # per-device tables, per check()
        # the classic engine's LB >= 4*FC floor is a thrash heuristic
        # for whole-level replays; this engine replays only single
        # steps, so the shard capacity honors the caller's lcap down
        # to the hard receive-window bound (LB > D*SC) — tests squeeze
        # it far below the widest level to force mid-level spills
        self.LB = self._round_lb(max(kw.get("lcap", 1 << 14) // self.D,
                                     2 * self.D * self.SC))
        self._step_atomic = True      # read at first trace of the step
        # in-burst level commits compact pruned rows out of the next
        # frontier (parallel/mesh commit note): this engine's host path
        # drops them before re-upload, and the window packing — hence
        # row order and gid assignment — must match it exactly
        self._burst_compact_frontier = True
        self.mid_level_spills = 0     # diagnostics: ovf-trip spills
        self._sseg_jit = jax.jit(self._spill_seg_call,
                                 donate_argnums=0, static_argnums=1)
        self._mslice_cache = {}
        self._mpaste_cache = {}
        self._bfront_cache = {}        # post-burst frontier fetch jits

    # -- device programs ----------------------------------------------

    def _spill_seg_call(self, carry, fam_caps):
        specs = jax.tree_util.tree_map(lambda _: P("d"), carry)
        out_specs = (specs, P(None))
        return _shard_map(
            lambda c: self._spill_seg_level(c, fam_caps), self.mesh,
            (specs,), out_specs)(carry)

    def _spill_seg_level(self, carry, fam_caps):
        """Run lock-step chunk steps until every device drained its
        frontier segment or any device tripped; report the summary
        matrix WITHOUT the classic finalize (no lvl->front swap — the
        host owns level assembly here)."""
        c = jax.tree_util.tree_map(lambda x: x[0], carry)

        def cond(c):
            more = c["base"] < c["n_front"]
            bad = c["ovf"] | c["fovf"] | c["sovf"] | c["hovf"]
            flags = jax.lax.all_gather(jnp.stack([more, bad]), "d")
            return flags[:, 0].any() & ~flags[:, 1].any()

        c = lax.while_loop(cond,
                           lambda cc: self._local_step(cc, fam_caps), c)
        summ = jax.lax.all_gather(jnp.concatenate([jnp.stack([
            c["n_lvl"], c["n_gen"],
            c["ovf"].astype(jnp.int32), c["fovf"].astype(jnp.int32),
            c["sovf"].astype(jnp.int32), c["hovf"].astype(jnp.int32),
            c["trip_base"]]), c["famx"]]), "d")
        return (jax.tree_util.tree_map(lambda x: x[None], c), summ)

    # -- host-side shard plumbing -------------------------------------

    def _fetch_shards(self, carry, nl: np.ndarray):
        """D2H of every device's filled level-shard rows (one
        quantized jit'd slice — fresh buffers, donation-safe), plus
        reset of the per-level device state.  Returns per-device blocks
        [(rows batch-major narrow, lpar, llane, linv, lcon, n)]."""
        blks = [None] * self.D
        nmax = int(nl.max())
        if nmax > 0:
            nq = SpillEngine._quantize(nmax, self.LB, floor=1 << 8)
            fn = self._mslice_cache.get(nq)
            if fn is None:
                def impl(lvl, lpar, llane, linv, lcon, lkey=None,
                         nq=nq):
                    out = (
                        {k: lax.slice_in_dim(v, 0, nq, axis=1)
                         for k, v in lvl.items()},
                        lax.slice_in_dim(lpar, 0, nq, axis=1),
                        lax.slice_in_dim(llane, 0, nq, axis=1),
                        lax.slice_in_dim(linv, 0, nq, axis=1),
                        lax.slice_in_dim(lcon, 0, nq, axis=1))
                    if lkey is not None:
                        out += (lax.slice_in_dim(lkey, 0, nq, axis=1),)
                    return out
                fn = self._mslice_cache[nq] = jax.jit(impl)
            sliced = jax.tree_util.tree_map(
                np.asarray,
                fn(carry["lvl"], carry["lpar"], carry["llane"],
                   carry["linv"], carry["lcon"],
                   carry["lkey"] if self._track_keys else None))
            lvl, lpar, llane, linv, lcon = sliced[:5]
            lkey = sliced[5] if self._track_keys else None
            for d in range(self.D):
                n = int(nl[d])
                if n:
                    blks[d] = dict(
                        rows={k: np.ascontiguousarray(v[d, :n])
                              for k, v in lvl.items()},
                        lpar=np.ascontiguousarray(lpar[d, :n]),
                        llane=np.ascontiguousarray(llane[d, :n]),
                        linv=np.ascontiguousarray(linv[d, :n]),
                        lcon=np.ascontiguousarray(lcon[d, :n]),
                        n=n)
                    if lkey is not None:
                        blks[d]["lkey"] = np.ascontiguousarray(
                            lkey[d, :n])
        # reset the per-level device state.  lrow reset closes the
        # stage-2 replacement epoch (module docstring): replacements
        # must never target rows that just left the device.
        carry["n_lvl"] = jnp.zeros((self.D,), jnp.int32)
        carry["lrow"] = jnp.full((self.D, self.VB), -1, jnp.int32)
        return carry, blks

    def _upload_seg(self, carry, seg):
        """Quantized H2D of one frontier segment: seg is a per-device
        list of (rows batch-major narrow, gids) or None."""
        ns = [0 if s is None else int(s[1].shape[0]) for s in seg]
        nq = SpillEngine._quantize(max(max(ns), 1), self.LB,
                                  floor=1 << 8)
        one = self.ir.narrow(self.lay, self.ir.encode(
            self.lay, *self.ir.init_state(self.cfg)))
        rows_np = {k: np.zeros((self.D, nq) + v.shape, v.dtype)
                   for k, v in one.items()}
        gids_np = np.full((self.D, nq), -1, np.int32)
        for d, s in enumerate(seg):
            if s is None:
                continue
            rows, gids = s
            for k in rows_np:
                rows_np[k][d, :ns[d]] = rows[k]
            gids_np[d, :ns[d]] = gids
        fn = self._mpaste_cache.get(nq)
        if fn is None:
            def impl(front, fgids, blocks, bg):
                front = {k: lax.dynamic_update_slice(
                    v, blocks[k], (0, 0) + (0,) * (v.ndim - 2))
                    for k, v in front.items()}
                return front, lax.dynamic_update_slice(fgids, bg, (0, 0))
            fn = self._mpaste_cache[nq] = jax.jit(
                impl, donate_argnums=(0, 1))
        carry["front"], carry["gids"] = fn(
            carry["front"], carry["gids"],
            {k: jnp.asarray(v) for k, v in rows_np.items()},
            jnp.asarray(gids_np))
        carry["n_front"] = jnp.asarray(np.asarray(ns, np.int32))
        carry["base"] = jnp.zeros((self.D,), jnp.int32)
        # prune-not-expand ran host-side (pruned rows never uploaded),
        # so every uploaded row is expandable; the step's fmask gate
        # must not mask them (the classic engine uses fmask to keep
        # pruned rows in place instead)
        LB = carry["fmask"].shape[1]
        carry["fmask"] = jnp.ones((self.D, LB), bool)
        return carry

    @staticmethod
    def _resegment_dev(blocks_per_dev, seg: int):
        """Per-device re-segmentation, lock-step across devices: yields
        per-device [(rows, gids) or None] lists of <= seg rows."""
        cursors = [list(b) for b in blocks_per_dev]
        while any(cursors):
            out = []
            for d, q in enumerate(cursors):
                take_rows, take_gids, have = [], [], 0
                while q and have < seg:
                    rows, gids = q[0]
                    n = int(gids.shape[0])
                    t = min(seg - have, n)
                    take_rows.append({k: v[:t]
                                      for k, v in rows.items()})
                    take_gids.append(gids[:t])
                    have += t
                    if t == n:
                        q.pop(0)
                    else:
                        q[0] = ({k: v[t:] for k, v in rows.items()},
                                gids[t:])
                if have:
                    keys = take_rows[0].keys()
                    out.append((
                        {k: np.concatenate([r[k] for r in take_rows])
                         for k in keys},
                        np.concatenate(take_gids)))
                else:
                    out.append(None)
            yield out

    # -- the check loop -----------------------------------------------

    def check(self, max_depth: int = 10 ** 9, max_states: int = 10 ** 9,
              stop_on_violation: bool = False,
              seed_states: Optional[List] = None,
              checkpoint_path: Optional[str] = None,
              checkpoint_every: int = 1,
              resume_from: Optional[str] = None,
              resume_image=None,
              verbose: bool = False, obs=None) -> CheckResult:
        """Checkpointing (round 12): at a level boundary the whole
        wavefront is host-reachable here too — the frontier blocks are
        host numpy, the visited set is either the device shards (one
        pooled sparse fetch) or the per-device host partitions, and
        ownership is a pure function of key content.  The checkpoint
        therefore stores the wavefront POOLED in gid order (the
        portable form), and resume re-routes rows and keys by hash
        ownership — which also makes ``resume_image`` (a checkpoint
        from any engine family / any mesh size) the same code path."""
        assert jax.process_count() == 1, \
            "single-controller engine (MultiHostEngine composition " \
            "is future work)"
        obs = self._obs = obs if obs is not None else NULL_OBS
        t0 = time.perf_counter()
        lay = self.lay
        D, W = self.D, self.W
        if resume_from is not None and resume_image is not None:
            raise ValueError(
                "resume_from and resume_image are mutually exclusive")
        resumed = False
        if resume_from is not None:
            (carry, res, frontier, frontier_keys, n_states, n_vis,
             depth) = self._load_spill_mesh_checkpoint(resume_from)
            resumed = True
        elif resume_image is not None:
            (carry, res, frontier, frontier_keys, n_states, n_vis,
             depth) = self._resume_portable(resume_image)
            resumed = True
        else:
            self._init_store()
            self._cur_parts = []

            # ---- roots: hash-owner placement into host blocks -------
            roots, rk, pin_interiors = self._dedup_roots(seed_states)
            res = CheckResult(distinct_states=0,
                              generated_states=len(rk), depth=0)
            self._check_pin_interiors(pin_interiors, res)
            per_dev: List[List[int]] = [[] for _ in range(D)]
            for r in range(len(rk)):
                per_dev[int(rk[r, W - 1]) % D].append(r)
            inv_r, con_r = (np.asarray(a) for a in self._phase2(
                {k: jnp.asarray(v) for k, v in roots.items()}))
            roots_n = self.ir.narrow(lay, roots)

            if self.host_table:
                self.hpts = [HostPartitionedTable(
                    W, partitions=self.partitions,
                    part_cap=self.part_cap) for _ in range(D)]
            carry = self._fresh_sharded_carry()
            vis_np = [np.array(t) for t in carry["vis"]]  # writable
            root_blks = [None] * D
            for d in range(D):
                idx = per_dev[d]
                if not idx:
                    continue
                rkd = rk[idx]
                slots = self._host_probe_assign(rkd, vcap=self.VB)
                for r, sl in enumerate(slots):
                    for w in range(W):
                        vis_np[w][d, sl] = rkd[r, w]
                root_blks[d] = dict(
                    rows={k: np.stack([np.asarray(roots_n[k][i])
                                       for i in idx]) for k in roots_n},
                    lpar=np.full((len(idx),), -1, np.int32),
                    llane=np.full((len(idx),), -1, np.int32),
                    linv=inv_r[idx], lcon=con_r[idx], n=len(idx))
                if self.host_table:
                    root_blks[d]["lkey"] = rkd.astype(np.uint32)
                    # roots enter the per-device host partitions
                    # through the same sweep as every level (all fresh)
                    self.hpts[d].sweep(root_blks[d]["lkey"])
            carry["vis"] = tuple(jnp.asarray(v) for v in vis_np)

            n_states = 0
            n_vis = np.array([len(p) for p in per_dev], np.int64)
            depth = 0
        self._stamp_mode(res)

        def harvest_blocks(blks):
            """Device-major harvest of one spill event's blocks:
            counts, violations, next-frontier rows (pruned rows
            dropped, prune-not-expand).  Returns per-device
            (rows, gids) or None."""
            nonlocal n_states
            _hv = obs.span("harvest")
            _hv.__enter__()
            out = [None] * D
            for d in range(D):
                blk = blks[d]
                if blk is None:
                    continue
                n = blk["n"]
                res.distinct_states += n
                res.overflow_faults += int(
                    (blk["rows"]["ctr"][:, C_OVERFLOW] > 0).sum())
                gids = np.arange(n_states, n_states + n,
                                 dtype=np.int32)
                inv_ok = blk["linv"]
                if inv_ok.size and not inv_ok.all():
                    bad = np.nonzero(~inv_ok)
                    res.violations_global += len(bad[0])
                    for s, j in zip(*bad):
                        vsv, vh = self.ir.decode(lay, {
                            k: np.asarray(v[s])
                            for k, v in blk["rows"].items()})
                        res.violations.append(Violation(
                            self.inv_names[j], int(gids[s]),
                            state=vsv, hist=vh))
                n_states += n
                driver.guard_id_space(n_states)
                if self.store_states:
                    # archive part in gid order (this loop assigns gids
                    # device-major per harvest event, so appending here
                    # keeps the archive's row order == gid order)
                    self._cur_parts.append(dict(
                        n=n, lpar=blk["lpar"], llane=blk["llane"],
                        rows_major=blk["rows"]))
                con = blk["lcon"].astype(bool)
                if con.all():
                    out[d] = (blk["rows"], gids, blk.get("lkey"))
                elif con.any():
                    keep = np.nonzero(con)[0]
                    out[d] = ({k: v[keep]
                               for k, v in blk["rows"].items()},
                              gids[keep],
                              blk["lkey"][keep]
                              if "lkey" in blk else None)
            _hv.__exit__(None, None, None)
            return out

        if not resumed:
            frontier = [[] for _ in range(D)]
            frontier_keys = [[] for _ in range(D)]
            root_front = harvest_blocks(root_blks)
            self._flush_level_parts()
            for d in range(D):
                if root_front[d] is not None:
                    rows_r, gids_r, fk_r = root_front[d]
                    frontier[d].append((rows_r, gids_r))
                    if fk_r is not None:
                        frontier_keys[d].append(fk_r)
            res.generated_states = len(rk)
        if stop_on_violation and res.violations:
            res.seconds = time.perf_counter() - t0
            return res

        # burst_ok: a burst that committed levels then bailed keeps the
        # bailing level's frontier intact — re-entering would replay
        # the identical chunks and bail again (one wasted round trip),
        # so skip the burst for that level; the segment driver re-arms
        burst_ok = True
        while any(frontier) and depth < max_depth and \
                res.distinct_states < max_states:
            # chaos site: dispatch-time device/tunnel error at the
            # level boundary (resil/chaos) — before any device work,
            # so the last checkpoint stays the exact resume point
            chaos_point("dispatch")
            if (self.burst and burst_ok and not self.host_table and
                    max(sum(int(g.shape[0]) for _r, g in q)
                        for q in frontier) <= self._mesh_burst_width()):
                d0 = depth
                (carry, frontier, depth, n_states, n_vis,
                 fused, bailed) = self._burst_mesh_levels(
                    carry, frontier, res, depth, n_states, n_vis,
                    max_depth, max_states, verbose)
                if fused:
                    burst_ok = not bailed
                    if checkpoint_path is not None and \
                            driver.ckpt_due_after_burst(
                                depth, d0, checkpoint_every):
                        self._save_spill_mesh_checkpoint(
                            checkpoint_path, carry, res, frontier,
                            frontier_keys, depth, n_states, n_vis)
                    if stop_on_violation and res.violations:
                        break
                    continue
                # first level bailed: the segment driver (with its
                # growth machinery) runs it below
            burst_ok = True        # re-arm after a per-level level
            depth += 1
            SEGB = self.LB             # per-device segment rows
            t1 = time.perf_counter()
            level_new = 0
            level_gen = 0
            next_frontier: List[List] = [[] for _ in range(D)]
            next_keys: List[List] = [[] for _ in range(D)]
            level_events: List[List] = []    # host-table: defer harvest

            def settle(blks):
                nonlocal level_new, n_vis
                for d in range(D):
                    if blks[d] is not None:
                        n_vis[d] += blks[d]["n"]
                        if not self.host_table:
                            level_new += blks[d]["n"]
                if self.host_table:
                    # harvest defers to the level-end per-device
                    # partition sweep (module docstring)
                    if any(b is not None for b in blks):
                        level_events.append(blks)
                    return
                outs = harvest_blocks(blks)
                for d in range(D):
                    if outs[d] is not None:
                        next_frontier[d].append(outs[d][:2])

            _lvl_span = obs.span("level_dispatch")
            _lvl_span.__enter__()
            for seg in self._resegment_dev(frontier, SEGB):
                carry = self._sgrow_table_if_needed(carry, n_vis)
                carry = self._upload_seg(carry, seg)
                while True:
                    carry, summ = self._sseg_jit(carry, self.FAM_CAPS)
                    s = np.asarray(summ)        # [D, Z_LEN + n_fams]
                    level_gen += int(s[:, Z_NGEN].sum())
                    carry["n_gen"] = jnp.zeros((D,), jnp.int32)
                    if not (s[:, Z_OVF].any() or s[:, Z_FOVF].any()
                            or s[:, Z_SOVF].any()
                            or s[:, Z_HOVF].any()):
                        break
                    carry = self._handle_mesh_trip(carry, s, n_vis,
                                                   settle, verbose)
            # level end: spill the remainder everywhere
            nl = np.asarray(carry["n_lvl"])
            carry, blks = self._fetch_shards(carry, nl)
            _lvl_span.__exit__(None, None, None)
            settle(blks)
            if self.host_table and level_events:
                # per-device key streams in (spill-event) order: each
                # device's keys are unique within the level (its table
                # shard is complete over the level) and disjoint across
                # devices (hash-ownership), so the sweeps are
                # independent; the keep verdicts then filter the
                # event-ordered blocks so gid assignment keeps the
                # engine's deterministic (event, device) order
                with obs.span("host_sweep"):
                    for d in range(D):
                        dev_blks = [ev[d] for ev in level_events
                                    if ev[d] is not None]
                        if not dev_blks:
                            continue
                        keys = np.concatenate(
                            [b["lkey"][:b["n"]] for b in dev_blks])
                        keep = self.hpts[d].sweep(
                            keys.astype(np.uint32))
                        off = 0
                        for b in dev_blks:
                            nb = b["n"]
                            b["_keep"] = keep[off:off + nb]
                            off += nb
                for ev in level_events:
                    fblks = [self._filter_blk(ev[d]) for d in range(D)]
                    for d in range(D):
                        if fblks[d] is not None:
                            level_new += fblks[d]["n"]
                    outs = harvest_blocks(fblks)
                    for d in range(D):
                        if outs[d] is not None:
                            rows_b, gids_b, fk_b = outs[d]
                            next_frontier[d].append((rows_b, gids_b))
                            next_keys[d].append(fk_b)
            self._flush_level_parts()
            res.generated_states += level_gen
            depth = driver.gate_level_depth(
                res, depth, level_new, level_gen,
                sum(int(g.shape[0]) for q in next_frontier
                    for _r, g in q))
            frontier = next_frontier
            frontier_keys = next_keys
            if self.host_table and int(n_vis.max()) > self.dev_keys:
                # level boundary: reseed every table shard with just
                # its frontier's keys (the host partitions answer for
                # everything archived)
                carry, n_vis = self._reseed_shards(carry, frontier_keys)
            if checkpoint_path is not None and \
                    driver.ckpt_due_at_level(depth, checkpoint_every):
                self._save_spill_mesh_checkpoint(
                    checkpoint_path, carry, res, frontier,
                    frontier_keys, depth, n_states, n_vis)
            obs.dispatch(
                kind="level", depth=depth,
                frontier=sum(int(g.shape[0])
                             for q in frontier for _r, g in q),
                metrics=res.metrics.as_dict())
            if stop_on_violation and res.violations:
                break
            if verbose:
                print(f"depth {depth}: +{level_new} states "
                      f"(total {res.distinct_states}), frontier "
                      f"{sum(int(g.shape[0]) for q in frontier for _r, g in q)}, "
                      f"{time.perf_counter() - t1:.2f}s", flush=True)
        res.depth = depth
        res.seconds = time.perf_counter() - t0
        return res

    # -- checkpoint / resume (round 12, ROADMAP item-5 closure) --------
    # At a level boundary the wavefront is host-reachable: frontier
    # blocks are host numpy, the visited set is the device shards (one
    # pooled sparse fetch) or the per-device host partitions.  The file
    # stores the wavefront POOLED in gid order — the portable form —
    # because hash ownership (key[W-1] % D) is a pure function of
    # content: resume re-routes rows and keys to their owners, which
    # reproduces the original per-device assignment exactly on the same
    # mesh, and re-partitions it on any other shape via resume_image.
    # The device-table slot layout is NOT serialized (membership is a
    # set property; rebuilt images dedup identically), and under
    # host_table the device cache resumes reseeded to the frontier's
    # keys — a state the engine itself produces at reseed boundaries,
    # so counts/gids/archives stay bit-exact (tests/test_resil.py).
    # ------------------------------------------------------------------

    _SM_EXTRA_KEYS = ("D", "LB", "VB", "FC", "SC", "fam_caps",
                      "host_table", "partitions")
    _SM_FMT = ("sm_format", 1,
               "the spill-mesh pooled-wavefront layout")

    def _pool_frontier(self, frontier, frontier_keys):
        """Per-device frontier queues -> (rows batch-major, gids,
        fkeys) pooled in global-id order (fkeys None outside
        host-table mode)."""
        rows_l, gids_l, keys_l = [], [], []
        for d in range(self.D):
            blocks = frontier[d]
            kq = (frontier_keys[d] if self.host_table
                  else [None] * len(blocks))
            for bi, (rows, gids) in enumerate(blocks):
                rows_l.append(rows)
                gids_l.append(gids)
                keys_l.append(kq[bi])
        if gids_l:
            g = np.concatenate(gids_l)
            order = np.argsort(g, kind="stable")
            keys0 = rows_l[0].keys()
            pf_rows = {k: np.ascontiguousarray(np.concatenate(
                [r[k] for r in rows_l])[order]) for k in keys0}
            pf_g = g[order].astype(np.int32)
            pfk = (np.concatenate(keys_l)[order].astype(np.uint32)
                   if self.host_table else None)
            return pf_rows, pf_g, pfk
        one = self.ir.narrow(self.lay, self.ir.encode(
            self.lay, *self.ir.init_state(self.cfg)))
        pf_rows = {k: np.zeros((0,) + v.shape, v.dtype)
                   for k, v in one.items()}
        return (pf_rows, np.zeros((0,), np.int32),
                np.zeros((0, self.W), np.uint32)
                if self.host_table else None)

    def _save_spill_mesh_checkpoint(self, path, carry, res, frontier,
                                    frontier_keys, depth, n_states,
                                    n_vis):
        with self._obs.span("checkpoint"):
            from ..resil.portable import dense_table_keys
            D, W = self.D, self.W
            ckpt = {}
            pf_rows, pf_g, pfk = self._pool_frontier(frontier,
                                                     frontier_keys)
            ckpt["pf|g"] = pf_g
            for k, v in pf_rows.items():
                ckpt[f"pf|rows|{k}"] = v
            if self.host_table:
                ckpt["pfk"] = pfk
                for d in range(D):
                    ckpt.update(self.hpts[d].state_dict(
                        prefix=f"hpt{d}"))
            else:
                vis_np = [np.asarray(t) for t in carry["vis"]]
                ckpt["keys"] = dense_table_keys(vis_np)
            parents, lanes, states, arch_meta = self._ckpt_store_args()
            ckpt_write(path, ckpt, self.store_states, parents, lanes,
                       states, res, dict(
                           spill=True, sharded=True, sm_format=1,
                           D=D, W=W, host_table=self.host_table,
                           partitions=self.partitions,
                           depth=depth, n_states=n_states,
                           n_vis=[int(x) for x in n_vis],
                           n_front=int(pf_g.shape[0]),
                           LB=self.LB, VB=self.VB, FC=self.FC,
                           SC=self.SC,
                           fam_caps=list(self.FAM_CAPS), **arch_meta,
                           layout=2, chunk=self.chunk,
                           spec=self.ir.name,
                           sym_canon=self.fpr.sym_canon,
                           ir_fingerprint=self.ir.fingerprint(),
                           cfg=repr(self.cfg)),
                       keep=self.ckpt_keep)

    def _load_spill_mesh_checkpoint(self, path):
        z, meta = ckpt_read(path, repr(self.cfg), self.chunk,
                            self._SM_EXTRA_KEYS, sharded=True,
                            spill=True, expected_format=self._SM_FMT,
                            spec_name=self.ir.name,
                            sym_canon=self.fpr.sym_canon)
        if meta["D"] != self.D:
            raise CheckpointError(
                f"checkpoint was written on a {meta['D']}-device "
                f"mesh; this engine has {self.D} devices — exact "
                "resume needs the same mesh, or re-partition with a "
                "portable resume (resume_image / --resume-portable)")
        if bool(meta.get("host_table")) != self.host_table:
            raise CheckpointError(
                f"{path}: checkpoint was written with host_table="
                f"{bool(meta.get('host_table'))}; resume with the "
                "same setting")
        if self.host_table and meta["partitions"] != self.partitions:
            raise CheckpointError(
                f"{path}: checkpoint has {meta['partitions']} "
                f"host-table partitions; engine has "
                f"{self.partitions} — resume with the same "
                "--partitions (counts are P-invariant, but the "
                "serialized images are not)")
        # capacities restore so segmentation — and therefore spill
        # event boundaries, row order and gid assignment — match the
        # interrupted run exactly
        self.LB = int(meta["LB"])
        self.VB = int(meta["VB"])
        self.FC = int(meta["FC"])
        self.SC = int(meta["SC"])
        self.FAM_CAPS = tuple(int(c) for c in meta["fam_caps"])
        rows = {}
        for nm in z.files:
            if nm.startswith("carry|pf|rows|"):
                rows[nm.split("|", 3)[3]] = np.asarray(z[nm])
        gids = np.asarray(z["carry|pf|g"]).astype(np.int32)
        if self.host_table:
            fkeys = np.asarray(z["carry|pfk"]).astype(np.uint32)
            self.hpts = [HostPartitionedTable.from_state(
                (lambda nm, _d=d: z["carry|" + nm]),
                prefix=f"hpt{d}") for d in range(self.D)]
            keys = None
        else:
            fkeys = None
            keys = np.asarray(z["carry|keys"]).astype(np.uint32)
        template = {"lvl": rows}
        self._load_archives(path, z, meta, template)
        self._cur_parts = []
        res = ckpt_result(z, meta)
        (carry, frontier, frontier_keys,
         n_vis) = self._restore_wavefront(keys, rows, gids, fkeys,
                                          exact_vb=True)
        z.close()
        return (carry, res, frontier, frontier_keys,
                meta["n_states"], n_vis, meta["depth"])

    def _resume_portable(self, img):
        """Shape-portable resume: re-partition a PortableImage (from
        ANY engine family / mesh size) onto this mesh — visited keys
        and frontier rows re-route by hash ownership; under host_table
        the archive set re-sweeps into fresh per-device partitions
        (any --partitions works)."""
        from ..resil.portable import validate_image
        validate_image(img, self.ir.name, repr(self.cfg), self.W)
        self._restore_portable_archives(img)
        self._cur_parts = []
        rows, gids = img.expandable()
        keys = img.keys.astype(np.uint32)
        if self.host_table:
            self.hpts = [HostPartitionedTable(
                self.W, partitions=self.partitions,
                part_cap=self.part_cap) for _ in range(self.D)]
            owner = keys[:, self.W - 1].astype(np.int64) % self.D
            step = 1 << 16
            for d in range(self.D):
                kd = keys[owner == d]
                for i in range(0, kd.shape[0], step):
                    self.hpts[d].sweep(
                        np.ascontiguousarray(kd[i:i + step]))
            keys = None
        (carry, frontier, frontier_keys,
         n_vis) = self._restore_wavefront(keys, rows, gids, None)
        return (carry, img.fresh_result(), frontier, frontier_keys,
                img.n_states, n_vis, img.depth)

    def _restore_wavefront(self, keys, rows, gids, fkeys,
                           exact_vb=False):
        """Pooled wavefront -> this mesh's per-device state: route
        frontier rows (and, non-host-table, the visited keys) to their
        hash owners, rebuild per-device table images with the host
        insert twin, and return (carry, frontier, frontier_keys,
        n_vis).  Under host_table the device shards reseed with the
        frontier's keys only — exactly the reseed-boundary state; the
        partitions (restored or re-swept by the caller) answer for
        everything archived."""
        D, W = self.D, self.W
        if gids.shape[0] and fkeys is None:
            b = {k: jnp.asarray(v)
                 for k, v in self.ir.widen(rows).items()}
            fkeys = np.asarray(self._rootfp_jit(b)).astype(np.uint32)
        frontier: List[List] = [[] for _ in range(D)]
        frontier_keys: List[List] = [[] for _ in range(D)]
        if gids.shape[0]:
            fowner = fkeys[:, W - 1].astype(np.int64) % D
            for d in range(D):
                idx = np.nonzero(fowner == d)[0]
                if len(idx):
                    frontier[d].append((
                        {k: np.ascontiguousarray(v[idx])
                         for k, v in rows.items()},
                        gids[idx].astype(np.int32)))
                    if self.host_table:
                        frontier_keys[d].append(
                            np.ascontiguousarray(fkeys[idx]))
        if self.host_table:
            key_src = [np.concatenate(q) if q
                       else np.zeros((0, W), np.uint32)
                       for q in frontier_keys]
        else:
            owner = keys[:, W - 1].astype(np.int64) % D
            key_src = [np.ascontiguousarray(keys[owner == d])
                       for d in range(D)]
        n_vis = np.array([k.shape[0] for k in key_src], np.int64)
        if not exact_vb:
            if self.host_table:
                self.VB = self.VB0
            while int(n_vis.max(initial=0)) + self.LB > \
                    self._LOAD_MAX * self.VB:
                self.VB *= 4
        carry = self._fresh_sharded_carry()
        vis_np = [np.full((D, self.VB), np.uint32(0xFFFFFFFF),
                          np.uint32) for _ in range(W)]
        for d in range(D):
            if key_src[d].shape[0]:
                img = np.full((W, self.VB), np.uint32(0xFFFFFFFF),
                              np.uint32)
                insert_np(img, key_src[d])
                for w in range(W):
                    vis_np[w][d] = img[w]
        carry["vis"] = tuple(jnp.asarray(v) for v in vis_np)
        return carry, frontier, frontier_keys, n_vis

    # -- trace-archive composition ------------------------------------

    def _flush_level_parts(self):
        """One finished level's harvested blocks -> the trace archive
        (engine/archive memmaps under archive_dir, else the in-RAM
        lists).  Row order within the level is exactly gid order, so
        the inherited Engine.trace / get_state_arrays walk works
        unchanged; a level that archived nothing appends nothing (the
        archives' gid->row mapping is cumulative, not per-level)."""
        if not self.store_states:
            return
        parts, self._cur_parts = self._cur_parts, []
        if not parts:
            return
        with self._obs.span("archive_io"):
            if self._arch is not None:
                self._arch.append_level_parts(parts)
                return
            self._parents.append(np.concatenate(
                [p["lpar"][:p["n"]] for p in parts]))
            self._lanes.append(np.concatenate(
                [p["llane"][:p["n"]] for p in parts]))
            keys = parts[0]["rows_major"].keys()
            self._states.append(
                {k: np.concatenate([p["rows_major"][k][:p["n"]]
                                    for p in parts]) for k in keys})

    # -- host-partitioned table composition ---------------------------

    @staticmethod
    def _filter_blk(blk):
        """Apply a sweep keep-verdict to one spilled block (rows whose
        key an earlier level archived drop before any counting)."""
        if blk is None or "_keep" not in blk:
            return blk
        kb = blk.pop("_keep")
        if kb.all():
            return blk
        kidx = np.nonzero(kb)[0]
        if not len(kidx):
            return None
        return dict(
            rows={k: np.ascontiguousarray(v[kidx])
                  for k, v in blk["rows"].items()},
            lpar=blk["lpar"][kidx], llane=blk["llane"][kidx],
            linv=blk["linv"][kidx], lcon=blk["lcon"][kidx],
            lkey=blk["lkey"][kidx], n=len(kidx))

    def _reseed_shards(self, carry, frontier_keys):
        """Reset every device's table shard to its own frontier's keys
        at (near) the initial capacity.  The shard images build
        host-side with engine/host_table.insert_np — the numpy twin of
        the device claim-insert, same home hash and probe walk — and
        upload in one piece; claims and the stage-2 lrow map reset with
        them."""
        D, W = self.D, self.W
        fk = [(np.concatenate(q).astype(np.uint32) if q else
               np.zeros((0, W), np.uint32)) for q in frontier_keys]
        nmax = max(k.shape[0] for k in fk)
        self.VB = self.VB0
        while nmax + self.LB > self._LOAD_MAX * self.VB:
            self.VB *= 4
        vis_np = [np.full((D, self.VB), np.uint32(0xFFFFFFFF),
                          np.uint32) for _ in range(W)]
        for d in range(D):
            if not fk[d].shape[0]:
                continue
            img = np.full((W, self.VB), np.uint32(0xFFFFFFFF),
                          np.uint32)
            insert_np(img, fk[d])
            for w in range(W):
                vis_np[w][d] = img[w]
        carry = dict(carry,
                     vis=tuple(jnp.asarray(v) for v in vis_np),
                     claims=jnp.full((D, self.VB), U32MAX),
                     lrow=jnp.full((D, self.VB), -1, jnp.int32))
        return carry, np.array([k.shape[0] for k in fk], np.int64)

    # -- fused multi-level burst --------------------------------------
    # While every device's frontier fits the burst ring and the
    # host-table sweep is not in play (host_table sweeps every level),
    # whole levels run inside ONE shard_map program (_shard_burst,
    # parallel/mesh) instead of the upload/window/fetch round trips of
    # the segment driver.  With no mid-level spill possible inside a
    # burst (any overflow bails the level), the stage-2
    # content-canonical epoch covers the whole level and the gid
    # assignment (device-major arithmetic in-loop) coincides exactly
    # with this engine's (event, device) harvest order — so counts,
    # archives and traces are bit-identical to the un-bursted path.
    # -----------------------------------------------------------------

    def _burst_mesh_levels(self, carry, frontier, res, depth, n_states,
                           n_vis, max_depth, max_states, verbose):
        """One fused K-level device call on tiny per-device frontiers.
        Returns (carry, frontier, depth, n_states, n_vis, fused,
        bailed) — fused=False means the first level bailed and the
        segment driver must run it (host frontier blocks left
        untouched); bailed=True means the call ended in a bail (even
        after committing levels), so re-entering the burst on the
        unchanged frontier would deterministically bail again."""
        t1 = time.perf_counter()
        lay = self.lay
        D = self.D
        obs = self._obs
        with obs.span("burst_dispatch"):
            kbd = self._mesh_burst_width()
            seg = []
            for q in frontier:
                if q:
                    keys = q[0][0].keys()
                    seg.append((
                        {k: np.concatenate([r[k] for r, _g in q])
                         for k in keys},
                        np.concatenate([g for _r, g in q])))
                else:
                    seg.append(None)
            carry = self._sgrow_table_if_needed(
                carry, n_vis, min_add=self.burst_levels * kbd)
            carry = self._upload_seg(carry, seg)
            # the burst's in-loop gid refresh is device-major
            # arithmetic from g_off; seed it at the next id this
            # engine would assign
            carry["g_off"] = jnp.full((D,), n_states, jnp.int32)
            lv_left = min(self.burst_levels, max_depth - depth)
            st_cap = max(1, min(max_states - res.distinct_states,
                                2 ** 31 - 1))
            carry, bout = self._burst_mesh_jit(
                carry, self.FAM_CAPS, jnp.int32(lv_left),
                jnp.int32(st_cap))
            stats = np.asarray(bout["stats"])       # [D, L_MAX+1, NS]
        nlev = int(stats[0, -1, 0])
        bailed = bool(stats[0, -1, 1])
        res.burst_dispatches += 1
        res.burst_bailouts += int(bailed)
        if nlev == 0:
            return (carry, frontier, depth, n_states, n_vis, False,
                    bailed)
        viol_any = bool(stats[0, -1, 3])
        _hv_span = obs.span("harvest")
        _hv_span.__enter__()
        par_h = lane_h = st_h = inv_h = None
        if self.store_states or viol_any:
            par_h = np.asarray(bout["par"])     # [D, L_MAX, kbd]
            lane_h = np.asarray(bout["lane"])
            st_h = {k: np.asarray(v) for k, v in bout["st"].items()}
            inv_h = np.asarray(bout["inv"])     # [D, L_MAX, kbd, n_inv]
        def _stats(li):
            return (int(stats[:, li, 0].sum()),
                    int(stats[:, li, 1].sum()),
                    int(stats[:, li, 2].sum()),
                    int(stats[:, li, 3].sum()),
                    int(stats[:, li, 4].sum()))

        def _arch(li, _n_lvl):
            if not self.store_states:
                return
            nl = stats[:, li, 0]
            for d in range(D):
                if not nl[d]:
                    continue
                # archive part in gid order (device-major per level —
                # exactly harvest_blocks' order)
                self._cur_parts.append(dict(
                    n=int(nl[d]),
                    lpar=par_h[d, li, :nl[d]].copy(),
                    llane=lane_h[d, li, :nl[d]].copy(),
                    rows_major={k: st_h[k][d, li, :nl[d]].copy()
                                for k in st_h}))

        def _viol(li, _n_lvl, gid_base):
            nl = stats[:, li, 0]
            prefix = np.cumsum(nl) - nl
            for d in range(D):
                if not nl[d] or not stats[d, li, 1]:
                    continue
                inv_ok = inv_h[d, li, :nl[d]]
                for s, j in zip(*np.nonzero(~inv_ok)):
                    vsv, vh = self.ir.decode(lay, {
                        k: np.asarray(st_h[k][d, li, s])
                        for k in st_h})
                    res.violations.append(Violation(
                        self.inv_names[j],
                        gid_base + int(prefix[d]) + int(s),
                        state=vsv, hist=vh))

        def _vis(li, _n_lvl):
            # the per-level part flush rides the shared loop's
            # post-level hook (it moves archive parts only — counters
            # never read it)
            self._flush_level_parts()
            for d in range(D):
                n_vis[d] += stats[d, li, 0]

        depth, n_states = driver.harvest_fused_levels(
            res, nlev, _stats, depth, n_states, archive=_arch,
            violations=_viol, visited=_vis)
        _hv_span.__exit__(None, None, None)
        # rebuild the per-device host frontier from the device shards
        # (pruned rows drop here — prune-not-expand stays host-side
        # outside the burst)
        nf = stats[:, -1, 2]
        frontier = [[] for _ in range(D)]
        if int(nf.max()) > 0:
            nq = SpillEngine._quantize(int(nf.max()), self.LB,
                                       floor=1 << 8)
            fn = self._bfront_cache.get(nq)
            if fn is None:
                def impl(front, gids, fmask, nq=nq):
                    return ({k: lax.slice_in_dim(v, 0, nq, axis=1)
                             for k, v in front.items()},
                            lax.slice_in_dim(gids, 0, nq, axis=1),
                            lax.slice_in_dim(fmask, 0, nq, axis=1))
                fn = self._bfront_cache[nq] = jax.jit(impl)
            rows, gids, fmask = jax.tree_util.tree_map(
                np.asarray,
                fn(carry["front"], carry["gids"], carry["fmask"]))
            for d in range(D):
                n = int(nf[d])
                if not n:
                    continue
                keep = np.nonzero(fmask[d, :n])[0]
                if len(keep):
                    frontier[d].append((
                        {k: np.ascontiguousarray(v[d][keep])
                         for k, v in rows.items()},
                        gids[d][keep].astype(np.int32)))
        obs.dispatch(
            kind="burst", depth=depth,
            frontier=sum(int(g.shape[0])
                         for q in frontier for _r, g in q),
            metrics=res.metrics.as_dict())
        if verbose:
            print(f"burst: {nlev} levels to depth {depth} "
                  f"(total {res.distinct_states}), frontier "
                  f"{sum(int(g.shape[0]) for q in frontier for _r, g in q)}, "
                  f"{time.perf_counter() - t1:.2f}s", flush=True)
        return carry, frontier, depth, n_states, n_vis, True, bailed

    # -- trip handling ------------------------------------------------

    def _sgrow_table_if_needed(self, carry, n_vis, min_add=0):
        need = int(n_vis.max()) + max(self.LB, min_add)
        if need > self._LOAD_MAX * self.VB:
            while need > self._LOAD_MAX * self.VB:
                self.VB *= 4
            carry = self._rehash_sharded(carry)
        return carry

    def _handle_mesh_trip(self, carry, s, n_vis, settle, verbose):
        """Spill every shard's committed rows (the tripped step itself
        committed nowhere — step-atomic), grow whatever tripped, and
        point every device back at the tripped chunk."""
        tb = int(s[:, Z_TRIP].max())
        assert tb >= 0, "trip flags set but no trip_base"
        nl = s[:, Z_NLVL].astype(np.int64)
        if s[:, Z_OVF].any():
            self.mid_level_spills += 1
        carry, blks = self._fetch_shards(carry, nl)
        settle(blks)
        if s[:, Z_FOVF].any():
            famx = s[:, Z_LEN:Z_LEN + len(self.FAM_CAPS)].max(axis=0)
            caps = list(self.FAM_CAPS)
            fam_over = False
            for fi, fam in enumerate(self.expander.families):
                hard = fam.n_lanes * self.BL
                while caps[fi] < hard and famx[fi] > caps[fi]:
                    caps[fi] = min(2 * caps[fi], hard)
                    fam_over = True
            self.FAM_CAPS = tuple(caps)
            if not fam_over:
                self.FC *= 4
        if s[:, Z_SOVF].any():
            self.SC = 4 * self.SC
        # only the HARD bound forces shard growth (the level shard must
        # hold a receive window on top of usable rows).  The classic
        # engine's 4*FC anti-thrash floor is deliberately NOT applied:
        # an ovf trip here costs one spill + program re-entry, and
        # running the shard near-full IS this engine's operating mode.
        if self.LB < 2 * self.D * self.SC:
            self.LB = self._round_lb(2 * self.D * self.SC)
        # grow when any capacity outran the carry's current shapes
        old_shapes = (carry["fmask"].shape[1], carry["cidx"].shape[1],
                      carry["sscr"].shape[1])
        if (self.LB, self.FC, self.SC) != old_shapes:
            carry = self._grow_sharded(carry)
        if s[:, Z_HOVF].any():
            self.VB *= 4
            carry = self._rehash_sharded(carry)
        carry = self._sgrow_table_if_needed(carry, n_vis)
        if verbose:
            print(f"mesh trip at base {tb}: ovf={s[:, Z_OVF].any()} "
                  f"fovf={s[:, Z_FOVF].any()} sovf={s[:, Z_SOVF].any()} "
                  f"hovf={s[:, Z_HOVF].any()} -> LB={self.LB} "
                  f"FC={self.FC} SC={self.SC} VB={self.VB}",
                  flush=True)
        D = self.D
        carry["ovf"] = jnp.zeros((D,), bool)
        carry["fovf"] = jnp.zeros((D,), bool)
        carry["sovf"] = jnp.zeros((D,), bool)
        carry["hovf"] = jnp.zeros((D,), bool)
        carry["famx"] = jnp.zeros((D, len(self.expander.families)),
                                  jnp.int32)
        carry["trip_base"] = jnp.full((D,), -1, jnp.int32)
        carry["base"] = jnp.full((D,), tb, jnp.int32)
        return carry
