"""Fault-tolerance layer (round 12): deterministic chaos injection,
checksummed last-K checkpoint chains, shape-portable resume images,
and the supervised retry/backoff runner.

- ``chaos`` — a seeded, deterministic fault schedule (``--chaos``)
  that injects failures at named engine sites (dispatch, checkpoint
  publish, archive writes, host-table sweeps, batch waves) so every
  recovery path is testable on CPU in tier-1.
- ``ckpt_chain`` — sha256-sidecar integrity for every checkpoint plus
  last-K rotation with atomic publish; a torn/corrupt head reads as
  "fall back to the previous valid checkpoint" with a named warning.
- ``portable`` — engine-agnostic resume images extracted from any
  engine family's checkpoint: the visited key set + the frontier rows
  in gid order, re-partitioned on load so a mesh checkpoint resumes on
  a different device count or on the spill engine.
- ``supervisor`` — catch → backend-reinit → resume-from-latest-valid
  with bounded exponential backoff + jitter; every attempt stamped
  into the run ledger and heartbeat.
"""

from .chaos import (ChaosSchedule, ChaosSpecError, InjectedFault,
                    chaos_fire, chaos_point, get_schedule, install,
                    uninstall)
from .ckpt_chain import ChainWarning

__all__ = [
    "ChaosSchedule", "ChaosSpecError", "InjectedFault", "chaos_fire",
    "chaos_point", "get_schedule", "install", "uninstall",
    "ChainWarning",
]
