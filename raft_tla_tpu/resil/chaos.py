"""Deterministic chaos injection: a seeded fault schedule fired at
named engine sites (``--chaos SPEC``).

The paper's TLC harness assumes a babysat JVM; our target is a
long-lived service on preemptible TPU tunnels, where rounds 4-5 lost
multi-hour runs to dropped connections.  Recovery code that only runs
when the tunnel actually dies is untested code — this module makes
every failure reproducible on CPU in tier-1: a schedule is a pure
function of (spec string, per-site hit counter), so a faulted run is
exactly replayable and the differential "faulted-then-recovered ≡
unfaulted" is a deterministic test, not a soak.

Spec grammar (';'-separated clauses)::

    seed=N                      PRNG seed for p= clauses (default 0)
    <site>:at=K[,K2,...]        fire on the K-th hit (1-based), once each
    <site>:every=N              fire on every N-th hit
    <site>:p=0.25               fire with probability p (seeded hash of
                                the hit counter — deterministic)

Sites (each names one injection point in the engines)::

    dispatch    raised at the top of every engine level/burst loop
                iteration — a dispatch-time device/tunnel error
    ckpt_torn   after a checkpoint publishes: truncate the head file
                (a torn write at crash time)
    ckpt_corrupt  after a checkpoint publishes: flip bytes mid-file
    archive     raised before a trace-archive level append (disk I/O
                error on the memmap files)
    host_table  raised before a host-partition sweep (partition image
                lost with the host process)
    wave_kill   raised at a serve wave boundary AFTER the per-job wave
                state persists — the deterministic stand-in for
                SIGKILLing a ``cli batch`` run mid-wave
    intake      raised in the daemon's spool scan (serve/intake)
                BEFORE a submission's claim rename — a disk/NFS error
                during intake; the submission stays in incoming/ and
                the next poll re-claims it

``dispatch``/``archive``/``host_table``/``wave_kill``/``intake`` RAISE
``InjectedFault`` (the supervised runner catches and recovers);
``ckpt_torn``/``ckpt_corrupt`` silently damage the just-published
checkpoint bytes so the NEXT resume exercises the chain fallback.

The schedule is process-global (``install``/``uninstall``) and its
counters deliberately survive recovery retries: an ``at=K`` clause
fires once ever, so a replayed level does not re-fault forever, while
``every=N`` keeps faulting on schedule — the supervised differential
uses exactly that to fault every level boundary.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

KNOWN_SITES = ("dispatch", "ckpt_torn", "ckpt_corrupt", "archive",
               "host_table", "wave_kill", "intake")


class ChaosSpecError(ValueError):
    """Malformed ``--chaos`` spec (unknown site/rule, bad value)."""


class InjectedFault(RuntimeError):
    """A chaos-injected failure.  Carries the site and hit index so
    ledgers and tests can attribute the fault."""

    def __init__(self, site: str, hit: int):
        super().__init__(f"chaos-injected fault at site {site!r} "
                         f"(hit #{hit})")
        self.site = site
        self.hit = hit


def _mix(x: int) -> int:
    """32-bit finalizer (the fmix32 constants) in pure Python — the
    p= clauses must not depend on numpy/jax import order."""
    x &= 0xFFFFFFFF
    x ^= x >> 16
    x = (x * 0x7FEB352D) & 0xFFFFFFFF
    x ^= x >> 15
    x = (x * 0x846CA68B) & 0xFFFFFFFF
    x ^= x >> 16
    return x


class ChaosSchedule:
    """Parsed fault schedule; ``fire(site)`` advances the site's hit
    counter and reports whether this hit faults (deterministic)."""

    def __init__(self, spec: str):
        self.spec = spec
        self.seed = 0
        # site -> ("at", frozenset) | ("every", N) | ("p", threshold)
        self.rules: Dict[str, Tuple[str, object]] = {}
        self.hits: Dict[str, int] = {}
        self.fired: List[Tuple[str, int]] = []     # (site, hit) log
        for clause in spec.split(";"):
            clause = clause.strip()
            if not clause:
                continue
            if clause.startswith("seed="):
                try:
                    self.seed = int(clause[5:])
                except ValueError:
                    raise ChaosSpecError(
                        f"chaos spec: bad seed in {clause!r}")
                continue
            if ":" not in clause:
                raise ChaosSpecError(
                    f"chaos spec: clause {clause!r} is not "
                    f"'site:rule' (known sites: "
                    f"{', '.join(KNOWN_SITES)})")
            site, rule = clause.split(":", 1)
            site = site.strip()
            if site not in KNOWN_SITES:
                raise ChaosSpecError(
                    f"chaos spec: unknown site {site!r}; known: "
                    f"{', '.join(KNOWN_SITES)}")
            if site in self.rules:
                raise ChaosSpecError(
                    f"chaos spec: site {site!r} declared twice")
            if "=" not in rule:
                raise ChaosSpecError(
                    f"chaos spec: rule {rule!r} is not at=/every=/p=")
            kind, val = rule.split("=", 1)
            kind = kind.strip()
            if kind not in ("at", "every", "p"):
                raise ChaosSpecError(
                    f"chaos spec: unknown rule {kind!r} for site "
                    f"{site!r} (use at=K[,..], every=N, or p=0.x)")
            try:
                if kind == "at":
                    hits = frozenset(int(v) for v in val.split(","))
                    if not hits or min(hits) < 1:
                        raise ValueError
                    self.rules[site] = ("at", hits)
                elif kind == "every":
                    n = int(val)
                    if n < 1:
                        raise ValueError
                    self.rules[site] = ("every", n)
                else:
                    p = float(val)
                    if not 0.0 <= p <= 1.0:
                        raise ValueError
                    self.rules[site] = ("p", int(p * 2.0 ** 32))
            except ChaosSpecError:
                raise
            except ValueError:
                raise ChaosSpecError(
                    f"chaos spec: bad {kind}= value {val!r} for site "
                    f"{site!r}")
        if not self.rules:
            raise ChaosSpecError(
                f"chaos spec {spec!r} declares no sites; clauses are "
                f"'site:rule' with sites {', '.join(KNOWN_SITES)}")

    def fire(self, site: str) -> bool:
        rule = self.rules.get(site)
        if rule is None:
            return False
        hit = self.hits.get(site, 0) + 1
        self.hits[site] = hit
        kind, val = rule
        if kind == "at":
            hot = hit in val
        elif kind == "every":
            hot = hit % val == 0
        else:
            site_h = _mix(sum(ord(c) for c in site) * 0x9E3779B1)
            hot = _mix(self.seed ^ site_h ^ hit) < val
        if hot:
            self.fired.append((site, hit))
        return hot

    def point(self, site: str):
        """Raise ``InjectedFault`` when this hit is scheduled to
        fault; otherwise a cheap counter bump."""
        if self.fire(site):
            raise InjectedFault(site, self.hits[site])


# ---------------------------------------------------------------------------
# process-global installation (the CLI/supervisor own the lifecycle;
# engines call chaos_point unconditionally — one global read when no
# schedule is installed)
# ---------------------------------------------------------------------------

_ACTIVE: Optional[ChaosSchedule] = None


def install(spec_or_schedule) -> ChaosSchedule:
    global _ACTIVE
    sched = (spec_or_schedule
             if isinstance(spec_or_schedule, ChaosSchedule)
             else ChaosSchedule(str(spec_or_schedule)))
    _ACTIVE = sched
    return sched


def uninstall():
    global _ACTIVE
    _ACTIVE = None


def get_schedule() -> Optional[ChaosSchedule]:
    return _ACTIVE


def chaos_point(site: str):
    """Engine-side injection hook: no-op unless a schedule is
    installed AND this hit is scheduled — then raises InjectedFault."""
    if _ACTIVE is not None:
        _ACTIVE.point(site)


def chaos_fire(site: str) -> bool:
    """Non-raising twin for sites that corrupt rather than fail
    (checkpoint tear/corrupt)."""
    return _ACTIVE.fire(site) if _ACTIVE is not None else False
