"""Checksummed last-K checkpoint chains with atomic publish.

A checkpoint that dies with the process is worse than none: rounds 4-5
lost multi-hour runs to dropped tunnels, and a crash DURING a
checkpoint write used to be able to leave a torn head that resumed as
an unpickling traceback.  This module hardens the engines' shared
serializer (engine/bfs.ckpt_write/ckpt_read) with three properties:

- **integrity**: every published checkpoint gets a sidecar
  ``<path>.sum`` recording its byte length and sha256; readers verify
  the digest BEFORE any array is touched, so truncation/corruption is
  a clear named condition, never a deep numpy/zipfile traceback;
- **last-K chain**: ``keep > 1`` rotates the previous head to
  ``<path>.1`` (and ``.1`` to ``.2``, ...) before publishing, so the
  most recent K checkpoints coexist;
- **fall back, don't crash**: a reader finding a torn/corrupt head
  emits a named ``ChainWarning`` and falls back to the newest valid
  predecessor in the chain — the run resumes a few levels earlier
  instead of dying.

Publish order is: rotate → ``os.replace(tmp, path)`` → write sidecar.
Every step is atomic, and a crash between any two of them leaves a
state the reader handles (an old-but-valid head, or a head whose
sidecar mismatch routes the resume to ``.1``).

This module deliberately knows nothing about the checkpoint payload —
the engines' serializer calls ``publish``; reads go through
``open_validated`` (used by ``ckpt_read`` and the portable-resume
loader).  ``IntegrityError`` is raised for an exhausted chain; callers
translate it to their own error type (``CheckpointError``).
"""

from __future__ import annotations

import hashlib
import json
import os
import warnings
from typing import List, Optional, Tuple

from .chaos import chaos_fire


class ChainWarning(UserWarning):
    """A checkpoint-chain member failed integrity and was skipped in
    favor of an older valid one."""


class IntegrityError(ValueError):
    """No member of the checkpoint chain passed integrity/readability
    validation."""


def _sidecar(path: str) -> str:
    return path + ".sum"


def chain_name(path: str, i: int) -> str:
    return path if i == 0 else f"{path}.{i}"


def chain_candidates(path: str) -> List[str]:
    """Existing chain members, newest first: path, path.1, path.2, ..."""
    out = []
    i = 0
    while True:
        cand = chain_name(path, i)
        if os.path.exists(cand):
            out.append(cand)
        elif i > 0:
            break
        i += 1
    return out


def _digest(path: str) -> Tuple[str, int]:
    h = hashlib.sha256()
    n = 0
    with open(path, "rb") as fh:
        while True:
            blk = fh.read(1 << 20)
            if not blk:
                break
            h.update(blk)
            n += len(blk)
    return h.hexdigest(), n


def write_sidecar(path: str):
    digest, n = _digest(path)
    tmp = _sidecar(path) + ".tmp"
    with open(tmp, "w") as fh:
        json.dump({"sha256": digest, "bytes": n}, fh)
    os.replace(tmp, _sidecar(path))


def verify(path: str) -> Tuple[Optional[bool], str]:
    """(verdict, why): True = digest matches; False = torn/corrupt
    (size or sha256 mismatch, or unreadable); None = no sidecar (a
    pre-round-12 checkpoint — caller falls back to structural
    validation)."""
    try:
        with open(_sidecar(path)) as fh:
            rec = json.load(fh)
        want_sha, want_n = rec["sha256"], int(rec["bytes"])
    except (OSError, ValueError, KeyError):
        return None, "no checksum sidecar (pre-round-12 checkpoint)"
    try:
        size = os.path.getsize(path)
    except OSError as e:
        return False, f"unreadable ({e})"
    if size != want_n:
        return False, (f"torn write: {size} bytes on disk, sidecar "
                       f"records {want_n}")
    got_sha, _ = _digest(path)
    if got_sha != want_sha:
        return False, "sha256 mismatch (corrupt bytes)"
    return True, "ok"


def _move(src: str, dst: str):
    try:
        os.replace(src, dst)
    except OSError:
        pass
    try:
        os.replace(_sidecar(src), _sidecar(dst))
    except OSError:
        # a member without its sidecar stays readable via the
        # structural path; never fail a publish over sidecar shuffling
        try:
            os.remove(_sidecar(dst))
        except OSError:
            pass


def publish(tmp: str, path: str, keep: int = 1):
    """Atomically publish ``tmp`` as the chain head, rotating the
    previous ``keep - 1`` heads down the chain first.  Applies the
    ``ckpt_torn``/``ckpt_corrupt`` chaos sites to the just-published
    head (never to the rotated predecessors — recovery must have
    something valid to fall back to)."""
    keep = max(1, int(keep))
    for i in range(keep - 2, -1, -1):
        src = chain_name(path, i)
        if os.path.exists(src):
            _move(src, chain_name(path, i + 1))
    os.replace(tmp, path)
    write_sidecar(path)
    if chaos_fire("ckpt_torn"):
        size = os.path.getsize(path)
        with open(path, "r+b") as fh:
            fh.truncate(max(1, size // 2))
    if chaos_fire("ckpt_corrupt"):
        size = os.path.getsize(path)
        with open(path, "r+b") as fh:
            fh.seek(size // 2)
            blk = fh.read(64)
            fh.seek(size // 2)
            fh.write(bytes(b ^ 0xFF for b in blk))


def load_engine_npz(path: str):
    """The shared structural loader for engine checkpoint files: np
    container readable, a ``meta`` record present and JSON-parseable.
    Raises on anything malformed — the shape ``open_validated``'s
    ``np_load`` hook expects.  ONE definition (ckpt_read and the
    portable-image loader both resume through it), so a future format
    tightening cannot skip one resume path."""
    import json

    import numpy as np
    z = np.load(path, allow_pickle=False)
    if "meta" not in z:
        raise ValueError("not an engine checkpoint (no meta record)")
    json.loads(str(z["meta"]))
    return z


def open_validated(path: str, np_load):
    """Walk the chain from ``path``, returning ``(z, used_path)`` for
    the newest member that passes integrity + structural load
    (``np_load`` is called with the candidate path and must raise on a
    malformed file).  Members that fail are skipped with a named
    ``ChainWarning``; an exhausted chain raises ``IntegrityError``
    naming the last failure."""
    cands = chain_candidates(path)
    if not cands:
        raise IntegrityError(f"{path}: no such checkpoint")
    last_why = "no candidates"
    for k, cand in enumerate(cands):
        ok, why = verify(cand)
        if ok is False:
            last_why = why
            warnings.warn(
                f"{cand}: checkpoint failed integrity validation "
                f"({why}) — falling back to the previous checkpoint "
                f"in the chain", ChainWarning, stacklevel=3)
            continue
        try:
            z = np_load(cand)
        except Exception as e:       # zipfile/OSError/ValueError zoo:
            # integrity said ok/unknown but the container is still
            # unreadable (legacy file without a sidecar) — same
            # fallback discipline
            last_why = f"unreadable checkpoint container ({e})"
            if k + 1 < len(cands):
                warnings.warn(
                    f"{cand}: {last_why} — falling back to the "
                    f"previous checkpoint in the chain", ChainWarning,
                    stacklevel=3)
                continue
            break
        return z, cand
    raise IntegrityError(
        f"{path}: no valid checkpoint in the chain ({last_why}) — "
        "re-run without --resume")


def latest_valid(path: str) -> Optional[str]:
    """The newest chain member passing integrity validation (sidecar
    digest, or mere existence for legacy members), or None.  Used by
    the supervised runner to decide whether a retry can resume."""
    for cand in chain_candidates(path):
        ok, _why = verify(cand)
        if ok is not False:
            return cand
    return None
