"""Shape-portable resume images: engine-agnostic extraction of a
checkpoint's BFS wavefront (ROADMAP item-2 elastic prerequisite).

Every engine family's checkpoint — classic ``Engine``, ``SpillEngine``,
``ShardedEngine``, ``SpilledShardedEngine`` — carries the same logical
wavefront under different physical layouts: the visited-fingerprint
SET, the frontier rows (the last committed level) in gid order, the
run counters, and the trace archives.  This module reads any of those
files into one normalized ``PortableImage``:

- ``keys``  — [N, W] u32 visited fingerprints (dense tables are
  sparsified; host-partition images are pooled; per-device shards are
  concatenated — membership is a set property, so the physical slot
  layout never matters);
- ``rows``/``gids``/``con`` — frontier rows batch-major in narrow
  storage dtypes, their global ids, and the constraint mask
  (prune-not-expand: ``con=False`` rows are archived but never
  expanded);
- counters (``CheckResult``), depth, ``n_states``, and the archives.

A target engine re-partitions on load: the spill engine rebuilds its
table image (and host partitions) from the key set, the sharded
engines re-route keys and frontier rows by hash ownership
(``key[W-1] % D`` — a pure function of content, so ANY device count
works).  That is what makes a mesh checkpoint resumable on a different
pod-slice shape or on the spill engine after a dropped tunnel.

Exactness: dedup needs key-set MEMBERSHIP, not slot layout, and gid
assignment for new states is discovery-order determined by the
frontier row order this image preserves — so a same-shape portable
resume is bit-exact, and a cross-shape one lands on the exact counts /
level sizes of an uninterrupted run at the target shape (each engine
is oracle-exact; the mesh engines are mesh-size invariant by the
content-canonical survivor policy).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from .ckpt_chain import IntegrityError, open_validated

U32 = np.uint32(0xFFFFFFFF)


@dataclass
class PortableImage:
    spec: str
    cfg_repr: str
    depth: int
    n_states: int
    res: object                       # engine.bfs.CheckResult
    keys: np.ndarray                  # [N, W] u32 visited fingerprints
    rows: Dict[str, np.ndarray]       # frontier, batch-major narrow
    gids: np.ndarray                  # [F] int32
    con: np.ndarray                   # [F] bool (expandable mask)
    store_states: bool
    # in-RAM trace archives (parents/lanes/state blocks per level), or
    # a disk-archive reference the target reattaches
    parents: List[np.ndarray] = field(default_factory=list)
    lanes: List[np.ndarray] = field(default_factory=list)
    states: List[Dict[str, np.ndarray]] = field(default_factory=list)
    disk_archive_levels: Optional[int] = None
    source_format: str = "engine"
    source_path: str = ""

    @property
    def W(self) -> int:
        return int(self.keys.shape[1])

    @property
    def n_vis(self) -> int:
        return int(self.keys.shape[0])

    @property
    def n_front(self) -> int:
        return int(self.gids.shape[0])

    def fresh_result(self):
        """A fresh CheckResult copy of the image's counters.  Resume
        consumers MUST continue on a copy — an image can seed several
        engines (the portable-resume tests fan one checkpoint out to
        multiple targets), and counters are mutated in place."""
        from ..engine.bfs import CheckResult
        r = self.res
        out = CheckResult(
            distinct_states=r.distinct_states,
            generated_states=r.generated_states, depth=r.depth,
            level_sizes=list(r.level_sizes),
            overflow_faults=r.overflow_faults,
            violations_global=r.violations_global,
            pin_interior_states=r.pin_interior_states,
            levels_fused=r.levels_fused,
            burst_dispatches=r.burst_dispatches,
            burst_bailouts=r.burst_bailouts)
        out.violations = list(r.violations)
        return out

    def expandable(self):
        """(rows, gids) with pruned rows dropped — the spill engines'
        frontier convention (prune-not-expand runs host-side there)."""
        if self.con.all():
            return self.rows, self.gids
        keep = np.nonzero(self.con)[0]
        return ({k: np.ascontiguousarray(v[keep])
                 for k, v in self.rows.items()}, self.gids[keep])


def validate_image(img: "PortableImage", spec_name: str,
                   cfg_repr: str, W: int):
    """The target-engine compatibility gate every ``resume_image``
    consumer runs: same spec, byte-identical config repr (the
    checkpoint-compat identity string), same fingerprint width.
    Raises ``CheckpointError`` with the engines' message style."""
    from ..engine.bfs import CheckpointError
    if not isinstance(img, PortableImage):
        raise CheckpointError(
            f"resume_image must be a resil.portable.PortableImage "
            f"(got {type(img).__name__}) — build one with "
            "load_portable_image(path)")
    if img.spec != spec_name:
        raise CheckpointError(
            f"portable image was written for spec {img.spec!r}; "
            f"engine is running spec {spec_name!r}")
    if img.cfg_repr != cfg_repr:
        raise CheckpointError(
            "portable image was written for a different model "
            f"config:\n  image:  {img.cfg_repr}\n"
            f"  engine: {cfg_repr}")
    if img.W != W:
        raise CheckpointError(
            f"portable image has {img.W} fingerprint streams; engine "
            f"expects {W} (fp64 vs fp128 mismatch)")


def dense_table_keys(words: List[np.ndarray]) -> np.ndarray:
    """Public alias of the sparsifier (the spill-mesh serializer pools
    its device shards through it)."""
    return _dense_table_keys(words)


def _dense_table_keys(words: List[np.ndarray]) -> np.ndarray:
    """[W] x u32[...C] dense open-addressing table -> [N, W] keys
    (all-ones aliases "empty" — the engines' accepted-risk class)."""
    occ = ~(words[0] == U32)
    for w in words[1:]:
        occ &= ~(w == U32)
    occ = occ if occ.ndim == 1 else occ.reshape(-1)
    flat = [w.reshape(-1) for w in words]
    idx = np.nonzero(occ)[0]
    return np.stack([w[idx] for w in flat], axis=1)


def _in_ram_archives(z, meta):
    n_levels = int(meta.get("n_levels", 0))
    if not (meta.get("store_states") and n_levels >= 0):
        return [], [], []
    st_keys = sorted({nm.split("|", 2)[2] for nm in z.files
                      if nm.startswith("states|0|")})
    parents = [np.asarray(z[f"parents|{i}"]) for i in range(n_levels)]
    lanes = [np.asarray(z[f"lanes|{i}"]) for i in range(n_levels)]
    states = [{k: np.asarray(z[f"states|{i}|{k}"]) for k in st_keys}
              for i in range(n_levels)]
    return parents, lanes, states


def load_portable_image(path: str) -> PortableImage:
    """Read any engine family's checkpoint into a PortableImage.
    Integrity-validated with chain fallback (resil/ckpt_chain), like
    every native resume.  Raises ``CheckpointError`` on unusable
    files."""
    import json

    from ..engine.bfs import CheckpointError, ckpt_result
    from .ckpt_chain import load_engine_npz
    try:
        z, used = open_validated(path, load_engine_npz)
    except IntegrityError as e:
        raise CheckpointError(str(e)) from e
    meta = json.loads(str(z["meta"]))
    spill = bool(meta.get("spill"))
    sharded = bool(meta.get("sharded"))
    try:
        if spill and sharded:
            img = _extract_spill_mesh(z, meta)
        elif spill:
            img = _extract_spill(z, meta)
        elif sharded:
            img = _extract_sharded(z, meta)
        else:
            img = _extract_engine(z, meta)
    except KeyError as e:
        raise CheckpointError(
            f"{used}: checkpoint lacks record {e} — written by an "
            "incompatible engine version; portable resume needs a "
            "round-12+ checkpoint for this engine family") from e
    img.res = ckpt_result(z, meta)
    img.depth = int(meta["depth"])
    img.n_states = int(meta["n_states"])
    img.spec = meta.get("spec", "raft")
    img.cfg_repr = meta["cfg"]
    img.store_states = bool(meta.get("store_states"))
    img.source_path = used
    if meta.get("disk_archive"):
        img.disk_archive_levels = int(meta["arch_levels"])
    else:
        img.parents, img.lanes, img.states = _in_ram_archives(z, meta)
    z.close()
    return img


def _blank(fmt) -> PortableImage:
    return PortableImage(spec="", cfg_repr="", depth=0, n_states=0,
                         res=None, keys=np.zeros((0, 2), np.uint32),
                         rows={}, gids=np.zeros((0,), np.int32),
                         con=np.zeros((0,), bool), store_states=False,
                         source_format=fmt)


def _extract_engine(z, meta) -> PortableImage:
    img = _blank("engine")
    words = []
    w = 0
    while f"carry|vis|{w}" in z:
        words.append(np.asarray(z[f"carry|vis|{w}"]))
        w += 1
    if not words:
        raise KeyError("carry|vis|0")
    img.keys = _dense_table_keys(words)
    n_front = int(meta["n_front"])
    pg_off = int(np.asarray(z["carry|pg_off"]))
    fmask = np.asarray(z["carry|fmask"])[:n_front]
    rows = {}
    for nm in z.files:
        if nm.startswith("carry|front|"):
            k = nm.split("|", 2)[2]
            v = np.asarray(z[nm])          # batch-LAST [..., LCAP]
            rows[k] = np.ascontiguousarray(
                np.moveaxis(v[..., :n_front], -1, 0))
    img.rows = rows
    img.gids = pg_off + np.arange(n_front, dtype=np.int32)
    img.con = fmask.astype(bool)
    return img


def _extract_sharded(z, meta) -> PortableImage:
    img = _blank("sharded")
    words = []
    w = 0
    while f"carry|vis|{w}" in z:
        words.append(np.asarray(z[f"carry|vis|{w}"]))   # [D, VB]
        w += 1
    if not words:
        raise KeyError("carry|vis|0")
    img.keys = _dense_table_keys(words)
    nfd = np.asarray(z["carry|n_front"])               # [D]
    fmask = np.asarray(z["carry|fmask"])               # [D, LB]
    gids = np.asarray(z["carry|gids"])                 # [D, LB]
    D = nfd.shape[0]
    fronts = {}
    for nm in z.files:
        if nm.startswith("carry|front|"):
            fronts[nm.split("|", 2)[2]] = np.asarray(z[nm])
    rows_d, gids_d, con_d = [], [], []
    for d in range(D):
        n = int(nfd[d])
        if not n:
            continue
        rows_d.append({k: v[d, :n] for k, v in fronts.items()})
        gids_d.append(gids[d, :n].astype(np.int32))
        con_d.append(fmask[d, :n].astype(bool))
    if rows_d:
        keys0 = rows_d[0].keys()
        rows = {k: np.concatenate([r[k] for r in rows_d])
                for k in keys0}
        g = np.concatenate(gids_d)
        c = np.concatenate(con_d)
        order = np.argsort(g, kind="stable")   # global gid order
        img.rows = {k: np.ascontiguousarray(v[order])
                    for k, v in rows.items()}
        img.gids = g[order]
        img.con = c[order]
    else:
        img.rows = {k: v[:0, 0] for k, v in fronts.items()}
        img.gids = np.zeros((0,), np.int32)
        img.con = np.zeros((0,), bool)
    return img


def _extract_spill(z, meta) -> PortableImage:
    img = _blank("spill")
    if meta.get("host_table"):
        # the host partitions are the authoritative visited set (the
        # device table is a bounded cache ⊆ them)
        shape = np.asarray(z["carry|hpt|shape"])
        P = int(shape[0])
        parts = [np.asarray(z[f"carry|hpt|keys{p}"]).T
                 for p in range(P)]            # [n_p, W]
        img.keys = (np.concatenate(parts) if parts
                    else np.asarray(z["carry|vis_keys"]).T)
    else:
        img.keys = np.ascontiguousarray(
            np.asarray(z["carry|vis_keys"]).T)
    rows_b, gids_b = [], []
    for i in range(int(meta["n_fblk"])):
        g = np.asarray(z[f"carry|fblk|{i}|g"])
        blk = {}
        for nm in z.files:
            pre = f"carry|fblk|{i}|r|"
            if nm.startswith(pre):
                v = np.asarray(z[nm])          # batch-LAST [..., n]
                blk[nm[len(pre):]] = np.ascontiguousarray(
                    np.moveaxis(v, -1, 0))
        rows_b.append(blk)
        gids_b.append(g.astype(np.int32))
    if rows_b:
        keys0 = rows_b[0].keys()
        img.rows = {k: np.concatenate([r[k] for r in rows_b])
                    for k in keys0}
        img.gids = np.concatenate(gids_b)
    img.con = np.ones((img.gids.shape[0],), bool)
    return img


def _extract_spill_mesh(z, meta) -> PortableImage:
    """The round-12 SpilledShardedEngine format writes the wavefront
    pooled and gid-ordered already — the portable form IS the native
    form (parallel/spill_mesh _save_checkpoint)."""
    img = _blank("spill_mesh")
    if meta.get("host_table"):
        D = int(meta["D"])
        parts = []
        for d in range(D):
            shape = np.asarray(z[f"carry|hpt{d}|shape"])
            for p in range(int(shape[0])):
                parts.append(np.asarray(z[f"carry|hpt{d}|keys{p}"]).T)
        img.keys = np.concatenate(parts) if parts else \
            np.zeros((0, int(meta.get("W", 2))), np.uint32)
    else:
        img.keys = np.ascontiguousarray(np.asarray(z["carry|keys"]))
    rows = {}
    for nm in z.files:
        if nm.startswith("carry|pf|rows|"):
            rows[nm.split("|", 3)[3]] = np.asarray(z[nm])  # batch-major
    img.rows = rows
    img.gids = np.asarray(z["carry|pf|g"]).astype(np.int32)
    img.con = np.ones((img.gids.shape[0],), bool)
    return img
