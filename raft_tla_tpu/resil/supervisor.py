"""Supervised retry/backoff runner: catch → backend reinit → resume
from the latest valid checkpoint, with bounded exponential backoff.

The drive loop of a long run on a preemptible TPU tunnel dies to
transient causes (dropped tunnel, device OOM race, host I/O blips) far
more often than to engine bugs — rounds 4-5 lost multi-hour runs
exactly that way.  ``supervised_check`` wraps any engine family's
``check()``:

- retryable failures (``InjectedFault``, ``RuntimeError`` — the XLA
  runtime's error class — and ``OSError``) trigger a bounded
  exponential backoff with deterministic jitter, a fresh engine from
  ``make_engine()`` (the backend-reinit hook: jit caches cleared, new
  executables, new device buffers), and a resume from the newest VALID
  member of the checkpoint chain (``resil.ckpt_chain``) — falling back
  to the original resume source, or a fresh start, when no checkpoint
  was written yet;
- non-retryable failures (``CheckpointError`` and other
  ``ValueError``s, assertion failures) propagate immediately — they
  mean misconfiguration, not weather;
- every attempt is stamped into the run ledger (``kind="retry"``) and
  the heartbeat (``status="backoff"``), so ``tools/watch.py`` shows a
  retrying run instead of a silent gap.

Because every engine resumes bit-exact from level-boundary
checkpoints, a supervised run's final counts are identical to an
unfaulted run — the chaos differentials in tests/test_resil.py pin
exactly that with faults injected at every level boundary.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from .chaos import InjectedFault
from .ckpt_chain import latest_valid

#: failures the supervisor treats as transient weather
RETRYABLE = (InjectedFault, RuntimeError, OSError)


class RetryExhausted(RuntimeError):
    """The supervised run failed on its final permitted attempt."""

    def __init__(self, attempts: int, last: BaseException):
        super().__init__(
            f"supervised run failed after {attempts} attempt(s); "
            f"last error: {last}")
        self.attempts = attempts
        self.last = last


def _jitter(attempt: int) -> float:
    """Deterministic jitter in [0, 1): decorrelates fleet retries
    without breaking replayability (no wall-clock entropy)."""
    return ((attempt + 1) * 2654435761 % (1 << 20)) / float(1 << 20)


def backoff_delay(attempt: int, backoff: float, backoff_max: float,
                  jitter_frac: float = 0.25) -> float:
    """Bounded exponential backoff + deterministic jitter for the
    k-th retry (0-based)."""
    base = min(backoff * (2.0 ** attempt), backoff_max)
    return base * (1.0 + jitter_frac * _jitter(attempt))


def _reinit_backend():
    """Best-effort backend reinit between attempts: drop every traced
    executable and live compilation cache so the fresh engine rebuilds
    them (on a real tunnel this is where a reconnect happens; the
    persistent on-disk compile cache keeps the rebuild cheap)."""
    try:
        import jax
        jax.clear_caches()
    except Exception:
        pass


def supervised_check(make_engine: Callable[[], object],
                     retries: int = 0,
                     backoff: float = 1.0,
                     backoff_max: float = 60.0,
                     obs=None,
                     checkpoint_path: Optional[str] = None,
                     resume_from: Optional[str] = None,
                     resume_image=None,
                     sleep: Callable[[float], None] = time.sleep,
                     reinit: bool = True,
                     **check_kw):
    """Run ``make_engine().check(...)`` under supervision.  Returns
    ``(res, engine, attempts_used)``; raises ``RetryExhausted`` when
    the last permitted attempt also fails.

    ``make_engine`` is called once per attempt — the backend-reinit
    contract (a fresh engine re-traces against a reconnected backend).
    ``checkpoint_path`` doubles as the recovery source: each retry
    resumes from the newest valid chain member; without one, retries
    fall back to the original ``resume_from``/``resume_image`` (or a
    fresh start).  ``reinit=False`` skips the jit-cache drop between
    attempts (the chaos differentials retry dozens of times on one
    CPU engine instance — re-tracing every executable there tests
    nothing and costs seconds per attempt; real tunnel recoveries
    keep the default).  Remaining kwargs pass through to
    ``check()``."""
    from ..obs import NULL_OBS
    obs = obs if obs is not None else NULL_OBS
    # the caller's resume source: retries fall back to it (or to a
    # fresh start) whenever the checkpoint chain has no valid member —
    # never to a stale chain path from an earlier attempt
    orig_from, orig_image = resume_from, resume_image
    attempt = 0
    while True:
        try:
            eng = make_engine()
            kw = dict(check_kw)
            if resume_image is not None:
                kw["resume_image"] = resume_image
            res = eng.check(checkpoint_path=checkpoint_path,
                            resume_from=resume_from, obs=obs, **kw)
            return res, eng, attempt + 1
        except NotImplementedError:
            # a RuntimeError subclass, but NEVER weather: it names a
            # capability the engine lacks (e.g. multi-controller
            # checkpointing) — retrying cannot help
            raise
        except RETRYABLE as e:
            if attempt >= retries:
                if retries:
                    raise RetryExhausted(attempt + 1, e) from e
                raise
            wait = backoff_delay(attempt, backoff, backoff_max)
            obs.retry(attempt=attempt + 1, max_attempts=retries + 1,
                      wait_s=wait, error=e)
            sleep(wait)
            if reinit:
                _reinit_backend()
            # recovery source for the next attempt: newest valid
            # checkpoint > the original resume source > fresh start
            lv = (latest_valid(checkpoint_path)
                  if checkpoint_path else None)
            if lv is not None:
                resume_from, resume_image = lv, None
            else:
                resume_from, resume_image = orig_from, orig_image
            attempt += 1
