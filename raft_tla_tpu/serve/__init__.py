"""Batched multi-tenant serving layer (ROADMAP 2b).

Takes a list of (spec, config, engine-options) jobs, groups them into
shape buckets, runs each bucket as ONE device program with a leading
job axis (serve/batch), and short-circuits repeat jobs through a
fingerprint-keyed result cache (serve/cache).  ``cli batch`` is the
command-line front door; serve/jobs defines the job objects and the
JSONL format.
"""

from .batch import (BatchReport, BucketEngine, JobOutcome, run_jobs)
from .cache import ResultCache
from .exec_cache import ExecCache
from .jobs import Job, job_from_dict, load_jobs
from .wavestate import WaveStateStore

__all__ = [
    "BatchReport", "BucketEngine", "ExecCache", "Job", "JobOutcome",
    "ResultCache",
    "WaveStateStore", "job_from_dict", "load_jobs", "run_jobs",
]
