"""Batched multi-tenant serving layer (ROADMAP 2b + item 1).

Takes a list of (spec, config, engine-options) jobs, groups them into
shape buckets, runs each bucket as ONE device program with a leading
job axis (serve/batch), and short-circuits repeat jobs through a
fingerprint-keyed result cache (serve/cache).  The driver loop lives
in serve/scheduler (``WaveScheduler``) — ``cli batch`` drains a job
list through it once, and the persistent daemon (serve/daemon +
serve/intake, ``cli serve``) runs it cycle after cycle over a spool
directory.  serve/jobs defines the job objects and the JSONL format.
"""

from .batch import (BatchReport, BucketEngine, JobOutcome, run_jobs)
from .cache import ResultCache
from .daemon import Daemon
from .exec_cache import ExecCache
from .intake import SpoolIntake, StreamTail, Submission
from .jobs import Job, job_from_dict, load_jobs
from .scheduler import WaveScheduler
from .wavestate import WaveStateStore

__all__ = [
    "BatchReport", "BucketEngine", "Daemon", "ExecCache", "Job",
    "JobOutcome", "ResultCache", "SpoolIntake", "StreamTail",
    "Submission", "WaveScheduler",
    "WaveStateStore", "job_from_dict", "load_jobs", "run_jobs",
]
