"""Multi-tenant batched checking: many (spec, config) jobs, ONE device
program per bucket (ROADMAP 2b — the serving half of the north star).

Every solo ``check`` pays its own compile (~6 s per engine instance on
XLA:CPU; 30-50 s on the tunneled TPU) and its own dispatch chain, so N
small jobs cost N× everything.  This layer amortizes both across
tenants, the same move PR 5 made across levels:

- **Buckets** — jobs group by their spec's ``serve_bucket`` hook:
  (spec, ceiling config, bucket params).  One ``BucketEngine`` per
  bucket compiles ONE job-vmapped burst program
  (``engine/bfs.Engine.burst_batched_fn``) and serves every job in the
  bucket through it, in waves of up to ``_MAX_WAVE`` jobs per device
  padded to a power of two (so the wave-size compile cache stays
  tiny).
- **Mesh waves** (rounds 16-17) — with more than one local device (a
  TPU slice, or CPU via ``--xla_force_host_platform_device_count``),
  the wave shards across a two-axis ``jax.make_mesh(("jobs",
  "state"))`` (``--wave-mesh JxS``): per-job scalars/cursors stay on
  ``P("jobs")`` while the big per-job arrays — visited-table slots,
  frontier rings, level buffers, archive staging — also shard
  ``P("jobs", "state")``, so ONE huge tenant's dedup state spans the
  pod inside a batched wave (the round-14 pjit substrate under the
  bucket program; the probe/claim scatter lowers to state-axis
  GSPMD collectives only — jobs stay collective-free).  ``S=1``
  degenerates to the round-16 job-axis mesh with a single
  pytree-prefix sharding; ``auto`` promotes spare devices to state
  shards when a bucket's ceiling VCAP exceeds the per-device budget.
  Waves pad to a J-axis multiple and the ceiling scales to J x 8
  lanes.  The per-job harvest, park/resume slices and wave-state
  files stay host-side numpy, so the same ``.wave.npz`` restores
  under ANY mesh shape, 2-D included (the portable restart matrix).
- **Job axis** — per-job frontier rings, visited tables, global-id
  cursors, depth gates and invariant verdicts all ride a leading
  ``[J, ...]`` axis.  JAX batches the burst's while_loops as
  run-until-all-jobs-done with per-job select masking: finished jobs
  freeze (their lanes contribute no work to the result) while
  stragglers keep stepping.  Each job's trajectory is bit-identical to
  a solo run — pinned by tests/test_serve.py on counts, level sizes,
  violation states and witness traces.
- **Fallback** — a job the batched path cannot hold (root set or a
  frontier outgrowing the per-job ring, a table overflow, seeded /
  prefix-pinned configs) is re-run solo from scratch on an ordinary
  ``Engine``; its batched partial progress is discarded, so fallback
  results are trivially exact.  Fallbacks are counted and labeled
  honestly in the report and the ledger.
- **Result cache** — (spec, IR, config, options)-fingerprint keyed
  (serve/cache): a repeat job is answered with zero device dispatches.
- **Observability** — spans attribute wall-clock to ``bucket_compile``
  vs ``batched_dispatch`` vs ``job_harvest`` (vs ``sequential_job``
  for fallbacks); the ledger gets one ``kind="batch"`` record per
  batched device call and one ``kind="job"`` record per finished job;
  the heartbeat carries a per-job status map ``tools/watch.py``
  renders one line per job.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..obs.metrics import check_stats
from ..resil.chaos import chaos_point
from ..spec import C_OVERFLOW, spec_of
from ..utils import take_arrays as _take
from .jobs import Job
from .wavestate import WaveStateStore

U32MAX_NP = np.uint32(0xFFFFFFFF)

# jobs per batched device program; a bucket with more runs extra waves
_MAX_WAVE = 8

# "auto" state-split budget (round 17): bytes of ONE job's dedup state
# (W visited-table words + the claims word, u32 each) a single device
# is allowed to hold before auto promotes spare job-axis devices into
# state shards (S > 1).  Sized for a ~16 GB HBM part with headroom for
# rings/levels/archives; override for tests and small-HBM parts.
_AUTO_STATE_BUDGET = int(os.environ.get(
    "RAFT_TPU_WAVE_STATE_BUDGET", str(256 << 20)))

# rule-matched partition specs for the batched wave carry/outputs under
# the 2-D ("jobs", "state") mesh (parallel/pjit_mesh's exemplar rules,
# serve-side tables).  Per-job cursors and runtime thresholds stay
# P("jobs") — collective-free; the per-job BIG arrays also shard the
# "state" axis: visited-table slots + claims on dim 1 (the probe/claim
# scatter lowers to state-axis GSPMD collectives), frontier rings /
# depth gates / level buffers / archive staging on their batch-last
# ring axis.
WAVE_CARRY_RULES = [
    (r"^vis\|", "jobs_slots"),
    (r"^claims$", "jobs_slots"),
    (r"^(fr\||fm$|gd$)", "jobs_rows"),
    (r".*", "jobs"),
]
WAVE_OUT_RULES = [
    (r"^(par$|lane$|inv$|st\|)", "jobs_rows"),
    (r".*", "jobs"),
]

# the serve_bucket contract's fallback when a spec declares no hook
DEFAULT_BUCKET_PARAMS = dict(chunk=128, vcap=1 << 15, burst_levels=8)


def _default_serve_bucket(cfg):
    return cfg, dict(DEFAULT_BUCKET_PARAMS)


def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


def resolve_wave_mesh(value) -> Tuple[int, int]:
    """Normalize a ``--wave-mesh`` spec to a (J, S) mesh shape.

    J is the job-axis device count, S the state-shard count — the
    two axes of the serving wave's ``("jobs", "state")`` mesh.
    ``(0, 1)`` means mesh off (the historical single-device wave).

    ``"auto"``/None -> all local devices on the job axis when more
    than one is visible, else off; ``BucketEngine`` may re-split an
    auto shape to S > 1 when the bucket ceiling's per-job dedup state
    exceeds the per-device budget (``_AUTO_STATE_BUDGET``).
    ``"off"``/0/1 -> off.  An integer N -> ``(N, 1)``, the round-16
    job-axis mesh.  ``"JxS"`` (e.g. ``4x2``) -> J job rows x S state
    shards; J*S must fit the backend.  Anything else is a ValueError
    with the offending value named (the CLI turns it into exit 2,
    never a traceback)."""
    import jax
    avail = jax.local_device_count()
    if value is None or value == "auto":
        return (avail, 1) if avail > 1 else (0, 1)
    if value == "off":
        return (0, 1)
    if isinstance(value, tuple):
        j, s = int(value[0]), int(value[1])
        if j < 0 or s < 1:
            raise ValueError(f"--wave-mesh shape must have J >= 0 and "
                             f"S >= 1, got {value!r}")
    elif isinstance(value, str) and "x" in value:
        try:
            j_txt, s_txt = value.split("x", 1)
            j, s = int(j_txt), int(s_txt)
        except ValueError:
            raise ValueError(
                f"--wave-mesh must be 'auto', 'off', a device count "
                f"or JxS (e.g. 4x2), got {value!r}")
        if j < 1 or s < 1:
            raise ValueError(
                f"--wave-mesh {value!r}: both the J (jobs) and S "
                f"(state) axes must be >= 1")
    else:
        try:
            n = int(value)
        except (TypeError, ValueError):
            raise ValueError(
                f"--wave-mesh must be 'auto', 'off', a device count "
                f"or JxS (e.g. 4x2), got {value!r}")
        if n < 0:
            raise ValueError(f"--wave-mesh device count must be >= 0, "
                             f"got {n}")
        j, s = n, 1
    if j * s > avail:
        raise ValueError(
            f"--wave-mesh {value!r} needs {j * s} device(s) and "
            f"exceeds the {avail} visible local device(s)")
    return (j, s) if j * s > 1 else (0, 1)


# ---------------------------------------------------------------------------
# per-job bookkeeping
# ---------------------------------------------------------------------------

class _JobRun:
    """One job's in-flight state inside a batched wave: the CheckResult
    under construction, the BFS cursors the harvest loop advances, and
    the per-level trace archives (host RAM lists, the in-RAM Engine
    archive format)."""

    def __init__(self, job: Job):
        from ..engine.bfs import CheckResult
        self.job = job
        self.res = CheckResult()
        # per-job wall clock starts when the job enters its wave, so
        # a job's reported seconds never absorb OTHER buckets' compile
        # or runtime (it still shares its own wave's wall, honestly)
        self._t0 = time.perf_counter()
        self.depth = 0
        self.n_states = 0
        self.n_front = 0
        self.parents: List[np.ndarray] = []
        self.lanes: List[np.ndarray] = []
        self.states: List[Dict[str, np.ndarray]] = []
        self.live = True
        self.fallback = False
        self.fallback_reason: Optional[str] = None
        # preemption / resume (round 12): a carry slice to enter the
        # next wave with instead of root admission — set by a wave
        # yield (parked) or a wave-state restore (resumed)
        self.preinit: Optional[Dict] = None
        self.parked = False
        self.resumed = False
        # SLO accounting (round 13): submission -> wave-entry seconds,
        # stamped by the driver's _SloTracker
        self.wait_s = 0.0

    def finish(self):
        self.live = False
        self.res.depth = self.depth
        self.res.seconds = time.perf_counter() - self._t0

    def mark_fallback(self, reason: str):
        self.live = False
        self.fallback = True
        self.fallback_reason = reason

    @property
    def status(self) -> str:
        if self.live:
            return "parked" if self.parked else "running"
        return "fallback" if self.fallback else "done"

    # -- wave-state (de)hydration (serve/wavestate) --------------------

    def book(self) -> Dict:
        res = self.res
        return dict(
            cache_key=self.job.cache_key(), label=self.job.label,
            depth=int(self.depth), n_states=int(self.n_states),
            n_front=int(self.n_front),
            distinct=int(res.distinct_states),
            generated=int(res.generated_states),
            faults=int(res.overflow_faults),
            viol_global=int(res.violations_global),
            levels_fused=int(res.levels_fused),
            burst_dispatches=int(res.burst_dispatches),
            burst_bailouts=int(res.burst_bailouts),
            level_sizes=[int(x) for x in res.level_sizes],
            violations=[[v.invariant, int(v.state_id)]
                        for v in res.violations],
            n_arch=len(self.parents))

    def wave_arrays(self) -> Dict[str, np.ndarray]:
        out = {}
        for nm in ("fm", "gd", "vis"):
            out[nm] = self.preinit[nm]
        for k, v in self.preinit["fr"].items():
            out[f"fr|{k}"] = v
        out["cursors"] = np.array(
            [self.preinit["nf"], self.preinit["g"],
             self.preinit["pg"]], np.int64)
        for i, (p, ln) in enumerate(zip(self.parents, self.lanes)):
            out[f"par|{i}"] = p
            out[f"lane|{i}"] = ln
            for k, v in self.states[i].items():
                out[f"st|{i}|{k}"] = v
        return out

    @classmethod
    def from_wave_state(cls, job: Job, arrays: Dict, book: Dict
                        ) -> "_JobRun":
        from ..engine.bfs import Violation
        run = cls(job)
        run.resumed = True
        run.depth = int(book["depth"])
        run.n_states = int(book["n_states"])
        run.n_front = int(book["n_front"])
        res = run.res
        res.distinct_states = int(book["distinct"])
        res.generated_states = int(book["generated"])
        res.overflow_faults = int(book["faults"])
        res.violations_global = int(book["viol_global"])
        res.levels_fused = int(book["levels_fused"])
        res.burst_dispatches = int(book["burst_dispatches"])
        res.burst_bailouts = int(book["burst_bailouts"])
        res.level_sizes = [int(x) for x in book["level_sizes"]]
        for inv, sid in book["violations"]:
            res.violations.append(Violation(str(inv), int(sid)))
        fr = {nm.split("|", 1)[1]: arrays[nm] for nm in arrays
              if nm.startswith("fr|")}
        cur = arrays["cursors"]
        run.preinit = dict(fr=fr, fm=arrays["fm"], vis=arrays["vis"],
                           gd=arrays["gd"], nf=int(cur[0]),
                           g=int(cur[1]), pg=int(cur[2]))
        n_arch = int(book.get("n_arch", 0))
        st_keys = sorted({nm.split("|", 2)[2] for nm in arrays
                          if nm.startswith("st|0|")})
        for i in range(n_arch):
            run.parents.append(arrays[f"par|{i}"])
            run.lanes.append(arrays[f"lane|{i}"])
            run.states.append({k: arrays[f"st|{i}|{k}"]
                               for k in st_keys})
        return run


class JobOutcome:
    """One job's final answer: status, the CheckResult (None for cache
    hits), the JSON-able report row, and — when trace archives exist —
    ``trace(gid)``/``get_state(gid)`` in the Engine format."""

    def __init__(self, job: Job, status: str, res=None, report=None,
                 archives=None, engine=None, reason=None):
        self.job = job
        self.status = status
        self.res = res
        self.report = report or {}
        self._archives = archives      # (parents, lanes, states, labels)
        self._engine = engine          # solo engine (fallback path)
        self.reason = reason

    @property
    def cache_hit(self) -> bool:
        return self.status == "cache_hit"

    def get_state(self, gid: int):
        if self._engine is not None:
            return self._engine.get_state(gid)
        if self._archives is None:
            raise ValueError(f"job {self.job.label!r}: no trace "
                             "archives (store_states off or cache hit)")
        ir, lay = self.job.ir, self._archives[4]
        _parents, _lanes, states, _labels = self._archives[:4]
        off = 0
        for blk in states:
            n = next(iter(blk.values())).shape[0]
            if gid < off + n:
                return ir.decode(lay, _take(blk, gid - off))
            off += n
        raise IndexError(gid)

    def trace(self, gid: int) -> List[Tuple]:
        """Witness trace (label, state) chain — the Engine.trace
        contract, replayed from the per-job archives."""
        if self._engine is not None:
            return self._engine.trace(gid)
        if self._archives is None:
            raise ValueError(f"job {self.job.label!r}: no trace "
                             "archives (store_states off or cache hit)")
        parents_l, lanes_l, _states, labels, _lay = self._archives
        parents = np.concatenate(parents_l)
        lanes = np.concatenate(lanes_l)
        chain = []
        g = gid
        while g >= 0:
            lane = int(lanes[g])
            label = labels[lane] if lane >= 0 else "Init"
            chain.append((label, self.get_state(g)[0]))
            g = int(parents[g])
        return list(reversed(chain))

    def cache_payload(self) -> Dict:
        return dict(self.report)

    @classmethod
    def _from_cache(cls, job: Job, payload: Dict) -> "JobOutcome":
        report = dict(payload)
        report["status"] = "cache_hit"
        report["label"] = job.label
        return cls(job, "cache_hit", report=report)


class BatchReport:
    """run_jobs' return value: outcomes in submission order + the
    batch-level meta counters (buckets, compiles, dispatches, cache
    hits, fallbacks)."""

    def __init__(self, outcomes: List[JobOutcome], meta: Dict,
                 seconds: float):
        self.outcomes = outcomes
        self.meta = dict(meta)
        self.meta["seconds"] = round(seconds, 3)

    @property
    def summary(self) -> Dict:
        # a drained serve() round leaves deferred outcomes as None —
        # they carry no violations yet, by definition
        return {"kind": "batch_summary", **self.meta,
                "violations": sum(int(o.report.get("violations", 0))
                                  for o in self.outcomes
                                  if o is not None)}


# ---------------------------------------------------------------------------
# report assembly
# ---------------------------------------------------------------------------

def _build_report(job: Job, res, status: str, reason=None,
                  tracer=None) -> Dict:
    ir = spec_of(job.cfg)
    out = check_stats(res.metrics.as_dict(), res.seconds,
                      len(res.violations),
                      fp_bits=128 if getattr(job.cfg, "fp128", False)
                      else 64,
                      spec=ir.name, ir_fp=ir.fingerprint())
    out["label"] = job.label
    out["status"] = status
    if reason:
        out["status_reason"] = reason
    out["cfg_fingerprint"] = job.cfg_fingerprint()
    out["opts_fingerprint"] = job.opts_fingerprint()
    out["cache_key"] = job.cache_key()
    out["level_sizes"] = [int(x) for x in res.level_sizes]
    det = []
    for v in res.violations[:8]:
        d = {"invariant": v.invariant, "state_id": int(v.state_id)}
        if tracer is not None and v.state_id >= 0:
            d["trace"] = [lbl for lbl, _sv in tracer(v.state_id)]
        det.append(d)
    out["violations_detail"] = det
    return out


def _job_row(obs, outcome: JobOutcome):
    if obs.ledger is None:
        return
    rec = dict(outcome.report)
    rec["kind"] = "job"
    obs.ledger.record(rec)


def _jobs_map(runs: List[_JobRun]) -> Dict[str, Dict]:
    return {run.job.label: {"depth": int(run.depth),
                            "distinct": int(run.res.distinct_states),
                            "status": run.status}
            for run in runs}


# ---------------------------------------------------------------------------
# SLO accounting (ROADMAP item 1, round 13): per-job wait (submission ->
# first wave entry) and service (wave entry -> answer) seconds, folded
# into fixed-bucket histograms the heartbeat carries live and the
# per-tenant ledger rollups summarize at batch end.
# ---------------------------------------------------------------------------

_SLO_EDGES = (0.01, 0.05, 0.25, 1.0, 5.0, 30.0, 120.0)


def slo_histogram(seconds: List[float]) -> Dict[str, int]:
    """Fixed log-ish latency buckets (cumulative-friendly: each key is
    the bucket's inclusive upper edge, 'inf' catches the tail)."""
    hist = {f"le_{e:g}": 0 for e in _SLO_EDGES}
    hist["inf"] = 0
    for s in seconds:
        for e in _SLO_EDGES:
            if s <= e:
                hist[f"le_{e:g}"] += 1
                break
        else:
            hist["inf"] += 1
    return hist


class _SloTracker:
    """The batch-global SLO state ``run_jobs`` maintains: submission
    timestamps, finished jobs' wait/service samples, and the live
    snapshot dict (mutated in place — run_wave's dispatches carry it
    into every heartbeat)."""

    def __init__(self, n_jobs: int):
        self.t_submit = time.perf_counter()
        self.waits: List[float] = []
        self.services: List[float] = []
        self.snapshot: Dict = {"queue_depth": n_jobs,
                               "jobs_done": 0,
                               "wait_hist": slo_histogram([]),
                               "service_hist": slo_histogram([])}

    def job_entered(self, run: "_JobRun"):
        run.wait_s = run._t0 - self.t_submit

    def job_done(self, wait_s: float, service_s: float):
        self.waits.append(max(0.0, float(wait_s)))
        self.services.append(max(0.0, float(service_s)))
        self.snapshot["jobs_done"] = len(self.services)
        self.snapshot["wait_hist"] = slo_histogram(self.waits)
        self.snapshot["service_hist"] = slo_histogram(self.services)

    def set_queue_depth(self, n: int):
        self.snapshot["queue_depth"] = max(0, int(n))


# ---------------------------------------------------------------------------
# the bucket engine
# ---------------------------------------------------------------------------

class BucketEngine:
    """One compiled batched checker per (spec, ceiling cfg, params)
    bucket.  Wraps an ordinary ``Engine`` for the ceiling config and
    drives its job-vmapped burst core; never calls ``Engine.check``,
    so the solo executables are never traced or compiled here."""

    def __init__(self, cfg, chunk: int = 128, vcap: int = 1 << 15,
                 burst_levels: int = 8, delta_matmul: bool = True,
                 sym_canon: str = "auto", exec_cache=None,
                 wave_mesh=0, wave_mesh_auto: bool = False):
        from ..engine.bfs import Engine
        # dedup_kernel="off": the Pallas probe kernel has no batching
        # rule; the lax claim walk is bit-identical in every mode
        # (tests/test_guard_matmul.py pins it), so the batched program
        # loses nothing but a TPU micro-optimization.  store_states
        # stays off on the engine — serve harvests its own per-job
        # archives straight from the burst outputs.  delta_matmul
        # vmaps cleanly (pure einsum blocks), so the batched program
        # keeps the group delta path; the kwarg exists for A/B tests
        # (bucket_overrides={"delta_matmul": False}).
        self.eng = Engine(cfg, chunk=chunk, store_states=False,
                          vcap=vcap, dedup_kernel="off",
                          burst_levels=burst_levels,
                          delta_matmul=delta_matmul,
                          sym_canon=sym_canon)
        self.KB = self.eng._burst_width()
        self.VCAP = self.eng.VCAP
        # Donation-free program whenever a persistent executable cache
        # is in play: carry donation bakes input->output aliasing into
        # the executable, and a serialize_executable round-trip loaded
        # in a DIFFERENT process silently corrupts the donated carry
        # outputs (stats stay right, the re-fed wave and the persisted
        # wave state go wrong — daemon_smoke's warm-restart phase
        # caught it).  The stored, loaded, and freshly-compiled
        # programs must be the SAME program, so the choice is made
        # once here and recorded in _exec_key_parts.
        self._donate = exec_cache is None
        # constant-padding ceilings flag first: the carry template the
        # 2-D spec trees match on needs to know whether rt rides along
        self.rt_mode = self.eng.ir.serve_runtime is not None
        self._rt_cache: Dict[str, Dict] = {}
        # mesh mode (rounds 16-17): shard the wave across a 2-D
        # (J, S) = ("jobs", "state") mesh of local devices.  With
        # S == 1 every leaf of the batched carry leads with [J], so
        # ONE job-axis NamedSharding is the pytree-prefix spec for the
        # whole program — GSPMD splits the wave with no data
        # collectives (lanes are independent).  With S > 1 the big
        # per-job arrays ALSO shard the "state" axis under per-leaf
        # rule-matched spec trees (WAVE_CARRY_RULES/WAVE_OUT_RULES —
        # the parallel/pjit_mesh substrate), so one huge tenant's
        # visited table and rings span J*S devices while the dedup
        # probe/claim scatter stays an in-program state-axis
        # collective.  Either way the per-job harvest slicing below
        # stays host-side and mode-blind.
        if isinstance(wave_mesh, tuple):
            mj, ms = int(wave_mesh[0]), int(wave_mesh[1])
        else:
            mj, ms = int(wave_mesh or 0), 1
        if wave_mesh_auto and mj > 1 and ms == 1:
            mj, ms = self._auto_split(mj)
        if mj * ms <= 1:
            mj, ms = 0, 1
        self.mesh_jobs = mj
        self.mesh_state = ms
        self.mesh_devices = mj * ms
        self._spec_trees = None
        if self.mesh_devices > 1:
            import jax
            from jax.sharding import NamedSharding, PartitionSpec
            mesh = jax.make_mesh(
                (mj, ms), ("jobs", "state"),
                devices=jax.devices()[:self.mesh_devices])
            self._sharding = NamedSharding(mesh, PartitionSpec("jobs"))
            if ms > 1:
                self._spec_trees = self._wave_spec_trees(mesh)
        else:
            self._sharding = None
        self._fn = self.eng.burst_batched_fn(
            donate=self._donate,
            sharding=(self._spec_trees if self._spec_trees is not None
                      else self._sharding))
        self._compiled = {}            # padded J -> AOT executable
        # constant-padding ceilings (round 13): with a serve_runtime
        # hook (rt_mode above), every job's guard thresholds / family
        # lane mask / search-bounds vector enter the batched program as
        # per-job device data (jst["rt"]) — cfg here is the bucket's
        # CEILING, which may sit strictly above any member job's config
        # (the rt memo cache itself is initialized next to rt_mode,
        # before the mesh build that may template rt arrays).
        # persistent AOT executable cache (serve/exec_cache): None =
        # the historical always-compile behavior
        self.exec_cache = exec_cache

    def _auto_split(self, D: int) -> Tuple[int, int]:
        """The ``auto`` 2-D heuristic (round 17): given D auto-resolved
        devices on the job axis, move power-of-two factors of D onto
        the state axis while ONE job's dedup state (W visited words +
        the claims word per table slot, u32 each) exceeds the
        per-device budget — a huge ceiling spans the mesh instead of
        pinning one device at its HBM wall.  S stays a divisor of D so
        the (J, S) grid is always full."""
        per_job = (self.eng.W + 1) * self.VCAP * 4
        s = 1
        while s * 2 <= D and D % (s * 2) == 0 and \
                per_job // s > _AUTO_STATE_BUDGET:
            s *= 2
        return D // s, s

    def _carry_template(self):
        """The batched carry as a [J=1] ShapeDtypeStruct pytree: the
        structure + leaf ranks the 2-D sharding rules match on
        (shardings are shape-free, so one template serves every wave
        width)."""
        import jax
        eng = self.eng
        one = eng.ir.narrow(eng.lay, eng.ir.encode(
            eng.lay, *eng.ir.init_state(eng.cfg)))
        sds = jax.ShapeDtypeStruct
        tpl = dict(
            vis=tuple(sds((1, self.VCAP), np.uint32)
                      for _ in range(eng.W)),
            claims=sds((1, self.VCAP), np.uint32),
            fr={k: sds((1,) + np.asarray(v).shape + (self.KB,),
                       np.asarray(v).dtype)
                for k, v in one.items()},
            fm=sds((1, self.KB), np.bool_),
            gd=sds((1, self.KB), np.int32),
            nf=sds((1,), np.int32),
            g=sds((1,), np.int32),
            pg=sds((1,), np.int32))
        if self.rt_mode:
            tpl["rt"] = {nm: sds((1,) + np.asarray(v).shape,
                                 np.asarray(v).dtype)
                         for nm, v in self._rt_of(eng.cfg).items()
                         if nm in ("thr", "mask", "bounds")}
        return tpl

    def _wave_spec_trees(self, mesh) -> Dict:
        """Per-leaf NamedSharding trees for the 2-D wave program:
        rule-matched PartitionSpecs (parallel/pjit_mesh's
        ``match_partition_rules``) over the carry template and the
        burst's output structure (via ``jax.eval_shape`` on the
        UNCHANGED ``_batched_burst_impl``), plus the job-axis gate
        sharding for the lv/cap vectors."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        from ..engine.bfs import _register_barrier_batching
        from ..parallel.pjit_mesh import match_partition_rules
        # the vmapped body hits the optimization-barrier batching rule
        # during eval_shape, before burst_batched_fn's own lazy
        # registration runs
        _register_barrier_batching()
        tpl = self._carry_template()
        gate = jax.ShapeDtypeStruct((1,), np.int32)
        out_tpl = jax.eval_shape(self.eng._batched_burst_impl,
                                 tpl, gate, gate)[1]

        def named(tree, rules):
            specs = match_partition_rules(rules, tree)
            return jax.tree_util.tree_map(
                lambda spec: NamedSharding(mesh, spec), specs,
                is_leaf=lambda x: isinstance(x, PartitionSpec))

        return {"carry": named(tpl, WAVE_CARRY_RULES),
                "gate": NamedSharding(mesh, PartitionSpec("jobs")),
                "out": named(out_tpl, WAVE_OUT_RULES)}

    def _rt_of(self, cfg) -> Dict[str, np.ndarray]:
        """One job's runtime-thresholds arrays under this bucket's
        ceiling expander, memoized per config repr (every wave of a
        resumed/parked job re-enters with identical arrays)."""
        key = repr(cfg)
        rt = self._rt_cache.get(key)
        if rt is None:
            rt = self._rt_cache[key] = \
                self.eng.ir.serve_runtime(self.eng.expander, cfg)
        return rt

    def _exec_key_parts(self, JP: int) -> Dict:
        """Every compile-relevant identity of the (bucket, JP)
        executable — serve/exec_cache docstring.  The ceiling cfg repr
        covers the predicate name lists, symmetry and fp128; the
        engine fields cover the program's static shapes and modes."""
        from ..obs.resources import backend_fingerprint
        from .exec_cache import code_fingerprint
        eng = self.eng
        return {
            "backend": backend_fingerprint(),
            # source identity: any package code change is a miss (a
            # stale executable must never answer for new semantics)
            "code": code_fingerprint(),
            "spec": eng.ir.name,
            "ir_fingerprint": eng.ir.fingerprint(),
            "ceiling_cfg": repr(eng.cfg),
            "JP": JP,
            "chunk": eng.chunk, "KB": self.KB, "VCAP": self.VCAP,
            "FCAP": eng.FCAP, "OCAP": eng.OCAP,
            "burst_levels": eng.burst_levels,
            "fam_caps": list(eng.FAM_CAPS),
            "W": eng.W,
            "guard_matmul": eng.guard_matmul,
            "delta_matmul": eng.expander.delta_active,
            # the RESOLVED canonicalization mode: sort and minperm
            # compile different fingerprint programs AND produce
            # different table values — never share an executable
            "sym_canon": eng.fpr.sym_canon,
            "incremental_fp": bool(eng.incremental_fp and
                                   eng.fpr.supports_incremental()),
            "rt_mode": self.rt_mode,
            # donation mode is program identity: a donated executable
            # must never be revived cross-process (see __init__)
            "donate": self._donate,
            # mesh shape is program identity too: the [J, S] grid (0
            # when off).  A 4x1 sharded executable must read as a
            # NAMED miss on a 2x2 or 1-device process (and vice
            # versa), never a wrong load — resharding changes the
            # GSPMD program, not just placement.  JP above already
            # covers the wave-lane width the mesh multiple changes.
            "wave_mesh": ([self.mesh_jobs, self.mesh_state]
                          if self.mesh_devices else 0),
        }

    # -- root admission ------------------------------------------------

    def _admit(self, run: _JobRun):
        """Level-0 admission for one job — the host-side twin of
        Engine.check's fresh-start path (roots dedup, invariant/
        constraint eval, archive, table placement).  Returns the
        per-job init arrays, or None when the root set cannot enter
        the batched path."""
        import jax.numpy as jnp

        from ..engine.bfs import Violation
        eng = self.eng
        roots, rk, _pins = eng._dedup_roots(run.job.seed_states)
        n = len(rk)
        if n > min(self.KB, int(eng._LOAD_MAX * self.VCAP)):
            run.mark_fallback(
                f"{n} root states exceed the bucket ring/table")
            return None
        narrow_mj = {k: np.asarray(v) for k, v in
                     eng.ir.narrow(eng.lay, eng.ir.widen(roots)).items()}
        rootsj = {k: jnp.asarray(v) for k, v in roots.items()}
        if self.rt_mode:
            # root constraints gate level-0 expansion: they must read
            # the JOB's bounds, not the ceiling's
            inv_r, con_r = eng._phase2_rt(
                rootsj,
                jnp.asarray(self._rt_of(run.job.cfg)["bounds"]))
        else:
            inv_r, con_r = eng._phase2(rootsj)
        inv_r, con_r = np.asarray(inv_r), np.asarray(con_r)
        res = run.res
        res.distinct_states = n
        res.generated_states = n
        res.overflow_faults = int(
            (np.asarray(roots["ctr"])[:, C_OVERFLOW] > 0).sum())
        res.violations_global = int((~inv_r).sum())
        eng._stamp_mode(res)
        if run.job.store_states:
            run.parents.append(np.full((n,), -1, np.int32))
            run.lanes.append(np.full((n,), -1, np.int32))
            run.states.append({k: v.copy()
                               for k, v in narrow_mj.items()})
        for jx, nm in enumerate(eng.inv_names):
            for s in np.nonzero(~inv_r[:, jx])[0]:
                vsv, vh = eng.ir.decode(eng.lay, _take(narrow_mj,
                                                       int(s)))
                res.violations.append(
                    Violation(nm, int(s), state=vsv, hist=vh))
        run.n_states = n
        run.n_front = n
        # the job is born finished when its gates already close
        if run.job.max_depth <= 0 or \
                res.distinct_states >= run.job.max_states or \
                (run.job.stop_on_violation and res.violations):
            run.finish()
        fr = {k: np.zeros(v.shape[1:] + (self.KB,), v.dtype)
              for k, v in narrow_mj.items()}
        for k in fr:
            fr[k][..., :n] = np.moveaxis(narrow_mj[k], 0, -1)
        fm = np.zeros((self.KB,), bool)
        fm[:n] = con_r
        vis = np.full((eng.W, self.VCAP), U32MAX_NP, np.uint32)
        slots = eng._host_probe_assign(rk, vcap=self.VCAP)
        for w in range(eng.W):
            vis[w][slots] = rk[:, w]
        return dict(fr=fr, fm=fm, vis=vis, nf=n, g=n)

    def _pad_init(self):
        """A frozen placeholder job (nf=0): pads a wave to its
        power-of-two width without contributing any work."""
        eng = self.eng
        one = eng.ir.narrow(eng.lay, eng.ir.encode(
            eng.lay, *eng.ir.init_state(eng.cfg)))
        fr = {k: np.zeros(v.shape + (self.KB,), v.dtype)
              for k, v in one.items()}
        fm = np.zeros((self.KB,), bool)
        vis = np.full((eng.W, self.VCAP), U32MAX_NP, np.uint32)
        out = dict(fr=fr, fm=fm, vis=vis, nf=0, g=0)
        if self.rt_mode:
            # a pad job still needs rt arrays of the stacked shape;
            # the ceiling's own (all-enabled) data is the natural
            # no-op — the pad lane is frozen (nf=0) regardless
            out["rt"] = self._rt_of(eng.cfg)
        return out

    def _pad_jp(self, n: int) -> int:
        """Wave width for n admitted jobs.  Single-device: the next
        power of two (tiny compile cache).  Mesh mode: a J-axis
        multiple J * pow2(ceil(n/J)) — the state axis never eats wave
        lanes — so every job row holds the same lane count and the pad
        lanes (frozen, nf=0) are the only idle-lane waste — surfaced
        as ``pad N/M`` by tools/watch."""
        J = self.mesh_jobs
        if J > 1:
            return J * _next_pow2(max(1, -(-n // J)))
        return _next_pow2(n)

    def _place(self, x):
        """Device placement for a job-axis wave input (the lv/cap gate
        vectors and, with S == 1, the whole carry): under the job mesh
        when sharding, else jax's default (single device).  Host numpy
        in (the _stack/_job_slice format is host-side and mode-blind)
        -> committed device arrays out, so a parked or restored carry
        re-enters ANY mesh shape — the wave.npz restart matrix is
        portable by construction."""
        if self._sharding is None:
            return x
        import jax
        return jax.device_put(x, self._sharding)

    def _place_carry(self, jst):
        """Carry placement: leaf-by-leaf under the 2-D per-leaf spec
        trees when the state axis is on, the single job-axis prefix
        otherwise (same _place portability contract either way)."""
        if self._spec_trees is not None:
            import jax
            return jax.tree_util.tree_map(jax.device_put, jst,
                                          self._spec_trees["carry"])
        return self._place(jst)

    def _stack(self, inits):
        import jax.numpy as jnp
        eng = self.eng
        JP = len(inits)
        # gd/pg default to the fresh-start values (root gids are the
        # ring prefix; no previous level); a restored/parked init
        # carries its real cursors (wave-state resume, round 12)
        gd0 = np.arange(self.KB, dtype=np.int32)
        rt = {}
        if self.rt_mode:
            # per-job runtime thresholds / lane masks / bounds on the
            # leading [J] axis (engine/bfs._batched_burst_impl)
            rt = dict(rt={
                nm: jnp.asarray(np.stack(
                    [np.asarray(it["rt"][nm]) for it in inits]))
                for nm in ("thr", "mask", "bounds")})
        return self._place_carry(dict(
            **rt,
            vis=tuple(jnp.asarray(np.stack([it["vis"][w]
                                            for it in inits]))
                      for w in range(eng.W)),
            claims=jnp.full((JP, self.VCAP), np.uint32(U32MAX_NP)),
            fr={k: jnp.asarray(np.stack([it["fr"][k] for it in inits]))
                for k in inits[0]["fr"]},
            fm=jnp.asarray(np.stack([it["fm"] for it in inits])),
            gd=jnp.asarray(np.stack([
                np.asarray(it.get("gd", gd0), np.int32)
                for it in inits])),
            nf=jnp.asarray(np.array([it["nf"] for it in inits],
                                    np.int32)),
            g=jnp.asarray(np.array([it["g"] for it in inits],
                                   np.int32)),
            pg=jnp.asarray(np.array([int(it.get("pg", 0))
                                     for it in inits], np.int32)),
        ))

    def _job_slice(self, jst, k: int) -> Dict:
        """One job's lane of the batched carry -> a host init dict
        (the _stack/_admit format plus gd/pg) — the parkable/
        persistable per-job wave state."""
        eng = self.eng
        return dict(
            fr={nm: np.asarray(v[k]) for nm, v in jst["fr"].items()},
            fm=np.asarray(jst["fm"][k]),
            vis=np.stack([np.asarray(jst["vis"][w][k])
                          for w in range(eng.W)]),
            gd=np.asarray(jst["gd"][k]),
            nf=int(np.asarray(jst["nf"][k])),
            g=int(np.asarray(jst["g"][k])),
            pg=int(np.asarray(jst["pg"][k])))

    # -- the wave driver -----------------------------------------------

    def run_wave(self, runs: List[_JobRun], obs, meta: Dict,
                 jobs_ctx: Optional[Dict] = None,
                 verbose: bool = False,
                 max_steps: Optional[int] = None,
                 wave_state: Optional[WaveStateStore] = None,
                 slo_ctx: Optional[Dict] = None,
                 stop=None):
        """Run up to a wave of jobs through the batched burst.
        Mutates the runs in place; jobs that bail are marked for the
        sequential fallback.  ``jobs_ctx`` is the batch-global per-job
        status map (heartbeat payload) this wave merges its own
        statuses into.

        ``max_steps`` — preemption (round 12): after that many batched
        device calls, still-live jobs PARK (their carry slice moves to
        ``run.preinit``) and the wave returns, yielding the lanes to
        waiting jobs; the driver re-enters parked runs in a later
        wave.  ``wave_state`` persists every live job's slice at each
        wave boundary, so a killed process resumes stragglers
        mid-BFS.

        ``stop`` — graceful drain (serve/scheduler): a callable
        checked at every wave step boundary, AFTER the wave-state
        persist; when it returns true, still-live jobs park exactly as
        a ``max_steps`` yield would, so the scheduler can defer them
        with their carries safely on disk."""
        import jax.numpy as jnp
        eng = self.eng
        with obs.span("job_admit"):
            admitted = []
            for run in runs:
                if run.preinit is not None:
                    # parked/restored job: enter with its carry slice,
                    # not root admission (counters already accrued)
                    init, run.preinit = run.preinit, None
                    eng._stamp_mode(run.res)
                else:
                    init = self._admit(run)
                if init is not None:
                    if self.rt_mode:
                        # rt is derived from the job's config, never
                        # persisted: parked/restored carries re-attach
                        # it here (bit-identical arrays by construction)
                        init["rt"] = self._rt_of(run.job.cfg)
                    admitted.append((run, init))
        if not any(run.live for run, _ in admitted):
            for run, _ in admitted:
                if not run.fallback:
                    run.finish()
            return
        JP = self._pad_jp(len(admitted))
        inits = [init for _run, init in admitted]
        inits += [self._pad_init()] * (JP - len(admitted))
        jst = self._stack(inits)
        # wave occupancy (rounds 16-17): the J x S grid, lanes and the
        # pad waste, for the heartbeat/ledger and the registry counters
        wave_dev = max(1, self.mesh_devices)
        wave_ss = max(1, self.mesh_state)
        wave_occ = {"devices": wave_dev, "lanes": JP,
                    "filled": len(admitted),
                    "pad": JP - len(admitted),
                    "jobs_per_device": JP // max(1, self.mesh_jobs),
                    "state_shards": wave_ss}
        meta["wave_devices"] = max(meta.get("wave_devices", 0),
                                   wave_dev)
        meta["wave_lanes"] = max(meta.get("wave_lanes", 0), JP)
        meta["wave_state_shards"] = max(
            meta.get("wave_state_shards", 0), wave_ss)
        steps = 0
        while any(run.live for run, _ in admitted):
            # chaos site: dispatch-time device/tunnel error on the
            # batched program (the batch-level --retries re-runs the
            # job list; cache + wave state make the retry incremental)
            chaos_point("dispatch")
            lv = np.zeros((JP,), np.int32)
            cap = np.ones((JP,), np.int32)
            for k, (run, _) in enumerate(admitted):
                if run.live:
                    lv[k] = min(eng.burst_levels,
                                run.job.max_depth - run.depth)
                    cap[k] = max(1, min(
                        run.job.max_states - run.res.distinct_states,
                        2 ** 31 - 1))
            lvj = self._place(jnp.asarray(lv))
            capj = self._place(jnp.asarray(cap))
            ex = self._compiled.get(JP)
            key = parts = None
            if ex is None and self.exec_cache is not None:
                # persistent AOT executable cache (serve/exec_cache):
                # a warm restart loads the serialized executable and
                # performs ZERO .compile() calls; any failure is a
                # labeled miss and falls through to the compile below
                from .exec_cache import exec_key
                parts = self._exec_key_parts(JP)
                key = exec_key(parts)
                with obs.span("bucket_exec_load"):
                    ex, _why = self.exec_cache.load(key, parts)
                if ex is not None:
                    self._compiled[JP] = ex
            if ex is None:
                # AOT compile, in its own span: the bench and the
                # ledger attribute bucket-compile seconds exactly
                with obs.span("bucket_compile"):
                    ex = self._fn.lower(jst, lvj, capj).compile()
                self._compiled[JP] = ex
                if self.exec_cache is not None:
                    # store failures are counted + named (a backend
                    # without serialization support), never raised
                    with obs.span("bucket_exec_store"):
                        self.exec_cache.store(key, ex, parts)
            with obs.span("batched_dispatch"):
                jst, out = ex(jst, lvj, capj)
                stats = np.asarray(out["stats"])   # the ONE sync
            meta["batch_dispatches"] += 1
            with obs.span("job_harvest"):
                for k, (run, _) in enumerate(admitted):
                    if not run.live:
                        continue
                    # archives transfer PER JOB, and only for jobs
                    # that keep traces or hit a violation — a wave
                    # where one job stores never pays the whole
                    # [J, levels, ...] stack's device-to-host cost
                    need = run.job.store_states or stats[k, -1, 3]
                    self._harvest(
                        run, stats[k],
                        np.asarray(out["par"][k]) if need else None,
                        np.asarray(out["lane"][k]) if need else None,
                        np.asarray(out["inv"][k]) if need else None,
                        {nm: np.asarray(v[k])
                         for nm, v in out["st"].items()}
                        if need else None)
            steps += 1
            if wave_state is not None:
                # wave boundary: persist every still-live job's carry
                # slice + bookkeeping, so a kill between here and the
                # next boundary resumes mid-BFS (finished jobs are
                # covered by the result cache instead)
                with obs.span("wave_persist"):
                    for k, (run, _) in enumerate(admitted):
                        if run.live:
                            run.preinit = self._job_slice(jst, k)
                            wave_state.save(run.job.cache_key(),
                                            run.wave_arrays(),
                                            run.book())
                            run.preinit = None
            # chaos site: the deterministic SIGKILL stand-in — fires
            # AFTER the persist, exactly like a kill at the boundary
            chaos_point("wave_kill")
            if ((max_steps is not None and steps >= max_steps) or
                    (stop is not None and stop())) and \
                    any(run.live for run, _ in admitted):
                # preemption: park the stragglers' carry slices and
                # yield the lanes to waiting jobs; the driver requeues
                # parked runs into a later wave
                for k, (run, _) in enumerate(admitted):
                    if run.live:
                        run.preinit = self._job_slice(jst, k)
                        run.parked = True
            live_runs = [run for run, _ in admitted]
            jobs_map = dict(jobs_ctx or {})
            jobs_map.update(_jobs_map(live_runs))
            if jobs_ctx is not None:
                jobs_ctx.update(jobs_map)
            obs.dispatch(
                kind="batch",
                depth=max((r.depth for r in live_runs), default=0),
                frontier=sum(r.n_front for r in live_runs if r.live),
                metrics={
                    "distinct_states": sum(
                        int(r.res.distinct_states) for r in live_runs),
                    "generated_states": sum(
                        int(r.res.generated_states)
                        for r in live_runs)},
                jobs=jobs_map, slo=slo_ctx, wave=wave_occ)
            if verbose:
                done = sum(1 for r in live_runs if not r.live)
                print(f"batch wave: {done}/{len(live_runs)} jobs done, "
                      f"max depth "
                      f"{max((r.depth for r in live_runs), default=0)}")
            if any(run.parked for run, _ in admitted):
                break

    def _harvest(self, run: _JobRun, sj, par_j, lane_j, inv_j, st_j):
        """One job's slice of a batched call — the solo burst harvest
        (the SHARED engine/driver core, so the serve copy can never
        drift from the engine drivers again; depth gating, pseudo-level
        skip, archive rows, violation decode all run in
        driver.harvest_fused_levels)."""
        from ..engine import driver
        eng = self.eng
        res = run.res
        nlev = int(sj[-1, 0])
        bailed = bool(sj[-1, 1])
        res.burst_dispatches += 1
        res.burst_bailouts += int(bailed)
        if bailed:
            # the job outgrew its per-job ring / table / family caps:
            # discard the batched progress and re-run it solo (the solo
            # engine owns every growth path).  Exact by construction.
            run.mark_fallback("burst bailed (per-job ring or table "
                              "overflow) — re-run sequentially")
            return

        def _arch(li, n_lvl):
            if not run.job.store_states:
                return
            # zero-row levels still occupy an archive slot so gid
            # arithmetic matches the solo archives
            par, lane, states = driver.burst_archive_slice(
                par_j, lane_j, st_j, li, n_lvl)
            run.parents.append(par)
            run.lanes.append(lane)
            run.states.append(states)

        def _viol(li, n_lvl, gid_base):
            driver.burst_decode_violations(
                res, eng.ir, eng.lay, eng.inv_names, inv_j, st_j,
                li, n_lvl, gid_base)

        # no id guard: per-job ids never approach 2^31 (the historical
        # serve harvest carried none — bit-exact re-homing)
        run.depth, run.n_states = driver.harvest_fused_levels(
            res, nlev, lambda li: sj[li, :5], run.depth, run.n_states,
            archive=_arch, violations=_viol, id_guard=False)
        run.n_front = int(sj[-1, 2])
        if run.n_front == 0 or run.depth >= run.job.max_depth or \
                res.distinct_states >= run.job.max_states or \
                (run.job.stop_on_violation and res.violations):
            run.finish()
        elif nlev == 0:
            # defensive: a live job that neither committed a level nor
            # bailed would spin this driver forever — route it to the
            # exact sequential path instead
            run.mark_fallback("batched call made no progress")


# ---------------------------------------------------------------------------
# the driver
# ---------------------------------------------------------------------------

def _run_solo(job: Job, obs, meta: Dict, status: str,
              reason: Optional[str],
              sym_canon: str = "auto") -> JobOutcome:
    """One job on its own Engine (the sequential reference path):
    used for --sequential runs, batched-path fallbacks, and seeded/
    pinned jobs.  Engine dispatches ride the same obs bundle, so the
    ledger records the solo device traffic honestly.  sym_canon
    follows any bucket override so a fallback job dedups with the
    same canonicalization program its bucket would have."""
    from ..engine.bfs import Engine
    with obs.span("sequential_job"):
        eng = Engine(job.cfg, store_states=job.store_states,
                     sym_canon=sym_canon)
        meta["engines_compiled"] += 1
        res = eng.check(max_depth=job.max_depth,
                        max_states=job.max_states,
                        stop_on_violation=job.stop_on_violation,
                        seed_states=job.seed_states, obs=obs)
    tracer = eng.trace if job.store_states else None
    report = _build_report(job, res, status, reason=reason,
                           tracer=tracer)
    return JobOutcome(job, status, res=res, report=report, engine=eng,
                      reason=reason)


def run_jobs(jobs: List[Job], cache=None, obs=None,
             sequential: bool = False, bucket_overrides=None,
             verbose: bool = False, wave_state=None,
             wave_yield: Optional[int] = None,
             max_wave: Optional[int] = None,
             exec_cache=None, wave_mesh=None) -> BatchReport:
    """Serve a job list: cache lookups, shape-bucket grouping, batched
    waves, sequential fallbacks, cache fill.  Returns a BatchReport
    with outcomes in submission order.

    sequential=True skips the batched path entirely (one solo Engine
    per job) — the honest A/B reference bench.py records.
    bucket_overrides overrides the per-spec bucket params (tests force
    tiny rings with it to exercise the fallback).

    exec_cache (round 13) — a serve/exec_cache.ExecCache or a
    directory path: bucket executables are serialized around their
    ``.lower().compile()`` so a process restart re-loads them instead
    of re-paying the 30-50 s TPU compiles; hit/miss/store counters
    (incl. named miss reasons on backends that cannot serialize) land
    in the batch meta, the ledger and the heartbeat SLO snapshot.

    Round 12 (preemptible waves): jobs schedule by descending
    ``Job.priority`` (stable on submission order); ``wave_yield=N``
    makes a wave yield its lanes after N batched device calls while
    other jobs wait — stragglers PARK their carry and continue in a
    later wave.  ``wave_state`` (a WaveStateStore or directory path)
    persists every live job's carry at wave boundaries and resumes
    jobs from it on the next invocation, so a killed run continues
    finished jobs from the result cache and stragglers mid-BFS —
    bit-exact per job.  ``max_wave`` overrides the jobs-per-wave
    ceiling (default 8 per device; tests shrink it to force parking).

    ``wave_mesh`` (rounds 16-17) — ``"auto"`` (default), ``"off"``, a
    device count, or a ``JxS`` grid (e.g. ``"4x2"``): shard every
    batched wave across a 2-D ("jobs", "state") mesh of local devices
    (``resolve_wave_mesh``); S > 1 also shards each job's visited
    table / rings / level buffers so one huge tenant spans the mesh,
    and ``auto`` promotes state shards when the bucket ceiling
    exceeds the per-device budget.  Per-job results stay bit-exact in
    every mode; the wave ceiling scales to J x 8 lanes unless
    ``max_wave`` pins it.

    This function is the one-shot wrapper over the shared
    ``serve/scheduler.WaveScheduler`` core — the SAME driver loop the
    persistent daemon (``cli serve``) runs every intake cycle.  All
    scheduling logic (priority, yield/park, dedup, restore, fallback,
    rollups) lives there; this module keeps the per-wave machinery
    (``BucketEngine``) and the per-job bookkeeping it drives."""
    from .scheduler import WaveScheduler
    return WaveScheduler(
        cache=cache, wave_state=wave_state, exec_cache=exec_cache,
        bucket_overrides=bucket_overrides, wave_yield=wave_yield,
        max_wave=max_wave, wave_mesh=wave_mesh).serve(
        jobs, obs=obs, sequential=sequential, verbose=verbose)
