"""Fingerprint-keyed result cache for the serving layer.

Keys are ``Job.cache_key()`` — (spec name, IR structure fingerprint,
config fingerprint, engine-options fingerprint) — so equal keys imply
an identical ``CheckResult``; a hit short-circuits the job with ZERO
device dispatches (the CI batch smoke asserts a re-run's ledger shows
none).  Values are the per-job report payloads (serve/batch builds
them): JSON-able counters, level sizes and violation summaries incl.
witness trace labels.

Storage is one JSON file per key under the cache directory, written
atomically (write-then-rename), plus a per-process dict so repeat jobs
inside one batch never touch the disk twice.  A corrupt or
foreign-keyed file reads as a miss, never an error — the cache is an
optimization, not a source of truth.

Eviction (round 11, ROADMAP 1): optional LRU-by-bytes.  With
``max_bytes`` set, every ``put`` trims the directory back under the
bound by deleting the least-recently-USED payload files first —
recency is the file mtime, which ``get`` refreshes on every disk hit,
so a hot key survives cold ones regardless of insertion order.  The
just-written payload is never evicted (a single oversized payload may
therefore transiently exceed the bound — the next put retires it like
any other cold entry).  ``max_bytes=None`` (the default) preserves the
historical unbounded behavior exactly.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional


class ResultCache:
    def __init__(self, path: str, max_bytes: Optional[int] = None):
        if max_bytes is not None and int(max_bytes) <= 0:
            raise ValueError(
                f"cache max_bytes must be positive (got {max_bytes}); "
                "omit it for an unbounded cache")
        self.path = path
        self.max_bytes = None if max_bytes is None else int(max_bytes)
        os.makedirs(path, exist_ok=True)
        self._mem: Dict[str, Dict] = {}

    def _file(self, key: str) -> str:
        return os.path.join(self.path, key + ".json")

    def _touch(self, key: str):
        """LRU recency refresh (file mtime) on a hit — including
        in-process dict hits, since the dict dies with the batch but
        the eviction order must not.  Unbounded caches skip it:
        reads stay write-free there."""
        if self.max_bytes is None:
            return
        try:
            os.utime(self._file(key))
        except OSError:
            pass

    def get(self, key: str) -> Optional[Dict]:
        hit = self._mem.get(key)
        if hit is not None:
            self._touch(key)
            return dict(hit)
        try:
            with open(self._file(key)) as fh:
                obj = json.load(fh)
        except (OSError, ValueError):
            return None
        if not isinstance(obj, dict) or obj.get("cache_key") != key:
            return None          # foreign/corrupt payload: a miss
        self._touch(key)
        self._mem[key] = obj
        return dict(obj)

    def put(self, key: str, payload: Dict):
        payload = dict(payload)
        payload["cache_key"] = key
        self._mem[key] = payload
        tmp = self._file(key) + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(payload, fh)
        os.replace(tmp, self._file(key))
        self._evict(keep=key)

    def _evict(self, keep: str):
        """Trim the directory back under max_bytes, least-recently-used
        first, never touching the just-written ``keep`` payload.  A
        racing deletion reads as already-evicted, never an error."""
        if self.max_bytes is None:
            return
        entries = []
        total = 0
        for nm in os.listdir(self.path):
            if not nm.endswith(".json"):
                continue
            fp = os.path.join(self.path, nm)
            try:
                st = os.stat(fp)
            except OSError:
                continue
            total += st.st_size
            entries.append((st.st_mtime, st.st_size, nm))
        if total <= self.max_bytes:
            return
        for mtime, size, nm in sorted(entries):
            if nm == keep + ".json":
                continue
            try:
                os.remove(os.path.join(self.path, nm))
            except OSError:
                continue
            self._mem.pop(nm[:-len(".json")], None)
            total -= size
            if total <= self.max_bytes:
                break

    def __len__(self) -> int:
        return sum(1 for nm in os.listdir(self.path)
                   if nm.endswith(".json"))
