"""Fingerprint-keyed result cache for the serving layer.

Keys are ``Job.cache_key()`` — (spec name, IR structure fingerprint,
config fingerprint, engine-options fingerprint) — so equal keys imply
an identical ``CheckResult``; a hit short-circuits the job with ZERO
device dispatches (the CI batch smoke asserts a re-run's ledger shows
none).  Values are the per-job report payloads (serve/batch builds
them): JSON-able counters, level sizes and violation summaries incl.
witness trace labels.

Storage is one JSON file per key under the cache directory, written
atomically (write-then-rename), plus a per-process dict so repeat jobs
inside one batch never touch the disk twice.  A corrupt or
foreign-keyed file reads as a miss, never an error — the cache is an
optimization, not a source of truth.  Eviction is deliberately absent
(ROADMAP 2b remaining work); the directory is the operator's to prune.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional


class ResultCache:
    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self._mem: Dict[str, Dict] = {}

    def _file(self, key: str) -> str:
        return os.path.join(self.path, key + ".json")

    def get(self, key: str) -> Optional[Dict]:
        hit = self._mem.get(key)
        if hit is not None:
            return dict(hit)
        try:
            with open(self._file(key)) as fh:
                obj = json.load(fh)
        except (OSError, ValueError):
            return None
        if not isinstance(obj, dict) or obj.get("cache_key") != key:
            return None          # foreign/corrupt payload: a miss
        self._mem[key] = obj
        return dict(obj)

    def put(self, key: str, payload: Dict):
        payload = dict(payload)
        payload["cache_key"] = key
        self._mem[key] = payload
        tmp = self._file(key) + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(payload, fh)
        os.replace(tmp, self._file(key))

    def __len__(self) -> int:
        return sum(1 for nm in os.listdir(self.path)
                   if nm.endswith(".json"))
