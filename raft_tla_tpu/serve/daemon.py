"""The persistent checking daemon — `cli serve` (ROADMAP item 1).

A long-lived process over the shared wave-scheduler core
(serve/scheduler): poll the spool (serve/intake) — and optionally a
JSONL stream tail — claim complete submissions, drain each claimed
batch through ``WaveScheduler.serve()``, and write one atomic result
JSON + done/ marker per submission.  ``cli batch`` is this loop run
for exactly one cycle with the jobs handed in directly; the daemon
adds only intake, the poll cadence, signals, and per-cycle telemetry
— every scheduling decision (priority, ``--wave-yield`` parking,
dedup, cache, wave-state restore) is the scheduler's.

Lifecycle / restart matrix (pinned by tools/daemon_smoke.py and
tests/test_daemon.py):

- **SIGTERM/SIGINT** — graceful drain: the current wave parks at its
  next step boundary (carries already persisted to ``--wave-state``),
  unanswered jobs stay claimed, a ``kind="daemon"`` drain row and one
  registry record (cmd="serve", status="done", drain reason) flush,
  the final heartbeat says ``status="done"`` — and the process exits
  0.  Watch renders that as FINISHED, never a stall.
- **SIGKILL mid-wave** — nothing graceful ran, but nothing is lost:
  claimed files survive, wave-state carries survive, finished jobs
  sit in the result cache.  The next start re-claims every leftover
  (``SpoolIntake.recover``) and the scheduler resumes stragglers
  mid-BFS bit-exact — the round-12 kill path, served warm.
- **warm restart with --executable-cache** — zero bucket compiles:
  the scheduler's persistent engines cover repeat buckets within a
  process, the executable cache covers them across processes.
- **cycle failure** — transient errors (the resil RETRYABLE set,
  chaos faults included) retry the whole cycle with bounded backoff
  (``--retries``/``--backoff``); the retry is incremental via the
  result cache + wave state.  Exhaustion exits 3 with a
  status="failed" registry record — the supervisor's restart signal.

Heartbeat: between waves the daemon beats ``status idle|serving|
draining`` with a ``daemon`` block (cycle counter, queue depths,
cumulative done/rejected, per-tenant rollups) that also rides every
in-wave dispatch beat — ``tools/watch.py`` renders the daemon view
from it and skips cadence-based stall flagging for a daemon that is
merely idle-but-beating.
"""

from __future__ import annotations

import signal
import time
from typing import Dict, List, Optional

from ..obs import NULL_OBS
from ..resil.supervisor import RETRYABLE, backoff_delay
from .intake import SpoolIntake, StreamTail, Submission
from .scheduler import WaveScheduler

__all__ = ["Daemon"]


class Daemon:
    """The serve loop (module docstring).  Construction wires the
    intake, the optional stream tail and the scheduler; ``run()`` is
    the process main loop and owns ``obs.finish`` (the CLI only
    builds and starts the bundle)."""

    def __init__(self, spool: str, cache=None, wave_state=None,
                 exec_cache=None, obs=None, poll_s: float = 0.5,
                 wave_yield: Optional[int] = None,
                 max_wave: Optional[int] = None,
                 wave_mesh=None,
                 bucket_overrides=None, retries: int = 0,
                 backoff: float = 2.0,
                 max_idle_polls: Optional[int] = None,
                 stream: Optional[str] = None, grace_s: float = 5.0,
                 verbose: bool = False, sleep=time.sleep):
        self.intake = SpoolIntake(spool, grace_s=grace_s)
        self.stream = (StreamTail(stream, self.intake)
                       if stream else None)
        # wave_mesh rides to the scheduler untouched: a mesh-mode
        # daemon restart resumes single-device .wave.npz carries and
        # vice versa (the slices are host numpy; BucketEngine._place
        # re-homes them under whatever mesh THIS process runs)
        self.sched = WaveScheduler(cache=cache, wave_state=wave_state,
                                   exec_cache=exec_cache,
                                   bucket_overrides=bucket_overrides,
                                   wave_yield=wave_yield,
                                   max_wave=max_wave,
                                   wave_mesh=wave_mesh)
        self.obs = obs if obs is not None else NULL_OBS
        self.poll_s = float(poll_s)
        self.retries = int(retries)
        self.backoff = float(backoff)
        self.max_idle_polls = max_idle_polls
        self.verbose = verbose
        self.sleep = sleep
        self.stats: Dict[str, int] = dict(
            cycles=0, jobs_claimed=0, jobs_done=0, jobs_rejected=0,
            jobs_recovered=0, cache_hits=0, violations=0)
        # per-tenant (spec) cumulative rollup for the daemon heartbeat
        self.tenants: Dict[str, Dict[str, int]] = {}
        self._pending: List[Submission] = []
        self._drain: Optional[str] = None

    # -- drain plumbing ------------------------------------------------

    def request_drain(self, reason: str):
        if self._drain is None:
            self._drain = reason

    def draining(self) -> bool:
        """The scheduler's ``stop`` callable: checked at every wave
        step boundary, after the wave-state persist."""
        return self._drain is not None

    def install_signals(self):
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, lambda signum, _frame:
                          self.request_drain(
                              f"signal {signal.Signals(signum).name}"))

    # -- telemetry -----------------------------------------------------

    def _daemon_block(self, status: str) -> Dict:
        d = dict(self.stats)
        d["status"] = status
        d.update(self.intake.counts())
        d["tenants"] = {k: dict(v) for k, v in self.tenants.items()}
        if self._drain is not None:
            d["drain_reason"] = self._drain
        return d

    def _beat(self, status: str):
        self.obs.daemon_beat(status=status,
                             stats=self._daemon_block(status))

    def _ledger(self, rec: Dict):
        if self.obs.ledger is not None:
            self.obs.ledger.record(rec)

    # -- the cycle -----------------------------------------------------

    def _poll_intake(self) -> List[Submission]:
        if self.stream is not None:
            self.stream.poll()
        claimed, rejected = self.intake.poll()
        for sub in claimed:
            self.stats["jobs_claimed"] += 1
            self._ledger({"kind": "intake", "action": "claimed",
                          "name": sub.name, "label": sub.job.label,
                          "spec": sub.job.ir.name,
                          "cache_key": sub.job.cache_key()})
        for name, reason in rejected:
            self.stats["jobs_rejected"] += 1
            self._ledger({"kind": "intake", "action": "rejected",
                          "name": name, "reason": reason[:300]})
        return claimed

    def _finalize(self, sub: Submission, outcome):
        self.intake.write_result(sub.name, outcome.report)
        self.intake.mark_done(sub.name, outcome.report)
        self.stats["jobs_done"] += 1
        self.stats["cache_hits"] += int(outcome.status == "cache_hit")
        self.stats["violations"] += int(
            outcome.report.get("violations", 0))
        t = self.tenants.setdefault(sub.job.ir.name, dict(
            jobs_done=0, cache_hits=0, violations=0))
        t["jobs_done"] += 1
        t["cache_hits"] += int(outcome.status == "cache_hit")
        t["violations"] += int(outcome.report.get("violations", 0))

    def run_cycle(self) -> Optional[object]:
        """One poll + serve round: None when intake was empty (idle),
        else the cycle's BatchReport.  Raises the last RETRYABLE error
        when per-cycle retries exhaust (run() turns that into exit
        3).  Exposed for in-process tests — run() is this in a loop
        plus signals and the drain epilogue."""
        new = self._pending + self._poll_intake()
        self._pending = []
        if not new:
            return None
        self.stats["cycles"] += 1
        self._beat("serving")
        jobs = [sub.job for sub in new]
        attempt = 0
        while True:
            try:
                rep = self.sched.serve(jobs, obs=self.obs,
                                       verbose=self.verbose,
                                       stop=self.draining)
                break
            except RETRYABLE as e:
                # the retry is incremental: answered jobs hit the
                # result cache, stragglers resume from wave state —
                # and the claimed files are untouched either way
                if attempt >= self.retries:
                    self._pending = new
                    raise
                wait = backoff_delay(attempt, self.backoff, 60.0)
                self.obs.retry(attempt=attempt + 1,
                               max_attempts=self.retries + 1,
                               wait_s=wait, error=e)
                self.sleep(wait)
                attempt += 1
        deferred = 0
        for sub, outcome in zip(new, rep.outcomes):
            if outcome is None:
                # deferred by a drain: the claimed file stays — this
                # process (or the next) picks it up again
                self._pending.append(sub)
                deferred += 1
                continue
            self._finalize(sub, outcome)
        self._ledger({"kind": "daemon", "cycle": self.stats["cycles"],
                      "claimed": len(new),
                      "done": len(new) - deferred,
                      "deferred": deferred,
                      **{k: rep.meta[k] for k in
                         ("cache_hits", "buckets", "engines_compiled",
                          "batch_dispatches", "resumed_jobs",
                          "parked_waves", "deferred_jobs", "drained")
                         if k in rep.meta}})
        return rep

    # -- the main loop -------------------------------------------------

    def run(self) -> int:
        recovered, rejected = self.intake.recover()
        for sub in recovered:
            self.stats["jobs_recovered"] += 1
            self._ledger({"kind": "intake", "action": "recovered",
                          "name": sub.name, "label": sub.job.label,
                          "spec": sub.job.ir.name,
                          "cache_key": sub.job.cache_key()})
        for name, reason in rejected:
            self.stats["jobs_rejected"] += 1
            self._ledger({"kind": "intake", "action": "rejected",
                          "name": name, "reason": reason[:300]})
        self._pending = recovered
        idle = 0
        status = "failed"              # any abnormal exit path
        try:
            while not self.draining():
                try:
                    rep = self.run_cycle()
                except RETRYABLE as e:
                    print(f"serve cycle failed: {e}", flush=True)
                    return 3
                if rep is None and not self._pending:
                    idle += 1
                    self._beat("idle")
                    if self.max_idle_polls is not None and \
                            idle >= self.max_idle_polls:
                        self.request_drain(
                            f"idle for {idle} polls")
                        break
                    self.sleep(self.poll_s)
                else:
                    idle = 0
            self._beat("draining")
            status = "done"
            return 0
        finally:
            # the drain epilogue runs on EVERY exit path (graceful
            # drain, retry exhaustion, unexpected error): final
            # heartbeat status "done"/"failed" with the drain reason,
            # plus the one registry record per drain cycle — both
            # cross-linked to the job/intake ledger rows by run id.
            # A graceful exit that still has work parked records
            # registry status "draining" (the heartbeat stays "done"):
            # `cli obs ls --status draining` lists exactly the drain
            # cycles a successor daemon must pick up.
            extra = {"daemon": self._daemon_block(status),
                     "drain_reason": self._drain or ""}
            if status == "done" and self._pending:
                extra["status"] = "draining"
            self.obs.finish(
                status=status,
                counters={k: int(v) for k, v in self.stats.items()},
                extra=extra)
