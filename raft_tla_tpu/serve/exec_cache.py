"""Persistent AOT bucket-executable cache (ROADMAP item 1, round 13).

The batched serving layer AOT-compiles one executable per (bucket,
padded job count) via ``.lower().compile()`` — 30-50 s per program on
the tunneled TPU — and until now every process restart re-paid all of
them.  This module serializes compiled executables to disk around that
call (``serve/batch.BucketEngine``), keyed so a stale or foreign entry
can never be silently executed:

- **key** — sha256 of the canonical JSON of every compile-relevant
  part: backend fingerprint (platform, device kind + count, jax
  version), spec name + IR structure fingerprint, the bucket CEILING
  config repr + bucket params, the padded job count JP, the wave-mesh
  shape (the ``[J, S]`` grid — resharding is a different GSPMD
  program, so a mesh-shape change is a NAMED miss, never a wrong
  load), and the engine's program-shaping option/mode flags
  (guard/delta matmul, runtime-thresholds mode, ring/cap widths, W,
  family caps).  Any drift in any part is a different key — a miss,
  never a wrong load.
- **entries** — one ``<key>.exec`` file per executable: a pickled
  container embedding the FULL key and its parts next to the
  serializer's blob, published atomically (write + rename).  A corrupt
  or truncated file, a foreign/renamed entry, or an embedded key
  mismatch all read as a labeled miss.
- **honesty** — backends whose runtime cannot (de)serialize
  executables (``jax.experimental.serialize_executable`` raising, or
  absent) degrade to a NAMED miss/store-failure reason, counted and
  surfaced in the batch summary + ledger — never a crash, never a
  silent recompile that the telemetry reports as a hit.

The serializer is injectable (``serializer=``) so CPU tests pin the
keying, the round-trip plumbing, and the corrupt-entry paths without
depending on the backend's serialization support (jax 0.4.37's CPU
runtime does round-trip, which the tests also exercise for real).
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
from typing import Dict, Optional, Tuple

_FORMAT = 1

# the backend identity helper was born here as part of the cache key;
# ISSUE 17 hoisted it to obs/resources.py (the obs layer stamps the
# same dict on every ledger meta row and registry record) — re-exported
# so cache-key call sites keep importing it from here
from ..obs.resources import backend_fingerprint  # noqa: E402,F401


_CODE_FP = None


def code_fingerprint() -> str:
    """sha256 over every ``raft_tla_tpu`` source file's bytes (path-
    sorted) — the SOURCE identity of the compiled program.  Without
    this a warm cache would happily serve executables compiled from
    an older checkout after a semantics-affecting engine/kernel/spec
    change: every other key part (backend, ceiling repr, shape flags)
    would still match, and the service would return the OLD code's
    answers while telemetry reports a healthy hit.  Hashing the
    package source makes any code drift a guaranteed (coarse but
    safe) miss.  Computed once per process."""
    global _CODE_FP
    if _CODE_FP is None:
        import raft_tla_tpu
        root = os.path.dirname(os.path.abspath(raft_tla_tpu.__file__))
        h = hashlib.sha256()
        for dirpath, dirnames, filenames in sorted(os.walk(root)):
            dirnames.sort()
            for nm in sorted(filenames):
                if not nm.endswith(".py"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, nm), root)
                h.update(rel.encode())
                with open(os.path.join(dirpath, nm), "rb") as fh:
                    h.update(fh.read())
        _CODE_FP = h.hexdigest()[:16]
    return _CODE_FP


def exec_key(parts: Dict) -> str:
    """Canonical-JSON sha256 of the key parts (order-independent)."""
    desc = json.dumps(parts, sort_keys=True, separators=(",", ":"),
                      default=str)
    return hashlib.sha256(desc.encode()).hexdigest()[:32]


class JaxExecSerializer:
    """The real thing: ``jax.experimental.serialize_executable``.
    ``serialize`` returns one bytes blob (payload + in/out pytree defs
    pickled together); ``deserialize`` loads it back into a callable
    Compiled.  Either side may raise on backends without serialization
    support — ExecCache turns that into a labeled miss."""

    name = "jax.experimental.serialize_executable"

    def serialize(self, compiled) -> bytes:
        from jax.experimental import serialize_executable as se
        payload, in_tree, out_tree = se.serialize(compiled)
        return pickle.dumps((payload, in_tree, out_tree))

    def deserialize(self, blob: bytes):
        from jax.experimental import serialize_executable as se
        payload, in_tree, out_tree = pickle.loads(blob)
        return se.deserialize_and_load(payload, in_tree, out_tree)


class ExecCache:
    """One directory of serialized bucket executables + honest hit/miss
    accounting.  ``load``/``store`` never raise on entry or backend
    problems — every failure is a counted, named miss (the acceptance
    contract: a non-serializable backend reads as a labeled miss, not
    a crash or a silent wrong result)."""

    def __init__(self, path: str, serializer=None,
                 max_bytes: Optional[int] = None):
        if max_bytes is not None and int(max_bytes) <= 0:
            raise ValueError(
                f"executable-cache max_bytes must be positive (got "
                f"{max_bytes}); omit it for an unbounded cache")
        self.path = path
        # LRU-by-bytes eviction bound (mirrors serve/cache.ResultCache,
        # the ROADMAP item-1 leftover: bucket executables are MBs each
        # on TPU, so a long-lived service needs a directory bound).
        # Recency = file mtime, refreshed on every warm LOAD, so a hot
        # bucket survives cold ones regardless of insertion order; the
        # just-stored entry is never the victim (one oversized
        # executable may transiently exceed the bound — the next store
        # retires it like any other cold entry).  None = the historical
        # unbounded behavior, exactly.
        self.max_bytes = None if max_bytes is None else int(max_bytes)
        os.makedirs(path, exist_ok=True)
        self._ser = serializer if serializer is not None \
            else JaxExecSerializer()
        self.hits = 0
        self.misses = 0
        self.stores = 0
        self.store_failures = 0
        self.evictions = 0
        # the most recent miss/store-failure reasons, newest last
        # (bounded: telemetry, not a log)
        self.miss_reasons = []
        self.store_fail_reasons = []

    # -- accounting ----------------------------------------------------

    def _miss(self, reason: str) -> Tuple[None, str]:
        self.misses += 1
        self.miss_reasons = (self.miss_reasons + [reason])[-8:]
        return None, reason

    def stats(self) -> Dict:
        return {
            "exec_cache_hits": self.hits,
            "exec_cache_misses": self.misses,
            "exec_cache_stores": self.stores,
            "exec_cache_store_failures": self.store_failures,
            "exec_cache_evictions": self.evictions,
            "exec_cache_miss_reasons": list(self.miss_reasons),
            "exec_cache_store_fail_reasons":
                list(self.store_fail_reasons),
        }

    def _touch(self, key: str):
        """LRU recency refresh on a warm load — bounded caches only
        (unbounded reads stay write-free, the historical behavior)."""
        if self.max_bytes is None:
            return
        try:
            os.utime(self._entry_path(key))
        except OSError:
            pass

    def _evict(self, keep: str):
        """Trim the directory back under max_bytes, least-recently-
        used (oldest mtime) first, never touching the just-written
        ``keep`` entry.  A racing deletion reads as already-evicted,
        never an error."""
        if self.max_bytes is None:
            return
        entries = []
        total = 0
        for nm in os.listdir(self.path):
            if not nm.endswith(".exec"):
                continue
            fp = os.path.join(self.path, nm)
            try:
                st = os.stat(fp)
            except OSError:
                continue
            total += st.st_size
            entries.append((st.st_mtime, st.st_size, nm))
        if total <= self.max_bytes:
            return
        for _mtime, size, nm in sorted(entries):
            if nm == keep + ".exec":
                continue
            try:
                os.remove(os.path.join(self.path, nm))
            except OSError:
                continue
            self.evictions += 1
            total -= size
            if total <= self.max_bytes:
                break

    def _entry_path(self, key: str) -> str:
        return os.path.join(self.path, key + ".exec")

    # -- the two operations BucketEngine wraps around lower/compile ----

    def load(self, key: str, parts: Optional[Dict] = None):
        """(executable | None, reason).  Every None is a labeled
        miss: cold entry, corrupt/truncated pickle, foreign entry
        (embedded key mismatch — e.g. a renamed or hand-copied file),
        serializer mismatch, or a backend that cannot deserialize."""
        fp = self._entry_path(key)
        if not os.path.exists(fp):
            return self._miss("cold: no entry for this key")
        try:
            with open(fp, "rb") as fh:
                obj = pickle.load(fh)
        except Exception as e:
            return self._miss(
                f"corrupt entry (unreadable: {type(e).__name__})")
        if not isinstance(obj, dict) or obj.get("format") != _FORMAT:
            return self._miss("corrupt entry (bad container format)")
        if obj.get("key") != key:
            return self._miss(
                "foreign entry (embedded key mismatch — file renamed "
                "or copied across caches)")
        if parts is not None and obj.get("parts") != dict(parts):
            # belt + suspenders under the truncated-sha key: the FULL
            # part set must match, not just its digest
            return self._miss(
                "foreign entry (embedded key parts mismatch)")
        ser_name = getattr(self._ser, "name", type(self._ser).__name__)
        if obj.get("serializer") != ser_name:
            return self._miss(
                f"serializer mismatch (entry: {obj.get('serializer')!r},"
                f" runtime: {ser_name!r})")
        try:
            ex = self._ser.deserialize(obj["blob"])
        except Exception as e:
            return self._miss(
                f"backend cannot deserialize executables "
                f"({type(e).__name__}: {str(e)[:120]})")
        self.hits += 1
        self._touch(key)
        return ex, "hit"

    def store(self, key: str, compiled, parts: Optional[Dict] = None
              ) -> bool:
        """Serialize + atomically publish one executable; False (with
        a recorded named reason) when the backend cannot serialize —
        the compile that just happened still served the run, the cache
        simply stays cold."""
        try:
            blob = self._ser.serialize(compiled)
        except Exception as e:
            self.store_failures += 1
            self.store_fail_reasons = (self.store_fail_reasons + [
                f"backend cannot serialize executables "
                f"({type(e).__name__}: {str(e)[:120]})"])[-8:]
            return False
        obj = {"format": _FORMAT, "key": key,
               "parts": dict(parts or {}),
               "serializer": getattr(self._ser, "name",
                                     type(self._ser).__name__),
               "blob": blob}
        fp = self._entry_path(key)
        tmp = fp + ".tmp"
        try:
            with open(tmp, "wb") as fh:
                pickle.dump(obj, fh)
            os.replace(tmp, fp)
        except OSError as e:
            self.store_failures += 1
            self.store_fail_reasons = (self.store_fail_reasons + [
                f"cache dir unwritable ({e})"])[-8:]
            return False
        self.stores += 1
        self._evict(keep=key)
        return True
