"""Spool-directory intake for the persistent checking daemon.

The daemon's submission surface is a directory, because a directory
is the one queue every client already has: drop a file, get a result
file back.  Layout (all subdirectories are created on demand, all
writes throughout are write-then-rename atomic):

    <spool>/incoming/   clients drop ONE JSON job object per file —
                        the same record schema as a ``--jobs`` JSONL
                        line (serve/jobs.job_from_dict), ending with
                        a trailing newline.
    <spool>/claimed/    the daemon atomically renames a submission
                        here before serving it.  A claimed file IS
                        the restart contract: a daemon killed
                        mid-wave re-claims every leftover on the next
                        start and resumes it (mid-BFS via wave state,
                        or instantly via the result cache).
    <spool>/rejected/   malformed submissions, moved verbatim, plus a
                        ``NAME.reason`` file naming the parse error —
                        quarantine, never a daemon crash.
    <spool>/results/    one atomic result JSON per submission (the
                        same per-job report row ``cli batch`` prints).
    <spool>/done/       one small marker per finished submission
                        (name, status, cache key) — the client-visible
                        completion signal, written AFTER the result
                        file, so a marker always has its result.

Write-then-rename protocol (documented in README "Daemon service",
enforced here, pinned by tests/test_daemon.py): clients MUST write
the job elsewhere (or to ``NAME.json.tmp`` in incoming/) and
``rename(2)`` it in — the rename is the commit point.  Two guards
keep a non-conforming or crashed writer from corrupting the queue:

- files named ``*.tmp`` / ``*.part`` and dotfiles are never claimed;
- a file NOT ending in a newline is treated as still-being-written
  and left untouched for ``grace_s`` seconds (measured from its
  mtime); past the grace it quarantines with a reason naming the torn
  write.  A complete submission therefore always ends with ``\\n`` —
  cheap for writers, and it makes "torn" detectable without fsync
  games.

Duplicates need no special casing here: two submissions of an
identical job claim independently and the scheduler answers the
second from the result cache / in-batch dedup (``cache_hit`` rows) —
the three-part job fingerprint is the dedup key, not the filename.

``chaos_point("intake")`` (resil/chaos) fires before each claim
rename: an injected intake fault leaves the submission in incoming/
for the next poll — claims are idempotent.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..resil.chaos import chaos_point
from .jobs import Job, job_from_dict

__all__ = ["SpoolIntake", "StreamTail", "Submission"]

_SKIP_SUFFIXES = (".tmp", ".part")


def _atomic_write(path: str, data: str):
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        fh.write(data)
    os.replace(tmp, path)


@dataclass
class Submission:
    """One claimed job: the spool name that keys its result/done
    files, the parsed Job, and where its claimed file sits."""
    name: str
    job: Job
    path: str
    recovered: bool = False


class SpoolIntake:
    """The spool-directory protocol (module docstring): scan, claim,
    quarantine, recover, and write results/markers."""

    def __init__(self, root: str, grace_s: float = 5.0):
        self.root = root
        self.grace_s = float(grace_s)
        self.dirs = {nm: os.path.join(root, nm)
                     for nm in ("incoming", "claimed", "rejected",
                                "results", "done")}
        for d in self.dirs.values():
            os.makedirs(d, exist_ok=True)

    # -- client side ---------------------------------------------------

    def submit(self, obj: Dict, name: str) -> str:
        """Write-then-rename a job object into incoming/ (the protocol
        clients must follow; tools and tests submit through this)."""
        if os.sep in name or name.startswith("."):
            raise ValueError(f"bad submission name {name!r}")
        final = os.path.join(self.dirs["incoming"], name + ".json")
        _atomic_write(final, json.dumps(obj) + "\n")
        return final

    # -- daemon side ---------------------------------------------------

    def _quarantine(self, src: str, name: str, reason: str):
        dst = os.path.join(self.dirs["rejected"],
                           os.path.basename(src))
        os.replace(src, dst)
        _atomic_write(dst + ".reason", reason.rstrip("\n") + "\n")

    def poll(self) -> Tuple[List[Submission],
                            List[Tuple[str, str]]]:
        """One incoming/ scan: claim every complete submission, leave
        in-progress writes alone, quarantine the malformed.  Returns
        (claimed submissions, [(name, reason)] rejections)."""
        claimed: List[Submission] = []
        rejected: List[Tuple[str, str]] = []
        inc = self.dirs["incoming"]
        now = time.time()
        for fn in sorted(os.listdir(inc)):
            if fn.startswith(".") or fn.endswith(_SKIP_SUFFIXES):
                continue
            path = os.path.join(inc, fn)
            name = fn[:-5] if fn.endswith(".json") else fn
            try:
                with open(path, "rb") as fh:
                    raw = fh.read()
            except OSError:
                continue               # raced with a writer's rename
            if not raw.endswith(b"\n"):
                # no trailing newline = still being written (or a torn
                # writer): honor the grace window, then quarantine
                try:
                    age = now - os.path.getmtime(path)
                except OSError:
                    continue
                if age < self.grace_s:
                    continue
                reason = (f"torn/incomplete job file (no trailing "
                          f"newline after {self.grace_s:g}s grace) — "
                          f"write-then-rename a complete JSON object "
                          f"ending with a newline")
                self._quarantine(path, name, reason)
                rejected.append((name, reason))
                continue
            # an injected intake fault aborts the scan BEFORE the
            # claim: the submission stays in incoming/ for the next
            # poll (claims are idempotent)
            chaos_point("intake")
            try:
                job = job_from_dict(
                    json.loads(raw.decode("utf-8")), where=fn)
            except Exception as e:     # malformed = quarantined, never
                reason = str(e)        # a daemon crash
                self._quarantine(path, name, reason)
                rejected.append((name, reason))
                continue
            # claimed files are always NAME.json, whatever the client
            # called the submission — mark_done recomputes this path
            dst = os.path.join(self.dirs["claimed"], name + ".json")
            os.replace(path, dst)
            claimed.append(Submission(name=name, job=job, path=dst))
        return claimed, rejected

    def recover(self) -> Tuple[List[Submission],
                               List[Tuple[str, str]]]:
        """Startup re-claim: every leftover claimed/ file from a
        killed daemon re-enters the queue.  A leftover whose result
        already landed (killed between result write and marker) is
        finalized instead of recomputed."""
        out: List[Submission] = []
        rejected: List[Tuple[str, str]] = []
        cl = self.dirs["claimed"]
        for fn in sorted(os.listdir(cl)):
            path = os.path.join(cl, fn)
            name = fn[:-5] if fn.endswith(".json") else fn
            res_path = os.path.join(self.dirs["results"],
                                    name + ".json")
            if os.path.exists(res_path):
                if not os.path.exists(os.path.join(
                        self.dirs["done"], name + ".json")):
                    try:
                        with open(res_path) as fh:
                            report = json.load(fh)
                    except (OSError, ValueError):
                        report = {}
                    self.mark_done(name, report)
                else:
                    os.unlink(path)
                continue
            try:
                with open(path, "rb") as fh:
                    raw = fh.read()
                job = job_from_dict(
                    json.loads(raw.decode("utf-8")), where=fn)
            except Exception as e:
                # defensive: claims are validated before the rename,
                # so this means the spool was tampered with — same
                # quarantine, not a crash
                reason = str(e)
                self._quarantine(path, name, reason)
                rejected.append((name, reason))
                continue
            out.append(Submission(name=name, job=job, path=path,
                                  recovered=True))
        return out, rejected

    def write_result(self, name: str, report: Dict) -> str:
        path = os.path.join(self.dirs["results"], name + ".json")
        _atomic_write(path, json.dumps(report) + "\n")
        return path

    def mark_done(self, name: str, report: Dict):
        """Write the done/ marker (AFTER the result file) and retire
        the claimed file — the submission's terminal transition."""
        marker = {"name": name,
                  "status": report.get("status"),
                  "label": report.get("label"),
                  "cache_key": report.get("cache_key")}
        _atomic_write(os.path.join(self.dirs["done"], name + ".json"),
                      json.dumps(marker) + "\n")
        claimed = os.path.join(self.dirs["claimed"], name + ".json")
        if os.path.exists(claimed):
            os.unlink(claimed)

    def counts(self) -> Dict[str, int]:
        """Live queue-depth numbers for the daemon heartbeat (watch's
        daemon view): files currently in each lifecycle directory."""
        out = {}
        for nm, d in self.dirs.items():
            try:
                out[nm] = sum(
                    1 for fn in os.listdir(d)
                    if not fn.startswith(".")
                    and not fn.endswith(_SKIP_SUFFIXES)
                    and not fn.endswith(".reason"))
            except OSError:
                out[nm] = 0
        return out


class StreamTail:
    """Tail an append-only JSONL job stream into the spool.

    Each COMPLETE appended line (newline-terminated; blank lines and
    #-comments skipped, the ``--jobs`` file conventions) materializes
    as a spool submission named ``stream-<n>`` through the normal
    incoming/ protocol — so validation, quarantine, claiming and
    recovery are all the directory path's, with no second copy.  The
    consumed byte offset persists atomically next to the spool; a
    restarted daemon resumes the tail where it left off, so stream
    jobs are neither re-submitted nor dropped.  A partial final line
    (writer mid-append) stays unconsumed until its newline lands.
    Re-materializing an already-written submission after a crash
    between the file write and the offset persist is harmless: the
    name is deterministic, the content identical."""

    def __init__(self, path: str, intake: SpoolIntake):
        self.path = path
        self.intake = intake
        self.state_path = os.path.join(intake.root, "stream.offset")
        self.offset = 0
        self.lineno = 0
        try:
            with open(self.state_path) as fh:
                st = json.load(fh)
            self.offset = int(st.get("offset", 0))
            self.lineno = int(st.get("lineno", 0))
        except (OSError, ValueError):
            pass

    def poll(self) -> int:
        """Consume complete appended lines; returns the number of
        submissions materialized."""
        try:
            with open(self.path, "rb") as fh:
                fh.seek(self.offset)
                data = fh.read()
        except OSError:
            return 0
        n = 0
        consumed = 0
        while True:
            nl = data.find(b"\n", consumed)
            if nl < 0:
                break
            line = data[consumed:nl]
            consumed = nl + 1
            text = line.decode("utf-8", "replace").strip()
            if not text or text.startswith("#"):
                continue
            self.lineno += 1
            name = f"stream-{self.lineno:06d}"
            final = os.path.join(self.intake.dirs["incoming"],
                                 name + ".json")
            _atomic_write(final, text + "\n")
            n += 1
        if consumed:
            self.offset += consumed
            _atomic_write(self.state_path, json.dumps(
                {"offset": self.offset, "lineno": self.lineno}))
        return n
