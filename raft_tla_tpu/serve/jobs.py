"""Job objects for the batched serving layer.

A ``Job`` is one user request: a model config plus the engine options
that shape the *result* (depth/state gates, stop-on-violation, trace
retention).  Jobs carry three fingerprints:

- the active spec's IR structure fingerprint (``SpecIR.fingerprint``),
- the config fingerprint — sha256 of ``repr(cfg)``, the same canonical
  identity string checkpoint resume compares byte-for-byte,
- the engine-options fingerprint — sha256 of the canonical JSON of the
  result-affecting options above (and a digest of any seed states).

Their concatenation is the result-cache key (serve/cache): two jobs
with equal keys are guaranteed the same ``CheckResult``, so a repeat
job is answered without any device dispatch.

``job_from_dict`` parses the JSONL job format the ``batch`` CLI
subcommand consumes (README "Batch / serving"); every unknown key
errors by name.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass
from typing import Dict, List, Optional

from ..spec import SpecIR, spec_of


def _sha(text: str) -> str:
    return hashlib.sha256(text.encode()).hexdigest()[:16]


@dataclass
class Job:
    """One check request.  ``cfg`` is a model config object (raft
    ``ModelConfig`` or ``PaxosConfig``); the rest are the engine
    options that affect the result.  Options that only shape the
    execution (chunk sizes, burst toggles) are bucket properties, not
    job ones — they cannot change the answer, so they stay out of the
    options fingerprint."""

    cfg: object
    max_depth: int = 10 ** 9
    max_states: int = 10 ** 9
    stop_on_violation: bool = True
    store_states: bool = True
    label: str = ""
    # engine seed SoA dicts (punctuated search) — batched waves route
    # seeded jobs to the sequential fallback; still cacheable (the
    # seed digest rides the options fingerprint)
    seed_states: Optional[List] = None
    # wave-scheduling priority (higher runs first; round 12): a
    # SCHEDULING property, not a result one — deliberately outside the
    # options fingerprint so priority changes never miss the cache
    priority: int = 0

    def __post_init__(self):
        if self.max_depth < 0:
            raise ValueError(f"max_depth must be >= 0 "
                             f"(got {self.max_depth})")
        if self.max_states < 0:
            raise ValueError(f"max_states must be >= 0 "
                             f"(got {self.max_states})")

    @property
    def ir(self) -> SpecIR:
        return spec_of(self.cfg)

    def cfg_fingerprint(self) -> str:
        return _sha(repr(self.cfg))

    def opts_fingerprint(self) -> str:
        opts = {"max_depth": int(self.max_depth),
                "max_states": int(self.max_states),
                "stop_on_violation": bool(self.stop_on_violation),
                "store_states": bool(self.store_states)}
        if self.seed_states is not None:
            import numpy as np
            h = hashlib.sha256()
            for seed in self.seed_states:
                for k in sorted(seed):
                    h.update(k.encode())
                    h.update(np.ascontiguousarray(
                        np.asarray(seed[k])).tobytes())
            opts["seeds"] = h.hexdigest()[:16]
        return _sha(json.dumps(opts, sort_keys=True))

    def cache_key(self) -> str:
        ir = self.ir
        return "-".join((ir.name, ir.fingerprint(),
                         self.cfg_fingerprint(),
                         self.opts_fingerprint()))


# ---------------------------------------------------------------------------
# JSONL job format (the `batch` CLI subcommand; README "Batch / serving")
# ---------------------------------------------------------------------------

_TOP_KEYS = ("spec", "config", "overrides", "max_depth", "max_states",
             "keep_going", "store", "label", "priority")
_RAFT_OVERRIDES = ("servers", "values", "max_inflight", "next",
                   "symmetry", "invariants", "bounds")
_RAFT_BOUNDS = ("max_log_length", "max_restarts", "max_timeouts",
                "max_client_requests", "max_membership_changes",
                "max_terms", "max_trace")
_NEXT_NAMES = ("NextAsync", "NextAsyncCrash", "Next", "NextDynamic")


def _raft_cfg(config, overrides, where: str):
    from ..cfg.parser import load_model
    from ..config import Bounds
    from ..spec import get_spec
    if not isinstance(config, str):
        raise ValueError(
            f"{where}: raft jobs need 'config': a TLC .cfg path "
            f"(got {config!r})")
    cfg = load_model(config)
    ov = dict(overrides or {})
    unknown = sorted(set(ov) - set(_RAFT_OVERRIDES))
    if unknown:
        raise ValueError(
            f"{where}: unknown raft override(s) "
            f"{', '.join(map(repr, unknown))}; known: "
            f"{', '.join(_RAFT_OVERRIDES)}")
    kw = {}
    if "servers" in ov:
        n = int(ov["servers"])
        kw["n_servers"] = n
        kw["init_servers"] = tuple(range(n))
        # MaxInFlightMessages is a formula over |Server| in the spec;
        # recompute it exactly as the CLI --servers override does
        old_n, infl = cfg.n_servers, cfg.max_inflight_override
        if infl == 2 * old_n * old_n:
            kw["max_inflight_override"] = 2 * n * n
        elif infl == 4 * old_n * old_n:
            kw["max_inflight_override"] = 4 * n * n
    if "values" in ov:
        kw["values"] = tuple(int(v) for v in ov["values"])
    if "max_inflight" in ov:
        kw["max_inflight_override"] = int(ov["max_inflight"])
    if "next" in ov:
        if ov["next"] not in _NEXT_NAMES:
            raise ValueError(
                f"{where}: unknown NEXT family {ov['next']!r}; known: "
                f"{', '.join(_NEXT_NAMES)}")
        kw["next_family"] = ov["next"]
    if "symmetry" in ov:
        kw["symmetry"] = bool(ov["symmetry"])
    if "invariants" in ov:
        known = get_spec("raft").known_invariants
        bad = [nm for nm in ov["invariants"] if nm not in known]
        if bad:
            raise ValueError(
                f"{where}: unknown invariant(s) "
                f"{', '.join(map(repr, bad))} for spec 'raft'")
        kw["invariants"] = tuple(ov["invariants"])
    if "bounds" in ov:
        bd = dict(ov["bounds"])
        unknown = sorted(set(bd) - set(_RAFT_BOUNDS))
        if unknown:
            raise ValueError(
                f"{where}: unknown bounds key(s) "
                f"{', '.join(map(repr, unknown))}; known: "
                f"{', '.join(_RAFT_BOUNDS)}")
        b = cfg.bounds
        kw["bounds"] = Bounds.make(
            max_log_length=bd.get("max_log_length", b.max_log_length),
            max_restarts=bd.get("max_restarts", b.max_restarts),
            max_timeouts=bd.get("max_timeouts", b.max_timeouts),
            max_client_requests=bd.get("max_client_requests",
                                       b.max_client_requests),
            max_membership_changes=bd.get("max_membership_changes",
                                          b.max_membership_changes),
            # None derives MaxTerms = MaxTimeouts + 1, the spec formula
            max_terms=bd.get("max_terms"),
            max_trace=bd.get("max_trace", b.max_trace))
    return cfg.with_(**kw) if kw else cfg


def _paxos_cfg(config, where: str):
    from ..cfg.parser import load_paxos_model, paxos_config_from_obj
    from ..spec.paxos.config import PaxosConfig
    if config is None or config == "default":
        return PaxosConfig()
    if isinstance(config, dict):
        return paxos_config_from_obj(config, where=where)
    if isinstance(config, str):
        if config.endswith(".cfg"):
            return load_paxos_model(config)
        with open(config) as fh:
            return paxos_config_from_obj(json.load(fh), where=config)
    raise ValueError(
        f"{where}: paxos 'config' must be a constants object, a .cfg/"
        f"JSON path, or 'default' (got {config!r})")


def job_from_dict(obj: Dict, where: str = "job") -> Job:
    """One JSONL job record -> a Job.  Format (README):

      {"spec": "raft"|"paxos", "config": ..., "overrides": {...},
       "max_depth": N, "max_states": N, "keep_going": bool,
       "store": bool, "label": "name"}

    raft: config is a TLC .cfg path; overrides tweak the parsed model
    (servers/values/max_inflight/next/symmetry/invariants/bounds).
    paxos: config is an inline constants object, a .cfg or JSON path,
    or "default"; overrides are rejected (fold constants into config).
    Unknown keys error by name."""
    from ..spec import spec_names
    if not isinstance(obj, dict):
        raise ValueError(f"{where}: a job must be a JSON object "
                         f"(got {type(obj).__name__})")
    unknown = sorted(set(obj) - set(_TOP_KEYS))
    if unknown:
        raise ValueError(
            f"{where}: unknown job key(s) "
            f"{', '.join(map(repr, unknown))}; known: "
            f"{', '.join(_TOP_KEYS)}")
    spec = obj.get("spec", "raft")
    if spec not in spec_names():
        raise ValueError(f"{where}: unknown spec {spec!r}; known "
                         f"specs: {', '.join(spec_names())}")
    if spec == "paxos":
        if obj.get("overrides"):
            raise ValueError(
                f"{where}: 'overrides' is raft-only — fold paxos "
                "constants into 'config'")
        cfg = _paxos_cfg(obj.get("config"), where)
    else:
        cfg = _raft_cfg(obj.get("config"), obj.get("overrides"), where)
    for nm in ("max_depth", "max_states"):
        v = obj.get(nm)
        if v is not None and (isinstance(v, bool)
                              or not isinstance(v, int) or v < 0):
            raise ValueError(
                f"{where}: {nm} must be a non-negative integer "
                f"(got {v!r})")
    prio = obj.get("priority", 0)
    if isinstance(prio, bool) or not isinstance(prio, int):
        raise ValueError(
            f"{where}: priority must be an integer (higher runs "
            f"first; got {prio!r})")
    return Job(cfg,
               max_depth=obj.get("max_depth", 10 ** 9),
               max_states=obj.get("max_states", 10 ** 9),
               stop_on_violation=not obj.get("keep_going", False),
               store_states=bool(obj.get("store", True)),
               label=str(obj.get("label", "")),
               priority=prio)


def load_jobs(path: str) -> List[Job]:
    """Parse a JSONL job file (one job object per line; blank lines
    and #-comments skipped)."""
    jobs = []
    with open(path) as fh:
        for ln, line in enumerate(fh, 1):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            where = f"{os.path.basename(path)}:{ln}"
            try:
                obj = json.loads(line)
            except ValueError as e:
                raise ValueError(f"{where}: not a JSON object ({e})")
            jobs.append(job_from_dict(obj, where=where))
    return jobs
