"""The shared wave-scheduler core — ONE copy of the serving driver.

``cli batch`` (drain a job list once) and the persistent daemon
(``cli serve`` / serve/daemon) are the same machine run at different
cadences, so the whole driver loop that used to live inline in
``serve/batch.run_jobs`` lives HERE and nowhere else: result-cache
lookups, in-batch duplicate dedup, wave-state restore, shape
bucketing, priority ordering, ``wave_yield`` parking, sequential
fallbacks, SLO tracking, per-tenant ledger rollups and the cache
fill/retire pass.  ``run_jobs`` is now a thin one-shot wrapper and
the daemon calls ``serve()`` once per intake cycle — neither owns a
second copy of any scheduling rule (tests/test_daemon.py pins the
routing the way tests/test_driver.py pins the engine drivers).

Why a class and not a function: the daemon is long-lived.  A
``WaveScheduler`` keeps its ``BucketEngine``s (and their compiled
executables) across ``serve()`` rounds, so a service that sees the
same bucket wave after wave compiles it ONCE per process — round N+1
reports ``engines_compiled=0`` even without ``--executable-cache``
(which extends the same guarantee across process restarts).

Graceful drain: ``serve(jobs, stop=...)`` checks the ``stop``
callable at every wave boundary (and between waves/buckets).  When it
fires, still-live jobs PARK — their carry slice is already persisted
to ``wave_state`` at the step boundary — and every unanswered job is
DEFERRED: its outcome stays ``None``, its wave state survives, and
``meta["deferred_jobs"]``/``meta["drained"]`` say so.  A later
``serve()`` of the same jobs (same process or a restart) answers
finished jobs from the result cache and resumes stragglers mid-BFS
bit-exact — the daemon's SIGTERM path is exactly the round-12 kill
path, minus the kill.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..obs import NULL_OBS
from ..spec import spec_of
from .batch import (_MAX_WAVE, BatchReport, BucketEngine, JobOutcome,
                    _build_report, _default_serve_bucket, _job_row,
                    _JobRun, _run_solo, _SloTracker,
                    resolve_wave_mesh)
from .jobs import Job
from .wavestate import WaveStateStore

__all__ = ["WaveScheduler"]


class WaveScheduler:
    """The serving driver's long-lived half: stores (result cache,
    wave state, executable cache), bucket parameters, and the
    persistent ``BucketEngine`` map.  ``serve()`` drains one job list
    through it; the daemon calls it once per intake cycle, ``cli
    batch`` exactly once."""

    def __init__(self, cache=None, wave_state=None, exec_cache=None,
                 bucket_overrides=None,
                 wave_yield: Optional[int] = None,
                 max_wave: Optional[int] = None,
                 wave_mesh=None):
        if isinstance(wave_state, str):
            wave_state = WaveStateStore(wave_state)
        if isinstance(exec_cache, str):
            from .exec_cache import ExecCache
            exec_cache = ExecCache(exec_cache)
        if wave_yield is not None and int(wave_yield) < 1:
            raise ValueError(f"wave_yield must be >= 1 "
                             f"(got {wave_yield})")
        # mesh waves (rounds 16-17): resolve "auto"/"off"/N/"JxS" once,
        # here, to the (J, S) grid — every BucketEngine this scheduler
        # builds shards (or not) identically, and the default wave
        # ceiling scales with the JOB axis only: J rows x _MAX_WAVE
        # lanes each (state shards widen a job, not the wave).  An
        # "auto" resolve additionally lets each bucket re-split its
        # grid to S > 1 when its ceiling outgrows the per-device state
        # budget (batch._auto_split) — wave_mesh_auto marks that
        # freedom.
        self.wave_mesh = resolve_wave_mesh(wave_mesh)
        self.wave_mesh_auto = wave_mesh is None or wave_mesh == "auto"
        wave_cap = (int(max_wave) if max_wave is not None
                    else _MAX_WAVE * max(1, self.wave_mesh[0]))
        if wave_cap < 1:
            raise ValueError(f"max_wave must be >= 1 (got {max_wave})")
        self.cache = cache
        self.wave_state = wave_state
        self.exec_cache = exec_cache
        self.bucket_overrides = dict(bucket_overrides or {})
        self.wave_yield = None if wave_yield is None else int(wave_yield)
        self.wave_cap = wave_cap
        # bucket key -> BucketEngine, persisted across serve() rounds:
        # a daemon serving the same bucket every cycle compiles once
        self._engines: Dict[tuple, BucketEngine] = {}

    def _bucket_engine(self, bkey, ceiling, params, meta
                       ) -> BucketEngine:
        be = self._engines.get(bkey)
        if be is None:
            be = BucketEngine(ceiling, exec_cache=self.exec_cache,
                              wave_mesh=self.wave_mesh,
                              wave_mesh_auto=self.wave_mesh_auto,
                              **params)
            self._engines[bkey] = be
            meta["engines_compiled"] += 1
        return be

    def serve(self, jobs: List[Job], obs=None,
              sequential: bool = False, verbose: bool = False,
              stop=None) -> BatchReport:
        """Drain one job list: cache lookups, dedup, wave-state
        restore, bucketed waves, solo fallbacks, cache fill.  Returns
        a BatchReport with outcomes in submission order — an outcome
        is ``None`` only when ``stop`` fired first (deferred; see the
        module docstring)."""
        obs = obs if obs is not None else NULL_OBS
        t0 = time.perf_counter()
        cache, wave_state = self.cache, self.wave_state
        meta = dict(jobs=len(jobs), cache_hits=0, buckets=0,
                    engines_compiled=0, batch_dispatches=0,
                    fallback_jobs=0, sequential=bool(sequential),
                    resumed_jobs=0, parked_waves=0,
                    # wave occupancy highwater marks (rounds 16-17):
                    # run_wave maxes these per wave; 0 = no batched
                    # wave ran (cache-only or sequential runs)
                    wave_devices=0, wave_lanes=0, wave_state_shards=0)
        slo = _SloTracker(len(jobs))
        stopped = False

        def _want_stop() -> bool:
            nonlocal stopped
            if not stopped and stop is not None and stop():
                stopped = True
            return stopped

        # labels key the heartbeat/watch job map and the report rows —
        # empty ones get positional names, duplicates get #N suffixes
        # so two same-labeled jobs never collapse into one watch line.
        # (The Job objects are relabeled in place: the outcome rows
        # must carry the same names the heartbeat used.)
        seen_labels: Dict[str, int] = {}
        for i, job in enumerate(jobs):
            if not job.label:
                job.label = f"job{i}"
            base = job.label
            if base in seen_labels:
                n = seen_labels[base]
                while f"{base}#{n + 1}" in seen_labels:
                    n += 1
                seen_labels[base] = n + 1
                job.label = f"{base}#{n + 1}"
            seen_labels.setdefault(job.label, 1)
        outcomes: List[Optional[JobOutcome]] = [None] * len(jobs)
        deferred: set = set()
        # the batch-global per-job status map every heartbeat carries
        jobs_ctx: Dict[str, Dict] = {}
        pending: List[int] = []
        key_first: Dict[str, int] = {}
        dup_of: Dict[int, int] = {}
        for i, job in enumerate(jobs):
            key = job.cache_key()
            hit = cache.get(key) if cache is not None else None
            if hit is not None:
                meta["cache_hits"] += 1
                outcomes[i] = JobOutcome._from_cache(job, hit)
                jobs_ctx[job.label] = {
                    "depth": int(hit.get("depth", 0)),
                    "distinct": int(hit.get("distinct_states", 0)),
                    "status": "cache_hit"}
                slo.job_done(0.0, 0.0)     # served instantly, honestly
                _job_row(obs, outcomes[i])
            elif key in key_first:
                # two equal cache keys in one list are guaranteed the
                # same result — compute once, answer the duplicate from
                # the first job's outcome
                dup_of[i] = key_first[key]
            else:
                key_first[key] = i
                pending.append(i)
        meta["deduped"] = len(dup_of)
        solo: List[Tuple[int, str, Optional[str]]] = []
        # wave-state resume: a pending job with a persisted carry
        # enters its wave mid-BFS instead of from the roots (a killed
        # run's stragglers; finished jobs were answered by the cache)
        restored: Dict[int, _JobRun] = {}
        if wave_state is not None and not sequential:
            for i in pending:
                hit = wave_state.load(jobs[i].cache_key())
                if hit is None:
                    continue
                arrays, book = hit
                restored[i] = _JobRun.from_wave_state(jobs[i], arrays,
                                                      book)
                meta["resumed_jobs"] += 1
                if obs.ledger is not None:
                    obs.ledger.record({
                        "kind": "wave_resume", "label": jobs[i].label,
                        "depth": int(book["depth"]),
                        "distinct": int(book["distinct"])})
        if sequential:
            solo = [(i, "done", None) for i in pending]
        else:
            buckets: Dict[tuple, list] = {}
            for i in pending:
                job = jobs[i]
                ir = spec_of(job.cfg)
                if job.seed_states is not None or \
                        getattr(job.cfg, "prefix_pins", ()):
                    solo.append((i, "fallback",
                                 "seeded/prefix-pinned jobs run "
                                 "sequentially"))
                    continue
                hook = ir.serve_bucket or _default_serve_bucket
                ceiling, params = hook(job.cfg)
                params = dict(params)
                params.update(self.bucket_overrides)
                bkey = (ir.name, ir.fingerprint(), repr(ceiling),
                        tuple(sorted(params.items())))
                buckets.setdefault(
                    bkey, [ceiling, params, []])[2].append(i)
            meta["buckets"] = len(buckets)
            for bkey, (ceiling, params, idxs) in buckets.items():
                if _want_stop():
                    deferred.update(idxs)
                    continue
                be = self._bucket_engine(bkey, ceiling, params, meta)
                # wave scheduling: priority first (stable on
                # submission order), parked jobs requeue at the back —
                # a long job yields its lane and continues in a later
                # wave
                queue = deque(sorted(
                    idxs, key=lambda i: (-jobs[i].priority, i)))
                parked_runs: Dict[int, _JobRun] = {}
                while queue:
                    if _want_stop():
                        # drain: everything still queued (incl. parked
                        # stragglers, whose carries are already on
                        # disk when wave_state is set) is deferred —
                        # a later serve() resumes them mid-BFS
                        deferred.update(queue)
                        break
                    wave = [queue.popleft()
                            for _ in range(min(self.wave_cap,
                                               len(queue)))]
                    runs = []
                    for i in wave:
                        run = parked_runs.pop(i, None)
                        if run is None:
                            # fresh AND wave-state-restored jobs stamp
                            # their wait here (a restored run's _t0 is
                            # its restore time in THIS process — its
                            # pre-kill runtime is not recoverable,
                            # which the row's "resumed from wave
                            # state" status_reason flags for SLO
                            # consumers); parked runs keep the wait
                            # stamped at their first entry
                            run = restored.pop(i, None) \
                                or _JobRun(jobs[i])
                            slo.job_entered(run)
                        run.parked = False
                        runs.append(run)
                    answered = sum(1 for o in outcomes
                                   if o is not None)
                    slo.set_queue_depth(
                        len(jobs) - answered - len(runs))
                    be.run_wave(
                        runs, obs, meta, jobs_ctx=jobs_ctx,
                        verbose=verbose,
                        max_steps=self.wave_yield if queue else None,
                        wave_state=wave_state, slo_ctx=slo.snapshot,
                        stop=stop)
                    if any(run.parked for run in runs):
                        # one increment per wave that yielded, however
                        # many jobs parked in it (the key counts WAVES)
                        meta["parked_waves"] += 1
                    for i, run in zip(wave, runs):
                        if run.parked:
                            parked_runs[i] = run
                            queue.append(i)
                            continue
                        if run.fallback:
                            solo.append((i, "fallback",
                                         run.fallback_reason))
                            continue
                        job = jobs[i]
                        archives = ((run.parents, run.lanes,
                                     run.states, be.eng.labels,
                                     be.eng.lay)
                                    if job.store_states else None)
                        tracer = None
                        outcome = JobOutcome(job, "done", res=run.res,
                                             report=None,
                                             archives=archives)
                        if job.store_states:
                            tracer = outcome.trace
                        reason = ("resumed from wave state"
                                  if run.resumed else None)
                        outcome.report = _build_report(job, run.res,
                                                       "done",
                                                       reason=reason,
                                                       tracer=tracer)
                        outcome.report["wait_s"] = round(run.wait_s, 3)
                        outcome.report["service_s"] = round(
                            run.res.seconds, 3)
                        slo.job_done(run.wait_s, run.res.seconds)
                        outcomes[i] = outcome
        meta["fallback_jobs"] = sum(1 for _i, st, _r in solo
                                    if st == "fallback")
        for i, status, reason in solo:
            if _want_stop():
                # drain: don't start new solo engines — the job's
                # claimed file / submission survives for a later round
                deferred.add(i)
                meta["fallback_jobs"] -= int(status == "fallback")
                continue
            wait_s = time.perf_counter() - slo.t_submit
            outcomes[i] = _run_solo(jobs[i], obs, meta, status, reason,
                                    sym_canon=self.bucket_overrides
                                    .get("sym_canon", "auto"))
            res = outcomes[i].res
            outcomes[i].report["wait_s"] = round(wait_s, 3)
            outcomes[i].report["service_s"] = round(res.seconds, 3)
            slo.job_done(wait_s, res.seconds)
            jobs_ctx[jobs[i].label] = {"depth": int(res.depth),
                                       "distinct":
                                       int(res.distinct_states),
                                       "status": status}
        for i, src in dup_of.items():
            if outcomes[src] is None:
                # the duplicate's source was deferred by the drain —
                # the duplicate defers with it (same fingerprint, same
                # later answer)
                deferred.add(i)
                continue
            payload = outcomes[src].cache_payload()
            outcomes[i] = JobOutcome._from_cache(jobs[i], payload)
            outcomes[i].report["status_reason"] = \
                f"duplicate of job {jobs[src].label!r} in this batch"
            jobs_ctx[jobs[i].label] = {
                "depth": int(payload.get("depth", 0)),
                "distinct": int(payload.get("distinct_states", 0)),
                "status": "cache_hit"}
            slo.job_done(0.0, 0.0)
            _job_row(obs, outcomes[i])
        for i in sorted(deferred):
            ctx = jobs_ctx.setdefault(jobs[i].label,
                                      {"depth": 0, "distinct": 0})
            ctx["status"] = "deferred"
        meta["deferred_jobs"] = len(deferred)
        meta["drained"] = stopped
        slo.set_queue_depth(len(deferred))
        if self.exec_cache is not None:
            # honest executable-cache accounting into the summary, the
            # heartbeat SLO snapshot and (below) the ledger
            stats = self.exec_cache.stats()
            meta.update(stats)
            slo.snapshot["exec_cache"] = {
                k: v for k, v in stats.items()
                if not k.endswith("_reasons")}
        if jobs_ctx:
            # the final heartbeat carries the whole batch's job map +
            # SLO snapshot, incl. cache hits and solo jobs that never
            # rode a batched dispatch
            obs.set_jobs(jobs_ctx, slo=slo.snapshot)
        if obs.ledger is not None:
            # per-tenant (spec) rollups: one kind="tenant" record per
            # spec in the batch — the multi-tenant SLO summary a
            # dashboard (tools/watch.py --ledger) reads without
            # parsing job rows
            tenants: Dict[str, Dict] = {}
            for o in outcomes:
                if o is None:
                    continue
                t = tenants.setdefault(o.job.ir.name, dict(
                    kind="tenant", spec=o.job.ir.name, jobs=0,
                    cache_hits=0, fallbacks=0, violations=0,
                    distinct_states=0, wait_s=0.0, service_s=0.0))
                t["jobs"] += 1
                t["cache_hits"] += int(o.status == "cache_hit")
                t["fallbacks"] += int(o.status == "fallback")
                t["violations"] += int(o.report.get("violations", 0))
                t["distinct_states"] += int(
                    o.report.get("distinct_states", 0))
                t["wait_s"] += float(o.report.get("wait_s", 0.0))
                t["service_s"] += float(o.report.get("service_s", 0.0))
            for t in tenants.values():
                t["wait_s"] = round(t["wait_s"], 3)
                t["service_s"] = round(t["service_s"], 3)
                obs.ledger.record(t)
            if self.exec_cache is not None:
                obs.ledger.record({"kind": "exec_cache",
                                   **self.exec_cache.stats()})
        for outcome in outcomes:
            if outcome is None or outcome.status == "cache_hit":
                continue
            if cache is not None:
                cache.put(outcome.report["cache_key"],
                          outcome.cache_payload())
            if wave_state is not None:
                # the job is answered (and cached): retire its mid-BFS
                # carry so a future invocation never resumes stale
                # state (a DEFERRED job's carry deliberately survives)
                wave_state.drop(outcome.report["cache_key"])
            _job_row(obs, outcome)
        return BatchReport(outcomes, meta,
                           seconds=time.perf_counter() - t0)
