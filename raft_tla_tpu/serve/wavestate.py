"""Per-job wave-state store: preemptible batch waves (ROADMAP item-1
preemption core).

The batched serving layer's per-job state is already exactly a
resumable carry — frontier ring, visited table, gid cursor, depth gate
all ride the job axis (serve/batch).  This module persists one job's
slice of that carry (plus its harvest bookkeeping and trace archives)
at every wave boundary, so:

- a SIGKILLed ``cli batch`` run resumes: finished jobs answer from the
  result cache, stragglers continue mid-BFS from their persisted carry
  — bit-exact, because every wave step is a deterministic function of
  the carry (tools/chaos_smoke.py kills and resumes a real run in CI);
- a long job can YIELD its lane to a waiting higher-priority job
  (``--wave-yield``): its carry parks here (or in memory) and the job
  continues in a later wave.

Storage is one ``<cache_key>.wave.npz`` per job under the state
directory, written atomically with the checkpoint-chain integrity
sidecar (resil/ckpt_chain) — a torn file from a kill mid-write reads
as "no saved state" (the job simply restarts), never a crash.

Mesh portability (rounds 16-17): the saved arrays are ALWAYS host
numpy per-job slices, never sharded device buffers — saving strips
any mesh placement and restoring re-enters the carry through
``BucketEngine._stack``/``_place_carry``, which ``jax.device_put``s
it under whatever wave sharding the restoring process runs — the
1-D job mesh, the 2-D (jobs, state) grid, or a bare single device.
A ``--wave-mesh 2x2`` daemon therefore resumes a single-device (or
``4x1``) ``.wave.npz`` bit-exact and vice versa; nothing in this
file (or the on-disk format) is mesh-aware, which is exactly why
the restart matrix is portable across every mesh shape.
"""

from __future__ import annotations

import json
import os
import warnings
from typing import Dict, Optional, Tuple

import numpy as np

from ..resil.ckpt_chain import publish, verify


class WaveStateStore:
    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)

    def _file(self, key: str) -> str:
        return os.path.join(self.path, key + ".wave.npz")

    def save(self, key: str, arrays: Dict[str, np.ndarray],
             book: Dict):
        data = dict(arrays)
        data["book"] = np.array(json.dumps(book))
        tmp = self._file(key) + ".tmp.npz"
        np.savez(tmp, **data)
        publish(tmp, self._file(key), keep=1)

    def load(self, key: str) -> Optional[Tuple[Dict, Dict]]:
        """(arrays, book) or None — a missing, torn or foreign file is
        a miss (the job restarts from scratch), never an error."""
        path = self._file(key)
        if not os.path.exists(path):
            return None
        ok, why = verify(path)
        if ok is False:
            warnings.warn(
                f"{path}: wave state failed integrity validation "
                f"({why}) — job restarts from scratch", UserWarning,
                stacklevel=2)
            return None
        try:
            z = np.load(path, allow_pickle=False)
            book = json.loads(str(z["book"]))
            arrays = {nm: np.asarray(z[nm]) for nm in z.files
                      if nm != "book"}
            z.close()
        except Exception:
            return None
        if book.get("cache_key") != key:
            return None
        return arrays, book

    def drop(self, key: str):
        for suffix in ("", ".sum"):
            try:
                os.remove(self._file(key) + suffix)
            except OSError:
                pass
