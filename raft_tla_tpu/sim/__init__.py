"""Random-walk simulation engine (TLC ``-simulate`` analogue).

``SimEngine`` runs W vmapped walkers on one device; the pmapped fleet
lives in parallel/sim_mesh.ShardedSimEngine.  See sim/walker.py for the
design notes.
"""

from .walker import SimEngine, SimResult, WalkerHit  # noqa: F401
