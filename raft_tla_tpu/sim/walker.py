"""TPU-native random-walk simulation engine: TLC ``-simulate``, vmapped.

BASELINE config #5-class spaces (Server=5, MaxTerm=4, MaxLogLen=4 with
scenario-property targets) sit orders of magnitude past the exhaustive
BFS stack even with the host-partitioned visited table, and the repo
had no analogue of TLC's ``-simulate`` mode.  This engine runs W
independent random walkers as ONE device program:

- per-walker ``jax.random`` key streams, keyed by GLOBAL walker id so a
  fixed ``seed`` replays bit-identical trajectories across runs AND
  across ``--walkers`` shardings (walker w's stream never depends on W
  or on the mesh shape — tests/test_sim.py pins this);
- uniform enabled-action sampling over the existing guard grid
  (engine/expand.guards_T + ops/kernels.select_enabled): the walker
  draws u ~ U[0, n_enabled) and takes the u-th enabled (action, server,
  param) lane — TLC ``-simulate``'s uniform successor choice on the
  same operator surface;
- per-walker step fusion (expand.Expander.step_lanes): one kernel
  application per FAMILY per walker instead of the full [B, A]
  candidate materialization;
- in-device invariant + scenario-predicate evaluation on every sampled
  successor (ops/vpredicates) — pruned states are checked then
  discarded, TLC's CONSTRAINT semantics;
- on-device trajectory recording: each walker's root-to-here lane ids
  live in a [traj_cap, W] buffer, so a scenario-hitting walker is
  decoded host-side into the same witness-trace format ``cli.py trace``
  emits (and into ``--seed-trace`` files — simulation FEEDS punctuated
  exhaustive search);
- a best-effort novelty Bloom filter over the fingerprints the
  exhaustive engines dedup on (engine/fingerprint.bloom_positions)
  reporting estimated distinct-state coverage.

Restart policies (the knob that decides what the fleet can reach):

``tlc``        — exact TLC ``-simulate`` shape: one uniform draw per
                 step; a pruned (CONSTRAINT-violating) successor, a
                 deadlock, or the depth bound abandons the walk and
                 restarts from the root.  Measured on config #5 this
                 finds nothing: under the Clean-start constraints the
                 mean walk dies in ~1.5 steps.
``punctuated`` — (default) two refinements, both preserving the
                 uniform per-step choice:
                 (a) prune-resampling: a pruned successor is checked,
                     then its lane is masked out and the walker redraws
                     uniformly among the REMAINING enabled lanes
                     (rejection sampling = uniform over the extendable
                     subset; measured 5 hits / 76 walks vs 0 / 209k
                     walks on a small membership scenario);
                 (b) per-walker progress bases: a walker restarts not
                     from the root but from its own best state on a
                     monotone scenario ladder (leader elected <
                     membership changes appended < latest-ConfigEntry
                     replication count), the in-engine analogue of the
                     spec's punctuated-search prefix pins
                     (raft.tla:1198-1234).  Measured on config #5 this
                     turns MembershipChangeCommits from unreachable
                     into a ~30k-step find.

The walker loop is a single ``lax.while_loop`` program running hundreds
of steps per dispatch — the persistent-kernel level-loop shape the
config #3/#4 dispatch-floor items call for: per dispatch the host syncs
one small stats vector, nothing else.

Differential anchor: models/explore.random_walk is the plain-Python
oracle twin; tests/test_sim.py replays engine trajectories through the
oracle transition relation step-for-step and pins the per-step enabled
counts (the sampling surface) against the oracle's successor counts.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..config import ModelConfig
from ..ops.kernels import select_enabled
from ..spec import spec_of
from ..engine.expand import Expander
from ..engine.bfs import enable_persistent_compilation_cache
from ..engine.fingerprint import (Fingerprinter, bloom_estimate,
                                  bloom_positions, resolve_sym_canon)

BLOOM_K = 2
# under FORCED min-over-perms (--sym-canon minperm), symmetry groups
# past this size pay more in per-step P-fold hashing than the novelty
# estimate is worth (the same threshold
# fingerprint.supports_incremental uses); the Bloom falls back to
# identity-permutation fingerprints, honestly labeled in the result.
# The orbit-sort canonicalizer (--sym-canon sort/auto, round 15)
# hashes ONE relabeling per state, so it keeps the Bloom canonical at
# ANY group size — this cap only gates the minperm path.
_BLOOM_CANONICAL_MAX_PERMS = 24


@dataclass
class WalkerHit:
    """One walker's scenario / invariant hit, decoded host-side."""
    invariant: str
    walker: int                  # global walker id
    depth: int                   # steps from the root (witness length)
    lanes: List[int]             # flat lane ids root -> hit state
    # (label, oracle-state) chain — the active spec's state type
    trace: List[Tuple] = field(default_factory=list)
    state_arrs: Optional[Dict[str, np.ndarray]] = None
    hist: Optional[object] = None


@dataclass
class SimResult:
    walkers: int
    steps_dispatched: int        # fleet-synchronous loop iterations
    walker_steps: int            # transitions taken (Σ accepted steps)
    sampled_steps: int           # successors sampled (incl. pruned)
    restarts: int
    deadlocks: int
    promotions: int              # progress-base advances (punctuated)
    seconds: float = 0.0
    hits: List[WalkerHit] = field(default_factory=list)
    bloom_bits_set: int = 0
    bloom_m_bits: int = 0
    bloom_saturated: bool = False
    bloom_canonical: bool = True  # False = identity-perm fingerprints
    est_distinct_states: float = 0.0

    @property
    def walker_steps_per_sec(self) -> float:
        return self.walker_steps / max(self.seconds, 1e-9)


# stats vector layout (int32 on device)
(ST_STEPS, ST_RESTARTS, ST_DEADLOCKS, ST_ITERS, ST_HIT, ST_SAMPLED,
 ST_PROMOS, ST_LEN) = range(8)


def dispatch_counters(stats2d: np.ndarray, walkers: int):
    """Per-dispatch ledger counters off the raw [n_shards, ST_LEN]
    stats matrix — the single stats→names mapping the sim ledger
    records use (key set pinned as obs.metrics.SIM_DISPATCH_KEYS, the
    subset of the SimResult counters knowable without a bloom fetch).
    Both sim engines (single-device and the pmapped fleet) call it, so
    their ledger schemas cannot drift."""
    return {
        "walkers": int(walkers),
        "steps_dispatched": int(stats2d[:, ST_ITERS].max()),
        "walker_steps": int(stats2d[:, ST_STEPS].sum()),
        "sampled_steps": int(stats2d[:, ST_SAMPLED].sum()),
        "restarts": int(stats2d[:, ST_RESTARTS].sum()),
        "deadlocks": int(stats2d[:, ST_DEADLOCKS].sum()),
        "promotions": int(stats2d[:, ST_PROMOS].sum()),
        "hits": int(stats2d[:, ST_HIT].sum()),
    }

class SimEngine:
    """W-walker random-walk explorer bound to one ModelConfig.

    walkers   — fleet width W (one vmapped lane per walker).
    max_depth — per-segment step budget: a walk restarts (to the root,
                or to its progress base under ``punctuated``) after
                this many steps beyond its base.
    traj_cap  — on-device trajectory buffer rows ([traj_cap, W] int32
                lanes from the ROOT); bounds the total witness depth.
    seed      — base PRNG seed; walker w uses fold_in(PRNGKey(seed), w)
                with w the GLOBAL walker id (see wid_base).
    policy    — 'punctuated' (default) or 'tlc' (see module docstring).
    bloom_bits— log2 of the novelty Bloom filter size in bits.
    wid_base  — global id of this engine's walker 0 (mesh shards pass
                d * walkers so streams are sharding-invariant).
    """

    _MAX_TRIES = 8               # prune-resampling rounds per step

    def __init__(self, cfg: ModelConfig, walkers: int = 256,
                 max_depth: int = 48, seed: int = 0,
                 policy: str = "punctuated",
                 traj_cap: Optional[int] = None,
                 bloom_bits: int = 22, wid_base: int = 0,
                 guard_matmul: bool = True,
                 delta_matmul: bool = True,
                 sym_canon: str = "auto"):
        enable_persistent_compilation_cache()
        if policy not in ("punctuated", "tlc"):
            raise ValueError(f"unknown restart policy {policy!r}")
        self.cfg = cfg
        self.W = int(walkers)
        self.budget = max(2, int(max_depth))
        self.R = int(traj_cap) if traj_cap else max(4 * self.budget, 64)
        self.seed = int(seed)
        self.policy = policy
        self.bloom_bits = int(bloom_bits)
        self.wid_base = int(wid_base)
        self.ir = spec_of(cfg)
        self.lay = self.ir.make_layout(cfg)
        self.kern = self.ir.make_kernels(self.lay)
        # the sim engine reuses select_enabled over the SAME guard grid
        # the exhaustive engines dispatch on, so the MXU guard-matrix
        # path (engine/expand docstring) drops in here unchanged:
        # guards_T becomes the int8 matmul, step_lanes' per-walker
        # param selection the one-hot einsum — trajectories are
        # bit-identical either way (tests/test_guard_matmul.py)
        # the delta-matmul successor path drops into step_lanes the
        # same way: affine-family walkers step through ONE group delta
        # matmul; trajectories are bit-identical either way
        # (tests/test_delta_matmul.py)
        self.guard_matmul = bool(guard_matmul)
        self.delta_matmul = bool(delta_matmul)
        self.expander = Expander(cfg, guard_matmul=self.guard_matmul,
                                 delta_matmul=self.delta_matmul)
        fp_cfg = cfg
        self.bloom_canonical = True
        mode = resolve_sym_canon(cfg, sym_canon)
        if cfg.symmetry and mode == "minperm":
            if len(self.ir.symmetry_perms(cfg)) > \
                    _BLOOM_CANONICAL_MAX_PERMS:
                import warnings
                warnings.warn(
                    f"--sym-canon minperm with "
                    f"{len(self.ir.symmetry_perms(cfg))} perms: the "
                    "novelty Bloom falls back to identity-permutation "
                    "fingerprints (bloom_canonical=false) — use "
                    "--sym-canon sort (or auto) to keep it canonical",
                    stacklevel=2)
                fp_cfg = cfg.with_(symmetry=False)
                self.bloom_canonical = False
        self.fpr = Fingerprinter(fp_cfg, sym_canon=mode)
        self.preds = self.ir.make_predicates(self.lay)
        # punctuated-restart progress ladder: a SpecIR hook (the raft
        # scenario ladder lives in spec/raft_ir.sim_progress); a spec
        # without one degrades punctuated to budget-only restarts
        self._progress_fn = (self.ir.sim_progress(self.kern, self.lay)
                             if self.ir.sim_progress else None)
        self.inv_names = list(cfg.invariants)
        self.con_names = list(cfg.constraints)
        self.act_names = list(cfg.action_constraints)
        self.labels = self.expander.lane_labels()
        self.A = self.expander.n_lanes
        self._root = self.ir.encode(self.lay,
                                    *self.ir.init_state(cfg))
        self._dispatch = jax.jit(self._dispatch_impl, donate_argnums=0,
                                 static_argnums=(1, 2))

    # ------------------------------------------------------------------
    # carry construction
    # ------------------------------------------------------------------

    def fresh_carry(self) -> Dict:
        W = self.W
        rootT = {k: jnp.asarray(np.repeat(
            np.asarray(v)[..., None], W, axis=-1))
            for k, v in self._root.items()}
        base = jax.random.PRNGKey(self.seed)
        wids = jnp.arange(self.wid_base, self.wid_base + W)
        keys = jax.vmap(lambda w: jax.random.fold_in(base, w))(wids)
        return dict(
            sv=rootT,                                   # [..., W] int32
            depth=jnp.zeros((W,), jnp.int32),           # from the ROOT
            key=keys,                                   # [W, 2] u32
            traj=jnp.full((self.R, W), -1, jnp.int32),
            # distinct buffers from sv: the dispatch donates the carry,
            # and aliased leaves would be donated twice
            base={k: v.copy() for k, v in rootT.items()},
            base_depth=jnp.zeros((W,), jnp.int32),
            score=jnp.zeros((W,), jnp.int32),
            hit=jnp.zeros((W,), bool),
            hit_inv=jnp.full((W,), -1, jnp.int32),
            hit_depth=jnp.full((W,), -1, jnp.int32),
            bloom=jnp.zeros((1 << self.bloom_bits,), bool),
            stats=jnp.zeros((ST_LEN,), jnp.int32),
        )

    # ------------------------------------------------------------------
    # predicates on batch-last rows (the engines' batch-minor shape)
    # ------------------------------------------------------------------

    def _phase2_T(self, svT):
        def one(sv):
            der = self.kern.derived(sv)
            inv = jnp.stack([self.preds.invariant_fn(nm)(sv, der)
                             for nm in self.inv_names]) \
                if self.inv_names else jnp.ones((0,), bool)
            con = jnp.bool_(True)
            for nm in self.con_names:
                con = con & self.preds.constraint_fn(nm)(sv, der)
            return inv, con
        return jax.vmap(one, in_axes=-1, out_axes=-1)(svT)

    def _progress_T(self, svT) -> jnp.ndarray:
        """Monotone scenario-ladder score [W] (the SpecIR sim_progress
        hook — raft: leader elected < membership changes appended <
        ConfigEntry replication; paxos: phase ladder).  Drives the
        ``punctuated`` restart bases; never consulted under ``tlc``.
        A spec without the hook scores every state 0 (punctuated
        degrades to budget-only restarts from the root)."""
        if self._progress_fn is None:
            return jnp.zeros((self.W,), jnp.int32)
        return self._progress_fn(svT)

    # ------------------------------------------------------------------
    # the fused step (shared by the single-device dispatch and the
    # pmapped fleet in parallel/sim_mesh.py)
    # ------------------------------------------------------------------

    def step(self, st: Dict) -> Dict:
        """One synchronous step of every walker; pure (jit/pmap-safe)."""
        W, A = self.W, self.A
        svT = st["sv"]
        frozen = st["hit"]
        derT = self.expander.derived_batch_T(svT)
        ok0 = self.expander.guards_T(svT, derT)             # [W, A]
        n_tries = self._MAX_TRIES if self.policy == "punctuated" else 1
        n_inv = len(self.inv_names)

        # ---- rejection-sampling rounds: draw a lane uniformly from
        # the remaining enabled set; a pruned successor is checked,
        # masked out, and redrawn (punctuated) or abandons the walk
        # (tlc).  All walkers run rounds in lockstep; each round costs
        # one fused step_lanes + predicate pass.
        def rcond(c):
            return (~c["done"]).any() & (c["tries"] < n_tries)

        def rbody(c):
            okm = c["okm"]
            n_en = okm.sum(axis=1, dtype=jnp.int32)
            active = ~c["done"] & (n_en > 0)
            splits = jax.vmap(jax.random.split)(c["key"])
            # a walker's key advances ONLY on its own draws — otherwise
            # the fleet-global resampling round count would leak into
            # every walker's stream and trajectories would depend on
            # the fleet width (tests pin sharding invariance)
            keys2 = jnp.where(active[:, None], splits[:, 0], c["key"])
            subs = splits[:, 1]
            u = jax.vmap(lambda k, n: jax.random.randint(
                k, (), 0, jnp.maximum(n, 1)))(subs, n_en)
            lane = jax.vmap(select_enabled)(okm, u)
            lane = jnp.where(active, lane, -1)
            cand = self.expander.step_lanes(svT, derT, lane)
            inv, con = self._phase2_T(cand)
            if n_inv:
                inv = inv | ~active[None]
                hitrow = ~inv.all(axis=0)
                hinv = jnp.argmax(~inv, axis=0).astype(jnp.int32)
            else:
                hitrow = jnp.zeros((W,), bool)
                hinv = jnp.full((W,), -1, jnp.int32)
            accept = active & con & ~hitrow
            reject = active & ~con & ~hitrow
            # mask the rejected lane out of the walker's enabled set
            li = jnp.clip(lane, 0, A - 1)
            okm = okm.at[jnp.arange(W), li].set(
                jnp.where(reject, False, okm[jnp.arange(W), li]))
            take = (accept | hitrow) & ~c["acc"]
            out = {k: jnp.where(take, cand[k], c["cand"][k])
                   for k in cand}
            lane_out = jnp.where(take, lane, c["lane"])
            return dict(
                okm=okm, key=keys2, cand=out, lane=lane_out,
                acc=c["acc"] | accept,
                hitrow=c["hitrow"] | hitrow,
                hinv=jnp.where(hitrow & (c["hinv"] < 0), hinv,
                               c["hinv"]),
                sampled=c["sampled"] + active.sum(dtype=jnp.int32),
                done=c["done"] | accept | hitrow | (n_en == 0),
                tries=c["tries"] + 1)

        c0 = dict(okm=ok0 & ~frozen[:, None], key=st["key"],
                  cand={k: v for k, v in svT.items()},
                  lane=jnp.full((W,), -1, jnp.int32),
                  acc=jnp.zeros((W,), bool),
                  hitrow=jnp.zeros((W,), bool),
                  hinv=jnp.full((W,), -1, jnp.int32),
                  sampled=jnp.int32(0),
                  done=frozen | (ok0.sum(axis=1) == 0),
                  tries=jnp.int32(0))
        c = lax.while_loop(rcond, rbody, c0)
        cand, lane = c["cand"], c["lane"]
        accepted = c["acc"]
        hit_now = c["hitrow"] & ~frozen
        took = accepted | hit_now                  # a lane was recorded
        deadlock = ~frozen & (ok0.sum(axis=1) == 0)
        # stuck = every enabled lane tried and pruned (or tries blown)
        stuck = ~frozen & ~took & ~deadlock

        # ---- trajectory record at the pre-step depth
        traj = st["traj"].at[st["depth"], jnp.arange(W)].set(
            jnp.where(took, lane, st["traj"][st["depth"],
                                            jnp.arange(W)]))

        # ---- novelty Bloom over the accepted rows' fingerprints
        fp = self.fpr.fingerprint_batch_T(cand)             # [T, W]
        pos = bloom_positions(fp, self.bloom_bits, BLOOM_K)  # [k, W]
        upd = jnp.where(accepted[None], pos,
                        jnp.int32(1 << self.bloom_bits)).reshape(-1)
        bloom = st["bloom"].at[upd].set(True, mode="drop")

        depth2 = jnp.where(took, st["depth"] + 1, st["depth"])
        hit_all = st["hit"] | hit_now

        # ---- punctuated progress bases
        if self.policy == "punctuated":
            score2 = self._progress_T(cand)
            promote = accepted & (score2 > st["score"]) & \
                (depth2 <= self.R - self.budget)
            base = {k: jnp.where(promote, cand[k], st["base"][k])
                    for k in cand}
            base_depth = jnp.where(promote, depth2, st["base_depth"])
            score = jnp.where(promote, score2, st["score"])
        else:
            promote = jnp.zeros((W,), bool)
            base, base_depth, score = (st["base"], st["base_depth"],
                                       st["score"])

        # ---- restart policy: segment budget blown, stuck, deadlock
        over = depth2 - base_depth >= self.budget
        restart = ~frozen & ~hit_now & \
            (deadlock | stuck | (accepted & over & ~promote))
        # stuck AT the base: demote the base to the root so the walker
        # cannot spin forever on an unextendable base
        demote = (stuck | deadlock) & (st["depth"] == base_depth)
        rootT = {k: jnp.asarray(np.asarray(v))[..., None]
                 for k, v in self._root.items()}
        base = {k: jnp.where(demote, rootT[k], base[k]) for k in base}
        base_depth = jnp.where(demote, 0, base_depth)
        score = jnp.where(demote, 0, score)

        sv_next = {k: jnp.where(restart, base[k],
                                jnp.where(accepted, cand[k], svT[k]))
                   for k in svT}
        depth3 = jnp.where(restart, base_depth, depth2)

        stats = st["stats"]
        stats = stats.at[ST_STEPS].add(accepted.sum(dtype=jnp.int32))
        stats = stats.at[ST_SAMPLED].add(c["sampled"])
        stats = stats.at[ST_RESTARTS].add(restart.sum(dtype=jnp.int32))
        stats = stats.at[ST_DEADLOCKS].add(
            deadlock.sum(dtype=jnp.int32))
        stats = stats.at[ST_PROMOS].add(promote.sum(dtype=jnp.int32))
        stats = stats.at[ST_ITERS].add(1)
        stats = stats.at[ST_HIT].set(hit_all.any().astype(jnp.int32))
        return dict(st, sv=sv_next, depth=depth3, key=c["key"],
                    traj=traj, base=base, base_depth=base_depth,
                    score=score, hit=hit_all,
                    hit_inv=jnp.where(hit_now & (st["hit_inv"] < 0),
                                      c["hinv"], st["hit_inv"]),
                    hit_depth=jnp.where(hit_now & (st["hit_depth"] < 0),
                                        depth2, st["hit_depth"]),
                    bloom=bloom, stats=stats)

    def _dispatch_impl(self, st: Dict, steps: int,
                       stop_on_hit: bool = True) -> Dict:
        """``steps`` walker steps in ONE device program (lax.while_loop
        — the persistent-kernel pattern: the host syncs only the stats
        vector per dispatch), exiting early on the first hit when
        stop_on_hit (hit walkers freeze either way)."""
        start = st["stats"][ST_ITERS]

        def cond(st):
            go = st["stats"][ST_ITERS] - start < steps
            if stop_on_hit:
                go = go & (st["stats"][ST_HIT] == 0)
            return go

        return lax.while_loop(cond, self.step, st)

    # ------------------------------------------------------------------
    # driver
    # ------------------------------------------------------------------

    def run(self, steps: int, steps_per_dispatch: int = 256,
            stop_on_hit: bool = True, verbose: bool = False,
            obs=None) -> SimResult:
        """Walk for up to ``steps`` synchronous fleet steps (early exit
        on the first scenario/invariant hit when stop_on_hit).

        obs — an ``obs.Obs`` bundle: one ledger record + heartbeat
        rewrite per device dispatch (the heartbeat's ``depth`` is the
        fleet-synchronous iteration count — a random walk has no BFS
        depth)."""
        from ..obs import NULL_OBS
        obs = obs if obs is not None else NULL_OBS
        t0 = time.perf_counter()
        # the step loop checks sampled SUCCESSORS; the root itself must
        # be checked once up front (a safety-invariant target can be
        # violated at depth 0 — check/trace report it there too)
        root_hit = self._check_root()
        if root_hit is not None and stop_on_hit:
            res = self._harvest(self.fresh_carry(),
                                time.perf_counter() - t0)
            res.hits.insert(0, root_hit)
            return res
        st = self.fresh_carry()
        done = 0
        while done < steps:
            k = min(steps_per_dispatch, steps - done)
            with obs.span("sim_dispatch"):
                st = self._dispatch(st, int(k), bool(stop_on_hit))
                stats = np.asarray(st["stats"])   # the ONE per-dispatch
                # sync
            done = int(stats[ST_ITERS])
            if obs.enabled:
                # light per-dispatch counters straight off the stats
                # vector (no bloom fetch mid-run); key set pinned by
                # obs.metrics.SIM_DISPATCH_KEYS
                obs.dispatch(
                    kind="sim", depth=done, frontier=self.W,
                    states=int(stats[ST_STEPS]),
                    metrics=dispatch_counters(stats[None], self.W))
            if verbose:
                print(f"sim: {done} iters, {int(stats[ST_STEPS])} "
                      f"walker-steps, {int(stats[ST_RESTARTS])} "
                      f"restarts, {int(stats[ST_PROMOS])} promotions",
                      flush=True)
            if stop_on_hit and stats[ST_HIT]:
                break
        res = self._harvest(st, time.perf_counter() - t0)
        if root_hit is not None:
            res.hits.insert(0, root_hit)
        return res

    def _check_root(self) -> Optional[WalkerHit]:
        """Evaluate the target invariants on the root state; a depth-0
        violation decodes like any other hit (empty lane list)."""
        if not self.inv_names:
            return None
        rootT = {k: jnp.asarray(np.asarray(v))[..., None]
                 for k, v in self._root.items()}
        inv, _con = self._phase2_T(rootT)
        inv = np.asarray(inv)[:, 0]
        if inv.all():
            return None
        return WalkerHit(
            invariant=self.inv_names[int(np.argmax(~inv))],
            walker=self.wid_base, depth=0, lanes=[])

    def build_result(self, stats2d: np.ndarray, union_bits: int,
                     walkers: int, seconds: float) -> SimResult:
        """Shared stats->SimResult assembly (this engine and the
        pmapped fleet): stats2d is [n_shards, ST_LEN]; iteration count
        is the max across shards (a hit exits one shard's loop early),
        everything else sums."""
        m = self.bloom_bits
        return SimResult(
            walkers=walkers,
            steps_dispatched=int(stats2d[:, ST_ITERS].max()),
            walker_steps=int(stats2d[:, ST_STEPS].sum()),
            sampled_steps=int(stats2d[:, ST_SAMPLED].sum()),
            restarts=int(stats2d[:, ST_RESTARTS].sum()),
            deadlocks=int(stats2d[:, ST_DEADLOCKS].sum()),
            promotions=int(stats2d[:, ST_PROMOS].sum()),
            seconds=seconds,
            bloom_bits_set=union_bits, bloom_m_bits=m,
            bloom_saturated=union_bits >= (1 << m) - 1,
            bloom_canonical=self.bloom_canonical,
            est_distinct_states=bloom_estimate(union_bits, m, BLOOM_K))

    def harvest_hits(self, res: SimResult, hit, traj, hdep, hinv,
                     wid_base: int):
        """Decode one shard's hit flags into WalkerHit entries (traj is
        [R, W] for that shard; global ids offset by wid_base)."""
        for w in np.nonzero(hit)[0]:
            d = int(hdep[w])
            res.hits.append(WalkerHit(
                invariant=self.inv_names[int(hinv[w])]
                if 0 <= int(hinv[w]) < len(self.inv_names) else "?",
                walker=wid_base + int(w), depth=d,
                lanes=[int(x) for x in traj[:d, w]]))

    def _harvest(self, st: Dict, seconds: float) -> SimResult:
        stats = np.asarray(st["stats"])
        bits = int(np.asarray(st["bloom"]).sum())
        res = self.build_result(stats[None], bits, self.W, seconds)
        hit = np.asarray(st["hit"])
        if hit.any():
            self.harvest_hits(res, hit, np.asarray(st["traj"]),
                              np.asarray(st["hit_depth"]),
                              np.asarray(st["hit_inv"]), self.wid_base)
        return res

    # ------------------------------------------------------------------
    # host-side witness decoding: replay the recorded lanes from the
    # root through the single-state expander (bit-identical to the
    # device step — same kernels, same params), producing the
    # (label, State) chain cli.py trace prints and the exact SoA arrays
    # --emit-seed needs.
    # ------------------------------------------------------------------

    def decode_hit(self, h: WalkerHit) -> WalkerHit:
        arrs = {k: np.asarray(v) for k, v in self._root.items()}
        chain: List[Tuple] = [
            ("Init", self.ir.decode(self.lay, arrs)[0])]
        for lane in h.lanes:
            enabled = self.expander.expand_one(arrs)
            match = [sv2 for (lbl, sv2) in enabled
                     if lbl == self.labels[lane]]
            if not match:
                raise RuntimeError(
                    f"sim replay divergence: lane {lane} "
                    f"({self.labels[lane]}) not enabled at depth "
                    f"{len(chain) - 1}")
            arrs = match[0]
            chain.append((self.labels[lane],
                          self.ir.decode(self.lay, arrs)[0]))
        h.trace = chain
        h.state_arrs = arrs
        h.hist = self.ir.decode(self.lay, arrs)[1]
        return h
