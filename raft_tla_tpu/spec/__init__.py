"""Spec-agnostic frontend: the ``SpecIR`` contract (ROADMAP item 2).

The five engines (bfs / spill / mesh / spill_mesh / sim) never execute
TLA+; they consume a *compiled operator surface*:

  * an Init-state constructor and a bit-packed SoA layout + codec
    (encode / decode / narrow / widen),
  * a registry of vmapped action *families* — each with its parameter
    grid, its successor kernel, AND its guard-algebra declaration (the
    signed-weight/threshold row the int8 guard-matmul of PR 8 compiles;
    a family without one fails loudly at Expander construction),
  * per-family enabled-lane density caps (buffer sizing),
  * invariant / constraint / scenario-property registries (device
    predicates) and their plain-Python oracle twins,
  * a symmetry-canonical fingerprinter and the oracle's symmetry group,
  * the oracle explorer the differential harness pins everything to.

``SpecIR`` bundles exactly that surface.  Everything Raft-specific that
used to be reached via direct ``models.raft`` / ``ops.*`` imports now
routes through the IR handle (``spec_of(cfg)``), so a second spec is a
data change, not an engine fork — ``spec/paxos`` is the proof tenant
(single-decree + multi-instance Paxos; PAPERS.md: arXiv:2004.05074
argues the two specs are near-isomorphic, arXiv:1905.10786 gives the
action mapping).

Config dispatch: every model config object carries a ``spec`` class
attribute naming its IR (``ModelConfig.spec == "raft"``,
``PaxosConfig.spec == "paxos"``).  It is a class attribute, not a
dataclass field, so ``repr(cfg)`` — the checkpoint-compat key — is
unchanged for every existing Raft checkpoint; the spec name is
additionally stamped into checkpoint meta / ``--stats-json`` / the obs
ledger, and resume refuses on a spec mismatch before the cfg repr is
even compared.

The SoA *ctr* contract is shared across specs: every spec's encoded
state carries a ``ctr`` int32[NCTR] lane vector with ``C_GLOBLEN``
(history length) and ``C_OVERFLOW`` (un-representability fault) at the
indices below — the engines' harvest loops and depth gates read only
these two, so they stay spec-blind.  (ops/codec re-exports them for the
historical import path.)
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Callable, Dict, Mapping, Optional, Tuple

# ---------------------------------------------------------------------------
# The shared ctr-lane contract (see module docstring).  ops/codec.py
# aliases these; spec/paxos/codec.py builds its ctr vector against them.
# ---------------------------------------------------------------------------

NCTR = 8
C_NLEADERS, C_NREQ, C_NTRIED, C_NMC, C_GLOBLEN, C_OVERFLOW = range(6)


@dataclass(frozen=True)
class SpecIR:
    """One spec's compiled operator surface (see module docstring).

    All members are plain callables/tables so an IR is constructed
    without importing JAX-heavy modules until the engines actually use
    it; the registry below builds each IR lazily and caches it.
    """

    name: str
    version: int                      # bumped on IR-structure changes

    # ---- packed layout + codec ----------------------------------------
    make_layout: Callable             # cfg -> layout object
    init_state: Callable              # cfg -> (sv, hist) oracle pair
    encode: Callable                  # (lay, sv, hist) -> SoA dict
    decode: Callable                  # (lay, arrs) -> (sv, hist)
    narrow: Callable                  # (lay, arrs) -> storage dtypes
    widen: Callable                   # arrs -> kernel dtypes
    view_keys: Tuple[str, ...]        # state-identity arrays
    nonview_keys: Tuple[str, ...]     # history/feature arrays
    state_to_obj: Callable            # (sv, hist) -> JSON-able dict
    state_from_obj: Callable          # dict -> (sv, hist)

    # ---- kernels + families -------------------------------------------
    make_kernels: Callable            # lay -> kernels object (.derived,
    #                                   .guard_features, .guard_feature_offsets)
    build_families: Callable          # lay -> List[engine.expand.Family]
    family_density: Mapping[str, int]  # per-family enabled-lane density

    # ---- predicates ----------------------------------------------------
    make_predicates: Callable         # lay -> device predicate object
    #                                   (.invariant_fn/.constraint_fn/.action_fn)
    scenario_properties: Tuple[str, ...]
    known_invariants: frozenset
    known_constraints: frozenset
    known_action_constraints: frozenset
    # invariants/constraints whose ORACLE form scans history records a
    # device-emitted seed cannot carry (cli seed-trace guard)
    glob_dependent: frozenset = frozenset()

    # ---- identity ------------------------------------------------------
    # make_fingerprinter receives the RESOLVED sym_canon mode ("sort" |
    # "minperm") from engine/fingerprint.Fingerprinter (round 15).
    make_fingerprinter: Callable = None   # (cfg, sym_canon) -> fingerprinter
    symmetry_perms: Callable = None       # cfg -> [perm tuples]
    # orbit-sort signature kernel (round 15): (fingerprinter, svT,
    # prep) -> u32[S, B] permutation-EQUIVARIANT per-server signature
    # (sig(relabel(s,σ))[σ(i)] == sig(s)[i]); svT is batch-last, prep
    # is the fingerprinter's own spec-defined precompute object.
    # Signature strength is performance-only — the certificate +
    # min-over-perms fallback in the fingerprinter pins correctness.
    server_signature: Callable = None

    # ---- oracle twins (the differential anchor) ------------------------
    oracle_explore: Callable = None       # explore(cfg, **kw)
    oracle_successors: Callable = None    # (sv, h, cfg) -> [(lbl, sv, h)]
    oracle_walk_key: Callable = None      # sv -> hashable identity key

    # ---- optional hooks ------------------------------------------------
    prefix_pin_seeds: Optional[Callable] = None   # cfg -> (seeds, interiors)
    sim_progress: Optional[Callable] = None       # (kern, lay) -> (svT -> [W])
    default_config: Optional[Callable] = None     # () -> a small cfg
    # serving-layer bucket ceiling (serve/batch): cfg -> (ceiling cfg,
    # bucket param dict).  Jobs whose ceiling cfg + params match batch
    # into ONE job-vmapped device program; the ceiling is the config
    # the bucket engine compiles.  Round 13: the ceiling may now be
    # STRICTLY ABOVE the job's config — value-like bounds (MaxTerm
    # etc., paxos ballots/values/instances) pad up to a rung ladder
    # (``pad_rung``) so heterogeneous small configs share one
    # AOT-compiled program — provided the spec also supplies
    # ``serve_runtime`` below to restore the job's exact semantics.
    serve_bucket: Optional[Callable] = None
    # (expander, job cfg) -> the job's runtime-thresholds data under
    # the bucket's CEILING expander: {"thr": int32 [A] guard
    # thresholds, "mask": bool [A] family lane mask, "bounds": int32
    # [NB] search-bounds vector} (host numpy; serve/batch stacks a
    # leading [J] axis and the batched burst vmaps them as device
    # data).  The contract that makes a padded ceiling EXACT: masked
    # lanes never generate candidates, so the surviving stream is the
    # job's own enumeration order, and every Bounded*-style constraint
    # reads the job's own bound from the vector.  None = ceilings are
    # always exact for this spec (the pre-round-13 contract).
    serve_runtime: Optional[Callable] = None

    @property
    def all_keys(self) -> Tuple[str, ...]:
        return self.view_keys + self.nonview_keys

    def fingerprint(self) -> str:
        """Short stable hash of the IR *structure* (not of any run
        config): stamped into ``--stats-json``, the obs ledger and
        checkpoint meta so a resumed/compared run records which
        frontend compiled it."""
        desc = json.dumps([
            self.name, self.version,
            sorted((k, int(v)) for k, v in
                   dict(self.family_density).items()),
            list(self.scenario_properties),
            sorted(self.known_invariants),
            sorted(self.known_constraints),
            sorted(self.known_action_constraints),
            list(self.view_keys), list(self.nonview_keys),
        ], separators=(",", ":"))
        return hashlib.sha256(desc.encode()).hexdigest()[:12]


def pad_rung(v: int, floor: int = 1) -> int:
    """The serving ceiling ladder: round a value-like bound up to the
    next power of two, never below ``floor``.  Shared by every spec's
    ``serve_bucket`` so two tenants' independently-computed ceilings
    agree whenever their bounds share a rung — that agreement IS the
    bucket hit.  Coarser rungs (a higher floor) = more sharing but
    bigger padded layouts; powers of two keep the worst-case pad at
    2x above the floor.  Each spec picks its floor by what padding
    costs it: raft bounds only widen bit-packing fields (floor 4 —
    the whole small-serving range shares one rung), while paxos
    ballots/values/instances multiply the message universe and the
    lane grid (floor 2)."""
    v = max(int(v), int(floor))
    if v <= 1:
        return max(v, 0)
    return 1 << (v - 1).bit_length()


# ---------------------------------------------------------------------------
# Registry.  Builders are lazy (each imports its spec's modules on first
# use) and cached; unknown names fail with the known-spec list — the
# error every CLI/engine entry point surfaces verbatim.
# ---------------------------------------------------------------------------

def _build_raft() -> SpecIR:
    from .raft_ir import build_ir
    return build_ir()


def _build_paxos() -> SpecIR:
    from .paxos.ir import build_ir
    return build_ir()


_BUILDERS: Dict[str, Callable[[], SpecIR]] = {
    "raft": _build_raft,
    "paxos": _build_paxos,
}

_CACHE: Dict[str, SpecIR] = {}


def spec_names() -> Tuple[str, ...]:
    return tuple(sorted(_BUILDERS))


def get_spec(name: str) -> SpecIR:
    if name not in _BUILDERS:
        raise ValueError(
            f"unknown spec {name!r}; known specs: "
            f"{', '.join(spec_names())}")
    ir = _CACHE.get(name)
    if ir is None:
        ir = _CACHE[name] = _BUILDERS[name]()
        assert ir.name == name, (ir.name, name)
    return ir


def spec_of(cfg) -> SpecIR:
    """The IR handle for a model config (``cfg.spec`` class attribute;
    absent attribute reads as the raft frontend — every pre-IR config
    object is a Raft one)."""
    return get_spec(getattr(cfg, "spec", "raft"))
