"""Paxos — the second ``SpecIR`` tenant (single-decree + multi-instance).

The proof that the frontend is real: the five engines run this spec
UNMODIFIED, differentially pinned against the plain-Python oracle in
``model.py`` exactly like Raft is pinned against ``models/raft.py``.
See ``ir.py`` for the operator-surface assembly and ``model.py`` for
the semantics source of truth.
"""

from .config import PaxosConfig  # noqa: F401
