"""Paxos model configuration (the ``PaxosConfig`` the engines bind to).

Bounds model (cf. Lamport's ``Paxos.tla`` as run under TLC): ballots
range over ``0..n_ballots-1``, values over ``0..n_values-1`` (values
are opaque — indices keep the packed layout dense), instances are
``n_instances`` fully independent single-decree consensus slots (the
product-state multi-instance form; the reachable set is exactly the
product of the per-instance sets, which the tests exploit as a
closed-form count check).  Unlike Raft, the whole state space is
finite WITHOUT search constraints — ``msgs`` is a monotone SET over a
finite message universe and every per-acceptor variable is bounded —
so the constraint registry is legitimately empty.

The engines read the same generic surface they read off
``ModelConfig``: ``invariants`` / ``constraints`` /
``action_constraints`` / ``symmetry`` / ``fp128`` / ``prefix_pins``
plus the dispatch marker ``spec`` (a class attribute, so it never
enters ``repr``/checkpoint-compat comparisons).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Tuple

DEFAULT_INVARIANTS = ("Agreement", "Validity", "OneValuePerBallot")


@dataclass(frozen=True)
class PaxosConfig:
    """One checkable Paxos model: acceptor/ballot/value/instance bounds
    + the toggle surface the engines consume."""

    n_servers: int = 3            # |Acceptor| (engines' generic name)
    n_ballots: int = 2            # ballots 0..n_ballots-1
    n_values: int = 2             # values 0..n_values-1
    n_instances: int = 1          # independent consensus slots
    symmetry: bool = True         # acceptor-permutation canonicalization
    fp128: bool = False
    invariants: Tuple[str, ...] = DEFAULT_INVARIANTS
    constraints: Tuple[str, ...] = ()         # finite space: none needed
    action_constraints: Tuple[str, ...] = ()
    prefix_pins: Tuple[str, ...] = ()         # raft-only feature

    # SpecIR dispatch marker — class attribute, NOT a dataclass field:
    # repr(cfg) (the checkpoint-compat key) is unaffected
    spec = "paxos"

    def __post_init__(self):
        if not (1 <= self.n_servers <= 7):
            raise ValueError(
                f"n_servers must be in 1..7 (got {self.n_servers}) — "
                "quorum enumeration is exponential in acceptors")
        for nm in ("n_ballots", "n_values", "n_instances"):
            v = getattr(self, nm)
            if not (1 <= v <= 32):
                raise ValueError(f"{nm} must be in 1..32 (got {v})")

    @property
    def values(self) -> Tuple[int, ...]:
        return tuple(range(self.n_values))

    @property
    def quorums(self) -> Tuple[Tuple[int, ...], ...]:
        """All majorities of the acceptor set (every TLA Quorum model
        instantiates it so); shared by the oracle and the kernels."""
        import itertools
        n = self.n_servers
        out = []
        for r in range(n // 2 + 1, n + 1):
            out.extend(itertools.combinations(range(n), r))
        return tuple(out)

    def with_(self, **kw) -> "PaxosConfig":
        return dataclasses.replace(self, **kw)
