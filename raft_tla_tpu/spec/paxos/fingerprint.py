"""Symmetry-canonical Paxos fingerprints (acceptor-permutation VIEW).

Same identity semantics as engine/fingerprint.RaftFingerprinter —
fp(s) = min over the symmetry group of a salted positional hash of the
VIEW (mb / vb / vv / msgs; ctr excluded) — but structurally far
simpler, because the Paxos layout has NO label-carrying values:
acceptor ids appear only as *positions* (the [I, N] columns and the
acc-indexed 1b/2b message bits), never inside stored values.  The
salt-permutation trick therefore covers the whole state: relabeling
under σ is hashing the state in place against statically permuted salt
tables (per-acceptor columns permute by σ(a); message-bit salts
permute by the layout's perm_bit_map), with zero per-σ value
rewriting.  Bit-identical to relabel-then-hash by the same commutative
u32-sum argument.

Streams: two independent 32-bit murmur-finalizer streams (64-bit
identity), fp128 doubles them — identical to the raft stream model, so
the engines' visited tables / Bloom filters are spec-blind.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from ...engine.fingerprint import fmix32, _salts
from .layout import PaxosLayout
from .model import symmetry_perms

U32 = jnp.uint32


class PaxosFingerprinter:
    def __init__(self, cfg):
        self.cfg = cfg
        self.lay = PaxosLayout(cfg)
        lay = self.lay
        self.n_streams = 4 if cfg.fp128 else 2
        # positions: mb | vb | vv (I*N each) | message bits
        self.n_scalar = 3 * lay.I * lay.N
        self.n_pos = self.n_scalar + lay.n_msg_bits
        self.pos_salts = [_salts(self.n_pos, 32 + t)
                          for t in range(self.n_streams)]
        perms = (symmetry_perms(cfg) if cfg.symmetry
                 else [tuple(range(lay.N))])
        self.sigmas = np.array(perms, dtype=np.int32)
        # statically permuted salt tables (engine/fingerprint docstring
        # for the algebra): psalts[p, t, i] is the salt position i's
        # content hashes against under σ_p
        idx = np.empty((len(perms), self.n_pos), dtype=np.int64)
        ar = np.arange(lay.N)
        for p, sig in enumerate(np.asarray(self.sigmas)):
            off = 0
            for _blk in range(3):                      # mb vb vv
                for i in range(lay.I):
                    base = off + i * lay.N
                    idx[p, base:base + lay.N] = base + sig[ar]
                off += lay.I * lay.N
            idx[p, off:] = off + lay.perm_bit_map(sig)
        self.psalts = np.stack(
            [np.stack([self.pos_salts[t][idx[p]]
                       for t in range(self.n_streams)])
             for p in range(len(perms))])       # [P, n_streams, n_pos]

    def supports_incremental(self) -> bool:
        """No incremental-delta path yet: Paxos configs are small and
        symmetry groups tiny (N! at N<=5); the direct positional sum is
        already cheap.  The engines fall back automatically."""
        return False

    # ------------------------------------------------------------------

    def _core(self, svT: Dict, nb: int) -> jnp.ndarray:
        lay = self.lay
        tail = (1,) * nb
        words = svT["msgs"]                            # [MW, ...]
        j = np.arange(lay.n_msg_bits)
        sh = jnp.asarray((j & 31).astype(np.uint32)).reshape(
            (lay.n_msg_bits,) + tail)
        bits = ((words[j >> 5] >> sh) & U32(1)).astype(U32)
        scal = [svT["mb"], svT["vb"], svT["vv"]]
        flat = jnp.concatenate(
            [p.reshape((-1,) + p.shape[p.ndim - nb:]).astype(U32)
             for p in scal] + [bits])                  # [n_pos, ...]

        def one_perm(psalt):
            out = []
            for t in range(self.n_streams):
                h = jnp.sum(fmix32(flat ^ psalt[t].reshape(
                    (self.n_pos,) + tail)), axis=0)
                out.append(h)
            return jnp.stack(out)                      # [n_streams, ...]

        hs = jax.vmap(one_perm)(jnp.asarray(self.psalts))
        return self._seal(self._lex_min(hs))

    def _lex_min(self, hs) -> jnp.ndarray:
        best = hs[0]
        for p in range(1, hs.shape[0]):
            cand = hs[p]
            less = jnp.zeros(best.shape[1:], bool)
            eq = jnp.ones(best.shape[1:], bool)
            for t in range(self.n_streams):
                less = less | (eq & (cand[t] < best[t]))
                eq = eq & (cand[t] == best[t])
            best = jnp.where(less, cand, best)
        return best

    def _seal(self, best):
        """All-ones fingerprints alias the visited tables' empty-slot
        sentinel; remap exactly like the raft sealer."""
        allones = jnp.ones(best.shape[1:], bool)
        for t in range(self.n_streams):
            allones = allones & (best[t] == U32(0xFFFFFFFF))
        return best.at[self.n_streams - 1].set(
            jnp.where(allones, U32(0xFFFFFFFE),
                      best[self.n_streams - 1]))

    # ---- the three engine entry points (raft-interface twins) ----------

    def fingerprint(self, sv: Dict) -> jnp.ndarray:
        return self._core(sv, nb=0)

    def fingerprint_batch(self, svb: Dict) -> jnp.ndarray:
        svT = {k: jnp.moveaxis(v, 0, -1) for k, v in svb.items()}
        return self._core(svT, nb=1).T                 # [B, n_streams]

    def fingerprint_batch_T(self, svT: Dict) -> jnp.ndarray:
        return self._core(svT, nb=1)
