"""Symmetry-canonical Paxos fingerprints (acceptor-permutation VIEW).

Same identity semantics as engine/fingerprint.RaftFingerprinter —
fp(s) = min over the symmetry group of a salted positional hash of the
VIEW (mb / vb / vv / msgs; ctr excluded) — but structurally far
simpler, because the Paxos layout has NO label-carrying values:
acceptor ids appear only as *positions* (the [I, N] columns and the
acc-indexed 1b/2b message bits), never inside stored values.  The
salt-permutation trick therefore covers the whole state: relabeling
under σ is hashing the state in place against statically permuted salt
tables (per-acceptor columns permute by σ(a); message-bit salts
permute by the layout's perm_bit_map), with zero per-σ value
rewriting.  Bit-identical to relabel-then-hash by the same commutative
u32-sum argument.

Streams: two independent 32-bit murmur-finalizer streams (64-bit
identity), fp128 doubles them — identical to the raft stream model, so
the engines' visited tables / Bloom filters are spec-blind.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from ...engine.fingerprint import fmix32, _salts
from .layout import PaxosLayout
from .model import symmetry_perms

U32 = jnp.uint32


def _fmix32_np(x: np.ndarray) -> np.ndarray:
    """Host-side murmur3 finalizer twin (uint32 wrapping)."""
    x = np.asarray(x, np.uint32)
    with np.errstate(over="ignore"):
        x = x ^ (x >> np.uint32(16))
        x = x * np.uint32(0x85EBCA6B)
        x = x ^ (x >> np.uint32(13))
        x = x * np.uint32(0xC2B2AE35)
        x = x ^ (x >> np.uint32(16))
    return x


def paxos_acceptor_signature(fpr, svT: Dict, bits) -> jnp.ndarray:
    """Paxos ``server_signature`` hook body: permutation-EQUIVARIANT
    per-acceptor signature u32[N, B].  Acceptor a's column of
    mb/vb/vv folds order-preservingly over instances (per-instance
    salts), and every message bit the acceptor OWNS (the 1b/2b
    blocks) adds its role weight — the bit's index with the owner
    relabeled to 0, hashed — so two acceptors tie exactly when their
    columns match and they own the same multiset of messages up to
    their own label.  No refinement rounds: the paxos layout has no
    acceptor-acceptor relations to refine over."""
    isalt = jnp.asarray(fpr._inst_salts)[:, None, None]  # [I, 1, 1]
    c = None
    for key, s in (("mb", 0x6B79D8A5), ("vb", 0x27D4EB2F),
                   ("vv", 0x165667B1)):
        M = svT[key].astype(U32)                         # [I, N, B]
        fold = jnp.sum(fmix32(M ^ isalt ^ U32(s)), axis=0)
        c = fold if c is None else fmix32(c + fold)
    rw = jnp.asarray(fpr._role_w)                        # [N, n_bits]
    c = fmix32(c + jnp.sum(rw[:, :, None] * bits[None].astype(U32),
                           axis=1))
    return c


class PaxosFingerprinter:
    def __init__(self, cfg, sym_canon: str = "minperm"):
        assert sym_canon in ("sort", "minperm"), sym_canon
        self.sym_canon = sym_canon
        self.cfg = cfg
        self.lay = PaxosLayout(cfg)
        lay = self.lay
        self.n_streams = 4 if cfg.fp128 else 2
        # positions: mb | vb | vv (I*N each) | message bits
        self.n_scalar = 3 * lay.I * lay.N
        self.n_pos = self.n_scalar + lay.n_msg_bits
        self.pos_salts = [_salts(self.n_pos, 32 + t)
                          for t in range(self.n_streams)]
        perms = (symmetry_perms(cfg) if cfg.symmetry
                 else [tuple(range(lay.N))])
        self.sigmas = np.array(perms, dtype=np.int32)
        # statically permuted salt tables (engine/fingerprint docstring
        # for the algebra): psalts[p, t, i] is the salt position i's
        # content hashes against under σ_p
        idx = np.empty((len(perms), self.n_pos), dtype=np.int64)
        ar = np.arange(lay.N)
        for p, sig in enumerate(np.asarray(self.sigmas)):
            off = 0
            for _blk in range(3):                      # mb vb vv
                for i in range(lay.I):
                    base = off + i * lay.N
                    idx[p, base:base + lay.N] = base + sig[ar]
                off += lay.I * lay.N
            idx[p, off:] = off + lay.perm_bit_map(sig)
        self.psalts = np.stack(
            [np.stack([self.pos_salts[t][idx[p]]
                       for t in range(self.n_streams)])
             for p in range(len(perms))])       # [P, n_streams, n_pos]
        if sym_canon == "sort":
            self._init_sort(cfg, lay)

    def _init_sort(self, cfg, lay):
        """Orbit-sort precompute (round 15).  Acceptor ids appear only
        as POSITIONS, and every owned message bit's layout index is
        AFFINE in its owning acceptor (idx_1b/idx_2b are linear in
        ``a``), so the per-lane salt permutation is pure index
        arithmetic: bit j's salt under σ sits at
        j + (σ(owner_j) − owner_j)·stride_j (identity for the unowned
        1a/2a blocks).  owner/stride are derived from the closed forms
        and cross-checked against perm_bit_map at init."""
        N, B, V = lay.N, lay.B, lay.V
        owner = np.zeros(lay.n_msg_bits, np.int32)
        stride = np.zeros(lay.n_msg_bits, np.int32)
        s1b = B * (B + 1) * (V + 1)
        j1b = np.arange(lay.off_2a - lay.off_1b)
        owner[lay.off_1b:lay.off_2a] = (j1b // s1b) % N
        stride[lay.off_1b:lay.off_2a] = s1b
        s2b = B * V
        j2b = np.arange(lay.n_msg_bits - lay.off_2b)
        owner[lay.off_2b:] = (j2b // s2b) % N
        stride[lay.off_2b:] = s2b
        jar = np.arange(lay.n_msg_bits)
        for sig in (np.roll(np.arange(N), 1), np.arange(N)[::-1]):
            ref = lay.perm_bit_map(tuple(int(x) for x in sig))
            chk = jar + (sig[owner] - owner) * stride
            assert np.array_equal(np.asarray(ref), chk), \
                "paxos owner/stride bit map diverged from perm_bit_map"
        self._bit_owner, self._bit_stride = owner, stride
        # role id: the bit's index with its owner relabeled to 0 —
        # equal for bits that are the same message up to the acceptor
        # label, distinct otherwise.  role_w[a, j] weights bit j into
        # acceptor a's signature multiset (0 for unowned bits).
        role = (jar - owner.astype(np.int64) * stride).astype(np.uint32)
        rw = _fmix32_np(role * np.uint32(0x9E3779B1)
                        + np.uint32(0x85EBCA6B))
        owned = stride > 0
        self._role_w = np.where(
            owned[None, :] & (owner[None, :] == np.arange(N)[:, None]),
            rw[None, :], np.uint32(0))           # [N, n_msg_bits]
        self._inst_salts = _salts(lay.I, 44)
        self._sort_salt = _salts(self.n_streams, 49)
        from .. import spec_of
        self._sig_fn = spec_of(cfg).server_signature

    def supports_incremental(self) -> bool:
        """No incremental-delta path yet: Paxos configs are small and
        symmetry groups tiny (N! at N<=5); the direct positional sum is
        already cheap.  The engines fall back automatically."""
        return False

    # ------------------------------------------------------------------

    def _hash_under(self, flat, nb: int, psalt) -> jnp.ndarray:
        """One salted positional hash -> u32[n_streams, ...]; psalt is
        a static [T, n_pos] table (min-over-perms path) or a per-lane
        gathered [T, n_pos, B] one (orbit-sort path)."""
        tail = (1,) * nb
        out = []
        for t in range(self.n_streams):
            p_t = psalt[t]
            if p_t.ndim == 1:
                p_t = p_t.reshape((self.n_pos,) + tail)
            out.append(jnp.sum(fmix32(flat ^ p_t), axis=0))
        return jnp.stack(out)                          # [n_streams, ...]

    def _core(self, svT: Dict, nb: int) -> jnp.ndarray:
        lay = self.lay
        tail = (1,) * nb
        words = svT["msgs"]                            # [MW, ...]
        j = np.arange(lay.n_msg_bits)
        sh = jnp.asarray((j & 31).astype(np.uint32)).reshape(
            (lay.n_msg_bits,) + tail)
        bits = ((words[j >> 5] >> sh) & U32(1)).astype(U32)
        scal = [svT["mb"], svT["vb"], svT["vv"]]
        flat = jnp.concatenate(
            [p.reshape((-1,) + p.shape[p.ndim - nb:]).astype(U32)
             for p in scal] + [bits])                  # [n_pos, ...]
        if self.sym_canon == "sort" and len(self.sigmas) > 1:
            assert nb == 1          # fingerprint() wraps with B=1
            return self._core_sort(svT, flat, bits)
        hs = jax.vmap(lambda p: self._hash_under(flat, nb, p))(
            jnp.asarray(self.psalts))
        return self._seal(self._lex_min(hs))

    # ---- orbit-sort path (round 15; engine/fingerprint._core_sort is
    # the documented twin — same certificate + cond-gated fallback
    # algebra, minus value rewrites, which Paxos simply has none of) --

    def _sort_perm(self, sig):
        """sig [N, B] -> (π [N, B] old→canonical slot, adjacent-tie
        certificates).  The paxos group is the full S_N: one block."""
        N = self.lay.N
        order = jnp.argsort(sig, axis=0, stable=True).astype(jnp.int32)
        col = jnp.arange(sig.shape[1])[None, :]
        pi = jnp.zeros_like(order)
        pi = pi.at[order, col].set(jnp.broadcast_to(
            jnp.arange(N, dtype=jnp.int32)[:, None], order.shape))
        ss = jnp.take_along_axis(sig, order, axis=0)
        ties = [(r, r + 1, ss[r] == ss[r + 1]) for r in range(N - 1)]
        return pi, ties

    def _dyn_psalts(self, pi):
        """pos_salts gathered under a PER-LANE permutation: the jnp
        mirror of __init__'s static index construction, with the
        message-bit block as the affine owner/stride map."""
        lay = self.lay
        I, N = lay.I, lay.N
        B = pi.shape[1:]
        parts, off = [], 0
        iar = jnp.arange(I, dtype=jnp.int32)[:, None, None]
        for _blk in range(3):                          # mb vb vv
            blkidx = off + iar * N + pi[None]
            parts.append(blkidx.reshape((I * N,) + B))
            off += I * N
        jar = jnp.arange(lay.n_msg_bits, dtype=jnp.int32)[:, None]
        own = jnp.asarray(self._bit_owner)
        stride = jnp.asarray(self._bit_stride)[:, None]
        parts.append(off + jar + (pi[own] - own[:, None]) * stride)
        idx = jnp.concatenate(parts)                   # [n_pos, B]
        return jnp.stack([jnp.asarray(self.pos_salts[t])[idx]
                          for t in range(self.n_streams)])

    def _sort_hashes(self, svT: Dict, flat, bits):
        sig = self._sig_fn(self, svT, bits)            # [N, B] u32
        pi, ties = self._sort_perm(sig)
        h0 = self._hash_under(flat, 1, self._dyn_psalts(pi))
        hard = jnp.zeros(h0.shape[1:], bool)
        tie = jnp.zeros(h0.shape[1:], bool)
        for a, b, eq in ties:
            tie = tie | eq
            pit = jnp.where(pi == a, b, jnp.where(pi == b, a, pi))
            ht = self._hash_under(flat, 1, self._dyn_psalts(pit))
            same = jnp.ones_like(hard)
            for t in range(self.n_streams):
                same = same & (ht[t] == h0[t])
            hard = hard | (eq & ~same)
        return h0, hard, tie

    def _core_sort(self, svT: Dict, flat, bits) -> jnp.ndarray:
        h0, hard, _tie = self._sort_hashes(svT, flat, bits)

        def _fallback(_):
            hs = jax.vmap(lambda p: self._hash_under(flat, 1, p))(
                jnp.asarray(self.psalts))
            return self._lex_min(hs)

        fp_min = jax.lax.cond(jnp.any(hard), _fallback,
                              lambda _: jnp.zeros_like(h0), None)
        fp = jnp.where(hard[None], fp_min, h0)
        fp = fmix32(fp ^ jnp.asarray(self._sort_salt)[:, None])
        return self._seal(fp)

    def sort_debug(self, svb: Dict) -> Dict:
        """Test/bench hook: per-state (hard, tie) masks for a batch-
        FIRST [B, ...] state dict under the sort canonicalizer."""
        assert self.sym_canon == "sort"
        lay = self.lay
        svT = {k: jnp.moveaxis(jnp.asarray(v), 0, -1)
               for k, v in svb.items()}
        words = svT["msgs"]
        j = np.arange(lay.n_msg_bits)
        sh = jnp.asarray((j & 31).astype(np.uint32)).reshape(
            (lay.n_msg_bits, 1))
        bits = ((words[j >> 5] >> sh) & U32(1)).astype(U32)
        scal = [svT["mb"], svT["vb"], svT["vv"]]
        flat = jnp.concatenate(
            [p.reshape((-1,) + p.shape[p.ndim - 1:]).astype(U32)
             for p in scal] + [bits])
        _h0, hard, tie = self._sort_hashes(svT, flat, bits)
        return dict(hard=np.asarray(hard), tie=np.asarray(tie))

    def _lex_min(self, hs) -> jnp.ndarray:
        best = hs[0]
        for p in range(1, hs.shape[0]):
            cand = hs[p]
            less = jnp.zeros(best.shape[1:], bool)
            eq = jnp.ones(best.shape[1:], bool)
            for t in range(self.n_streams):
                less = less | (eq & (cand[t] < best[t]))
                eq = eq & (cand[t] == best[t])
            best = jnp.where(less, cand, best)
        return best

    def _seal(self, best):
        """All-ones fingerprints alias the visited tables' empty-slot
        sentinel; remap exactly like the raft sealer."""
        allones = jnp.ones(best.shape[1:], bool)
        for t in range(self.n_streams):
            allones = allones & (best[t] == U32(0xFFFFFFFF))
        return best.at[self.n_streams - 1].set(
            jnp.where(allones, U32(0xFFFFFFFE),
                      best[self.n_streams - 1]))

    # ---- the three engine entry points (raft-interface twins) ----------

    def fingerprint(self, sv: Dict) -> jnp.ndarray:
        if self.sym_canon == "sort" and len(self.sigmas) > 1:
            svT = {k: jnp.asarray(v)[..., None] for k, v in sv.items()}
            return self._core(svT, nb=1)[..., 0]
        return self._core(sv, nb=0)

    def fingerprint_batch(self, svb: Dict) -> jnp.ndarray:
        svT = {k: jnp.moveaxis(v, 0, -1) for k, v in svb.items()}
        return self._core(svT, nb=1).T                 # [B, n_streams]

    def fingerprint_batch_T(self, svT: Dict) -> jnp.ndarray:
        return self._core(svT, nb=1)
