"""Paxos ``SpecIR`` assembly — the whole operator surface in one place.

Families enumerate in the oracle's order (model.successors): Phase1a,
Phase1b, Phase2a, Phase2b, instance-major within each family.  Every
family declares its guard algebra — each guard is exactly ONE feature
of kernels.guard_features (set-ness makes Paxos guards single-feature
thresholds; the interesting logic lives in the feature computation),
so the int8 guard matmul is a permutation-selection matrix here and
bit-exactness vs the lane sweep is immediate.
"""

from __future__ import annotations

from typing import List

import numpy as np

from .. import SpecIR


# Enabled-lane density (buffer sizing; overflow grows + replays).  A
# fresh Paxos state enables one Phase1a per unsent ballot and fans 1b/2b
# out per acceptor; small lane grids make generous caps cheap.
FAMILY_DENSITY = {
    "Phase1a": 4, "Phase1b": 8, "Phase2a": 4, "Phase2b": 8,
}


def _send_bit(off, idx, src=None):
    """Delta triples for the monotone bit-send ``msgs |= 1 << idx``:
    the bit's weight rides its own bit-clear feature (``src`` overrides
    the source — Phase1b routes through the (mbal, mval) one-hot), so
    the int32 add IS the set-OR, exactly (the 1<<31 lane wraps through
    two's complement — engine/expand builds the matrix with the wrap)."""
    if src is None:
        src = off["_src_f"] + off["_feat"]["notbit"] + idx
    return [(off["msgs"] + (idx >> 5), src, 1 << (idx & 31))]


def build_families(lay) -> List["Family"]:
    from .. import C_GLOBLEN
    from ...engine.expand import Family, d_set
    from .kernels import PaxosKernels
    kern = PaxosKernels(lay)
    I, N, B, V = lay.I, lay.N, lay.B, lay.V

    def grid(*ranges):
        arrs = np.meshgrid(*[np.asarray(r, np.int32) for r in ranges],
                           indexing="ij")
        return tuple(a.ravel() for a in arrs)

    # ---- delta-algebra declarations: every Paxos action is
    # slot-affine (set-monotone sends + per-cell scalar sets), so the
    # whole spec's expansion runs as the group delta matmul with ZERO
    # per-family kernels — the "new spec gets vectorized expansion
    # from its declarations alone" proof (ROADMAP item 3).

    def glob(off):
        return [(off["ctr"] + C_GLOBLEN, off["_const"], 1)]

    def d_1a(off, lay, i, b):
        return _send_bit(off, lay.off_1a + i * lay.B + b) + glob(off)

    def d_1b(off, lay, i, a, b):
        P = (lay.B + 1) * (lay.V + 1)
        base = lay.off_1b + ((i * lay.N + a) * lay.B + b) * P
        mb = off["mb"] + i * lay.N + a
        tr = [(mb, off["_const"], b), (mb, off["_src_x"] + mb, -1)]
        # the report bit position depends on (vb, vv): spread the send
        # over the (mbal, mval) one-hot block — exactly one position
        # fires, and monotone-mb means the bit is provably clear
        fsel = off["_src_f"] + off["_feat"]["sel1b"] \
            + (i * lay.N + a) * P
        for p in range(P):
            tr += _send_bit(off, base + p, src=fsel + p)
        return tr + glob(off)

    def d_2a(off, lay, i, b, v):
        return _send_bit(
            off, lay.off_2a + (i * lay.B + b) * lay.V + v) + glob(off)

    def d_2b(off, lay, i, a, b, v):
        mb = off["mb"] + i * lay.N + a
        vb = off["vb"] + i * lay.N + a
        vv = off["vv"] + i * lay.N + a
        return (d_set(off, mb, b) + d_set(off, vb, b) +
                d_set(off, vv, v) +
                # a re-accept's bit is already set: notbit sourcing
                # makes the add a no-op there, exactly the set-OR
                _send_bit(off, lay.off_2b +
                          ((i * lay.N + a) * lay.B + b) * lay.V + v) +
                glob(off))

    return [
        Family("Phase1a", kern.phase1a, grid(range(I), range(B)),
               lambda i, b: f"Phase1a({i},{b})",
               guard=lambda off, lay, i, b: (
                   [(off["p1a"] + i * lay.B + b, 1)], 1),
               delta=d_1a),
        Family("Phase1b", kern.phase1b,
               grid(range(I), range(N), range(B)),
               lambda i, a, b: f"Phase1b({i},{a},{b})",
               guard=lambda off, lay, i, a, b: (
                   [(off["p1b"] + (i * lay.N + a) * lay.B + b, 1)], 1),
               delta=d_1b),
        Family("Phase2a", kern.phase2a,
               grid(range(I), range(B), range(V)),
               lambda i, b, v: f"Phase2a({i},{b},{v})",
               guard=lambda off, lay, i, b, v: (
                   [(off["p2a"] + (i * lay.B + b) * lay.V + v, 1)], 1),
               delta=d_2a),
        Family("Phase2b", kern.phase2b,
               grid(range(I), range(N), range(B), range(V)),
               lambda i, a, b, v: f"Phase2b({i},{a},{b},{v})",
               guard=lambda off, lay, i, a, b, v: (
                   [(off["p2b"] +
                     ((i * lay.N + a) * lay.B + b) * lay.V + v, 1)],
                   1),
               delta=d_2b),
    ]


def sim_progress(kern, lay):
    """Punctuated-restart ladder for the sim engine: proposal seen <
    acceptance seen < value chosen (the paxos phase ladder)."""
    import jax
    import jax.numpy as jnp

    def score(svT):
        derT = jax.vmap(kern.derived, in_axes=-1, out_axes=-1)(svT)
        any2a = jnp.any(derT["b2a"] > 0, axis=(0, 1, 2))
        any2b = jnp.any(derT["b2b"] > 0, axis=(0, 1, 2, 3))
        chose = jnp.any(derT["chosen"], axis=(0, 1))
        return (any2a.astype(jnp.int32) +
                2 * any2b.astype(jnp.int32) +
                4 * chose.astype(jnp.int32))

    return score


def serve_bucket(cfg):
    """Bucket ceiling for the batched serving layer (serve/batch).

    Round 13 — constant-padding ceilings: ballots, values and
    instances pad up to the shared rung ladder (``spec.pad_rung``), so
    heterogeneous matched-constants sweeps (the *Paxos vs Raft*
    arXiv:2004.05074 workload) share ONE compiled program per ceiling.
    The padded message universe and [I, N] arrays compile at the
    ceiling's widths; each job's own bounds become its family LANE
    MASK (``serve_runtime`` below) — a padded ballot/value/instance
    lane is masked off before compaction, so no message with an
    out-of-bounds constant is ever sent, every quorum/choice closed
    form sees exactly the job's own message set, and padded instances
    sit frozen at their init cells.  Acceptor count stays exact: it is
    structural (quorum enumeration, the symmetry group).

    Paxos states are tiny (a u32 msgs bitmask + [I, N] acceptor
    arrays), so the default small-job ring (4 * chunk rows, 2^15-slot
    table) is generous."""
    from .. import pad_rung
    # floor 2: paxos padding multiplies the message universe (the 1b
    # block is ~B^2*V per acceptor), so the ladder stays tight —
    # 1->2->4->8; instances floor 1 (a padded instance is pure dead
    # weight in every state row)
    ceiling = cfg.with_(n_ballots=pad_rung(cfg.n_ballots, floor=2),
                        n_values=pad_rung(cfg.n_values, floor=2),
                        n_instances=pad_rung(cfg.n_instances))
    return ceiling, dict(chunk=128, vcap=1 << 15, burst_levels=8)


def serve_runtime(expander, cfg):
    """The job's runtime-thresholds data under the bucket's ceiling
    expander (SpecIR.serve_runtime contract).  Thresholds are the
    ceiling's (paxos guards are single-feature, threshold 1); the lane
    mask is where the job's bounds live: each family's (instance,
    ballot[, value]) lane params must fall inside the job's own
    n_instances/n_ballots/n_values.  The acceptor param (Phase1b/2b's
    ``a``) is never masked — acceptors are structural."""
    import numpy as np
    I, B, V = cfg.n_instances, cfg.n_ballots, cfg.n_values
    thr, mask = expander.runtime_thresholds()
    in_bounds = {
        "Phase1a": lambda i, b: i < I and b < B,
        "Phase1b": lambda i, a, b: i < I and b < B,
        "Phase2a": lambda i, b, v: i < I and b < B and v < V,
        "Phase2b": lambda i, a, b, v: i < I and b < B and v < V,
    }
    lane = 0
    for fam in expander.families:
        ok = in_bounds[fam.name]
        for vals in zip(*fam.params) if fam.params else [()]:
            mask[lane] = ok(*(int(v) for v in vals))
            lane += 1
    assert lane == expander.n_lanes
    return dict(thr=thr, mask=mask,
                bounds=np.zeros((0,), np.int32))


def build_ir() -> SpecIR:
    from . import layout as codec
    from .config import PaxosConfig
    from .kernels import PaxosKernels
    from .layout import PaxosLayout
    from .model import (GLOB_DEPENDENT, INVARIANTS, init_state,
                        state_from_obj, state_to_obj, successors,
                        symmetry_perms, walk_key)
    from .oracle import explore
    from .vpredicates import (PaxosPredicates, SCENARIO_PROPERTIES)

    def make_fingerprinter(cfg, sym_canon="minperm"):
        from .fingerprint import PaxosFingerprinter
        return PaxosFingerprinter(cfg, sym_canon=sym_canon)

    def server_signature(fpr, svT, prep):
        from .fingerprint import paxos_acceptor_signature
        return paxos_acceptor_signature(fpr, svT, prep)

    return SpecIR(
        name="paxos",
        version=1,
        make_layout=PaxosLayout,
        init_state=init_state,
        encode=codec.encode,
        decode=codec.decode,
        narrow=codec.narrow,
        widen=codec.widen,
        view_keys=codec.VIEW_KEYS,
        nonview_keys=codec.NONVIEW_KEYS,
        state_to_obj=state_to_obj,
        state_from_obj=state_from_obj,
        make_kernels=PaxosKernels,
        build_families=build_families,
        family_density=dict(FAMILY_DENSITY),
        make_predicates=PaxosPredicates,
        scenario_properties=SCENARIO_PROPERTIES,
        known_invariants=frozenset(INVARIANTS),
        known_constraints=frozenset(),
        known_action_constraints=frozenset(),
        glob_dependent=GLOB_DEPENDENT,
        make_fingerprinter=make_fingerprinter,
        symmetry_perms=symmetry_perms,
        server_signature=server_signature,
        oracle_explore=explore,
        oracle_successors=successors,
        oracle_walk_key=walk_key,
        prefix_pin_seeds=None,
        sim_progress=sim_progress,
        default_config=PaxosConfig,
        serve_bucket=serve_bucket,
        serve_runtime=serve_runtime,
    )
