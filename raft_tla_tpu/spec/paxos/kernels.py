"""Vectorizable Paxos action kernels: the Next-relation as pure jnp.

Same contract as ops/kernels.RaftKernels: each kernel maps a *single*
SoA state (layout.py) plus static-shaped lane parameters to
``(ok, state')`` — ``ok`` is the enabling guard, the returned state is
garbage when False and the engine masks it.  The engines vmap kernels
over the frontier axis and parameter grids; semantics source of truth
is ``model.py`` (the oracle), pinned by differential tests.

Because ``msgs`` is a bitmask over a finite universe (layout.py), the
whole action system is branch-free by construction: guards are bit
tests + scalar compares, effects are bit ORs + [i, a] cell updates.
The one non-trivial guard — Phase2a's ∃-quorum value rule — runs once
per state in ``derived`` (a static python loop over the quorum list,
each iteration pure jnp reductions), exactly mirroring the oracle's
union-over-quorums form.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax.numpy as jnp
import numpy as np

from .. import C_GLOBLEN
from .layout import PaxosLayout

State = Dict[str, jnp.ndarray]

U32 = jnp.uint32


class PaxosKernels:
    """Kernel family bound to one (PaxosLayout, PaxosConfig)."""

    def __init__(self, lay: PaxosLayout):
        self.lay = lay
        self.cfg = lay.cfg
        self.N, self.B, self.V, self.I = lay.N, lay.B, lay.V, lay.I

    # ------------------------------------------------------------------
    # bitmask helpers (single state; engines vmap around these)
    # ------------------------------------------------------------------

    def unpack_bits(self, words) -> jnp.ndarray:
        """u32[MW] -> int32[n_msg_bits] 0/1 vector."""
        j = np.arange(self.lay.n_msg_bits)
        sh = jnp.asarray((j & 31).astype(np.uint32))
        return ((words[j >> 5] >> sh) & U32(1)).astype(jnp.int32)

    def _bit(self, words, idx):
        """One (possibly traced) bit index -> 0/1 int32."""
        sh = (idx & 31).astype(jnp.uint32)
        return ((words[idx >> 5] >> sh) & U32(1)).astype(jnp.int32)

    def _send(self, sv: State, idx) -> State:
        """Monotone set add: OR the message's bit."""
        w = idx >> 5
        mask = U32(1) << (idx & 31).astype(jnp.uint32)
        words = sv["msgs"]
        return dict(sv, msgs=words.at[w].set(words[w] | mask))

    def _glob(self, sv: State) -> State:
        return dict(sv, ctr=sv["ctr"].at[C_GLOBLEN].add(1))

    # ------------------------------------------------------------------
    # Derived per-state quantities (recomputed once per expansion)
    # ------------------------------------------------------------------

    def derived(self, sv: State) -> State:
        lay = self.lay
        I, N, B, V = self.I, self.N, self.B, self.V
        bits = self.unpack_bits(sv["msgs"])
        b1a = bits[lay.off_1a:lay.off_1b].reshape(I, B)
        b1b = bits[lay.off_1b:lay.off_2a].reshape(I, N, B, B + 1, V + 1)
        b2a = bits[lay.off_2a:lay.off_2b].reshape(I, B, V)
        b2b = bits[lay.off_2b:].reshape(I, N, B, V)
        no2a = jnp.sum(b2a, axis=2) == 0                    # [I, B]
        # chosen(i, v): ∃b with a 2b majority (quorums ARE the
        # majorities, so existence is a counting test here — unlike
        # Phase2a's value rule below, which couples to the quorum)
        cnt = jnp.sum(b2b, axis=1)                          # [I, B, V]
        chosen = jnp.any(2 * cnt > N, axis=1)               # [I, V]
        # Phase2a value rule per (i, b, v): union over the static
        # quorum list of the spec's ∃Q conjunct (model._p2a_value_ok)
        bal = np.arange(B)
        p2a = jnp.zeros((I, B, V), bool)
        for Q in self.cfg.quorums:
            qb = b1b[:, list(Q)]             # [I, |Q|, B, B+1, V+1]
            have = jnp.all(jnp.sum(qb, axis=(3, 4)) > 0, axis=1)
            pres = jnp.sum(qb, axis=1)       # [I, B, B+1, V+1]
            voted = pres[:, :, 1:, :]        # mbal >= 0   [I, B, B, V+1]
            any_voted = jnp.sum(voted, axis=(2, 3)) > 0     # [I, B]
            mb_any = jnp.sum(voted, axis=3) > 0             # [I, B, Bm]
            mx = jnp.max(jnp.where(mb_any, bal[None, None, :], -1),
                         axis=2)                            # [I, B]
            vmatch = voted[:, :, :, 1:] > 0  # real mvals [I, B, Bm, V]
            at_max = vmatch & (bal[None, None, :, None] ==
                               mx[:, :, None, None])
            has_v = jnp.any(at_max, axis=2)                 # [I, B, V]
            okq = have[:, :, None] & jnp.where(
                any_voted[:, :, None], has_v, True)
            p2a = p2a | okq
        return {"bits": bits, "b1a": b1a, "b2a": b2a, "b1b": b1b,
                "b2b": b2b, "no2a": no2a, "p2a": p2a, "chosen": chosen}

    # ------------------------------------------------------------------
    # Guard features (the int8 guard-matmul surface; offsets below)
    # ------------------------------------------------------------------

    def guard_features(self, sv: State, der: State) -> jnp.ndarray:
        I, N, B, V = self.I, self.N, self.B, self.V
        bal = jnp.arange(B)
        f1a = 1 - der["b1a"]                                 # [I, B]
        f1b = (der["b1a"][:, None, :] > 0) & \
            (bal[None, None, :] > sv["mb"][:, :, None])      # [I, N, B]
        f2a = der["no2a"][:, :, None] & der["p2a"]           # [I, B, V]
        f2b = (der["b2a"][:, None] > 0) & \
            (bal[None, None, :, None] >=
             sv["mb"][:, :, None, None])                     # [I, N, B, V]
        return jnp.concatenate([
            f1a.reshape(-1), f1b.reshape(-1).astype(jnp.int32),
            f2a.reshape(-1).astype(jnp.int32),
            f2b.reshape(-1).astype(jnp.int32)]).astype(jnp.int8)

    def guard_feature_offsets(self) -> Dict[str, int]:
        I, N, B, V = self.I, self.N, self.B, self.V
        off = dict(p1a=0, p1b=I * B, p2a=I * B + I * N * B)
        off["p2b"] = off["p2a"] + I * B * V
        off["total"] = off["p2b"] + I * N * B * V
        return off

    # ------------------------------------------------------------------
    # Delta features (the delta-matmul successor path; engine/expand).
    #
    # Paxos needs exactly two source blocks to make EVERY action
    # slot-affine (ir.py declares all four families, so expansion runs
    # with zero per-family kernels):
    #
    # - ``notbit`` — 1 - bits over the whole message universe: sourcing
    #   a bit-send's weight (1 << bit) through the bit's own clearness
    #   makes the int32 add exactly the monotone set-OR, even on
    #   re-accept lanes (Phase2b) whose message is already present;
    # - ``sel1b`` — per (i, a), the one-hot over the (B+1)(V+1)
    #   (mbal, mval) report positions selected by the acceptor's
    #   current (vb, vv): Phase1b's message bit is the one
    #   data-dependent slot in the whole spec.
    # ------------------------------------------------------------------

    def delta_features(self, sv: State, der: State) -> jnp.ndarray:
        V = self.V
        notbit = 1 - der["bits"]                   # [n_msg_bits]
        P = (self.B + 1) * (V + 1)
        p = (sv["vb"] + 1) * (V + 1) + (sv["vv"] + 1)      # [I, N]
        sel1b = (p[:, :, None] ==
                 jnp.arange(P, dtype=jnp.int32)[None, None, :]) \
            .astype(jnp.int32)                     # [I, N, P]
        return jnp.concatenate(
            [notbit, sel1b.reshape(-1)]).astype(jnp.int32)

    def delta_feature_offsets(self) -> Dict[str, int]:
        P = (self.B + 1) * (self.V + 1)
        off = dict(notbit=0, sel1b=self.lay.n_msg_bits)
        off["total"] = self.lay.n_msg_bits + self.I * self.N * P
        return off

    # ------------------------------------------------------------------
    # Action kernels (oracle twins in model.py, cited per kernel)
    # ------------------------------------------------------------------

    def phase1a(self, sv: State, der: State, i, b) \
            -> Tuple[jnp.ndarray, State]:
        """model.phase1a: start (or preempt with) ballot b; novelty-
        guarded — a re-send is the identity transition."""
        idx = self.lay.off_1a + i * self.B + b
        ok = self._bit(sv["msgs"], idx) == 0
        return ok, self._glob(self._send(sv, idx))

    def phase1b(self, sv: State, der: State, i, a, b) \
            -> Tuple[jnp.ndarray, State]:
        """model.phase1b: promise b, reporting the accepted pair."""
        B, V, N = self.B, self.V, self.N
        ok = (self._bit(sv["msgs"], self.lay.off_1a + i * B + b) == 1) \
            & (b > sv["mb"][i, a])
        mbal = sv["vb"][i, a]
        mval = sv["vv"][i, a]
        idx = self.lay.off_1b + \
            (((i * N + a) * B + b) * (B + 1) + (mbal + 1)) * (V + 1) \
            + (mval + 1)
        sv2 = dict(sv, mb=sv["mb"].at[i, a].set(b))
        return ok, self._glob(self._send(sv2, idx))

    def phase2a(self, sv: State, der: State, i, b, v) \
            -> Tuple[jnp.ndarray, State]:
        """model.phase2a: propose v at b (∃-quorum rule in derived)."""
        ok = der["no2a"][i, b] & der["p2a"][i, b, v]
        idx = self.lay.off_2a + (i * self.B + b) * self.V + v
        return ok, self._glob(self._send(sv, idx))

    def phase2b(self, sv: State, der: State, i, a, b, v) \
            -> Tuple[jnp.ndarray, State]:
        """model.phase2b: accept (b, v)."""
        B, V, N = self.B, self.V, self.N
        idx2a = self.lay.off_2a + (i * B + b) * V + v
        ok = (self._bit(sv["msgs"], idx2a) == 1) & \
            (b >= sv["mb"][i, a])
        sv2 = dict(sv,
                   mb=sv["mb"].at[i, a].set(b),
                   vb=sv["vb"].at[i, a].set(b),
                   vv=sv["vv"].at[i, a].set(v))
        idx = self.lay.off_2b + ((i * N + a) * B + b) * V + v
        return ok, self._glob(self._send(sv2, idx))
