"""Paxos packed-state layout + host codec.

Device representation: the same SoA-dict contract the engines already
speak (ops/codec.py docstring), with one structural simplification the
Paxos semantics buys us — **messages are a monotone SET over a finite
universe**, so the whole bag machinery (slots, counts, commutative
hashing, split-slot identity) collapses to a fixed-width **bitmask**:

    mb, vb, vv : i32[I, N]    per-(instance, acceptor) scalars (-1 = Nil)
    msgs       : u32[MW]      one bit per possible message (set = sent)
    ctr        : i32[NCTR]    the shared ctr contract (spec package):
                              C_GLOBLEN = actions taken, C_OVERFLOW = 0
                              (everything is statically bounded)

Bit universe, block-major with arithmetic indexing (the kernels compute
bit ids from lane params with closed-form products, no tables needed on
device):

    1a(b, i)                idx =                i*B + b
    1b(a, b, mbal, mval, i) idx = off1b + (((i*N + a)*B + b)*(B+1)
                                  + (mbal+1))*(V+1) + (mval+1)
    2a(b, v, i)             idx = off2a + (i*B + b)*V + v
    2b(a, b, v, i)          idx = off2b + ((i*N + a)*B + b)*V + v

Set-ness also makes every guard a bit test — ideal grist for the
guard-feature matmul — and makes the fingerprint purely positional:
acceptor relabeling permutes bit POSITIONS (never values), so the
salt-permutation trick of engine/fingerprint covers the entire state
with zero per-sigma value rewriting (fingerprint.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import cached_property
from typing import Dict, Tuple

import numpy as np

from .. import C_GLOBLEN, NCTR
from .model import NIL, PaxosHist, PaxosState

VIEW_KEYS = ("mb", "vb", "vv", "msgs")
NONVIEW_KEYS = ("ctr",)
ALL_KEYS = VIEW_KEYS + NONVIEW_KEYS


@dataclass(frozen=True)
class PaxosLayout:
    cfg: object

    @cached_property
    def N(self):
        return self.cfg.n_servers

    @cached_property
    def B(self):
        return self.cfg.n_ballots

    @cached_property
    def V(self):
        return self.cfg.n_values

    @cached_property
    def I(self):
        return self.cfg.n_instances

    # ---- bit-block offsets ---------------------------------------------
    @cached_property
    def off_1a(self):
        return 0

    @cached_property
    def off_1b(self):
        return self.I * self.B

    @cached_property
    def off_2a(self):
        return self.off_1b + self.I * self.N * self.B * \
            (self.B + 1) * (self.V + 1)

    @cached_property
    def off_2b(self):
        return self.off_2a + self.I * self.B * self.V

    @cached_property
    def n_msg_bits(self):
        return self.off_2b + self.I * self.N * self.B * self.V

    @cached_property
    def msg_words(self):
        return (self.n_msg_bits + 31) // 32

    # ---- host-side bit index <-> oracle message ------------------------

    def idx_1a(self, b, i):
        return self.off_1a + i * self.B + b

    def idx_1b(self, a, b, mbal, mval, i):
        return self.off_1b + \
            (((i * self.N + a) * self.B + b) * (self.B + 1) +
             (mbal + 1)) * (self.V + 1) + (mval + 1)

    def idx_2a(self, b, v, i):
        return self.off_2a + (i * self.B + b) * self.V + v

    def idx_2b(self, a, b, v, i):
        return self.off_2b + \
            ((i * self.N + a) * self.B + b) * self.V + v

    def msg_index(self, m) -> int:
        t = m[0]
        if t == "1a":
            return self.idx_1a(m[1], m[2])
        if t == "1b":
            return self.idx_1b(m[1], m[2], m[3], m[4], m[5])
        if t == "2a":
            return self.idx_2a(m[1], m[2], m[3])
        if t == "2b":
            return self.idx_2b(m[1], m[2], m[3], m[4])
        raise ValueError(f"bad paxos message {m!r}")

    @cached_property
    def universe(self) -> Tuple[tuple, ...]:
        """Every representable message, indexed by bit id (decode side
        and the fingerprint permutation maps read this)."""
        out = [None] * self.n_msg_bits
        I, N, B, V = self.I, self.N, self.B, self.V
        for i in range(I):
            for b in range(B):
                out[self.idx_1a(b, i)] = ("1a", b, i)
        for i in range(I):
            for a in range(N):
                for b in range(B):
                    for mbal in range(-1, B):
                        for mval in range(-1, V):
                            out[self.idx_1b(a, b, mbal, mval, i)] = \
                                ("1b", a, b, mbal, mval, i)
        for i in range(I):
            for b in range(B):
                for v in range(V):
                    out[self.idx_2a(b, v, i)] = ("2a", b, v, i)
        for i in range(I):
            for a in range(N):
                for b in range(B):
                    for v in range(V):
                        out[self.idx_2b(a, b, v, i)] = ("2b", a, b, v, i)
        assert all(m is not None for m in out)
        return tuple(out)

    def perm_bit_map(self, sigma) -> np.ndarray:
        """bit id -> bit id of the acceptor-relabeled message (1b/2b
        carry an acceptor; 1a/2a map to themselves).  Drives the
        fingerprinter's statically permuted salt tables."""
        from .model import _perm_msg
        out = np.empty((self.n_msg_bits,), np.int64)
        for k, m in enumerate(self.universe):
            out[k] = self.msg_index(_perm_msg(m, sigma))
        return out

    def describe(self) -> str:
        return (f"PaxosLayout(N={self.N}, B={self.B}, V={self.V}, "
                f"I={self.I}, msg_bits={self.n_msg_bits}, "
                f"msg_words={self.msg_words})")


# ---------------------------------------------------------------------------
# Codec: oracle (PaxosState, PaxosHist) <-> SoA arrays
# ---------------------------------------------------------------------------

def encode(lay: PaxosLayout, sv: PaxosState, h: PaxosHist
           ) -> Dict[str, np.ndarray]:
    out = {
        "mb": np.array(sv.mb, np.int32).reshape(lay.I, lay.N),
        "vb": np.array(sv.vb, np.int32).reshape(lay.I, lay.N),
        "vv": np.array(sv.vv, np.int32).reshape(lay.I, lay.N),
    }
    words = np.zeros((lay.msg_words,), np.uint32)
    for m in sv.msgs:
        k = lay.msg_index(m)
        words[k >> 5] |= np.uint32(1) << np.uint32(k & 31)
    out["msgs"] = words
    ctr = np.zeros((NCTR,), np.int32)
    ctr[C_GLOBLEN] = len(h.glob)
    out["ctr"] = ctr
    return out


def decode(lay: PaxosLayout, arrs) -> Tuple[PaxosState, PaxosHist]:
    """SoA arrays -> (PaxosState, PaxosHist).  Like the raft decode,
    the history *sequence* is host-side only: the returned hist carries
    an empty glob (its length lives in ctr[C_GLOBLEN])."""
    a = {k: np.asarray(v) for k, v in arrs.items()}
    msgs = []
    words = a["msgs"].astype(np.uint32)
    for k, m in enumerate(lay.universe):
        if (int(words[k >> 5]) >> (k & 31)) & 1:
            msgs.append(m)
    sv = PaxosState(
        mb=tuple(tuple(int(x) for x in row) for row in a["mb"]),
        vb=tuple(tuple(int(x) for x in row) for row in a["vb"]),
        vv=tuple(tuple(int(x) for x in row) for row in a["vv"]),
        msgs=tuple(sorted(msgs)))
    return sv, PaxosHist(glob=())


def narrow(lay: PaxosLayout, arrs):
    """int32 SoA rows -> storage dtypes (ballot/value scalars fit int8
    under the <=32 config bounds; the bit words stay u32)."""
    dts = {"mb": np.int8, "vb": np.int8, "vv": np.int8,
           "msgs": np.uint32, "ctr": np.int32}
    return {k: v.astype(dts[k]) for k, v in arrs.items()}


def widen(arrs):
    """Storage rows -> the kernels' int32/uint32 contract."""
    return {k: v.astype(np.uint32) if k == "msgs"
            else v.astype(np.int32) for k, v in arrs.items()}
