"""Plain-Python executable reference model of bounded Paxos (the oracle).

Deliberately literal transcription of the single-decree Paxos action
system (Lamport's ``Paxos.tla`` shape, bounded for model checking),
extended to ``n_instances`` fully independent consensus slots.  The
vectorized kernels in ``kernels.py`` are differentially tested against
THIS module: same successor sets, same distinct-state counts, same
invariant verdicts — the same oracle role ``models/raft.py`` plays for
the Raft frontend.

State:
  * ``mb[i][a]``  maxBal   — highest ballot acceptor ``a`` promised in
                  instance ``i`` (-1 = none)
  * ``vb[i][a]``  maxVBal  — highest ballot ``a`` accepted in (-1)
  * ``vv[i][a]``  maxVal   — the value accepted at ``vb`` (-1)
  * ``msgs``      a monotone SET of messages (sorted tuple — Paxos
                  messages are never consumed, so no bag counts exist)

Messages (tuples; acceptors/ballots/values are small ints):
  ("1a", b, i)                   Phase1a — a proposer starts ballot b
  ("1b", a, b, mbal, mval, i)    Phase1b — promise, reporting (vb, vv)
  ("2a", b, v, i)                Phase2a — proposal of v at ballot b
  ("2b", a, b, v, i)             Phase2b — acceptance

Actions (one vmapped family each, kernels.py):
  * Phase1a(i, b): send 1a(b, i).  Guarded by novelty (the message is
    not already in the set) — a re-send is the identity transition, so
    the reachable graph is unchanged and the trivial self-loop lanes
    are dropped.  A Phase1a at a ballot above every current promise IS
    leader preemption (arXiv:1905.10786's mapping of Raft's
    Timeout/term bump).
  * Phase1b(i, a, b): 1a(b, i) ∈ msgs ∧ b > mb[i][a] → promise: set
    mb, send 1b carrying the accepted pair.
  * Phase2a(i, b, v): no 2a at (b, i) yet ∧ ∃ quorum Q whose 1b(b)
    messages are all present and pick v (the value of a maximal-mbal
    report, free choice when all report -1).  Quantification is over
    MESSAGES, exactly as in the spec — the kernels implement the same
    union-over-Q form.
  * Phase2b(i, a, b, v): 2a(b, v, i) ∈ msgs ∧ b >= mb[i][a] → accept:
    set mb = vb = b, vv = v, send 2b.

History: ``glob`` records one label per action (drives the shared
``ctr[C_GLOBLEN]`` lane); nothing else — no Paxos predicate scans
history records, so engine-emitted seeds are always oracle-evaluable.
"""

from __future__ import annotations

from collections import namedtuple
from typing import List, Tuple

PaxosState = namedtuple("PaxosState", ["mb", "vb", "vv", "msgs"])
PaxosHist = namedtuple("PaxosHist", ["glob"])

NIL = -1


# ---------------------------------------------------------------------------
# Init / helpers
# ---------------------------------------------------------------------------

def init_state(cfg) -> Tuple[PaxosState, PaxosHist]:
    I, N = cfg.n_instances, cfg.n_servers
    row = ((NIL,) * N,) * I
    return PaxosState(mb=row, vb=row, vv=row, msgs=()), PaxosHist(glob=())


def _cell(mat, i, a, v):
    row = mat[i][:a] + (v,) + mat[i][a + 1:]
    return mat[:i] + (row,) + mat[i + 1:]


def _send(sv: PaxosState, m) -> PaxosState:
    """Monotone set add (sorted tuple keeps the representation
    canonical — message order is not part of state identity)."""
    if m in sv.msgs:
        return sv
    return sv._replace(msgs=tuple(sorted(sv.msgs + (m,))))


def _bump(h: PaxosHist, label: str) -> PaxosHist:
    return PaxosHist(glob=h.glob + (label,))


# ---------------------------------------------------------------------------
# Actions
# ---------------------------------------------------------------------------

def phase1a(sv, h, i, b, cfg):
    m = ("1a", b, i)
    if m in sv.msgs:
        return []
    lbl = f"Phase1a({i},{b})"
    return [(lbl, _send(sv, m), _bump(h, lbl))]


def phase1b(sv, h, i, a, b, cfg):
    if ("1a", b, i) not in sv.msgs or b <= sv.mb[i][a]:
        return []
    sv2 = sv._replace(mb=_cell(sv.mb, i, a, b))
    sv2 = _send(sv2, ("1b", a, b, sv.vb[i][a], sv.vv[i][a], i))
    lbl = f"Phase1b({i},{a},{b})"
    return [(lbl, sv2, _bump(h, lbl))]


def _p2a_value_ok(sv, i, b, v, cfg) -> bool:
    """The Phase2a value rule, quantified over messages exactly as the
    spec writes it: ∃Q ∈ Quorum such that every a ∈ Q has a 1b at
    (b, i) in msgs, and either no report in Q carries an accepted pair
    (free choice) or v is the value of a maximal-mbal report in Q."""
    onebs = {}
    for m in sv.msgs:
        if m[0] == "1b" and m[2] == b and m[5] == i:
            onebs.setdefault(m[1], []).append((m[3], m[4]))
    for Q in cfg.quorums:
        if not all(a in onebs for a in Q):
            continue
        reports = [r for a in Q for r in onebs[a]]
        voted = [r for r in reports if r[0] >= 0]
        if not voted:
            return True
        mx = max(r[0] for r in voted)
        if any(r == (mx, v) for r in voted):
            return True
    return False


def phase2a(sv, h, i, b, v, cfg):
    if any(m[0] == "2a" and m[1] == b and m[3] == i for m in sv.msgs):
        return []
    if not _p2a_value_ok(sv, i, b, v, cfg):
        return []
    lbl = f"Phase2a({i},{b},{v})"
    return [(lbl, _send(sv, ("2a", b, v, i)), _bump(h, lbl))]


def phase2b(sv, h, i, a, b, v, cfg):
    if ("2a", b, v, i) not in sv.msgs or b < sv.mb[i][a]:
        return []
    sv2 = sv._replace(mb=_cell(sv.mb, i, a, b))
    sv2 = sv2._replace(vb=_cell(sv2.vb, i, a, b),
                       vv=_cell(sv2.vv, i, a, v))
    sv2 = _send(sv2, ("2b", a, b, v, i))
    lbl = f"Phase2b({i},{a},{b},{v})"
    return [(lbl, sv2, _bump(h, lbl))]


def successors(sv: PaxosState, h: PaxosHist, cfg):
    """All successors in the kernels' lane-grid enumeration order
    (family-major; instance-major inside each family) so candidate
    streams are comparable, like models/raft.successors."""
    I, N, B, V = (cfg.n_instances, cfg.n_servers, cfg.n_ballots,
                  cfg.n_values)
    out = []
    for i in range(I):
        for b in range(B):
            out += phase1a(sv, h, i, b, cfg)
    for i in range(I):
        for a in range(N):
            for b in range(B):
                out += phase1b(sv, h, i, a, b, cfg)
    for i in range(I):
        for b in range(B):
            for v in range(V):
                out += phase2a(sv, h, i, b, v, cfg)
    for i in range(I):
        for a in range(N):
            for b in range(B):
                for v in range(V):
                    out += phase2b(sv, h, i, a, b, v, cfg)
    return out


# ---------------------------------------------------------------------------
# Symmetry: acceptors are interchangeable (ballots and values are not)
# ---------------------------------------------------------------------------

def symmetry_perms(cfg) -> List[Tuple[int, ...]]:
    import itertools
    return [tuple(p) for p in
            itertools.permutations(range(cfg.n_servers))]


def _perm_msg(m, sigma):
    if m[0] == "1b":
        return (m[0], sigma[m[1]]) + m[2:]
    if m[0] == "2b":
        return (m[0], sigma[m[1]]) + m[2:]
    return m


def relabel(sv: PaxosState, sigma, cfg) -> PaxosState:
    """Acceptor relabeling (old id -> new id) across the per-acceptor
    columns and the acc field of 1b/2b messages."""
    n = cfg.n_servers
    inv = [0] * n
    for i in range(n):
        inv[sigma[i]] = i

    def pr(mat):
        return tuple(tuple(row[inv[k]] for k in range(n)) for row in mat)

    return PaxosState(
        mb=pr(sv.mb), vb=pr(sv.vb), vv=pr(sv.vv),
        msgs=tuple(sorted(_perm_msg(m, sigma) for m in sv.msgs)))


def canonicalize(sv: PaxosState, perms, cfg) -> PaxosState:
    return min(relabel(sv, s, cfg) for s in perms)


def walk_key(sv: PaxosState):
    """State-identity key (msgs is kept sorted, so the tuple itself is
    canonical) — the paxos twin of models/explore._walk_key."""
    return sv


# ---------------------------------------------------------------------------
# Oracle predicates ((sv, h, cfg) -> holds, mirroring models/predicates)
# ---------------------------------------------------------------------------

def chosen_values(sv: PaxosState, i: int, cfg) -> set:
    """{v : ∃b ∃Q ∈ Quorum: ∀a ∈ Q: 2b(a, b, v, i) ∈ msgs}.  Quorums
    are exactly the majorities, so existence = a counting test."""
    n = cfg.n_servers
    out = set()
    for b in range(cfg.n_ballots):
        for v in range(cfg.n_values):
            cnt = sum(1 for a in range(n)
                      if ("2b", a, b, v, i) in sv.msgs)
            if 2 * cnt > n:
                out.add(v)
    return out


def agreement(sv, h, cfg) -> bool:
    """At most one value is ever chosen per instance — THE safety
    property of consensus."""
    return all(len(chosen_values(sv, i, cfg)) <= 1
               for i in range(cfg.n_instances))


def validity(sv, h, cfg) -> bool:
    """Acceptances trace to proposals: every 2b has its 2a, and every
    1b reporting an accepted pair (mbal >= 0) has the 2a it accepted.
    (Vacuous by construction — its violation would be a kernel bug,
    which is exactly why it runs in every differential.)"""
    for m in sv.msgs:
        if m[0] == "2b" and ("2a", m[2], m[3], m[4]) not in sv.msgs:
            return False
        if m[0] == "1b":
            mbal, mval = m[3], m[4]
            if (mbal >= 0) != (mval >= 0):
                return False
            if mbal >= 0 and ("2a", mbal, mval, m[5]) not in sv.msgs:
                return False
    return True


def one_value_per_ballot(sv, h, cfg) -> bool:
    """A ballot proposes at most one value per instance (the Phase2a
    novelty guard's invariant form)."""
    for i in range(cfg.n_instances):
        for b in range(cfg.n_ballots):
            vs = {m[2] for m in sv.msgs
                  if m[0] == "2a" and m[1] == b and m[3] == i}
            if len(vs) > 1:
                return False
    return True


# Scenario ("test case") properties — negated reachability, like the
# raft Test-cases block: a "violation" is a wanted witness.

def value_chosen(sv, h, cfg) -> bool:
    return all(not chosen_values(sv, i, cfg)
               for i in range(cfg.n_instances))


def two_ballots(sv, h, cfg) -> bool:
    """Holds until two distinct ballots have been started (a competing-
    proposers witness)."""
    bals = {m[1] for m in sv.msgs if m[0] == "1a"}
    return len(bals) < 2


def preempted(sv, h, cfg) -> bool:
    """Holds until some acceptor that accepted a value has promised a
    strictly higher ballot — the leader-preemption witness
    (arXiv:1905.10786: the Paxos analogue of a Raft term bump over a
    live leader)."""
    for i in range(cfg.n_instances):
        for a in range(cfg.n_servers):
            if sv.vb[i][a] >= 0 and sv.mb[i][a] > sv.vb[i][a]:
                return False
    return True


INVARIANTS = {
    "Agreement": agreement,
    "Validity": validity,
    "OneValuePerBallot": one_value_per_ballot,
    "ValueChosen": value_chosen,
    "TwoBallots": two_ballots,
    "Preempted": preempted,
}

CONSTRAINTS = {}            # the space is finite without any
ACTION_CONSTRAINTS = {}
GLOB_DEPENDENT = frozenset()    # no predicate scans history records

SCENARIO_PROPERTIES = ("ValueChosen", "TwoBallots", "Preempted")


# ---------------------------------------------------------------------------
# JSON-able (de)serialization — the seed-trace file format
# ---------------------------------------------------------------------------

def state_to_obj(sv: PaxosState, h: PaxosHist) -> dict:
    return {"paxos": True,
            "state": [[list(r) for r in sv.mb],
                      [list(r) for r in sv.vb],
                      [list(r) for r in sv.vv],
                      [list(m) for m in sv.msgs]],
            "hist": [list(h.glob)]}


def state_from_obj(obj: dict) -> Tuple[PaxosState, PaxosHist]:
    mb, vb, vv, msgs = obj["state"]
    sv = PaxosState(
        mb=tuple(tuple(r) for r in mb),
        vb=tuple(tuple(r) for r in vb),
        vv=tuple(tuple(r) for r in vv),
        msgs=tuple(sorted(tuple(m) for m in msgs)))
    return sv, PaxosHist(glob=tuple(obj["hist"][0]))
