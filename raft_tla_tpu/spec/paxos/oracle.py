"""Oracle-side explicit-state BFS for Paxos — the differential anchor.

The same deliberately simple, trustworthy shape as models/explore.py
(TLC worker-loop semantics: VIEW identity, symmetry canonicalization,
CONSTRAINT = prune-not-expand), parameterized by the paxos model.  It
reuses models/explore's ``ExploreResult``/``Violation`` result types so
the CLI's oracle engine path is spec-blind.
"""

from __future__ import annotations

from typing import Dict, List

from ...models.explore import ExploreResult, Violation
from .model import (INVARIANTS, canonicalize, init_state, successors,
                    symmetry_perms, walk_key)


def explore(cfg, max_depth: int = 10 ** 9, max_states: int = 10 ** 9,
            keep_states: bool = False, stop_on_violation: bool = False,
            trace_violations: bool = False,
            seed_states=None) -> ExploreResult:
    """Level-synchronous BFS from Init (or ``seed_states``).  Paxos has
    no constraints / action constraints / prefix pins, so the loop is
    the models/explore core minus those arms; invariant names resolve
    from model.INVARIANTS (unknown names fail loudly, naming the
    spec)."""
    perms = symmetry_perms(cfg) if cfg.symmetry else None
    try:
        inv_fns = [(nm, INVARIANTS[nm]) for nm in cfg.invariants]
    except KeyError as e:
        raise KeyError(
            f"unknown invariant {e.args[0]!r} for spec 'paxos'; "
            f"known: {', '.join(sorted(INVARIANTS))}") from None
    if cfg.constraints or cfg.action_constraints:
        raise KeyError(
            "spec 'paxos' declares no constraints / action "
            "constraints; remove them from the config")

    def key_of(sv):
        return canonicalize(sv, perms, cfg) if perms else walk_key(sv)

    roots = (seed_states if seed_states is not None
             else [init_state(cfg)])
    seen: Dict = {}
    parent: Dict = {}
    result = ExploreResult(distinct_states=0, generated_states=0,
                           depth=0)

    def check(sv, h, k):
        for nm, fn in inv_fns:
            if not fn(sv, h, cfg):
                v = Violation(nm, sv, h)
                if trace_violations:
                    v.trace = _trace_to(k, parent)
                result.violations.append(v)
                if stop_on_violation:
                    return False
        return True

    frontier = []
    for sv0, h0 in roots:
        k0 = key_of(sv0)
        if k0 in seen:
            continue
        seen[k0] = (sv0, h0)
        parent[k0] = (None, None)
        result.generated_states += 1
        if not check(sv0, h0, k0) and stop_on_violation:
            result.distinct_states = len(seen)
            result.states = seen if keep_states else None
            return result
        frontier.append((sv0, h0, k0))
    depth = 0
    while frontier and depth < max_depth and len(seen) < max_states:
        depth += 1
        nxt = []
        for sv, h, k in frontier:
            for label, sv2, h2 in successors(sv, h, cfg):
                result.generated_states += 1
                k2 = key_of(sv2)
                if k2 in seen:
                    continue
                seen[k2] = (sv2, h2)
                parent[k2] = (k, label)
                if not check(sv2, h2, k2) and stop_on_violation:
                    result.distinct_states = len(seen)
                    result.depth = depth
                    result.states = seen if keep_states else None
                    return result
                nxt.append((sv2, h2, k2))
        result.level_sizes.append(len(nxt))
        frontier = nxt
    result.distinct_states = len(seen)
    result.depth = depth
    result.states = seen if keep_states else None
    return result


def oracle_validates_walk(cfg, states: List) -> List[str]:
    """Replay an engine-decoded state chain through the oracle
    transition relation (the paxos twin of
    models/explore.oracle_validates_walk — sim witnesses are accepted
    under this check)."""
    sv, h = init_state(cfg)
    if walk_key(states[0]) != walk_key(sv):
        raise ValueError("walk does not start at Init")
    out: List[str] = []
    for t, nxt in enumerate(states[1:]):
        want = walk_key(nxt)
        matches = [(lb, s2, h2)
                   for (lb, s2, h2) in successors(sv, h, cfg)
                   if walk_key(s2) == want]
        if not matches:
            raise ValueError(
                f"step {t + 1}: engine state is not an oracle "
                f"successor")
        lb, sv, h = matches[0]
        out.append(lb)
    return out


def _trace_to(k, parent) -> List[str]:
    out = []
    while True:
        pk, label = parent[k]
        if pk is None:
            break
        out.append(label)
        k = pk
    return list(reversed(out))
