"""Vectorized Paxos invariants + scenario properties (device twins of
model.py's oracle predicates, same (sv, der) -> holds contract as
ops/vpredicates.Predicates).

Quantifier structure becomes broadcasting over the unpacked message-bit
blocks (derived carries them): "every 2b has its 2a" is one masked
compare, Agreement's ∃-quorum "chosen" test is the majority counting
closed form (quorums = majorities), computed once per state in
``kernels.derived``.

Paxos declares NO constraints and NO action constraints — the state
space is finite without them (config.py docstring) — so those
registries are empty and resolve loudly, naming the spec.
"""

from __future__ import annotations

from typing import Callable, Dict

import jax.numpy as jnp

from .kernels import PaxosKernels
from .layout import PaxosLayout


class PaxosPredicates:
    """Predicate family bound to one (PaxosLayout, PaxosConfig)."""

    def __init__(self, lay: PaxosLayout):
        self.lay = lay
        self.cfg = lay.cfg
        self.kern = PaxosKernels(lay)

    # ---- safety invariants (oracle twins in model.py) ------------------

    def agreement(self, sv, der):
        """model.agreement: ≤ 1 chosen value per instance."""
        return jnp.all(jnp.sum(der["chosen"], axis=1) <= 1)

    def validity(self, sv, der):
        """model.validity: every 2b traces to its 2a; every 1b report
        is consistent (mbal >= 0 iff mval >= 0) and traces to the 2a it
        accepted."""
        b1b, b2a, b2b = der["b1b"], der["b2a"], der["b2b"]
        ok_2b = jnp.all((b2b <= b2a[:, None]))
        incons = jnp.any(b1b[:, :, :, 1:, 0] > 0) | \
            jnp.any(b1b[:, :, :, 0, 1:] > 0)
        # real reports [(mbal, mval) >= 0], any acceptor/promise ballot
        rep = jnp.any(b1b[:, :, :, 1:, 1:] > 0, axis=(1, 2))  # [I,Bm,V]
        ok_1b = jnp.all(~rep | (b2a > 0))
        return ok_2b & ~incons & ok_1b

    def one_value_per_ballot(self, sv, der):
        """model.one_value_per_ballot."""
        return jnp.all(jnp.sum(der["b2a"], axis=2) <= 1)

    # ---- scenario properties (negated reachability) --------------------

    def value_chosen(self, sv, der):
        return ~jnp.any(der["chosen"])

    def two_ballots(self, sv, der):
        started = jnp.any(der["b1a"] > 0, axis=0)          # [B]
        return jnp.sum(started) < 2

    def preempted(self, sv, der):
        return ~jnp.any((sv["vb"] >= 0) & (sv["mb"] > sv["vb"]))

    # ---- registries ----------------------------------------------------

    def invariant_fn(self, name: str) -> Callable:
        try:
            return INVARIANTS[name].__get__(self)
        except KeyError:
            raise KeyError(
                f"unknown invariant {name!r} for spec 'paxos'; known: "
                f"{', '.join(sorted(INVARIANTS))}") from None

    def constraint_fn(self, name: str) -> Callable:
        raise KeyError(
            f"unknown constraint {name!r} for spec 'paxos' — paxos "
            "declares no search constraints (the bounded space is "
            "finite without them)")

    def action_fn(self, name: str) -> Callable:
        raise KeyError(
            f"unknown action constraint {name!r} for spec 'paxos' — "
            "paxos declares none")


INVARIANTS: Dict[str, Callable] = {
    "Agreement": PaxosPredicates.agreement,
    "Validity": PaxosPredicates.validity,
    "OneValuePerBallot": PaxosPredicates.one_value_per_ballot,
    "ValueChosen": PaxosPredicates.value_chosen,
    "TwoBallots": PaxosPredicates.two_ballots,
    "Preempted": PaxosPredicates.preempted,
}

SCENARIO_PROPERTIES = ("ValueChosen", "TwoBallots", "Preempted")

for _nm in SCENARIO_PROPERTIES:
    assert _nm in INVARIANTS, \
        f"scenario property {_nm!r} has no device predicate"
