"""The Raft spec as the first ``SpecIR`` instance.

This is a *re-homing*, not a rewrite: the model oracle
(``models/raft.py``), packed layout/codec (``ops/layout.py`` /
``ops/codec.py``), kernels (``ops/kernels.py``), device predicates
(``ops/vpredicates.py``), symmetry fingerprinter
(``engine/fingerprint.RaftFingerprinter``) and oracle explorer
(``models/explore.py``) all stay where they are — this module only
assembles them into the operator surface the engines consume, and owns
the two tables that used to be hard-wired into ``engine/expand.py``:

  * the action-family registry (``build_families``) — each family now
    carries its guard-algebra declaration (the signed-weight/threshold
    row of the PR-8 int8 guard matmul) instead of the old if/elif chain
    inside ``Expander._build_guard_matrix``; a new family without a
    declaration fails at Expander construction naming THIS spec;
  * the per-family enabled-lane density table (``FAMILY_DENSITY``) —
    the raft-measured buffer-sizing caps ``--fam-cap-density``
    overrides, now namespaced per spec.

All existing Raft differential tests pin this assembly bit-exactly:
lane order, guard weights and densities are byte-identical to the
pre-IR ``engine/expand.py`` tables.
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..config import (NEXT_ASYNC_CRASH, NEXT_DYNAMIC, NEXT_FULL, Bounds)
from . import SpecIR


# ---------------------------------------------------------------------------
# Action families (moved verbatim from engine/expand.build_families),
# now with per-family guard-algebra declarations: each ``guard`` maps
# (feature-offset table, layout, *lane params) -> ([(index, weight)],
# threshold) over ops/kernels.guard_features — the exact rows the old
# Expander._build_guard_matrix if/elif chain produced.
# ---------------------------------------------------------------------------

def build_families(lay) -> List["Family"]:
    from ..config import CANDIDATE, FOLLOWER, LEADER, NIL, VALUE_ENTRY
    from ..engine.expand import Family, d_set
    from ..ops.codec import (C_GLOBLEN, C_NLEADERS, C_NREQ, C_OVERFLOW,
                             F_BL2_SEEN, F_LAST_RESTART_POS, F_LCDCC,
                             F_MIN_RESTART_GAP, F_NJBL)
    from ..ops.kernels import RaftKernels
    cfg = lay.cfg
    kern = RaftKernels(lay)
    S, K = lay.S, lay.K
    fams: List[Family] = []

    def grid(*ranges):
        arrs = np.meshgrid(*[np.asarray(r, np.int32) for r in ranges],
                           indexing="ij")
        return tuple(a.ravel() for a in arrs)

    ij = grid(range(S), range(S))
    ij_ne = tuple(a[ij[0] != ij[1]] for a in ij)        # i != j lanes
    iv = grid(range(S), list(cfg.values))
    i_ = grid(range(S))
    k_ = grid(range(K))

    # ---- delta-algebra declarations (the scatter-as-matmul successor
    # path, engine/expand delta-matrix comment).  The slot-affine
    # majority declares its writes as (slot, source, weight) triples
    # over the flat int32 state view; the data-dependent pieces ride
    # the kernels' delta_features (ops/kernels.delta_feature_offsets).
    # Bag inserts (RequestVote/AppendEntries/...), the Receive branch
    # family and AdvanceCommitIndex's quorum/prefix scan are genuinely
    # nonlinear — they declare NO delta and transparently keep the
    # per-family kernel path.  UpdateTerm (dst-one-hot set-difference
    # features) and Restart (its min-gap min folds into a
    # pre-differenced feature) joined the affine tail in round 17.

    def d_timeout(off, lay, i):
        F, FS = off["_feat"], off["_src_f"]
        X, C = off["_src_x"], off["_const"]
        return (
            d_set(off, off["st"] + i, CANDIDATE) +
            # ct' = min(ct+1, cap): the room feature IS the increment
            [(off["ct"] + i, FS + F["ctroom"] + i, 1)] +
            d_set(off, off["vf"] + i, NIL) +
            [(off["vr"] + i, X + off["vr"] + i, -1),
             (off["vg"] + i, X + off["vg"] + i, -1),
             (off["timeout"] + i, C, 1),
             # overflow = 1 - room
             (off["ctr"] + C_OVERFLOW, C, 1),
             (off["ctr"] + C_OVERFLOW, FS + F["ctroom"] + i, -1),
             (off["ctr"] + C_GLOBLEN, C, 1)])

    def d_become_leader(off, lay, i):
        F, FS = off["_feat"], off["_src_f"]
        X, C = off["_src_x"], off["_const"]
        tr = d_set(off, off["st"] + i, LEADER)
        for j in range(lay.S):
            nij = off["ni"] + i * lay.S + j
            mij = off["mi"] + i * lay.S + j
            # ni' = 1 + llen[i]; mi' = 0
            tr += [(nij, C, 1), (nij, X + off["llen"] + i, 1),
                   (nij, X + nij, -1), (mij, X + mij, -1)]
        tr += [(off["ctr"] + C_NLEADERS, C, 1),
               # the three feat maxes, pre-differenced in the features
               (off["feat"] + F_BL2_SEEN, FS + F["bl2"] + i, 1),
               (off["feat"] + F_NJBL, FS + F["njbl"] + i, 1),
               (off["feat"] + F_LCDCC, FS + F["lcdcc"], 1),
               (off["ctr"] + C_GLOBLEN, C, 1)]
        return tr

    def d_client_request(off, lay, i, v):
        F, FS, C = off["_feat"], off["_src_f"], off["_const"]
        vb = lay.value_bits
        cv = (VALUE_ENTRY << vb) | int(v)     # the term-free entry bits
        tshift = 1 << (1 + vb)                # term field scale
        tr = []
        for p in range(lay.Lcap):
            lp = off["log"] + i * lay.Lcap + p
            fp = i * lay.Lcap + p
            # log[i, llen] = pack_entry(ct, VALUE_ENTRY, v): the llen
            # one-hot places it, × ct scales the term field, × old log
            # word cancels the overwritten value — overflow zeroes all
            tr += [(lp, FS + F["croh"] + fp, cv),
                   (lp, FS + F["crohct"] + fp, tshift),
                   (lp, FS + F["crohold"] + fp, -1)]
        tr += [(off["llen"] + i, FS + F["crroom"] + i, 1),
               (off["ctr"] + C_NREQ, C, 1),
               (off["ctr"] + C_OVERFLOW, C, 1),
               (off["ctr"] + C_OVERFLOW, FS + F["crroom"] + i, -1)]
        return tr

    def d_update_term(off, lay, k):
        # ct[dst]=mterm, st[dst]=FOLLOWER, vf[dst]=NIL: the [K, S]
        # dst-one-hot set-difference features carry (new - old) per
        # server, so each write is one ADD row per (slot, server); the
        # message is NOT consumed and glob does not advance — exactly
        # kernels.update_term
        F, FS = off["_feat"], off["_src_f"]
        tr = []
        for j in range(lay.S):
            kj = k * lay.S + j
            tr += [(off["ct"] + j, FS + F["utdct"] + kj, 1),
                   (off["st"] + j, FS + F["utdst"] + kj, 1),
                   (off["vf"] + j, FS + F["utdvf"] + kj, 1)]
        return tr

    def d_restart(off, lay, i):
        F, FS = off["_feat"], off["_src_f"]
        X, C = off["_src_x"], off["_const"]
        tr = d_set(off, off["st"] + i, FOLLOWER) + [
            (off["vr"] + i, X + off["vr"] + i, -1),
            (off["vg"] + i, X + off["vg"] + i, -1),
            (off["ci"] + i, X + off["ci"] + i, -1)]
        for j in range(lay.S):
            nij = off["ni"] + i * lay.S + j
            mij = off["mi"] + i * lay.S + j
            # ni' = 1; mi' = 0 (nextIndex/matchIndex reset)
            tr += [(nij, C, 1), (nij, X + nij, -1),
                   (mij, X + mij, -1)]
        tr += [(off["restarted"] + i, C, 1),
               # last_restart_pos' = globlen + 1 (set via cancel-old)
               (off["feat"] + F_LAST_RESTART_POS, C, 1),
               (off["feat"] + F_LAST_RESTART_POS,
                X + off["ctr"] + C_GLOBLEN, 1),
               (off["feat"] + F_LAST_RESTART_POS,
                X + off["feat"] + F_LAST_RESTART_POS, -1),
               # min_restart_gap' = min(old, gap): pre-differenced
               (off["feat"] + F_MIN_RESTART_GAP, FS + F["rgap"], 1),
               (off["ctr"] + C_GLOBLEN, C, 1)]
        return tr

    def d_duplicate(off, lay, k):
        return [(off["cnt"] + k, off["_const"], 1)]

    def d_drop(off, lay, k):
        X = off["_src_x"]
        tr = [(off["cnt"] + k, X + off["cnt"] + k, -1)]
        for w in range(lay.msg_words):
            bw = off["bag"] + k * lay.msg_words + w
            tr.append((bw, X + bw, -1))
        return tr

    fams.append(Family(
        "RequestVote", kern.request_vote, ij,
        lambda i, j: f"RequestVote({i},{j})",
        guard=lambda off, lay, i, j: (
            [(off["cand"] + i, 1), (off["needvote"] + i * lay.S + j, 1)],
            2)))
    fams.append(Family(
        "BecomeLeader", kern.become_leader, i_,
        lambda i: f"BecomeLeader({i})",
        guard=lambda off, lay, i: (
            [(off["cand"] + i, 1), (off["blq"] + i, 1)], 2),
        delta=d_become_leader))
    fams.append(Family(
        "ClientRequest", kern.client_request, iv,
        lambda i, v: f"ClientRequest({i},{v})",
        guard=lambda off, lay, i, v: ([(off["leader"] + i, 1)], 1),
        delta=d_client_request))
    fams.append(Family(
        "AdvanceCommitIndex", kern.advance_commit_index, i_,
        lambda i: f"AdvanceCommitIndex({i})",
        guard=lambda off, lay, i: ([(off["leader"] + i, 1)], 1)))
    fams.append(Family(
        "AppendEntries", kern.append_entries, ij_ne,
        lambda i, j: f"AppendEntries({i},{j})",
        guard=lambda off, lay, i, j: (
            [(off["leader"] + i, 1), (off["cfg"] + i * lay.S + j, 1)],
            2)))
    fams.append(Family(
        "UpdateTerm", kern.update_term, k_,
        lambda k: f"UpdateTerm[slot{k}]",
        guard=lambda off, lay, k: ([(off["ut"] + k, 1)], 1),
        delta=d_update_term))
    fams.append(Family(
        "CocDiscard", kern.coc_discard, k_,
        lambda k: f"CocDiscard[slot{k}]",
        guard=lambda off, lay, k: ([(off["cocd"] + k, 1)], 1)))
    fams.append(Family(
        "Receive", kern.receive_main, k_,
        lambda k: f"Receive[slot{k}]",
        guard=lambda off, lay, k: ([(off["recv"] + k, 1)], 1)))
    fams.append(Family(
        "Timeout", kern.timeout, i_,
        lambda i: f"Timeout({i})",
        guard=lambda off, lay, i: (
            [(off["folc"] + i, 1), (off["cfg"] + i * lay.S + i, 1)], 2),
        delta=d_timeout))
    if cfg.next_family in (NEXT_ASYNC_CRASH, NEXT_FULL, NEXT_DYNAMIC):
        fams.append(Family(
            "Restart", lambda sv, der, i: kern.restart(sv, i), i_,
            lambda i: f"Restart({i})",
            guard=lambda off, lay, i: ([], 0),    # unconditional
            delta=d_restart))
    if cfg.next_family in (NEXT_FULL, NEXT_DYNAMIC):
        fams.append(Family(
            "Duplicate", lambda sv, der, k: kern.duplicate_message(sv, k),
            k_, lambda k: f"Duplicate[slot{k}]",
            guard=lambda off, lay, k: ([(off["cnt1"] + k, 1)], 1),
            delta=d_duplicate))
        fams.append(Family(
            "Drop", lambda sv, der, k: kern.drop_message(sv, k),
            k_, lambda k: f"Drop[slot{k}]",
            guard=lambda off, lay, k: ([(off["cnt1"] + k, 1)], 1),
            delta=d_drop))
    if cfg.next_family == NEXT_DYNAMIC:
        fams.append(Family(
            "AddNewServer", kern.add_new_server, ij,
            lambda i, j: f"AddNewServer({i},{j})",
            # j ∉ config enters with weight -1 and no threshold share
            guard=lambda off, lay, i, j: (
                [(off["leader"] + i, 1),
                 (off["cfg"] + i * lay.S + j, -1)], 1)))
        fams.append(Family(
            "DeleteServer", kern.delete_server, ij_ne,
            lambda i, j: f"DeleteServer({i},{j})",
            guard=lambda off, lay, i, j: (
                [(off["leader"] + i, 1), (off["folc"] + j, 1),
                 (off["cfg"] + i * lay.S + j, 1)], 3)))
    return fams


# Expected enabled-lane density per parent state, by family (measured
# on the BASELINE configs; engine/expand sizes the per-family
# materialization buffers from these — cap_f = chunk * min(lanes, d)).
# Throughput tuning, not correctness bounds: overflow grows + replays.
FAMILY_DENSITY = {
    "Restart": 1 << 30, "Timeout": 1 << 30,
    "RequestVote": 2, "BecomeLeader": 1, "ClientRequest": 2,
    "AdvanceCommitIndex": 2, "AppendEntries": 2,
    "UpdateTerm": 2, "CocDiscard": 1, "Receive": 4,
    "Duplicate": 4, "Drop": 4, "AddNewServer": 2, "DeleteServer": 2,
}


# ---------------------------------------------------------------------------
# The sim engine's punctuated-restart progress ladder (moved from
# sim/walker._progress_T): leader elected < membership changes appended
# < latest-ConfigEntry replication count at a current leader.
# ---------------------------------------------------------------------------

_SCORE_LEADER = 1 << 20
_SCORE_NMC = 1 << 10


def sim_progress(kern, lay):
    import jax
    import jax.numpy as jnp

    from ..config import LEADER
    from ..ops.codec import C_NLEADERS, C_NMC

    def score(svT):
        derT = jax.vmap(kern.derived, in_axes=-1, out_axes=-1)(svT)
        leader_seen = (svT["ctr"][C_NLEADERS] > 0).astype(jnp.int32)
        nmc = svT["ctr"][C_NMC]
        maxcfg = derT["maxcfg"]                       # [S, W]
        repl = jnp.sum(svT["mi"] >= maxcfg[:, None, :],
                       axis=1, dtype=jnp.int32)       # [S, W]
        is_l = (svT["st"] == LEADER) & (maxcfg > 0)
        repl = jnp.max(jnp.where(is_l, repl, 0), axis=0)
        return leader_seen * _SCORE_LEADER + \
            jnp.minimum(nmc, _SCORE_LEADER // _SCORE_NMC - 1) * \
            _SCORE_NMC + jnp.minimum(repl, _SCORE_NMC - 1)

    return score


# Which search bound each Bounded* constraint guards: a bound may pad
# up to the serving ceiling ONLY while its constraint is active —
# without the constraint the bound is load-bearing in the kernels'
# representability clamps (ops/kernels.term_cap), and padding it would
# change the reachable set.  An inactive constraint keeps the bound
# exact in the ceiling, so such jobs simply bucket by exact value.
_BOUND_CONSTRAINTS = {
    "max_log_length": "BoundedLogSize",
    "max_restarts": "BoundedRestarts",
    "max_timeouts": "BoundedTimeouts",
    "max_terms": "BoundedTerms",
    "max_client_requests": "BoundedClientRequests",
    "max_tried_membership_changes": "BoundedTriedMembershipChanges",
    "max_membership_changes": "BoundedMembershipChanges",
}


def serve_bucket(cfg):
    """Bucket ceiling for the batched serving layer (serve/batch).

    Round 13 — constant-padding ceilings: every constraint-guarded
    search bound pads up to the shared rung ladder (``spec.pad_rung``)
    so heterogeneous tenants (differing MaxTerm/MaxTimeouts/... under
    the stock constraint set) land in ONE bucket and share one
    AOT-compiled program.  The int8 guard matrix, the delta matrices
    and the packed layout compile at the CEILING's widths; each job's
    own bounds ride the runtime-bounds vector (``serve_runtime``
    below) into the constraint predicates, and the witness-bearing
    clamps (terms, log room) stay at the ceiling's representability
    width — exact, because a constraint-pruned state is never expanded
    in either layout, so an in-bounds job can never reach a clamp.

    Bounds WITHOUT their guarding constraint stay exact in the ceiling
    (see _BOUND_CONSTRAINTS); structural constants (servers, values,
    NEXT family, rounds, the predicate name lists, symmetry/fp128)
    always key the bucket exactly — padding those would change the
    compiled operator surface itself.  max_trace stays exact too: it
    backs the BoundedTrace *scenario invariant*, whose verdict is part
    of the job's answer.

    The params size the per-job rings for small serving jobs: ring =
    4 * chunk frontier rows per job, a 2^15-slot visited table
    (~13k keys at the 0.40 load bound).  A job outgrowing either bails
    to the sequential fallback."""
    from . import pad_rung
    b = cfg.bounds
    cons = set(cfg.constraints)

    def pad(name):
        # floor 4: every bound in the small-serving range rounds onto
        # ONE rung (raft bound padding only widens bit-packed fields,
        # so a generous floor is near-free and maximizes bucket hits)
        v = getattr(b, name)
        return pad_rung(v, floor=4) if _BOUND_CONSTRAINTS[name] in cons \
            else v

    ceiling_bounds = Bounds(
        max_log_length=pad("max_log_length"),
        max_restarts=pad("max_restarts"),
        max_timeouts=pad("max_timeouts"),
        max_client_requests=pad("max_client_requests"),
        max_membership_changes=pad("max_membership_changes"),
        max_terms=pad("max_terms"),
        max_tried_membership_changes=pad(
            "max_tried_membership_changes"),
        max_trace=b.max_trace)
    kw = {}
    if ceiling_bounds != b:
        kw["bounds"] = ceiling_bounds
    if cfg.max_inflight_override is not None and \
            "BoundedInFlightMessages" in cons:
        # the override is a real bound (shape-bearing via bag slots):
        # pad it like the rest; the derived 2*S^2 default is a formula
        # over the structural |Server| and stays as-is
        padded = pad_rung(cfg.max_inflight_override)
        if padded != cfg.max_inflight_override:
            kw["max_inflight_override"] = padded
    ceiling = cfg.with_(**kw) if kw else cfg
    return ceiling, dict(chunk=128, vcap=1 << 15, burst_levels=8)


def serve_runtime(expander, cfg):
    """The job's runtime-thresholds data under the bucket's ceiling
    expander (SpecIR.serve_runtime contract): ceiling guard thresholds
    as device data, an all-enabled lane mask (raft lane grids are
    structural — servers/values/bag slots — and bag-slot lanes must
    stay enabled even under a padded MaxInFlight, since occupancy of
    the first K_job slots drives them), and the job's own search
    bounds for the constraint predicates."""
    from ..ops.vpredicates import runtime_bounds
    thr, mask = expander.runtime_thresholds()
    return dict(thr=thr, mask=mask, bounds=runtime_bounds(cfg))


# ---------------------------------------------------------------------------
# IR assembly
# ---------------------------------------------------------------------------

def build_ir() -> SpecIR:
    from ..models import predicates as OP
    from ..models.explore import (_walk_key, explore, symmetry_perms)
    from ..models.golden import prefix_pin_seeds
    from ..models.raft import (init_state, state_from_obj, state_to_obj,
                               successors)
    from ..ops import codec
    from ..ops.layout import Layout
    from ..ops.kernels import RaftKernels
    from ..ops.vpredicates import (CONSTRAINTS as VC, INVARIANTS as VI,
                                   Predicates, SCENARIO_PROPERTIES)

    def make_fingerprinter(cfg, sym_canon="minperm"):
        from ..engine.fingerprint import RaftFingerprinter
        return RaftFingerprinter(cfg, sym_canon=sym_canon)

    def server_signature(fpr, svT, prep):
        from ..engine.fingerprint import raft_server_signature
        return raft_server_signature(fpr, svT, prep)

    return SpecIR(
        name="raft",
        version=1,
        make_layout=Layout,
        init_state=init_state,
        encode=codec.encode,
        decode=codec.decode,
        narrow=codec.narrow,
        widen=codec.widen,
        view_keys=codec.VIEW_KEYS,
        nonview_keys=codec.NONVIEW_KEYS,
        state_to_obj=state_to_obj,
        state_from_obj=state_from_obj,
        make_kernels=RaftKernels,
        build_families=build_families,
        family_density=dict(FAMILY_DENSITY),
        make_predicates=Predicates,
        scenario_properties=SCENARIO_PROPERTIES,
        known_invariants=frozenset(VI) | frozenset(OP.INVARIANTS),
        known_constraints=frozenset(VC) | frozenset(OP.CONSTRAINTS),
        known_action_constraints=frozenset(OP.ACTION_CONSTRAINTS),
        glob_dependent=frozenset(OP.GLOB_DEPENDENT),
        make_fingerprinter=make_fingerprinter,
        symmetry_perms=symmetry_perms,
        server_signature=server_signature,
        oracle_explore=explore,
        oracle_successors=successors,
        oracle_walk_key=_walk_key,
        prefix_pin_seeds=prefix_pin_seeds,
        sim_progress=sim_progress,
        default_config=None,
        serve_bucket=serve_bucket,
        serve_runtime=serve_runtime,
    )
