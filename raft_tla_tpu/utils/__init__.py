"""Shared host-side helpers used across the engines.

Device-side math lives in ops/ and engine/fingerprint; these are the
small numpy/python twins the BFS drivers share (engine/bfs re-exports
them under its historical names for backward compatibility).
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np


# the probe-walk contract every visited-table image shares (device
# tables in engine/bfs + engine/spill, host partitions in
# engine/host_table): home slot = fmix32-fold of the key streams
# seeded with this salt.  ONE definition — a drifted twin would walk
# different probe chains on host vs device and silently inflate
# distinct counts.
HOME_SALT = 0x9E3779B9


def fmix32_int(x: int) -> int:
    """Host twin of engine.fingerprint.fmix32 (murmur3 finalizer) on
    plain ints — used for host-side probe placement of root/seed keys."""
    x &= 0xFFFFFFFF
    x ^= x >> 16
    x = (x * 0x85EBCA6B) & 0xFFFFFFFF
    x ^= x >> 13
    x = (x * 0xC2B2AE35) & 0xFFFFFFFF
    x ^= x >> 16
    return x


def fmix32_np(x: np.ndarray) -> np.ndarray:
    """Vectorized numpy twin of the same finalizer — host-side image
    building/probing over whole key arrays (engine/host_table)."""
    x = x.astype(np.uint32, copy=True)
    x ^= x >> np.uint32(16)
    x *= np.uint32(0x85EBCA6B)
    x ^= x >> np.uint32(13)
    x *= np.uint32(0xC2B2AE35)
    x ^= x >> np.uint32(16)
    return x


def cat_arrays(chunks: List[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
    """Concatenate a list of SoA dicts along the batch axis."""
    return {k: np.concatenate([c[k] for c in chunks]) for k in chunks[0]}


def take_arrays(arrs: Dict[str, np.ndarray], idx) -> Dict[str, np.ndarray]:
    """Row-select every array of an SoA dict."""
    return {k: v[idx] for k, v in arrs.items()}


def combine_u64(fp: np.ndarray) -> np.ndarray:
    """[N, n_streams] u32 -> [N, n_streams//2] u64 words (a single u64
    column for the default 2-stream mode) — the canonical bit layout of
    the dedup key (engine.fingerprint re-exports this)."""
    fp = np.asarray(fp, dtype=np.uint64)
    return (fp[:, 0::2] << np.uint64(32)) | fp[:, 1::2]


def fp_key(fp_u32: np.ndarray) -> np.ndarray:
    """[N, n_streams] u32 -> 1-D sortable dedup key covering ALL streams:
    plain u64 for the 2-stream default, a lexicographic structured array
    for fp128 (so the extra streams actually buy collision resistance)."""
    u64 = combine_u64(fp_u32)
    if u64.shape[1] == 1:
        return u64[:, 0]
    dtype = np.dtype([(f"w{i}", "<u8") for i in range(u64.shape[1])])
    return np.ascontiguousarray(u64).view(dtype)[:, 0]
