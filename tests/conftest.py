"""Test env: force JAX onto a virtual 8-device CPU mesh so multi-chip
sharding paths compile+run without TPU hardware (the driver separately
dry-runs the real multi-chip path via __graft_entry__.dryrun_multichip).

The axon TPU plugin in this image overrides ``JAX_PLATFORMS`` during its
sitecustomize registration, so the env var alone is not enough —
``jax.config.update`` after import is what actually selects CPU here.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Shared oracle-reference cache (round-13 suite diet): many files
# compare engines against the SAME (cfg, depth) oracle exploration —
# each Python BFS re-run costs seconds against the 870s tier-1 budget.
# Results are treated as READ-ONLY by every caller (counts /
# level_sizes / violations / kept states are only read).
# ---------------------------------------------------------------------------

_ORACLE_CACHE = {}


def cached_explore(cfg, **kw):
    """spec_of(cfg).oracle_explore(cfg, **kw), memoized per (spec,
    cfg repr, kwargs) for the whole session."""
    from raft_tla_tpu.spec import spec_of
    ir = spec_of(cfg)
    key = (ir.name, repr(cfg), tuple(sorted(kw.items())))
    if key not in _ORACLE_CACHE:
        _ORACLE_CACHE[key] = ir.oracle_explore(cfg, **kw)
    return _ORACLE_CACHE[key]


def ref_or_local(path: str) -> str:
    """A reference model path (/root/reference/...), falling back to
    the repo-local twin under configs/ when the reference tree is not
    shipped in this container (tests/test_sim.py pins that the twin
    parses identically).  Tests needing the FULL reference spec text
    (e.g. TLC emit vendoring) should skip instead — the twins carry
    only the cfg + the bound-constant stub the parser scans."""
    if os.path.exists(path):
        return path
    local = os.path.join(_REPO, "configs",
                         os.path.relpath(path, "/root/reference"))
    return local if os.path.exists(local) else path
