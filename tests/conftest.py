"""Test env: force JAX onto a virtual 8-device CPU mesh so multi-chip
sharding paths compile+run without TPU hardware (the driver separately
dry-runs the real multi-chip path via __graft_entry__.dryrun_multichip).

The axon TPU plugin in this image overrides ``JAX_PLATFORMS`` during its
sitecustomize registration, so the env var alone is not enough —
``jax.config.update`` after import is what actually selects CPU here.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def ref_or_local(path: str) -> str:
    """A reference model path (/root/reference/...), falling back to
    the repo-local twin under configs/ when the reference tree is not
    shipped in this container (tests/test_sim.py pins that the twin
    parses identically).  Tests needing the FULL reference spec text
    (e.g. TLC emit vendoring) should skip instead — the twins carry
    only the cfg + the bound-constant stub the parser scans."""
    if os.path.exists(path):
        return path
    local = os.path.join(_REPO, "configs",
                         os.path.relpath(path, "/root/reference"))
    return local if os.path.exists(local) else path
