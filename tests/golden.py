"""Golden witness traces — re-exported from the package.

The label sequences moved to ``raft_tla_tpu.models.golden`` when the
cfg-level prefix pins (``CommitWhenConcurrentLeaders_unique`` /
``MajorityOfClusterRestarts_constraint``, raft.tla:1198-1234) started
compiling into engine seeds; tests import them from here unchanged.
"""

from raft_tla_tpu.models.golden import (  # noqa: F401
    CONCURRENT_LEADERS_LABELS, CWCL_EXTENSION_LABELS, GOLDEN_20_KINDS,
    GOLDEN_28_KINDS)
