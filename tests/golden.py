"""Golden witness traces — the reference's punctuated-search fixtures.

The reference embeds two hard-coded witness traces as search-prefix pins
(tlc_membership/raft.tla):

  * ConcurrentLeaders witness, 20 history records, inside
    ``CommitWhenConcurrentLeaders_unique`` (raft.tla:1198-1204)
  * CommitWhenConcurrentLeaders witness, 28 history records, inside
    ``MajorityOfClusterRestarts_constraint`` (raft.tla:1228-1234)

Here they are re-expressed as oracle successor-label sequences (with the
reference's s1,s2,s3 mapped to server ids 0,1,2).  History records are
emitted by Send/Discard/Reply and the named actions — one top-level step
can emit 0, 1 or 2 records (e.g. ``UpdateTerm`` consumes nothing and logs
nothing, raft.tla:826-832; a Reply logs Receive + Send, raft.tla:308-314)
— so 18 labels produce the 20-record trace and 9 more labels produce
records 21-28.
"""

# --- records 1-20: two elections ending with concurrent leaders --------
# r2/r3: s1 sends RVReq to s2 first, then to itself (golden record order).
# r8/r9 and r18/r19: the remote vote response is received before the
# self-response.
CONCURRENT_LEADERS_LABELS = [
    "Timeout(0)",           # r1
    "RequestVote(0,1)",     # r2   Send RVReq 0->1
    "RequestVote(0,0)",     # r3   Send RVReq 0->0
    "HandleRVReq(0<-0)",    # r4,r5   Receive + Send RVResp (self grant)
    "UpdateTerm(1)",        # (no record; non-consuming, raft.tla:831)
    "HandleRVReq(1<-0)",    # r6,r7
    "HandleRVResp(0<-1)",   # r8
    "HandleRVResp(0<-0)",   # r9
    "BecomeLeader(0)",      # r10  leaders={0}
    "Timeout(1)",           # r11
    "RequestVote(1,1)",     # r12  Send RVReq 1->1 (self first, golden)
    "RequestVote(1,2)",     # r13
    "HandleRVReq(1<-1)",    # r14,r15
    "UpdateTerm(2)",        # (no record)
    "HandleRVReq(2<-1)",    # r16,r17
    "HandleRVResp(1<-2)",   # r18
    "HandleRVResp(1<-1)",   # r19
    "BecomeLeader(1)",      # r20  leaders={0,1}
]

# --- records 21-28: both leaders replicate; commit under 2 leaders -----
# ClientRequest bumps hadNumClientRequests but logs no record
# (raft.tla:488-497); AENoConflict appends without reply or record
# (raft.tla:668-672) — the success reply comes from the *second* receive
# of the same request (AlreadyDone, raft.tla:639-655).
CWCL_EXTENSION_LABELS = [
    "ClientRequest(0,1)",       # log[0] = [(2, Value, 1)]
    "AppendEntries(0,1)",       # r21  Send AEReq 0->1 (entry term 2)
    "ClientRequest(1,2)",       # log[1] = [(3, Value, 2)]
    "AppendEntries(1,2)",       # r22  Send AEReq 1->2 (entry term 3)
    "AENoConflict(2)",          # (no record) s2 appends the entry
    "AEAlreadyDone(2)",         # r23,r24  Receive + Send success reply
    "HandleAEResp(1<-2)",       # r25  matchIndex[1][2] := 1
    "AdvanceCommitIndex(1)",    # r26  CommitEntry (term 3, value 2)
    "RejectAEReq(1)",           # r27,r28  stale-term AEReq from s1
]

GOLDEN_20_KINDS = [
    "Timeout", "Send", "Send", "Receive", "Send", "Receive", "Send",
    "Receive", "Receive", "BecomeLeader",
    "Timeout", "Send", "Send", "Receive", "Send", "Receive", "Send",
    "Receive", "Receive", "BecomeLeader",
]

GOLDEN_28_KINDS = GOLDEN_20_KINDS + [
    "Send", "Send", "Receive", "Send", "Receive", "CommitEntry",
    "Receive", "Send",
]
