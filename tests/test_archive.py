"""Disk-backed per-level trace archives (engine/archive): the memmap'd
files must replay traces bit-identically to the historical in-RAM
archive path, survive checkpoint resume via attach+truncate, and keep
the growing per-level arrays OFF the host heap (the round-5 ~21 GB
trace-archive ceiling, BASELINE.md)."""

import json
import os

import numpy as np
import pytest

from raft_tla_tpu.config import Bounds, ModelConfig, NEXT_ASYNC
from raft_tla_tpu.engine.archive import ArchiveError, DiskArchive

MICRO = ModelConfig(
    n_servers=2, init_servers=(0, 1), values=(1,),
    next_family=NEXT_ASYNC, symmetry=True, max_inflight_override=4,
    bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                       max_client_requests=1))


# -- unit level: the file format round-trips exactly -------------------


def _mk_level(rng, n, with_matrix=True):
    parents = rng.integers(-1, 50, size=n).astype(np.int32)
    lanes = rng.integers(-1, 8, size=n).astype(np.int32)
    states = {"ct": rng.integers(0, 5, size=n).astype(np.int8),
              "votes": rng.integers(0, 2, size=(n, 3)).astype(np.uint8)}
    if not with_matrix:
        states.pop("votes")
    return parents, lanes, states


@pytest.mark.smoke
def test_disk_archive_roundtrip_batch_major(tmp_path):
    rng = np.random.default_rng(5)
    arch = DiskArchive(str(tmp_path / "run"))
    levels = [_mk_level(rng, n) for n in (3, 17, 1)]
    for par, lane, st in levels:
        arch.append_level(par, lane, st)
    assert arch.n_levels == 3 and arch.total_rows == 21
    for i, (par, lane, st) in enumerate(levels):
        np.testing.assert_array_equal(arch.parents(i), par)
        np.testing.assert_array_equal(arch.lanes(i), lane)
        got = arch.states(i)
        for k in st:
            np.testing.assert_array_equal(got[k], st[k])
    # global-id addressing crosses level boundaries
    assert arch.locate(0) == (0, 0)
    assert arch.locate(3) == (1, 0)
    assert arch.locate(20) == (2, 0)
    par, lane = arch.parent_lane(4)
    assert (par, lane) == (int(levels[1][0][1]), int(levels[1][1][1]))
    row = arch.state_row(5)
    np.testing.assert_array_equal(row["ct"], levels[1][2]["ct"][2])


@pytest.mark.smoke
def test_disk_archive_parts_stream_batch_last(tmp_path):
    """Spill parts arrive batch-LAST (the device block layout) and may
    be over-allocated past n; the archive must transpose and trim
    per part without a whole-level concat buffer."""
    rng = np.random.default_rng(9)
    arch = DiskArchive(str(tmp_path / "run"))
    par, lane, st = _mk_level(rng, 10)
    parts = []
    for lo, hi in ((0, 4), (4, 10)):
        m = hi - lo
        pad = 3                      # over-allocated tail, must be cut
        rows = {k: np.moveaxis(
            np.concatenate([v[lo:hi], v[:pad]]), 0, -1)
            for k, v in st.items()}
        parts.append(dict(n=m, lpar=np.concatenate(
            [par[lo:hi], par[:pad]]),
            llane=np.concatenate([lane[lo:hi], lane[:pad]]),
            rows=rows))
    arch.append_level_parts(parts)
    np.testing.assert_array_equal(arch.parents(0), par)
    np.testing.assert_array_equal(arch.lanes(0), lane)
    for k, v in st.items():
        np.testing.assert_array_equal(arch.states(0)[k], v)


@pytest.mark.smoke
def test_disk_archive_attach_truncate_resume(tmp_path):
    """attach=True reopens a killed run's completed levels; truncate
    drops levels past a checkpoint so the resumed run re-appends them
    — and refuses an archive shorter than the checkpoint expects."""
    rng = np.random.default_rng(13)
    root = str(tmp_path / "run")
    arch = DiskArchive(root)
    levels = [_mk_level(rng, n) for n in (4, 6, 5)]
    for par, lane, st in levels:
        arch.append_level(par, lane, st)
    re = DiskArchive(root, attach=True)
    assert re.level_rows == [4, 6, 5]
    re.truncate(1)
    assert re.n_levels == 1 and not os.path.exists(
        os.path.join(root, "lvl0001.parents.npy"))
    np.testing.assert_array_equal(re.parents(0), levels[0][0])
    with pytest.raises(ArchiveError, match="wrong"):
        re.truncate(3)
    with pytest.raises(ArchiveError, match="not a readable"):
        DiskArchive(str(tmp_path / "nope"), attach=True)
    # meta is rewritten atomically: no .tmp survives a clean append
    assert not os.path.exists(os.path.join(root, "meta.json.tmp"))
    assert json.load(open(os.path.join(root, "meta.json")))[
        "level_rows"] == [4]


# -- engine level: disk path ≡ in-RAM path on a violation trace --------


def test_engine_trace_roundtrip_disk_vs_ram(tmp_path):
    """The satellite's core claim: a violation trace replayed through
    the memmap'd per-level files matches the in-RAM archive path
    exactly — labels, states, and every archived row."""
    from raft_tla_tpu.engine.bfs import Engine
    cfg = MICRO.with_(invariants=("FirstBecomeLeader",))
    e_ram = Engine(cfg, chunk=64, store_states=True)
    r_ram = e_ram.check(stop_on_violation=True)
    e_dsk = Engine(cfg, chunk=64, store_states=True,
                   archive_dir=str(tmp_path / "arch"))
    r_dsk = e_dsk.check(stop_on_violation=True)
    assert r_dsk.distinct_states == r_ram.distinct_states
    assert r_dsk.violations[0].state_id == r_ram.violations[0].state_id

    # the disk engine holds NO in-RAM archive — rows live on disk only
    assert e_dsk._states == [] and e_dsk._parents == []
    assert e_dsk._arch.total_rows == r_dsk.distinct_states

    gid = r_dsk.violations[0].state_id
    tr_ram, tr_dsk = e_ram.trace(gid), e_dsk.trace(gid)
    assert [lbl for lbl, _s in tr_dsk] == [lbl for lbl, _s in tr_ram]
    assert [s for _l, s in tr_dsk] == [s for _l, s in tr_ram]
    # and every archived row matches, not just the witness chain
    for g in range(r_dsk.distinct_states):
        ram_row = e_ram.get_state_arrays(g)
        dsk_row = e_dsk.get_state_arrays(g)
        for k in ram_row:
            np.testing.assert_array_equal(ram_row[k], dsk_row[k])


@pytest.mark.slow
def test_spill_engine_archive_dir_and_resume(tmp_path):
    """SpillEngine + archive_dir: spilled parts stream to the memmaps
    (batch-last path), traces replay, and a checkpoint resume
    reattaches the SAME archive dir — truncating past-checkpoint
    levels so the resumed run is bit-identical."""
    from raft_tla_tpu.engine.bfs import CheckpointError
    from raft_tla_tpu.engine.spill import SpillEngine
    cfg = MICRO.with_(invariants=("FirstBecomeLeader",))
    kw = dict(chunk=64, store_states=True, seg=1 << 10, vcap=1 << 12,
              sync_every=2)
    e_ram = SpillEngine(cfg, **kw)
    r_ram = e_ram.check()
    e_dsk = SpillEngine(cfg, archive_dir=str(tmp_path / "a1"), **kw)
    r_dsk = e_dsk.check()
    assert r_dsk.distinct_states == r_ram.distinct_states
    assert r_dsk.level_sizes == r_ram.level_sizes
    gid = r_dsk.violations[0].state_id
    assert [lbl for lbl, _s in e_dsk.trace(gid)] == \
        [lbl for lbl, _s in e_ram.trace(gid)]

    # checkpoint/resume reattaches the archive and stays identical
    ckpt = str(tmp_path / "s.ckpt")
    a2 = str(tmp_path / "a2")
    SpillEngine(cfg, archive_dir=a2, **kw).check(
        max_depth=8, checkpoint_path=ckpt)
    e_res = SpillEngine(cfg, archive_dir=a2, **kw)
    r_res = e_res.check(resume_from=ckpt)
    assert r_res.distinct_states == r_ram.distinct_states
    assert e_res._arch.total_rows == r_ram.distinct_states
    assert [lbl for lbl, _s in e_res.trace(gid)] == \
        [lbl for lbl, _s in e_ram.trace(gid)]
    # resuming a disk-archive checkpoint WITHOUT the dir is refused
    with pytest.raises(CheckpointError, match="archive"):
        SpillEngine(cfg, **kw).check(resume_from=ckpt)
