"""bench.py perf-regression floor (VERDICT r3 #5): a deliberate
slowdown trips the warn tier, a collapse below the measured noise band
zeroes the score, non-headline runs and foreign platforms skip, and a
new best ratchets the floor file."""

import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

spec = importlib.util.spec_from_file_location(
    "bench", os.path.join(REPO, "bench.py"))
bench = importlib.util.module_from_spec(spec)
sys.modules["bench"] = bench
spec.loader.exec_module(bench)


def floor_file(tmp_path, best=100000.0):
    p = tmp_path / "floor.json"
    p.write_text(json.dumps({"tlc_membership_S3_T3_L3": {
        "platform_prefix": "TPU", "machine": "test",
        "best_states_per_sec": best, "source": "test",
        "warn_frac": 0.6, "hard_frac": 0.3}}))
    return str(p)


def test_floor_trips_on_slowdown(tmp_path):
    fp = floor_file(tmp_path)
    # healthy rate: ok, not zeroed
    info, zero = bench.perf_floor(90000.0, bench.MAX_DEPTH, "TPU v5", fp)
    assert info["status"] == "ok" and not zero
    # deliberate slowdown (e.g. --chunk 64): warn tier trips
    info, zero = bench.perf_floor(45000.0, bench.MAX_DEPTH, "TPU v5", fp)
    assert info["status"] == "warn" and not zero
    # collapse below the noise band: score is zeroed
    info, zero = bench.perf_floor(10000.0, bench.MAX_DEPTH, "TPU v5", fp)
    assert info["status"] == "hard" and zero


def test_floor_skips_nonheadline_and_foreign_platform(tmp_path):
    fp = floor_file(tmp_path)
    info, zero = bench.perf_floor(10.0, 5, "TPU v5", fp)
    assert "skipped" in info["status"] and not zero
    info, zero = bench.perf_floor(10.0, bench.MAX_DEPTH, "cpu", fp)
    assert "skipped" in info["status"] and not zero
    # missing floor file: floor disabled, never zeroes
    info, zero = bench.perf_floor(10.0, bench.MAX_DEPTH, "TPU v5",
                                  str(tmp_path / "absent.json"))
    assert info is None and not zero


def test_floor_ratchets_on_new_best(tmp_path):
    fp = floor_file(tmp_path, best=50000.0)
    info, zero = bench.perf_floor(60000.0, bench.MAX_DEPTH, "TPU v5", fp)
    assert info["status"] == "ok" and not zero
    assert json.load(open(fp))["tlc_membership_S3_T3_L3"][
        "best_states_per_sec"] == 60000.0
    # a failing correctness gate must NOT ratchet the floor
    bench.perf_floor(99000.0, bench.MAX_DEPTH, "TPU v5", fp,
                     gate_ok=False)
    assert json.load(open(fp))["tlc_membership_S3_T3_L3"][
        "best_states_per_sec"] == 60000.0


import pytest

_FLOOR_KEYS = sorted(k for k in json.load(
    open(os.path.join(REPO, "BENCH_FLOOR.json"))) if k[0] != "_")


def test_floor_covers_every_measured_config():
    """VERDICT r4 #6: the configs rounds 3-5 fought for must each have
    a regression floor — a 3x collapse on any of them must not ship
    green via the headline row alone."""
    want = {"tlc_membership_S3_T3_L3", "config1_budgeted",
            "config2_budgeted", "config3_budgeted", "config4_budgeted",
            "config5_budgeted", "spill_config2_depth19"}
    assert want <= set(_FLOOR_KEYS), sorted(want - set(_FLOOR_KEYS))


@pytest.mark.parametrize("key", _FLOOR_KEYS)
def test_repo_floor_rows_are_valid(key):
    e = json.load(open(os.path.join(REPO, "BENCH_FLOOR.json")))[key]
    assert 0 < e["hard_frac"] < e["warn_frac"] < 1
    assert e["best_states_per_sec"] > 0
    assert e["platform_prefix"] and e["source"]


@pytest.mark.parametrize("key", _FLOOR_KEYS)
def test_floor_machinery_per_row(key, tmp_path):
    """Every row works through the same warn/hard/ratchet machinery."""
    p = tmp_path / "floor.json"
    p.write_text(json.dumps({key: {
        "platform_prefix": "TPU", "machine": "test",
        "best_states_per_sec": 100000.0, "source": "test",
        "warn_frac": 0.6, "hard_frac": 0.3}}))
    fp = str(p)
    info, zero = bench.perf_floor(45000.0, 0, "TPU v5", fp, key=key,
                                  headline_depth=0)
    assert info["status"] == "warn" and not zero
    info, zero = bench.perf_floor(10000.0, 0, "TPU v5", fp, key=key,
                                  headline_depth=0)
    assert info["status"] == "hard" and zero
    info, zero = bench.perf_floor(103000.0, 0, "TPU v5", fp, key=key,
                                  headline_depth=0, bump_source="t")
    assert info["status"] == "ok"
    assert json.load(open(fp))[key]["best_states_per_sec"] == 103000.0
