"""Small-level burst (engine/bfs._burst_impl): up to 16 whole BFS
levels per device call while the frontier fits one chunk.  The burst
must be an exact drop-in for the per-level driver — counts, level
sizes, archives, violations and checkpoints all bit-identical with
burst on vs off (and vs the Python oracle via the suite's existing
differential tests, which run with the default burst=True)."""

import numpy as np
import pytest

from raft_tla_tpu.config import Bounds, ModelConfig
from raft_tla_tpu.engine.bfs import Engine

MICRO = ModelConfig(
    n_servers=2, init_servers=(0, 1), values=(1,),
    max_inflight_override=4, symmetry=True,
    bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                       max_client_requests=1))

SMALL = ModelConfig(
    n_servers=3, init_servers=(0, 1, 2), values=(1, 2),
    max_inflight_override=4, symmetry=True,
    bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                       max_client_requests=1),
    constraints=("BoundedTimeouts", "BoundedClientRequests"))


# slow-marked (tier-1 budget, PR 2): the burst==driver A/B runs the
# space twice; the default burst path stays covered by
# test_burst_finds_violation and the engine micro differentials
@pytest.mark.slow
@pytest.mark.parametrize("cfg", [MICRO, SMALL], ids=["micro", "small"])
def test_burst_matches_per_level_driver(cfg):
    e_on = Engine(cfg, chunk=64, store_states=True, burst=True)
    r_on = e_on.check()
    e_off = Engine(cfg, chunk=64, store_states=True, burst=False)
    r_off = e_off.check()
    assert r_on.distinct_states == r_off.distinct_states
    assert r_on.generated_states == r_off.generated_states
    assert r_on.depth == r_off.depth
    assert r_on.level_sizes == r_off.level_sizes
    assert r_on.violations_global == r_off.violations_global
    # archives identical level by level, row by row (same enumeration
    # order => same global ids => identical traces)
    assert len(e_on._parents) == len(e_off._parents)
    for pa, pb in zip(e_on._parents, e_off._parents):
        np.testing.assert_array_equal(pa, pb)
    for la, lb in zip(e_on._lanes, e_off._lanes):
        np.testing.assert_array_equal(la, lb)
    for sa, sb in zip(e_on._states, e_off._states):
        assert sa.keys() == sb.keys()
        for k in sa:
            np.testing.assert_array_equal(sa[k], sb[k])


@pytest.mark.slow
def test_burst_respects_max_depth_and_budget():
    for md in (1, 3, 7):
        r_on = Engine(MICRO, chunk=64, store_states=False,
                      burst=True).check(max_depth=md)
        r_off = Engine(MICRO, chunk=64, store_states=False,
                       burst=False).check(max_depth=md)
        assert r_on.depth == r_off.depth == md
        assert r_on.distinct_states == r_off.distinct_states
        assert r_on.level_sizes == r_off.level_sizes
    # max_states stops at the same level boundary either way
    r_on = Engine(MICRO, chunk=64, store_states=False,
                  burst=True).check(max_states=50)
    r_off = Engine(MICRO, chunk=64, store_states=False,
                   burst=False).check(max_states=50)
    assert r_on.distinct_states == r_off.distinct_states
    assert r_on.depth == r_off.depth


@pytest.mark.slow
def test_burst_checkpoint_resume(tmp_path):
    full = Engine(MICRO, chunk=64, store_states=True,
                  burst=True).check()
    ckpt = str(tmp_path / "b.ckpt")
    e1 = Engine(MICRO, chunk=64, store_states=True, burst=True)
    part = e1.check(max_depth=6, checkpoint_path=ckpt)
    assert part.depth == 6
    # resume with burst OFF: the checkpoint format is driver-agnostic
    e2 = Engine(MICRO, chunk=64, store_states=True, burst=False)
    resumed = e2.check(resume_from=ckpt)
    assert resumed.distinct_states == full.distinct_states
    assert resumed.level_sizes == full.level_sizes


def test_burst_finds_violation():
    # a scenario property (negated reachability — FirstBecomeLeader
    # fires at the first leader election, a shallow burst-path level)
    # is found with its decoded state, and stop_on_violation stops
    # the run at the same state either way
    cfg = MICRO.with_(invariants=MICRO.invariants +
                      ("FirstBecomeLeader",))
    e_on = Engine(cfg, chunk=64, store_states=False, burst=True)
    r_on = e_on.check(stop_on_violation=True)
    e_off = Engine(cfg, chunk=64, store_states=False, burst=False)
    r_off = e_off.check(stop_on_violation=True)
    assert r_on.violations and r_off.violations
    v_on, v_off = r_on.violations[0], r_off.violations[0]
    assert v_on.invariant == v_off.invariant
    assert v_on.state_id == v_off.state_id
    assert v_on.state == v_off.state
