"""Fused multi-level burst (engine/bfs._burst_core and its engine
wrappers): up to burst_levels whole BFS levels per device call while
the frontier fits the burst ring (_burst_chunks frontier chunks).  The
burst must be an exact drop-in for the per-level driver in EVERY
engine — counts, level sizes, archives, violations, traces and
checkpoints all bit-identical with burst on vs off (and vs the Python
oracle via the suite's existing differential tests, which run with the
default burst=True)."""

import numpy as np
import pytest

from raft_tla_tpu.config import Bounds, ModelConfig, NEXT_ASYNC
from raft_tla_tpu.engine.bfs import Engine
from raft_tla_tpu.engine.spill import SpillEngine

MICRO = ModelConfig(
    n_servers=2, init_servers=(0, 1), values=(1,),
    max_inflight_override=4, symmetry=True,
    bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                       max_client_requests=1))

SMALL = ModelConfig(
    n_servers=3, init_servers=(0, 1, 2), values=(1, 2),
    max_inflight_override=4, symmetry=True,
    bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                       max_client_requests=1),
    constraints=("BoundedTimeouts", "BoundedClientRequests"))

# spill-engine micro (test_spill's shape: NEXT_ASYNC keeps the space
# small with segment capacities squeezed)
SPILL_MICRO = ModelConfig(
    n_servers=2, init_servers=(0, 1), values=(1,),
    next_family=NEXT_ASYNC, symmetry=True, max_inflight_override=4,
    bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                       max_client_requests=1))
SPILL_KW = dict(chunk=64, seg=1 << 10, vcap=1 << 12, sync_every=2)

# mesh micro (test_sharded's shape: VIEW-only constraints, where
# count parity is representative-insensitive by construction)
MESH_MICRO = ModelConfig(
    n_servers=2, init_servers=(0, 1), values=(1,),
    max_inflight_override=2, next_family=NEXT_ASYNC, symmetry=False,
    constraints=("BoundedInFlightMessages", "BoundedRequestVote",
                 "BoundedLogSize", "BoundedTerms"),
    invariants=("ElectionSafety", "LogMatching"),
    bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                       max_client_requests=1))


def _counts_match(a, b):
    assert a.distinct_states == b.distinct_states
    assert a.generated_states == b.generated_states
    assert a.depth == b.depth
    assert a.level_sizes == b.level_sizes
    assert a.violations_global == b.violations_global


def _archives_match(e_on, e_off):
    """Archives identical level by level, row by row (same enumeration
    order => same global ids => identical traces)."""
    assert len(e_on._parents) == len(e_off._parents)
    for pa, pb in zip(e_on._parents, e_off._parents):
        np.testing.assert_array_equal(pa, pb)
    for la, lb in zip(e_on._lanes, e_off._lanes):
        np.testing.assert_array_equal(la, lb)
    for sa, sb in zip(e_on._states, e_off._states):
        assert sa.keys() == sb.keys()
        for k in sa:
            np.testing.assert_array_equal(sa[k], sb[k])


# slow-marked (tier-1 budget, PR 2): the burst==driver A/B runs the
# space twice; the default burst path stays covered by
# test_burst_finds_violation and the engine micro differentials
@pytest.mark.slow
@pytest.mark.parametrize("cfg", [MICRO, SMALL], ids=["micro", "small"])
def test_burst_matches_per_level_driver(cfg):
    e_on = Engine(cfg, chunk=64, store_states=True, burst=True)
    r_on = e_on.check()
    e_off = Engine(cfg, chunk=64, store_states=True, burst=False)
    r_off = e_off.check()
    _counts_match(r_on, r_off)
    assert r_on.levels_fused > 0     # the fused path actually engaged
    _archives_match(e_on, e_off)


@pytest.mark.slow
def test_burst_respects_max_depth_and_budget():
    for md in (1, 3, 7):
        r_on = Engine(MICRO, chunk=64, store_states=False,
                      burst=True).check(max_depth=md)
        r_off = Engine(MICRO, chunk=64, store_states=False,
                       burst=False).check(max_depth=md)
        assert r_on.depth == r_off.depth == md
        assert r_on.distinct_states == r_off.distinct_states
        assert r_on.level_sizes == r_off.level_sizes
    # max_states stops at the same level boundary either way
    r_on = Engine(MICRO, chunk=64, store_states=False,
                  burst=True).check(max_states=50)
    r_off = Engine(MICRO, chunk=64, store_states=False,
                   burst=False).check(max_states=50)
    assert r_on.distinct_states == r_off.distinct_states
    assert r_on.depth == r_off.depth


@pytest.mark.slow
def test_burst_checkpoint_resume(tmp_path):
    full = Engine(MICRO, chunk=64, store_states=True,
                  burst=True).check()
    ckpt = str(tmp_path / "b.ckpt")
    e1 = Engine(MICRO, chunk=64, store_states=True, burst=True)
    part = e1.check(max_depth=6, checkpoint_path=ckpt)
    assert part.depth == 6
    # resume with burst OFF: the checkpoint format is driver-agnostic
    e2 = Engine(MICRO, chunk=64, store_states=True, burst=False)
    resumed = e2.check(resume_from=ckpt)
    assert resumed.distinct_states == full.distinct_states
    assert resumed.level_sizes == full.level_sizes


@pytest.mark.slow  # tier-1 budget (round 14): ~18s; violation +
# stop_on_violation parity under the (batched) burst core stays fast
# via test_serve::test_batched_violation_states_and_witness_parity.
def test_burst_finds_violation():
    # a scenario property (negated reachability — FirstBecomeLeader
    # fires at the first leader election, a shallow burst-path level)
    # is found with its decoded state, and stop_on_violation stops
    # the run at the same state either way
    cfg = MICRO.with_(invariants=MICRO.invariants +
                      ("FirstBecomeLeader",))
    e_on = Engine(cfg, chunk=64, store_states=False, burst=True)
    r_on = e_on.check(stop_on_violation=True)
    e_off = Engine(cfg, chunk=64, store_states=False, burst=False)
    r_off = e_off.check(stop_on_violation=True)
    assert r_on.violations and r_off.violations
    v_on, v_off = r_on.violations[0], r_off.violations[0]
    assert v_on.invariant == v_off.invariant
    assert v_on.state_id == v_off.state_id
    assert v_on.state == v_off.state


def test_burst_rejects_nonpositive_levels():
    with pytest.raises(ValueError, match="burst_levels"):
        Engine(MICRO, chunk=64, burst_levels=0)
    with pytest.raises(ValueError, match="burst_levels"):
        SpillEngine(SPILL_MICRO, burst_levels=-3)


# ---------------------------------------------------------------------
# fused multi-chunk dispatch (ISSUE 5): the dispatch-floor acceptance
# pin plus one fast burst≡per-level representative per engine family;
# the heavier full-space duplicates (archives, traces, checkpoints)
# are slow-marked per the ROADMAP tier-1 budget rule.
# ---------------------------------------------------------------------


@pytest.mark.smoke
def test_burst_dispatch_floor_tiny_levels():
    """The acceptance shape (config #3's 12 sub-ring early levels):
    12 levels cost <= 2 burst dispatches instead of 12 per-level round
    trips, with counts identical to the per-level driver — asserted
    via the new levels_fused stat."""
    r_on = Engine(MICRO, chunk=64, store_states=False,
                  burst=True).check(max_depth=12)
    r_off = Engine(MICRO, chunk=64, store_states=False,
                   burst=False).check(max_depth=12)
    _counts_match(r_on, r_off)
    assert r_on.depth == 12
    assert r_on.levels_fused == 12
    assert r_on.burst_dispatches <= 2
    assert r_off.levels_fused == 0 and r_off.burst_dispatches == 0


def test_spill_burst_matches_segment_driver():
    """Fast representative: the spill engine's fused path vs its
    segment driver on a bounded prefix of the space."""
    r_on = SpillEngine(SPILL_MICRO, store_states=False, burst=True,
                       **SPILL_KW).check(max_depth=10)
    r_off = SpillEngine(SPILL_MICRO, store_states=False, burst=False,
                        **SPILL_KW).check(max_depth=10)
    _counts_match(r_on, r_off)
    assert r_on.levels_fused > 0
    assert r_off.levels_fused == 0


@pytest.mark.slow
def test_sharded_burst_matches_level_driver():
    """The mesh engines' fused K-level driver vs the per-level
    shard_map program (8-virtual-device CPU mesh).  Slow-marked: two
    shard_map compiles per engine cost ~2 min on this container; the
    default tier-1 representative for the burst is the classic +
    spill pair above, and the existing default sharded differentials
    run with burst=True anyway (engaging the fused path against the
    oracle)."""
    from raft_tla_tpu.parallel.mesh import ShardedEngine
    r_on = ShardedEngine(MESH_MICRO, chunk=64, store_states=False,
                         burst=True).check(max_depth=10)
    r_off = ShardedEngine(MESH_MICRO, chunk=64, store_states=False,
                          burst=False).check(max_depth=10)
    _counts_match(r_on, r_off)
    assert r_on.levels_fused > 0
    assert r_off.levels_fused == 0


@pytest.mark.slow
def test_spill_burst_full_parity_archives_traces():
    """Full space: spill burst on/off counts, archives, violations AND
    witness-trace replay bit-identical (the burst's gid assignment
    must coincide with the spilled harvest order exactly)."""
    e_on = SpillEngine(SPILL_MICRO, store_states=True, burst=True,
                       **SPILL_KW)
    r_on = e_on.check()
    e_off = SpillEngine(SPILL_MICRO, store_states=True, burst=False,
                        **SPILL_KW)
    r_off = e_off.check()
    _counts_match(r_on, r_off)
    assert r_on.levels_fused > 0
    _archives_match(e_on, e_off)
    g = r_on.distinct_states - 1
    ta, tb = e_on.trace(g), e_off.trace(g)
    assert [l for l, _ in ta] == [l for l, _ in tb]
    assert all(sa == sb for (_, sa), (_, sb) in zip(ta, tb))


@pytest.mark.slow
def test_spill_burst_violation_and_checkpoint():
    """Spill burst: violation states identical on/off, and a
    checkpoint written mid-run by the bursting engine resumes on the
    per-level engine to the identical final counts (the checkpoint
    format is driver-agnostic)."""
    cfg = SPILL_MICRO.with_(invariants=SPILL_MICRO.invariants +
                            ("FirstBecomeLeader",))
    a = SpillEngine(cfg, store_states=False, burst=True,
                    **SPILL_KW).check(stop_on_violation=True)
    b = SpillEngine(cfg, store_states=False, burst=False,
                    **SPILL_KW).check(stop_on_violation=True)
    assert a.violations and b.violations
    assert a.violations[0].state_id == b.violations[0].state_id
    assert a.violations[0].state == b.violations[0].state

    import os
    import tempfile
    full = SpillEngine(SPILL_MICRO, store_states=False, burst=True,
                       **SPILL_KW).check()
    with tempfile.TemporaryDirectory() as td:
        ckpt = os.path.join(td, "sb.ckpt")
        e1 = SpillEngine(SPILL_MICRO, store_states=False, burst=True,
                         **SPILL_KW)
        part = e1.check(max_depth=6, checkpoint_path=ckpt,
                        checkpoint_every=1)
        assert part.depth == 6
        e2 = SpillEngine(SPILL_MICRO, store_states=False, burst=False,
                         **SPILL_KW)
        resumed = e2.check(resume_from=ckpt)
    assert resumed.distinct_states == full.distinct_states
    assert resumed.level_sizes == full.level_sizes


@pytest.mark.slow
def test_sharded_burst_full_parity_archives():
    """Full space on the virtual mesh: counts, violations and the
    device-major archives bit-identical burst on/off."""
    from collections import Counter

    from raft_tla_tpu.parallel.mesh import ShardedEngine
    e_on = ShardedEngine(MESH_MICRO, chunk=64, store_states=True,
                         burst=True)
    r_on = e_on.check()
    e_off = ShardedEngine(MESH_MICRO, chunk=64, store_states=True,
                          burst=False)
    r_off = e_off.check()
    _counts_match(r_on, r_off)
    assert r_on.levels_fused > 0
    assert Counter(v.invariant for v in r_on.violations) == \
        Counter(v.invariant for v in r_off.violations)
    assert sorted(v.state_id for v in r_on.violations) == \
        sorted(v.state_id for v in r_off.violations)
    _archives_match(e_on, e_off)


@pytest.mark.slow
def test_spill_mesh_burst_full_parity_archives_traces():
    """Full space, spill-composed mesh: counts, archives, violations
    and witness-trace replay bit-identical burst on/off — including
    the in-burst frontier compaction matching the host's
    prune-not-expand row drop exactly."""
    from collections import Counter

    from raft_tla_tpu.parallel.spill_mesh import SpilledShardedEngine
    e_on = SpilledShardedEngine(MESH_MICRO, chunk=64,
                                store_states=True, lcap=1 << 11,
                                burst=True)
    r_on = e_on.check()
    e_off = SpilledShardedEngine(MESH_MICRO, chunk=64,
                                 store_states=True, lcap=1 << 11,
                                 burst=False)
    r_off = e_off.check()
    _counts_match(r_on, r_off)
    assert r_on.levels_fused > 0
    assert Counter(v.invariant for v in r_on.violations) == \
        Counter(v.invariant for v in r_off.violations)
    _archives_match(e_on, e_off)
    g = r_on.distinct_states - 1
    ta, tb = e_on.trace(g), e_off.trace(g)
    assert [l for l, _ in ta] == [l for l, _ in tb]
    assert all(sa == sb for (_, sa), (_, sb) in zip(ta, tb))
