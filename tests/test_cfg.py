"""cfg front-end tests: parse the actual reference model files.

The reference tree (/root/reference) is not shipped in every container;
its parse tests skip when absent.  The CLI end-to-end tests run against
the repo-local twin (configs/tlc_membership — tests/test_sim.py pins
that it parses identically to the reference expectations), so they
exercise the CLI everywhere.
"""

import subprocess
import sys
import json
import os

import pytest

from raft_tla_tpu.cfg.parser import load_model, read_bounds_from_spec
from raft_tla_tpu.config import (NEXT_ASYNC_CRASH, NEXT_FULL)

TLC_CFG = "/root/reference/tlc_membership/raft.cfg"
APA_CFG = "/root/reference/apalache_no_membership/raft.cfg"
LOCAL_CFG = "configs/tlc_membership/raft.cfg"

needs_reference = pytest.mark.skipif(
    not os.path.exists(TLC_CFG),
    reason="reference spec tree not present in this container")


@needs_reference
def test_parse_tlc_membership():
    cfg = load_model(TLC_CFG)
    assert cfg.n_servers == 3
    assert cfg.init_servers == (0, 1, 2)
    assert cfg.values == (1, 2)
    assert cfg.num_rounds == 1
    assert cfg.next_family == NEXT_ASYNC_CRASH
    assert cfg.symmetry is True
    assert not cfg.apalache_variant
    # the 12 enabled constraints and 8 enabled invariants (raft.cfg:37-87)
    assert len(cfg.constraints) == 12
    assert cfg.invariants == (
        "LeaderVotesQuorum", "CandidateTermNotInLog", "ElectionSafety",
        "LogMatching", "VotesGrantedInv", "QuorumLogInv",
        "MoreUpToDateCorrect", "LeaderCompleteness")
    # in-spec bounds lifted from raft.tla:22-30
    b = cfg.bounds
    assert (b.max_log_length, b.max_restarts, b.max_timeouts,
            b.max_client_requests, b.max_terms,
            b.max_membership_changes) == (5, 2, 3, 3, 4, 3)
    assert b.max_trace == 24
    assert cfg.max_inflight == 2 * 9  # 2 * S^2 (raft.tla:30)


@needs_reference
def test_parse_apalache_no_membership():
    cfg = load_model(APA_CFG)
    assert cfg.n_servers == 2
    assert cfg.init_servers == (0, 1)
    assert cfg.values == (1, 2, 3)
    assert cfg.next_family == NEXT_FULL
    assert cfg.symmetry is False
    assert cfg.apalache_variant
    assert "CleanFirstLeaderElection" in cfg.constraints
    b = cfg.bounds
    assert (b.max_log_length, b.max_restarts, b.max_timeouts) == (5, 2, 2)
    assert b.max_trace == 12
    assert cfg.max_inflight == 16  # (2*S)^2 (apalache raft.tla:22)


def run_cli(*argv):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-m", "raft_tla_tpu"] + list(argv),
        capture_output=True, text=True, cwd="/root/repo", env=env,
        timeout=900)


def test_cli_check_micro():
    """End-to-end CLI on the tlc cfg with micro bounds, both
    engines must agree."""
    common = [LOCAL_CFG, "--servers", "2", "--max-timeouts", "1",
              "--max-log-length", "1", "--max-client-requests", "1",
              "--max-depth", "12"]
    outs = {}
    for engine in ("tpu", "oracle"):
        r = run_cli("check", *common, "--engine", engine)
        assert r.returncode == 0, r.stderr
        outs[engine] = json.loads(r.stdout.splitlines()[0])
    assert outs["tpu"]["distinct_states"] == \
        outs["oracle"]["distinct_states"]
    assert outs["tpu"]["depth"] == outs["oracle"]["depth"]
    assert outs["tpu"]["violations"] == outs["oracle"]["violations"] == 0


@pytest.mark.slow
def test_cli_trace_first_commit():
    r = run_cli("trace", LOCAL_CFG, "--servers", "2", "--max-timeouts", "1",
                "--max-log-length", "1", "--max-client-requests", "1",
                "--target", "FirstCommit")
    assert r.returncode == 0, r.stderr
    assert "witness for FirstCommit" in r.stdout
    assert "AdvanceCommitIndex" in r.stdout
