"""cfg front-end tests: parse the actual reference model files.

The reference tree (/root/reference) is not shipped in every container;
its parse tests skip when absent.  The CLI end-to-end tests run against
the repo-local twin (configs/tlc_membership — tests/test_sim.py pins
that it parses identically to the reference expectations), so they
exercise the CLI everywhere.
"""

import subprocess
import sys
import json
import os

import pytest

from raft_tla_tpu.cfg.parser import load_model, read_bounds_from_spec
from raft_tla_tpu.config import (NEXT_ASYNC_CRASH, NEXT_FULL)

TLC_CFG = "/root/reference/tlc_membership/raft.cfg"
APA_CFG = "/root/reference/apalache_no_membership/raft.cfg"
LOCAL_CFG = "configs/tlc_membership/raft.cfg"

needs_reference = pytest.mark.skipif(
    not os.path.exists(TLC_CFG),
    reason="reference spec tree not present in this container")


@needs_reference
def test_parse_tlc_membership():
    cfg = load_model(TLC_CFG)
    assert cfg.n_servers == 3
    assert cfg.init_servers == (0, 1, 2)
    assert cfg.values == (1, 2)
    assert cfg.num_rounds == 1
    assert cfg.next_family == NEXT_ASYNC_CRASH
    assert cfg.symmetry is True
    assert not cfg.apalache_variant
    # the 12 enabled constraints and 8 enabled invariants (raft.cfg:37-87)
    assert len(cfg.constraints) == 12
    assert cfg.invariants == (
        "LeaderVotesQuorum", "CandidateTermNotInLog", "ElectionSafety",
        "LogMatching", "VotesGrantedInv", "QuorumLogInv",
        "MoreUpToDateCorrect", "LeaderCompleteness")
    # in-spec bounds lifted from raft.tla:22-30
    b = cfg.bounds
    assert (b.max_log_length, b.max_restarts, b.max_timeouts,
            b.max_client_requests, b.max_terms,
            b.max_membership_changes) == (5, 2, 3, 3, 4, 3)
    assert b.max_trace == 24
    assert cfg.max_inflight == 2 * 9  # 2 * S^2 (raft.tla:30)


@needs_reference
def test_parse_apalache_no_membership():
    cfg = load_model(APA_CFG)
    assert cfg.n_servers == 2
    assert cfg.init_servers == (0, 1)
    assert cfg.values == (1, 2, 3)
    assert cfg.next_family == NEXT_FULL
    assert cfg.symmetry is False
    assert cfg.apalache_variant
    assert "CleanFirstLeaderElection" in cfg.constraints
    b = cfg.bounds
    assert (b.max_log_length, b.max_restarts, b.max_timeouts) == (5, 2, 2)
    assert b.max_trace == 12
    assert cfg.max_inflight == 16  # (2*S)^2 (apalache raft.tla:22)


def run_cli(*argv):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-m", "raft_tla_tpu"] + list(argv),
        capture_output=True, text=True, cwd="/root/repo", env=env,
        timeout=900)


def test_cli_check_micro():
    """End-to-end CLI on the tlc cfg with micro bounds, both
    engines must agree."""
    common = [LOCAL_CFG, "--servers", "2", "--max-timeouts", "1",
              "--max-log-length", "1", "--max-client-requests", "1",
              "--max-depth", "12"]
    outs = {}
    for engine in ("tpu", "oracle"):
        r = run_cli("check", *common, "--engine", engine)
        assert r.returncode == 0, r.stderr
        outs[engine] = json.loads(r.stdout.splitlines()[0])
    assert outs["tpu"]["distinct_states"] == \
        outs["oracle"]["distinct_states"]
    assert outs["tpu"]["depth"] == outs["oracle"]["depth"]
    assert outs["tpu"]["violations"] == outs["oracle"]["violations"] == 0


@pytest.mark.slow
def test_cli_trace_first_commit():
    r = run_cli("trace", LOCAL_CFG, "--servers", "2", "--max-timeouts", "1",
                "--max-log-length", "1", "--max-client-requests", "1",
                "--target", "FirstCommit")
    assert r.returncode == 0, r.stderr
    assert "witness for FirstCommit" in r.stdout
    assert "AdvanceCommitIndex" in r.stdout


# ---------------------------------------------------------------------------
# TLC .cfg front-end for paxos constants (ROADMAP 2a leftover):
# `--spec paxos model.cfg` parses CONSTANTS into PaxosConfig, with
# clear errors naming unsupported keys, round-tripping against the
# JSON constants path.
# ---------------------------------------------------------------------------

PAXOS_CFG_TEXT = """\
\\* small paxos model
CONSTANTS
  a1 = 1
  a2 = 2
  a3 = 3
  Acceptor = {a1, a2, a3}
  Ballot = {0, 1}
  Value = {0, 1}
  Instances = 2
SYMMETRY perms
INIT Init
NEXT Next
INVARIANTS
  Agreement
  Validity
"""


def test_paxos_cfg_roundtrips_with_json_path(tmp_path):
    from raft_tla_tpu.cfg.parser import (load_paxos_model,
                                         paxos_config_from_obj)
    p = tmp_path / "paxos.cfg"
    p.write_text(PAXOS_CFG_TEXT)
    cfg = load_paxos_model(str(p))
    assert (cfg.n_servers, cfg.n_ballots, cfg.n_values,
            cfg.n_instances) == (3, 2, 2, 2)
    assert cfg.symmetry is True
    assert cfg.invariants == ("Agreement", "Validity")
    # round-trip: the JSON constants path builds the identical config
    via_json = paxos_config_from_obj(
        {"acceptors": 3, "ballots": 2, "values": 2, "instances": 2,
         "symmetry": True, "invariants": ["Agreement", "Validity"]},
        where="json")
    assert cfg == via_json
    # no SYMMETRY line -> symmetry off (TLC semantics); no INVARIANT
    # lines -> the spec defaults
    p2 = tmp_path / "plain.cfg"
    p2.write_text("CONSTANTS\n  a1 = 1\n  Acceptor = {a1}\n"
                  "  Ballot = {0}\n  Value = {0}\n")
    cfg2 = load_paxos_model(str(p2))
    assert cfg2.symmetry is False and cfg2.n_servers == 1
    assert cfg2 == paxos_config_from_obj(
        {"acceptors": 1, "ballots": 1, "values": 1, "symmetry": False},
        where="json")


def test_paxos_cfg_clear_errors(tmp_path):
    from raft_tla_tpu.cfg.parser import CfgError, load_paxos_model

    def expect(text, pattern):
        p = tmp_path / "bad.cfg"
        p.write_text(text)
        with pytest.raises(CfgError, match=pattern):
            load_paxos_model(str(p))

    base = "CONSTANTS\n  a1 = 1\n  Acceptor = {a1}\n"
    # unsupported constant, by name
    expect(base + "  Frob = {a1}\n", "unsupported paxos CONSTANT 'Frob'")
    # Quorum is derived
    expect(base + "  Quorum = {a1}\n", "Quorum is not cfg-settable")
    # non-dense ballot set
    expect(base + "  Ballot = {1, 3}\n", "contiguous set 0..N-1")
    # unknown invariant names the spec (the shared JSON-path message)
    expect(base + "INVARIANT NotAThing\n",
           r"unknown invariant\(s\) 'NotAThing' for spec 'paxos'")
    # paxos declares no constraints
    expect(base + "CONSTRAINT Bounded\n", "declares no constraints")
    # unsupported NEXT family
    expect(base + "NEXT NextAsync\n", "unsupported NEXT")


def test_cli_check_paxos_cfg_matches_json(tmp_path):
    """`--spec paxos model.cfg` end-to-end: the .cfg and the JSON
    constants path land on identical counts."""
    cfg_p = tmp_path / "m.cfg"
    cfg_p.write_text("CONSTANTS\n  a1 = 1\n  a2 = 2\n"
                     "  Acceptor = {a1, a2}\n  Ballot = {0}\n"
                     "  Value = {0}\n")
    json_p = tmp_path / "m.json"
    json_p.write_text(json.dumps(
        {"acceptors": 2, "ballots": 1, "values": 1,
         "symmetry": False}))
    outs = {}
    for name, path in (("cfg", cfg_p), ("json", json_p)):
        r = run_cli("check", str(path), "--spec", "paxos",
                    "--engine", "oracle", "--max-depth", "4")
        assert r.returncode == 0, r.stderr
        outs[name] = json.loads(r.stdout.splitlines()[0])
    assert outs["cfg"]["distinct_states"] == \
        outs["json"]["distinct_states"]
    assert outs["cfg"]["depth"] == outs["json"]["depth"]
