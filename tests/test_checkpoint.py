"""Checkpoint/resume: interrupt at level k, resume, land on counts
identical to an uninterrupted run (TLC's states/ checkpointing —
/root/reference/.gitignore:4; SURVEY §5)."""

import json
import subprocess
import sys

import pytest

from raft_tla_tpu.config import Bounds, ModelConfig
from raft_tla_tpu.engine.bfs import Engine

MICRO = ModelConfig(
    n_servers=2, init_servers=(0, 1), values=(1,),
    max_inflight_override=4, symmetry=True,
    bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                       max_client_requests=1))


@pytest.mark.slow
def test_checkpoint_resume_identical(tmp_path):
    full = Engine(MICRO, chunk=64, store_states=True).check()

    ckpt = str(tmp_path / "run.ckpt")
    e1 = Engine(MICRO, chunk=64, store_states=True)
    part = e1.check(max_depth=12, checkpoint_path=ckpt)
    assert part.depth == 12
    assert part.distinct_states < full.distinct_states

    e2 = Engine(MICRO, chunk=64, store_states=True)
    resumed = e2.check(resume_from=ckpt)
    assert resumed.distinct_states == full.distinct_states
    assert resumed.depth == full.depth
    assert resumed.generated_states == full.generated_states
    assert resumed.level_sizes == full.level_sizes
    # the parent/lane archives survive the resume: every state of the
    # full run is reconstructible
    assert sum(len(p) for p in e2._parents) == full.distinct_states


@pytest.mark.slow
def test_sharded_checkpoint_resume_identical(tmp_path):
    import jax

    from raft_tla_tpu.parallel.mesh import ShardedEngine
    devs = jax.devices()
    full = ShardedEngine(MICRO, devices=devs, chunk=8 * len(devs),
                         store_states=True).check()

    ckpt = str(tmp_path / "sharded.ckpt")
    e1 = ShardedEngine(MICRO, devices=devs, chunk=8 * len(devs),
                       store_states=True)
    part = e1.check(max_depth=12, checkpoint_path=ckpt)
    assert part.depth == 12
    assert part.distinct_states < full.distinct_states

    e2 = ShardedEngine(MICRO, devices=devs, chunk=8 * len(devs),
                       store_states=True)
    resumed = e2.check(resume_from=ckpt)
    assert resumed.distinct_states == full.distinct_states
    assert resumed.depth == full.depth
    assert resumed.generated_states == full.generated_states
    assert resumed.level_sizes == full.level_sizes
    assert sum(len(p) for p in e2._parents) == full.distinct_states

    # cross-engine resumes are rejected with a clear error
    from raft_tla_tpu.engine.bfs import CheckpointError
    with pytest.raises(CheckpointError, match="sharded-engine"):
        Engine(MICRO, chunk=64).check(resume_from=ckpt)


def test_checkpoint_config_mismatch(tmp_path):
    ckpt = str(tmp_path / "run.ckpt")
    Engine(MICRO, chunk=64, store_states=False).check(
        max_depth=6, checkpoint_path=ckpt)
    other = Engine(MICRO.with_(symmetry=False), chunk=64,
                   store_states=False)
    with pytest.raises(ValueError, match="different model config"):
        other.check(resume_from=ckpt)


@pytest.mark.slow
def test_cli_checkpoint_resume(tmp_path):
    ckpt = str(tmp_path / "cli.ckpt")
    base = [sys.executable, "-m", "raft_tla_tpu", "check",
            __import__("conftest").ref_or_local(
                "/root/reference/tlc_membership/raft.cfg"),
            "--servers", "2", "--init-servers", "2",
            "--max-log-length", "1", "--max-timeouts", "1",
            "--max-client-requests", "1", "--chunk", "64",
            "--no-store", "--keep-going"]
    r1 = subprocess.run(base + ["--max-depth", "8",
                                "--checkpoint", ckpt],
                        capture_output=True, text=True, timeout=600)
    assert r1.returncode == 0, r1.stderr
    r2 = subprocess.run(base + ["--resume", ckpt, "--max-depth", "12"],
                        capture_output=True, text=True, timeout=600)
    assert r2.returncode == 0, r2.stderr
    full = subprocess.run(base + ["--max-depth", "12"],
                          capture_output=True, text=True, timeout=600)
    assert full.returncode == 0, full.stderr
    got = json.loads(r2.stdout.splitlines()[0])
    want = json.loads(full.stdout.splitlines()[0])
    assert got["distinct_states"] == want["distinct_states"]
    assert got["depth"] == want["depth"]
