"""Codec round-trip: oracle states -> SoA arrays -> oracle states."""

import pytest

from raft_tla_tpu.config import (Bounds, ModelConfig, NEXT_DYNAMIC,
                                 NEXT_FULL)
from raft_tla_tpu.models.explore import explore
from raft_tla_tpu.models.raft import init_state
from raft_tla_tpu.ops.codec import decode, encode, features_from_hist
from raft_tla_tpu.ops.layout import Layout


def reachable_states(cfg, max_states=4000):
    res = explore(cfg, max_states=max_states, keep_states=True)
    return list(res.states.values())


SMALL = ModelConfig(
    n_servers=2, init_servers=(0, 1), values=(1,),
    bounds=Bounds.make(max_log_length=2, max_timeouts=2),
    symmetry=False)

UNRELIABLE = SMALL.with_(next_family=NEXT_FULL)

MEMBER = ModelConfig(
    n_servers=3, init_servers=(0, 1), values=(1,),
    next_family=NEXT_DYNAMIC,
    bounds=Bounds.make(max_log_length=2, max_timeouts=2),
    symmetry=False)


@pytest.mark.parametrize("cfg", [SMALL, UNRELIABLE, MEMBER],
                         ids=["small", "unreliable", "membership"])
def test_roundtrip(cfg):
    lay = Layout(cfg)
    states = reachable_states(cfg)
    assert len(states) > 50
    # make sure the sample exercises logs and bags
    assert any(s.msgs for s, _h in states)
    assert any(any(s.log) for s, _h in states)
    for sv, h in states:
        arrs = encode(lay, sv, h)
        sv2, h2 = decode(lay, arrs)
        assert sv2 == sv
        assert h2.restarted == h.restarted and h2.timeout == h.timeout
        assert (h2.nleaders, h2.nreq, h2.ntried, h2.nmc) == \
            (h.nleaders, h.nreq, h.ntried, h.nmc)


def test_membership_msgs_roundtrip():
    """Catchup/CheckOldConfig messages (incl. the absent-mcommitIndex
    follow-up CatchupRequest) must round-trip with field-set identity."""
    from raft_tla_tpu.config import MT_CATREQ, MT_CATRESP, MT_COC
    cfg = MEMBER
    lay = Layout(cfg)
    res = explore(cfg, max_states=4000, keep_states=True)
    seen_types = set()
    for sv, h in res.states.values():
        for m, _c in sv.msgs:
            seen_types.add(m[0])
        arrs = encode(lay, sv, h)
        sv2, _h2 = decode(lay, arrs)
        assert sv2.msgs == sv.msgs
    assert MT_CATREQ in seen_types
    assert MT_COC in seen_types


def test_feature_lanes_match_oracle_predicates():
    """The incremental feature lanes must agree with a direct reading of
    the oracle global history."""
    from raft_tla_tpu.ops import codec as C
    cfg = SMALL
    res = explore(cfg, max_states=1500, keep_states=True)
    states = list(res.states.values())
    # BFS at this size does not reach a commit; the scenario-property
    # machinery itself finds one (EntryCommitted as negated reachability,
    # raft.tla:1160-1163) so the CommitEntry feature path is exercised.
    deep = explore(cfg.with_(invariants=("EntryCommitted",)),
                   stop_on_violation=True)
    assert deep.violations
    states.append((deep.violations[0].state, deep.violations[0].hist))
    n_commit = 0
    for sv, h in states:
        feat = features_from_hist(h)
        assert feat[C.F_COMMIT_SEEN] == int(
            any(r[0] == "CommitEntry" for r in h.glob))
        restarts = [k + 1 for k, r in enumerate(h.glob)
                    if r[0] == "Restart"]
        assert feat[C.F_LAST_RESTART_POS] == (restarts[-1] if restarts
                                              else 0)
        if len(restarts) >= 2:
            assert feat[C.F_MIN_RESTART_GAP] == min(
                b - a for a, b in zip(restarts, restarts[1:]))
        n_commit += feat[C.F_COMMIT_SEEN]
    assert n_commit > 0


def test_membership_feature_lanes_match_oracle_predicates():
    """The membership feature lanes must agree with the oracle scenario
    predicates (models/predicates.py) that read the full glob history."""
    from raft_tla_tpu.ops import codec as C
    from raft_tla_tpu.models import predicates as P
    cfg = MEMBER
    # Scenario machinery produces histories with AddServer /
    # CommitMembershipChange / BecomeLeader interleavings.
    samples = []
    for target in ("AddSucessful", "MembershipChangeCommits"):
        res = explore(cfg.with_(invariants=(target,)),
                      stop_on_violation=True, max_states=300_000)
        assert res.violations, f"no witness found for {target}"
        samples.append((res.violations[0].state, res.violations[0].hist))
    res = explore(cfg, max_states=3000, keep_states=True)
    samples.extend(res.states.values())
    # Deeper interleavings (NewlyJoinedBecomeLeader / LeaderChanges...) are
    # beyond a quick BFS; exercise those lanes with synthetic histories.
    sv0, h0 = init_state(cfg)
    synth = [
        (("AddServer", 0, 2), ("BecomeLeader", 2, 0b100)),          # NJBL
        (("AddServer", 0, 2), ("BecomeLeader", 1, 0b010)),          # LCDCC
        (("AddServer", 0, 2), ("CommitMembershipChange", 0, 0b111),
         ("BecomeLeader", 1, 0b010)),                               # neither
        (("AddServer", 0, 2), ("CommitMembershipChange", 0, 0b111),
         ("AddServer", 0, 3), ("BecomeLeader", 2, 0b100)),          # NJBL only
    ]
    samples.extend((sv0, h0._replace(glob=g)) for g in synth)
    seen_added = False
    for sv, h in samples:
        feat = features_from_hist(h)
        added = 0
        for r in h.glob:
            if r[0] == "AddServer":
                added |= 1 << r[2]
        assert feat[C.F_ADDED_SET] == added
        assert feat[C.F_MC_COMMITS] == sum(
            1 for r in h.glob if r[0] == "CommitMembershipChange")
        seen_added = seen_added or added != 0
        # feature-lane forms of the oracle predicates
        assert (feat[C.F_ADD_COMMITS] == 0) == P.add_commits(sv, h, cfg)
        assert (feat[C.F_NJBL] == 0) == \
            P.newly_joined_become_leader(sv, h, cfg)
        assert (feat[C.F_LCDCC] == 0) == \
            P.leader_changes_during_conf_change(sv, h, cfg)
    assert seen_added


def test_narrow_widen_roundtrip_and_fp_parity():
    """Engines store rows in codec.narrow_dtypes; narrowing must be
    lossless under the configured bounds and the fingerprint must be
    bit-identical on narrow and wide rows (the sharded engine
    fingerprints wide rows but ships narrow rows over the ICI)."""
    import jax
    import numpy as np
    from raft_tla_tpu.engine.fingerprint import Fingerprinter
    from raft_tla_tpu.ops.codec import narrow, widen, stack

    import jax.numpy as jnp

    cfg = MEMBER.with_(symmetry=True)
    lay = Layout(cfg)
    arrs = stack([encode(lay, s, h)
                  for (s, h) in reachable_states(cfg, 250)[:200]])
    nar = narrow(lay, arrs)
    assert nar["ct"].dtype == np.int8
    assert nar["log"].dtype in (np.int8, np.int16)
    assert nar["bag"].dtype == np.uint32 and nar["ctr"].dtype == np.int32
    wide = widen(nar)
    for k in arrs:
        assert (np.asarray(wide[k]) == arrs[k]).all(), k
    fpr = Fingerprinter(cfg)
    fp_w = np.asarray(jax.jit(fpr.fingerprint_batch)(
        {k: jnp.asarray(v) for k, v in arrs.items()}))
    fp_n = np.asarray(jax.jit(fpr.fingerprint_batch)(
        {k: jnp.asarray(v) for k, v in nar.items()}))
    assert (fp_w == fp_n).all()


def test_fingerprint_batch_matches_per_state():
    """The batch-minor fingerprint formulation (the engine's hot path)
    is bit-identical to the per-state reference formulation, for both
    64- and 128-bit streams."""
    import jax
    import jax.numpy as jnp

    from raft_tla_tpu.config import NEXT_ASYNC_CRASH, Bounds, ModelConfig
    from raft_tla_tpu.engine.fingerprint import Fingerprinter
    from raft_tla_tpu.models.explore import explore

    cfg = ModelConfig(
        n_servers=3, init_servers=(0, 1, 2), values=(1, 2),
        next_family=NEXT_ASYNC_CRASH, symmetry=True,
        max_inflight_override=6,
        bounds=Bounds.make(max_log_length=2, max_timeouts=1,
                           max_client_requests=1))
    import numpy as np
    r = explore(cfg, max_states=2000, keep_states=True)
    lay = Layout(cfg)
    pairs = list(r.states.values())[:256]
    arrs = [encode(lay, sv, h) for sv, h in pairs]
    svb = {k: jnp.asarray(np.stack([a[k] for a in arrs]))
           for k in arrs[0]}
    for variant in (cfg, cfg.with_(fp128=True)):
        fpr = Fingerprinter(variant)
        ref = np.asarray(
            jax.jit(lambda s: jax.vmap(fpr.fingerprint)(s))(svb))
        got = np.asarray(jax.jit(fpr.fingerprint_batch)(svb))
        assert np.array_equal(ref, got)
