"""Persistent checking daemon (ISSUE 18): spool-dir intake protocol,
stream tail, the shared wave-scheduler core's drain/defer/resume
contract, the daemon cycle loop, and the watch daemon view.

Budget: exactly two batched bucket compiles live here (one MICRO raft
engine for the scheduler drain/resume chain, one tiny paxos engine for
the daemon cycle chain — each WaveScheduler is reused across every
serve round of its test).  Everything else is device-free and
smoke-marked.  The cross-process halves (SIGTERM, SIGKILL+restart,
warm zero-compile) live in tools/daemon_smoke.py, which ci_smoke.sh
runs over the real CLI.
"""

import importlib.util
import inspect
import json
import os
import time

import pytest

from raft_tla_tpu.config import Bounds, ModelConfig, NEXT_ASYNC
from raft_tla_tpu.obs import Heartbeat, Obs, RunLedger, RunRegistry
from raft_tla_tpu.resil import chaos
from raft_tla_tpu.resil.chaos import InjectedFault
from raft_tla_tpu.resil.supervisor import RETRYABLE
from raft_tla_tpu.serve import (Daemon, ExecCache, Job, ResultCache,
                                SpoolIntake, StreamTail, WaveScheduler,
                                run_jobs)
from raft_tla_tpu.serve.batch import BucketEngine, _default_serve_bucket
from raft_tla_tpu.spec.paxos.config import PaxosConfig

from conftest import cached_explore

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MICRO = ModelConfig(
    n_servers=2, init_servers=(0, 1), values=(1,),
    next_family=NEXT_ASYNC, symmetry=True, max_inflight_override=4,
    bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                       max_client_requests=1))
PAX = PaxosConfig(n_servers=2, n_ballots=2, n_values=1)
# the same model as a client-side job record (serve/jobs README shape)
PAX_JOB = {"spec": "paxos",
           "config": {"acceptors": 2, "ballots": 2, "values": 1},
           "max_depth": 3, "label": "pax"}


def _write_raw(intake, name, data):
    """A NON-conforming client: bytes straight into incoming/ (the
    submit() helper always writes valid JSON + newline)."""
    path = os.path.join(intake.dirs["incoming"], name)
    with open(path, "wb") as fh:
        fh.write(data)
    return path


# ---------------------------------------------------------------------------
# spool protocol (intake edge cases — device-free)
# ---------------------------------------------------------------------------

@pytest.mark.smoke
def test_spool_claim_quarantine_and_guards(tmp_path):
    """One poll() sweep: complete submissions claim (always as
    NAME.json), malformed ones quarantine with a .reason file, torn
    writes get the grace window, and tmp/part/dot names are never
    touched."""
    intake = SpoolIntake(str(tmp_path), grace_s=0.2)
    intake.submit(PAX_JOB, "good")
    # a conforming client under a bare name (no .json): claimed file
    # still normalizes to NAME.json
    _write_raw(intake, "bare", (json.dumps(PAX_JOB) + "\n").encode())
    _write_raw(intake, "garbage.json", b"{not json\n")
    _write_raw(intake, "badkey.json",
               (json.dumps({"spec": "paxos", "bogus": 1}) +
                "\n").encode())
    _write_raw(intake, "torn.json", b'{"spec": "paxos"')   # no newline
    _write_raw(intake, "skip.json.tmp", b"x")
    _write_raw(intake, "skip.part", b"x")
    _write_raw(intake, ".hidden.json", b"x")

    claimed, rejected = intake.poll()
    assert sorted(s.name for s in claimed) == ["bare", "good"]
    for sub in claimed:
        assert sub.path == os.path.join(intake.dirs["claimed"],
                                        sub.name + ".json")
        assert os.path.exists(sub.path)
        assert not sub.recovered
        assert sub.job.ir.name == "paxos"
    rej = dict(rejected)
    assert set(rej) == {"garbage", "badkey"}
    assert "bogus" in rej["badkey"]
    for name in rej:
        assert os.path.exists(os.path.join(
            intake.dirs["rejected"], name + ".json"))
        with open(os.path.join(intake.dirs["rejected"],
                               name + ".json.reason")) as fh:
            assert fh.read().strip() == rej[name].strip()
    # the torn file rode its grace window: untouched this poll
    assert os.path.exists(os.path.join(intake.dirs["incoming"],
                                       "torn.json"))
    # the guarded names are invisible to claiming AND to counts()
    counts = intake.counts()
    assert counts == {"incoming": 1, "claimed": 2, "rejected": 2,
                      "results": 0, "done": 0}

    # past the grace the torn write quarantines with a named reason
    time.sleep(0.25)
    claimed2, rejected2 = intake.poll()
    assert claimed2 == []
    assert len(rejected2) == 1 and rejected2[0][0] == "torn"
    assert "no trailing newline" in rejected2[0][1]
    assert os.path.exists(os.path.join(intake.dirs["rejected"],
                                       "torn.json"))

    # result + done marker retire the claim
    intake.write_result("good", {"status": "done", "label": "pax",
                                 "cache_key": "k", "violations": 0})
    intake.mark_done("good", {"status": "done", "label": "pax",
                              "cache_key": "k"})
    with open(os.path.join(intake.dirs["done"], "good.json")) as fh:
        marker = json.load(fh)
    assert marker == {"name": "good", "status": "done",
                      "label": "pax", "cache_key": "k"}
    assert not os.path.exists(os.path.join(intake.dirs["claimed"],
                                           "good.json"))

    # submit() refuses names that would escape or hide in the spool
    with pytest.raises(ValueError):
        intake.submit(PAX_JOB, "a" + os.sep + "b")
    with pytest.raises(ValueError):
        intake.submit(PAX_JOB, ".dot")


@pytest.mark.smoke
def test_spool_recover_reclaims_finalizes_and_quarantines(tmp_path):
    """The restart contract: a leftover claimed file re-enters the
    queue (recovered=True); one whose result already landed is
    finalized, not recomputed; a tampered one quarantines."""
    intake = SpoolIntake(str(tmp_path), grace_s=0.0)
    intake.submit(PAX_JOB, "inflight")
    intake.submit(dict(PAX_JOB, label="fin"), "finished")
    claimed, _ = intake.poll()
    assert len(claimed) == 2
    # "finished" died between the result write and the done marker
    intake.write_result("finished", {"status": "done", "label": "fin",
                                     "cache_key": "k2"})
    with open(os.path.join(intake.dirs["claimed"],
                           "tampered.json"), "w") as fh:
        fh.write("{broken\n")

    recovered, rejected = intake.recover()
    assert [s.name for s in recovered] == ["inflight"]
    assert recovered[0].recovered
    # finalized from its surviving result: done marker written, claim
    # retired, NOT handed back for recompute
    with open(os.path.join(intake.dirs["done"],
                           "finished.json")) as fh:
        assert json.load(fh)["cache_key"] == "k2"
    assert not os.path.exists(os.path.join(intake.dirs["claimed"],
                                           "finished.json"))
    assert [name for name, _ in rejected] == ["tampered"]
    assert os.path.exists(os.path.join(intake.dirs["rejected"],
                                       "tampered.json.reason"))
    # idempotent: a second recover re-claims the same leftover again
    recovered2, _ = intake.recover()
    assert [s.name for s in recovered2] == ["inflight"]


@pytest.mark.smoke
def test_stream_tail_offsets_and_partial_lines(tmp_path):
    """The JSONL stream tail: complete lines materialize as ordered
    stream-<n> submissions, a partial final line waits for its
    newline, and the persisted offset makes restarts resume without
    re-submitting or dropping."""
    intake = SpoolIntake(str(tmp_path / "spool"))
    stream_path = str(tmp_path / "jobs.jsonl")
    with open(stream_path, "w") as fh:
        fh.write(json.dumps(PAX_JOB) + "\n")
        fh.write("# a comment line\n\n")
        fh.write(json.dumps(dict(PAX_JOB, label="p2")) + "\n")
        fh.write('{"spec": "paxos"')          # writer mid-append
    tail = StreamTail(stream_path, intake)
    assert tail.poll() == 2
    inc = sorted(os.listdir(intake.dirs["incoming"]))
    assert inc == ["stream-000001.json", "stream-000002.json"]
    # nothing new, partial line still unconsumed
    assert tail.poll() == 0
    # the writer finishes its line and appends one more
    with open(stream_path, "a") as fh:
        fh.write(', "label": "p3"}\n')
        fh.write(json.dumps(dict(PAX_JOB, label="p4")) + "\n")
    assert tail.poll() == 2
    assert sorted(os.listdir(intake.dirs["incoming"]))[-1] == \
        "stream-000004.json"
    # restart: a fresh tail resumes from the persisted offset
    tail2 = StreamTail(stream_path, intake)
    assert tail2.offset == tail.offset and tail2.lineno == 4
    assert tail2.poll() == 0
    # the materialized submissions parse through the normal protocol
    claimed, rejected = intake.poll()
    assert len(claimed) == 4 and rejected == []
    assert claimed[2].job.label == "p3"


@pytest.mark.smoke
def test_chaos_intake_site_is_retryable_and_idempotent(tmp_path):
    """An injected intake fault aborts the scan BEFORE the claim
    rename: the submission survives in incoming/ and the next poll
    claims it — and the fault type is in the daemon's RETRYABLE set,
    so `--retries` covers the intake path too."""
    intake = SpoolIntake(str(tmp_path))
    intake.submit(PAX_JOB, "j1")
    chaos.install("intake:at=1")
    try:
        with pytest.raises(InjectedFault) as exc:
            intake.poll()
        assert exc.value.site == "intake"
        assert isinstance(exc.value, RETRYABLE)
        assert os.listdir(intake.dirs["claimed"]) == []
        assert os.path.exists(os.path.join(intake.dirs["incoming"],
                                           "j1.json"))
    finally:
        chaos.uninstall()
    claimed, _ = intake.poll()
    assert [s.name for s in claimed] == ["j1"]


# ---------------------------------------------------------------------------
# routing: ONE copy of the driver loop (serve/scheduler)
# ---------------------------------------------------------------------------

@pytest.mark.smoke
def test_run_jobs_and_daemon_route_through_scheduler(monkeypatch):
    """`cli batch` (run_jobs) and the daemon cycle are thin calls into
    WaveScheduler.serve — pinned the way test_driver pins the engine
    drivers, so a second scheduling-rule copy can't grow back."""
    calls = {}

    def fake_serve(self, jobs, obs=None, sequential=False,
                   verbose=False, stop=None):
        calls["jobs"] = list(jobs)
        calls["sequential"] = sequential
        return "SENTINEL"

    monkeypatch.setattr(WaveScheduler, "serve", fake_serve)
    out = run_jobs([Job(PAX, max_depth=1)], sequential=True)
    assert out == "SENTINEL"
    assert calls["sequential"] is True and len(calls["jobs"]) == 1
    # source pins: the wrapper and the cycle hold no driver loop of
    # their own — they construct/call the shared core and nothing else
    src = inspect.getsource(run_jobs)
    assert "WaveScheduler(" in src and ".serve(" in src
    assert "run_wave" not in src
    cyc = inspect.getsource(Daemon.run_cycle)
    assert "self.sched.serve(" in cyc
    assert "run_wave" not in cyc and "BucketEngine" not in cyc


@pytest.mark.smoke
def test_bucket_program_donation_mode(tmp_path):
    """With a persistent executable cache the bucket program compiles
    WITHOUT carry donation (a donated executable deserialized in
    another process returns corrupted carries — the daemon_smoke
    warm-restart path caught it), and the mode is part of the
    executable's cache identity."""
    ceiling, params = _default_serve_bucket(PAX)
    be = BucketEngine(ceiling, exec_cache=ExecCache(str(tmp_path)),
                      **params)
    assert be._donate is False
    assert be._exec_key_parts(1)["donate"] is False
    assert be._fn is be.eng.burst_batched_fn(donate=False)
    # both variants exist side by side and memoize independently
    assert be.eng.burst_batched_fn(donate=True) is not be._fn
    assert be.eng.burst_batched_fn(donate=False) is be._fn
    be2 = BucketEngine(ceiling, **params)
    assert be2._donate is True
    assert be2._exec_key_parts(1)["donate"] is True
    assert be2._fn is be2.eng.burst_batched_fn()


# ---------------------------------------------------------------------------
# scheduler drain/defer/resume (the ONE raft bucket compile)
# ---------------------------------------------------------------------------

def test_scheduler_drain_defers_and_resumes_bit_exact(tmp_path):
    """The graceful-drain contract in one process: a stop that fires
    before any work defers everything with ZERO compiles; one that
    fires mid-BFS (after the first wave-state persist) parks the job
    and defers it; the next serve() resumes it from the carry
    bit-exact against the oracle; the one after answers from the
    result cache — all on one persistent scheduler (one engine
    compile total)."""
    waves = tmp_path / "waves"
    sched = WaveScheduler(cache=ResultCache(str(tmp_path / "cache")),
                          wave_state=str(waves),
                          # one BFS level per device call, so the
                          # depth-6 job spans several step boundaries
                          bucket_overrides={"burst_levels": 1})

    def job():
        return Job(MICRO, max_depth=6, label="m6")

    # drain-before-work: deferred at the bucket gate, nothing compiled
    rep0 = sched.serve([job()], stop=lambda: True)
    assert rep0.outcomes == [None]
    assert rep0.meta["drained"] and rep0.meta["deferred_jobs"] == 1
    assert rep0.meta["engines_compiled"] == 0
    assert rep0.meta["batch_dispatches"] == 0

    # drain mid-BFS: the stop trips at the first step boundary AFTER
    # the carry persisted (exactly the daemon's SIGTERM timing)
    def stop_after_persist():
        return waves.is_dir() and any(
            fn.endswith(".wave.npz") for fn in os.listdir(waves))

    assert not stop_after_persist()
    rep1 = sched.serve([job()], stop=stop_after_persist)
    assert rep1.outcomes == [None]
    assert rep1.meta["drained"] and rep1.meta["deferred_jobs"] == 1
    assert rep1.meta["engines_compiled"] == 1
    assert rep1.meta["batch_dispatches"] >= 1
    assert stop_after_persist(), "the deferred carry must survive"

    # resume: mid-BFS from the carry, same engine (no recompile),
    # bit-exact vs the oracle
    rep2 = sched.serve([job()])
    o = rep2.outcomes[0]
    assert o is not None and o.status == "done"
    assert rep2.meta["resumed_jobs"] == 1
    assert rep2.meta["engines_compiled"] == 0
    assert o.report["status_reason"] == "resumed from wave state"
    want = cached_explore(MICRO, max_depth=6)
    assert o.report["distinct_states"] == want.distinct_states
    assert o.report["generated_states"] == want.generated_states
    assert o.report["depth"] == want.depth
    assert o.report["level_sizes"] == list(want.level_sizes)
    # answered: the carry retired so no future serve resumes stale
    # state
    assert not stop_after_persist()

    # and the result cache now owns the answer outright
    rep3 = sched.serve([job()])
    assert rep3.meta["cache_hits"] == 1
    assert rep3.meta["batch_dispatches"] == 0
    assert rep3.outcomes[0].status == "cache_hit"


# ---------------------------------------------------------------------------
# the daemon cycle loop (the ONE paxos bucket compile)
# ---------------------------------------------------------------------------

def test_daemon_cycles_dedup_eviction_and_idle_drain(tmp_path):
    """One in-process daemon across cycles: serve, cross-cycle cache
    hit, in-batch duplicate, recompute after eviction, malformed
    quarantine, then the idle drain — with the ledger/heartbeat/
    registry surface a real `cli serve` run writes."""
    spool = str(tmp_path / "spool")
    cache = ResultCache(str(tmp_path / "cache"))
    led_path = str(tmp_path / "ledger.jsonl")
    hb_path = str(tmp_path / "hb.json")
    reg = RunRegistry(str(tmp_path / "reg"))
    obs = Obs(ledger=RunLedger(led_path), heartbeat=Heartbeat(hb_path),
              registry=reg, run_info={"cmd": "serve"})
    d = Daemon(spool, cache=cache, obs=obs, poll_s=0.0,
               max_idle_polls=2, sleep=lambda s: None)

    assert d.run_cycle() is None          # empty intake = idle cycle
    assert d.stats["cycles"] == 0

    # cycle 1: a real serve
    d.intake.submit(PAX_JOB, "pax")
    rep = d.run_cycle()
    assert rep is not None and d.stats["jobs_done"] == 1
    with open(os.path.join(spool, "results", "pax.json")) as fh:
        res1 = json.load(fh)
    want = cached_explore(PAX, max_depth=3)
    assert res1["distinct_states"] == want.distinct_states
    assert res1["depth"] == want.depth
    assert res1["level_sizes"] == list(want.level_sizes)
    assert os.path.exists(os.path.join(spool, "done", "pax.json"))

    # cycle 2: identical job under a new name = a cache hit, zero
    # device work, zero compiles (persistent engine aside — nothing
    # even dispatches)
    d.intake.submit(PAX_JOB, "pax-again")
    rep = d.run_cycle()
    assert rep.meta["cache_hits"] == 1
    assert rep.meta["batch_dispatches"] == 0
    assert d.stats["cache_hits"] == 1 and d.stats["jobs_done"] == 2

    # cycle 3: two identical NEW jobs in one cycle — computed once,
    # the duplicate answered in-batch; the shared bucket engine
    # persists across cycles so nothing recompiles
    twin = dict(PAX_JOB, max_depth=2)
    d.intake.submit(twin, "twin-a")
    d.intake.submit(twin, "twin-b")
    rep = d.run_cycle()
    assert rep.meta["deduped"] == 1
    assert rep.meta["engines_compiled"] == 0
    assert d.stats["jobs_done"] == 4
    ra = json.load(open(os.path.join(spool, "results", "twin-a.json")))
    rb = json.load(open(os.path.join(spool, "results", "twin-b.json")))
    assert ra["distinct_states"] == rb["distinct_states"]
    assert "duplicate of job" in rb.get("status_reason", "") or \
        "duplicate of job" in ra.get("status_reason", "")

    # cycle 4: eviction, then re-submission — honestly recomputed
    cache._mem.clear()
    for fn in os.listdir(cache.path):
        os.unlink(os.path.join(cache.path, fn))
    d.intake.submit(PAX_JOB, "pax-evicted")
    rep = d.run_cycle()
    assert rep.meta["cache_hits"] == 0
    assert rep.meta["batch_dispatches"] >= 1
    assert rep.meta["engines_compiled"] == 0
    res2 = json.load(open(os.path.join(spool, "results",
                                       "pax-evicted.json")))
    assert res2["distinct_states"] == res1["distinct_states"]

    # a malformed drop quarantines without failing the cycle
    with open(os.path.join(spool, "incoming", "bad.json"), "w") as fh:
        fh.write("{nope\n")
    assert d.run_cycle() is None          # nothing claimable
    assert d.stats["jobs_rejected"] == 1

    # idle drain: run() re-recovers (nothing left), idles out, and
    # finishes done with the full telemetry surface
    rc = d.run()
    assert rc == 0 and d._drain == "idle for 2 polls"
    hb = json.load(open(hb_path))
    assert hb["status"] == "done"
    blk = hb["daemon"]
    assert blk["jobs_done"] == 5 and blk["cache_hits"] == 2
    assert blk["jobs_rejected"] == 1
    assert blk["tenants"]["paxos"]["jobs_done"] == 5
    assert blk["drain_reason"] == "idle for 2 polls"
    kinds = set()
    actions = set()
    cycles = []
    with open(led_path) as fh:
        for line in fh:
            r = json.loads(line)
            kinds.add(r.get("kind"))
            if r.get("kind") == "intake":
                actions.add(r.get("action"))
            if r.get("kind") == "daemon":
                cycles.append(r["cycle"])
    assert {"intake", "daemon", "tenant", "job"} <= kinds
    assert actions == {"claimed", "rejected"}
    assert cycles == [1, 2, 3, 4]
    rid_recs = [rec for _rid, rec in reg.records()]
    assert len(rid_recs) == 1
    rec = rid_recs[0]
    assert rec["cmd"] == "serve" and rec["status"] == "done"
    assert rec["counters"]["jobs_done"] == 5
    assert rec["daemon"]["status"] == "done"


@pytest.mark.smoke
def test_drain_with_parked_work_records_draining(tmp_path, capsys):
    """A graceful exit that still has work parked: the heartbeat says
    "done" (the process exited as asked) but the REGISTRY record says
    "draining" — and `cli obs ls --cmd serve --status draining` lists
    exactly the drain cycles a successor must pick up, with the
    claimed file intact."""
    spool = str(tmp_path / "spool")
    # a leftover claim from a previous daemon's crash
    pre = SpoolIntake(spool)
    pre.submit(PAX_JOB, "stuck")
    assert len(pre.poll()[0]) == 1
    reg_dir = str(tmp_path / "reg")
    hb_path = str(tmp_path / "hb.json")
    obs = Obs(ledger=RunLedger(str(tmp_path / "ledger.jsonl")),
              heartbeat=Heartbeat(hb_path),
              registry=RunRegistry(reg_dir),
              run_info={"cmd": "serve"})
    d = Daemon(spool, obs=obs, poll_s=0.0, sleep=lambda s: None)
    d.request_drain("supervisor handoff")
    assert d.run() == 0
    # recovered but never served: the claim survives for the successor
    assert d.stats["jobs_recovered"] == 1
    assert os.path.exists(os.path.join(spool, "claimed", "stuck.json"))
    assert json.load(open(hb_path))["status"] == "done"
    recs = [rec for _rid, rec in RunRegistry(reg_dir).records()]
    assert len(recs) == 1 and recs[0]["status"] == "draining"
    assert recs[0]["drain_reason"] == "supervisor handoff"

    from raft_tla_tpu import cli
    rc = cli.main(["obs", "ls", "--registry", reg_dir,
                   "--cmd", "serve", "--status", "draining"])
    assert not rc
    out = capsys.readouterr().out.splitlines()
    rows = out[1:]                        # drop the header
    assert len(rows) == 1
    assert "serve" in rows[0] and "draining" in rows[0]
    # the filter is honest: nothing matches status=done
    rc = cli.main(["obs", "ls", "--registry", reg_dir,
                   "--cmd", "serve", "--status", "done"])
    assert not rc
    assert capsys.readouterr().out.splitlines()[1:] == []


# ---------------------------------------------------------------------------
# watch daemon view
# ---------------------------------------------------------------------------

def _load_watch():
    spec = importlib.util.spec_from_file_location(
        "watch", os.path.join(_REPO, "tools", "watch.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.smoke
def test_watch_daemon_view_and_idle_cadence(tmp_path):
    """An idle-but-beating daemon is healthy even when its historical
    serving cadence says the gap is abnormal (the cadence rule is for
    runs, not pollers); the same numbers WITHOUT a daemon block do
    flag; a drained daemon's "done" renders FINISHED; and the daemon
    block renders queue depths, tenant rollups and the drain
    reason."""
    watch = _load_watch()
    now = time.time()
    hb = {"pid": os.getpid(), "depth": 3, "states_enqueued": 44,
          "status": "idle", "beats": 61,
          "started_ts": now - 180, "last_dispatch_ts": now - 120,
          "daemon": {"status": "idle", "cycles": 4, "incoming": 0,
                     "claimed": 0, "done": 5, "rejected": 1,
                     "jobs_done": 5, "cache_hits": 2, "violations": 0,
                     "jobs_recovered": 1,
                     "tenants": {"paxos": {"jobs_done": 5,
                                           "cache_hits": 2,
                                           "violations": 0}}}}
    hb_path = str(tmp_path / "hb.json")
    with open(hb_path, "w") as fh:
        json.dump(hb, fh)
    # cadence here is ~1s/beat over 61 beats; age 120s would trip the
    # 8x-cadence rule on a batch run — the daemon block suppresses it
    line, code = watch.status_line(hb_path, None, stale_s=300)
    assert code == 0 and "STALLED" not in line
    assert "daemon idle" in line and "cycle 4" in line
    assert "served 5 jobs" in line and "2 cache hits" in line
    assert "1 recovered" in line
    assert "tenant paxos: 5 done" in line
    # identical rhythm without the daemon block: the cadence rule bites
    hb2 = {k: v for k, v in hb.items() if k != "daemon"}
    hb2["status"] = "running"
    with open(hb_path, "w") as fh:
        json.dump(hb2, fh)
    line, code = watch.status_line(hb_path, None, stale_s=300)
    assert code == 1 and "STALLED?" in line
    # graceful drain: terminal "done" renders FINISHED, exit 0 — and
    # the drain reason line rides along
    hb["status"] = "done"
    hb["daemon"]["status"] = "done"
    hb["daemon"]["drain_reason"] = "signal SIGTERM"
    with open(hb_path, "w") as fh:
        json.dump(hb, fh)
    line, code = watch.status_line(hb_path, None, stale_s=300)
    assert code == 0 and "FINISHED" in line
    assert "draining: signal SIGTERM" in line
    # the absolute stale bound still guards a wedged daemon
    hb["status"] = "idle"
    hb["last_dispatch_ts"] = now - 9000
    with open(hb_path, "w") as fh:
        json.dump(hb, fh)
    line, code = watch.status_line(hb_path, None, stale_s=300)
    assert code == 1 and "STALLED?" in line
