"""Delta-matmul successor generation (round 11): frontier expansion as
MXU matrix algebra.

The contract is bit-exactness BY CONSTRUCTION, pinned differentially:
``delta_matmul=True`` (default) — every family with a declared delta
algebra applies as ONE batched scatter-as-matmul per family group —
must be an exact drop-in for the per-family kernel path in EVERY
engine: counts, level sizes, global ids, archives, witness traces,
violation states, sim trajectories and batched-serve waves, for raft
AND paxos.  A family without a declaration transparently keeps the
kernel path (pinned below by stripping one).  One fast representative
per engine family runs in tier-1; full-space duplicates are
slow-marked (870s budget)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tla_tpu.config import Bounds, ModelConfig, NEXT_ASYNC, \
    NEXT_DYNAMIC
from raft_tla_tpu.engine.bfs import Engine
from raft_tla_tpu.engine.expand import Expander
from raft_tla_tpu.engine.spill import SpillEngine
from raft_tla_tpu.spec import get_spec
from raft_tla_tpu.spec.paxos.config import PaxosConfig

# tiny configs (test_guard_matmul shapes: small spaces, fast)
TINY = ModelConfig(
    n_servers=2, init_servers=(0, 1), values=(1,),
    max_inflight_override=2, next_family=NEXT_ASYNC, symmetry=False,
    constraints=("BoundedInFlightMessages", "BoundedRequestVote",
                 "BoundedLogSize", "BoundedTerms"),
    invariants=("ElectionSafety", "LogMatching"),
    bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                       max_client_requests=1))

MICRO = ModelConfig(
    n_servers=2, init_servers=(0, 1), values=(1,),
    max_inflight_override=4, symmetry=True,
    bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                       max_client_requests=1))

# NextDynamic at S=3: every affine family gets lanes (incl. the
# Duplicate/Drop pair), mixed with every kernel-path family
DYN = ModelConfig(
    n_servers=3, init_servers=(0, 1), values=(1,),
    next_family=NEXT_DYNAMIC, symmetry=False, max_inflight_override=4,
    bounds=Bounds.make(max_log_length=2, max_timeouts=1,
                       max_client_requests=1))


def _key(r):
    return (r.distinct_states, r.generated_states, r.depth,
            tuple(r.level_sizes), r.violations_global)


def _oracle_key(cfg, max_depth=10 ** 9):
    from conftest import cached_explore
    w = cached_explore(cfg, max_depth=max_depth)
    return (w.distinct_states, w.generated_states, w.depth,
            tuple(w.level_sizes), len(w.violations))


def _reachable_svT(cfg, n=120):
    """A batch of reachable states, batch-last, via the oracle."""
    from conftest import cached_explore
    ir = get_spec(getattr(cfg, "spec", "raft"))
    lay = ir.make_layout(cfg)
    r = cached_explore(cfg, max_states=3 * n, keep_states=True)
    pairs = list(r.states.values())[:n]
    rows = [ir.encode(lay, sv, h) for sv, h in pairs]
    batch = ir.widen({k: np.stack([s[k] for s in rows])
                      for k in rows[0]})
    return {k: jnp.moveaxis(jnp.asarray(v), 0, -1)
            for k, v in batch.items()}


def _materialize_pair(cfg, svT):
    """(cand ON, cand OFF, famx ON, famx OFF, n_enabled) on a real
    guard mask over the batch — the full materialize surface."""
    ex_on = Expander(cfg, delta_matmul=True)
    ex_off = Expander(cfg, delta_matmul=False)
    derT = ex_on.derived_batch_T(svT)
    ok = np.asarray(ex_on.guards_T(svT, derT))
    B = ok.shape[0]
    okf = jnp.asarray(ok.reshape(-1))
    FCAP = int(ok.sum()) + 8
    epos = jnp.where(okf, jnp.cumsum(okf.astype(jnp.int32)) - 1, FCAP)
    caps = ex_on.default_fam_caps(B)
    c_on, f_on = jax.jit(lambda s, d: ex_on.materialize(
        s, d, okf, epos, FCAP, caps))(svT, derT)
    c_off, f_off = jax.jit(lambda s, d: ex_off.materialize(
        s, d, okf, epos, FCAP, caps))(svT, derT)
    return ex_on, c_on, c_off, f_on, f_off, int(ok.sum())


# ---------------------------------------------------------------------
# expander level: delta matmul ≡ kernel path (the @smoke acceptance pin)
# ---------------------------------------------------------------------


@pytest.mark.smoke
def test_delta_matmul_equals_kernel_path_on_reachable_states():
    """The group scatter-as-matmul reproduces every enabled successor
    bit-exactly on reachable NextDynamic states — all seven affine
    raft families (Timeout's clamped term, BecomeLeader's feat maxes,
    ClientRequest's log append, Duplicate/Drop, and round 17's
    UpdateTerm dst-one-hot sets + Restart minus its min-gap min)
    interleaved with the kernel-path families in oracle enumeration
    order."""
    svT = _reachable_svT(DYN, n=120)
    ex_on, c_on, c_off, f_on, f_off, n_e = _materialize_pair(DYN, svT)
    assert set(ex_on.delta_family_names) == {
        "BecomeLeader", "ClientRequest", "Timeout", "Duplicate",
        "Drop", "UpdateTerm", "Restart"}
    # declaration coverage: 7 of the NextDynamic registry's families
    # ride the delta path now — a silently dropped declaration (or a
    # regression back to the kernel path) fails here by count, not
    # just by name
    fam_names = [f.name for f in ex_on.families]
    declared = [f.name for f in ex_on.families
                if f.delta is not None]
    assert len(declared) == 7 and len(fam_names) > len(declared)
    np.testing.assert_array_equal(np.asarray(f_on), np.asarray(f_off))
    for k in c_on:
        np.testing.assert_array_equal(
            np.asarray(c_on[k])[..., :n_e],
            np.asarray(c_off[k])[..., :n_e], err_msg=k)
    assert n_e > 100          # the grid was live


def test_paxos_delta_matmul_equals_kernel_path():
    """Paxos: ALL four families are affine — expansion of the whole
    spec runs with zero per-family kernels (the declarations-only
    vectorization proof), bit-exact vs the kernel path, incl. the
    Phase1b data-dependent report bit and Phase2b re-accept sends."""
    cfg = PaxosConfig()
    svT = _reachable_svT(cfg, n=150)
    ex_on, c_on, c_off, f_on, f_off, n_e = _materialize_pair(cfg, svT)
    assert ex_on.delta_family_names == (
        "Phase1a", "Phase1b", "Phase2a", "Phase2b")
    np.testing.assert_array_equal(np.asarray(f_on), np.asarray(f_off))
    for k in c_on:
        np.testing.assert_array_equal(
            np.asarray(c_on[k])[..., :n_e],
            np.asarray(c_off[k])[..., :n_e], err_msg=k)


# ---------------------------------------------------------------------
# fast representatives, one per engine family (tier-1).
#
# The default flipped to delta_matmul=True, so the ENTIRE existing
# differential suite now exercises the delta path against the oracle;
# fresh fast coverage is (a) the classic-engine ON ≡ OFF pair (counts
# AND archives => identical global ids), and (b) the legacy OFF
# program staying oracle-correct in each engine family — the full
# ON/OFF pairs for the parallel engines are slow-marked below.
# ---------------------------------------------------------------------


def test_engine_delta_on_off_tiny():
    e_on = Engine(TINY, chunk=64, store_states=True, delta_matmul=True)
    r_on = e_on.check(max_depth=9)
    e_off = Engine(TINY, chunk=64, store_states=True,
                   delta_matmul=False)
    r_off = e_off.check(max_depth=9)
    assert _key(r_on) == _key(r_off)
    assert r_on.delta_matmul == 1 and r_off.delta_matmul == 0
    for pa, pb in zip(e_on._parents, e_off._parents):
        np.testing.assert_array_equal(pa, pb)
    for la, lb in zip(e_on._lanes, e_off._lanes):
        np.testing.assert_array_equal(la, lb)


def test_spill_delta_off_matches_oracle():
    r = SpillEngine(TINY, chunk=64, store_states=False, seg=1 << 10,
                    vcap=1 << 12, sync_every=2,
                    delta_matmul=False).check(max_depth=6)
    assert r.delta_matmul == 0
    assert _key(r) == _oracle_key(TINY, max_depth=6)


def test_mesh_delta_off_matches_oracle():
    from raft_tla_tpu.parallel.mesh import ShardedEngine
    r = ShardedEngine(TINY, chunk=64, store_states=False,
                      delta_matmul=False).check(max_depth=6)
    assert _key(r) == _oracle_key(TINY, max_depth=6)


def test_spill_mesh_delta_off_matches_oracle():
    from raft_tla_tpu.parallel.spill_mesh import SpilledShardedEngine
    r = SpilledShardedEngine(TINY, chunk=64, store_states=False,
                             lcap=1 << 11,
                             delta_matmul=False).check(max_depth=4)
    assert _key(r) == _oracle_key(TINY, max_depth=4)


def test_sim_delta_bit_identical_trajectories():
    """The fifth engine: same seed, delta ON vs OFF — walker
    trajectories, counters and Bloom estimates all bit-identical
    (identical guards => identical draws => step_lanes must land the
    identical successor through the group matmul)."""
    from raft_tla_tpu.sim.walker import SimEngine
    cfg = TINY.with_(invariants=("ElectionSafety",))
    out = {}
    for dm in (True, False):
        eng = SimEngine(cfg, walkers=8, max_depth=8, seed=3,
                        bloom_bits=12, delta_matmul=dm)
        r = eng.run(steps=24, steps_per_dispatch=8, stop_on_hit=False)
        out[dm] = (r.walker_steps, r.sampled_steps, r.restarts,
                   r.deadlocks, r.promotions, len(r.hits),
                   round(float(r.est_distinct_states), 3))
    assert out[True] == out[False]


def test_paxos_engine_delta_on_off_full_space():
    """Paxos stock model end-to-end: ON ≡ OFF on the full 857-state
    symmetric space (tiny, so the full space IS the fast rep) — the
    declarations-only tenant never touches a hand-written kernel on
    the delta path."""
    pc = PaxosConfig()
    r_on = Engine(pc, chunk=128, store_states=False,
                  delta_matmul=True).check()
    r_off = Engine(pc, chunk=128, store_states=False,
                   delta_matmul=False).check()
    assert _key(r_on) == _key(r_off)
    assert r_on.distinct_states == 857
    assert r_on.delta_matmul == 1 and r_off.delta_matmul == 0


@pytest.mark.slow  # tier-1 budget (round 14): ~38s; batched-serve
# runs with delta ON (the default) in every fast test_serve rep, and
# tools/delta_smoke.py pins CLI ON≡OFF counts each CI run.
def test_serve_batch_delta_wave_matches_sequential():
    """A batched `cli batch` wave with delta ON (the default) is
    bit-exact per job vs the sequential reference — the job-vmapped
    burst core vmaps the group delta matmul cleanly.  (The reference
    is ONE solo engine checked per depth gate — what run_jobs
    --sequential does per job, minus the per-job engine compiles the
    tier-1 budget can't afford.)"""
    from raft_tla_tpu.serve import Job, run_jobs

    rb = run_jobs([Job(MICRO, max_depth=4, label="a",
                       store_states=False),
                   Job(MICRO, max_depth=6, label="b",
                       store_states=False)])
    solo = Engine(MICRO, store_states=False)
    for ob, depth in zip(rb.outcomes, (4, 6)):
        rs = solo.check(max_depth=depth)
        assert ob.status == "done"
        assert ob.report["distinct_states"] == rs.distinct_states
        assert ob.report["generated_states"] == rs.generated_states
        assert ob.report["depth"] == rs.depth
        assert ob.report["level_sizes"] == list(rs.level_sizes)
        assert ob.report["violations"] == len(rs.violations)
        assert ob.report["delta_matmul"] == 1


# ---------------------------------------------------------------------
# the fallback contract: a family WITHOUT a delta declaration silently
# keeps the kernel path (acceptance pin: strip one declaration)
# ---------------------------------------------------------------------


def test_family_without_delta_declaration_uses_kernel_path():
    ir = get_spec("raft")
    orig = ir.build_families

    def stripped(lay):
        fams = orig(lay)
        for f in fams:
            if f.name == "Timeout":
                f.delta = None            # Family is a plain dataclass
        return fams

    # SpecIR is a frozen dataclass and the registry caches the
    # instance: swap the hook via object.__setattr__, restore always
    object.__setattr__(ir, "build_families", stripped)
    try:
        ex = Expander(TINY, delta_matmul=True)
        assert "Timeout" not in ex.delta_family_names
        assert "ClientRequest" in ex.delta_family_names
        r_on = Engine(TINY, chunk=64, store_states=False,
                      delta_matmul=True).check(max_depth=6)
        # still stamped ON: the group just lost one family
        assert r_on.delta_matmul == 1
    finally:
        object.__setattr__(ir, "build_families", orig)
    assert _key(r_on) == _oracle_key(TINY, max_depth=6)


@pytest.mark.smoke
def test_delta_group_compiles_and_validates():
    """Group compilation invariants: the matrices cover exactly the
    declared families' lanes, V has one source per triple, P one slot
    per triple — and a declaration writing outside the state view
    fails loudly naming the family."""
    from raft_tla_tpu.engine.expand import Family
    ex = Expander(TINY, delta_matmul=True)
    dg = ex._dgroup
    assert dg["n_lanes"] == sum(
        f.n_lanes for f in ex.families if f.delta is not None)
    assert (np.asarray(dg["Q"]).sum(axis=0) == 1).all()
    assert (np.asarray(dg["P"]).sum(axis=1) == 1).all()
    # lane_to_aff marks exactly the affine lanes
    marked = (np.asarray(dg["lane_to_aff"]) >= 0).sum()
    assert marked == dg["n_lanes"]
    # a bad declaration errors by family name, not a jit traceback
    ir = get_spec("raft")
    orig = ir.build_families

    def bad(lay):
        fams = orig(lay)
        fams[1] = Family(
            fams[1].name, fams[1].fn, fams[1].params, fams[1].labeler,
            guard=fams[1].guard,
            delta=lambda off, lay, i: [(10 ** 9, 0, 1)])
        return fams

    object.__setattr__(ir, "build_families", bad)
    try:
        with pytest.raises(KeyError, match="BecomeLeader"):
            Expander(TINY, delta_matmul=True)
    finally:
        object.__setattr__(ir, "build_families", orig)


# ---------------------------------------------------------------------
# full-space duplicates (slow: the 870s tier-1 budget)
# ---------------------------------------------------------------------


@pytest.mark.slow
def test_engine_delta_full_space_archives_and_traces():
    """Classic engine on the symmetric micro space (incremental
    fingerprints engaged): ON ≡ OFF across counts, archives (=>
    identical global ids) and a replayed witness trace."""
    e_on = Engine(MICRO, chunk=64, store_states=True, delta_matmul=True)
    r_on = e_on.check()
    e_off = Engine(MICRO, chunk=64, store_states=True,
                   delta_matmul=False)
    r_off = e_off.check()
    assert _key(r_on) == _key(r_off)
    for sa, sb in zip(e_on._states, e_off._states):
        for k in sa:
            np.testing.assert_array_equal(sa[k], sb[k])
    gid = r_on.distinct_states - 1
    ta = [(lbl, repr(sv)) for lbl, sv in e_on.trace(gid)]
    tb = [(lbl, repr(sv)) for lbl, sv in e_off.trace(gid)]
    assert ta == tb


@pytest.mark.slow
def test_delta_violation_states_identical():
    """Scenario witness hunt: reported violation ids, states and
    traces match ON vs OFF."""
    cfg = TINY.with_(invariants=("FirstBecomeLeader",))
    outs = {}
    for dm in (True, False):
        eng = Engine(cfg, chunk=64, store_states=True, delta_matmul=dm)
        r = eng.check(stop_on_violation=True)
        assert r.violations, "scenario witness not found"
        v = r.violations[0]
        outs[dm] = (v.invariant, v.state_id, repr(v.state),
                    [(lbl, repr(sv)) for lbl, sv in
                     eng.trace(v.state_id)])
    assert outs[True] == outs[False]


@pytest.mark.slow
def test_spill_delta_on_off_full_space():
    rs = {}
    for dm in (True, False):
        rs[dm] = SpillEngine(MICRO, chunk=64, store_states=False,
                             seg=1 << 10, vcap=1 << 12, sync_every=2,
                             delta_matmul=dm).check()
    assert _key(rs[True]) == _key(rs[False])


@pytest.mark.slow
def test_mesh_delta_on_off_full_space():
    from raft_tla_tpu.parallel.mesh import ShardedEngine
    rs = {}
    for dm in (True, False):
        rs[dm] = ShardedEngine(TINY, chunk=64, store_states=False,
                               delta_matmul=dm).check()
    assert _key(rs[True]) == _key(rs[False])


@pytest.mark.slow
def test_delta_without_guard_matmul_cross_mode():
    """The two MXU flags are independent: delta ON composes with the
    legacy guard lane sweep (guard_matmul=False) bit-exactly."""
    r_a = Engine(TINY, chunk=64, store_states=False,
                 guard_matmul=False, delta_matmul=True).check()
    r_b = Engine(TINY, chunk=64, store_states=False,
                 guard_matmul=False, delta_matmul=False).check()
    assert _key(r_a) == _key(r_b)
    assert r_a.delta_matmul == 1 and r_a.guard_matmul == 0


@pytest.mark.slow
def test_paxos_multi_instance_delta_on_off():
    """Multi-instance paxos (I=2): instance-major lane grids stay
    bit-exact through the group delta."""
    pc = PaxosConfig(n_instances=2)
    r_on = Engine(pc, chunk=128, store_states=False,
                  delta_matmul=True).check(max_depth=8)
    r_off = Engine(pc, chunk=128, store_states=False,
                   delta_matmul=False).check(max_depth=8)
    assert _key(r_on) == _key(r_off)


# ---------------------------------------------------------------------
# chunk skip (round 14, the ROADMAP item-3 leftover): the delta group
# applies as per-family lax.cond blocks, skipping a family's whole
# cap-wide slice when the chunk enables none of its lanes.  Default
# follows the MXU lowering (ON on TPU, OFF on CPU); forced ON here to
# pin both cond branches bit-exact against the kernel path.
# ---------------------------------------------------------------------


def _materialize_skip(cfg, svT):
    """cand under delta_chunk_skip=True on a real guard mask, plus the
    kernel-path reference and per-family enabled counts."""
    ex_skip = Expander(cfg, delta_matmul=True, delta_chunk_skip=True)
    ex_off = Expander(cfg, delta_matmul=False)
    assert ex_skip.delta_chunk_skip and not ex_off.delta_chunk_skip
    derT = ex_skip.derived_batch_T(svT)
    ok = np.asarray(ex_skip.guards_T(svT, derT))
    B = ok.shape[0]
    okf = jnp.asarray(ok.reshape(-1))
    FCAP = int(ok.sum()) + 8
    epos = jnp.where(okf, jnp.cumsum(okf.astype(jnp.int32)) - 1, FCAP)
    caps = ex_skip.default_fam_caps(B)
    c_skip, f_skip = jax.jit(lambda s, d: ex_skip.materialize(
        s, d, okf, epos, FCAP, caps))(svT, derT)
    c_off, f_off = jax.jit(lambda s, d: ex_off.materialize(
        s, d, okf, epos, FCAP, caps))(svT, derT)
    famx = {ex_skip.families[fi].name: int(np.asarray(f_skip)[fi])
            for fi in ex_skip._dgroup["fam_idx"]}
    return c_skip, c_off, f_skip, f_off, int(ok.sum()), famx


@pytest.mark.slow
def test_delta_chunk_skip_equals_kernel_path():
    """Reachable-state batch: some affine families enable lanes (apply
    branch), others none (skip branch — early BFS states have no
    leader, so BecomeLeader/ClientRequest sit disabled) — columns
    bit-equal to the kernel path either way.  (The fast/smoke rep is
    the root-chunk test below; the engine-scale pair is slow too.)"""
    svT = _reachable_svT(DYN, n=120)
    c_skip, c_off, f_skip, f_off, n_e, famx = _materialize_skip(
        DYN, svT)
    np.testing.assert_array_equal(np.asarray(f_skip),
                                  np.asarray(f_off))
    assert any(v > 0 for v in famx.values()), famx
    assert any(v == 0 for v in famx.values()), famx
    for k in c_skip:
        np.testing.assert_array_equal(
            np.asarray(c_skip[k])[..., :n_e],
            np.asarray(c_off[k])[..., :n_e], err_msg=k)


@pytest.mark.smoke
def test_delta_chunk_skip_all_disabled_families():
    """Root-only chunk: several affine families enable NO lanes, so
    their conds take the SKIP branch — enabled successors still
    bit-equal the kernel path (the skipped slices were compaction
    garbage no consumer reads)."""
    ir = get_spec("raft")
    lay = ir.make_layout(DYN)
    row = ir.widen(ir.encode(lay, *ir.init_state(DYN)))
    svT = {k: jnp.moveaxis(jnp.asarray(np.stack([np.asarray(v)] * 4)),
                           0, -1) for k, v in row.items()}
    c_skip, c_off, f_skip, f_off, n_e, famx = _materialize_skip(
        DYN, svT)
    np.testing.assert_array_equal(np.asarray(f_skip),
                                  np.asarray(f_off))
    # the init chunk really drives the skip branch: Timeout fires,
    # the message-dependent affine families (Duplicate/Drop) cannot
    assert famx["Timeout"] > 0 and famx["Duplicate"] == 0 \
        and famx["Drop"] == 0, famx
    assert n_e > 0
    for k in c_skip:
        np.testing.assert_array_equal(
            np.asarray(c_skip[k])[..., :n_e],
            np.asarray(c_off[k])[..., :n_e], err_msg=k)


@pytest.mark.slow
def test_engine_delta_chunk_skip_full_space():
    """End-to-end: a chunk-skip engine reproduces the default engine's
    counts, archives and gids over the full TINY space (per-level
    chunks routinely enable only a subset of families — both cond
    branches exercised at engine scale)."""
    e_skip = Engine(TINY, chunk=64, store_states=True,
                    delta_chunk_skip=True)
    r_skip = e_skip.check()
    e_def = Engine(TINY, chunk=64, store_states=True)
    r_def = e_def.check()
    assert _key(r_skip) == _key(r_def)
    for pa, pb in zip(e_skip._parents, e_def._parents):
        np.testing.assert_array_equal(pa, pb)
    for la, lb in zip(e_skip._lanes, e_def._lanes):
        np.testing.assert_array_equal(la, lb)
