"""The apalache-variant divergence (SURVEY §2.7, §7.3 exit criterion).

apalache_no_membership knowingly ships Ricketts' original —
documented-FALSE — forms of VotesGrantedInv and LeaderCompleteness as
its live invariants (apalache_no_membership/raft.tla:715-723, 746-750;
the tlc variant documents the falsity at tlc_membership/raft.tla:
1028-1035, 1072-1075).  A faithful checker must FIND the
LeaderCompleteness violation: it fires when a commit happens under
concurrent leaders, which needs >= 3 servers (the shipped cfg binds
Server={1,2}, where concurrent leaders are unreachable — so the spec
"checked clean" for its authors).

The hunt uses the reference's own signature technique: punctuated
search.  The 20-record ConcurrentLeaders witness (the hard-coded
prefix inside CommitWhenConcurrentLeaders_unique,
tlc_membership/raft.tla:1198-1204) replays under the apalache-variant
semantics at S=3 and seeds the search; both the oracle and the TPU
engine then find the commit-under-two-leaders violation of the false
LeaderCompleteness at the same depth, and the corrected (verdi-raft)
form of the tlc variant holds on the very same search — proving the
divergence is the invariant FORM, not the engine.
"""

import sys

import pytest

sys.path.insert(0, str(__import__("pathlib").Path(__file__).parent))

from golden import CONCURRENT_LEADERS_LABELS, CWCL_EXTENSION_LABELS

from raft_tla_tpu.cfg.parser import load_model
from raft_tla_tpu.config import Bounds
from raft_tla_tpu.engine.bfs import Engine
from raft_tla_tpu.models import predicates
from raft_tla_tpu.models.explore import explore
from raft_tla_tpu.models.raft import init_state, successors


def _ap_cfg():
    from conftest import ref_or_local
    cfg = load_model(
        ref_or_local("/root/reference/apalache_no_membership/raft.cfg"),
                     bounds=Bounds.make(max_log_length=2, max_timeouts=3,
                                        max_client_requests=2))
    # concurrent leaders need 3 servers; the shipped Server={1,2}
    # binding cannot reach the violation
    return cfg.with_(n_servers=3, init_servers=(0, 1, 2))


def _seed(cfg, labels=CONCURRENT_LEADERS_LABELS):
    sv, h = init_state(cfg)
    for lbl in labels:
        matches = [(s2, h2) for l, s2, h2 in successors(sv, h, cfg)
                   if l == lbl]
        assert len(matches) == 1, lbl
        sv, h = matches[0]
    return sv, h


@pytest.mark.slow
def test_apalache_false_leader_completeness_found():
    """Oracle and TPU engine, seeded with the ConcurrentLeaders
    witness, find the LeaderCompleteness_false violation at the same
    depth; the live apalache name resolves to the false form."""
    cfg = _ap_cfg().with_(invariants=("LeaderCompleteness",))
    assert cfg.apalache_variant
    fn = predicates.resolve_invariant("LeaderCompleteness", cfg)
    assert fn is predicates.INVARIANTS["LeaderCompleteness_false"]

    seed = _seed(cfg)
    want = explore(cfg, seed_states=[seed], stop_on_violation=True,
                   trace_violations=True, max_states=200_000)
    assert want.violations, "oracle did not find the violation"
    assert want.violations[0].invariant == "LeaderCompleteness"

    eng = Engine(cfg, chunk=256, store_states=True)
    got = eng.check(seed_states=[seed], stop_on_violation=True,
                    max_states=200_000)
    assert got.violations, "engine did not find the violation"
    assert got.violations[0].invariant == "LeaderCompleteness"
    assert got.depth == want.depth, (got.depth, want.depth)
    # the engine reconstructs a witness extension ending in the commit
    chain = eng.trace(got.violations[0].state_id)
    labels = [lbl for lbl, _ in chain]
    assert any(lbl.startswith("AdvanceCommitIndex") for lbl in labels)


def test_apalache_false_votes_granted_inv_found():
    """VotesGrantedInv_false fires one step past the 28-record
    CommitWhenConcurrentLeaders witness: UpdateTerm pulls the old
    term-2 leader s0 (whose STALE votesGranted={s0,s1} survives, the
    exact variable-meaning confusion the reference documents at
    tlc_membership/raft.tla:1028-1035) into s1's term while s1 holds
    committed entries that conflict with s0's log.  Both engines find
    it at depth 1 from the seed."""
    cfg = _ap_cfg().with_(invariants=("VotesGrantedInv",))
    fn = predicates.resolve_invariant("VotesGrantedInv", cfg)
    assert fn is predicates.INVARIANTS["VotesGrantedInv_false"]

    seed = _seed(cfg, CONCURRENT_LEADERS_LABELS + CWCL_EXTENSION_LABELS)
    want = explore(cfg, seed_states=[seed], stop_on_violation=True,
                   trace_violations=True, max_states=50_000)
    assert want.violations
    assert want.violations[0].invariant == "VotesGrantedInv"
    assert want.depth == 1          # UpdateTerm(0) away from the seed

    eng = Engine(cfg, chunk=256, store_states=True)
    got = eng.check(seed_states=[seed], stop_on_violation=True,
                    max_states=50_000)
    assert got.violations
    assert got.violations[0].invariant == "VotesGrantedInv"
    assert got.depth == want.depth


def test_corrected_votes_granted_inv_holds_on_same_search():
    """Contrast: the tlc variant's corrected VotesGrantedInv
    (votedFor-based, tlc_membership/raft.tla:1048-1052) holds on the
    same seeded search."""
    cfg = _ap_cfg().with_(invariants=("VotesGrantedInv",),
                          apalache_variant=False)
    fn = predicates.resolve_invariant("VotesGrantedInv", cfg)
    assert fn is predicates.INVARIANTS["VotesGrantedInv"]
    seed = _seed(cfg, CONCURRENT_LEADERS_LABELS + CWCL_EXTENSION_LABELS)
    r = explore(cfg, seed_states=[seed], max_states=5_000)
    assert not r.violations


def test_corrected_leader_completeness_holds_on_same_search():
    """Contrast: the tlc variant's corrected LeaderCompleteness
    (verdi-raft form, tlc_membership/raft.tla:1089-1099) holds on the
    exact same seeded search that violates the false form."""
    cfg = _ap_cfg().with_(invariants=("LeaderCompleteness",),
                          apalache_variant=False)
    fn = predicates.resolve_invariant("LeaderCompleteness", cfg)
    assert fn is predicates.INVARIANTS["LeaderCompleteness"]
    seed = _seed(cfg)
    r = explore(cfg, seed_states=[seed], max_states=5_000)
    assert not r.violations
