"""The shared level-harvest/driver core (engine/driver — ROADMAP item
5): unit reps for the extracted loop's exact semantics (depth gate,
id guard, burst checkpoint crossing, callback ordering) plus the
routing reps pinning that all FIVE former copies (bfs, spill, mesh,
spill_mesh, batched serve) actually call it — the bit-exactness of the
re-homed call sites themselves is pinned by every existing engine
differential (test_engine / test_spill / test_sharded /
test_spill_mesh / test_serve run unchanged).
"""

import inspect

import numpy as np
import pytest

from raft_tla_tpu.engine import driver
from raft_tla_tpu.engine.bfs import CheckResult


def _res():
    return CheckResult()


# ---------------------------------------------------------------------------
# unit reps: the extracted semantics, exactly
# ---------------------------------------------------------------------------

def test_ckpt_due_after_burst_crosses_any_multiple():
    # a multi-level jump over a multiple fires even when the landing
    # depth is not an exact multiple (the exact-modulo test would skip
    # every checkpoint with checkpoint_every > 1)
    assert driver.ckpt_due_after_burst(7, 3, 5)        # crossed 5
    assert not driver.ckpt_due_after_burst(4, 3, 5)    # no multiple
    assert driver.ckpt_due_after_burst(10, 9, 5)       # exact landing
    assert driver.ckpt_due_after_burst(23, 4, 5)       # several crossed
    # checkpoint_every <= 1 clamps to every level
    assert driver.ckpt_due_after_burst(2, 1, 0)


def test_ckpt_due_at_level_plain_modulo():
    assert driver.ckpt_due_at_level(10, 5)
    assert not driver.ckpt_due_at_level(9, 5)
    assert driver.ckpt_due_at_level(3, 1)
    assert driver.ckpt_due_at_level(3, 0)        # clamped to 1


def test_guard_id_space():
    driver.guard_id_space(2 ** 31 - 2)           # fine
    with pytest.raises(RuntimeError, match="state-id space exhausted"):
        driver.guard_id_space(2 ** 31 - 1)


def test_gate_level_depth():
    res = _res()
    # all-pruned pseudo-level: depth rolls back, no level size
    assert driver.gate_level_depth(res, 5, 0, 0, 17) == 4
    assert res.level_sizes == []
    # all-duplicates level (n_gen > 0) DOES count
    assert driver.gate_level_depth(res, 5, 0, 3, 17) == 5
    assert res.level_sizes == [17]
    assert driver.gate_level_depth(res, 6, 2, 9, 11) == 6
    assert res.level_sizes == [17, 11]


def test_harvest_fused_levels_accumulation_and_gating():
    res = _res()
    # levels: (n_lvl, n_viol, faults, n_expand, n_gen)
    stats = [(3, 1, 0, 7, 9),       # real level with a violation
             (0, 0, 0, 5, 0),       # all-pruned pseudo-level
             (0, 0, 1, 4, 2),       # all-duplicates level: counts
             (2, 0, 0, 6, 8)]
    calls = []
    depth, n_states = driver.harvest_fused_levels(
        res, len(stats), lambda li: stats[li], 10, 100,
        archive=lambda li, n: calls.append(("arch", li, n)),
        violations=lambda li, n, base: calls.append(("viol", li, n,
                                                     base)),
        visited=lambda li, n: calls.append(("vis", li, n)))
    assert depth == 13                  # 3 real levels of 4
    assert n_states == 105
    assert res.distinct_states == 5
    assert res.generated_states == 19
    assert res.overflow_faults == 1
    assert res.violations_global == 1
    assert res.levels_fused == 3        # ≡ depth advanced
    assert res.level_sizes == [7, 4, 6]
    # archive runs for EVERY level (the callback owns its own
    # empty-level policy); violations only where seen, with the
    # PRE-increment gid base; visited after the gid advance, per level
    assert calls == [("arch", 0, 3), ("viol", 0, 3, 100),
                     ("vis", 0, 3),
                     ("arch", 1, 0), ("vis", 1, 0),
                     ("arch", 2, 0), ("vis", 2, 0),
                     ("arch", 3, 2), ("vis", 3, 2)]


def test_harvest_fused_levels_id_guard_flag():
    near = 2 ** 31 - 3
    stats = [(2, 0, 0, 2, 2)]
    with pytest.raises(RuntimeError, match="state-id space"):
        driver.harvest_fused_levels(_res(), 1, lambda li: stats[li],
                                    0, near)
    # id_guard=False preserves the batched-serve semantics (per-job
    # ids never approach 2^31; the historical serve harvest carried
    # no guard)
    depth, n = driver.harvest_fused_levels(
        _res(), 1, lambda li: stats[li], 0, near, id_guard=False)
    assert (depth, n) == (1, near + 2)


def test_burst_archive_slice_copies_out_of_ring():
    L, KB = 3, 4
    par = np.arange(L * KB, dtype=np.int32).reshape(L, KB)
    lane = par + 100
    st = {"x": np.arange(2 * 5 * L * KB, dtype=np.int32)
          .reshape(2, 5, L, KB)}
    p, ln, rows = driver.burst_archive_slice(par, lane, st, 1, 2)
    assert p.tolist() == [4, 5] and ln.tolist() == [104, 105]
    assert rows["x"].shape == (2, 2, 5)     # batch-major
    assert np.array_equal(rows["x"][0], st["x"][:, :, 1, 0])
    # the slices are COPIES (the ring buffer is reused next burst)
    p[0] = -1
    assert par[1, 0] == 4


# ---------------------------------------------------------------------------
# routing reps: the five former copies all call the shared core (the
# point of ROADMAP item 5 — control-flow duplication is dead, so a
# drift class can no longer exist)
# ---------------------------------------------------------------------------

FIVE_CALL_SITES = [
    ("raft_tla_tpu.engine.bfs", "Engine"),
    ("raft_tla_tpu.engine.spill", "SpillEngine"),
    ("raft_tla_tpu.parallel.mesh", "ShardedEngine"),
    ("raft_tla_tpu.parallel.spill_mesh", "SpilledShardedEngine"),
    ("raft_tla_tpu.serve.batch", "BucketEngine"),
]


@pytest.mark.parametrize("modname,_cls", FIVE_CALL_SITES)
def test_harvest_routes_through_driver(modname, _cls):
    import importlib
    src = inspect.getsource(importlib.import_module(modname))
    assert "harvest_fused_levels" in src, \
        f"{modname}: fused harvest no longer routes through " \
        "engine/driver"
    # the tell-tale of a re-inlined copy: the pseudo-level counter
    # bump next to a local depth increment (levels_fused is accounted
    # INSIDE driver.harvest_fused_levels / the per-level drivers'
    # shared gate only)
    assert "res.levels_fused += 1" not in src, \
        f"{modname}: a local harvest copy re-appeared"


def test_per_level_drivers_share_the_gate():
    import importlib
    for modname in ("raft_tla_tpu.engine.bfs",
                    "raft_tla_tpu.parallel.mesh",
                    "raft_tla_tpu.engine.spill",
                    "raft_tla_tpu.parallel.spill_mesh"):
        src = inspect.getsource(importlib.import_module(modname))
        assert ("gate_level_depth" in src
                or "harvest_fused_levels" in src), modname
        # checkpoint crossing decisions live in driver too
        assert ("ckpt_due_at_level" in src
                or "ckpt_due_after_burst" in src), modname
