"""End-to-end engine differential tests vs the oracle BFS.

Exit criterion from SURVEY §7.3: identical distinct-state counts and
identical invariant verdicts on the same model, with and without
symmetry reduction.
"""

from collections import Counter

import pytest

from raft_tla_tpu.config import Bounds, ModelConfig, NEXT_DYNAMIC, NEXT_FULL
from raft_tla_tpu.engine.bfs import Engine
from raft_tla_tpu.models.explore import explore

MICRO = ModelConfig(
    n_servers=2, init_servers=(0, 1), values=(1,),
    max_inflight_override=4,
    bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                       max_client_requests=1),
    symmetry=False)

SMALL = ModelConfig(
    n_servers=2, init_servers=(0, 1), values=(1,),
    bounds=Bounds.make(max_log_length=2, max_timeouts=2),
    symmetry=False)

MEMBER = ModelConfig(
    n_servers=3, init_servers=(0, 1), values=(1,),
    next_family=NEXT_DYNAMIC, max_inflight_override=6,
    bounds=Bounds.make(max_log_length=2, max_timeouts=1,
                       max_client_requests=1, max_membership_changes=1),
    symmetry=False)


def compare(cfg, max_depth=10 ** 9, max_states=10 ** 9, **engine_kw):
    want = explore(cfg, max_depth=max_depth, max_states=max_states)
    eng = Engine(cfg, chunk=256, **engine_kw)
    got = eng.check(max_depth=max_depth, max_states=max_states)
    assert got.overflow_faults == 0
    assert got.distinct_states == want.distinct_states, \
        (got.distinct_states, want.distinct_states)
    assert got.depth == want.depth, (got.depth, want.depth)
    want_viol = Counter(v.invariant for v in want.violations)
    got_viol = Counter(v.invariant for v in got.violations)
    assert got_viol == want_viol, (got_viol, want_viol)
    return eng, got


@pytest.mark.parametrize("sym", [
    False,
    # slow-marked (tier-1 budget, PR 2): the sym variant repeats the
    # same space under canonicalization for +80s
    pytest.param(True, marks=pytest.mark.slow),
], ids=["nosym", "sym"])
def test_micro_exhaustive(sym):
    compare(MICRO.with_(symmetry=sym))


@pytest.mark.slow
def test_micro_fp128():
    """128-bit fingerprints (4 streams, structured dedup keys) must give
    identical counts."""
    compare(MICRO.with_(fp128=True))


@pytest.mark.slow
def test_small_bounded():
    compare(SMALL, max_depth=6)


@pytest.mark.slow
def test_small_symmetric_exhaustive():
    compare(SMALL.with_(symmetry=True), max_depth=8)


@pytest.mark.slow
def test_membership_bounded():
    compare(MEMBER, max_depth=5)


@pytest.mark.slow
def test_unreliable_bounded():
    compare(SMALL.with_(next_family=NEXT_FULL), max_depth=4)


def test_violation_and_trace():
    """Scenario property: engine finds the FirstCommit witness and can
    reconstruct its trace (the 15-step election+replication chain)."""
    cfg = MICRO.with_(invariants=("FirstCommit",), symmetry=True)
    eng = Engine(cfg, chunk=256, store_states=True)
    got = eng.check(stop_on_violation=True)
    assert got.violations
    v = got.violations[0]
    sv, h = eng.get_state(v.state_id)
    assert any(c > 0 for c in sv.ci)
    chain = eng.trace(v.state_id)
    assert chain[0][0] == "Init"
    assert len(chain) == 16  # 15 actions + Init
    # oracle agrees on the depth of the first witness
    want = explore(cfg, stop_on_violation=True, trace_violations=True)
    assert len(want.violations[0].trace) == 15
