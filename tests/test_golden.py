"""Golden-trace fixtures replayed through oracle AND engine, plus the
punctuated-search CLI flow end-to-end (seed emit -> seeded check).

Mirrors the reference's signature technique: pin the search to a known
witness prefix and explore only its extensions
(tlc_membership/raft.tla:1188-1234, "punctuated search").
"""

import json
import subprocess
import sys

import numpy as np
import pytest

from raft_tla_tpu.config import LEADER, ModelConfig, NEXT_ASYNC
from raft_tla_tpu.models.raft import init_state, state_to_obj, successors
from raft_tla_tpu.models import predicates

from golden import (CONCURRENT_LEADERS_LABELS, CWCL_EXTENSION_LABELS,
                    GOLDEN_20_KINDS, GOLDEN_28_KINDS)

CFG3 = ModelConfig(n_servers=3, init_servers=(0, 1, 2), values=(1, 2),
                   next_family=NEXT_ASYNC)
from conftest import ref_or_local

TLC_CFG = ref_or_local("/root/reference/tlc_membership/raft.cfg")


def apply_label(sv, h, cfg, label):
    matches = [(s2, h2) for l, s2, h2 in successors(sv, h, cfg)
               if l == label]
    assert matches, f"no successor labelled {label}"
    assert len(matches) == 1, f"ambiguous label {label}"
    return matches[0]


def replay(labels, cfg=CFG3, start=None):
    sv, h = start if start is not None else init_state(cfg)
    states = [(sv, h)]
    for lbl in labels:
        sv, h = apply_label(sv, h, cfg, lbl)
        states.append((sv, h))
    return states


def test_golden_concurrent_leaders_oracle():
    """Replaying the 20-record ConcurrentLeaders witness
    (raft.tla:1201) reaches exactly the documented end state."""
    sv, h = replay(CONCURRENT_LEADERS_LABELS)[-1]
    assert [r[0] for r in h.glob] == GOLDEN_20_KINDS
    # golden trailer: hadNumLeaders=2, timeouts s1=1 s2=1 s3=0,
    # no restarts, no client requests (raft.tla:1201)
    assert h.nleaders == 2 and h.nreq == 0
    assert h.timeout == (1, 1, 0) and h.restarted == (0, 0, 0)
    assert sv.st[0] == LEADER and sv.st[1] == LEADER
    assert sv.ct == (2, 3, 3)
    # ConcurrentLeaders scenario property fires here (raft.tla:1158)
    assert not predicates.INVARIANTS["ConcurrentLeaders"](sv, h, CFG3)


def test_golden_cwcl_oracle():
    """The 28-record CommitWhenConcurrentLeaders witness
    (raft.tla:1231): a commit lands while two leaders coexist."""
    sv, h = replay(CONCURRENT_LEADERS_LABELS + CWCL_EXTENSION_LABELS)[-1]
    assert [r[0] for r in h.glob] == GOLDEN_28_KINDS
    assert h.nreq == 2 and h.nleaders == 2
    # CommitEntry at record 26, trace runs 2 further records, and both
    # leaders still stand (raft.tla:1165-1176)
    assert h.glob[25][0] == "CommitEntry"
    assert sv.st[0] == LEADER and sv.st[1] == LEADER
    assert sv.ci == (0, 1, 0)
    assert not predicates.INVARIANTS["CommitWhenConcurrentLeaders"](
        sv, h, CFG3)


def test_golden_engine_replay():
    """Every golden step is reproduced by the device expansion: the
    child's fingerprint appears among the parent's enabled candidates,
    and the engine's scenario predicate fires on the end state."""
    import jax
    from raft_tla_tpu.engine.bfs import Engine, fp_key
    from raft_tla_tpu.ops.codec import encode

    cfg = CFG3.with_(symmetry=False)
    eng = Engine(cfg, chunk=1, store_states=False)
    states = replay(CONCURRENT_LEADERS_LABELS + CWCL_EXTENSION_LABELS,
                    cfg=cfg)
    enc = [encode(eng.lay, sv, h) for sv, h in states]
    fp1 = jax.jit(eng.fpr.fingerprint)
    for step, (parent, child) in enumerate(zip(enc, enc[1:])):
        svb = {k: np.asarray(v)[None] for k, v in parent.items()}
        ok, _cand, fp = eng._phase1(svb)
        keys = fp_key(np.asarray(fp).reshape(-1, eng.fpr.n_streams))
        child_key = fp_key(np.asarray(fp1(
            {k: np.asarray(v) for k, v in child.items()}))[None])[0]
        hit = (keys == child_key) & np.asarray(ok).reshape(-1)
        label = (CONCURRENT_LEADERS_LABELS + CWCL_EXTENSION_LABELS)[step]
        assert hit.any(), f"step {step} ({label}) not among candidates"
    # end state: engine-side CommitWhenConcurrentLeaders verdict
    final = {k: np.asarray(v)[None] for k, v in enc[-1].items()}
    eng2 = Engine(cfg.with_(
        invariants=("CommitWhenConcurrentLeaders",)), chunk=1,
        store_states=False)
    inv, _con = eng2._phase2({k: np.asarray(v) for k, v in final.items()})
    assert not bool(np.asarray(inv)[0, 0]), \
        "engine must report CommitWhenConcurrentLeaders violated"


def run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "raft_tla_tpu", *args],
        capture_output=True, text=True, timeout=1200)


@pytest.mark.slow
def test_punctuated_search_cli(tmp_path):
    """End-to-end punctuated search (raft.tla:1198-1210): seed = the
    golden ConcurrentLeaders end state; a seeded check with the CWCL
    action constraint finds CommitWhenConcurrentLeaders quickly."""
    sv, h = replay(CONCURRENT_LEADERS_LABELS)[-1]
    seed = tmp_path / "seed.json"
    seed.write_text(json.dumps(state_to_obj(sv, h)))
    r = run_cli(
        "check", TLC_CFG, "--engine", "tpu",
        "--seed-trace", str(seed),
        "--invariant", "CommitWhenConcurrentLeaders",
        "--action-constraint",
        "CommitWhenConcurrentLeaders_action_constraint",
        "--max-log-length", "1", "--max-client-requests", "2",
        "--max-timeouts", "1", "--max-restarts", "0", "--max-terms", "4",
        "--max-depth", "12", "--chunk", "256")
    assert r.returncode == 1, (r.stdout, r.stderr)
    head = json.loads(r.stdout.splitlines()[0])
    assert head["violations"] >= 1
    assert "CommitWhenConcurrentLeaders" in r.stdout


@pytest.mark.slow
def test_prefix_pin_cfg_runs_unchanged(tmp_path):
    """The reference cfg with the punctuated-search lines UNCOMMENTED
    (raft.cfg:53-55, 57, 68) runs as-is: the parser accepts the two
    hard-coded prefix-pin constraint names, compiles them into seeds
    (raft.tla:1198-1234 -> models/golden), and BOTH engines hunt down
    the CommitWhenConcurrentLeaders witness from the cfg alone — the
    full chain cfg pins -> implicit seeds -> BFS hunt -> CWCL witness
    in one run.  The replayed prefix interior states (which TLC counts
    and we seed past) are invariant-checked and their count surfaced
    (models/golden docstring; ADVICE r3)."""
    text = open(TLC_CFG).read()
    text = text.replace(r"    \* CommitWhenConcurrentLeaders_unique",
                        "    CommitWhenConcurrentLeaders_unique")
    text = text.replace(
        r"    \* CommitWhenConcurrentLeaders_action_constraint",
        "    CommitWhenConcurrentLeaders_action_constraint")
    text = text.replace("    \\* CommitWhenConcurrentLeaders\n",
                        "    CommitWhenConcurrentLeaders\n")
    cfg_path = tmp_path / "raft.cfg"
    cfg_path.write_text(text)

    from raft_tla_tpu.cfg.parser import load_model
    from raft_tla_tpu.config import Bounds
    # max_terms=4 explicitly: the pinned witness reaches terms {2,3}
    # (BoundedTerms would otherwise prune the seed state itself, since
    # the derived default MaxTerms = MaxTimeouts+1 = 2)
    cfg = load_model(cfg_path, variant="tlc", bounds=Bounds.make(
        max_log_length=1, max_timeouts=1, max_restarts=0,
        max_client_requests=2, max_terms=4))
    assert cfg.prefix_pins == ("CommitWhenConcurrentLeaders_unique",)
    assert "CommitWhenConcurrentLeaders_unique" not in cfg.constraints
    assert cfg.invariants[0] == "CommitWhenConcurrentLeaders"

    from raft_tla_tpu.engine.bfs import Engine
    from raft_tla_tpu.models.explore import explore
    oracle = explore(cfg, max_depth=10, stop_on_violation=True)
    assert any(v.invariant == "CommitWhenConcurrentLeaders"
               for v in oracle.violations)
    # TLC counts the 18 replayed prefix states (Init + 17 interiors);
    # we seed past them but still invariant-check them
    assert oracle.pin_interior_states > 0
    # the engine derives the same implicit seed and runs the SAME hunt
    # end-to-end: the witness must fall out of the cfg alone
    eng = Engine(cfg, chunk=64, store_states=False)
    r = eng.check(max_depth=9, stop_on_violation=True)
    assert any(v.invariant == "CommitWhenConcurrentLeaders"
               for v in r.violations), \
        "cfg-pinned TPU hunt must find the CWCL witness"
    assert r.pin_interior_states == oracle.pin_interior_states


def test_prefix_pin_majority_restarts_seed():
    """The 28-record pin resolves to the CommitWhenConcurrentLeaders
    end state; with both pins listed the longer witness wins (the
    conjunction of the two IsPrefix constraints IS the longer one)."""
    from raft_tla_tpu.models.golden import (GOLDEN_28_KINDS,
                                            prefix_pin_seeds)
    cfg = CFG3.with_(prefix_pins=(
        "CommitWhenConcurrentLeaders_unique",
        "MajorityOfClusterRestarts_constraint"))
    seeds = prefix_pin_seeds(cfg)
    assert len(seeds) == 1                     # symmetry on: one assign
    sv, h = seeds[0]
    assert [r[0] for r in h.glob] == GOLDEN_28_KINDS
    # without symmetry: one seed per injective (s1,s2,s3) assignment
    seeds6 = prefix_pin_seeds(cfg.with_(symmetry=False))
    assert len(seeds6) == 6
    views = {s for (s, _h) in seeds6}
    assert len(views) == 6                     # all relabelings distinct


@pytest.mark.slow
def test_no_store_violation_prints_state():
    """Under --no-store the parent chain is gone but the violating
    state itself is decoded at detection time and must still be shown
    (TLC always reports at least the bad state)."""
    r = run_cli(
        "check", TLC_CFG, "--engine", "tpu", "--no-store",
        "--servers", "2", "--init-servers", "2",
        "--max-log-length", "1", "--max-timeouts", "1",
        "--max-client-requests", "1", "--chunk", "64",
        "--invariant", "FirstBecomeLeader", "--max-depth", "12")
    assert r.returncode == 1, (r.stdout, r.stderr)
    assert "Violation 0: invariant FirstBecomeLeader" in r.stdout
    # the single-state pseudo-trace carries the decoded State repr
    assert "violating state" in r.stdout
    assert "State(" in r.stdout


@pytest.mark.slow
def test_emit_seed_roundtrip(tmp_path):
    """`trace --emit-seed` writes a seed that `check --seed-trace`
    accepts on both engines (the CLI surface of punctuated search)."""
    common = [TLC_CFG, "--servers", "2", "--max-timeouts", "1",
              "--max-log-length", "1", "--max-client-requests", "1"]
    seed = tmp_path / "first_leader.json"
    r = run_cli("trace", *common, "--target", "FirstBecomeLeader",
                "--emit-seed", str(seed))
    assert r.returncode == 0, (r.stdout, r.stderr)
    obj = json.loads(seed.read_text())
    assert "state" in obj and "nonview" in obj
    outs = {}
    for engine in ("tpu", "oracle"):
        r2 = run_cli("check", *common, "--engine", engine,
                     "--seed-trace", str(seed), "--max-depth", "6",
                     "--keep-going")
        assert r2.returncode == 0, (r2.stdout, r2.stderr)
        outs[engine] = json.loads(r2.stdout.splitlines()[0])
    assert outs["tpu"]["distinct_states"] == \
        outs["oracle"]["distinct_states"]
    assert outs["tpu"]["depth"] == outs["oracle"]["depth"]
