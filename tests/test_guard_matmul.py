"""MXU-native expansion (round 9): guard grid as int8 matmul, batched
successor einsum, Pallas probe/claim dedup kernel.

The contract is bit-exactness BY CONSTRUCTION, pinned differentially:
``guard_matmul=True`` (default) must be an exact drop-in for the
historical vmapped lane sweep in EVERY engine — counts, level sizes,
global ids, archives, witness traces, violation states — and the
Pallas dedup kernel must reproduce the lax probe/claim sequence's
outcomes (fresh set, slots, table contents) on forced-collision
fixtures.  One fast representative per engine family runs in tier-1;
the full-space duplicates are slow-marked (870s budget)."""

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tla_tpu.config import Bounds, ModelConfig, NEXT_ASYNC, \
    NEXT_DYNAMIC
from raft_tla_tpu.engine.bfs import Engine, U32MAX
from raft_tla_tpu.engine.expand import Expander, parse_fam_density
from raft_tla_tpu.engine.fingerprint import probe_claim_insert_pallas
from raft_tla_tpu.engine.spill import SpillEngine

# tiny configs (test_obs/test_burst shapes: small spaces, fast)
TINY = ModelConfig(
    n_servers=2, init_servers=(0, 1), values=(1,),
    max_inflight_override=2, next_family=NEXT_ASYNC, symmetry=False,
    constraints=("BoundedInFlightMessages", "BoundedRequestVote",
                 "BoundedLogSize", "BoundedTerms"),
    invariants=("ElectionSafety", "LogMatching"),
    bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                       max_client_requests=1))

MICRO = ModelConfig(
    n_servers=2, init_servers=(0, 1), values=(1,),
    max_inflight_override=4, symmetry=True,
    bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                       max_client_requests=1))

# NextDynamic at S=3: every action family (incl. the membership pair)
# gets lanes, so the guard matrix is exercised row-complete
DYN = ModelConfig(
    n_servers=3, init_servers=(0, 1), values=(1,),
    next_family=NEXT_DYNAMIC, symmetry=False, max_inflight_override=4,
    bounds=Bounds.make(max_log_length=2, max_timeouts=1,
                       max_client_requests=1))


def _key(r):
    return (r.distinct_states, r.generated_states, r.depth,
            tuple(r.level_sizes), r.violations_global)


def _reachable_svT(cfg, n=150):
    """A batch of reachable states, batch-last, via the oracle."""
    from conftest import cached_explore
    from raft_tla_tpu.ops.codec import encode, widen
    from raft_tla_tpu.ops.layout import Layout
    lay = Layout(cfg)
    r = cached_explore(cfg, max_states=3 * n, keep_states=True)
    pairs = list(r.states.values())[:n]
    rows = [encode(lay, sv, h) for sv, h in pairs]
    batch = widen({k: np.stack([s[k] for s in rows]) for k in rows[0]})
    return {k: jnp.moveaxis(jnp.asarray(v), 0, -1)
            for k, v in batch.items()}


# ---------------------------------------------------------------------
# guard grid: matmul ≡ lane sweep (the @smoke acceptance pin)
# ---------------------------------------------------------------------


@pytest.mark.smoke
def test_guard_matmul_equals_lane_sweep_on_reachable_states():
    """The packed int8 guard matrix reproduces every lane's enabling
    guard exactly on reachable NextDynamic states (all families incl.
    the signed-weight AddNewServer row)."""
    svT = _reachable_svT(DYN, n=120)
    ex_on = Expander(DYN, guard_matmul=True)
    ex_off = Expander(DYN, guard_matmul=False)
    derT = ex_on.derived_batch_T(svT)
    ok_mm = np.asarray(ex_on.guards_T(svT, derT))
    ok_ln = np.asarray(ex_off.guards_T(svT, derT))
    np.testing.assert_array_equal(ok_mm, ok_ln)
    # and the grid is live (some lanes enabled, some not)
    assert ok_mm.any() and not ok_mm.all()


def test_engine_guard_matmul_on_off_tiny():
    """Fast classic-engine representative: ON ≡ OFF end to end (counts,
    ids via archives) on the tiny config, burst default.  Depth-capped
    for the tier-1 budget — the full space runs in the slow duplicate
    below (and tools/ci_smoke.sh runs the CLI-level ON ≡ OFF smoke)."""
    e_on = Engine(TINY, chunk=64, store_states=True, guard_matmul=True)
    r_on = e_on.check(max_depth=12)
    e_off = Engine(TINY, chunk=64, store_states=True,
                   guard_matmul=False)
    r_off = e_off.check(max_depth=12)
    assert _key(r_on) == _key(r_off)
    assert r_on.guard_matmul == 1 and r_off.guard_matmul == 0
    for pa, pb in zip(e_on._parents, e_off._parents):
        np.testing.assert_array_equal(pa, pb)
    for la, lb in zip(e_on._lanes, e_off._lanes):
        np.testing.assert_array_equal(la, lb)


# ---------------------------------------------------------------------
# fast representatives, one per engine family (tier-1).
#
# The default flipped to guard_matmul=True, so the ENTIRE existing
# differential suite now exercises the matmul path against the oracle;
# what needs fresh fast coverage is (a) the classic-engine ON ≡ OFF
# pair above and (b) the legacy OFF program staying oracle-correct in
# each engine family (one run each — the full ON/OFF pairs for the
# parallel engines are slow-marked below, ~1 min apiece).
# ---------------------------------------------------------------------


def _oracle_key(cfg, max_depth=10 ** 9):
    from conftest import cached_explore
    w = cached_explore(cfg, max_depth=max_depth)
    return (w.distinct_states, w.generated_states, w.depth,
            tuple(w.level_sizes), len(w.violations))


def _engine_key(r):
    return (r.distinct_states, r.generated_states, r.depth,
            tuple(r.level_sizes), r.violations_global)


@pytest.mark.slow
def test_spill_lane_path_matches_oracle():
    # slow-marked (round-13 suite diet): the legacy guard_matmul=False
    # sweep on the spill family — its DEFAULT guard path stays fast in
    # tests/test_delta_matmul.py (spill-vs-oracle with guard ON), and
    # the classic family's fast ON≡OFF pair covers the sweep program
    r = SpillEngine(TINY, chunk=64, store_states=False, seg=1 << 10,
                    vcap=1 << 12, sync_every=2,
                    guard_matmul=False).check(max_depth=10)
    assert r.guard_matmul == 0
    assert _engine_key(r) == _oracle_key(TINY, max_depth=10)


@pytest.mark.slow
def test_mesh_lane_path_matches_oracle():
    # slow-marked (round-13 suite diet): same reasoning as the spill
    # twin above — mesh keeps a fast default-path oracle differential
    # in test_delta_matmul.py
    from raft_tla_tpu.parallel.mesh import ShardedEngine
    r = ShardedEngine(TINY, chunk=64, store_states=False,
                      guard_matmul=False).check(max_depth=10)
    assert _engine_key(r) == _oracle_key(TINY, max_depth=10)


@pytest.mark.slow
def test_spill_mesh_lane_path_matches_oracle():
    # slow-marked: the spill-composed mesh inherits its whole guard
    # path from Engine/ShardedEngine (both covered fast above); its
    # own ON/OFF pair runs in the slow set too
    from raft_tla_tpu.parallel.spill_mesh import SpilledShardedEngine
    r = SpilledShardedEngine(TINY, chunk=64, store_states=False,
                            lcap=1 << 11, guard_matmul=False).check()
    assert _engine_key(r) == _oracle_key(TINY)


def test_sim_guard_matmul_bit_identical_trajectories():
    """The fifth engine: same seed, matmul ON vs OFF — walker
    trajectories, counters and Bloom estimates all bit-identical
    (guards identical => identical uniform draws => identical
    step_lanes selections)."""
    from raft_tla_tpu.sim.walker import SimEngine
    cfg = TINY.with_(invariants=("ElectionSafety",))
    out = {}
    for gm in (True, False):
        eng = SimEngine(cfg, walkers=8, max_depth=8, seed=3,
                        bloom_bits=12, guard_matmul=gm)
        r = eng.run(steps=24, steps_per_dispatch=8, stop_on_hit=False)
        out[gm] = (r.walker_steps, r.sampled_steps, r.restarts,
                   r.deadlocks, r.promotions, len(r.hits),
                   round(float(r.est_distinct_states), 3))
    assert out[True] == out[False]


# ---------------------------------------------------------------------
# Pallas probe/claim dedup kernel ≡ lax sequence (forced collisions)
# ---------------------------------------------------------------------


def test_pallas_dedup_kernel_forced_collision_fixture():
    """The acceptance fixture: a small table, few distinct keys, many
    duplicates and dead lanes, a pre-populated cohort — kernel
    (interpret=True, the CPU fallback) and lax sequence must agree on
    the table contents, the fresh set and every lane's final slot."""
    eng = Engine(MICRO, chunk=64, store_states=False)
    W = eng.W
    rng = np.random.RandomState(7)
    VCAP, M = 128, 96
    distinct = rng.randint(0, 1 << 32, size=(24, W)).astype(np.uint32)
    keys_np = distinct[rng.randint(0, 24, size=M)]
    live_np = rng.rand(M) > 0.2
    keys_np[~live_np] = 0xFFFFFFFF
    keys = tuple(jnp.asarray(keys_np[:, w]) for w in range(W))
    live = jnp.asarray(live_np)
    table0 = tuple(jnp.full((VCAP,), U32MAX) for _ in range(W))
    claims0 = jnp.full((VCAP,), U32MAX)
    # pre-populate (cross-call duplicate detection)
    pre = tuple(jnp.asarray(distinct[:4, w]) for w in range(W))
    t1, c1, _f, _p, _h = eng._probe_insert_lax(
        table0, claims0, pre, jnp.ones(4, bool),
        jnp.arange(4, dtype=jnp.uint32))
    tA, _cA, fA, pA, hA = eng._probe_insert_lax(
        t1, c1, keys, live, jnp.arange(M, dtype=jnp.uint32))
    tB, fB, pB, hB = probe_claim_insert_pallas(
        t1, keys, live, max_rounds=4096, interpret=True)
    for w in range(W):
        np.testing.assert_array_equal(np.asarray(tA[w]),
                                      np.asarray(tB[w]))
    np.testing.assert_array_equal(np.asarray(fA), np.asarray(fB))
    np.testing.assert_array_equal(np.asarray(pA), np.asarray(pB))
    assert bool(hA) == bool(hB) is False
    # the fixture actually forced duplicates AND dead lanes
    assert fA.sum() < live_np.sum()


# ---------------------------------------------------------------------
# fam-cap-density tunable (satellite)
# ---------------------------------------------------------------------


@pytest.mark.smoke
def test_fam_cap_density_parse_and_validate():
    assert parse_fam_density("Receive=8, Timeout=2") == {
        "Receive": 8, "Timeout": 2}
    with pytest.raises(ValueError, match="unknown action family"):
        parse_fam_density("NoSuchFamily=3")
    with pytest.raises(ValueError, match="must be >= 1"):
        parse_fam_density("Receive=0")
    with pytest.raises(ValueError, match="must be an integer"):
        parse_fam_density("Receive=abc")
    with pytest.raises(ValueError, match="fam=k"):
        parse_fam_density("Receive")
    # engine kwarg path raises the same clear error, not a jit trace
    with pytest.raises(ValueError, match="unknown action family"):
        Engine(TINY, chunk=64, fam_density={"Nope": 2})


def test_fam_cap_density_changes_caps_not_counts():
    """A density override resizes the materialization buffers only —
    counts are invariant (overflowing families grow-and-replay).
    Compared against the oracle (one engine run, tier-1 budget)."""
    e_dflt = Engine(TINY, chunk=64, store_states=False)
    e_tight = Engine(TINY, chunk=64, store_states=False,
                     fam_density={"Receive": 1, "UpdateTerm": 1})
    assert e_tight.FAM_CAPS != e_dflt.FAM_CAPS
    r = e_tight.check(max_depth=10)
    assert _engine_key(r) == _oracle_key(TINY, max_depth=10)


# ---------------------------------------------------------------------
# full-space duplicates (slow: the 870s tier-1 budget)
# ---------------------------------------------------------------------


@pytest.mark.slow
def test_engine_guard_matmul_full_space_archives_and_traces():
    """Classic engine on the symmetric micro space: ON ≡ OFF across
    counts, archives (=> identical global ids) and a replayed trace."""
    e_on = Engine(MICRO, chunk=64, store_states=True, guard_matmul=True)
    r_on = e_on.check()
    e_off = Engine(MICRO, chunk=64, store_states=True,
                   guard_matmul=False)
    r_off = e_off.check()
    assert _key(r_on) == _key(r_off)
    for sa, sb in zip(e_on._states, e_off._states):
        for k in sa:
            np.testing.assert_array_equal(sa[k], sb[k])
    # witness-trace parity on an arbitrary deep state
    gid = r_on.distinct_states - 1
    ta = [(lbl, repr(sv)) for lbl, sv in e_on.trace(gid)]
    tb = [(lbl, repr(sv)) for lbl, sv in e_off.trace(gid)]
    assert ta == tb


@pytest.mark.slow
def test_guard_matmul_violation_states_identical():
    """Scenario witness hunt (negated-reachability 'violation'): the
    reported violation ids, states and traces match ON vs OFF."""
    cfg = TINY.with_(invariants=("FirstBecomeLeader",))
    outs = {}
    for gm in (True, False):
        eng = Engine(cfg, chunk=64, store_states=True, guard_matmul=gm)
        r = eng.check(stop_on_violation=True)
        assert r.violations, "scenario witness not found"
        v = r.violations[0]
        outs[gm] = (v.invariant, v.state_id, repr(v.state),
                    [(lbl, repr(sv)) for lbl, sv in
                     eng.trace(v.state_id)])
    assert outs[True] == outs[False]


@pytest.mark.slow
def test_engine_dedup_kernel_on_matches_off():
    """Full-engine Pallas parity through the interpreter (the CPU
    fallback): dedup_kernel='on' ≡ 'off', depth-capped — interpret
    mode costs per-lane Python, so the space is kept tiny."""
    r_on = Engine(MICRO, chunk=16, store_states=False,
                  dedup_kernel="on").check(max_depth=3)
    r_off = Engine(MICRO, chunk=16, store_states=False,
                   dedup_kernel="off").check(max_depth=3)
    assert _key(r_on) == _key(r_off)
    assert r_on.dedup_kernel == 1 and r_off.dedup_kernel == 0


@pytest.mark.slow
def test_mesh_dedup_kernel_on_matches_off():
    """Pallas kernel inside the shard_map step (the path a TPU mesh
    runs under dedup_kernel='auto'): interpreter-pinned ≡ lax, so the
    mesh default has a CPU-side signal before TPU hardware sees it."""
    from raft_tla_tpu.parallel.mesh import ShardedEngine
    r_on = ShardedEngine(TINY, chunk=16, store_states=False,
                         dedup_kernel="on").check(max_depth=3)
    r_off = ShardedEngine(TINY, chunk=16, store_states=False,
                          dedup_kernel="off").check(max_depth=3)
    assert _key(r_on) == _key(r_off)
    assert r_on.dedup_kernel == 1 and r_off.dedup_kernel == 0


@pytest.mark.slow
def test_spill_guard_matmul_full_space_with_bursts():
    """Spill engine with squeezed segments (burst + segment driver both
    engaged): ON ≡ OFF, and the OCAP-compacted burst path commits."""
    rs = {}
    for gm in (True, False):
        eng = SpillEngine(MICRO, chunk=64, store_states=False,
                          seg=1 << 10, vcap=1 << 12, sync_every=2,
                          guard_matmul=gm)
        rs[gm] = eng.check()
        assert rs[gm].levels_fused > 0
    assert _key(rs[True]) == _key(rs[False])


@pytest.mark.slow
def test_mesh_guard_matmul_on_off_pair():
    from raft_tla_tpu.parallel.mesh import ShardedEngine
    rs = {gm: ShardedEngine(TINY, chunk=64, store_states=False,
                            guard_matmul=gm).check()
          for gm in (True, False)}
    assert _key(rs[True]) == _key(rs[False])


@pytest.mark.slow
def test_spill_mesh_guard_matmul_on_off_pair():
    from raft_tla_tpu.parallel.spill_mesh import SpilledShardedEngine
    rs = {gm: SpilledShardedEngine(TINY, chunk=64, store_states=False,
                                   lcap=1 << 11,
                                   guard_matmul=gm).check()
          for gm in (True, False)}
    assert _key(rs[True]) == _key(rs[False])
