"""Differential tests for the host-partitioned visited table
(engine/host_table + engine/spill host_table=True): the authoritative
visited set lives in host RAM as fingerprint-prefix partitions and the
HBM table degrades to a bounded cache, yet every count stays
bit-identical to the in-HBM engine and the Python oracle.

Capacities here are squeezed so the streaming dedup actually engages:
``dev_keys`` is forced far below the config's distinct-key count, so
the device cache reseeds at level boundaries and the host-partition
sweep is what drops re-generated old-level keys — exactly the
beyond-the-HBM-ceiling regime the tentpole targets, exercised on the
CPU backend at micro scale (ISSUE 1 acceptance; the budgeted
configs #1/#2 shapes need the TPU host's reference cfgs)."""

import numpy as np
import pytest

from raft_tla_tpu.config import Bounds, ModelConfig, NEXT_ASYNC
from raft_tla_tpu.engine.host_table import (HostPartitionedTable,
                                            insert_np, member_np)
from raft_tla_tpu.engine.spill import SpillEngine
from raft_tla_tpu.models.explore import explore

MICRO = ModelConfig(
    n_servers=2, init_servers=(0, 1), values=(1,),
    next_family=NEXT_ASYNC, symmetry=True, max_inflight_override=4,
    bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                       max_client_requests=1))

# dev_keys=64 << the micro space's distinct count: the device cache
# reseeds after nearly every level, so dedup against anything older
# than the frontier can ONLY come from the host partitions
SQUEEZE = dict(chunk=64, store_states=False, seg=1 << 10, vcap=1 << 12,
               sync_every=2, host_table=True, part_cap=1 << 6,
               dev_keys=64)


def _match(r, want):
    assert r.distinct_states == want.distinct_states
    assert r.depth == want.depth
    assert r.generated_states == want.generated_states
    assert len(r.violations) == len(want.violations)
    assert r.level_sizes == want.level_sizes


@pytest.mark.slow
def test_host_table_partition_count_invariance():
    """P=1 ≡ P=4 ≡ P=8: bit-identical distinct counts and level sizes
    (the partition id is a pure function of the key, so P only changes
    the sweep's batching, never its verdict).  The SQUEEZE capacities
    force the streaming path for real (ISSUE 1 acceptance: table
    capacity below the distinct-key count, 0 overflow faults): the
    oracle `want` equals the in-HBM engine's counts on this cfg
    (test_spill pins that), so matching it here IS the differential
    against the in-HBM engine."""
    want = explore(MICRO)
    assert want.distinct_states > 64          # the squeeze is real
    results = {}
    for P in (1, 4, 8):
        eng = SpillEngine(MICRO, partitions=P, **SQUEEZE)
        r = eng.check()
        _match(r, want)
        assert r.overflow_faults == 0
        # the host table is the authority: it holds every distinct key
        assert eng.hpt.n_keys == want.distinct_states
        assert eng.hpt.P == P
        # every partition saw keys, and the forced-tiny 2^6 images
        # rehash-grew under the load bound
        assert all(c > 0 for c in eng.hpt.counts)
        assert any(eng.hpt.cap(p) > 1 << 6 for p in range(P))
        results[P] = (r.distinct_states, tuple(r.level_sizes))
    assert results[1] == results[4] == results[8]


def test_host_table_traces_and_violations():
    """store_states path under the host table: first-seen semantics
    (which copy of a state is archived) must be preserved, so traces
    replay exactly as the oracle's witness."""
    cfg = MICRO.with_(invariants=("FirstBecomeLeader",))
    want = explore(cfg, stop_on_violation=True, trace_violations=True)
    eng = SpillEngine(cfg, partitions=4,
                      **dict(SQUEEZE, store_states=True))
    r = eng.check(stop_on_violation=True)
    assert r.violations and want.violations
    assert r.violations[0].invariant == "FirstBecomeLeader"
    tr = eng.trace(r.violations[0].state_id)
    assert len(tr) - 1 == len(want.violations[0].trace)
    assert tr[0][0] == "Init"


@pytest.mark.slow
def test_host_table_checkpoint_resume_identical(tmp_path):
    """Interrupt mid-run, resume: the partition images restore
    exact-image (no rehash drift) and the run lands bit-identical to
    an uninterrupted one."""
    full = SpillEngine(MICRO, partitions=4, **SQUEEZE).check()

    ckpt = str(tmp_path / "ht.ckpt")
    part = SpillEngine(MICRO, partitions=4, **SQUEEZE).check(
        max_depth=8, checkpoint_path=ckpt)
    assert part.distinct_states < full.distinct_states

    e2 = SpillEngine(MICRO, partitions=4, **SQUEEZE)
    resumed = e2.check(resume_from=ckpt)
    assert resumed.distinct_states == full.distinct_states
    assert resumed.depth == full.depth
    assert resumed.generated_states == full.generated_states
    assert resumed.level_sizes == full.level_sizes
    assert e2.hpt.n_keys == full.distinct_states


def test_host_table_checkpoint_mismatch_rejected(tmp_path):
    """Resume must repeat the checkpoint's host-table settings: the
    serialized images are per-P, and a silent fallback would change
    dedup authority mid-run."""
    from raft_tla_tpu.engine.bfs import CheckpointError
    ckpt = str(tmp_path / "ht.ckpt")
    SpillEngine(MICRO, partitions=4, **SQUEEZE).check(
        max_depth=6, checkpoint_path=ckpt)
    with pytest.raises(CheckpointError, match="host_table"):
        SpillEngine(MICRO, chunk=64, store_states=False, seg=1 << 10,
                    vcap=1 << 12).check(resume_from=ckpt)
    with pytest.raises(CheckpointError, match="partitions"):
        SpillEngine(MICRO, partitions=8, **SQUEEZE).check(
            resume_from=ckpt)


# -- overflow / bail paths (forced-tiny partition) ---------------------


def test_insert_np_bails_on_full_image():
    """The host-side claim-insert must fail LOUD, not loop or corrupt,
    when a partition image has no empty slot left."""
    rng = np.random.default_rng(7)
    img = np.full((2, 64), np.uint32(0xFFFFFFFF), np.uint32)
    keys = rng.integers(0, 2 ** 32 - 2, size=(64, 2), dtype=np.uint64
                        ).astype(np.uint32)
    keys = np.unique(keys, axis=0)
    insert_np(img, keys)                      # fills every slot it can
    assert not (img == np.uint32(0xFFFFFFFF)).all(axis=0).any() or \
        keys.shape[0] < 64
    more = rng.integers(0, 2 ** 32 - 2, size=(8, 2), dtype=np.uint64
                        ).astype(np.uint32)
    if keys.shape[0] == 64:                   # truly full image
        with pytest.raises(RuntimeError, match="full"):
            insert_np(img, more)


def test_member_np_matches_insert_np():
    """Host membership is exact over inserted keys and clean misses."""
    rng = np.random.default_rng(11)
    img = np.full((2, 256), np.uint32(0xFFFFFFFF), np.uint32)
    keys = np.unique(rng.integers(0, 2 ** 32 - 2, size=(80, 2),
                                  dtype=np.uint64).astype(np.uint32),
                     axis=0)
    insert_np(img, keys)
    assert member_np(img, keys).all()
    misses = keys.copy()
    misses[:, 1] ^= np.uint32(1)
    fresh = ~(misses[:, None] == keys[None]).all(-1).any(1)
    assert not member_np(img, misses[fresh]).any()


def test_sweep_bails_on_poisoned_partition():
    """Device-side sweep bail: a partition image with NO empty slot
    (forced behind reserve()'s back) can never terminate the probe
    walk — the engine must raise, not return a wrong verdict."""
    eng = SpillEngine(MICRO, partitions=1, **SQUEEZE)
    eng.hpt = HostPartitionedTable(eng.W, partitions=1,
                                   part_cap=1 << 6)
    # poison: every slot occupied by a key that matches nothing
    eng.hpt.imgs[0][:] = np.uint32(0)
    eng.hpt.counts[0] = 0                     # reserve() won't grow it
    keys = np.full((4, eng.W), np.uint32(123), np.uint32)
    keys[:, 0] = np.arange(1, 5, dtype=np.uint32)
    with pytest.raises(RuntimeError, match="full"):
        eng._sweep_level_keys(keys)


def test_host_table_partition_ids_pure_and_bounded():
    """Partition ids come from stream 0's top bits only: every id is
    in range and P=1 collapses to a single bucket."""
    rng = np.random.default_rng(3)
    keys = rng.integers(0, 2 ** 32 - 1, size=(1000, 2),
                        dtype=np.uint64).astype(np.uint32)
    for P in (1, 2, 8):
        t = HostPartitionedTable(2, partitions=P)
        pids = t.partition_ids(keys)
        assert pids.min() >= 0 and pids.max() < P
        if P > 1:
            assert (pids == (keys[:, 0] >> np.uint32(
                32 - t.bits)).astype(np.int64)).all()
    with pytest.raises(ValueError, match="power of two"):
        HostPartitionedTable(2, partitions=3)


@pytest.mark.slow
def test_host_table_fovf_growth_composition():
    """Family-cap growth replays compose with the host sweep: tiny
    fam caps force fovf grow-and-replay while the table streams."""
    want = explore(MICRO)
    eng = SpillEngine(MICRO, partitions=4, fcap=64, **SQUEEZE)
    eng.FAM_CAPS = tuple(min(c, 16) for c in eng.FAM_CAPS)
    r = eng.check()
    _match(r, want)
