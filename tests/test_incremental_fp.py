"""Incremental per-action fingerprints (engine/fingerprint.py
"Incremental" section): bit-identity against the direct
min-over-permutations hash on real reachable states, across the
action families — including membership (AddNewServer / DeleteServer /
Catchup / CheckOldConfig, config entries inside logs and messages) and
the unreliable-network lanes (Duplicate / Drop).

The claim rests on u32 modular-sum associativity plus exact
cancellation of untouched superset terms; these tests falsify any
touch-superset omission or relabel mismatch, because a single wrong
position yields a different 64/128-bit key with probability ~1."""

import numpy as np
import pytest

from raft_tla_tpu.config import (Bounds, ModelConfig, NEXT_ASYNC,
                                 NEXT_DYNAMIC, NEXT_FULL)
from raft_tla_tpu.engine.bfs import Engine
from raft_tla_tpu.models.explore import explore
from raft_tla_tpu.ops.codec import encode, widen
from raft_tla_tpu.utils import cat_arrays as _cat


MICRO = ModelConfig(
    n_servers=2, init_servers=(0, 1), values=(1,),
    next_family=NEXT_ASYNC, symmetry=True, max_inflight_override=4,
    bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                       max_client_requests=1))

# membership: Server=4 > InitServer=3, NextDynamic — covers catchup
# splices, CheckOldConfig self-sends, ConfigEntry payload relabeling
MEMB = ModelConfig(
    n_servers=4, init_servers=(0, 1, 2), values=(1,),
    next_family=NEXT_DYNAMIC, symmetry=True, max_inflight_override=6,
    bounds=Bounds.make(max_log_length=2, max_timeouts=1,
                       max_client_requests=1, max_membership_changes=1))

# unreliable network: Duplicate / Drop lanes
UNREL = ModelConfig(
    n_servers=2, init_servers=(0, 1), values=(1,),
    next_family=NEXT_FULL, symmetry=False, max_inflight_override=4,
    bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                       max_restarts=1, max_client_requests=1))


def _frontier_batch(cfg, n_rows, depth):
    """Reachable states from a shallow oracle BFS, encoded batch-major
    int32 — enough action variety to light up every family."""
    r = explore(cfg, max_depth=depth, keep_states=True)
    lay = Engine(cfg, chunk=16, store_states=False).lay
    rows = [encode(lay, sv, h) for sv, h in list(r.states.values())]
    rows = rows[:: max(1, len(rows) // n_rows)][:n_rows]
    return widen(_cat([{k: np.asarray(v)[None] for k, v in s.items()}
                       for s in rows]))


def _assert_identity(cfg, depth=4, chunk=16):
    eng = Engine(cfg, chunk=chunk, store_states=False)
    assert eng.fpr.supports_incremental()
    batch = _frontier_batch(cfg, chunk, depth)
    n = len(batch["ct"])
    svT = {k: np.moveaxis(np.concatenate(
        [v, np.zeros((chunk - n,) + v.shape[1:], v.dtype)]), 0, -1)
        for k, v in batch.items()}
    valid = np.arange(chunk) < n

    import jax
    import jax.numpy as jnp

    def run(incr):
        eng.incremental_fp = incr
        cand, elive, fp, take, famx, n_e = jax.jit(
            lambda sv, va: eng._expand_fp_chunk(
                sv, va, eng.FAM_CAPS, eng.FCAP))(
            {k: jnp.asarray(v) for k, v in svT.items()},
            jnp.asarray(valid))
        return (np.asarray(elive), np.asarray(fp))

    elive_i, fp_i = run(True)
    elive_d, fp_d = run(False)
    np.testing.assert_array_equal(elive_i, elive_d)
    assert elive_i.any(), "no enabled candidates — test config too small"
    np.testing.assert_array_equal(fp_i[:, elive_i], fp_d[:, elive_d])


def test_identity_micro():
    _assert_identity(MICRO)


@pytest.mark.slow
def test_identity_membership_dynamic():
    """The widest family set: membership actions, catchup, CoC, cfg
    entries in logs AND messages, under the InitServer-fixing
    symmetry subgroup."""
    _assert_identity(MEMB, depth=5, chunk=32)


def test_identity_unreliable_fp128():
    """Duplicate/Drop lanes + 4-stream fingerprints."""
    _assert_identity(UNREL.with_(fp128=True), depth=4)


@pytest.mark.slow
def test_counts_match_direct_engine():
    """End-to-end: the incremental engine lands on the oracle's exact
    counts (the direct engine's parity is pinned by the existing
    differential suite)."""
    want = explore(MEMB, max_depth=6)
    eng = Engine(MEMB, chunk=64, store_states=False)
    assert eng.incremental_fp
    r = eng.check(max_depth=6)
    assert r.distinct_states == want.distinct_states
    assert r.generated_states == want.generated_states
    assert r.depth == want.depth


def test_big_symmetry_group_falls_back():
    cfg = MICRO.with_(n_servers=5, init_servers=(0, 1, 2, 3, 4))
    eng = Engine(cfg, chunk=16, store_states=False)
    # P = 120: auto resolves to orbit-sort, whose data-dependent
    # canonical permutation has no per-perm delta algebra
    assert eng.fpr.sym_canon == "sort"
    assert not eng.fpr.supports_incremental()
    # forced minperm past 24 perms falls back too (the historical gate)
    eng = Engine(cfg, chunk=16, store_states=False,
                 sym_canon="minperm")
    assert not eng.fpr.supports_incremental()    # P = 120 > 24
