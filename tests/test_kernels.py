"""Differential tests: vmapped kernels vs the Python oracle.

For a sample of oracle-reachable states, the engine's expansion must
produce exactly the oracle's successor multiset — same states, same
history counters, same feature lanes (SURVEY §7.2 L1 exit criterion).
"""

from collections import Counter

import numpy as np
import pytest

from raft_tla_tpu.config import (Bounds, ModelConfig, NEXT_DYNAMIC,
                                 NEXT_FULL)
from raft_tla_tpu.engine.expand import Expander
from raft_tla_tpu.models.explore import explore
from raft_tla_tpu.models.raft import successors
from raft_tla_tpu.ops.codec import (C_GLOBLEN, C_NLEADERS, C_NMC, C_NREQ,
                                    C_NTRIED, C_OVERFLOW, decode, encode,
                                    features_from_hist)
from raft_tla_tpu.ops.layout import Layout

SMALL = ModelConfig(
    n_servers=2, init_servers=(0, 1), values=(1,),
    bounds=Bounds.make(max_log_length=2, max_timeouts=2),
    symmetry=False)

UNRELIABLE = SMALL.with_(next_family=NEXT_FULL)

MEMBER = ModelConfig(
    n_servers=3, init_servers=(0, 1), values=(1,),
    next_family=NEXT_DYNAMIC,
    bounds=Bounds.make(max_log_length=2, max_timeouts=2),
    symmetry=False)


def oracle_succ_multiset(sv, h, cfg):
    out = Counter()
    for _label, sv2, h2 in successors(sv, h, cfg):
        key = (sv2, h2.restarted, h2.timeout, h2.nleaders, h2.nreq,
               h2.ntried, h2.nmc, len(h2.glob),
               tuple(features_from_hist(h2)))
        out[key] += 1
    return out


def engine_succ_multiset(exp, lay, arrs, cfg):
    out = Counter()
    for _label, sv2arr in exp.expand_one(arrs):
        assert int(sv2arr["ctr"][C_OVERFLOW]) == 0, "overflow fault"
        sv2, h2 = decode(lay, sv2arr)
        key = (sv2, h2.restarted, h2.timeout, h2.nleaders, h2.nreq,
               h2.ntried, h2.nmc, int(sv2arr["ctr"][C_GLOBLEN]),
               tuple(int(x) for x in sv2arr["feat"]))
        out[key] += 1
    return out


def sample_states(cfg, n, extra_targets=()):
    """Sample EXPANDABLE reachable states: kernels only ever run on
    constraint-satisfying frontier states (CONSTRAINT semantics gate
    expansion, SURVEY §2.8), so constraint-violating states are out of
    contract (e.g. the term-capacity clamp fires beyond max_terms+1)."""
    from raft_tla_tpu.models import predicates as OP
    res = explore(cfg, max_states=4000, keep_states=True)
    states = [
        (sv, h) for sv, h in res.states.values()
        if all(OP.CONSTRAINTS[nm](sv, h, cfg) for nm in cfg.constraints)]
    rng = np.random.RandomState(42)
    idx = rng.choice(len(states), size=min(n, len(states)), replace=False)
    sample = [states[i] for i in idx]
    # always include init and deep scenario witnesses (commit paths etc.)
    sample.append(states[0])
    for target in extra_targets:
        deep = explore(cfg.with_(invariants=(target,)),
                       stop_on_violation=True, max_states=200_000)
        assert deep.violations, f"no witness for {target}"
        sv, h = deep.violations[0].state, deep.violations[0].hist
        assert all(OP.CONSTRAINTS[nm](sv, h, cfg)
                   for nm in cfg.constraints), \
            f"witness for {target} is not expandable; pick another target"
        sample.append((sv, h))
    return sample


def run_differential(cfg, n=120, extra_targets=()):
    lay = Layout(cfg)
    exp = Expander(cfg)
    mismatches = []
    for sv, h in sample_states(cfg, n, extra_targets):
        want = oracle_succ_multiset(sv, h, cfg)
        got = engine_succ_multiset(exp, lay, encode(lay, sv, h), cfg)
        if want != got:
            missing = want - got
            spurious = got - want
            mismatches.append((sv, h, missing, spurious))
    assert not mismatches, (
        f"{len(mismatches)} states mismatch; first: state="
        f"{mismatches[0][0]}\nhist={mismatches[0][1]}\n"
        f"missing={list(mismatches[0][2].items())[:3]}\n"
        f"spurious={list(mismatches[0][3].items())[:3]}")


def test_differential_async_crash():
    run_differential(SMALL, extra_targets=("EntryCommitted",))


def test_differential_unreliable():
    run_differential(UNRELIABLE, n=80)


def test_differential_membership():
    run_differential(
        MEMBER, n=60,
        extra_targets=("AddSucessful", "MembershipChangeCommits"))
