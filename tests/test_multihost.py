"""Multi-host (multi-controller) BFS: two processes x two virtual CPU
devices, gloo collectives — the in-repo stand-in for a DCN-spanning
mesh (SURVEY §2.14 "DCN across hosts").  Both controllers must land on
the oracle's exact counts, independently.
"""
import json
import os
import socket
import subprocess
import sys

import pytest

from raft_tla_tpu.config import NEXT_ASYNC, Bounds, ModelConfig
from raft_tla_tpu.models.explore import explore

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tools", "multihost_worker.py")

MICRO = ModelConfig(
    n_servers=2, init_servers=(0, 1), values=(1,),
    next_family=NEXT_ASYNC, symmetry=True, max_inflight_override=4,
    bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                       max_client_requests=1))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_controllers_match_oracle():
    want = explore(MICRO)
    outs = _run_pair({})
    for r in outs:
        assert r["n_devices"] == 4          # 2 procs x 2 devices
        assert r["distinct"] == want.distinct_states
        assert r["depth"] == want.depth
        assert r["generated"] == want.generated_states
        assert r["violations"] == 0
    # both controllers report identical global results
    assert outs[0] == dict(outs[1], pid=0)


def _run_pair(opts):
    port = _free_port()
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    procs = [subprocess.Popen(
        [sys.executable, WORKER, str(pid), "2", str(port),
         json.dumps(opts)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=REPO) for pid in range(2)]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=600)
        assert p.returncode == 0, f"worker failed:\n{out}\n{err}"
        line = [ln for ln in out.splitlines() if ln.startswith("RESULT ")]
        assert line, f"no RESULT line:\n{out}\n{err}"
        outs.append(json.loads(line[-1][len("RESULT "):]))
    return outs


@pytest.mark.slow
def test_multihost_checkpoint_resume(tmp_path):
    """Kill a 2-controller run at depth 6, resume from the
    per-controller checkpoint shards (<path>.proc<k>), land on the
    exact counts of an uninterrupted run (VERDICT r2 item 8)."""
    want = explore(MICRO)
    ckpt = str(tmp_path / "mh.ckpt")
    part = _run_pair({"checkpoint": ckpt, "max_depth": 6})
    assert all(r["distinct"] < want.distinct_states for r in part)
    assert os.path.exists(ckpt + ".proc0")
    assert os.path.exists(ckpt + ".proc1")
    full = _run_pair({"resume": ckpt})
    for r in full:
        assert r["distinct"] == want.distinct_states
        assert r["depth"] == want.depth
        assert r["generated"] == want.generated_states


@pytest.mark.slow
def test_multihost_violation_trace(tmp_path):
    """Mesh-scale witness reconstruction (VERDICT r3 missing #2): a
    scenario hit under 2 controllers replays its full parent trace
    across the merged per-controller archive files (trace_dir), so the
    witness exists WITHOUT a single-host re-run.  The chain must match
    the oracle's semantics: Init root, an election, a client request
    and the commit that fires FirstCommit."""
    want = explore(MICRO.with_(invariants=("FirstCommit",)),
                   stop_on_violation=True, trace_violations=True)
    want_labels = want.violations[0].trace
    outs = _run_pair({"invariants": ["FirstCommit"],
                      "trace_dir": str(tmp_path / "arch"),
                      "stop_on_violation": True})
    assert any(r["violations"] > 0 for r in outs)
    traced = [t for r in outs for t in r["traces"]]
    assert traced, f"no controller produced a trace: {outs}"
    for labels in traced:
        assert labels[0] == "Init"
        assert any(lbl.startswith("BecomeLeader") for lbl in labels)
        assert any(lbl.startswith("ClientRequest") for lbl in labels)
        assert any(lbl.startswith("AdvanceCommitIndex")
                   for lbl in labels)
        # same depth class as the oracle's witness (BFS shortest
        # trace; the engine chain includes the Init root, the oracle
        # trace does not)
        assert len(labels) == len(want_labels) + 1, (labels, want_labels)


@pytest.mark.slow
def test_multihost_store_states_with_checkpoint(tmp_path):
    """store_states × checkpointing WORKS (round 14 — previously a
    documented exclusion): every controller's checkpoint shard carries
    its own archive rows + device segmentation, so a resumed run keeps
    appending and the final merged witness trace is bit-identical to
    an uninterrupted run's."""
    ref = _run_pair({"trace_dir": str(tmp_path / "arch_ref"),
                     "trace_gid": 100, "max_depth": 9})
    ckpt = str(tmp_path / "mh.ckpt")
    _run_pair({"checkpoint": ckpt, "max_depth": 6,
               "trace_dir": str(tmp_path / "arch_part")})
    assert os.path.exists(ckpt + ".proc0")
    full = _run_pair({"resume": ckpt, "max_depth": 9,
                      "trace_dir": str(tmp_path / "arch_res"),
                      "trace_gid": 100})
    for r in full:
        assert r["distinct"] == ref[0]["distinct"]
        assert r["depth"] == ref[0]["depth"]
        assert r["traces"][0] == ref[0]["traces"][0]


@pytest.mark.slow
def test_multihost_midrun_growth():
    """Tiny send/level caps force mid-run capacity growth; every
    controller takes the identical growth branch (replicated scal) and
    the re-homed global arrays still land on the oracle's counts
    (VERDICT r2 item 8: lifted NotImplementedError)."""
    want = explore(MICRO)
    outs = _run_pair({"scap": 2, "lcap": 64, "vcap": 1 << 12})
    for r in outs:
        assert r["distinct"] == want.distinct_states
        assert r["depth"] == want.depth
        assert r["generated"] == want.generated_states
    # growth actually happened (caps above their floors)
    assert all(r["final_caps"][1] > 2 for r in outs)
