"""Differential tests: native C++ checker vs the Python oracle.

The native runtime (native/raft_checker.cc) is the framework's CPU
engine and the machine-measured stand-in for the reference's
"TLC -workers N" baseline (BASELINE.md) — it must agree with the oracle
on distinct-state counts, depth and invariant verdicts, with and
without symmetry reduction, across the Next families.
"""

import pytest

from raft_tla_tpu import native
from raft_tla_tpu.config import Bounds, ModelConfig, NEXT_DYNAMIC, NEXT_FULL
from raft_tla_tpu.models.explore import explore

MICRO = ModelConfig(
    n_servers=2, init_servers=(0, 1), values=(1,),
    max_inflight_override=4,
    bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                       max_client_requests=1),
    symmetry=False)

SMALL = ModelConfig(
    n_servers=2, init_servers=(0, 1), values=(1,),
    bounds=Bounds.make(max_log_length=2, max_timeouts=2),
    symmetry=False)

MEMBER = ModelConfig(
    n_servers=3, init_servers=(0, 1), values=(1,),
    next_family=NEXT_DYNAMIC, max_inflight_override=6,
    bounds=Bounds.make(max_log_length=2, max_timeouts=1,
                       max_client_requests=1, max_membership_changes=1),
    symmetry=False)


def compare(cfg, max_depth=10 ** 9, threads=4):
    want = explore(cfg, max_depth=max_depth)
    got = native.check(cfg, threads=threads, max_depth=max_depth)
    assert got.distinct_states == want.distinct_states, \
        (got.distinct_states, want.distinct_states)
    assert got.depth == want.depth, (got.depth, want.depth)
    want_viol = {v.invariant for v in want.violations
                 if v.invariant in native.INVARIANT_ORDER}
    assert set(got.violations) == want_viol, (got.violations, want_viol)
    return got


@pytest.mark.parametrize("sym", [False, True], ids=["nosym", "sym"])
def test_native_micro_exhaustive(sym):
    compare(MICRO.with_(symmetry=sym))


def test_native_small_bounded():
    compare(SMALL, max_depth=6)


def test_native_membership_bounded():
    compare(MEMBER, max_depth=5)


def test_native_unreliable_bounded():
    compare(SMALL.with_(next_family=NEXT_FULL), max_depth=4)


def test_native_single_thread_deterministic():
    a = compare(MICRO, threads=1)
    b = compare(MICRO, threads=8)
    assert a.distinct_states == b.distinct_states
