"""Unified observability layer (raft_tla_tpu/obs): span recorder,
metrics registry, ledger, heartbeat — and the cross-engine telemetry
parity the registry exists to guarantee.

The parity test is the structural guard against the PR-5 drift class
(`levels_fused` counted differently per harvest loop): all five
engines run the same tiny config and must emit the identical registry
key set, with the burst counters byte-equal between the ledger's final
record, the --stats-json payload and the checkpoint meta.
"""

import json
import os

import numpy as np
import pytest

from raft_tla_tpu.config import Bounds, ModelConfig, NEXT_ASYNC
from raft_tla_tpu.obs import (CHECK_COUNTER_KEYS, BURST_COUNTER_KEYS,
                              SIM_DISPATCH_KEYS, Heartbeat,
                              MetricsRegistry, Obs, RunLedger,
                              SpanRecorder, check_stats)
from raft_tla_tpu.obs.heartbeat import read_heartbeat

# the same tiny config for every engine (test_sharded's micro: VIEW-
# only constraints so count parity is representative-insensitive)
TINY = ModelConfig(
    n_servers=2, init_servers=(0, 1), values=(1,),
    max_inflight_override=2, next_family=NEXT_ASYNC, symmetry=False,
    constraints=("BoundedInFlightMessages", "BoundedRequestVote",
                 "BoundedLogSize", "BoundedTerms"),
    invariants=("ElectionSafety", "LogMatching"),
    bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                       max_client_requests=1))


# ---------------------------------------------------------------------
# unit tests (smoke tier: no device programs beyond import)
# ---------------------------------------------------------------------


@pytest.mark.smoke
def test_metrics_registry_is_strict():
    m = MetricsRegistry()
    m.register("a", 1)
    m.inc("a", 2)
    assert m.get("a") == 3
    with pytest.raises(ValueError):
        m.register("a")            # double registration
    with pytest.raises(KeyError):
        m.set("typo", 1)           # undeclared counter fails loudly
    assert m.as_dict() == {"a": 3}


@pytest.mark.smoke
def test_check_result_counters_are_registry_views():
    from raft_tla_tpu.engine.bfs import CheckResult
    r = CheckResult(distinct_states=7, generated_states=9)
    r.levels_fused += 2
    r.depth = 5
    # the attribute IS the registry entry — one store, no copies
    assert r.metrics.get("levels_fused") == 2
    assert r.metrics.get("depth") == 5
    assert tuple(r.metrics.keys()) == CHECK_COUNTER_KEYS


@pytest.mark.smoke
def test_check_stats_keys_byte_compatible():
    """--stats-json keys must match the pre-registry CLI output
    exactly (acceptance: byte-compatible in keys)."""
    from raft_tla_tpu.engine.bfs import CheckResult
    r = CheckResult(distinct_states=10, generated_states=20, depth=3)
    # engine payload (fp_bits given)
    out = check_stats(r.metrics.as_dict(), 1.5, 0, fp_bits=64)
    assert tuple(out.keys()) == (
        "distinct_states", "generated_states", "depth", "seconds",
        "states_per_sec", "dedup_hit_rate", "violations", "fp_bits",
        "expected_fp_collisions", "levels_fused", "burst_dispatches",
        "burst_bailouts", "guard_matmul", "dedup_kernel",
        "delta_matmul", "sym_canon")
    # oracle payload (no engine telemetry)
    out = check_stats(r.metrics.as_dict(), 1.5, 2)
    assert tuple(out.keys()) == (
        "distinct_states", "generated_states", "depth", "seconds",
        "states_per_sec", "dedup_hit_rate", "violations")
    # pin_interior_states appears only when nonzero, after violations
    r.pin_interior_states = 4
    out = check_stats(r.metrics.as_dict(), 1.5, 0, fp_bits=64)
    keys = list(out.keys())
    assert keys.index("pin_interior_states") == \
        keys.index("violations") + 1


@pytest.mark.smoke
def test_span_recorder_nesting_and_file(tmp_path):
    path = str(tmp_path / "tl.json")
    rec = SpanRecorder(path)
    with rec.span("outer"):
        with rec.span("inner"):
            pass
        with rec.span("inner"):
            pass
    rec.close()
    events = json.load(open(path))
    assert [e["name"] for e in events] == ["inner", "inner", "outer"]
    for e in events:
        assert e["ph"] == "X" and e["ts"] >= 0 and e["dur"] >= 0
    outer = events[-1]
    for inner in events[:2]:
        # proper nesting: inner spans inside the outer interval
        assert inner["ts"] >= outer["ts"]
        assert inner["ts"] + inner["dur"] <= \
            outer["ts"] + outer["dur"] + 1.0
    tot = rec.totals()
    assert tot["inner"]["count"] == 2 and tot["outer"]["count"] == 1


@pytest.mark.smoke
def test_span_recorder_killed_run_file_parses(tmp_path):
    """A run killed mid-span-stream must leave a loadable timeline
    (missing ] only — the trace-event spec makes it optional)."""
    path = str(tmp_path / "tl.json")
    rec = SpanRecorder(path)
    with rec.span("a"):
        pass
    with rec.span("b"):
        pass
    # no close(): simulate the kill; repair exactly as Perfetto does
    text = open(path).read()
    assert not text.rstrip().endswith("]")
    events = json.loads(text.rstrip().rstrip(",") + "]")
    assert [e["name"] for e in events] == ["a", "b"]


@pytest.mark.smoke
def test_heartbeat_and_ledger(tmp_path):
    hb_path = str(tmp_path / "hb.json")
    hb = Heartbeat(hb_path)
    hb.beat(depth=3, states=42)
    obj = read_heartbeat(hb_path)
    assert obj["depth"] == 3 and obj["states_enqueued"] == 42
    assert obj["pid"] == os.getpid() and obj["status"] == "running"
    hb.beat(depth=4, states=50, status="finished")
    assert read_heartbeat(hb_path)["status"] == "finished"
    # no .tmp leftover (write-then-rename)
    assert not os.path.exists(hb_path + ".tmp")

    led_path = str(tmp_path / "run.jsonl")
    led = RunLedger(led_path)
    led.record({"kind": "level", "depth": 1})
    led.record({"kind": "burst", "depth": 4})
    # readable BEFORE close: the killed-run contract
    lines = [json.loads(x) for x in open(led_path)]
    assert [x["kind"] for x in lines] == ["level", "burst"]
    assert all("ts" in x and "t_mono" in x for x in lines)
    led.close()


@pytest.mark.smoke
def test_obs_dispatch_record_shape(tmp_path):
    led_path = str(tmp_path / "run.jsonl")
    obs = Obs(ledger=RunLedger(led_path),
              heartbeat=Heartbeat(str(tmp_path / "hb.json")))
    obs.start()
    # the dispatch-passed depth must win over the registry's stale
    # `depth` counter (finalized only at run end)
    obs.dispatch(kind="level", depth=9, frontier=5,
                 metrics={"distinct_states": 100,
                          "generated_states": 200, "depth": 0})
    obs.finish(depth=9, states=100)
    recs = [json.loads(x) for x in open(led_path)]
    # ISSUE 17: start() writes a kind="meta" row (run identity) and
    # the resource sampler a kind="resource" row — the dispatch record
    # itself is the single kind="level" row
    (rec,) = [x for x in recs if x["kind"] == "level"]
    assert rec["depth"] == 9
    assert rec["frontier"] == 5 and rec["rss_bytes"] > 0
    assert rec["dedup_hit_rate"] == 0.5
    hb = read_heartbeat(str(tmp_path / "hb.json"))
    assert hb["depth"] == 9 and hb["status"] == "finished"


# ---------------------------------------------------------------------
# cross-engine telemetry parity (the acceptance test): all five
# engines, same tiny config, identical registry key sets; burst
# counters consistent between ledger, --stats-json payload and
# checkpoint meta
# ---------------------------------------------------------------------


def _run_with_obs(name, make_engine, tmp_path, checkpoint=True):
    led_path = str(tmp_path / f"{name}.jsonl")
    hb_path = str(tmp_path / f"{name}.hb.json")
    ckpt_path = str(tmp_path / f"{name}.ckpt")
    obs = Obs(ledger=RunLedger(led_path), heartbeat=Heartbeat(hb_path))
    obs.start()
    eng = make_engine()
    kw = dict(checkpoint_path=ckpt_path, checkpoint_every=1) \
        if checkpoint else {}
    r = eng.check(obs=obs, **kw)
    obs.finish(depth=int(r.depth), states=int(r.distinct_states))
    recs = [json.loads(x) for x in open(led_path)]
    assert recs, f"{name}: no ledger records"
    stats = check_stats(r.metrics.as_dict(), r.seconds,
                        len(r.violations), fp_bits=64)
    meta = None
    if checkpoint:
        z = np.load(ckpt_path, allow_pickle=False)
        meta = json.loads(str(z["meta"]))
        z.close()
    return r, recs, stats, meta, read_heartbeat(hb_path)


def _engine_cases():
    from raft_tla_tpu.engine.bfs import Engine
    from raft_tla_tpu.engine.spill import SpillEngine
    from raft_tla_tpu.parallel.mesh import ShardedEngine
    from raft_tla_tpu.parallel.spill_mesh import SpilledShardedEngine

    return {
        "bfs": (lambda: Engine(TINY, chunk=64, store_states=False),
                True),
        "spill": (lambda: SpillEngine(
            TINY, chunk=64, store_states=False, seg=1 << 10,
            vcap=1 << 12, sync_every=2), True),
        "mesh": (lambda: ShardedEngine(TINY, chunk=64,
                                       store_states=False), True),
        # SpilledShardedEngine does not checkpoint yet (its check
        # raises) — ledger/stats parity only
        "spill_mesh": (lambda: SpilledShardedEngine(
            TINY, chunk=64, store_states=False, lcap=1 << 11), False),
    }


def _telemetry_parity(name, tmp_path):
    """One engine family on the tiny config: registry key set,
    ledger/stats/checkpoint-meta burst-counter agreement, heartbeat
    parity."""
    make, ckpt = _engine_cases()[name]
    r, recs, stats, meta, hb = _run_with_obs(
        name, make, tmp_path, checkpoint=ckpt)
    # 1. the registry key set — structural identity across engines
    assert tuple(r.metrics.keys()) == CHECK_COUNTER_KEYS, name
    # 2. every DISPATCH record carries every registry key (the
    #    kind="meta"/"resource" rows ISSUE 17 added are bookkeeping,
    #    not dispatches)
    drecs = [x for x in recs if x.get("kind") in ("level", "burst")]
    assert drecs, f"{name}: no dispatch records"
    for rec in drecs:
        missing = set(CHECK_COUNTER_KEYS) - set(rec)
        assert not missing, f"{name}: ledger record lacks {missing}"
    # 3. burst counters: ledger final record == stats payload
    last = recs[-1]
    for k in BURST_COUNTER_KEYS:
        assert last[k] == stats[k], (name, k)
    # ... == checkpoint meta (the third historical copy)
    if meta is not None:
        for k in BURST_COUNTER_KEYS:
            assert meta[k] == stats[k], (name, k)
        assert meta["distinct"] == stats["distinct_states"], name
    # 4. heartbeat final depth == the run's reported depth
    assert hb["depth"] == r.depth == stats["depth"], name
    assert hb["states_enqueued"] == r.distinct_states, name
    assert hb["status"] == "finished", name
    # the fused path engaged (so the burst counters are live, not
    # trivially zero) — every engine's default burst must fire on
    # this tiny space
    assert r.levels_fused > 0, name
    # cross-engine count identity, anchored to the shared ORACLE
    # reference (conftest session cache) so every parametrized variant
    # asserts it independently — no ordering or selection dependence
    from conftest import cached_explore
    w = cached_explore(TINY)
    assert (r.distinct_states, r.depth, tuple(r.level_sizes)) == \
        (w.distinct_states, w.depth, tuple(w.level_sizes)), name


@pytest.mark.parametrize("name", ["bfs", "spill"])
def test_telemetry_parity_engine(name, tmp_path):
    """Fast representatives (tier-1 budget, round-13 suite diet): the
    single-device families.  The mesh variants below run the same body
    slow-marked — the MetricsRegistry single-source design plus the
    mesh count differentials elsewhere keep the fast signal."""
    _telemetry_parity(name, tmp_path)


@pytest.mark.slow
@pytest.mark.parametrize("name", ["mesh", "spill_mesh"])
def test_telemetry_parity_engine_mesh_slow(name, tmp_path):
    _telemetry_parity(name, tmp_path)


def test_burst_bailout_reuses_warmed_per_level_executable():
    """The BENCH_r08 recompile leak (round-9 satellite): in burst mode
    the per-level path runs only when a burst BAILS, and its cold
    compile used to land mid-run inside a level_dispatch span (11.6 s
    over 9 dispatches vs 1.65 s over 30 in per-level mode).  Pin the
    fix: the per-level executables warm at run start inside ONE
    compile span per mode, and the post-bail dispatches reuse the
    warmed executable — the step jit compiles exactly once (the
    density override maxes every family cap so no growth retrace can
    blur the count)."""
    from raft_tla_tpu.engine.bfs import Engine
    from raft_tla_tpu.engine.expand import _FAMILY_DENSITY
    dens = {nm: 1 << 10 for nm in _FAMILY_DENSITY}
    for mode, burst in (("burst", True), ("per_level", False)):
        rec = SpanRecorder()
        obs = Obs(spans=rec)
        # chunk=16 -> burst ring of 64 states: TINY's mid-run levels
        # outgrow it, so bursts engage on the tiny levels AND bail
        # mid-run, exercising the post-bail per-level path
        eng = Engine(TINY, chunk=16, store_states=False, burst=burst,
                     fam_density=dens)
        r = eng.check(obs=obs)
        tot = rec.totals()
        assert tot["compile"]["count"] == 1, (mode, tot)
        assert eng._step_jit._cache_size() == 1, mode
        assert eng._fin_jit._cache_size() == 1, mode
        if burst:
            # the leak path actually engaged: bursts committed levels,
            # bailed, and the per-level driver ran dispatches after
            assert r.levels_fused > 0
            assert r.burst_bailouts >= 1
            assert tot["level_dispatch"]["count"] >= 1
            assert r.depth - r.levels_fused >= 1


def test_telemetry_parity_sim_engine(tmp_path):
    """The fifth engine family: the sim ledger's per-dispatch records
    carry exactly the canonical SIM_DISPATCH_KEYS, consistent with the
    SimResult the run returns."""
    from raft_tla_tpu.sim.walker import SimEngine

    cfg = TINY.with_(invariants=("ElectionSafety",))
    led_path = str(tmp_path / "sim.jsonl")
    hb_path = str(tmp_path / "sim.hb.json")
    obs = Obs(ledger=RunLedger(led_path), heartbeat=Heartbeat(hb_path))
    obs.start()
    eng = SimEngine(cfg, walkers=8, max_depth=8, seed=0,
                    bloom_bits=12)
    r = eng.run(steps=24, steps_per_dispatch=8, stop_on_hit=False)
    # rerun through run(obs=...) — separate engine so the jit caches
    # stay warm from the first run
    r = SimEngine(cfg, walkers=8, max_depth=8, seed=0,
                  bloom_bits=12).run(steps=24, steps_per_dispatch=8,
                                     stop_on_hit=False, obs=obs)
    obs.finish(depth=int(r.steps_dispatched),
               states=int(r.walker_steps))
    recs = [json.loads(x) for x in open(led_path)]
    drecs = [x for x in recs if x.get("kind") == "sim"]
    assert drecs, "sim wrote no dispatch records"
    for rec in drecs:
        missing = set(SIM_DISPATCH_KEYS) - set(rec)
        assert not missing, f"sim ledger record lacks {missing}"
    last = recs[-1]
    # final record consistent with the returned SimResult
    assert last["steps_dispatched"] == r.steps_dispatched
    assert last["walker_steps"] == r.walker_steps
    assert last["restarts"] == r.restarts
    hb = read_heartbeat(hb_path)
    assert hb["depth"] == r.steps_dispatched
    assert hb["states_enqueued"] == r.walker_steps
