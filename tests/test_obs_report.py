"""Run registry + report engine (raft_tla_tpu/obs/registry,report —
ISSUE 17): atomic append, corrupt-record tolerance, parity/mode-drift
verdicts on REAL engine runs, regress exit codes through the CLI, the
resource-telemetry fields, per-process ledger seq demux, and the
cadence-aware watch stall detection.

One module-scope engine keeps the suite fast: a single compile warms
the jit caches via the depth-gated run (which doubles as the injected-
mismatch record), then the two full runs A/B record into the same
registry the CLI-level tests query."""

import importlib.util
import json
import os
import time

import pytest

from raft_tla_tpu.obs import from_flags
from raft_tla_tpu.obs.registry import RunRegistry, new_run_id
from raft_tla_tpu.obs.report import (diff_runs, extract,
                                     format_span_totals, regress)
from test_obs import TINY

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_watch():
    spec = importlib.util.spec_from_file_location(
        "watch", os.path.join(_REPO, "tools", "watch.py"))
    watch = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(watch)
    return watch


# ---------------------------------------------------------------------
# unit tests (smoke tier: no device programs)
# ---------------------------------------------------------------------


@pytest.mark.smoke
def test_registry_append_atomic_and_resolve(tmp_path):
    reg = RunRegistry(str(tmp_path / "reg"))
    with pytest.raises(ValueError):
        reg.append({"cmd": "check"})          # no run_id: loud
    ra, rb = "r20260806-000001-1-aaaaaa", "r20260806-000002-1-bbbbbb"
    reg.append({"run_id": ra, "cmd": "check", "status": "finished"})
    reg.append({"run_id": rb, "cmd": "bench", "status": "finished"})
    assert reg.run_ids() == [ra, rb]
    # atomic publish: no tmp leftovers, schema stamped
    assert not [n for n in os.listdir(reg.root) if n.endswith(".tmp")]
    assert reg.load(ra)["schema"] == 1
    assert reg.resolve(ra) == ra
    assert reg.resolve("last") == rb
    assert reg.resolve("r20260806-000001") == ra   # unique prefix
    assert reg.resolve("r2026") is None            # ambiguous
    assert reg.resolve("nope") is None


@pytest.mark.smoke
def test_registry_corrupt_record_skipped_with_warning(tmp_path, capsys):
    reg = RunRegistry(str(tmp_path / "reg"))
    rid = new_run_id()
    reg.append({"run_id": rid, "cmd": "check"})
    bad = os.path.join(reg.root, "rzz-corrupt.json")
    with open(bad, "w") as fh:
        fh.write("{ torn mid-wr")
    got = dict(reg.records())
    assert set(got) == {rid}
    err = capsys.readouterr().err
    assert "skipping corrupt record" in err and "rzz-corrupt" in err


@pytest.mark.smoke
def test_format_span_totals():
    s = format_span_totals({"harvest": {"count": 4, "seconds": 0.5},
                            "compile": {"count": 1, "seconds": 6.1}})
    assert s == "compile=6.10s/1  harvest=0.50s/4"


@pytest.mark.smoke
def test_extract_shapes():
    # flat --stats-json payload: numeric keys become counters
    e = extract({"distinct_states": 7, "depth": 3, "seconds": 0.1,
                 "guard_matmul": 1})
    assert e["counters"]["distinct_states"] == 7
    assert e["level_sizes"] is None
    # bench headline: descend into detail
    e = extract({"metric": "m", "value": 1.0,
                 "detail": {"distinct_states": 7, "depth": 3}})
    assert e["counters"]["depth"] == 3
    # BENCH A/B row: phase_seconds/phase_counts become span totals
    e = extract({"distinct_states": 7,
                 "phase_seconds": {"expand": 1.5},
                 "phase_counts": {"expand": 3}})
    assert e["spans"]["expand"] == {"count": 3, "seconds": 1.5}
    # deep_run row: "distinct" fills distinct_states
    assert extract({"distinct": 9})["counters"]["distinct_states"] == 9


@pytest.mark.smoke
def test_regress_span_ratio_opt_in():
    base = {"run_id": "ra", "counters": {"distinct_states": 5},
            "spans": {"x": {"count": 1, "seconds": 1.0},
                      "tiny": {"count": 1, "seconds": 0.001}}}
    run = {"run_id": "rb", "counters": {"distinct_states": 5},
           "spans": {"x": {"count": 1, "seconds": 10.0},
                     "tiny": {"count": 1, "seconds": 1.0}}}
    rep, code = regress(run, base)            # ratios off by default
    assert code == 0 and rep["verdict"] == "ok"
    rep, code = regress(run, base, max_span_ratio=2.0)
    assert code == 1
    assert any("span 'x' regressed" in f for f in rep["failures"])
    # the sub-min_seconds baseline phase never trips (CI noise guard)
    assert not any("tiny" in f for f in rep["failures"])


@pytest.mark.smoke
def test_ledger_seq_demux_and_legacy_rows(tmp_path):
    """tools/watch.py rate estimation demuxes interleaved runs by
    (run_id, seq); pre-ISSUE-17 rows carry neither and still parse."""
    watch = _load_watch()
    path = str(tmp_path / "ledger.jsonl")
    rows = [
        # legacy rows: no run_id, no seq
        {"kind": "level", "distinct_states": 10, "seconds": 1.0},
        {"kind": "level", "distinct_states": 20, "seconds": 2.0},
    ]
    with open(path, "w") as fh:
        for r in rows:
            fh.write(json.dumps(r) + "\n")
    legacy = watch.last_ledger_records(path)
    assert [r["distinct_states"] for r in legacy] == [10, 20]
    # a resumed run appends stamped rows (out of file order, even):
    # only the NEWEST run id's rows feed the rate, in seq order
    more = [
        {"kind": "meta", "run_id": "r2", "seq": 1},
        {"kind": "level", "run_id": "r2", "seq": 3,
         "distinct_states": 99, "seconds": 9.0},
        {"kind": "resource", "run_id": "r2", "seq": 4},
        {"kind": "level", "run_id": "r2", "seq": 2,
         "distinct_states": 50, "seconds": 5.0},
    ]
    with open(path, "a") as fh:
        for r in more:
            fh.write(json.dumps(r) + "\n")
    got = watch.last_ledger_records(path)
    assert [r["seq"] for r in got] == [2, 3]
    assert all(r["run_id"] == "r2" for r in got)


@pytest.mark.smoke
def test_watch_cadence_stall(tmp_path):
    """A heartbeat whose age exceeds N x its own observed cadence
    flags STALLED? before the absolute --stale bound trips."""
    watch = _load_watch()
    now = time.time()
    hb_path = str(tmp_path / "hb.json")

    def write_hb(last_ts, started_ts, beats):
        with open(hb_path, "w") as fh:
            json.dump({"pid": os.getpid(), "status": "running",
                       "depth": 5, "states_enqueued": 100,
                       "last_dispatch_ts": last_ts,
                       "started_ts": started_ts, "beats": beats}, fh)

    # 9 beats over 40s -> 5s cadence; 120s silence >> 8x5s (and the
    # 30s floor), yet far under the 10000s absolute bound
    write_hb(now - 120, now - 160, beats=9)
    line, code = watch.status_line(hb_path, None, stale_s=10_000)
    assert code == 1 and "STALLED?" in line and "cadence" in line
    # same silence, too few beats: no cadence estimate, healthy
    write_hb(now - 120, now - 160, beats=3)
    line, code = watch.status_line(hb_path, None, stale_s=10_000)
    assert code == 0 and "STALLED" not in line
    # fresh heartbeat with a cadence: healthy
    write_hb(now - 2, now - 42, beats=9)
    line, code = watch.status_line(hb_path, None, stale_s=10_000)
    assert code == 0 and "STALLED" not in line
    # --cadence-factor 0 disables the cadence branch entirely
    write_hb(now - 120, now - 160, beats=9)
    line, code = watch.status_line(hb_path, None, stale_s=10_000,
                                   cadence_factor=0)
    assert code == 0
    # the absolute --stale bound still wins when older than it
    line, code = watch.status_line(hb_path, None, stale_s=60)
    assert code == 1 and "STALLED?" in line


# ---------------------------------------------------------------------
# real-run tests: one engine, one registry, three recorded runs
# ---------------------------------------------------------------------


@pytest.fixture(scope="module")
def runs(tmp_path_factory):
    from raft_tla_tpu.engine.bfs import Engine
    td = tmp_path_factory.mktemp("obs_report")
    reg_dir = str(td / "registry")
    eng = Engine(TINY, chunk=64, store_states=False)
    ids = {}

    def record(tag, **kw):
        obs = from_flags(ledger=str(td / f"{tag}.jsonl"),
                         heartbeat=str(td / f"{tag}.hb.json"),
                         registry=reg_dir,
                         run_info={"cmd": "check", "cfg": repr(TINY)},
                         meta={"spec": eng.ir.name,
                               "ir_fingerprint": eng.ir.fingerprint()})
        obs.start()
        r = eng.check(obs=obs, **kw)
        obs.finish(depth=int(r.depth), states=int(r.distinct_states),
                   counters=r.metrics.as_dict(),
                   level_sizes=[int(x) for x in r.level_sizes])
        ids[tag] = obs.run_id
        return r

    record("gated", max_depth=2)   # warms the caches AND is the
    record("a")                    # injected-mismatch record
    record("b")
    return {"dir": td, "reg_dir": reg_dir, "ids": ids}


def test_diff_clean_on_identical_runs(runs):
    reg = RunRegistry(runs["reg_dir"])
    rep = diff_runs(reg.load(runs["ids"]["a"]),
                    reg.load(runs["ids"]["b"]))
    assert rep["verdict"] == "clean"
    assert rep["mode_drift"] == []
    counts = rep["parity"]["counts"]
    assert counts["distinct_states"]["equal"]
    assert rep["parity"]["level_sizes_equal"] is True
    assert rep["run_a"]["run_id"] == runs["ids"]["a"]
    # span deltas cover the phases both runs recorded
    assert rep["spans"], "no span deltas on instrumented runs"


def test_diff_mismatch_on_depth_gate(runs):
    reg = RunRegistry(runs["reg_dir"])
    rep = diff_runs(reg.load(runs["ids"]["a"]),
                    reg.load(runs["ids"]["gated"]))
    assert rep["verdict"] == "mismatch"
    assert not rep["parity"]["counts"]["distinct_states"]["equal"]
    assert rep["parity"]["level_sizes_equal"] is False


def test_diff_mode_drift_named(runs):
    """Counts equal under different mode flags is the repo's A/B shape
    — named drift, not a mismatch (synthesized record: the flags are
    pure counter values, no second compile needed)."""
    reg = RunRegistry(runs["reg_dir"])
    a = reg.load(runs["ids"]["a"])
    d = json.loads(json.dumps(a))
    d["counters"]["delta_matmul"] = 1 - int(
        a["counters"]["delta_matmul"])
    rep = diff_runs(a, d)
    assert rep["verdict"] == "mode_drift"
    assert rep["mode_drift"] == ["delta_matmul"]


def test_obs_cli_exit_codes(runs, capsys):
    from raft_tla_tpu import cli
    reg, ids = runs["reg_dir"], runs["ids"]
    assert cli.main(["obs", "ls", "--registry", reg]) == 0
    out = capsys.readouterr().out
    for rid in ids.values():
        assert rid in out
    assert cli.main(["obs", "show", "--registry", reg, "last"]) == 0
    capsys.readouterr()
    # diff: clean pair 0, depth-gated pair 1, unresolvable token 2
    assert cli.main(["obs", "diff", "--registry", reg,
                     ids["a"], ids["b"]]) == 0
    assert json.loads(capsys.readouterr().out)["verdict"] == "clean"
    assert cli.main(["obs", "diff", "--registry", reg,
                     ids["a"], ids["gated"]]) == 1
    capsys.readouterr()
    assert cli.main(["obs", "diff", "--registry", reg,
                     ids["a"], "nope"]) == 2
    capsys.readouterr()
    # regress: parity pair 0, injected mismatch 1, usage error 2
    assert cli.main(["obs", "regress", "--registry", reg, ids["b"],
                     "--against", ids["a"]]) == 0
    capsys.readouterr()
    assert cli.main(["obs", "regress", "--registry", reg,
                     ids["gated"], "--against", ids["a"]]) == 1
    rep = json.loads(capsys.readouterr().out)
    assert any("count mismatch" in f for f in rep["failures"])
    assert cli.main(["obs", "regress", "--registry", reg,
                     ids["b"]]) == 2
    capsys.readouterr()


def test_obs_cli_regress_baseline_file(runs, tmp_path, capsys):
    """--baseline accepts a committed file: a registry record and a
    BENCH-style rows map (--baseline-row)."""
    from raft_tla_tpu import cli
    reg, ids = runs["reg_dir"], runs["ids"]
    rec = RunRegistry(reg).load(ids["a"])
    base = str(tmp_path / "base.json")
    with open(base, "w") as fh:
        json.dump(rec, fh)
    assert cli.main(["obs", "regress", "--registry", reg, ids["b"],
                     "--baseline", base]) == 0
    capsys.readouterr()
    rows = str(tmp_path / "rows.json")
    with open(rows, "w") as fh:
        json.dump({"rows": {"on": rec}}, fh)
    # rows map without --baseline-row: loud usage error
    with pytest.raises(SystemExit):
        cli.main(["obs", "regress", "--registry", reg, ids["b"],
                  "--baseline", rows])
    assert cli.main(["obs", "regress", "--registry", reg, ids["b"],
                     "--baseline", rows, "--baseline-row", "on"]) == 0
    capsys.readouterr()


def test_resource_telemetry_fields(runs):
    """The registry record, heartbeat and ledger all carry the
    sampler's fields; the gated (compiling) run attributes its compile
    wall-clock."""
    reg = RunRegistry(runs["reg_dir"])
    for tag in ("gated", "a", "b"):
        rec = reg.load(runs["ids"][tag])
        res = rec["resources"]
        assert res["samples"] >= 1, (tag, res)
        assert res["rss_peak_bytes"] > 0, (tag, res)
        assert "compile_seconds" in res, (tag, res)
        assert rec["backend"]["platform"], tag
        assert rec["cmd"] == "check" and "ModelConfig" in rec["cfg"]
        assert rec["spans"], tag
        assert rec["counters"]["distinct_states"] == \
            rec["distinct_states"]
        assert rec["artifacts"]["ledger"].endswith(f"{tag}.jsonl")
    # the compile happened under the gated run's obs
    gated = reg.load(runs["ids"]["gated"])["resources"]
    assert gated["compile_seconds"] > 0 and gated["compile_count"] >= 1
    # heartbeat: run_id + final resource snapshot
    hb = json.load(open(str(runs["dir"] / "a.hb.json")))
    assert hb["run_id"] == runs["ids"]["a"]
    assert hb["resources"]["rss_bytes"] > 0


def test_ledger_rows_stamped_and_sequenced(runs):
    """Every ledger row carries the registry's run id plus a strictly
    increasing per-process seq; the meta row opens with the backend
    fingerprint; a resource row precedes the dispatch rows; the FINAL
    row stays the final dispatch record (the obs_smoke contract)."""
    for tag in ("a", "b"):
        rows = [json.loads(x)
                for x in open(str(runs["dir"] / f"{tag}.jsonl"))]
        assert all(r["run_id"] == runs["ids"][tag] for r in rows)
        seqs = [r["seq"] for r in rows]
        assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
        kinds = [r["kind"] for r in rows]
        assert kinds[0] == "meta"
        assert rows[0]["backend"]["platform"]
        assert "resource" in kinds
        assert kinds[-1] in ("level", "burst"), kinds
        res = next(r for r in rows if r["kind"] == "resource")
        assert res["rss_bytes"] > 0
