"""Tests for the plain-Python oracle (models/raft.py, explore.py).

The key fixture is a semantic replay to a 2-concurrent-leaders state — the
reference documents that such a state is reachable (shortest NextAsync trace
has length 20, tlc_membership/raft.tla:1179-1181).  We drive the oracle's
successor function by action label, which exercises elections, vote handling
and BecomeLeader end-to-end.
"""

import pytest

from raft_tla_tpu.config import (Bounds, LEADER, CANDIDATE, FOLLOWER,
                                 ModelConfig, NEXT_ASYNC, NEXT_ASYNC_CRASH,
                                 NEXT_DYNAMIC, NIL)
from raft_tla_tpu.models.explore import (canonicalize, explore, relabel,
                                         symmetry_perms)
from raft_tla_tpu.models.raft import init_state, successors
from raft_tla_tpu.models import predicates


def apply_label(sv, h, cfg, label):
    matches = [(l, s2, h2) for l, s2, h2 in successors(sv, h, cfg)
               if l == label]
    assert matches, f"no successor labelled {label}"
    assert len(matches) == 1, f"ambiguous label {label}"
    return matches[0][1], matches[0][2]


CFG3 = ModelConfig(n_servers=3, init_servers=(0, 1, 2), values=(1, 2),
                   next_family=NEXT_ASYNC)

# An election of server `a` at a fresh term, voters = a (self) and b.
# b must first adopt a's term via UpdateTerm (raft.tla:826-832) — the
# request handler only fires once m.mterm <= currentTerm[i] (raft.tla:585).
def election(a, b):
    return [
        f"Timeout({a})",
        f"RequestVote({a},{a})",
        f"RequestVote({a},{b})",
        f"HandleRVReq({a}<-{a})",
        f"HandleRVResp({a}<-{a})",
        f"UpdateTerm({b})",
        f"HandleRVReq({b}<-{a})",
        f"HandleRVResp({a}<-{b})",
        f"BecomeLeader({a})",
    ]


def test_two_concurrent_leaders_reachable():
    """Reproduces the reference's ConcurrentLeaders scenario
    (raft.tla:1158, 1179-1181): elect s0 at term 2 with votes {s0,s1},
    then elect s1 at term 3 with votes {s1,s2}; s0 stays Leader."""
    sv, h = init_state(CFG3)
    for lbl in election(0, 1) + election(1, 2):
        sv, h = apply_label(sv, h, CFG3, lbl)
    assert sv.st[0] == LEADER and sv.st[1] == LEADER
    assert sv.ct == (2, 3, 3)
    assert h.nleaders == 2
    assert h.timeout == (1, 1, 0)
    # ConcurrentLeaders scenario property is violated here (that's the
    # point of the property: a violation is the witness trace).
    assert not predicates.concurrent_leaders(sv, h, CFG3)
    # And the real safety invariants still hold.
    for nm in CFG3.invariants:
        assert predicates.INVARIANTS[nm](sv, h, CFG3), nm
    # BecomeLeader(1) logged both leaders (raft.tla:483).
    bl = [r for r in h.glob if r[0] == "BecomeLeader"]
    assert bl[-1][2] == 0b011


def test_update_term_does_not_consume_message():
    """UpdateTerm leaves the message in the bag (raft.tla:831)."""
    sv, h = init_state(CFG3)
    for lbl in ["Timeout(0)", "RequestVote(0,1)"]:
        sv, h = apply_label(sv, h, CFG3, lbl)
    bag_before = sv.msgs
    sv2, h2 = apply_label(sv, h, CFG3, "UpdateTerm(1)")
    assert sv2.msgs == bag_before
    assert sv2.ct[1] == 2 and sv2.st[1] == FOLLOWER and sv2.vf[1] == NIL
    # After adopting the term, the request can still be handled.
    sv3, h3 = apply_label(sv2, h2, CFG3, "HandleRVReq(1<-0)")
    assert sv3.vf[1] == 0


def test_restart_keeps_stable_storage():
    cfg = CFG3.with_(next_family=NEXT_ASYNC_CRASH)
    sv, h = init_state(cfg)
    for lbl in election(0, 1) + ["ClientRequest(0,1)"]:
        sv, h = apply_label(sv, h, cfg, lbl)
    sv2, h2 = apply_label(sv, h, cfg, "Restart(0)")
    # Keeps currentTerm, votedFor, log; resets the rest (raft.tla:401-411).
    assert sv2.ct[0] == sv.ct[0]
    assert sv2.vf[0] == sv.vf[0]
    assert sv2.log[0] == sv.log[0]
    assert sv2.st[0] == FOLLOWER and sv2.ci[0] == 0
    assert sv2.ni[0] == (1, 1, 1) and sv2.mi[0] == (0, 0, 0)
    assert h2.restarted == (1, 0, 0)


def test_replication_and_commit():
    """§3.3 call stack: ClientRequest → AppendEntries → accept → response →
    AdvanceCommitIndex."""
    cfg = CFG3
    sv, h = init_state(cfg)
    for lbl in election(0, 1) + ["ClientRequest(0,1)",
                                 "AppendEntries(0,1)",
                                 "AENoConflict(1)"]:
        sv, h = apply_label(sv, h, cfg, lbl)
    assert sv.log[1] == sv.log[0]
    # NoConflict did NOT consume the request nor reply (raft.tla:668-672);
    # reprocessing it now hits AlreadyDone, which replies.
    sv, h = apply_label(sv, h, cfg, "AEAlreadyDone(1)")
    sv, h = apply_label(sv, h, cfg, "HandleAEResp(0<-1)")
    assert sv.mi[0][1] == 1 and sv.ni[0][1] == 2
    sv, h = apply_label(sv, h, cfg, "AdvanceCommitIndex(0)")
    assert sv.ci[0] == 1
    assert h.glob[-1][0] == "CommitEntry"


def test_membership_add_end_to_end():
    """§3.4 call stack: AddNewServer → catchup → CheckOldConfig → ConfigEntry
    append, on Server={0..3}, InitServer={0,1,2}."""
    cfg = ModelConfig(n_servers=4, init_servers=(0, 1, 2), values=(1,),
                      next_family=NEXT_DYNAMIC)
    sv, h = init_state(cfg)
    for lbl in election(0, 1):
        sv, h = apply_label(sv, h, cfg, lbl)
    sv, h = apply_label(sv, h, cfg, "AddNewServer(0,3)")
    assert h.ntried == 1
    assert h.glob[-2][0] == "TryAddServer"
    sv, h = apply_label(sv, h, cfg, "CatReqOk(3)")
    sv, h = apply_label(sv, h, cfg, "CatRespDone(0)")   # NumRounds=1
    sv, h = apply_label(sv, h, cfg, "CocApply(0)")
    assert sv.log[0][-1][1] == 1                         # ConfigEntry
    assert sv.log[0][-1][2] == 0b1111                    # {0,1,2,3}
    assert h.nmc == 1
    assert h.glob[-1][0] == "AddServer"
    # Timeout guard: the added server may now campaign only per ITS OWN
    # config view, which is still InitServer (its log lacks the entry).
    assert not any(l == "Timeout(3)" for l, _, _ in successors(sv, h, cfg))


def test_catchup_multiple_rounds_bag_stays_orderable():
    """Regression: the follow-up CatchupRequest's absent mcommitIndex field
    (encoded -1, raft.tla:762-771) must coexist in the bag with an
    AddNewServer CatchupRequest (which has the field) without breaking the
    canonical bag sort."""
    cfg = ModelConfig(n_servers=4, init_servers=(0, 1, 2), values=(1,),
                      next_family=NEXT_DYNAMIC, num_rounds=2)
    sv, h = init_state(cfg)
    for lbl in election(0, 1) + ["AddNewServer(0,3)", "CatReqOk(3)",
                                 "CatRespMore(0)",       # rounds 2 -> 1
                                 "AddNewServer(0,3)"]:   # second, with field
        sv, h = apply_label(sv, h, cfg, lbl)
    kinds = sorted(m[4] for m, _ in sv.msgs if m[0] == 5)  # MT_CATREQ mcommit
    assert kinds == [-1, 0]
    # and canonicalization over the bag still works
    perms = symmetry_perms(cfg)
    canonicalize(sv, perms, cfg)
    # both in-flight requests are receivable (two CatReqOk(3) successors)
    n_catreqok = sum(1 for l, _, _ in successors(sv, h, cfg)
                     if l == "CatReqOk(3)")
    assert n_catreqok == 2


def test_coc_discard_and_process_both_enabled():
    """The HandleCheckOldConfig guard quirk (raft.tla:796): for a Leader at
    the message's term, discard AND process are both enabled."""
    cfg = ModelConfig(n_servers=3, init_servers=(0, 1, 2), values=(1,),
                      next_family=NEXT_DYNAMIC)
    sv, h = init_state(cfg)
    for lbl in election(0, 1):
        sv, h = apply_label(sv, h, cfg, lbl)
    sv, h = apply_label(sv, h, cfg, "DeleteServer(0,2)")
    labels = [l for l, _, _ in successors(sv, h, cfg)]
    assert "CocDiscard(0)" in labels and "CocApply(0)" in labels


def test_symmetry_relabel_roundtrip():
    cfg = CFG3
    sv, h = init_state(cfg)
    for lbl in election(0, 1) + ["ClientRequest(0,2)", "AppendEntries(0,2)"]:
        sv, h = apply_label(sv, h, cfg, lbl)
    perms = symmetry_perms(cfg)
    assert len(perms) == 6
    for sigma in perms:
        rl = relabel(sv, sigma, cfg)
        # canonical form is permutation-invariant
        assert canonicalize(rl, perms, cfg) == canonicalize(sv, perms, cfg)
    # identity perm is a no-op
    assert relabel(sv, (0, 1, 2), cfg) == sv


MICRO = ModelConfig(
    n_servers=2, init_servers=(0, 1), values=(1,), next_family=NEXT_ASYNC,
    symmetry=False, max_inflight_override=2,
    bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                       max_client_requests=1))


def test_micro_bfs_deterministic_and_symmetry_consistent():
    r1 = explore(MICRO)
    r2 = explore(MICRO)
    assert r1.distinct_states == r2.distinct_states
    assert r1.violations == [] and r2.violations == []
    rs = explore(MICRO.with_(symmetry=True))
    assert rs.violations == []
    assert rs.distinct_states <= r1.distinct_states
    assert r1.distinct_states <= 2 * rs.distinct_states


def test_micro_bfs_crash_family_grows_space():
    r_async = explore(MICRO)
    r_crash = explore(MICRO.with_(next_family=NEXT_ASYNC_CRASH))
    assert r_crash.distinct_states > r_async.distinct_states
