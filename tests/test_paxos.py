"""Paxos — the second SpecIR tenant — differentially pinned.

Mirrors the Raft test architecture: the plain-Python oracle
(spec/paxos/model.py) is the semantics anchor; the engines must match
it bit-for-bit through the UNMODIFIED bfs/spill/mesh/sim stack.  Fast
tier-1 representatives here are the oracle step-for-step differential
and one engine-vs-oracle count parity run (sub-5s each); full-space
and mesh/spill duplicates are slow-marked (tier-1 budget, ROADMAP
standing constraint).
"""

import numpy as np
import pytest

from raft_tla_tpu.spec import get_spec, spec_of
from raft_tla_tpu.spec.paxos.config import PaxosConfig
from raft_tla_tpu.spec.paxos.layout import PaxosLayout, decode, encode
from raft_tla_tpu.spec.paxos.model import (
    PaxosState, agreement, canonicalize, chosen_values, init_state,
    relabel, successors, symmetry_perms, validity, value_chosen,
    walk_key)
from raft_tla_tpu.spec.paxos.oracle import explore

CFG = PaxosConfig()                       # N=3, B=2, V=2, I=1
CFG_NS = CFG.with_(symmetry=False)

# full-space golden counts for the stock model (cross-checked against
# the oracle at runtime in the parity tests; pinned here so a silent
# oracle regression cannot re-pin the engines to a wrong count)
GOLD_SYM = dict(distinct=857, generated=3328, depth=17)
GOLD_NOSYM = dict(distinct=3921, generated=15299, depth=17)


def apply_label(sv, h, cfg, label):
    matches = [(l, s2, h2) for l, s2, h2 in successors(sv, h, cfg)
               if l == label]
    assert len(matches) == 1, f"label {label}: {len(matches)} matches"
    return matches[0][1], matches[0][2]


# ---------------------------------------------------------------------------
# oracle semantics
# ---------------------------------------------------------------------------

def test_oracle_chosen_value_replay():
    """Minimal chosen-value run: 1a, two promises, proposal, quorum of
    accepts — Agreement/Validity hold throughout, ValueChosen flips
    exactly at the quorum accept."""
    sv, h = init_state(CFG)
    steps = ["Phase1a(0,0)", "Phase1b(0,0,0)", "Phase1b(0,1,0)",
             "Phase2a(0,0,1)", "Phase2b(0,0,0,1)"]
    for lbl in steps:
        sv, h = apply_label(sv, h, CFG, lbl)
        assert agreement(sv, h, CFG) and validity(sv, h, CFG)
        assert value_chosen(sv, h, CFG)       # no quorum of 2b yet
    sv, h = apply_label(sv, h, CFG, "Phase2b(0,1,0,1)")
    assert chosen_values(sv, 0, CFG) == {1}
    assert not value_chosen(sv, h, CFG)       # the witness
    assert agreement(sv, h, CFG) and validity(sv, h, CFG)
    assert len(h.glob) == 6


def test_oracle_value_rule_pins_accepted_value():
    """After a value is accepted by a quorum member, a higher ballot's
    Phase2a must re-propose THAT value (the consensus core): with the
    1b reports of {a0 (voted v=1 at b0), a1 (fresh)} the only enabled
    Phase2a at b1 is value 1."""
    sv, h = init_state(CFG)
    for lbl in ["Phase1a(0,0)", "Phase1b(0,0,0)", "Phase1b(0,1,0)",
                "Phase2a(0,0,1)", "Phase2b(0,0,0,1)",
                "Phase1a(0,1)", "Phase1b(0,0,1)", "Phase1b(0,1,1)"]:
        sv, h = apply_label(sv, h, CFG, lbl)
    labels = [l for l, _s, _h in successors(sv, h, CFG)]
    assert "Phase2a(0,1,1)" in labels
    assert "Phase2a(0,1,0)" not in labels
    # and a0 is now preempted: promised b1 above its accepted b0
    assert sv.mb[0][0] == 1 and sv.vb[0][0] == 0


def test_oracle_explore_counts_and_symmetry():
    r = explore(CFG)
    assert (r.distinct_states, r.generated_states, r.depth) == \
        (GOLD_SYM["distinct"], GOLD_SYM["generated"], GOLD_SYM["depth"])
    assert not r.violations
    r2 = explore(CFG_NS)
    assert (r2.distinct_states, r2.generated_states, r2.depth) == \
        (GOLD_NOSYM["distinct"], GOLD_NOSYM["generated"],
         GOLD_NOSYM["depth"])
    # canonicalization sanity: relabeled states share a canonical form
    perms = symmetry_perms(CFG)
    sv, h = init_state(CFG)
    for lbl in ["Phase1a(0,0)", "Phase1b(0,2,0)"]:
        sv, h = apply_label(sv, h, CFG, lbl)
    for sig in perms:
        assert canonicalize(relabel(sv, sig, CFG), perms, CFG) == \
            canonicalize(sv, perms, CFG)


def test_multi_instance_product_law():
    """Independent instances ⇒ the reachable set is the product: the
    I=2 distinct count is exactly the I=1 count squared (symmetry off —
    acceptor relabeling couples instances)."""
    c1 = PaxosConfig(symmetry=False, n_ballots=1, n_values=2)
    c2 = c1.with_(n_instances=2)
    r1, r2 = explore(c1), explore(c2)
    assert r2.distinct_states == r1.distinct_states ** 2
    assert not r1.violations and not r2.violations


# ---------------------------------------------------------------------------
# codec + fingerprint
# ---------------------------------------------------------------------------

def test_codec_roundtrip_reachable():
    lay = PaxosLayout(CFG)
    r = explore(CFG_NS, keep_states=True, max_depth=5)
    assert r.states
    for sv, h in r.states.values():
        sv2, h2 = decode(lay, encode(lay, sv, h))
        assert sv2 == sv


def test_fingerprint_symmetry_and_distinctness():
    """Relabeled states fingerprint identically; distinct canonical
    states fingerprint distinctly (on the reachable sample)."""
    import jax.numpy as jnp
    ir = get_spec("paxos")
    lay = PaxosLayout(CFG)
    fpr = ir.make_fingerprinter(CFG)
    perms = symmetry_perms(CFG)
    r = explore(CFG_NS, keep_states=True, max_depth=4)
    seen = {}
    for sv, h in r.states.values():
        fp = tuple(int(x) for x in np.asarray(
            fpr.fingerprint({k: jnp.asarray(v) for k, v in
                             encode(lay, sv, h).items()})))
        for sig in perms[1:3]:
            svp = relabel(sv, sig, CFG)
            fpp = tuple(int(x) for x in np.asarray(
                fpr.fingerprint({k: jnp.asarray(v) for k, v in
                                 encode(lay, svp, h).items()})))
            assert fpp == fp, "relabeling changed the fingerprint"
        key = canonicalize(sv, perms, CFG)
        if key in seen:
            assert seen[key] == fp
        else:
            assert fp not in set(seen.values()), \
                "distinct canonical states collided"
            seen[key] = fp


# ---------------------------------------------------------------------------
# engine differentials (fast tier-1 representatives)
# ---------------------------------------------------------------------------

def _decode_all(lay, expander, arrs):
    out = []
    for lbl, sv2 in expander.expand_one(arrs):
        out.append((lbl, walk_key(decode(lay, sv2)[0])))
    return out


def test_kernels_step_for_step_differential():
    """Oracle step-for-step: on a reachable-state sample, the
    expander's enabled lanes (labels AND decoded successor states)
    equal the oracle's successor enumeration exactly — the paxos twin
    of tests/test_kernels.py."""
    from raft_tla_tpu.engine.expand import Expander
    lay = PaxosLayout(CFG)
    exp = Expander(CFG)
    r = explore(CFG_NS, keep_states=True, max_depth=6)
    states = list(r.states.values())[::3][:40]
    assert len(states) >= 20
    for sv, h in states:
        got = _decode_all(lay, exp, encode(lay, sv, h))
        want = [(lbl, walk_key(s2))
                for lbl, s2, _h2 in successors(sv, h, CFG)]
        assert got == want, f"successor divergence at {sv}"


def test_engine_vs_oracle_full_space_bfs():
    """The acceptance pin: `check --spec paxos` lands on the oracle's
    exact counts through the unmodified bfs engine (distinct,
    generated, depth, level sizes, zero violations)."""
    from raft_tla_tpu.engine.bfs import Engine
    ro = explore(CFG)
    eng = Engine(CFG, chunk=128, store_states=False)
    r = eng.check()
    assert r.distinct_states == ro.distinct_states == \
        GOLD_SYM["distinct"]
    assert r.generated_states == ro.generated_states
    assert r.depth == ro.depth
    assert r.level_sizes == ro.level_sizes
    assert not r.violations and r.violations_global == 0


def test_engine_vs_oracle_spill_depth_capped():
    """Spill-engine parity rep, depth-capped to stay sub-5s; the
    full-space duplicate is slow-marked below."""
    from raft_tla_tpu.engine.spill import SpillEngine
    ro = explore(CFG, max_depth=9)
    eng = SpillEngine(CFG, chunk=128, store_states=False, seg=1 << 12)
    r = eng.check(max_depth=9)
    assert r.distinct_states == ro.distinct_states
    assert r.generated_states == ro.generated_states
    assert r.depth == ro.depth


def test_sim_engine_walks_and_oracle_replays():
    """The sim engine runs paxos unmodified: a ValueChosen witness is
    found and its decoded trace replays through the oracle transition
    relation (the sim acceptance check, paxos twin of test_sim)."""
    from raft_tla_tpu.sim import SimEngine
    from raft_tla_tpu.spec.paxos.oracle import oracle_validates_walk
    eng = SimEngine(CFG.with_(invariants=("ValueChosen",)),
                    walkers=32, max_depth=24, seed=3)
    r = eng.run(steps=300, steps_per_dispatch=64)
    assert r.hits, "no ValueChosen witness in 300 fleet steps"
    h = eng.decode_hit(r.hits[0])
    states = [sv for _lbl, sv in h.trace]
    labels = oracle_validates_walk(CFG, states)
    assert labels == [lbl for lbl, _sv in h.trace[1:]]


# ---------------------------------------------------------------------------
# registry / error paths + spec stamping
# ---------------------------------------------------------------------------

@pytest.mark.smoke
def test_spec_registry_error_paths():
    # unknown spec name fails loudly with the known list
    with pytest.raises(ValueError, match="known specs: paxos, raft"):
        get_spec("multipaxos")
    # spec dispatch off the config marker
    assert spec_of(CFG).name == "paxos"
    from raft_tla_tpu.config import ModelConfig
    assert spec_of(ModelConfig()).name == "raft"
    # a family without a declared guard algebra fails at Expander
    # construction, naming the spec
    from raft_tla_tpu.engine.expand import Expander, Family
    ir = get_spec("paxos")
    import raft_tla_tpu.spec.paxos.ir as pir

    def broken(lay):
        fams = pir.build_families(lay)
        f0 = fams[0]
        fams[0] = Family(f0.name, f0.fn, f0.params, f0.labeler,
                         guard=None)
        return fams

    orig = ir.build_families
    object.__setattr__(ir, "build_families", broken)
    try:
        with pytest.raises(KeyError, match="spec 'paxos'"):
            Expander(CFG)
    finally:
        object.__setattr__(ir, "build_families", orig)
    # per-spec fam-cap-density: unknown family names the active spec
    from raft_tla_tpu.engine.expand import parse_fam_density
    with pytest.raises(ValueError, match="spec 'paxos'"):
        parse_fam_density("Receive=4", get_spec("paxos"))
    assert parse_fam_density("Phase2b=2", get_spec("paxos")) == \
        {"Phase2b": 2}
    # raft default preserved for legacy callers
    assert parse_fam_density("Receive=4") == {"Receive": 4}
    # paxos declares no constraints / action constraints
    lay = PaxosLayout(CFG)
    preds = ir.make_predicates(lay)
    with pytest.raises(KeyError, match="spec 'paxos'"):
        preds.constraint_fn("BoundedLogSize")
    with pytest.raises(KeyError, match="spec 'paxos'"):
        preds.action_fn("anything")
    # config bounds validation
    with pytest.raises(ValueError, match="n_servers"):
        PaxosConfig(n_servers=9)


@pytest.mark.smoke
def test_checkpoint_refuses_spec_mismatch(tmp_path):
    """ckpt_read's spec gate: a checkpoint stamped for one spec
    refuses to resume under another, BEFORE the cfg-repr compare (and
    a meta without a spec key reads as raft — every pre-IR checkpoint
    is one)."""
    import json
    from raft_tla_tpu.engine.bfs import CheckpointError, ckpt_read
    path = str(tmp_path / "x.npz")
    meta = dict(spec="paxos", cfg="whatever", chunk=128)
    np.savez(path, meta=np.array(json.dumps(meta)))
    with pytest.raises(CheckpointError, match="spec 'paxos'"):
        ckpt_read(path, "whatever", 128, (), sharded=False,
                  spec_name="raft")
    # legacy meta (no spec key) == raft; passes the spec gate and
    # proceeds to the ordinary validation (here: missing base keys)
    meta2 = dict(cfg="whatever", chunk=128)
    np.savez(path, meta=np.array(json.dumps(meta2)))
    with pytest.raises(CheckpointError, match="older engine"):
        ckpt_read(path, "whatever", 128, (), sharded=False,
                  spec_name="raft")


@pytest.mark.smoke
def test_check_stats_spec_stamp_appends_after_pinned_keys():
    from raft_tla_tpu.engine.bfs import CheckResult
    from raft_tla_tpu.obs.metrics import check_stats
    r = CheckResult(distinct_states=10, generated_states=20, depth=3)
    base = check_stats(r.metrics.as_dict(), 1.5, 0, fp_bits=64)
    out = check_stats(r.metrics.as_dict(), 1.5, 0, fp_bits=64,
                      spec="paxos", ir_fp="abc123")
    assert list(out.keys()) == list(base.keys()) + \
        ["spec", "ir_fingerprint"]
    assert out["spec"] == "paxos" and out["ir_fingerprint"] == "abc123"


def test_ir_fingerprints_are_stable_and_distinct():
    raft_fp = get_spec("raft").fingerprint()
    paxos_fp = get_spec("paxos").fingerprint()
    assert raft_fp != paxos_fp
    assert raft_fp == get_spec("raft").fingerprint()
    assert len(raft_fp) == 12


# ---------------------------------------------------------------------------
# full-space / mesh / spill duplicates (slow tier)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_engine_vs_oracle_spill_full_space():
    from raft_tla_tpu.engine.spill import SpillEngine
    ro = explore(CFG)
    eng = SpillEngine(CFG, chunk=128, store_states=False, seg=1 << 12)
    r = eng.check()
    assert (r.distinct_states, r.generated_states, r.depth) == \
        (ro.distinct_states, ro.generated_states, ro.depth)
    assert r.level_sizes == ro.level_sizes


@pytest.mark.slow
def test_engine_vs_oracle_mesh_full_space():
    from raft_tla_tpu.parallel.mesh import ShardedEngine
    ro = explore(CFG)
    eng = ShardedEngine(CFG, chunk=64, store_states=False)
    r = eng.check()
    assert (r.distinct_states, r.generated_states, r.depth) == \
        (ro.distinct_states, ro.generated_states, ro.depth)


@pytest.mark.slow
def test_engine_vs_oracle_spill_mesh_full_space():
    from raft_tla_tpu.parallel.spill_mesh import SpilledShardedEngine
    ro = explore(CFG)
    eng = SpilledShardedEngine(CFG, chunk=64, store_states=False,
                               lcap=1 << 12)
    r = eng.check()
    assert (r.distinct_states, r.generated_states, r.depth) == \
        (ro.distinct_states, ro.generated_states, ro.depth)


@pytest.mark.slow
def test_guard_matmul_on_off_identical_paxos():
    from raft_tla_tpu.engine.bfs import Engine
    r_on = Engine(CFG, chunk=128, store_states=False,
                  guard_matmul=True).check()
    r_off = Engine(CFG, chunk=128, store_states=False,
                   guard_matmul=False).check()
    assert (r_on.distinct_states, r_on.generated_states, r_on.depth,
            r_on.level_sizes) == \
        (r_off.distinct_states, r_off.generated_states, r_off.depth,
         r_off.level_sizes)


@pytest.mark.slow
def test_engine_no_symmetry_and_fp128_full_space():
    from raft_tla_tpu.engine.bfs import Engine
    ro = explore(CFG_NS)
    r = Engine(CFG_NS, chunk=256, store_states=False).check()
    assert (r.distinct_states, r.generated_states, r.depth) == \
        (ro.distinct_states, ro.generated_states, ro.depth)
    r128 = Engine(CFG.with_(fp128=True), chunk=128,
                  store_states=False).check()
    assert r128.distinct_states == GOLD_SYM["distinct"]


@pytest.mark.slow
def test_multi_instance_engine_parity():
    from raft_tla_tpu.engine.bfs import Engine
    cfg = PaxosConfig(symmetry=False, n_ballots=1, n_values=2,
                      n_instances=2)
    ro = explore(cfg)
    r = Engine(cfg, chunk=256, store_states=False).check()
    assert (r.distinct_states, r.generated_states, r.depth) == \
        (ro.distinct_states, ro.generated_states, ro.depth)
