"""Pod-scale pjit engine (parallel/pjit_mesh) differentials.

The engine puts the WHOLE BFS state under NamedShardings on a device
mesh (here: conftest's 8 virtual CPU devices in-process, plus 2
controller processes x 2 virtual devices with gloo collectives as the
DCN stand-in) and must stay bit-identical to the classic engine — same
program, different partitioning — and therefore to the oracle: counts,
level sizes, global ids, archives, witness traces, checkpoints.

Budget: the classic reference and the pjit engine are module-shared
(one depth-capped run each — every engine instance costs ~6-10s of
XLA:CPU compile); the 2-controller rep is depth-capped; full-space
duplicates are slow-marked.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from raft_tla_tpu.config import Bounds, ModelConfig, NEXT_ASYNC
from raft_tla_tpu.engine.bfs import Engine
from raft_tla_tpu.parallel.pjit_mesh import (
    CARRY_RULES, PjitShardedEngine, match_partition_rules)

from conftest import cached_explore

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tools", "pjit_worker.py")

MICRO = ModelConfig(
    n_servers=2, init_servers=(0, 1), values=(1,),
    next_family=NEXT_ASYNC, symmetry=True, max_inflight_override=4,
    bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                       max_client_requests=1))
DEPTH = 10


@pytest.fixture(scope="module")
def ref():
    eng = Engine(MICRO, chunk=64, lcap=1 << 12, vcap=1 << 15,
                 store_states=True)
    res = eng.check(max_depth=DEPTH)
    return eng, res


@pytest.fixture(scope="module")
def pj():
    eng = PjitShardedEngine(MICRO, chunk=64, lcap=1 << 12,
                            vcap=1 << 15, store_states=True)
    res = eng.check(max_depth=DEPTH)
    return eng, res


# ---------------------------------------------------------------------------
# rule-matched PartitionSpec trees (the SNIPPETS.md exemplar shape)
# ---------------------------------------------------------------------------

def test_partition_rules_axis_kinds():
    from jax.sharding import PartitionSpec as P
    tree = {"vis": (np.zeros((8, 2)),), "claims": np.zeros((8,)),
            "front": {"x": np.zeros((3, 4, 16))},
            "lpar": np.zeros((16,)), "fmask": np.zeros((16,)),
            "n_front": np.zeros(())}
    specs = match_partition_rules(CARRY_RULES, tree)
    assert specs["vis"][0] == P("d", None)       # slot axis = dim 0
    assert specs["claims"] == P("d")
    assert specs["front"]["x"] == P(None, None, "d")   # batch-last
    assert specs["lpar"] == P("d")
    assert specs["n_front"] == P()               # scalars replicate


def test_pjit_mesh_spans_all_devices(pj):
    eng, _res = pj
    assert eng.D == 8                            # conftest's 8-dev CPU


def test_pjit_cli_flag_validation():
    from raft_tla_tpu.cli import main
    cfg_path = os.path.join(REPO, "configs", "tlc_membership",
                            "raft.cfg")
    # --pjit and --spill are different engines: usage error, exit 2
    assert main(["check", cfg_path, "--pjit", "--spill",
                 "--max-depth", "1"]) == 2


# ---------------------------------------------------------------------------
# parity vs the oracle and the classic engine (counts / level sizes /
# gids / archives / witness traces)
# ---------------------------------------------------------------------------

def test_pjit_parity_counts(pj):
    _eng, res = pj
    want = cached_explore(MICRO, max_depth=DEPTH)
    assert res.distinct_states == want.distinct_states
    assert res.depth == want.depth
    assert res.generated_states == want.generated_states
    assert list(res.level_sizes) == list(want.level_sizes)
    assert res.overflow_faults == 0


def test_pjit_gids_and_traces_match_classic(ref, pj):
    e1, r1 = ref
    e2, r2 = pj
    assert r2.distinct_states == r1.distinct_states
    # global ids are bit-identical (same program, different
    # partitioning): spot-check states and full witness chains across
    # the id range
    for gid in (0, 1, 7, 50, r1.distinct_states - 1):
        assert e1.get_state(gid) == e2.get_state(gid), gid
        t1 = [lbl for lbl, _ in e1.trace(gid)]
        t2 = [lbl for lbl, _ in e2.trace(gid)]
        assert t1 == t2, (gid, t1, t2)


def test_pjit_checkpoint_is_classic_format_and_archives_resume(
        pj, ref, tmp_path):
    """The pjit engine writes CLASSIC-format checkpoints (gathered to
    host), so (a) the classic engine resumes them directly, and (b)
    store_states x checkpoint works from day one: archives ride the
    checkpoint and a resumed run's gids/traces equal an uninterrupted
    run's."""
    eng, _res = pj
    e1, r1 = ref
    ck = str(tmp_path / "pjit.ckpt")
    part = eng.check(max_depth=6, checkpoint_path=ck)
    assert part.distinct_states < r1.distinct_states
    assert os.path.exists(ck)
    # (b) resume on the SAME pjit engine: archives restored, final
    # state bit-equal to the uninterrupted reference
    full = eng.check(max_depth=DEPTH, resume_from=ck)
    assert full.distinct_states == r1.distinct_states
    assert list(full.level_sizes) == list(r1.level_sizes)
    for gid in (3, 80, r1.distinct_states - 1):
        assert [l for l, _ in eng.trace(gid)] == \
            [l for l, _ in e1.trace(gid)], gid
    # (a) the classic engine resumes the pjit checkpoint directly
    got = e1.check(max_depth=DEPTH, resume_from=ck)
    assert got.distinct_states == r1.distinct_states
    assert list(got.level_sizes) == list(r1.level_sizes)
    # leave the module-shared engines on their canonical full-run
    # state for any later test (cheap: programs are already compiled)
    eng.check(max_depth=DEPTH)
    e1.check(max_depth=DEPTH)


def test_pjit_portable_resume_from_mesh_checkpoint(tmp_path, pj):
    """Round-12 portable contract at pod shape: a mesh
    (ShardedEngine) checkpoint — archives included — re-partitions
    onto the pjit mesh via resume_image and lands on the exact
    counts, archives and witness traces of an uninterrupted run (the
    acceptance rep).  Resumes onto the module-shared pjit engine: its
    compiled programs are capacity-compatible, so the rep costs no
    extra engine compile."""
    from raft_tla_tpu.parallel.mesh import ShardedEngine
    from raft_tla_tpu.resil.portable import load_portable_image
    eng, r_full = pj
    ck = str(tmp_path / "mesh.ckpt")
    mesh = ShardedEngine(MICRO, chunk=64, store_states=True,
                         lcap=1 << 12, vcap=1 << 15)
    mesh.check(max_depth=6, checkpoint_path=ck)
    img = load_portable_image(ck)
    res = eng.check(max_depth=DEPTH, resume_image=img)
    assert res.distinct_states == r_full.distinct_states
    assert res.depth == r_full.depth
    assert list(res.level_sizes) == list(r_full.level_sizes)
    assert res.generated_states == r_full.generated_states
    # archives ported whole: every state has its row (pre-checkpoint
    # rows keep the MESH engine's device-major gid order — portable
    # archives preserve the source engine's id assignment, so label-
    # for-label equality with the classic engine is only defined for
    # counts, not row order) and a witness chain replays root-first
    assert sum(len(p) for p in eng._parents) == res.distinct_states
    labels = [l for l, _ in eng.trace(res.distinct_states - 1)]
    assert labels[0] == "Init" and len(labels) == DEPTH + 1
    # NOTE: eng (the module-shared pjit engine) now holds mesh-ordered
    # archives; keep this test LAST among the fast per-gid users of
    # the fixture (e1 stays untouched)


# ---------------------------------------------------------------------------
# 2 controller processes x 2 virtual CPU devices, gloo (DCN stand-in)
# ---------------------------------------------------------------------------

def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_pair(opts):
    port = _free_port()
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    procs = [subprocess.Popen(
        [sys.executable, WORKER, str(pid), "2", str(port),
         json.dumps(opts)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=REPO) for pid in range(2)]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=600)
        assert p.returncode == 0, f"worker failed:\n{out}\n{err}"
        line = [ln for ln in out.splitlines()
                if ln.startswith("RESULT ")]
        assert line, f"no RESULT line:\n{out}\n{err}"
        outs.append(json.loads(line[-1][len("RESULT "):]))
    return outs


def test_pjit_two_controllers_depth_capped():
    """The fast multi-controller rep: the whole BFS state under
    NamedShardings spanning 2 processes (4 devices total), hash-
    ownership exchange as in-program collectives — both controllers
    land on the oracle's exact counts AND replay the same witness
    chain (archives are controller-replicated under the gather
    fns)."""
    want = cached_explore(MICRO, max_depth=8)
    outs = _run_pair({"max_depth": 8, "trace_gid": 50})
    for r in outs:
        assert r["n_devices"] == 4          # 2 procs x 2 devices
        assert r["distinct"] == want.distinct_states
        assert r["depth"] == want.depth
        assert r["generated"] == want.generated_states
        assert r["level_sizes"] == list(want.level_sizes)
        assert r["violations"] == 0
    assert outs[0]["trace"] == outs[1]["trace"]
    assert outs[0]["trace"][0] == "Init"
    assert outs[0] == dict(outs[1], pid=0)


@pytest.mark.slow
def test_pjit_two_controllers_full_space():
    want = cached_explore(MICRO)
    outs = _run_pair({"trace_gid": 5000})
    for r in outs:
        assert r["distinct"] == want.distinct_states
        assert r["depth"] == want.depth
        assert r["generated"] == want.generated_states
        assert r["level_sizes"] == list(want.level_sizes)
    assert outs[0]["trace"] == outs[1]["trace"]


@pytest.mark.slow
def test_pjit_two_controllers_portable_resume(tmp_path):
    """Multi-controller resume through the portable contract: a
    2-controller pjit run checkpoints (classic format, proc-0
    publish), and a fresh 2-controller run resumes it via
    resume_portable, finishing on the oracle's counts."""
    want = cached_explore(MICRO)
    ck = str(tmp_path / "pjit2.ckpt")
    part = _run_pair({"max_depth": 6, "checkpoint": ck})
    assert all(r["distinct"] < want.distinct_states for r in part)
    assert os.path.exists(ck)
    outs = _run_pair({"resume_portable": ck})
    for r in outs:
        assert r["distinct"] == want.distinct_states
        assert r["depth"] == want.depth


@pytest.mark.slow
def test_pjit_full_space_parity():
    want = cached_explore(MICRO)
    eng = PjitShardedEngine(MICRO, chunk=64, lcap=1 << 13,
                            vcap=1 << 17, store_states=False)
    res = eng.check()
    assert res.distinct_states == want.distinct_states
    assert res.depth == want.depth
    assert res.generated_states == want.generated_states
    assert list(res.level_sizes) == list(want.level_sizes)
