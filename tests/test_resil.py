"""Fault-tolerance layer (resil/): chaos-driven recovery
differentials — for each injection site, a faulted-then-recovered run
must equal the unfaulted run bit-for-bit (counts, level sizes, gids,
witness traces) — plus the checkpoint-chain integrity contract,
shape-portable resume, and preemptible batch waves.

One fast representative per engine family runs in tier-1; full-space
and cross-shape duplicates are slow-marked (tier-1 budget, ROADMAP
standing constraint).
"""

import importlib.util
import json
import os
import warnings

import numpy as np
import pytest

from raft_tla_tpu.config import Bounds, ModelConfig, NEXT_ASYNC
from raft_tla_tpu.engine.bfs import CheckpointError, Engine
from raft_tla_tpu.resil import chaos
from raft_tla_tpu.resil.chaos import (ChaosSchedule, ChaosSpecError,
                                      InjectedFault)
from raft_tla_tpu.resil.ckpt_chain import (ChainWarning,
                                           chain_candidates,
                                           latest_valid, verify)
from raft_tla_tpu.resil.portable import load_portable_image
from raft_tla_tpu.resil.supervisor import (RetryExhausted,
                                           backoff_delay,
                                           supervised_check)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MICRO = ModelConfig(
    n_servers=2, init_servers=(0, 1), values=(1,),
    next_family=NEXT_ASYNC, symmetry=True, max_inflight_override=4,
    bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                       max_client_requests=1))


def _same(res, ref):
    assert (res.distinct_states, res.generated_states, res.depth) == \
        (ref.distinct_states, ref.generated_states, ref.depth)
    assert res.level_sizes == ref.level_sizes
    assert [(v.invariant, v.state_id) for v in res.violations] == \
        [(v.invariant, v.state_id) for v in ref.violations]


def _labels(trace):
    return [label for label, _sv in trace]


@pytest.fixture(autouse=True)
def _chaos_clean():
    """Every test leaves the process-global schedule uninstalled."""
    yield
    chaos.uninstall()


@pytest.fixture(scope="module")
def classic():
    # burst_levels=2 so checkpoint chains actually build up (one
    # 16-level burst would cover the whole micro prefix in one save)
    return Engine(MICRO, chunk=64, burst_levels=2)


@pytest.fixture(scope="module")
def classic_ref(classic):
    """ONE unfaulted depth-8 reference run (counts + witness trace)
    shared by every classic-engine differential below — the engine's
    archives are reset by later runs, so the trace is captured here."""
    ref = classic.check(max_depth=8)
    return ref, _labels(classic.trace(ref.distinct_states - 1))


@pytest.fixture(scope="module")
def sm2():
    import jax

    from raft_tla_tpu.parallel.spill_mesh import SpilledShardedEngine
    return SpilledShardedEngine(MICRO, devices=jax.devices()[:2],
                                chunk=16, store_states=True,
                                lcap=1 << 10, burst_levels=2)


@pytest.fixture(scope="module")
def mesh2():
    import jax

    from raft_tla_tpu.parallel.mesh import ShardedEngine
    return ShardedEngine(MICRO, devices=jax.devices()[:2], chunk=16,
                         store_states=True, burst_levels=2)


# ---------------------------------------------------------------------------
# chaos schedule: parsing, determinism, sites
# ---------------------------------------------------------------------------

@pytest.mark.smoke
def test_chaos_spec_parse_and_determinism():
    s = ChaosSchedule("seed=3;dispatch:at=2,4;archive:every=3;"
                      "host_table:p=0.5")
    assert [s.fire("dispatch") for _ in range(5)] == \
        [False, True, False, True, False]
    assert [s.fire("archive") for _ in range(6)] == \
        [False, False, True, False, False, True]
    # p= clauses are a pure function of (seed, site, hit): replays
    # are identical
    a = [ChaosSchedule("seed=7;host_table:p=0.5").fire("host_table")
         for _ in range(8)]
    b = [ChaosSchedule("seed=7;host_table:p=0.5").fire("host_table")
         for _ in range(8)]
    del a, b  # schedules above are single-hit; compare multi-hit:
    s1 = ChaosSchedule("seed=7;host_table:p=0.5")
    s2 = ChaosSchedule("seed=7;host_table:p=0.5")
    assert [s1.fire("host_table") for _ in range(32)] == \
        [s2.fire("host_table") for _ in range(32)]
    # unknown sites/rules/values error by name
    for bad, msg in [("nope:at=1", "unknown site"),
                     ("dispatch:often=2", "unknown rule"),
                     ("dispatch:at=0", "bad at= value"),
                     ("dispatch", "not 'site:rule'"),
                     ("seed=x;dispatch:at=1", "bad seed"),
                     ("seed=4", "declares no sites")]:
        with pytest.raises(ChaosSpecError, match=msg):
            ChaosSchedule(bad)
    # point() raises InjectedFault with site + hit attribution
    s3 = ChaosSchedule("dispatch:at=2")
    s3.point("dispatch")
    with pytest.raises(InjectedFault) as ei:
        s3.point("dispatch")
    assert ei.value.site == "dispatch" and ei.value.hit == 2
    assert s3.fired == [("dispatch", 2)]
    # uninstalled global points are no-ops
    chaos.uninstall()
    chaos.chaos_point("dispatch")
    assert chaos.chaos_fire("ckpt_torn") is False


@pytest.mark.smoke
def test_backoff_delay_bounded_and_deterministic():
    d = [backoff_delay(k, 1.0, 8.0) for k in range(6)]
    assert d == [backoff_delay(k, 1.0, 8.0) for k in range(6)]
    base = [min(1.0 * 2.0 ** k, 8.0) for k in range(6)]
    for got, b in zip(d, base):
        assert b <= got <= b * 1.25


# ---------------------------------------------------------------------------
# checkpoint chain: rotation, integrity sidecars, torn-head fallback
# ---------------------------------------------------------------------------

def test_ckpt_chain_rotation_and_torn_head_fallback(classic,
                                                    classic_ref,
                                                    tmp_path):
    ref, _ref_trace = classic_ref
    ck = str(tmp_path / "run.ckpt")
    classic.ckpt_keep = 3
    classic.check(max_depth=6, checkpoint_path=ck, checkpoint_every=1)
    names = sorted(os.listdir(tmp_path))
    assert "run.ckpt" in names and "run.ckpt.1" in names
    assert "run.ckpt.sum" in names and "run.ckpt.1.sum" in names
    assert verify(ck) == (True, "ok")
    assert latest_valid(ck) == ck
    assert chain_candidates(ck)[0] == ck
    # tear the head: resume falls back to .1 with a NAMED warning and
    # still lands bit-exact
    with open(ck, "r+b") as fh:
        fh.truncate(os.path.getsize(ck) // 2)
    assert verify(ck)[0] is False
    assert latest_valid(ck) == ck + ".1"
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        resumed = classic.check(max_depth=8, resume_from=ck)
    assert any(issubclass(x.category, ChainWarning) and
               "integrity" in str(x.message) for x in w)
    _same(resumed, ref)
    assert sum(len(p) for p in classic._parents) == ref.distinct_states
    # corrupt BYTES (same length) are caught by the sha256, not size
    with open(ck + ".1", "r+b") as fh:
        size = os.path.getsize(ck + ".1")
        fh.seek(size // 2)
        fh.write(b"\xff" * 32)
    assert verify(ck + ".1") == (False, "sha256 mismatch "
                                 "(corrupt bytes)")


def test_ckpt_read_truncated_yields_clear_error(classic, tmp_path):
    """Satellite: payload integrity validates BEFORE the cfg-repr
    compare — a truncated file (with or without its sidecar) is a
    clear CheckpointError, never a numpy/zipfile traceback."""
    ck = str(tmp_path / "solo.ckpt")
    classic.ckpt_keep = 1            # no chain: nothing to fall back to
    classic.check(max_depth=4, checkpoint_path=ck)
    with open(ck, "r+b") as fh:
        fh.truncate(os.path.getsize(ck) // 3)
    with pytest.raises(CheckpointError, match="no valid checkpoint"):
        classic.check(resume_from=ck)
    # legacy file (no sidecar): the structural load catches the torn
    # zip container with the same named error
    os.remove(ck + ".sum")
    with pytest.raises(CheckpointError, match="no valid checkpoint"):
        classic.check(resume_from=ck)
    with pytest.raises(CheckpointError, match="no such checkpoint"):
        classic.check(resume_from=str(tmp_path / "missing.ckpt"))
    classic.ckpt_keep = 2


# ---------------------------------------------------------------------------
# supervised chaos differentials: one fast rep per engine family
# ---------------------------------------------------------------------------

def test_supervised_chaos_classic_every_boundary(classic,
                                                 classic_ref,
                                                 tmp_path):
    """The acceptance rep: dispatch faults at every level boundary
    (every 2nd loop hit — the alternating hits are the post-resume
    re-entries) plus one torn and one corrupt checkpoint head, all
    recovered by the supervised runner, bit-exact vs unfaulted."""
    ck = str(tmp_path / "sup.ckpt")
    ref, ref_trace = classic_ref
    sched = chaos.install(
        "dispatch:every=2;ckpt_torn:at=2;ckpt_corrupt:at=3")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", ChainWarning)
        res, eng, attempts = supervised_check(
            lambda: classic, retries=50, backoff=0.01,
            checkpoint_path=ck, checkpoint_every=1, max_depth=8,
            sleep=lambda s: None, reinit=False)
    assert attempts > 1
    assert any(site == "dispatch" for site, _ in sched.fired)
    assert any(site == "ckpt_torn" for site, _ in sched.fired)
    _same(res, ref)
    assert _labels(eng.trace(res.distinct_states - 1)) == ref_trace
    chaos.uninstall()
    # exhaustion is a named error, not an infinite loop (no
    # checkpoint: every-dispatch faults allow no progress at all)
    chaos.install("dispatch:every=1")
    with pytest.raises(RetryExhausted, match="after 3 attempt"):
        supervised_check(lambda: classic, retries=2, backoff=0.01,
                         max_depth=8, sleep=lambda s: None,
                         reinit=False)


def test_supervised_chaos_spill_dispatch_and_archive(tmp_path):
    """Spill-family rep, with the trace archives on DISK: dispatch
    faults AND an archive-write fault both recover via resume
    (reattach + truncate), bit-exact including the memmap'd trace."""
    from raft_tla_tpu.engine.spill import SpillEngine
    arch = str(tmp_path / "arch")
    ck = str(tmp_path / "spill.ckpt")
    eng = SpillEngine(MICRO, chunk=64, seg=1 << 12, store_states=True,
                      archive_dir=arch, burst_levels=2)
    ref = eng.check(max_depth=7)
    ref_trace = _labels(eng.trace(ref.distinct_states - 1))
    sched = chaos.install("dispatch:at=2;archive:at=5")
    res, eng2, attempts = supervised_check(
        lambda: eng, retries=4, backoff=0.01, checkpoint_path=ck,
        checkpoint_every=1, max_depth=7, sleep=lambda s: None,
        reinit=False)
    assert attempts > 1
    assert {site for site, _ in sched.fired} == {"dispatch",
                                                 "archive"}
    _same(res, ref)
    assert _labels(eng2.trace(res.distinct_states - 1)) == ref_trace


def test_supervised_chaos_sharded_mesh(mesh2, tmp_path):
    eng = mesh2
    ck = str(tmp_path / "mesh.ckpt")
    ref = eng.check(max_depth=6)
    ref_trace = _labels(eng.trace(ref.distinct_states - 1))
    chaos.install("dispatch:at=2")
    res, eng2, attempts = supervised_check(
        lambda: eng, retries=1, backoff=0.01, checkpoint_path=ck,
        checkpoint_every=1, max_depth=6, sleep=lambda s: None,
        reinit=False)
    assert attempts == 2
    _same(res, ref)
    assert _labels(eng2.trace(res.distinct_states - 1)) == ref_trace


def test_supervised_chaos_spill_mesh_and_native_resume(sm2, tmp_path):
    """SpilledShardedEngine rep (ROADMAP item-5 closure): the engine
    now checkpoints — supervised chaos recovery is bit-exact, and a
    plain partial+resume lands on identical counts, gids and witness
    traces (the shared recovery contract)."""
    eng = sm2
    ck = str(tmp_path / "sm.ckpt")
    ref = eng.check(max_depth=6)
    gid = ref.distinct_states - 1
    ref_trace = _labels(eng.trace(gid))
    chaos.install("dispatch:at=2")
    res, eng2, attempts = supervised_check(
        lambda: eng, retries=1, backoff=0.01, checkpoint_path=ck,
        checkpoint_every=1, max_depth=6, sleep=lambda s: None,
        reinit=False)
    assert attempts == 2
    _same(res, ref)
    assert _labels(eng2.trace(gid)) == ref_trace
    chaos.uninstall()
    # plain interrupt/resume, no chaos: counts + archives + traces
    ck2 = str(tmp_path / "sm2.ckpt")
    eng.check(max_depth=4, checkpoint_path=ck2, checkpoint_every=1)
    resumed = eng.check(max_depth=6, resume_from=ck2)
    _same(resumed, ref)
    assert sum(len(p) for p in eng._parents) == ref.distinct_states
    assert _labels(eng.trace(gid)) == ref_trace
    # format pin: the file is the pooled portable form with the
    # spill+sharded gates set (the wrong-D refusal itself is pinned
    # by the slow cross-shape duplicate)
    meta = json.loads(str(np.load(ck2)["meta"]))
    assert meta["D"] == 2 and meta["spill"] and meta["sharded"]


# ---------------------------------------------------------------------------
# shape-portable resume (resil/portable)
# ---------------------------------------------------------------------------

def test_portable_resume_classic_and_mesh_cross_family(classic,
                                                       classic_ref,
                                                       mesh2, sm2,
                                                       tmp_path):
    """The elastic-resume contract, fast reps: a classic-Engine
    checkpoint and a 2-device mesh checkpoint both resume on the
    spill-composed mesh by re-partitioning the visited image and
    frontier on load — final counts/level sizes/depth equal the
    uninterrupted run (the spill-engine and cross-device-count
    targets run in the slow duplicate)."""
    ref, _ref_trace = classic_ref
    ck = str(tmp_path / "classic.ckpt")
    classic.check(max_depth=5, checkpoint_path=ck)
    img = load_portable_image(ck)
    assert img.source_format == "engine" and img.depth == 5
    res = sm2.check(max_depth=8, resume_image=img)
    _same(res, ref)
    assert sum(len(p) for p in sm2._parents) == ref.distinct_states
    # mesh source: counts are mesh-size invariant, so the cross-family
    # continuation must land on the same totals
    ckm = str(tmp_path / "mesh.ckpt")
    mesh2.check(max_depth=5, checkpoint_path=ckm)
    img_m = load_portable_image(ckm)
    assert img_m.source_format == "sharded"
    res_m = sm2.check(max_depth=8, resume_image=img_m)
    assert (res_m.distinct_states, res_m.depth) == \
        (ref.distinct_states, ref.depth)
    assert res_m.level_sizes == ref.level_sizes
    # target gates: wrong config refuses by name
    img_bad = load_portable_image(ck)
    img_bad.cfg_repr = "nope"
    with pytest.raises(CheckpointError, match="different model "
                                              "config"):
        sm2.check(resume_image=img_bad)


@pytest.mark.slow
def test_portable_resume_mesh_to_other_mesh_sizes(tmp_path):
    """Mesh D=2 checkpoint re-partitions onto D=4 meshes (classic and
    spill-composed) AND onto the single-chip spill engine: the
    different-device-count / different-engine elastic resume the
    ROADMAP item-2 prerequisite names."""
    import jax

    from raft_tla_tpu.engine.spill import SpillEngine
    from raft_tla_tpu.parallel.mesh import ShardedEngine
    from raft_tla_tpu.parallel.spill_mesh import SpilledShardedEngine
    devs = jax.devices()
    e2 = ShardedEngine(MICRO, devices=devs[:2], chunk=16,
                       store_states=True)
    full = e2.check(max_depth=12)
    ck = str(tmp_path / "mesh2.ckpt")
    e2.check(max_depth=6, checkpoint_path=ck)
    img = load_portable_image(ck)
    e4 = ShardedEngine(MICRO, devices=devs[:4], chunk=16,
                       store_states=True)
    res4 = e4.check(max_depth=12, resume_image=img)
    _same(res4, full)
    sm4 = SpilledShardedEngine(MICRO, devices=devs[:4], chunk=16,
                               store_states=True, lcap=1 << 10)
    res_sm = sm4.check(max_depth=12, resume_image=img)
    assert (res_sm.distinct_states, res_sm.depth,
            res_sm.level_sizes) == (full.distinct_states, full.depth,
                                    full.level_sizes)
    # exact same-shape resume refuses a wrong-D native load with a
    # pointer to the portable path
    sp = SpillEngine(MICRO, chunk=64, seg=1 << 12, store_states=True)
    res_sp = sp.check(max_depth=12, resume_image=img)
    _same(res_sp, full)
    sm2 = SpilledShardedEngine(MICRO, devices=devs[:2], chunk=16,
                               store_states=True, lcap=1 << 10)
    ck_sm = str(tmp_path / "sm2.ckpt")
    sm2.check(max_depth=6, checkpoint_path=ck_sm)
    with pytest.raises(CheckpointError, match="portable"):
        sm4.check(resume_from=ck_sm)


@pytest.mark.slow
def test_supervised_chaos_host_table_partition_loss(tmp_path):
    """host_table site: a lost host partition mid-run recovers via
    checkpoint resume (exact sparse partition images), bit-exact."""
    from raft_tla_tpu.engine.spill import SpillEngine
    kw = dict(chunk=64, seg=1 << 12, store_states=False,
              host_table=True, partitions=2, part_cap=1 << 8,
              dev_keys=64)
    eng = SpillEngine(MICRO, **kw)
    ref = eng.check(max_depth=10)
    ck = str(tmp_path / "ht.ckpt")
    chaos.install("host_table:at=4")
    res, _eng, attempts = supervised_check(
        lambda: eng, retries=2, backoff=0.01, checkpoint_path=ck,
        checkpoint_every=1, max_depth=10, sleep=lambda s: None, reinit=False)
    assert attempts > 1
    _same(res, ref)


@pytest.mark.slow
def test_supervised_chaos_classic_full_space(tmp_path):
    """Full-space duplicate of the acceptance rep: the whole micro
    model to exhaustion under every-boundary dispatch faults."""
    eng = Engine(MICRO, chunk=64, burst_levels=4)
    ref = eng.check()
    ck = str(tmp_path / "full.ckpt")
    chaos.install("dispatch:every=2;ckpt_torn:at=3")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", ChainWarning)
        res, eng2, attempts = supervised_check(
            lambda: eng, retries=64, backoff=0.01,
            checkpoint_path=ck, checkpoint_every=1,
            sleep=lambda s: None, reinit=False)
    assert attempts > 2
    _same(res, ref)
    gid = ref.distinct_states - 1
    assert _labels(eng2.trace(gid)) == _labels(eng.trace(gid))


# ---------------------------------------------------------------------------
# preemptible batch waves (serve/)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_wave_state_kill_resume_and_preemption_bit_exact(classic,
                                                         classic_ref,
                                                         tmp_path):
    """The batch acceptance rep: a run killed at a wave boundary (the
    deterministic SIGKILL stand-in, firing AFTER the wave-state
    persist) resumes to bit-exact per-job results — finished jobs from
    the cache, stragglers mid-BFS from their carry — and the long job
    parks (yields its lane) when another job waits on the single
    lane.  References are solo-engine runs: batched ≡ solo is the
    PR-10 pinned contract, so the classic engine is the exact
    per-job answer."""
    from raft_tla_tpu.serve import Job, ResultCache, run_jobs
    ws = str(tmp_path / "waves")
    cache = ResultCache(str(tmp_path / "cache"))
    bo = {"burst_levels": 2}
    ref8, _tr = classic_ref
    ref3 = classic.check(max_depth=3)

    def mk():
        return [Job(MICRO, max_depth=8, label="long"),
                Job(MICRO, max_depth=3, label="hi", priority=5)]
    # killed mid-run: single lane + 1-step yield budget; hi (priority
    # 5) takes boundaries 1-2, the kill fires at boundary 3 — the
    # long job's first step, right after its carry persisted
    chaos.install("wave_kill:at=3")
    with pytest.raises(InjectedFault):
        run_jobs(mk(), cache=cache, wave_state=ws, max_wave=1,
                 wave_yield=1, bucket_overrides=bo)
    chaos.uninstall()
    assert any(nm.endswith(".wave.npz") for nm in os.listdir(ws))
    rep = run_jobs(mk(), cache=cache, wave_state=ws, max_wave=1,
                   wave_yield=1, bucket_overrides=bo)
    assert rep.meta["resumed_jobs"] >= 1
    assert rep.meta["fallback_jobs"] == 0
    long_o, hi_o = rep.outcomes
    assert long_o.report["status_reason"] == "resumed from wave state"
    _same(long_o.res, ref8)
    _same(hi_o.res, ref3)
    # wave state retired at completion; a re-run is all cache hits
    assert not [nm for nm in os.listdir(ws)
                if nm.endswith(".wave.npz")]
    rep2 = run_jobs(mk(), cache=cache, wave_state=ws,
                    bucket_overrides=bo)
    assert all(o.status == "cache_hit" for o in rep2.outcomes)
    with pytest.raises(ValueError, match="wave_yield"):
        run_jobs(mk(), wave_yield=0)


@pytest.mark.slow
def test_wave_kill_park_priority_full(tmp_path):
    """Full-surface duplicate: 3 jobs, parking + priority scheduling +
    witness-trace parity against a clean batched reference."""
    from raft_tla_tpu.serve import Job, ResultCache, run_jobs
    ws = str(tmp_path / "waves")
    cache = ResultCache(str(tmp_path / "cache"))
    bo = {"burst_levels": 2}

    def mk():
        return [Job(MICRO, max_depth=12, label="long"),
                Job(MICRO, max_depth=3, label="hi", priority=5),
                Job(MICRO, max_depth=4, label="mid")]
    ref = run_jobs(mk(), bucket_overrides=bo)
    assert ref.meta["fallback_jobs"] == 0
    chaos.install("wave_kill:at=3")
    with pytest.raises(InjectedFault):
        run_jobs(mk(), cache=cache, wave_state=ws, max_wave=1,
                 wave_yield=1, bucket_overrides=bo)
    chaos.uninstall()
    rep = run_jobs(mk(), cache=cache, wave_state=ws, max_wave=1,
                   wave_yield=1, bucket_overrides=bo)
    assert rep.meta["resumed_jobs"] >= 1
    assert rep.meta["parked_waves"] >= 1
    for got, want in zip(rep.outcomes, ref.outcomes):
        _same(got.res, want.res)
        gid = want.res.distinct_states - 1
        assert _labels(got.trace(gid)) == _labels(want.trace(gid))


def test_wave_state_store_corruption_is_a_miss(tmp_path):
    from raft_tla_tpu.serve.wavestate import WaveStateStore
    ws = WaveStateStore(str(tmp_path))
    ws.save("k1", {"fm": np.ones((4,), bool)},
            {"cache_key": "k1", "depth": 3})
    arrays, book = ws.load("k1")
    assert book["depth"] == 3 and arrays["fm"].all()
    # torn file -> miss with a warning, never an error
    path = ws._file("k1")
    with open(path, "r+b") as fh:
        fh.truncate(os.path.getsize(path) // 2)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert ws.load("k1") is None
    assert any("integrity" in str(x.message) for x in w)
    # foreign key -> miss
    ws.save("k2", {}, {"cache_key": "OTHER"})
    assert ws.load("k2") is None
    ws.drop("k1")
    assert ws.load("k1") is None


# ---------------------------------------------------------------------------
# obs / watch: retry stamps
# ---------------------------------------------------------------------------

def test_obs_retry_ledger_heartbeat_and_watch(tmp_path):
    from raft_tla_tpu.obs import Obs
    from raft_tla_tpu.obs.heartbeat import Heartbeat
    from raft_tla_tpu.obs.ledger import RunLedger
    spec = importlib.util.spec_from_file_location(
        "watch", os.path.join(_REPO, "tools", "watch.py"))
    watch = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(watch)
    ledger_path = str(tmp_path / "ledger.jsonl")
    hb_path = str(tmp_path / "hb.json")
    obs = Obs(ledger=RunLedger(ledger_path),
              heartbeat=Heartbeat(hb_path),
              meta={"spec": "raft"})
    obs.start()
    obs.dispatch(kind="level", depth=3,
                 metrics={"distinct_states": 42})
    obs.retry(attempt=2, max_attempts=4, wait_s=1.5,
              error=RuntimeError("tunnel dropped"))
    recs = [json.loads(ln) for ln in open(ledger_path)]
    rr = next(r for r in recs if r["kind"] == "retry")
    assert rr["attempt"] == 2 and rr["max_attempts"] == 4
    assert "tunnel dropped" in rr["error"] and rr["spec"] == "raft"
    hb = json.load(open(hb_path))
    assert hb["status"] == "backoff" and \
        hb["retry"]["attempt"] == 2
    # watch renders RETRYING (healthy, not stalled) even when the
    # last dispatch is old
    line, code = watch.status_line(hb_path, ledger_path, stale_s=0.0)
    assert code == 0 and "RETRYING attempt 2/4" in line
    obs.finish(depth=3, states=42)


def test_cli_chaos_and_retry_flag_validation():
    from raft_tla_tpu.cli import main
    # malformed chaos spec is a usage error (exit 2), not a traceback
    rc = main(["check", os.path.join(_REPO, "configs",
                                     "tlc_membership", "raft.cfg"),
               "--chaos", "bogus_site:at=1", "--max-depth", "1"])
    assert rc == 2
    rc = main(["check", os.path.join(_REPO, "configs",
                                     "tlc_membership", "raft.cfg"),
               "--retries", "-1", "--max-depth", "1"])
    assert rc == 2
    rc = main(["check", os.path.join(_REPO, "configs",
                                     "tlc_membership", "raft.cfg"),
               "--resume-portable", "--max-depth", "1"])
    assert rc == 2


@pytest.mark.slow
def test_wave_kill_with_retries_self_heals(tmp_path):
    """--retries on batch absorbs the kill: one invocation, the retry
    re-runs the job list and the wave state makes it incremental."""
    import subprocess
    import sys
    cfg = os.path.join(_REPO, "configs", "tlc_membership", "raft.cfg")
    job = json.dumps({
        "spec": "raft", "config": cfg, "label": "j",
        "max_depth": 12,
        "overrides": {"servers": 2, "next": "NextAsync",
                      "bounds": {"max_log_length": 1,
                                 "max_timeouts": 1,
                                 "max_client_requests": 1}}})
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, "-m", "raft_tla_tpu", "batch", "--job", job,
         "--cache-dir", str(tmp_path / "cache"),
         "--wave-state", str(tmp_path / "waves"),
         "--chaos", "wave_kill:at=1", "--retries", "1",
         "--backoff", "0.01"],
        capture_output=True, text=True, cwd=_REPO, env=env,
        timeout=600)
    assert p.returncode == 0, (p.stdout, p.stderr)
    rows = [json.loads(ln) for ln in p.stdout.splitlines() if ln]
    assert rows[0]["resumed_jobs"] == 1
    assert rows[1]["status"] == "done"
