"""Batched multi-tenant serving layer (serve/): batched ≡ sequential
bit-exactness, cache hit/miss paths, fallbacks, job parsing, and the
multi-job observability surface.

One fast representative of each contract runs in tier-1; the
full-space duplicates are slow-marked (tier-1 budget, ROADMAP
standing constraint).
"""

import importlib.util
import json
import os

import pytest

from raft_tla_tpu.config import Bounds, ModelConfig, NEXT_ASYNC
from raft_tla_tpu.engine.bfs import Engine
from raft_tla_tpu.serve import (Job, ResultCache, job_from_dict,
                                load_jobs, run_jobs)
from raft_tla_tpu.spec.paxos.config import PaxosConfig

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MICRO = ModelConfig(
    n_servers=2, init_servers=(0, 1), values=(1,),
    next_family=NEXT_ASYNC, symmetry=True, max_inflight_override=4,
    bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                       max_client_requests=1))
PAX = PaxosConfig(n_servers=2, n_ballots=2, n_values=1)


def _same(res, ref):
    assert (res.distinct_states, res.generated_states, res.depth) == \
        (ref.distinct_states, ref.generated_states, ref.depth)
    assert res.level_sizes == ref.level_sizes
    assert [(v.invariant, v.state_id) for v in res.violations] == \
        [(v.invariant, v.state_id) for v in ref.violations]


def _trace_key(trace):
    return [(label, repr(sv)) for label, sv in trace]


def test_batched_mixed_specs_bit_exact():
    """The tier-1 representative: a mixed raft+paxos job list through
    the batched path lands bit-exact against per-job sequential
    engines — counts, level sizes, violation ids AND witness traces —
    while compiling exactly one engine per (spec, bucket)."""
    jobs = [Job(MICRO, max_depth=4, label="r4"),
            Job(MICRO, max_depth=6, label="r6"),
            Job(PAX, max_depth=3, label="p3"),
            Job(PAX, label="pfull")]
    rep = run_jobs(jobs)
    assert rep.meta["buckets"] == 2
    assert rep.meta["engines_compiled"] == 2
    assert rep.meta["fallback_jobs"] == 0
    assert all(o.status == "done" for o in rep.outcomes)
    re_r, re_p = Engine(MICRO), Engine(PAX)
    _same(rep.outcomes[0].res, re_r.check(max_depth=4))
    _same(rep.outcomes[2].res, re_p.check(max_depth=3))
    ref6 = re_r.check(max_depth=6)
    _same(rep.outcomes[1].res, ref6)
    # witness-trace parity: the deepest raft state replays identically
    # from the per-job batched archives and the solo engine's
    last = ref6.distinct_states - 1
    assert _trace_key(rep.outcomes[1].trace(last)) == \
        _trace_key(re_r.trace(last))
    refp = re_p.check()
    _same(rep.outcomes[3].res, refp)
    lastp = refp.distinct_states - 1
    assert _trace_key(rep.outcomes[3].trace(lastp)) == \
        _trace_key(re_p.trace(lastp))
    # the stats stamps every job row carries
    row = rep.outcomes[3].report
    assert row["spec"] == "paxos" and row["status"] == "done"
    assert row["cache_key"].startswith("paxos-")


def test_batched_violation_states_and_witness_parity():
    """A job that FINDS a violation (ValueChosen as invariant, the
    trace-command idiom): the batched run reports the same violating
    state ids and replays the same witness trace as the sequential
    engine, and stop_on_violation gates identically."""
    vcfg = PAX.with_(invariants=("ValueChosen",))
    rep = run_jobs([Job(vcfg, label="vc")])
    o = rep.outcomes[0]
    assert o.status == "done"
    ref_eng = Engine(vcfg)
    ref = ref_eng.check(stop_on_violation=True)
    _same(o.res, ref)
    assert o.res.violations, "expected a ValueChosen witness"
    sid = o.res.violations[0].state_id
    assert _trace_key(o.trace(sid)) == _trace_key(ref_eng.trace(sid))
    det = o.report["violations_detail"]
    assert det and det[0]["invariant"] == "ValueChosen"
    assert det[0]["trace"] == [lbl for lbl, _ in ref_eng.trace(sid)]


def test_result_cache_hit_and_fingerprint_misses(tmp_path):
    """Cache round-trip: an identical job is served with ZERO device
    work; any changed fingerprint component (engine options, config)
    misses."""
    cache = ResultCache(str(tmp_path))
    rep1 = run_jobs([Job(PAX, max_depth=2, label="a")], cache=cache)
    assert rep1.meta["cache_hits"] == 0
    assert rep1.meta["batch_dispatches"] >= 1
    # identical (cfg, options) under a different label: a hit, no
    # engine, no dispatch
    rep2 = run_jobs([Job(PAX, max_depth=2, label="b")], cache=cache)
    assert rep2.meta["cache_hits"] == 1
    assert rep2.meta["batch_dispatches"] == 0
    assert rep2.meta["engines_compiled"] == 0
    o = rep2.outcomes[0]
    assert o.status == "cache_hit" and o.cache_hit
    assert o.report["distinct_states"] == \
        rep1.outcomes[0].report["distinct_states"]
    assert o.report["level_sizes"] == \
        rep1.outcomes[0].report["level_sizes"]
    # options-fingerprint misses: depth gate, stop-on-violation,
    # store toggle all key separately
    assert cache.get(Job(PAX, max_depth=3).cache_key()) is None
    assert cache.get(Job(PAX, max_depth=2,
                         stop_on_violation=False).cache_key()) is None
    assert cache.get(Job(PAX, max_depth=2,
                         store_states=False).cache_key()) is None
    # config-fingerprint miss
    assert cache.get(Job(PAX.with_(n_ballots=1),
                         max_depth=2).cache_key()) is None
    # the payload survives a fresh cache handle (disk round-trip)
    fresh = ResultCache(str(tmp_path))
    key = Job(PAX, max_depth=2).cache_key()
    assert fresh.get(key)["distinct_states"] == \
        rep1.outcomes[0].report["distinct_states"]


def test_ring_overflow_falls_back_sequential_exact():
    """A job whose frontier outgrows the per-job ring bails out of the
    batched program and re-runs solo — results stay exact and the
    fallback is reported honestly."""
    rep = run_jobs([Job(MICRO, label="big")],
                   bucket_overrides=dict(chunk=16, vcap=1 << 10))
    assert rep.meta["fallback_jobs"] == 1
    o = rep.outcomes[0]
    assert o.status == "fallback"
    assert "re-run sequentially" in o.report["status_reason"]
    _same(o.res, Engine(MICRO).check())


def test_job_from_dict_format_and_errors(tmp_path):
    cfg_path = os.path.join(_REPO, "configs", "tlc_membership",
                            "raft.cfg")
    job = job_from_dict({
        "spec": "raft", "config": cfg_path,
        "overrides": {"servers": 2, "values": [1], "max_inflight": 4,
                      "next": "NextAsync",
                      "bounds": {"max_log_length": 1,
                                 "max_timeouts": 1,
                                 "max_client_requests": 1}},
        "max_depth": 3, "label": "r"})
    assert job.cfg.n_servers == 2 and job.cfg.values == (1,)
    assert job.cfg.max_inflight == 4
    assert job.cfg.bounds.max_terms == 2       # derived: timeouts + 1
    assert job.max_depth == 3 and job.stop_on_violation
    pj = job_from_dict({"spec": "paxos",
                        "config": {"acceptors": 2, "ballots": 2,
                                   "values": 1},
                        "keep_going": True})
    assert pj.cfg == PAX.with_() and not pj.stop_on_violation
    # errors name the offending key
    with pytest.raises(ValueError, match="unknown job key.*'frobnicate'"):
        job_from_dict({"spec": "paxos", "frobnicate": 1})
    with pytest.raises(ValueError, match="unknown raft override.*'speed'"):
        job_from_dict({"spec": "raft", "config": cfg_path,
                       "overrides": {"speed": 11}})
    with pytest.raises(ValueError, match="unknown paxos config key 'qs'"):
        job_from_dict({"spec": "paxos", "config": {"qs": 3}})
    with pytest.raises(ValueError, match="raft-only"):
        job_from_dict({"spec": "paxos", "overrides": {"servers": 2}})
    with pytest.raises(ValueError, match="max_depth"):
        job_from_dict({"spec": "paxos", "max_depth": -1})
    # JSONL loader: comments/blank lines skipped, line numbers in errors
    p = tmp_path / "jobs.jsonl"
    p.write_text('# comment\n\n{"spec": "paxos", "max_depth": 2}\n')
    assert len(load_jobs(str(p))) == 1
    p.write_text('{"spec": "nope"}\n')
    with pytest.raises(ValueError, match="jobs.jsonl:1.*unknown spec"):
        load_jobs(str(p))


def test_cache_keys_are_spec_and_ir_scoped():
    """Same options, different specs/configs never collide: the key
    embeds the spec name, IR structure fingerprint and cfg repr."""
    k1 = Job(PAX, max_depth=4).cache_key()
    k2 = Job(MICRO, max_depth=4).cache_key()
    k3 = Job(PAX, max_depth=4).cache_key()
    assert k1 != k2 and k1 == k3
    assert k1.startswith("paxos-") and k2.startswith("raft-")


def test_watch_renders_multi_job_heartbeat(tmp_path):
    """tools/watch.py multi-job mode: a batch heartbeat's per-job map
    renders one status line per job."""
    from raft_tla_tpu.obs.heartbeat import Heartbeat
    spec = importlib.util.spec_from_file_location(
        "watch", os.path.join(_REPO, "tools", "watch.py"))
    watch = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(watch)
    hb_path = str(tmp_path / "hb.json")
    hb = Heartbeat(hb_path)
    hb.beat(depth=4, states=34, extra={"jobs": {
        "r4": {"depth": 4, "distinct": 29, "status": "done"},
        "p3": {"depth": 3, "distinct": 5, "status": "running"}}})
    line, code = watch.status_line(hb_path, None, stale_s=300)
    assert code == 0
    assert "job r4: depth 4  29 states  done" in line
    assert "job p3: depth 3  5 states  running" in line
    # single-run heartbeats render exactly as before
    hb2 = Heartbeat(str(tmp_path / "hb2.json"))
    hb2.beat(depth=2, states=9)
    line2, _ = watch.status_line(str(tmp_path / "hb2.json"), None, 300)
    assert "job " not in line2 and "\n" not in line2


def test_batch_obs_ledger_rows_and_heartbeat(tmp_path):
    """The obs threading: one kind='batch' ledger record per batched
    device call, one kind='job' row per job, per-job heartbeat map,
    and span timelines attributing bucket_compile vs batched_dispatch
    vs job_harvest."""
    from raft_tla_tpu.obs import Obs
    from raft_tla_tpu.obs.heartbeat import Heartbeat
    from raft_tla_tpu.obs.ledger import RunLedger
    from raft_tla_tpu.obs.spans import SpanRecorder
    ledger_path = str(tmp_path / "ledger.jsonl")
    rec = SpanRecorder()
    obs = Obs(spans=rec, ledger=RunLedger(ledger_path),
              heartbeat=Heartbeat(str(tmp_path / "hb.json")))
    obs.start()
    rep = run_jobs([Job(PAX, max_depth=2, label="p")], obs=obs)
    obs.finish(depth=2, states=int(
        rep.outcomes[0].res.distinct_states))
    recs = [json.loads(ln) for ln in open(ledger_path)]
    kinds = [r.get("kind") for r in recs]
    assert "batch" in kinds and "job" in kinds
    batch_rec = next(r for r in recs if r["kind"] == "batch")
    assert batch_rec["jobs_total"] == 1
    job_rec = next(r for r in recs if r["kind"] == "job")
    assert job_rec["label"] == "p" and job_rec["status"] == "done"
    hb = json.load(open(tmp_path / "hb.json"))
    assert hb["status"] == "finished" and "p" in hb["jobs"]
    totals = rec.totals()
    for nm in ("bucket_compile", "batched_dispatch", "job_harvest"):
        assert nm in totals and totals[nm]["count"] >= 1, (nm, totals)


# ---------------------------------------------------------------------------
# slow duplicates: bigger spaces, bigger waves
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_batched_stock_paxos_and_deep_raft_parity_slow():
    """Full-space duplicates of the fast representative: the stock
    paxos model (857 distinct symmetric, fully batched) mixed with the
    raft micro space to exhaustion (20,438 distinct, peak level 740 —
    deliberately NOT a small job: it must overflow the per-job burst
    ring, fall back to a solo engine, and still land exact with an
    honest status).  The ring/table are widened (4*256 rows, 2^17
    slots) so the fallback is the burst's own bail, not the root
    admission check."""
    stock = PaxosConfig()
    jobs = [Job(stock, label="stock"),
            Job(MICRO, label="micro-full"),
            Job(MICRO, max_depth=5, label="micro-d5")]
    rep = run_jobs(jobs, bucket_overrides=dict(chunk=256,
                                               vcap=1 << 17))
    assert rep.meta["buckets"] == 2
    refs = [Engine(stock).check(), Engine(MICRO).check(),
            Engine(MICRO).check(max_depth=5)]
    statuses = [o.status for o in rep.outcomes]
    assert statuses == ["done", "fallback", "done"], statuses
    assert rep.meta["fallback_jobs"] == 1
    for o, ref in zip(rep.outcomes, refs):
        _same(o.res, ref)


@pytest.mark.slow
def test_batched_wave_of_identical_options_slow():
    """A wave wider than a power of two boundary (5 jobs -> padded to
    8) with mixed depth gates, all one bucket — stragglers keep
    stepping while short jobs freeze."""
    jobs = [Job(MICRO, max_depth=d, label=f"d{d}")
            for d in (2, 3, 4, 5, 6)]
    rep = run_jobs(jobs)
    assert rep.meta["buckets"] == 1
    assert rep.meta["engines_compiled"] == 1
    eng = Engine(MICRO)
    for o, d in zip(rep.outcomes, (2, 3, 4, 5, 6)):
        _same(o.res, eng.check(max_depth=d))


# ---------------------------------------------------------------------
# LRU-by-bytes eviction (round 11, ROADMAP 1: --cache-max-bytes)
# ---------------------------------------------------------------------


@pytest.mark.smoke
def test_result_cache_lru_eviction_by_bytes(tmp_path):
    """With max_bytes set, put trims the directory back under the
    bound, least-recently-USED first; a get refreshes recency, and the
    just-written payload is never the victim."""
    pad = "x" * 200                      # ~220 B/payload on disk
    cache = ResultCache(str(tmp_path), max_bytes=3 * 260)
    t = 1_000_000_000
    for i, key in enumerate(("k0", "k1", "k2")):
        cache.put(key, {"n": i, "pad": pad})
        t += 10
        os.utime(os.path.join(str(tmp_path), key + ".json"),
                 (t, t))                 # deterministic recency order
    assert len(cache) == 3
    # touch k0: now k1 is the least recently used
    fresh = ResultCache(str(tmp_path), max_bytes=3 * 260)
    assert fresh.get("k0")["n"] == 0
    t += 10
    os.utime(os.path.join(str(tmp_path), "k0.json"), (t, t))
    fresh.put("k3", {"n": 3, "pad": pad})
    names = sorted(nm for nm in os.listdir(str(tmp_path))
                   if nm.endswith(".json"))
    assert "k3.json" in names            # never evicts its own put
    assert "k0.json" in names            # refreshed by the get
    assert "k1.json" not in names        # the LRU victim
    # evicted keys miss even through the in-process dict
    assert fresh.get("k1") is None


@pytest.mark.smoke
def test_result_cache_unbounded_and_bad_bound(tmp_path):
    """max_bytes=None preserves the historical unbounded behavior;
    a non-positive bound errors at construction, not mid-batch."""
    cache = ResultCache(str(tmp_path / "c"))
    for i in range(8):
        cache.put(f"k{i}", {"n": i, "pad": "y" * 500})
    assert len(cache) == 8
    with pytest.raises(ValueError, match="max_bytes"):
        ResultCache(str(tmp_path / "d"), max_bytes=0)


def test_result_cache_eviction_serves_survivors(tmp_path):
    """End-to-end: a bounded cache under run_jobs still serves the
    surviving key with zero dispatches after eviction pressure."""
    cache = ResultCache(str(tmp_path), max_bytes=1 << 20)
    run_jobs([Job(PAX, max_depth=2, label="a")], cache=cache)
    rep = run_jobs([Job(PAX, max_depth=2, label="b")], cache=cache)
    assert rep.meta["cache_hits"] == 1
    assert rep.meta["batch_dispatches"] == 0
