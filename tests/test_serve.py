"""Batched multi-tenant serving layer (serve/): batched ≡ sequential
bit-exactness, cache hit/miss paths, fallbacks, job parsing, the
multi-job observability surface, and (round 13) the constant-padding
bucket ceilings + persistent AOT executable cache.

One fast representative of each contract runs in tier-1; the
full-space duplicates are slow-marked (tier-1 budget, ROADMAP
standing constraint).
"""

import importlib.util
import json
import os
import pickle

import pytest

from raft_tla_tpu.config import Bounds, ModelConfig, NEXT_ASYNC
from raft_tla_tpu.engine.bfs import Engine
from raft_tla_tpu.serve import (ExecCache, Job, ResultCache,
                                job_from_dict, load_jobs, run_jobs)
from raft_tla_tpu.spec.paxos.config import PaxosConfig

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MICRO = ModelConfig(
    n_servers=2, init_servers=(0, 1), values=(1,),
    next_family=NEXT_ASYNC, symmetry=True, max_inflight_override=4,
    bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                       max_client_requests=1))
PAX = PaxosConfig(n_servers=2, n_ballots=2, n_values=1)


def _het_raft(mll, mt):
    """A MICRO variant whose (max_log_length, max_timeouts) pair makes
    its depth-13 reachable count DISTINCT from its siblings — the
    heterogeneous-ceiling fixtures (each pair's count is pinned in
    test_heterogeneous_*; bench._ceiling_ab uses the same grid)."""
    return MICRO.with_(bounds=Bounds.make(
        max_log_length=mll, max_timeouts=mt, max_client_requests=2))


def _same(res, ref):
    assert (res.distinct_states, res.generated_states, res.depth) == \
        (ref.distinct_states, ref.generated_states, ref.depth)
    assert res.level_sizes == ref.level_sizes
    assert [(v.invariant, v.state_id) for v in res.violations] == \
        [(v.invariant, v.state_id) for v in ref.violations]


def _trace_key(trace):
    return [(label, repr(sv)) for label, sv in trace]


@pytest.mark.slow  # tier-1 budget (round 14): ~43s; batched ≡ solo
# parity (counts, violation ids, witness traces) stays fast via
# test_batched_violation_states_and_witness_parity, and
# tools/serve_smoke.py batches a mixed raft+paxos wave through the
# real CLI every CI run.
def test_batched_mixed_specs_bit_exact():
    """The tier-1 representative: a mixed raft+paxos job list through
    the batched path lands bit-exact against per-job sequential
    engines — counts, level sizes, violation ids AND witness traces —
    while compiling exactly one engine per (spec, bucket)."""
    jobs = [Job(MICRO, max_depth=4, label="r4"),
            Job(MICRO, max_depth=6, label="r6"),
            Job(PAX, max_depth=3, label="p3"),
            Job(PAX, label="pfull")]
    rep = run_jobs(jobs)
    assert rep.meta["buckets"] == 2
    assert rep.meta["engines_compiled"] == 2
    assert rep.meta["fallback_jobs"] == 0
    assert all(o.status == "done" for o in rep.outcomes)
    re_r, re_p = Engine(MICRO), Engine(PAX)
    _same(rep.outcomes[0].res, re_r.check(max_depth=4))
    _same(rep.outcomes[2].res, re_p.check(max_depth=3))
    ref6 = re_r.check(max_depth=6)
    _same(rep.outcomes[1].res, ref6)
    # witness-trace parity: the deepest raft state replays identically
    # from the per-job batched archives and the solo engine's
    last = ref6.distinct_states - 1
    assert _trace_key(rep.outcomes[1].trace(last)) == \
        _trace_key(re_r.trace(last))
    refp = re_p.check()
    _same(rep.outcomes[3].res, refp)
    lastp = refp.distinct_states - 1
    assert _trace_key(rep.outcomes[3].trace(lastp)) == \
        _trace_key(re_p.trace(lastp))
    # the stats stamps every job row carries
    row = rep.outcomes[3].report
    assert row["spec"] == "paxos" and row["status"] == "done"
    assert row["cache_key"].startswith("paxos-")


def test_batched_violation_states_and_witness_parity():
    """A job that FINDS a violation (ValueChosen as invariant, the
    trace-command idiom): the batched run reports the same violating
    state ids and replays the same witness trace as the sequential
    engine, and stop_on_violation gates identically."""
    vcfg = PAX.with_(invariants=("ValueChosen",))
    rep = run_jobs([Job(vcfg, label="vc")])
    o = rep.outcomes[0]
    assert o.status == "done"
    ref_eng = Engine(vcfg)
    ref = ref_eng.check(stop_on_violation=True)
    _same(o.res, ref)
    assert o.res.violations, "expected a ValueChosen witness"
    sid = o.res.violations[0].state_id
    assert _trace_key(o.trace(sid)) == _trace_key(ref_eng.trace(sid))
    det = o.report["violations_detail"]
    assert det and det[0]["invariant"] == "ValueChosen"
    assert det[0]["trace"] == [lbl for lbl, _ in ref_eng.trace(sid)]


def test_result_cache_hit_and_fingerprint_misses(tmp_path):
    """Cache round-trip: an identical job is served with ZERO device
    work; any changed fingerprint component (engine options, config)
    misses."""
    cache = ResultCache(str(tmp_path))
    rep1 = run_jobs([Job(PAX, max_depth=2, label="a")], cache=cache)
    assert rep1.meta["cache_hits"] == 0
    assert rep1.meta["batch_dispatches"] >= 1
    # identical (cfg, options) under a different label: a hit, no
    # engine, no dispatch
    rep2 = run_jobs([Job(PAX, max_depth=2, label="b")], cache=cache)
    assert rep2.meta["cache_hits"] == 1
    assert rep2.meta["batch_dispatches"] == 0
    assert rep2.meta["engines_compiled"] == 0
    o = rep2.outcomes[0]
    assert o.status == "cache_hit" and o.cache_hit
    assert o.report["distinct_states"] == \
        rep1.outcomes[0].report["distinct_states"]
    assert o.report["level_sizes"] == \
        rep1.outcomes[0].report["level_sizes"]
    # options-fingerprint misses: depth gate, stop-on-violation,
    # store toggle all key separately
    assert cache.get(Job(PAX, max_depth=3).cache_key()) is None
    assert cache.get(Job(PAX, max_depth=2,
                         stop_on_violation=False).cache_key()) is None
    assert cache.get(Job(PAX, max_depth=2,
                         store_states=False).cache_key()) is None
    # config-fingerprint miss
    assert cache.get(Job(PAX.with_(n_ballots=1),
                         max_depth=2).cache_key()) is None
    # the payload survives a fresh cache handle (disk round-trip)
    fresh = ResultCache(str(tmp_path))
    key = Job(PAX, max_depth=2).cache_key()
    assert fresh.get(key)["distinct_states"] == \
        rep1.outcomes[0].report["distinct_states"]


def test_ring_overflow_falls_back_sequential_exact():
    """A job whose frontier outgrows the per-job ring bails out of the
    batched program and re-runs solo — results stay exact and the
    fallback is reported honestly.  (Depth-capped: the tiny 16-chunk
    ring overflows by depth ~13 already, and the full 20k-state solo
    reference was most of this test's cost — tier-1 budget.)"""
    rep = run_jobs([Job(MICRO, max_depth=16, label="big")],
                   bucket_overrides=dict(chunk=16, vcap=1 << 10))
    assert rep.meta["fallback_jobs"] == 1
    o = rep.outcomes[0]
    assert o.status == "fallback"
    assert "re-run sequentially" in o.report["status_reason"]
    _same(o.res, Engine(MICRO).check(max_depth=16))


def test_job_from_dict_format_and_errors(tmp_path):
    cfg_path = os.path.join(_REPO, "configs", "tlc_membership",
                            "raft.cfg")
    job = job_from_dict({
        "spec": "raft", "config": cfg_path,
        "overrides": {"servers": 2, "values": [1], "max_inflight": 4,
                      "next": "NextAsync",
                      "bounds": {"max_log_length": 1,
                                 "max_timeouts": 1,
                                 "max_client_requests": 1}},
        "max_depth": 3, "label": "r"})
    assert job.cfg.n_servers == 2 and job.cfg.values == (1,)
    assert job.cfg.max_inflight == 4
    assert job.cfg.bounds.max_terms == 2       # derived: timeouts + 1
    assert job.max_depth == 3 and job.stop_on_violation
    pj = job_from_dict({"spec": "paxos",
                        "config": {"acceptors": 2, "ballots": 2,
                                   "values": 1},
                        "keep_going": True})
    assert pj.cfg == PAX.with_() and not pj.stop_on_violation
    # errors name the offending key
    with pytest.raises(ValueError, match="unknown job key.*'frobnicate'"):
        job_from_dict({"spec": "paxos", "frobnicate": 1})
    with pytest.raises(ValueError, match="unknown raft override.*'speed'"):
        job_from_dict({"spec": "raft", "config": cfg_path,
                       "overrides": {"speed": 11}})
    with pytest.raises(ValueError, match="unknown paxos config key 'qs'"):
        job_from_dict({"spec": "paxos", "config": {"qs": 3}})
    with pytest.raises(ValueError, match="raft-only"):
        job_from_dict({"spec": "paxos", "overrides": {"servers": 2}})
    with pytest.raises(ValueError, match="max_depth"):
        job_from_dict({"spec": "paxos", "max_depth": -1})
    # JSONL loader: comments/blank lines skipped, line numbers in errors
    p = tmp_path / "jobs.jsonl"
    p.write_text('# comment\n\n{"spec": "paxos", "max_depth": 2}\n')
    assert len(load_jobs(str(p))) == 1
    p.write_text('{"spec": "nope"}\n')
    with pytest.raises(ValueError, match="jobs.jsonl:1.*unknown spec"):
        load_jobs(str(p))


def test_cache_keys_are_spec_and_ir_scoped():
    """Same options, different specs/configs never collide: the key
    embeds the spec name, IR structure fingerprint and cfg repr."""
    k1 = Job(PAX, max_depth=4).cache_key()
    k2 = Job(MICRO, max_depth=4).cache_key()
    k3 = Job(PAX, max_depth=4).cache_key()
    assert k1 != k2 and k1 == k3
    assert k1.startswith("paxos-") and k2.startswith("raft-")


def test_watch_renders_multi_job_heartbeat(tmp_path):
    """tools/watch.py multi-job mode: a batch heartbeat's per-job map
    renders one status line per job."""
    from raft_tla_tpu.obs.heartbeat import Heartbeat
    spec = importlib.util.spec_from_file_location(
        "watch", os.path.join(_REPO, "tools", "watch.py"))
    watch = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(watch)
    hb_path = str(tmp_path / "hb.json")
    hb = Heartbeat(hb_path)
    hb.beat(depth=4, states=34, extra={"jobs": {
        "r4": {"depth": 4, "distinct": 29, "status": "done"},
        "p3": {"depth": 3, "distinct": 5, "status": "running"}}})
    line, code = watch.status_line(hb_path, None, stale_s=300)
    assert code == 0
    assert "job r4: depth 4  29 states  done" in line
    assert "job p3: depth 3  5 states  running" in line
    # single-run heartbeats render exactly as before
    hb2 = Heartbeat(str(tmp_path / "hb2.json"))
    hb2.beat(depth=2, states=9)
    line2, _ = watch.status_line(str(tmp_path / "hb2.json"), None, 300)
    assert "job " not in line2 and "\n" not in line2


def test_batch_obs_ledger_rows_and_heartbeat(tmp_path):
    """The obs threading: one kind='batch' ledger record per batched
    device call, one kind='job' row per job, per-job heartbeat map,
    and span timelines attributing bucket_compile vs batched_dispatch
    vs job_harvest."""
    from raft_tla_tpu.obs import Obs
    from raft_tla_tpu.obs.heartbeat import Heartbeat
    from raft_tla_tpu.obs.ledger import RunLedger
    from raft_tla_tpu.obs.spans import SpanRecorder
    ledger_path = str(tmp_path / "ledger.jsonl")
    rec = SpanRecorder()
    obs = Obs(spans=rec, ledger=RunLedger(ledger_path),
              heartbeat=Heartbeat(str(tmp_path / "hb.json")))
    obs.start()
    rep = run_jobs([Job(PAX, max_depth=2, label="p")], obs=obs)
    obs.finish(depth=2, states=int(
        rep.outcomes[0].res.distinct_states))
    recs = [json.loads(ln) for ln in open(ledger_path)]
    kinds = [r.get("kind") for r in recs]
    assert "batch" in kinds and "job" in kinds
    batch_rec = next(r for r in recs if r["kind"] == "batch")
    assert batch_rec["jobs_total"] == 1
    job_rec = next(r for r in recs if r["kind"] == "job")
    assert job_rec["label"] == "p" and job_rec["status"] == "done"
    hb = json.load(open(tmp_path / "hb.json"))
    assert hb["status"] == "finished" and "p" in hb["jobs"]
    totals = rec.totals()
    for nm in ("bucket_compile", "batched_dispatch", "job_harvest"):
        assert nm in totals and totals[nm]["count"] >= 1, (nm, totals)


# ---------------------------------------------------------------------------
# Constant-padding bucket ceilings (round 13): heterogeneous value
# bounds through ONE compiled bucket, bit-exact per job vs solo.
# ---------------------------------------------------------------------------


@pytest.mark.slow  # tier-1 budget (round 14): ~50s; the paxos hetero
# rep below stays fast and tools/serve_smoke.py runs a 4-distinct-
# bounds raft hetero wave on the real CLI every CI run.
def test_heterogeneous_raft_bounds_one_bucket_bit_exact():
    """Two raft jobs with DIFFERENT search bounds (so their reachable
    sets genuinely differ at the test depth) land in ONE padded bucket
    ceiling, compile one engine, and each result is bit-exact vs its
    own solo engine — counts, level sizes, violation ids, witness
    traces.  (The K=4 grid incl. paxos is the slow duplicate below;
    bench._ceiling_ab and tools/serve_smoke.py pin the K=4
    compile-once contract every run.)"""
    from raft_tla_tpu.spec import spec_of
    cfgs = [_het_raft(1, 1), _het_raft(2, 2)]
    assert len({repr(spec_of(c).serve_bucket(c)[0])
                for c in cfgs}) == 1
    rep = run_jobs([Job(c, max_depth=13, label=f"h{k}")
                    for k, c in enumerate(cfgs)])
    assert rep.meta["buckets"] == 1
    assert rep.meta["engines_compiled"] == 1
    assert rep.meta["fallback_jobs"] == 0
    counts = []
    for o, c in zip(rep.outcomes, cfgs):
        ref_eng = Engine(c)
        ref = ref_eng.check(max_depth=13)
        assert o.status == "done"
        _same(o.res, ref)
        last = ref.distinct_states - 1
        assert _trace_key(o.trace(last)) == \
            _trace_key(ref_eng.trace(last))
        counts.append(int(o.res.distinct_states))
    # the jobs' answers DIFFER — the per-job runtime bounds are live,
    # not a coincidence of equal spaces under a shared ceiling
    assert counts == [616, 743], counts


def test_heterogeneous_paxos_bounds_one_bucket_bit_exact():
    """Paxos twin: differing (ballots, values) pad to one ceiling;
    padded lanes are masked per job, so each job's reachable set,
    level sizes and witness labels match its solo engine exactly."""
    from raft_tla_tpu.spec import spec_of
    cfgs = [PaxosConfig(n_servers=2, n_ballots=3, n_values=3),
            PaxosConfig(n_servers=2, n_ballots=4, n_values=4)]
    assert len({repr(spec_of(c).serve_bucket(c)[0])
                for c in cfgs}) == 1
    rep = run_jobs([Job(c, max_depth=4, label=f"p{k}")
                    for k, c in enumerate(cfgs)])
    assert rep.meta["buckets"] == 1
    assert rep.meta["engines_compiled"] == 1
    assert rep.meta["fallback_jobs"] == 0
    counts = []
    for o, c in zip(rep.outcomes, cfgs):
        ref_eng = Engine(c)
        ref = ref_eng.check(max_depth=4)
        assert o.status == "done"
        _same(o.res, ref)
        last = ref.distinct_states - 1
        # padded layouts decode wider state rows, so trace parity is
        # on the action-label chain (the state identity is already
        # pinned by counts/level sizes/violation ids above)
        assert [lbl for lbl, _ in o.trace(last)] == \
            [lbl for lbl, _ in ref_eng.trace(last)]
        counts.append(int(o.res.distinct_states))
    assert counts == [44, 88], counts


# ---------------------------------------------------------------------------
# Persistent AOT executable cache (serve/exec_cache, round 13)
# ---------------------------------------------------------------------------


class _FakeSerializer:
    """Deterministic stand-in: 'serializes' to a token and keeps the
    live executable in a registry — simulates a serializable backend
    without depending on runtime support, so the keying/round-trip/
    corrupt-entry contracts pin on every platform."""

    name = "fake"
    registry = {}

    def serialize(self, compiled):
        token = f"tok{id(compiled)}".encode()
        _FakeSerializer.registry[token] = compiled
        return token

    def deserialize(self, blob):
        return _FakeSerializer.registry[blob]


class _BrokenSerializer:
    name = "broken"

    def serialize(self, compiled):
        raise RuntimeError("this backend cannot serialize executables")

    def deserialize(self, blob):
        raise RuntimeError("this backend cannot serialize executables")


@pytest.mark.smoke
def test_exec_cache_key_stability_and_parts(tmp_path):
    """Key = sha of the canonical parts: stable across repeats,
    different for ANY changed part (JP, ceiling, mode flags,
    backend)."""
    from raft_tla_tpu.serve.exec_cache import backend_fingerprint, \
        exec_key
    base = dict(backend=backend_fingerprint(), spec="raft",
                ceiling_cfg="cfgA", JP=2, chunk=128,
                guard_matmul=True)
    assert exec_key(base) == exec_key(dict(base))
    assert exec_key(base) == exec_key(
        dict(reversed(list(base.items()))))     # order-independent
    for change in (dict(JP=4), dict(ceiling_cfg="cfgB"),
                   dict(guard_matmul=False), dict(spec="paxos"),
                   dict(backend={"platform": "other"})):
        assert exec_key({**base, **change}) != exec_key(base), change


@pytest.mark.smoke
def test_exec_cache_roundtrip_corrupt_and_foreign_miss(tmp_path):
    """Disk round-trip through an injected serializer; a corrupt
    entry, a foreign (renamed) entry, and a serializer mismatch all
    read as labeled misses — never an exception, never a wrong
    load."""
    cache = ExecCache(str(tmp_path), serializer=_FakeSerializer())
    sentinel = object()
    assert cache.store("k1", sentinel)
    ex, why = cache.load("k1")
    assert ex is sentinel and why == "hit"
    # cold key
    ex, why = cache.load("k2")
    assert ex is None and "cold" in why
    # corrupt entry: truncated pickle
    with open(tmp_path / "k3.exec", "wb") as fh:
        fh.write(b"\x80\x04 garbage")
    ex, why = cache.load("k3")
    assert ex is None and "corrupt" in why
    # foreign entry: a valid container copied under the wrong name
    os.replace(tmp_path / "k1.exec", tmp_path / "k4.exec")
    ex, why = cache.load("k4")
    assert ex is None and "foreign" in why
    # serializer mismatch reads as a miss, not a wrong deserialize
    cache2 = ExecCache(str(tmp_path), serializer=_BrokenSerializer())
    cache2.store("k5", sentinel)        # records a named failure
    assert cache2.store_failures == 1
    assert "cannot serialize" in cache2.store_fail_reasons[-1]
    with open(tmp_path / "k6.exec", "wb") as fh:
        pickle.dump({"format": 1, "key": "k6", "parts": {},
                     "serializer": "fake", "blob": b"x"}, fh)
    ex, why = cache2.load("k6")
    assert ex is None and "serializer mismatch" in why
    stats = cache.stats()
    assert stats["exec_cache_hits"] == 1
    assert stats["exec_cache_misses"] >= 3


@pytest.mark.smoke
def test_exec_cache_lru_bytes_eviction(tmp_path):
    """LRU-by-bytes bound (round 14 — the eviction half ROADMAP item 1
    left open, mirroring serve/cache.ResultCache): every store trims
    the directory back under max_bytes, oldest-mtime first; a warm
    LOAD refreshes recency so a hot bucket survives; the just-written
    entry is never the victim; None keeps the historical unbounded
    behavior."""
    import time as _t

    def entry_bytes(key):
        cache = ExecCache(str(tmp_path), serializer=_FakeSerializer())
        cache.store(key, object())
        return os.path.getsize(tmp_path / f"{key}.exec")

    one = entry_bytes("probe")
    os.remove(tmp_path / "probe.exec")
    with pytest.raises(ValueError, match="must be positive"):
        ExecCache(str(tmp_path), max_bytes=0)
    cache = ExecCache(str(tmp_path), serializer=_FakeSerializer(),
                      max_bytes=int(2.5 * one))
    assert cache.store("a", object())
    _t.sleep(0.05)
    assert cache.store("b", object())
    _t.sleep(0.05)
    # a warm load refreshes "a"'s mtime: it becomes the NEWEST
    ex, why = cache.load("a")
    assert why == "hit"
    _t.sleep(0.05)
    # third entry overflows the bound: the LRU victim is now "b"
    assert cache.store("c", object())
    assert cache.evictions == 1
    assert sorted(p.name for p in tmp_path.glob("*.exec")) == \
        ["a.exec", "c.exec"]
    # the just-written entry is never the victim, even when a single
    # oversized store exceeds the bound on its own
    tiny = ExecCache(str(tmp_path / "tiny"),
                     serializer=_FakeSerializer(), max_bytes=1)
    assert tiny.store("big", object())
    assert os.path.exists(tmp_path / "tiny" / "big.exec")
    assert tiny.evictions == 0
    # ... and the NEXT store retires it like any other cold entry
    assert tiny.store("big2", object())
    assert not os.path.exists(tmp_path / "tiny" / "big.exec")
    # unbounded default: no eviction ever, loads stay write-free
    unb = ExecCache(str(tmp_path / "unb"),
                    serializer=_FakeSerializer())
    for i in range(4):
        unb.store(f"k{i}", object())
    assert unb.evictions == 0
    assert len(list((tmp_path / "unb").glob("*.exec"))) == 4
    assert unb.stats()["exec_cache_evictions"] == 0


def test_exec_cache_max_bytes_cli_validation():
    """batch --executable-cache-max-bytes is a usage error (exit 2,
    named message) without --executable-cache or with a non-positive
    bound — never a traceback."""
    from raft_tla_tpu.cli import main
    assert main(["batch", "--job", '{"spec": "paxos"}',
                 "--executable-cache-max-bytes", "100"]) == 2
    assert main(["batch", "--job", '{"spec": "paxos"}',
                 "--executable-cache", "/tmp/nope",
                 "--executable-cache-max-bytes", "-5"]) == 2


def test_exec_cache_warm_restart_zero_compiles_and_slo_obs(tmp_path):
    """End-to-end acceptance: a warm ``exec_cache`` restart (fresh
    BucketEngine, fresh run_jobs) performs ZERO .compile() calls —
    no bucket_compile span — and serves bit-identical results.  Uses
    the REAL jax serializer (this backend round-trips); a backend
    that cannot serialize is covered by the _BrokenSerializer test
    above (honest labeled miss).  The same runs pin the round-13 SLO
    surface: wait_s/service_s on every report row, the heartbeat SLO
    snapshot (queue depth + histograms + exec-cache counters), and
    the per-tenant ledger rollups."""
    from raft_tla_tpu.obs import Obs
    from raft_tla_tpu.obs.heartbeat import Heartbeat
    from raft_tla_tpu.obs.ledger import RunLedger
    from raft_tla_tpu.obs.spans import SpanRecorder
    exec_dir = str(tmp_path / "exec")
    rec1 = SpanRecorder()
    rep1 = run_jobs([Job(PAX, max_depth=3, label="a")],
                    obs=Obs(spans=rec1), exec_cache=exec_dir)
    assert rec1.totals()["bucket_compile"]["count"] == 1
    assert rep1.meta["exec_cache_misses"] == 1
    assert rep1.meta["exec_cache_stores"] == 1

    rec2 = SpanRecorder()
    ledger_path = str(tmp_path / "ledger.jsonl")
    hb_path = str(tmp_path / "hb.json")
    cache2 = ExecCache(exec_dir)
    obs2 = Obs(spans=rec2, ledger=RunLedger(ledger_path),
               heartbeat=Heartbeat(hb_path))
    obs2.start()
    rep2 = run_jobs([Job(PAX, max_depth=3, label="b")], obs=obs2,
                    exec_cache=cache2)
    obs2.finish(depth=3, states=1)
    assert rec2.totals().get("bucket_compile",
                             {}).get("count", 0) == 0
    assert cache2.hits == 1
    assert rep2.meta["exec_cache_hits"] == 1
    assert rep1.outcomes[0].res.level_sizes == \
        rep2.outcomes[0].res.level_sizes
    # SLO surface: report rows, heartbeat snapshot, tenant rollups
    row = rep2.outcomes[0].report
    assert "wait_s" in row and "service_s" in row
    hb = json.load(open(hb_path))
    slo = hb["slo"]
    assert slo["queue_depth"] == 0 and slo["jobs_done"] == 1
    assert sum(slo["service_hist"].values()) == 1
    assert slo["exec_cache"]["exec_cache_hits"] == 1
    recs = [json.loads(ln) for ln in open(ledger_path)]
    tenant = [r for r in recs if r.get("kind") == "tenant"]
    assert len(tenant) == 1 and tenant[0]["spec"] == "paxos"
    assert tenant[0]["jobs"] == 1 and tenant[0]["service_s"] >= 0
    assert any(r.get("kind") == "exec_cache" for r in recs)
    batch_rec = next(r for r in recs if r.get("kind") == "batch")
    assert "queue_depth" in batch_rec
    # watch renders the SLO lines
    spec = importlib.util.spec_from_file_location(
        "watch_slo", os.path.join(_REPO, "tools", "watch.py"))
    watch = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(watch)
    line, code = watch.status_line(hb_path, None, stale_s=300)
    assert code == 0
    assert "queue: 0 waiting, 1 done" in line
    assert "exec-cache: 1 hits" in line


# ---------------------------------------------------------------------------
# slow duplicates: bigger spaces, bigger waves
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_heterogeneous_k4_grid_bit_exact_slow():
    """The full K=4 acceptance grid, raft AND paxos: four distinct
    value-bound configs per spec, each spec ONE bucket and ONE
    compile, every job bit-exact vs its solo engine (the fast 2-job
    representatives above keep tier-1 lean)."""
    from raft_tla_tpu.spec import spec_of
    rcfgs = [_het_raft(m, t) for m, t in
             ((1, 1), (1, 2), (2, 1), (2, 2))]
    pcfgs = [PaxosConfig(n_servers=2, n_ballots=b, n_values=v)
             for b, v in ((3, 3), (3, 4), (4, 3), (4, 4))]
    assert len({repr(spec_of(c).serve_bucket(c)[0])
                for c in rcfgs}) == 1
    assert len({repr(spec_of(c).serve_bucket(c)[0])
                for c in pcfgs}) == 1
    jobs = [Job(c, max_depth=13, label=f"r{k}")
            for k, c in enumerate(rcfgs)] + \
           [Job(c, max_depth=4, label=f"p{k}")
            for k, c in enumerate(pcfgs)]
    rep = run_jobs(jobs)
    assert rep.meta["buckets"] == 2
    assert rep.meta["engines_compiled"] == 2
    assert rep.meta["fallback_jobs"] == 0
    counts = {}
    for o, c, d in zip(rep.outcomes, rcfgs + pcfgs,
                       [13] * 4 + [4] * 4):
        ref_eng = Engine(c)
        ref = ref_eng.check(max_depth=d)
        assert o.status == "done"
        _same(o.res, ref)
        last = ref.distinct_states - 1
        assert [lbl for lbl, _ in o.trace(last)] == \
            [lbl for lbl, _ in ref_eng.trace(last)]
        counts[o.job.label] = int(o.res.distinct_states)
    assert len({counts[f"r{k}"] for k in range(4)}) == 4, counts
    assert len({counts[f"p{k}"] for k in range(4)}) >= 3, counts

@pytest.mark.slow
def test_batched_stock_paxos_and_deep_raft_parity_slow():
    """Full-space duplicates of the fast representative: the stock
    paxos model (857 distinct symmetric, fully batched) mixed with the
    raft micro space to exhaustion (20,438 distinct, peak level 740 —
    deliberately NOT a small job: it must overflow the per-job burst
    ring, fall back to a solo engine, and still land exact with an
    honest status).  The ring/table are widened (4*256 rows, 2^17
    slots) so the fallback is the burst's own bail, not the root
    admission check."""
    stock = PaxosConfig()
    jobs = [Job(stock, label="stock"),
            Job(MICRO, label="micro-full"),
            Job(MICRO, max_depth=5, label="micro-d5")]
    rep = run_jobs(jobs, bucket_overrides=dict(chunk=256,
                                               vcap=1 << 17))
    assert rep.meta["buckets"] == 2
    refs = [Engine(stock).check(), Engine(MICRO).check(),
            Engine(MICRO).check(max_depth=5)]
    statuses = [o.status for o in rep.outcomes]
    assert statuses == ["done", "fallback", "done"], statuses
    assert rep.meta["fallback_jobs"] == 1
    for o, ref in zip(rep.outcomes, refs):
        _same(o.res, ref)


@pytest.mark.slow
def test_batched_wave_of_identical_options_slow():
    """A wave wider than a power of two boundary (5 jobs -> padded to
    8) with mixed depth gates, all one bucket — stragglers keep
    stepping while short jobs freeze."""
    jobs = [Job(MICRO, max_depth=d, label=f"d{d}")
            for d in (2, 3, 4, 5, 6)]
    rep = run_jobs(jobs)
    assert rep.meta["buckets"] == 1
    assert rep.meta["engines_compiled"] == 1
    eng = Engine(MICRO)
    for o, d in zip(rep.outcomes, (2, 3, 4, 5, 6)):
        _same(o.res, eng.check(max_depth=d))


# ---------------------------------------------------------------------
# LRU-by-bytes eviction (round 11, ROADMAP 1: --cache-max-bytes)
# ---------------------------------------------------------------------


@pytest.mark.smoke
def test_result_cache_lru_eviction_by_bytes(tmp_path):
    """With max_bytes set, put trims the directory back under the
    bound, least-recently-USED first; a get refreshes recency, and the
    just-written payload is never the victim."""
    pad = "x" * 200                      # ~220 B/payload on disk
    cache = ResultCache(str(tmp_path), max_bytes=3 * 260)
    t = 1_000_000_000
    for i, key in enumerate(("k0", "k1", "k2")):
        cache.put(key, {"n": i, "pad": pad})
        t += 10
        os.utime(os.path.join(str(tmp_path), key + ".json"),
                 (t, t))                 # deterministic recency order
    assert len(cache) == 3
    # touch k0: now k1 is the least recently used
    fresh = ResultCache(str(tmp_path), max_bytes=3 * 260)
    assert fresh.get("k0")["n"] == 0
    t += 10
    os.utime(os.path.join(str(tmp_path), "k0.json"), (t, t))
    fresh.put("k3", {"n": 3, "pad": pad})
    names = sorted(nm for nm in os.listdir(str(tmp_path))
                   if nm.endswith(".json"))
    assert "k3.json" in names            # never evicts its own put
    assert "k0.json" in names            # refreshed by the get
    assert "k1.json" not in names        # the LRU victim
    # evicted keys miss even through the in-process dict
    assert fresh.get("k1") is None


@pytest.mark.smoke
def test_result_cache_unbounded_and_bad_bound(tmp_path):
    """max_bytes=None preserves the historical unbounded behavior;
    a non-positive bound errors at construction, not mid-batch."""
    cache = ResultCache(str(tmp_path / "c"))
    for i in range(8):
        cache.put(f"k{i}", {"n": i, "pad": "y" * 500})
    assert len(cache) == 8
    with pytest.raises(ValueError, match="max_bytes"):
        ResultCache(str(tmp_path / "d"), max_bytes=0)


def test_result_cache_eviction_serves_survivors(tmp_path):
    """End-to-end: a bounded cache under run_jobs still serves the
    surviving key with zero dispatches after eviction pressure."""
    cache = ResultCache(str(tmp_path), max_bytes=1 << 20)
    run_jobs([Job(PAX, max_depth=2, label="a")], cache=cache)
    rep = run_jobs([Job(PAX, max_depth=2, label="b")], cache=cache)
    assert rep.meta["cache_hits"] == 1
    assert rep.meta["batch_dispatches"] == 0


# ---------------------------------------------------------------------
# Mesh-sharded waves (round 16): the job axis across every local
# device.  conftest forces 8 virtual CPU devices for the whole test
# session (the test_pjit pattern), so wave_mesh=4 shards across a
# device subset in-process.
# ---------------------------------------------------------------------


def test_mesh_wave_bit_exact_vs_single_device():
    """The tier-1 mesh representative: a K=8 mixed raft+paxos wave
    under a 4-device job mesh is bit-exact per job vs the
    single-device wave (counts, level sizes, violation ids, witness
    traces) — and the single-device wave is itself pinned against
    solo engines by the tests above, so mesh ≡ solo transitively
    (the slow duplicate below checks solo directly).  One
    bucket_compile per bucket, one batched_dispatch per burst round,
    and the wave occupancy lands in the meta, the ledger rows and the
    heartbeat."""
    from raft_tla_tpu.obs import Obs
    from raft_tla_tpu.obs.heartbeat import Heartbeat
    from raft_tla_tpu.obs.ledger import RunLedger
    from raft_tla_tpu.obs.spans import SpanRecorder
    import tempfile

    def jobs():
        return ([Job(MICRO, max_depth=d, label=f"r{d}")
                 for d in (3, 4, 5, 6, 7, 8)] +
                [Job(PAX, max_depth=3, label="p3"),
                 Job(PAX, label="pfull")])

    with tempfile.TemporaryDirectory() as td:
        rec = SpanRecorder()
        led_path = os.path.join(td, "ledger.jsonl")
        hb_path = os.path.join(td, "hb.json")
        obs = Obs(spans=rec, ledger=RunLedger(led_path),
                  heartbeat=Heartbeat(hb_path))
        obs.start()
        rep_m = run_jobs(jobs(), wave_mesh=4, obs=obs)
        obs.finish(depth=8, states=1)
        rep_s = run_jobs(jobs(), wave_mesh="off")
        hb = json.load(open(hb_path))
        recs = [json.loads(ln) for ln in open(led_path)]
    assert rep_m.meta["buckets"] == 2
    assert rep_m.meta["fallback_jobs"] == 0
    assert rep_m.meta["wave_devices"] == 4
    # 6 raft jobs -> mesh multiple 4 * pow2(ceil(6/4)) = 8 lanes
    assert rep_m.meta["wave_lanes"] == 8
    assert rep_s.meta["wave_devices"] == 1
    for om, osd in zip(rep_m.outcomes, rep_s.outcomes):
        assert om.status == "done" and osd.status == "done"
        _same(om.res, osd.res)
    # witness-trace parity through the mesh harvest path (r6's
    # deepest state replays identically in both modes)
    last = rep_s.outcomes[3].res.distinct_states - 1
    assert _trace_key(rep_m.outcomes[3].trace(last)) == \
        _trace_key(rep_s.outcomes[3].trace(last))
    # ONE bucket_compile per bucket, ONE batched_dispatch per round
    totals = rec.totals()
    assert totals["bucket_compile"]["count"] == 2
    assert totals["batched_dispatch"]["count"] == \
        rep_m.meta["batch_dispatches"]
    # same round count in both modes: the mesh changes placement,
    # never the per-job trajectory
    assert rep_m.meta["batch_dispatches"] == \
        rep_s.meta["batch_dispatches"]
    # occupancy on the obs surface: every kind=batch ledger row and
    # the final heartbeat carry the wave block
    batch_rows = [r for r in recs if r.get("kind") == "batch"]
    assert batch_rows and all(r["wave_devices"] == 4
                              for r in batch_rows)
    assert any(r["wave_lanes"] == 8 for r in batch_rows)
    assert hb["wave"]["devices"] == 4
    assert hb["wave"]["jobs_per_device"] * 4 == hb["wave"]["lanes"]
    assert hb["wave"]["state_shards"] == 1

    # the 2-D grid: the same 4 devices as a 2x2 jobs x state mesh.
    # Identical per-job results, still ONE bucket_compile per bucket,
    # and the state axis surfaces in meta, ledger and heartbeat.
    with tempfile.TemporaryDirectory() as td:
        rec2 = SpanRecorder()
        led2 = os.path.join(td, "ledger.jsonl")
        hb2p = os.path.join(td, "hb.json")
        obs2 = Obs(spans=rec2, ledger=RunLedger(led2),
                   heartbeat=Heartbeat(hb2p))
        obs2.start()
        rep_2 = run_jobs(jobs(), wave_mesh="2x2", obs=obs2)
        obs2.finish(depth=8, states=1)
        hb2 = json.load(open(hb2p))
        recs2 = [json.loads(ln) for ln in open(led2)]
    assert rep_2.meta["wave_devices"] == 4
    assert rep_2.meta["wave_state_shards"] == 2
    # J=2 axis: 6 raft jobs -> 2 * pow2(ceil(6/2)) = 8 lanes again
    assert rep_2.meta["wave_lanes"] == 8
    assert rep_2.meta["fallback_jobs"] == 0
    for o2, osd in zip(rep_2.outcomes, rep_s.outcomes):
        assert o2.status == "done"
        _same(o2.res, osd.res)
    assert _trace_key(rep_2.outcomes[3].trace(last)) == \
        _trace_key(rep_s.outcomes[3].trace(last))
    totals2 = rec2.totals()
    assert totals2["bucket_compile"]["count"] == 2
    assert totals2["batched_dispatch"]["count"] == \
        rep_2.meta["batch_dispatches"]
    assert rep_2.meta["batch_dispatches"] == \
        rep_s.meta["batch_dispatches"]
    rows2 = [r for r in recs2 if r.get("kind") == "batch"]
    assert rows2 and all(r["wave_state_shards"] == 2 for r in rows2)
    assert hb2["wave"]["devices"] == 4
    assert hb2["wave"]["state_shards"] == 2


@pytest.mark.slow  # tier-1 budget: the fast reps pin mesh ≡
# single-device (itself pinned vs solo); this is the direct
# full-space mesh ≡ solo duplicate, 1-D and 2-D
def test_mesh_wave_vs_solo_engines_slow():
    def jobs():
        return ([Job(MICRO, max_depth=d, label=f"r{d}")
                 for d in (4, 6, 13)] +
                [Job(_het_raft(1, 2), max_depth=6, label="h6"),
                 Job(MICRO, max_depth=5, label="r5b"),
                 Job(MICRO, max_depth=3, label="r3b"),
                 Job(PAX, max_depth=3, label="p3"),
                 Job(PAX, label="pfull")])
    rep = run_jobs(jobs(), wave_mesh=4)
    assert rep.meta["wave_devices"] == 4
    assert rep.meta["fallback_jobs"] == 0
    solos = []
    for o in rep.outcomes:
        eng = Engine(o.job.cfg)
        solos.append(eng.check(max_depth=o.job.max_depth))
        _same(o.res, solos[-1])
    # the 2-D grid against the same solo results
    rep2 = run_jobs(jobs(), wave_mesh="2x2")
    assert rep2.meta["wave_state_shards"] == 2
    assert rep2.meta["fallback_jobs"] == 0
    for o, want in zip(rep2.outcomes, solos):
        _same(o.res, want)


def test_exec_cache_key_discriminates_mesh_shapes_and_padding():
    """A mesh-shape change is a NAMED miss, never a wrong load: the
    4x1, 2x2 and single-device bucket executables' keys all differ at
    the same padded width, because the [J, S] grid joins the key
    parts — and they differ in wave_mesh ONLY, so the discrimination
    is exactly the mesh shape.  Also pins the padding rule the width
    half of the key rides on: J-axis multiples, the state axis never
    eats lanes."""
    from raft_tla_tpu.serve.batch import BucketEngine
    from raft_tla_tpu.serve.exec_cache import exec_key
    be_off = BucketEngine(MICRO)
    be_mesh = BucketEngine(MICRO, wave_mesh=4)
    be_2d = BucketEngine(MICRO, wave_mesh=(2, 2))
    p_off, p_mesh, p_2d = (be_off._exec_key_parts(8),
                           be_mesh._exec_key_parts(8),
                           be_2d._exec_key_parts(8))
    assert p_off["wave_mesh"] == 0 and p_mesh["wave_mesh"] == [4, 1] \
        and p_2d["wave_mesh"] == [2, 2]
    for a, b in ((p_off, p_mesh), (p_off, p_2d), (p_mesh, p_2d)):
        assert {k for k in a if a[k] != b[k]} == {"wave_mesh"}
    assert len({exec_key(p) for p in (p_off, p_mesh, p_2d)}) == 3
    # padding: single-device pads to pow2, mesh to a J-axis multiple
    # with equal per-row lane counts (4x1 and 2x2 use the same 4
    # devices but round to different lane widths — J=4 vs J=2)
    assert [be_off._pad_jp(n) for n in (1, 2, 5, 8)] == [1, 2, 8, 8]
    assert [be_mesh._pad_jp(n) for n in (1, 4, 5, 8, 9)] == \
        [4, 4, 8, 8, 16]
    assert [be_2d._pad_jp(n) for n in (1, 2, 3, 5)] == [2, 2, 4, 8]


def test_wave_mesh_resolution_and_scheduler_ceiling():
    """resolve_wave_mesh normalizes auto/off/N/JxS to the (J, S) grid
    with named errors, and the scheduler's default wave ceiling
    scales to J x 8 lanes (the state axis never widens the wave)
    unless --max-wave pins it."""
    from raft_tla_tpu.serve import WaveScheduler
    from raft_tla_tpu.serve.batch import resolve_wave_mesh
    assert resolve_wave_mesh("auto") == (8, 1)  # conftest's 8 devices
    assert resolve_wave_mesh(None) == (8, 1)
    assert resolve_wave_mesh("off") == (0, 1)
    assert resolve_wave_mesh(1) == (0, 1)      # 1 device = no mesh
    assert resolve_wave_mesh("4") == (4, 1)
    assert resolve_wave_mesh("2x2") == (2, 2)
    assert resolve_wave_mesh("4x2") == (4, 2)
    assert resolve_wave_mesh("1x2") == (1, 2)  # state-only split
    assert resolve_wave_mesh("1x1") == (0, 1)  # 1 device = no mesh
    with pytest.raises(ValueError, match="banana"):
        resolve_wave_mesh("banana")
    with pytest.raises(ValueError, match="exceeds the 8"):
        resolve_wave_mesh(64)
    with pytest.raises(ValueError, match="exceeds the 8"):
        resolve_wave_mesh("3x3")
    with pytest.raises(ValueError, match=">= 1"):
        resolve_wave_mesh("0x2")
    with pytest.raises(ValueError, match=">= 0"):
        resolve_wave_mesh(-2)
    assert WaveScheduler(wave_mesh=4).wave_cap == 32
    assert WaveScheduler(wave_mesh="2x2").wave_cap == 16
    assert WaveScheduler(wave_mesh="off").wave_cap == 8
    assert WaveScheduler(wave_mesh=4, max_wave=5).wave_cap == 5
    with pytest.raises(ValueError, match="max_wave"):
        WaveScheduler(max_wave=0)


@pytest.mark.smoke
def test_wave_mesh_and_max_wave_cli_validation():
    """batch --max-wave/--wave-mesh usage errors are exit 2 with a
    named message, never a traceback (serve shares the checks)."""
    from raft_tla_tpu.cli import main
    base = ["batch", "--job", '{"spec": "paxos"}']
    assert main(base + ["--max-wave", "0"]) == 2
    assert main(base + ["--wave-mesh", "banana"]) == 2
    assert main(base + ["--wave-mesh", "64"]) == 2


def test_parked_carry_restores_across_mesh_modes(tmp_path):
    """The portable restart matrix: a carry parked under a 4-device
    mesh resumes bit-exact on a single-device scheduler, and a
    single-device carry resumes under the mesh — the .wave.npz slices
    are host numpy, re-placed by whichever mode restores them."""
    from raft_tla_tpu.serve import WaveScheduler
    from conftest import cached_explore
    waves = tmp_path / "waves"
    cache = ResultCache(str(tmp_path / "cache"))
    ovr = {"burst_levels": 1}   # several step boundaries per job
    mesh = WaveScheduler(cache=cache, wave_state=str(waves),
                         wave_mesh=4, bucket_overrides=ovr)
    single = WaveScheduler(cache=cache, wave_state=str(waves),
                           wave_mesh="off", bucket_overrides=ovr)

    def stop_after_persist():
        return waves.is_dir() and any(
            fn.endswith(".wave.npz") for fn in os.listdir(waves))

    # mesh park -> single-device resume
    rep1 = mesh.serve([Job(MICRO, max_depth=6, label="m6")],
                      stop=stop_after_persist)
    assert rep1.outcomes == [None] and rep1.meta["deferred_jobs"] == 1
    assert stop_after_persist(), "the mesh carry must survive"
    rep2 = single.serve([Job(MICRO, max_depth=6, label="m6")])
    o = rep2.outcomes[0]
    assert o.status == "done" and rep2.meta["resumed_jobs"] == 1
    want = cached_explore(MICRO, max_depth=6)
    _same(o.res, want)
    assert not stop_after_persist()

    # single-device park -> mesh resume (both engines already
    # compiled: zero new compiles either side)
    rep3 = single.serve([Job(MICRO, max_depth=5, label="m5")],
                        stop=stop_after_persist)
    assert rep3.outcomes == [None]
    assert rep3.meta["engines_compiled"] == 0
    rep4 = mesh.serve([Job(MICRO, max_depth=5, label="m5")])
    o4 = rep4.outcomes[0]
    assert o4.status == "done" and rep4.meta["resumed_jobs"] == 1
    assert rep4.meta["engines_compiled"] == 0
    assert rep4.meta["wave_devices"] == 4
    _same(o4.res, cached_explore(MICRO, max_depth=5))


def test_parked_carry_restores_across_mesh_shapes(tmp_path):
    """The 2-D restart matrix: a carry parked under the 2x2 grid
    resumes bit-exact under 4x1, 1x1 and plain single-device
    schedulers and back again — the .wave.npz slices are host numpy,
    so the grid shape at park time never leaks into the file.  Every
    scheduler keeps a warm exec cache; the second leg of each
    direction compiles nothing."""
    from raft_tla_tpu.serve import WaveScheduler
    from conftest import cached_explore
    waves = tmp_path / "waves"
    cache = ResultCache(str(tmp_path / "cache"))
    ovr = {"burst_levels": 1}   # several step boundaries per job

    def sched(mesh):
        return WaveScheduler(cache=cache, wave_state=str(waves),
                             wave_mesh=mesh, bucket_overrides=ovr,
                             exec_cache=str(tmp_path / "exec"))

    s22, s41, s11 = sched("2x2"), sched("4x1"), sched("1x1")

    def parked():
        return waves.is_dir() and any(
            fn.endswith(".wave.npz") for fn in os.listdir(waves))

    # 2x2 park -> 4x1 resume (same 4 devices, different grid)
    rep1 = s22.serve([Job(MICRO, max_depth=6, label="m6")],
                     stop=parked)
    assert rep1.outcomes == [None] and rep1.meta["deferred_jobs"] == 1
    assert rep1.meta["wave_state_shards"] == 2
    assert parked(), "the 2x2 carry must survive"
    rep2 = s41.serve([Job(MICRO, max_depth=6, label="m6")])
    o2 = rep2.outcomes[0]
    assert o2.status == "done" and rep2.meta["resumed_jobs"] == 1
    assert rep2.meta["wave_devices"] == 4
    assert rep2.meta["wave_state_shards"] == 1
    _same(o2.res, cached_explore(MICRO, max_depth=6))
    assert not parked()

    # 4x1 park -> 2x2 resume: both engines warm, zero new compiles
    # on either side (the second leg of the matrix)
    rep3 = s41.serve([Job(MICRO, max_depth=5, label="m5")],
                     stop=parked)
    assert rep3.outcomes == [None]
    assert rep3.meta["engines_compiled"] == 0
    rep4 = s22.serve([Job(MICRO, max_depth=5, label="m5")])
    o4 = rep4.outcomes[0]
    assert o4.status == "done" and rep4.meta["resumed_jobs"] == 1
    assert rep4.meta["engines_compiled"] == 0
    assert rep4.meta["wave_state_shards"] == 2
    _same(o4.res, cached_explore(MICRO, max_depth=5))

    # 2x2 park -> single-device resume ("1x1" resolves to no mesh)
    rep5 = s22.serve([Job(MICRO, max_depth=4, label="m4")],
                     stop=parked)
    assert rep5.outcomes == [None]
    assert rep5.meta["engines_compiled"] == 0
    rep6 = s11.serve([Job(MICRO, max_depth=4, label="m4")])
    o6 = rep6.outcomes[0]
    assert o6.status == "done" and rep6.meta["resumed_jobs"] == 1
    assert rep6.meta["wave_devices"] == 1
    _same(o6.res, cached_explore(MICRO, max_depth=4))


@pytest.mark.smoke
def test_watch_renders_wave_occupancy(tmp_path):
    """tools/watch.py renders the wave block as devices x lanes with
    the idle-lane waste as pad N/M, in any view that carries it."""
    from raft_tla_tpu.obs.heartbeat import Heartbeat
    spec = importlib.util.spec_from_file_location(
        "watch_wave", os.path.join(_REPO, "tools", "watch.py"))
    watch = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(watch)
    hb_path = str(tmp_path / "hb.json")
    Heartbeat(hb_path).beat(depth=4, states=100, extra={
        "jobs": {"r4": {"depth": 4, "distinct": 29,
                        "status": "running"}},
        "wave": {"devices": 4, "lanes": 8, "filled": 6, "pad": 2,
                 "jobs_per_device": 2}})
    line, code = watch.status_line(hb_path, None, stale_s=300)
    assert code == 0
    assert "wave: 4 devices x 2 lanes/device  6 jobs  pad 2/8" in line
    # daemon view: the same block renders next to the daemon lines
    hb2 = str(tmp_path / "hb2.json")
    Heartbeat(hb2).beat(depth=2, states=9, status="serving", extra={
        "daemon": {"status": "serving", "cycles": 1},
        "wave": {"devices": 2, "lanes": 16, "filled": 16, "pad": 0,
                 "jobs_per_device": 8}})
    line2, _ = watch.status_line(hb2, None, 300)
    assert "wave: 2 devices x 8 lanes/device  16 jobs  pad 0/16" \
        in line2
    assert "daemon serving" in line2
    # 2-D grid: devices/state_shards = the J axis, rendered as a grid
    hb4 = str(tmp_path / "hb4.json")
    Heartbeat(hb4).beat(depth=4, states=50, extra={
        "wave": {"devices": 4, "lanes": 8, "filled": 6, "pad": 2,
                 "jobs_per_device": 2, "state_shards": 2}})
    line4, _ = watch.status_line(hb4, None, 300)
    assert "wave: 2x2 grid  6 jobs  pad 2/8  state shards 2" in line4
    # heartbeats without a wave block render exactly as before
    hb3 = str(tmp_path / "hb3.json")
    Heartbeat(hb3).beat(depth=2, states=9)
    line3, _ = watch.status_line(hb3, None, 300)
    assert "wave:" not in line3
