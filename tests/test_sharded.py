"""Differential tests for the ownership-partitioned sharded engine on
the 8-virtual-device CPU mesh (conftest.py provisions it).

The sharded engine partitions the visited/level fingerprint sets by
hash ownership and routes candidates over ``all_to_all`` (SURVEY
§2.14, TLC's partitioned fingerprint table).  Step partitioning
differs from the single-device engine, but claim ranks are canonical
(enumeration-order within each receive window — mesh.py docstring), and
the full-constraint test below pins oracle count-parity even under the
counter-dependent constraint set; the micro configs here use VIEW-only
constraint sets where parity is order-insensitive by construction.
"""

from collections import Counter

import jax
import pytest

from raft_tla_tpu.config import Bounds, ModelConfig, NEXT_ASYNC
from raft_tla_tpu.models.explore import explore
from raft_tla_tpu.parallel.mesh import ShardedEngine

VIEW_CONSTRAINTS = ("BoundedInFlightMessages", "BoundedRequestVote",
                    "BoundedLogSize", "BoundedTerms")

MICRO = ModelConfig(
    n_servers=2, init_servers=(0, 1), values=(1,),
    max_inflight_override=2, next_family=NEXT_ASYNC, symmetry=False,
    constraints=VIEW_CONSTRAINTS,
    invariants=("ElectionSafety", "LogMatching"),
    bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                       max_client_requests=1))


def compare(cfg, max_depth=10 ** 9, **kw):
    want = explore(cfg, max_depth=max_depth)
    eng = ShardedEngine(cfg, chunk=64, **kw)
    got = eng.check(max_depth=max_depth)
    assert got.overflow_faults == 0
    assert got.distinct_states == want.distinct_states, \
        (got.distinct_states, want.distinct_states)
    assert got.depth == want.depth, (got.depth, want.depth)
    assert got.generated_states == want.generated_states
    want_viol = Counter(v.invariant for v in want.violations)
    got_viol = Counter(v.invariant for v in got.violations)
    assert got_viol == want_viol
    return eng, got


def test_sharded_uses_eight_devices():
    assert len(jax.devices()) == 8
    eng = ShardedEngine(MICRO, chunk=64, store_states=False)
    assert eng.D == 8


# round-15 tier-1 diet: the full-space exhaustive rep joins its
# symmetric twin in the slow tier — the mesh keeps fast oracle
# differentials via test_delta_matmul.test_mesh_delta_off_matches_oracle
# (depth-capped count parity) and test_resil's sharded-mesh chaos rep
# (end-to-end with resume), and the full-space behavior stays pinned by
# the slow siblings below
@pytest.mark.slow
def test_sharded_micro_exhaustive():
    compare(MICRO, store_states=False)


@pytest.mark.slow
def test_sharded_micro_symmetric():
    compare(MICRO.with_(symmetry=True), store_states=False)


@pytest.mark.slow
def test_sharded_growth_replay():
    """An undersized send window forces an sovf overflow; growth +
    exact replay must keep counts identical.  (Capacities are only
    mildly undersized: each growth replay re-runs every collective,
    and XLA's in-process CPU communicator aborts if its rendezvous
    watchdog fires under hundreds of slow 8-participant all_to_alls
    on this single-core host.)"""
    eng = ShardedEngine(MICRO, chunk=64, store_states=False,
                        lcap=8 * 256, scap=2)
    got = eng.check()
    want = explore(MICRO)
    assert got.distinct_states == want.distinct_states
    assert got.depth == want.depth
    assert got.generated_states == want.generated_states


@pytest.mark.slow
def test_sharded_reference_cfg_full_constraints():
    """The UNMODIFIED reference cfg — full DEFAULT_CONSTRAINTS
    including the counter-dependent BoundedRestarts / BoundedTimeouts /
    BoundedClientRequests / CleanStart* set (raft.cfg:37-49) — under
    the content-canonical survivor policy (VERDICT r3 #6; mesh.py
    module docstring):

    - a 4-device and an 8-device mesh (different chunk sizes, hence
      entirely different all_to_all window packings) land on IDENTICAL
      counts and level sizes at depth 16 — determinism by
      construction, not arrival order;
    - and both equal the sequential oracle exactly (on this config the
      content-min representative coincides with the oracle's
      first-seen one; the arrival-rank policy this replaced measured
      82,751 vs the oracle's 82,771 here — the policy, not luck, is
      what the first two assertions pin)."""
    from raft_tla_tpu.cfg.parser import load_model
    from conftest import ref_or_local
    cfg = load_model(
        ref_or_local("/root/reference/tlc_membership/raft.cfg"),
                     bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                                        max_client_requests=1))
    want = explore(cfg, max_depth=16)
    runs = {}
    for d in (4, 8):
        eng = ShardedEngine(cfg, devices=jax.devices()[:d],
                            chunk=16 * d, store_states=False)
        runs[d] = eng.check(max_depth=16)
    a, b = runs[4], runs[8]
    assert a.distinct_states == b.distinct_states, \
        (a.distinct_states, b.distinct_states)
    assert a.generated_states == b.generated_states
    assert a.level_sizes == b.level_sizes, (a.level_sizes, b.level_sizes)
    assert a.depth == b.depth == 16
    assert a.distinct_states == want.distinct_states
    assert a.generated_states == want.generated_states
    assert a.level_sizes == want.level_sizes


@pytest.mark.slow
def test_sharded_trace_mesh_invariant():
    """VERDICT r4 #9: witness PROVENANCE is mesh-invariant, not just
    counts — the canonical survivor key extends to (parent
    fingerprint, lane), so the same violation reproduced on a 4- and
    an 8-device mesh (different chunk and window packings) replays an
    action-by-action identical trace."""
    cfg = MICRO.with_(invariants=("FirstCommit",))
    chains = {}
    for d in (4, 8):
        eng = ShardedEngine(cfg, devices=jax.devices()[:d],
                            chunk=16 * d, store_states=True)
        got = eng.check(stop_on_violation=True)
        assert got.violations, f"FirstCommit witness not found (D={d})"
        chains[d] = eng.trace(got.violations[0].state_id)
    labels4 = [lbl for lbl, _s in chains[4]]
    labels8 = [lbl for lbl, _s in chains[8]]
    assert labels4 == labels8
    for (l4, s4), (l8, s8) in zip(chains[4], chains[8]):
        assert s4 == s8, f"state divergence at {l4}"


@pytest.mark.slow
def test_sharded_violation_and_trace():
    """Scenario property through the sharded engine: find the
    FirstCommit witness and reconstruct its trace across device-major
    global ids."""
    cfg = MICRO.with_(invariants=("FirstCommit",))
    eng = ShardedEngine(cfg, chunk=64, store_states=True)
    got = eng.check(stop_on_violation=True)
    assert got.violations, "FirstCommit witness not found"
    v = got.violations[0]
    chain = eng.trace(v.state_id)
    assert chain[0][0] == "Init"
    assert len(chain) >= 10          # election + replication + commit
    labels = [lbl for lbl, _ in chain]
    assert any(lbl.startswith("ClientRequest") for lbl in labels)
    assert any(lbl.startswith("AdvanceCommitIndex") for lbl in labels)
