"""Random-walk simulation engine tests (sim/walker, parallel/sim_mesh).

Determinism: a fixed --seed replays bit-identical trajectories across
runs and across --walkers shardings (per-walker streams are keyed by
GLOBAL walker id, never by fleet shape).

Differential: the oracle random-walk twin (models/explore) replays the
engine's witness step-for-step — every engine transition is an oracle
transition, and the per-step enabled-lane count (the uniform-sampling
surface) equals the oracle's successor count.
"""

import json

import numpy as np
import pytest

from raft_tla_tpu.config import Bounds, ModelConfig, NEXT_DYNAMIC
from raft_tla_tpu.models.explore import (oracle_validates_walk,
                                         random_walk, walk_enabled)
from raft_tla_tpu.sim import SimEngine

MICRO = ModelConfig(
    n_servers=2, init_servers=(0, 1), values=(1,),
    max_inflight_override=4,
    bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                       max_client_requests=1),
    symmetry=False, invariants=("FirstBecomeLeader",))

MEMBER = ModelConfig(
    n_servers=3, init_servers=(0, 1), values=(1,),
    next_family=NEXT_DYNAMIC, max_inflight_override=6,
    bounds=Bounds.make(max_log_length=2, max_timeouts=1,
                       max_client_requests=1, max_membership_changes=1),
    symmetry=False, invariants=("MembershipChange",))


# ---------------------------------------------------------------------------
# unit layer (smoke: pure host/device helpers, no fleet compiles)
# ---------------------------------------------------------------------------

@pytest.mark.smoke
def test_select_enabled_unit():
    import jax.numpy as jnp
    from raft_tla_tpu.ops.kernels import select_enabled
    ok = jnp.asarray([False, True, False, True, True])
    assert int(select_enabled(ok, 0)) == 1
    assert int(select_enabled(ok, 1)) == 3
    assert int(select_enabled(ok, 2)) == 4
    assert int(select_enabled(jnp.zeros(5, bool), 0)) == -1


@pytest.mark.smoke
def test_bloom_estimate_monotone():
    from raft_tla_tpu.engine.fingerprint import bloom_estimate
    assert bloom_estimate(0, 16) == 0.0
    a, b = bloom_estimate(100, 16), bloom_estimate(1000, 16)
    assert 0 < a < b
    # sparse filters estimate ~bits/k
    assert abs(a - 100 / 2) / (100 / 2) < 0.01


@pytest.mark.smoke
def test_scenario_registry_shared():
    """The ONE scenario table (ops/vpredicates) is consistent with both
    predicate registries and carries the sim-reachable targets."""
    from raft_tla_tpu.models import predicates as OP
    from raft_tla_tpu.ops.vpredicates import (INVARIANTS,
                                              SCENARIO_PROPERTIES)
    for nm in SCENARIO_PROPERTIES:
        assert nm in INVARIANTS, nm
        assert nm in OP.INVARIANTS, nm
    assert "MembershipChangeCommits" in SCENARIO_PROPERTIES


@pytest.mark.smoke
def test_repo_local_cfg_parses_like_reference():
    """configs/tlc_membership mirrors the reference parse exactly
    (tests/test_cfg.py pins the reference file when that tree exists;
    this repo-local twin is what the CLI runs against here)."""
    from raft_tla_tpu.cfg.parser import load_model
    cfg = load_model("configs/tlc_membership/raft.cfg")
    assert cfg.n_servers == 3 and cfg.init_servers == (0, 1, 2)
    assert cfg.values == (1, 2) and cfg.symmetry
    assert len(cfg.constraints) == 12
    b = cfg.bounds
    assert (b.max_log_length, b.max_restarts, b.max_timeouts,
            b.max_client_requests, b.max_terms,
            b.max_membership_changes, b.max_trace) == (5, 2, 3, 3, 4, 3,
                                                       24)
    assert cfg.max_inflight == 18


@pytest.mark.smoke
def test_cli_target_validation_uses_registry(capsys):
    """trace/simulate --target validation and its error text come from
    the active spec's registry (SpecIR.scenario_properties), not a
    hand-kept string."""
    from raft_tla_tpu.cli import _check_target
    from raft_tla_tpu.spec import get_spec
    raft = get_spec("raft")
    assert _check_target("MembershipChangeCommits", raft)
    assert _check_target("ElectionSafety", raft)   # safety hunts legal
    assert not _check_target("NoSuchScenario", raft)
    err = capsys.readouterr().err
    assert "MembershipChangeCommits" in err
    assert "LeaderChangesDuringConfChange" in err
    # per-spec: the same unknown name errors with the paxos registry
    paxos = get_spec("paxos")
    assert _check_target("ValueChosen", paxos)
    assert not _check_target("MembershipChangeCommits", paxos)
    err = capsys.readouterr().err
    assert "spec 'paxos'" in err and "ValueChosen" in err


@pytest.mark.smoke
def test_oracle_random_walk_micro():
    """The plain-Python twin on its own: finds the shallow scenario,
    and its trace replays as an oracle behavior by construction."""
    r = random_walk(MICRO, steps=4000, max_depth=16, seed=3,
                    resample_pruned=True)
    assert r.hits, "FirstBecomeLeader should be an easy find"
    assert r.hit_trace and r.hit_trace[-1].startswith("BecomeLeader")


# ---------------------------------------------------------------------------
# engine determinism
# ---------------------------------------------------------------------------

def _final_carry(eng, steps):
    st = eng.fresh_carry()
    return eng._dispatch(st, steps)


# determinism runs use a hit-free target set: a hit stops the WHOLE
# fleet early, so fleets of different widths would truncate at
# different iteration counts and trajectories could not be compared
FREE = MICRO.with_(invariants=())


def test_sim_fixed_seed_bit_identical():
    """Same seed, same fleet -> bit-identical trajectories and stats
    across two fresh runs."""
    eng = SimEngine(FREE, walkers=8, max_depth=12, seed=7,
                    bloom_bits=12)
    a = _final_carry(eng, 40)
    b = _final_carry(eng, 40)
    for k in ("traj", "depth", "hit", "hit_depth", "stats"):
        assert np.array_equal(np.asarray(a[k]), np.asarray(b[k])), k


def test_sim_sharding_invariant_streams():
    """Walker w's trajectory depends only on its GLOBAL id: a W=16
    fleet and a W=8 fleet with wid_base=8 (the mesh shard layout)
    produce identical walks for walkers 8..15."""
    full = SimEngine(FREE, walkers=16, max_depth=12, seed=7,
                     bloom_bits=12)
    half = SimEngine(FREE, walkers=8, max_depth=12, seed=7,
                     bloom_bits=12, wid_base=8)
    a = _final_carry(full, 25)
    b = _final_carry(half, 25)
    assert np.array_equal(np.asarray(a["traj"])[:, 8:],
                          np.asarray(b["traj"]))
    assert np.array_equal(np.asarray(a["depth"])[8:],
                          np.asarray(b["depth"]))


def test_sim_fleet_matches_single_device():
    """The pmapped fleet (2 virtual CPU devices) produces exactly the
    single-device fleet's trajectories — sharding is invisible."""
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("needs the multi-device CPU mesh (conftest)")
    from raft_tla_tpu.parallel.sim_mesh import ShardedSimEngine
    single = SimEngine(FREE, walkers=16, max_depth=12, seed=5,
                       bloom_bits=12)
    fleet = ShardedSimEngine(FREE, walkers=16,
                             devices=jax.devices()[:2],
                             max_depth=12, seed=5, bloom_bits=12)
    a = _final_carry(single, 25)
    st = fleet.fresh_carry()
    b = fleet._pdisp(st, 25, True)
    traj = np.asarray(b["traj"])            # [D, R, Wd]
    merged = np.concatenate([traj[d] for d in range(2)], axis=1)
    assert np.array_equal(np.asarray(a["traj"]), merged)


# ---------------------------------------------------------------------------
# oracle twin: step-for-step agreement + seed handoff
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def member_hit():
    eng = SimEngine(MEMBER, walkers=16, max_depth=30, seed=1,
                    bloom_bits=14)
    r = eng.run(steps=4000, steps_per_dispatch=256)
    assert r.hits, "MembershipChange walk found no witness"
    h = eng.decode_hit(r.hits[0])
    return eng, r, h


def test_sim_witness_oracle_step_for_step(member_hit):
    """Every engine step is an oracle transition (state equality modulo
    bag-slot order) AND the sampling surfaces agree: per-step engine
    enabled-lane count == oracle successor count."""
    eng, _r, h = member_hit
    states = [sv for _lbl, sv in h.trace]
    labels = oracle_validates_walk(MEMBER, states)
    assert len(labels) == h.depth
    # enabled-count parity along the walk (the uniform-choice surface)
    from raft_tla_tpu.models.explore import _walk_key
    from raft_tla_tpu.models.raft import init_state
    from raft_tla_tpu.ops.codec import decode, encode
    arrs = {k: np.asarray(v)
            for k, v in encode(eng.lay, *init_state(MEMBER)).items()}
    sv, hh = init_state(MEMBER)
    for lane in h.lanes[:8]:          # prefix is enough; O(A) per step
        succ = walk_enabled(sv, hh, MEMBER)
        enabled = eng.expander.expand_one(arrs)
        assert len(enabled) == len(succ)
        arrs = [a for (lbl, a) in enabled
                if lbl == eng.labels[lane]][0]
        want = _walk_key(decode(eng.lay, arrs)[0])
        match = [(s2, h2) for _lb, s2, h2 in succ
                 if _walk_key(s2) == want]
        assert match, "engine step is not an oracle successor"
        sv, hh = match[0]


def test_sim_seed_feeds_punctuated_check(member_hit, tmp_path):
    """The emitted --seed-trace file is accepted by check --seed-trace
    (simulation feeds punctuated exhaustive search) and seeds the
    engine with EXACT non-VIEW lanes."""
    eng, _r, h = member_hit
    from raft_tla_tpu.models.raft import state_to_obj
    from raft_tla_tpu.ops.codec import NONVIEW_KEYS
    obj = state_to_obj(h.trace[-1][1], h.hist)
    obj["nonview"] = {k: np.asarray(h.state_arrs[k]).tolist()
                      for k in NONVIEW_KEYS}
    seed_file = tmp_path / "seed.json"
    seed_file.write_text(json.dumps(obj))

    from raft_tla_tpu.cli import _engine_seed_arrays, _load_seeds
    from raft_tla_tpu.spec import get_spec
    _oracle_seeds, raw = _load_seeds(str(seed_file), get_spec("raft"))
    seeds = _engine_seed_arrays(MEMBER, get_spec("raft"), raw)
    assert np.array_equal(seeds[0]["ctr"],
                          np.asarray(h.state_arrs["ctr"]))
    from raft_tla_tpu.engine.bfs import Engine
    bfs = Engine(MEMBER.with_(invariants=()), chunk=64)
    got = bfs.check(max_depth=1, seed_states=seeds)
    assert got.distinct_states >= 1
    assert got.generated_states >= got.distinct_states


def test_sim_bloom_reports_coverage(member_hit):
    """The novelty Bloom estimate is positive, finite and bounded by
    the walker-step count (it can only undercount distinct states)."""
    _eng, r, _h = member_hit
    assert 0 < r.est_distinct_states <= r.walker_steps + r.walkers
    assert not r.bloom_saturated


def test_sim_root_violation_reported_at_depth_zero():
    """A target already violated at Init is reported as a depth-0 hit
    (the step loop checks successors only; the root gets its own check
    — parity with check/trace, which report depth-0 violations)."""
    cfg = MICRO.with_(invariants=("BoundedTrace",),
                      bounds=Bounds.make(max_log_length=1,
                                         max_timeouts=1,
                                         max_client_requests=1,
                                         max_trace=-1))
    eng = SimEngine(cfg, walkers=4, max_depth=8, seed=0, bloom_bits=10)
    r = eng.run(steps=50)
    assert r.hits and r.hits[0].depth == 0
    h = eng.decode_hit(r.hits[0])
    assert [lbl for lbl, _sv in h.trace] == ["Init"]
    assert random_walk(cfg, steps=10).hit_trace == []


def test_sim_tlc_policy_runs():
    """The TLC-parity policy (no resampling, root restarts) runs and
    restarts aggressively under the Clean-start constraints."""
    eng = SimEngine(MICRO, walkers=8, max_depth=12, seed=2,
                    policy="tlc", bloom_bits=12)
    r = eng.run(steps=60, steps_per_dispatch=60, stop_on_hit=False)
    assert r.steps_dispatched == 60
    assert r.sampled_steps >= r.walker_steps
    assert r.restarts > 0            # Clean-start prunes abandon walks
    assert r.promotions == 0         # no progress bases under tlc
