"""Differential tests for the host-spill engine (engine/spill):
identical distinct-state counts, depths, generated counts, violations
and traces vs the Python oracle and the classic device-resident engine
— with segment capacities squeezed so every spill/trip path runs.

The spill engine's claim (module docstring) is bit-exact parity with
the classic engine below the HBM wall; these tests pin it where both
can run.  Beyond-the-wall behavior is exercised on hardware by
tools/deep_run.py (BASELINE.md round 4)."""

import numpy as np
import pytest

from raft_tla_tpu.config import Bounds, ModelConfig, NEXT_ASYNC, \
    NEXT_ASYNC_CRASH
from raft_tla_tpu.engine.spill import SpillEngine
from raft_tla_tpu.models.explore import explore

MICRO = ModelConfig(
    n_servers=2, init_servers=(0, 1), values=(1,),
    next_family=NEXT_ASYNC, symmetry=True, max_inflight_override=4,
    bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                       max_client_requests=1))


def _match(r, want):
    assert r.distinct_states == want.distinct_states
    assert r.depth == want.depth
    assert r.generated_states == want.generated_states
    assert len(r.violations) == len(want.violations)
    assert r.level_sizes == want.level_sizes


def test_spill_micro_exhaustive_tiny_segments():
    """seg barely above the floor forces a spill nearly every window;
    counts must still match the oracle exactly (enumeration-order
    parity: host pruning/segmentation must not change first-seen)."""
    want = explore(MICRO)
    eng = SpillEngine(MICRO, chunk=64, store_states=False,
                      seg=1 << 10, vcap=1 << 12, sync_every=2)
    r = eng.check()
    _match(r, want)
    assert r.dedup_hit_rate > 0


@pytest.mark.slow
def test_spill_matches_classic_engine_and_traces():
    """store_states path: archives merge across spills; trace() must
    reproduce the oracle's witness semantics (constraints + violation
    on the reference cfg micro model)."""
    cfg = MICRO.with_(invariants=("FirstBecomeLeader",))
    want = explore(cfg, stop_on_violation=True, trace_violations=True)
    eng = SpillEngine(cfg, chunk=64, store_states=True,
                      seg=1 << 10, vcap=1 << 12, sync_every=2)
    r = eng.check(stop_on_violation=True)
    assert r.violations and want.violations
    assert r.violations[0].invariant == "FirstBecomeLeader"
    tr = eng.trace(r.violations[0].state_id)
    # same depth and an equally-long witness as the oracle's
    assert len(tr) - 1 == len(want.violations[0].trace)
    assert tr[0][0] == "Init"


@pytest.mark.slow
def test_spill_constraint_pruning_parity():
    """Host-side prune-not-expand: pruned states are counted and
    checked but not expanded — counts match the oracle on a config
    where constraints bite (BoundedTerms etc. active)."""
    cfg = ModelConfig(
        n_servers=2, init_servers=(0, 1), values=(1,),
        next_family=NEXT_ASYNC_CRASH, symmetry=False,
        max_inflight_override=4,
        bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                           max_restarts=1, max_client_requests=1))
    want = explore(cfg)
    eng = SpillEngine(cfg, chunk=64, store_states=False,
                      seg=1 << 11, vcap=1 << 13, sync_every=3)
    r = eng.check()
    _match(r, want)


@pytest.mark.slow
def test_spill_fovf_growth_replay():
    """Deliberately-tiny family caps trip fovf; the chunk-local
    grow-and-replay must preserve exact counts."""
    want = explore(MICRO)
    eng = SpillEngine(MICRO, chunk=64, store_states=False,
                      seg=1 << 10, vcap=1 << 12, fcap=64, sync_every=2)
    # squeeze the per-family caps to force the fovf path
    eng.FAM_CAPS = tuple(min(c, 16) for c in eng.FAM_CAPS)
    r = eng.check()
    _match(r, want)


@pytest.mark.slow
def test_spill_checkpoint_resume_identical(tmp_path):
    """Interrupt at a mid-run level, resume, land on counts identical
    to an uninterrupted run — the insurance the hours-scale
    beyond-the-wall runs need (VERDICT r4 #2; TLC's states/ dir)."""
    cfg = MICRO.with_(invariants=("ElectionSafety",))
    e_full = SpillEngine(cfg, chunk=64, store_states=True,
                         seg=1 << 10, vcap=1 << 12, sync_every=2)
    full = e_full.check()

    ckpt = str(tmp_path / "spill.ckpt")
    e1 = SpillEngine(cfg, chunk=64, store_states=True,
                     seg=1 << 10, vcap=1 << 12, sync_every=2)
    part = e1.check(max_depth=10, checkpoint_path=ckpt)
    assert part.depth == 10
    assert part.distinct_states < full.distinct_states

    e2 = SpillEngine(cfg, chunk=64, store_states=True,
                     seg=1 << 10, vcap=1 << 12, sync_every=2)
    resumed = e2.check(resume_from=ckpt)
    assert resumed.distinct_states == full.distinct_states
    assert resumed.depth == full.depth
    assert resumed.generated_states == full.generated_states
    assert resumed.level_sizes == full.level_sizes
    # archives survive the resume: every state reconstructible
    assert sum(len(p) for p in e2._parents) == full.distinct_states
    # the parent chain replays across the checkpoint boundary
    gid = full.distinct_states - 1
    assert [lbl for lbl, _s in e2.trace(gid)] == \
        [lbl for lbl, _s in e_full.trace(gid)]


@pytest.mark.slow
def test_spill_checkpoint_cross_engine_rejected(tmp_path):
    """Spill checkpoints resume only on SpillEngine; classic Engine
    files are rejected symmetrically (distinct wavefront layouts)."""
    from raft_tla_tpu.engine.bfs import CheckpointError, Engine
    ckpt = str(tmp_path / "spill.ckpt")
    SpillEngine(MICRO, chunk=64, store_states=False, seg=1 << 10,
                vcap=1 << 12).check(max_depth=6, checkpoint_path=ckpt)
    with pytest.raises(CheckpointError, match="host-spill"):
        Engine(MICRO, chunk=64, store_states=False).check(
            resume_from=ckpt)
    classic = str(tmp_path / "classic.ckpt")
    Engine(MICRO, chunk=64, store_states=False).check(
        max_depth=6, checkpoint_path=classic)
    with pytest.raises(CheckpointError, match="not a SpillEngine"):
        SpillEngine(MICRO, chunk=64, store_states=False, seg=1 << 10,
                    vcap=1 << 12).check(resume_from=classic)


@pytest.mark.slow
def test_spill_table_growth_midrun():
    """vcap small enough that the visited table must rehash-grow
    between segments."""
    cfg = MICRO.with_(bounds=Bounds.make(max_log_length=2,
                                         max_timeouts=1,
                                         max_client_requests=2))
    want = explore(cfg)
    eng = SpillEngine(cfg, chunk=64, store_states=False,
                      seg=1 << 10, vcap=1 << 10, sync_every=2)
    r = eng.check()
    _match(r, want)
    assert eng.VCAP > 1 << 10        # growth actually happened
