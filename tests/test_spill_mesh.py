"""Differential tests for the spill-composed sharded engine
(parallel/spill_mesh): per-device level shards stream through host RAM
while dedup stays hash-partitioned over all_to_all — the mesh scale
story and the host-spill depth story in one engine (VERDICT r4 #5).

Shard capacities are squeezed far below the level sizes so every run
here exercises mid-level spills and step-atomic trip recovery; counts
must still match the oracle exactly (the micro configs use VIEW-only
constraint sets, where the surviving representative's non-VIEW content
cannot affect reachability — spill_mesh module docstring)."""

from collections import Counter

import jax
import pytest

from raft_tla_tpu.config import Bounds, ModelConfig, NEXT_ASYNC
from raft_tla_tpu.models.explore import explore
from raft_tla_tpu.parallel.spill_mesh import SpilledShardedEngine

VIEW_CONSTRAINTS = ("BoundedInFlightMessages", "BoundedRequestVote",
                    "BoundedLogSize", "BoundedTerms")

MICRO = ModelConfig(
    n_servers=2, init_servers=(0, 1), values=(1,),
    max_inflight_override=2, next_family=NEXT_ASYNC, symmetry=False,
    constraints=VIEW_CONSTRAINTS,
    invariants=("ElectionSafety", "LogMatching"),
    bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                       max_client_requests=1))


@pytest.mark.slow
def test_spilled_sharded_micro_exhaustive():
    """Exhaustive micro parity: counts, level sizes and violations
    equal the oracle through the composed engine (spill plumbing end
    to end; the capacity/mid-level-spill claim is pinned by
    test_spilled_sharded_beyond_shard_capacity below on a space big
    enough to overflow shards)."""
    want = explore(MICRO)
    eng = SpilledShardedEngine(MICRO, devices=jax.devices()[:2],
                               chunk=16, lcap=128, scap=8,
                               vcap=1 << 13)
    got = eng.check()
    assert got.distinct_states == want.distinct_states, \
        (got.distinct_states, want.distinct_states)
    assert got.depth == want.depth
    assert got.generated_states == want.generated_states
    assert got.level_sizes == want.level_sizes
    want_viol = Counter(v.invariant for v in want.violations)
    got_viol = Counter(v.invariant for v in got.violations)
    assert got_viol == want_viol


@pytest.mark.slow
def test_spilled_sharded_beyond_shard_capacity():
    """The done-criterion run (VERDICT r4 #5): an 8-device mesh on the
    reference cfg whose level rows exceed the mesh's usable shard
    capacity — levels stream through host RAM in multiple mid-level
    spill epochs (ovf trips), counts equal the oracle.  Constraints
    are restricted to the VIEW-only set so the epoch-min survivor
    policy provably cannot affect reachability (spill_mesh module
    docstring)."""
    from raft_tla_tpu.cfg.parser import load_model
    from conftest import ref_or_local
    cfg = load_model(
        ref_or_local("/root/reference/tlc_membership/raft.cfg"),
                     bounds=Bounds.make(max_log_length=1,
                                        max_timeouts=1,
                                        max_client_requests=1))
    cfg = cfg.with_(constraints=VIEW_CONSTRAINTS, invariants=())
    want = explore(cfg, max_depth=14)
    eng = SpilledShardedEngine(cfg, chunk=64, lcap=8 * 512, scap=16,
                               fcap=512, vcap=1 << 15)
    got = eng.check(max_depth=14)
    assert got.distinct_states == want.distinct_states, \
        (got.distinct_states, want.distinct_states)
    assert got.generated_states == want.generated_states
    assert got.level_sizes == want.level_sizes
    # the run genuinely could not fit device-resident: the widest
    # level exceeds the mesh's TOTAL shard capacity, and the ovf-trip
    # mid-level spill path fired repeatedly
    assert max(want.level_sizes) > eng.D * eng.LB, \
        (max(want.level_sizes), eng.D, eng.LB)
    assert eng.mid_level_spills > 2, eng.mid_level_spills


@pytest.mark.slow
def test_spilled_sharded_symmetric():
    want = explore(MICRO.with_(symmetry=True))
    eng = SpilledShardedEngine(MICRO.with_(symmetry=True), chunk=64,
                               lcap=8 * 192, vcap=1 << 13)
    got = eng.check()
    assert got.distinct_states == want.distinct_states
    assert got.depth == want.depth
    assert got.generated_states == want.generated_states


@pytest.mark.slow
def test_spilled_sharded_matches_device_resident():
    """Same model, same mesh: the composed engine's counts equal the
    classic device-resident ShardedEngine's (which in turn equal the
    oracle's) — the composition changes WHERE levels live, not what is
    reachable."""
    from raft_tla_tpu.parallel.mesh import ShardedEngine
    classic = ShardedEngine(MICRO, chunk=64,
                            store_states=False).check(max_depth=14)
    eng = SpilledShardedEngine(MICRO, chunk=64, lcap=8 * 192,
                               vcap=1 << 13)
    got = eng.check(max_depth=14)
    assert got.distinct_states == classic.distinct_states
    assert got.generated_states == classic.generated_states
    assert got.level_sizes == classic.level_sizes


@pytest.mark.slow
def test_spilled_sharded_mesh_size_invariance():
    """D=4 vs D=8, different chunk packings and spill timings: counts
    agree (VIEW-only constraints — representative-choice independent)."""
    runs = {}
    for d in (4, 8):
        eng = SpilledShardedEngine(MICRO, devices=jax.devices()[:d],
                                   chunk=16 * d, lcap=d * 192,
                                   vcap=1 << 13)
        runs[d] = eng.check(max_depth=14)
    assert runs[4].distinct_states == runs[8].distinct_states
    assert runs[4].generated_states == runs[8].generated_states
    assert runs[4].level_sizes == runs[8].level_sizes


def test_spilled_sharded_store_states_accepted():
    """store_states no longer raises (ROADMAP item closed), and since
    round 12 neither does checkpointing — the last engine without
    checkpoint/resume gained it; the checkpoint format and the full
    resume differentials are pinned in tests/test_resil.py (shared
    engine fixture, so no extra compile here)."""
    eng = SpilledShardedEngine(MICRO, chunk=64, store_states=True)
    assert eng.store_states
    assert hasattr(eng, "_save_spill_mesh_checkpoint")


@pytest.mark.slow
def test_spilled_sharded_host_table_parity():
    """Host-partitioned table composed with mesh dedup (ISSUE 1): each
    device's authoritative visited set moves to a per-device
    prefix-partitioned host table while hash-ownership keeps routing
    keys — with dev_keys squeezed far below the distinct count so the
    per-device caches reseed and the host sweep is what drops
    old-level keys, counts must equal the un-composed engine's
    bit-identically."""
    want = explore(MICRO)
    base = SpilledShardedEngine(MICRO, chunk=64, lcap=8 * 192,
                                vcap=1 << 13)
    ref = base.check()
    eng = SpilledShardedEngine(MICRO, chunk=64, lcap=8 * 192,
                               vcap=1 << 13, host_table=True,
                               partitions=4, part_cap=1 << 6,
                               dev_keys=32)
    got = eng.check()
    assert got.distinct_states == want.distinct_states
    assert got.depth == want.depth
    assert got.generated_states == want.generated_states
    assert got.level_sizes == want.level_sizes
    assert (got.distinct_states, got.level_sizes) == \
        (ref.distinct_states, ref.level_sizes)
    # the per-device host tables jointly hold every distinct key, and
    # ownership keeps them disjoint
    assert sum(t.n_keys for t in eng.hpts) == want.distinct_states
    want_viol = Counter(v.invariant for v in want.violations)
    got_viol = Counter(v.invariant for v in got.violations)
    assert got_viol == want_viol


@pytest.mark.slow
def test_spilled_sharded_store_states_archive_parity(tmp_path):
    """SpilledShardedEngine.store_states (ROADMAP open item): the
    spilled blocks compose into engine/archive per-level memmaps in
    gid order.  Parity is against the UNSHARDED engine's archive rows
    on the canonical VIEW content (the spill-mesh epoch-min survivor
    policy may pick different non-VIEW representatives, and bag-slot
    order is not state identity — spill_mesh module docstring), plus a
    full witness-trace replay from the memmaps."""
    import numpy as np
    from raft_tla_tpu.engine.bfs import Engine
    from raft_tla_tpu.models.explore import _walk_key
    from raft_tla_tpu.ops.codec import decode

    depth = 8
    ref = Engine(MICRO, chunk=64, store_states=True,
                 archive_dir=str(tmp_path / "ref"))
    want = ref.check(max_depth=depth)

    def key(eng, g):
        return _walk_key(decode(eng.lay, eng.get_state_arrays(g))[0])

    n = want.distinct_states
    rows_ref = sorted(key(ref, g) for g in range(n))

    eng = SpilledShardedEngine(MICRO, devices=jax.devices()[:2],
                               chunk=16, lcap=128, scap=8,
                               vcap=1 << 13, store_states=True,
                               archive_dir=str(tmp_path / "mesh"))
    got = eng.check(max_depth=depth)
    assert got.distinct_states == n
    assert sorted(key(eng, g) for g in range(n)) == rows_ref
    # memmap-walking trace replays to Init with a valid parent chain
    tr = eng.trace(n - 1)
    assert tr[0][0] == "Init"
    assert 2 <= len(tr) <= depth + 1
    # in-RAM backing takes the same path minus the memmaps
    eng2 = SpilledShardedEngine(MICRO, devices=jax.devices()[:2],
                                chunk=16, lcap=128, scap=8,
                                vcap=1 << 13, store_states=True)
    got2 = eng2.check(max_depth=depth)
    assert got2.distinct_states == n
    assert sorted(key(eng2, g) for g in range(n)) == rows_ref
