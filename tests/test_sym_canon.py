"""Orbit-sort symmetry canonicalization (round 15).

The sort canonicalizer (engine/fingerprint) must induce EXACTLY the
orbit partition of the P-fold min-over-perms on every config shape:
equivariant per-server signatures + argsort pick one canonical
relabeling, adjacent-transposition certificates verify signature
ties, and any uncertified tie (a WL-hard state) falls back to the
full min-over-perms.  These tests pin the partition against the
oracle's ``symmetry_perms`` canonicalization, the hard-fallback
trigger on constructed WL-hard fixtures, the cross-mode checkpoint
refusal, the mesh chunk rounding, and the sim Bloom staying
canonical at S=5 (P=120)."""

import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tla_tpu.config import (Bounds, ModelConfig, NEXT_ASYNC,
                                 NEXT_DYNAMIC)
from raft_tla_tpu.engine.fingerprint import (Fingerprinter,
                                             resolve_sym_canon)
from raft_tla_tpu.models.explore import canonicalize, symmetry_perms
from raft_tla_tpu.models.raft import init_state
from raft_tla_tpu.ops.codec import encode, stack
from raft_tla_tpu.ops.layout import Layout

# S=3 with a 2-server init block: the perm group is the inside x
# outside block product (models/explore.symmetry_perms), so this pins
# the per-block argsort + per-block salts
DYN3 = ModelConfig(
    n_servers=3, init_servers=(0, 1), values=(1,),
    next_family=NEXT_DYNAMIC, max_inflight_override=6,
    bounds=Bounds.make(max_log_length=2, max_timeouts=1,
                       max_client_requests=1,
                       max_membership_changes=1),
    symmetry=True)

# BASELINE config #5 shape: Server=5 all-init (full S_5, P=120) —
# the group size where min-over-perms stops being viable
CFG5 = ModelConfig(
    n_servers=5, init_servers=(0, 1, 2, 3, 4), values=(1,),
    next_family=NEXT_ASYNC, max_inflight_override=4,
    bounds=Bounds.make(max_log_length=4, max_timeouts=3,
                       max_client_requests=3),
    symmetry=True)


def _partition(keys):
    groups = {}
    for i, k in enumerate(keys):
        groups.setdefault(k, []).append(i)
    return sorted(tuple(v) for v in groups.values())


def _raft_batch(cfg, pairs):
    lay = Layout(cfg)
    return stack([encode(lay, s, h) for s, h in pairs])


def _fp_partition(fpr, arrs):
    svb = {k: jnp.asarray(v) for k, v in arrs.items()}
    fp = np.asarray(jax.jit(fpr.fingerprint_batch)(svb))
    return _partition([tuple(r) for r in fp]), fp


@pytest.fixture(scope="module")
def fpr5():
    """The ONE (minperm, sort) fingerprinter pair at P=120 — the
    120-way vmap compiles once per shape, shared module-wide."""
    return (Fingerprinter(CFG5, "minperm"), Fingerprinter(CFG5, "sort"))


# ---------------------------------------------------------------------------
# mode resolution
# ---------------------------------------------------------------------------

@pytest.mark.smoke
def test_resolve_sym_canon():
    # symmetry off: there is no group to canonicalize over
    assert resolve_sym_canon(CFG5.with_(symmetry=False), "auto") \
        == "minperm"
    assert resolve_sym_canon(CFG5.with_(symmetry=False), "sort") \
        == "minperm"
    # auto: sort only past the tiny-group threshold
    assert resolve_sym_canon(DYN3, "auto") == "minperm"      # P = 2
    assert resolve_sym_canon(CFG5, "auto") == "sort"         # P = 120
    # explicit modes pass through
    assert resolve_sym_canon(DYN3, "sort") == "sort"
    assert resolve_sym_canon(CFG5, "minperm") == "minperm"
    with pytest.raises(ValueError, match="sym_canon"):
        resolve_sym_canon(DYN3, "fast")


@pytest.mark.smoke
def test_sort_mode_disables_incremental_fp():
    fpr = Fingerprinter(DYN3, "sort")
    assert fpr.sym_canon == "sort"
    assert not fpr.supports_incremental()
    assert Fingerprinter(DYN3, "minperm").supports_incremental()


# ---------------------------------------------------------------------------
# orbit-partition parity vs the oracle canonicalization
# ---------------------------------------------------------------------------

def test_orbit_partition_parity_dynamic_blocks():
    """NextDynamic S=3 (inside/outside perm blocks): the sort and
    minperm partitions both equal the oracle's min-over-perms orbit
    partition, and the two modes' VALUES differ (the mode-separation
    bijection)."""
    from conftest import cached_explore
    res = cached_explore(DYN3.with_(symmetry=False), max_depth=10 ** 9,
                         max_states=800, keep_states=True)
    pairs = list(res.states.values())
    perms = symmetry_perms(DYN3)
    po = _partition([canonicalize(s, perms, DYN3) for s, _h in pairs])
    arrs = _raft_batch(DYN3, pairs)
    pm, fm = _fp_partition(Fingerprinter(DYN3, "minperm"), arrs)
    ps, fs = _fp_partition(Fingerprinter(DYN3, "sort"), arrs)
    assert pm == po
    assert ps == po
    assert not np.array_equal(fm, fs)


def test_orbit_partition_parity_config5_shape(fpr5):
    """Config #5 shape (S=5 all-init, P=120), depth-capped: sort ≡
    minperm ≡ oracle on every reachable state, and the per-state
    fingerprint path matches the batch path."""
    from conftest import cached_explore
    fpr_m, fpr_s = fpr5
    res = cached_explore(CFG5.with_(symmetry=False), max_depth=3,
                         keep_states=True)
    pairs = list(res.states.values())
    assert len(pairs) > 100
    perms = symmetry_perms(CFG5)
    po = _partition([canonicalize(s, perms, CFG5) for s, _h in pairs])
    arrs = _raft_batch(CFG5, pairs)
    pm, _fm = _fp_partition(fpr_m, arrs)
    ps, fs = _fp_partition(fpr_s, arrs)
    assert pm == po
    assert ps == po
    one = {k: jnp.asarray(v[0]) for k, v in arrs.items()}
    f1 = np.asarray(jax.jit(fpr_s.fingerprint)(one))
    assert (f1 == fs[0]).all()


@pytest.mark.slow
def test_orbit_partition_parity_s5_deeper(fpr5):
    """Deeper S=5 sweep (the fast rep's full-space duplicate)."""
    from conftest import cached_explore
    fpr_m, fpr_s = fpr5
    res = cached_explore(CFG5.with_(symmetry=False), max_depth=4,
                         max_states=4000, keep_states=True)
    pairs = list(res.states.values())
    perms = symmetry_perms(CFG5)
    po = _partition([canonicalize(s, perms, CFG5) for s, _h in pairs])
    arrs = _raft_batch(CFG5, pairs)
    assert _fp_partition(fpr_m, arrs)[0] == po
    assert _fp_partition(fpr_s, arrs)[0] == po


def test_signature_tie_hard_fallback(fpr5):
    """WL-hard fixtures: servers identical except the vf functional
    graph.  1-WL refinement cannot rank them (every server has in/out
    degree 1), so the argsort tie is real and UNCERTIFIED — the
    min-over-perms fallback must fire, isomorphic 5-cycles must
    collide, and distinct cycle types must separate."""
    _fpr_m, fpr_s = fpr5

    def vf_state(vf):
        sv, h = init_state(CFG5)
        return sv._replace(vf=tuple(vf)), h

    fixtures = [
        vf_state((1, 2, 3, 4, 0)),    # 5-cycle i -> i+1
        vf_state((2, 3, 4, 0, 1)),    # 5-cycle i -> i+2 (isomorphic)
        vf_state((1, 2, 0, 4, 3)),    # 3-cycle + 2-cycle
        vf_state((1, 0, 2, 4, 3)),    # 2-cycle + fixed + 2-cycle
    ]
    perms = symmetry_perms(CFG5)
    po = _partition([canonicalize(s, perms, CFG5)
                     for s, _h in fixtures])
    assert po == [(0, 1), (2,), (3,)]
    arrs = _raft_batch(CFG5, fixtures)
    # (minperm parity at this P is pinned by the config-#5 test — no
    # second 120-way vmap compile at this batch shape)
    assert _fp_partition(fpr_s, arrs)[0] == po
    dbg = fpr_s.sort_debug(arrs)
    # every fixture carries an uncertifiable tie -> hard fallback
    assert dbg["tie"].all()
    assert dbg["hard"].all()


def test_paxos_partition_parity():
    """Paxos full-S_N sort (affine owned-bit salt map): the sort
    partition equals min-over-perms on the stock model's reachable
    prefix; per-state equals batch."""
    from conftest import cached_explore
    from raft_tla_tpu.spec.paxos.config import PaxosConfig
    from raft_tla_tpu.spec.paxos import layout as pl
    from raft_tla_tpu.spec.paxos.fingerprint import PaxosFingerprinter
    from raft_tla_tpu.spec.paxos.layout import PaxosLayout
    cfg = PaxosConfig()
    res = cached_explore(cfg.with_(symmetry=False), max_depth=6,
                         keep_states=True)
    pairs = list(res.states.values())
    lay = PaxosLayout(cfg)
    rows = [pl.encode(lay, s, h) for s, h in pairs]
    arrs = {k: np.stack([r[k] for r in rows]) for k in rows[0]}
    fpr_s = PaxosFingerprinter(cfg, "sort")
    pm, fm = _fp_partition(PaxosFingerprinter(cfg, "minperm"), arrs)
    ps, fs = _fp_partition(fpr_s, arrs)
    assert pm == ps
    assert len(ps) < len(pairs)          # symmetry actually collapsed
    assert not np.array_equal(fm, fs)
    one = {k: jnp.asarray(v[0]) for k, v in arrs.items()}
    f1 = np.asarray(jax.jit(fpr_s.fingerprint)(one))
    assert (f1 == fs[0]).all()


# ---------------------------------------------------------------------------
# engine surface: checkpoint refusal, chunk rounding, sim Bloom
# ---------------------------------------------------------------------------

MICRO = ModelConfig(
    n_servers=2, init_servers=(0, 1), values=(1,),
    max_inflight_override=4, symmetry=True,
    bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                       max_client_requests=1))


def test_ckpt_read_refuses_cross_mode(tmp_path):
    """The serializer-level refusal (shared by every engine family):
    a minperm-stamped checkpoint handed to a sort engine raises a
    named CheckpointError BEFORE any array or compile is touched."""
    from raft_tla_tpu.engine.bfs import (CheckpointError, CheckResult,
                                         ckpt_read, ckpt_write)
    path = str(tmp_path / "mode.ckpt")
    meta = dict(cfg=repr(MICRO), chunk=64, spec="raft",
                sym_canon="minperm", depth=1, n_states=1, n_vis=1,
                n_front=1)
    ckpt_write(path, {"x": np.zeros(4, np.int32)}, False, [], [], [],
               CheckResult(), meta)
    with pytest.raises(CheckpointError,
                       match=r"--sym-canon minperm.*resolved sort"):
        ckpt_read(path, repr(MICRO), 64, (), sharded=False,
                  sym_canon="sort")
    # a legacy checkpoint (no sym_canon key) reads as minperm
    meta.pop("sym_canon")
    ckpt_write(path, {"x": np.zeros(4, np.int32)}, False, [], [], [],
               CheckResult(), meta)
    with pytest.raises(CheckpointError, match="--sym-canon minperm"):
        ckpt_read(path, repr(MICRO), 64, (), sharded=False,
                  sym_canon="sort")


@pytest.mark.slow
def test_checkpoint_refuses_cross_mode_resume(tmp_path):
    """End-to-end rep of the serializer-level refusal above: a real
    minperm run's checkpoint, a sort engine's refusal, and a
    same-mode resume that still works."""
    from raft_tla_tpu.engine.bfs import CheckpointError, Engine
    ckpt = str(tmp_path / "run.ckpt")
    Engine(MICRO, chunk=64, store_states=False,
           sym_canon="minperm").check(max_depth=6,
                                      checkpoint_path=ckpt)
    other = Engine(MICRO, chunk=64, store_states=False,
                   sym_canon="sort")
    with pytest.raises(CheckpointError,
                       match=r"--sym-canon minperm.*resolved sort"):
        other.check(resume_from=ckpt)
    # same mode resumes fine
    res = Engine(MICRO, chunk=64, store_states=False,
                 sym_canon="minperm").check(resume_from=ckpt)
    assert res.depth >= 6


@pytest.mark.smoke
def test_mesh_chunk_rounds_up_to_devices():
    from raft_tla_tpu.parallel import mesh
    assert mesh._round_chunk_to_devices(512, 8) == 512
    mesh._warned_uneven_chunk = False
    with pytest.warns(UserWarning, match="not a multiple"):
        assert mesh._round_chunk_to_devices(20, 8) == 24
    # warn-once: the second uneven call is silent
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert mesh._round_chunk_to_devices(20, 8) == 24
    mesh._warned_uneven_chunk = False


def test_sharded_engine_rounds_chunk():
    import jax as _jax
    from raft_tla_tpu.parallel import mesh
    from raft_tla_tpu.parallel.mesh import ShardedEngine
    from raft_tla_tpu.parallel.pjit_mesh import PjitShardedEngine
    devs = _jax.devices()
    mesh._warned_uneven_chunk = False
    with pytest.warns(UserWarning, match="rounded up"):
        eng = ShardedEngine(MICRO, devices=devs,
                            chunk=len(devs) * 8 - 1)
    assert eng.chunk == len(devs) * 8
    assert eng.BL == 8
    mesh._warned_uneven_chunk = False
    with pytest.warns(UserWarning, match="rounded up"):
        pe = PjitShardedEngine(MICRO, devices=devs,
                               chunk=len(devs) * 8 - 1)
    assert pe.chunk == len(devs) * 8
    mesh._warned_uneven_chunk = False


def test_sim_bloom_stays_canonical_at_s5():
    """P=120 used to force the novelty Bloom onto identity-perm
    fingerprints; under orbit-sort it stays canonical, with
    bit-identical stats across same-seed runs."""
    from raft_tla_tpu.sim import SimEngine
    cfg = CFG5.with_(invariants=(),
                     bounds=Bounds.make(max_log_length=1,
                                        max_timeouts=1,
                                        max_client_requests=1))
    eng = SimEngine(cfg, walkers=4, max_depth=8, seed=3,
                    bloom_bits=12)
    assert eng.bloom_canonical
    assert eng.fpr.sym_canon == "sort"
    st_a = eng._dispatch(eng.fresh_carry(), 12)
    st_b = eng._dispatch(eng.fresh_carry(), 12)
    for k in ("traj", "depth", "stats", "bloom"):
        assert np.array_equal(np.asarray(st_a[k]),
                              np.asarray(st_b[k])), k


@pytest.mark.smoke
def test_sim_forced_minperm_still_disables_and_names_flag():
    from raft_tla_tpu.sim import SimEngine
    cfg = CFG5.with_(invariants=(),
                     bounds=Bounds.make(max_log_length=1,
                                        max_timeouts=1,
                                        max_client_requests=1))
    with pytest.warns(UserWarning, match="--sym-canon sort"):
        eng = SimEngine(cfg, walkers=4, max_depth=8, seed=3,
                        bloom_bits=12, sym_canon="minperm")
    assert not eng.bloom_canonical
    assert eng.fpr.sym_canon == "minperm"
