"""tools/tlc_baseline.py: the real-TLC harness emits a faithful
cfg+tla pair for any ModelConfig and cleanly skips where Java is
absent (this image — BASELINE.md documents the 50x target awaits a
Java-equipped host running this tool)."""

import importlib.util
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

spec = importlib.util.spec_from_file_location(
    "tlc_baseline", os.path.join(REPO, "tools", "tlc_baseline.py"))
tb = importlib.util.module_from_spec(spec)
sys.modules["tlc_baseline"] = tb
spec.loader.exec_module(tb)


def test_emit_rewrites_bounds_and_mirrors_cfg(tmp_path):
    import pytest
    if not os.path.exists("/root/reference/tlc_membership/raft.cfg"):
        # emit vendors TypedBags.tla etc. from the full reference
        # checkout — the repo-local cfg twin cannot stand in here
        pytest.skip("reference spec tree not present in this container")
    from raft_tla_tpu.cfg.parser import load_model
    from raft_tla_tpu.config import Bounds
    cfg = load_model("/root/reference/tlc_membership/raft.cfg",
                     bounds=Bounds.make(max_log_length=2, max_timeouts=1,
                                        max_client_requests=1))
    cfg = cfg.with_(invariants=("ElectionSafety",))
    out = tmp_path / "model"
    tb.emit_tlc_model(cfg, str(out))
    tla = (out / "raft.tla").read_text()
    # in-spec bounds rewritten to the config's Bounds (SURVEY §5 tier b)
    assert "MaxLogLength == 2" in tla
    assert "MaxTimeouts == 1" in tla
    assert "MaxTerms == 2" in tla
    # vendored libraries ride along so TLC can resolve EXTENDS
    assert (out / "TypedBags.tla").exists()
    assert (out / "SequencesExt.tla").exists()
    gen = (out / "raft.cfg").read_text()
    assert "Server      = {s1, s2, s3}" in gen
    assert "SYMMETRY perms" in gen and "VIEW vars" in gen
    assert "NEXT NextAsyncCrash" in gen
    assert "BoundedInFlightMessages" in gen
    assert "ElectionSafety" in gen


def test_main_skips_cleanly_without_java(tmp_path):
    env = dict(os.environ, PATH="/nonexistent")  # hide any java
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "tlc_baseline.py"),
         "--out", str(tmp_path / "m")],
        capture_output=True, text=True, timeout=300, env=env)
    assert r.returncode == 0, (r.stdout, r.stderr)
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["status"] == "skipped"
    assert "java" in rec["reason"]
