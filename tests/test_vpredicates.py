"""Differential tests: vectorized predicates vs oracle predicates."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tla_tpu.config import Bounds, ModelConfig, NEXT_DYNAMIC
from raft_tla_tpu.models import predicates as OP
from raft_tla_tpu.models.explore import explore
from raft_tla_tpu.ops.codec import encode
from raft_tla_tpu.ops.kernels import RaftKernels
from raft_tla_tpu.ops.layout import Layout
from raft_tla_tpu.ops import vpredicates as VP

SMALL = ModelConfig(
    n_servers=2, init_servers=(0, 1), values=(1,),
    bounds=Bounds.make(max_log_length=2, max_timeouts=2),
    symmetry=False)

MEMBER = ModelConfig(
    n_servers=3, init_servers=(0, 1), values=(1,),
    next_family=NEXT_DYNAMIC,
    bounds=Bounds.make(max_log_length=2, max_timeouts=2),
    symmetry=False)

# scenario witnesses to enrich the sample with deep states
TARGETS = {
    "small": ("EntryCommitted", "FirstRestart"),
    "member": ("AddSucessful", "MembershipChangeCommits"),
}


def gather_sample(cfg, targets, n=150):
    res = explore(cfg, max_states=4000, keep_states=True)
    states = list(res.states.values())
    rng = np.random.RandomState(7)
    idx = rng.choice(len(states), size=min(n, len(states)), replace=False)
    sample = [states[i] for i in idx]
    for t in targets:
        deep = explore(cfg.with_(invariants=(t,)), stop_on_violation=True,
                       max_states=200_000)
        assert deep.violations
        sample.append((deep.violations[0].state, deep.violations[0].hist))
    return sample


@pytest.mark.parametrize("cfgname", ["small", "member"])
def test_predicates_differential(cfgname):
    cfg = {"small": SMALL, "member": MEMBER}[cfgname]
    lay = Layout(cfg)
    kern = RaftKernels(lay)
    preds = VP.Predicates(lay)
    sample = gather_sample(cfg, TARGETS[cfgname])
    batch = {k: jnp.asarray(np.stack(
        [encode(lay, sv, h)[k] for sv, h in sample]))
        for k in encode(lay, *sample[0])}

    names = list(VP.INVARIANTS) + list(VP.CONSTRAINTS)

    @jax.jit
    def run(batch):
        def one(sv):
            der = kern.derived(sv)
            out = {}
            for nm in VP.INVARIANTS:
                out[nm] = VP.INVARIANTS[nm].__get__(preds)(sv, der)
            for nm in VP.CONSTRAINTS:
                out[nm] = VP.CONSTRAINTS[nm].__get__(preds)(sv, der)
            return out
        return jax.vmap(one)(batch)

    got = {k: np.asarray(v) for k, v in run(batch).items()}
    bad = []
    for nm in names:
        ofn = OP.INVARIANTS.get(nm) or OP.CONSTRAINTS[nm]
        for s_idx, (sv, h) in enumerate(sample):
            want = bool(ofn(sv, h, cfg))
            if bool(got[nm][s_idx]) != want:
                bad.append((nm, s_idx, want, sv, h))
    assert not bad, (f"{len(bad)} verdict mismatches; first: "
                     f"{bad[0][0]} state#{bad[0][1]} want={bad[0][2]}\n"
                     f"state={bad[0][3]}\nhist={bad[0][4]}")
    # sanity: the sample actually exercises both verdicts somewhere
    assert any(not got[nm].all() for nm in names), \
        "sample never violates anything — too weak"
