"""Walker-throughput bench for the random-walk sim engine (sim/walker).

Measures steady-state walker-steps/sec on two workloads:

  small — the 3-server membership scenario shape (NextDynamic,
          InitServer ⊊ Server) the differential tests use;
  cfg5  — the BASELINE config #5 shape (Server=5, MaxTerm=4,
          MaxLogLen=4, NextDynamic) the sim engine exists for.

Both run HIT-FREE (no target invariant) so the number is pure
transition throughput — sampling, step fusion, predicates, fingerprint,
Bloom — not witness luck.  The platform is recorded verbatim: on this
CPU-only container the figures are an honest CPU fallback, not TPU
numbers (BASELINE.md round 7 carries the same label).

Usage:  python tools/bench_sim.py [out.json]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

OUT = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "BENCH_r06.json")


def build(name):
    from raft_tla_tpu.cfg.parser import load_model
    from raft_tla_tpu.config import Bounds, NEXT_DYNAMIC
    cfg = load_model("configs/tlc_membership/raft.cfg")
    if name == "small":
        return cfg.with_(
            n_servers=3, init_servers=(0, 1), next_family=NEXT_DYNAMIC,
            max_inflight_override=6, invariants=(),
            bounds=Bounds.make(max_log_length=2, max_timeouts=1,
                               max_client_requests=1,
                               max_membership_changes=1))
    if name == "cfg5":
        return cfg.with_(
            n_servers=5, init_servers=(0, 1, 2, 3, 4),
            next_family=NEXT_DYNAMIC, max_inflight_override=50,
            invariants=(),
            bounds=Bounds.make(max_log_length=4, max_timeouts=3,
                               max_client_requests=3, max_terms=4))
    raise SystemExit(name)


def measure(name, walkers, steps, warm=16):
    import jax
    from raft_tla_tpu.sim import SimEngine
    eng = SimEngine(build(name), walkers=walkers, max_depth=48, seed=0,
                    bloom_bits=20)
    t0 = time.time()
    eng.run(steps=warm, steps_per_dispatch=warm)     # compile + warm
    compile_s = time.time() - t0
    st = eng.fresh_carry()
    t0 = time.time()
    st = eng._dispatch(st, steps)
    sdone = int(st["stats"][0])                      # blocks on device
    secs = time.time() - t0
    return {
        "workload": name, "walkers": walkers, "fleet_steps": steps,
        "walker_steps": sdone,
        "walker_steps_per_sec": round(sdone / max(secs, 1e-9), 1),
        "sampled_steps": int(st["stats"][5]),
        "seconds": round(secs, 3),
        "compile_seconds": round(compile_s, 1),
        "platform": jax.default_backend(),
    }


def main():
    import jax
    rows = [measure("small", walkers=64, steps=256),
            measure("cfg5", walkers=64, steps=128)]
    out = {
        "bench": "sim walker throughput (tools/bench_sim.py)",
        "platform": jax.default_backend(),
        "honest_label": (
            "CPU-only fallback: this container has no TPU; figures "
            "measure the same device program XLA:CPU-compiled"
            if jax.default_backend() == "cpu" else
            "TPU-measured"),
        "rows": rows,
    }
    with open(OUT, "w") as fh:
        json.dump(out, fh, indent=1)
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
