"""CI chaos smoke: kill a tiny ``cli batch`` run mid-wave, re-invoke,
assert bit-exact completion with a ledger that shows the resume.

Three invocations of the real CLI (subprocesses, CPU-only):

1. REFERENCE — the job list runs clean; its per-job reports are the
   ground truth.
2. KILL — the same jobs with ``--chaos wave_kill:at=1``: the
   deterministic SIGKILL stand-in fires at the first wave boundary,
   AFTER the per-job wave state persisted (serve/wavestate) — the run
   exits non-zero mid-wave, exactly like a preempted process.
3. RESUME — the same command again, no chaos: the straggler must
   resume MID-BFS from its wave state (the ledger shows a
   ``wave_resume`` record and the job row says "resumed from wave
   state"), every job must finish, and counts/level_sizes must equal
   the reference bit-for-bit.

Also exercises: the result cache sharing a directory with the wave
state, ``--retries`` self-healing (run 2's failure would have been
absorbed by ``--retries 1`` — asserted via the in-process tests; here
the two-invocation shape mirrors a real kill).
"""

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_batch(jobs_path, tmp, extra, expect_rc):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [sys.executable, "-m", "raft_tla_tpu", "batch",
           "--jobs", jobs_path,
           "--cache-dir", os.path.join(tmp, "cache"),
           "--wave-state", os.path.join(tmp, "waves"),
           "--ledger", os.path.join(tmp, "ledger.jsonl"),
           "--heartbeat", os.path.join(tmp, "hb.json")] + extra
    p = subprocess.run(cmd, capture_output=True, text=True, cwd=REPO,
                       env=env, timeout=600)
    assert p.returncode == expect_rc, \
        (p.returncode, expect_rc, p.stdout, p.stderr)
    rows = [json.loads(ln) for ln in p.stdout.splitlines() if ln]
    return rows


def ledger_records(tmp):
    recs = []
    with open(os.path.join(tmp, "ledger.jsonl")) as fh:
        for line in fh:
            recs.append(json.loads(line))
    return recs


def main():
    raft_cfg = os.path.join(REPO, "configs", "tlc_membership",
                            "raft.cfg")
    jobs = [
        {"spec": "raft", "config": raft_cfg, "label": "deep",
         "max_depth": 14,
         "overrides": {"servers": 2, "next": "NextAsync",
                       "bounds": {"max_log_length": 1,
                                  "max_timeouts": 1,
                                  "max_client_requests": 1}}},
        {"spec": "raft", "config": raft_cfg, "label": "short",
         "max_depth": 3, "priority": 1,
         "overrides": {"servers": 2, "next": "NextAsync",
                       "bounds": {"max_log_length": 1,
                                  "max_timeouts": 1,
                                  "max_client_requests": 1}}},
    ]
    ref_tmp = tempfile.mkdtemp(prefix="chaos_smoke_ref_")
    tmp = tempfile.mkdtemp(prefix="chaos_smoke_")
    jobs_path = os.path.join(tmp, "jobs.jsonl")
    with open(jobs_path, "w") as fh:
        for obj in jobs:
            fh.write(json.dumps(obj) + "\n")

    # 1. clean reference
    ref_rows = run_batch(jobs_path, ref_tmp, [], expect_rc=0)
    ref = {r["label"]: r for r in ref_rows[1:]}
    assert set(ref) == {"deep", "short"}, ref_rows

    # 2. kill mid-wave (exit 3: the batch driver reports the injected
    # fault as a failed run after 0 retries)
    run_batch(jobs_path, tmp, ["--chaos", "wave_kill:at=1"],
              expect_rc=3)
    waves = os.listdir(os.path.join(tmp, "waves"))
    assert any(nm.endswith(".wave.npz") for nm in waves), \
        f"no wave state persisted before the kill: {waves}"

    # 3. resume — every job completes, stragglers mid-BFS
    rows = run_batch(jobs_path, tmp, [], expect_rc=0)
    summary, per_job = rows[0], {r["label"]: r for r in rows[1:]}
    assert summary["resumed_jobs"] >= 1, summary
    resumed = [r for r in per_job.values()
               if r.get("status_reason") == "resumed from wave state"]
    assert resumed, per_job
    for label, want in ref.items():
        got = per_job[label]
        assert got["status"] in ("done", "cache_hit"), got
        for key in ("distinct_states", "generated_states", "depth",
                    "level_sizes", "violations"):
            assert got[key] == want[key], (label, key, got[key],
                                           want[key])
    recs = ledger_records(tmp)
    assert any(r.get("kind") == "wave_resume" for r in recs), \
        sorted({r.get("kind") for r in recs})
    # wave state retired once the jobs finished
    waves = [nm for nm in os.listdir(os.path.join(tmp, "waves"))
             if nm.endswith(".wave.npz")]
    assert not waves, waves
    print("chaos smoke OK: killed mid-wave, resumed bit-exact "
          f"(resumed_jobs={summary['resumed_jobs']})")


if __name__ == "__main__":
    main()
