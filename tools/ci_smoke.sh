#!/usr/bin/env bash
# Sub-minute CPU-only CI gate: runs exactly the `smoke` pytest marker
# set (pyproject.toml) with the TPU plugin forced off.  Independent of
# the tier-1 budget — future PRs get a fast red/green signal even when
# the full differential suite would blow the harness timeout.
#
# Usage: tools/ci_smoke.sh [extra pytest args]
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m smoke \
    -p no:cacheprovider "$@"
