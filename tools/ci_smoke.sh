#!/usr/bin/env bash
# Sub-minute CPU-only CI gate: runs exactly the `smoke` pytest marker
# set (pyproject.toml) with the TPU plugin forced off, then the
# observability smoke step (tools/obs_smoke.py): one tiny check with
# --ledger --heartbeat --trace-timeline, validating that the JSONL
# parses, spans nest (every end has a start, no negative durations)
# and the heartbeat depth matches the final stats.  Independent of
# the tier-1 budget — future PRs get a fast red/green signal even when
# the full differential suite would blow the harness timeout.
#
# Usage: tools/ci_smoke.sh [extra pytest args]
set -euo pipefail
cd "$(dirname "$0")/.."
env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m smoke \
    -p no:cacheprovider "$@"
env JAX_PLATFORMS=cpu python tools/obs_smoke.py
env JAX_PLATFORMS=cpu python tools/guard_matmul_smoke.py
# delta-matmul gate (round 11): depth-capped CLI ON ≡ OFF count parity
# for the scatter-as-matmul successor path, raft AND paxos (the paxos
# run proves the declarations-only tenant needs zero kernels)
env JAX_PLATFORMS=cpu python tools/delta_smoke.py
# spec-agnostic frontend gate (round 10): one depth-capped
# `check --spec paxos` pinned against the in-process oracle, plus the
# engine-layer grep gate (engine/ and parallel/ must never import
# models.raft directly — everything routes through the SpecIR handle)
env JAX_PLATFORMS=cpu python tools/paxos_smoke.py
# batch-serving gate (round 11): two tiny jobs (raft + paxos, the
# paxos one through the TLC .cfg front-end) through `cli batch`, then
# a re-run asserting the second invocation is served entirely from the
# fingerprint-keyed result cache — 0 device dispatches in the ledger
env JAX_PLATFORMS=cpu python tools/serve_smoke.py
# fault-tolerance gate (round 12): kill a tiny `cli batch` run
# mid-wave via the deterministic wave_kill chaos site, re-invoke, and
# assert bit-exact completion with a ledger showing the wave resume
env JAX_PLATFORMS=cpu python tools/chaos_smoke.py
# pod-scale pjit gate (round 14): depth-capped `check --pjit`
# (whole-state named shardings) ≡ the default engine, reference-less
# CLI A/B count parity
env JAX_PLATFORMS=cpu python tools/pjit_smoke.py
# orbit-sort canonicalization gate (round 15): depth-capped CLI
# `--sym-canon sort` (one argsorted canonical hash) ≡ `minperm` (the
# P-fold min-over-perms) count parity, raft block-product group AND
# paxos full S_N, with the stats mode flag pinned 1/0
env JAX_PLATFORMS=cpu python tools/sym_smoke.py
# run-registry gate (ISSUE 17): three tiny --registry check runs, then
# `cli obs diff/regress` must pass the identical pair (verdict clean,
# rc 0) and CATCH an injected depth-gate count mismatch (rc 1), with
# resource telemetry (RSS peak, compile seconds) on the records
env JAX_PLATFORMS=cpu python tools/obs_report_smoke.py
# daemon gate (ISSUE 18): a real `cli serve` daemon over a spool dir —
# two tenants served bit-exact vs a clean `cli batch` reference,
# SIGTERM graceful drain (exit 0, registry cmd=serve), then a
# kill-mid-wave / restart pair: the new daemon re-claims the leftover
# job, resumes MID-BFS from its persisted wave state bit-exact, and
# (exec cache warm) compiles zero bucket programs on the way
env JAX_PLATFORMS=cpu python tools/daemon_smoke.py
# mesh-wave gate (round 16): one `cli batch` wave under FORCED 4
# virtual CPU devices, job axis sharded (`--wave-mesh 4`) vs the
# single-device reference (`--wave-mesh off`) — per-job count parity,
# wave_devices=4 stamped in the summary AND the --registry record, and
# the shared exec cache treating the mesh-shape change as a named
# miss (never a wrong load)
env JAX_PLATFORMS=cpu python tools/wave_mesh_smoke.py
