"""Break down the engine's warm-start cost on the tunneled TPU
(VERDICT r3 #4 "kill the compile tax"): how much of the measured
36-205 s `compile_seconds` is (a) Python tracing + MLIR lowering on the
1-vCPU host, (b) backend compile / persistent-cache load, (c) the first
real dispatch round trips.

Usage: python tools/compile_probe.py [config_no] [--chunk N] [--lcap N]
       [--vcap N]

The split decides the fix: (a) dominates -> cache at the jaxpr level /
slim the traced program; (b) dominates -> prewarm the persistent cache
(tools/prewarm.py ladder); (c) dominates -> nothing to win below the
tunnel's round-trip floor.
"""
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main():
    import jax

    from raft_tla_tpu.engine.bfs import Engine
    from tools.measure_baseline import ENGINE_KW, build_cfg

    args = sys.argv[1:]
    conf_no = int(args.pop(0)) if args and not args[0].startswith("-") \
        else 2
    opts = dict(zip(args[::2], args[1::2]))
    kw = dict(ENGINE_KW[conf_no])
    for k in ("chunk", "lcap", "vcap"):
        if f"--{k}" in opts:
            kw[k] = int(opts[f"--{k}"])

    cfg = build_cfg(conf_no)
    t0 = time.time()
    eng = Engine(cfg, store_states=False, **kw)
    t_init = time.time() - t0
    print(f"engine init (incl. salt tables): {t_init:.1f}s", flush=True)

    # build a real carry the way check() does, then time each stage of
    # the step executable explicitly
    carry = eng._fresh_carry(eng.LCAP, eng.VCAP, eng.FCAP)
    t0 = time.time()
    lowered = eng._step_jit.lower(carry, eng.FAM_CAPS)
    t_lower = time.time() - t0
    print(f"step trace+lower: {t_lower:.1f}s", flush=True)
    t0 = time.time()
    lowered.compile()
    t_compile = time.time() - t0
    print(f"step backend compile (or cache load): {t_compile:.1f}s",
          flush=True)
    # a plain dispatch through the normal jit path (its own cache)
    t0 = time.time()
    carry = eng._step_jit(carry, eng.FAM_CAPS)
    jax.block_until_ready(carry["n_lvl"])
    t_disp = time.time() - t0
    print(f"first jit dispatch (trace+compile+run on top of AOT "
          f"warmth): {t_disp:.1f}s", flush=True)

    t0 = time.time()
    lowered_f = eng._fin_jit.lower(carry)
    print(f"finalize trace+lower: {time.time() - t0:.1f}s", flush=True)
    t0 = time.time()
    lowered_f.compile()
    print(f"finalize compile/load: {time.time() - t0:.1f}s", flush=True)

    t0 = time.time()
    r = eng.check(max_depth=2)
    print(f"check(max_depth=2) after all of the above: "
          f"{time.time() - t0:.1f}s  ({r.distinct_states} states)",
          flush=True)


if __name__ == "__main__":
    main()
