"""CI daemon smoke: the persistent ``cli serve`` loop end-to-end —
spool intake, graceful drain, kill-mid-wave restart (ISSUE 18).

Three daemon invocations over the real CLI (subprocesses, CPU-only),
pinned against a clean ``cli batch`` reference of the same jobs:

1. SERVICE — a daemon watches a spool; a deep raft job and a paxos
   job arrive via the client protocol (write-then-rename, trailing
   newline); both results land in results/ with done/ markers,
   bit-exact vs the batch reference; the ledger holds the
   ``kind="intake"`` claim rows and a ``kind="daemon"`` cycle row.
   SIGTERM then drains it: exit 0, final heartbeat ``status="done"``,
   one registry record ``cmd="serve"`` listed by ``obs ls``.
2. KILL — a fresh spool, ``--chaos wave_kill:at=1``: the
   deterministic SIGKILL stand-in fires at the first wave boundary,
   AFTER the job's wave state persisted.  The cycle fails, retries
   are exhausted (0), the daemon exits 3 — and the claimed file plus
   the ``.wave.npz`` carry survive on disk, exactly the crash
   contract a real ``kill -9`` leaves behind.
3. RESTART — a new daemon on the same spool re-claims the leftover
   (``recover``), the scheduler resumes the straggler MID-BFS from
   its wave state (``kind="wave_resume"`` ledger row), the result is
   bit-exact vs the reference, and — executable cache warm from run
   1 — the span timeline holds ZERO ``bucket_compile`` events.  On a
   backend whose runtime cannot serialize executables the
   zero-compile assertion SKIPS with a named reason (the honest-miss
   contract) — never a crash.
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PAXOS_CFG = """\\* tiny paxos model (daemon smoke)
CONSTANTS
  a1 = 1
  a2 = 2
  Acceptor = {a1, a2}
  Ballot = {0}
  Value = {0}
INIT Init
NEXT Next
INVARIANT Agreement
"""

DEEP_RAFT = {
    "spec": "raft",
    "config": os.path.join(REPO, "configs", "tlc_membership",
                           "raft.cfg"),
    "label": "deep", "max_depth": 14,
    "overrides": {"servers": 2, "next": "NextAsync",
                  "bounds": {"max_log_length": 1, "max_timeouts": 1,
                             "max_client_requests": 1}},
}

COMPARE_KEYS = ("distinct_states", "generated_states", "depth",
                "level_sizes", "violations")


def submit(spool, name, obj):
    """The client protocol: write the complete JSON (trailing
    newline) to a dot-tmp name, then rename into incoming/.  Clients
    may create incoming/ themselves — the daemon's intake does the
    same idempotently, so whoever arrives first wins."""
    os.makedirs(os.path.join(spool, "incoming"), exist_ok=True)
    tmp = os.path.join(spool, "incoming", f".{name}.tmp")
    with open(tmp, "w") as fh:
        fh.write(json.dumps(obj) + "\n")
    os.rename(tmp, os.path.join(spool, "incoming", name + ".json"))


def start_daemon(spool, tmp, exec_dir, extra=()):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [sys.executable, "-m", "raft_tla_tpu", "serve",
           "--spool", spool, "--poll", "0.1",
           "--executable-cache", exec_dir,
           "--ledger", os.path.join(tmp, "ledger.jsonl"),
           "--heartbeat", os.path.join(tmp, "hb.json"),
           "--registry", os.path.join(tmp, "reg"), *extra]
    return subprocess.Popen(cmd, cwd=REPO, env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)


def wait_for(pred, what, timeout_s=420):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.2)
    raise AssertionError(f"timed out waiting for {what}")


def wait_exit(proc, what, timeout_s=420):
    try:
        out, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        proc.kill()
        raise AssertionError(f"daemon did not exit: {what}")
    return proc.returncode, out, err


def ledger_records(tmp):
    recs = []
    with open(os.path.join(tmp, "ledger.jsonl")) as fh:
        for line in fh:
            recs.append(json.loads(line))
    return recs


def read_result(spool, name):
    with open(os.path.join(spool, "results", name + ".json")) as fh:
        return json.load(fh)


def obs_ls(tmp, *filters):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, "-m", "raft_tla_tpu", "obs", "ls",
         "--registry", os.path.join(tmp, "reg"), *filters],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=120)
    assert p.returncode == 0, (p.returncode, p.stdout, p.stderr)
    return p.stdout.splitlines()[1:]          # drop the header


def main():
    top = tempfile.mkdtemp(prefix="daemon_smoke_")
    pax_cfg = os.path.join(top, "paxos.cfg")
    with open(pax_cfg, "w") as fh:
        fh.write(PAXOS_CFG)
    pax_job = {"spec": "paxos", "config": pax_cfg, "max_depth": 3,
               "label": "pax"}
    exec_dir = os.path.join(top, "exec")

    # 0. clean `cli batch` reference — the ground truth both the
    # service path and the restart path must match bit-for-bit
    ref_tmp = os.path.join(top, "ref")
    os.makedirs(ref_tmp)
    jobs_path = os.path.join(ref_tmp, "jobs.jsonl")
    with open(jobs_path, "w") as fh:
        fh.write(json.dumps(DEEP_RAFT) + "\n")
        fh.write(json.dumps(pax_job) + "\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, "-m", "raft_tla_tpu", "batch",
         "--jobs", jobs_path,
         "--cache-dir", os.path.join(ref_tmp, "cache")],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=600)
    assert p.returncode == 0, (p.returncode, p.stdout, p.stderr)
    ref = {r["label"]: r for r in
           (json.loads(ln) for ln in p.stdout.splitlines() if ln)
           if r.get("kind") != "batch_summary"}
    assert set(ref) == {"deep", "pax"}, sorted(ref)

    # 1. SERVICE — daemon up, two tenants submit, results land,
    # SIGTERM drains it
    t1 = os.path.join(top, "t1")
    spool1 = os.path.join(t1, "spool")
    os.makedirs(spool1)
    d1 = start_daemon(spool1, t1, exec_dir)
    try:
        submit(spool1, "deep", DEEP_RAFT)
        submit(spool1, "pax", pax_job)
        done = os.path.join(spool1, "done")
        wait_for(lambda: os.path.exists(os.path.join(done,
                                                     "deep.json"))
                 and os.path.exists(os.path.join(done, "pax.json")),
                 "done/ markers for both submissions")
    finally:
        d1.send_signal(signal.SIGTERM)
    rc, out, err = wait_exit(d1, "SIGTERM drain")
    assert rc == 0, (rc, out, err)
    for name in ("deep", "pax"):
        got = read_result(spool1, name)
        for key in COMPARE_KEYS:
            assert got[key] == ref[name][key], \
                (name, key, got[key], ref[name][key])
    recs = ledger_records(t1)
    claimed = [r for r in recs if r.get("kind") == "intake"
               and r.get("action") == "claimed"]
    assert {r["name"] for r in claimed} == {"deep", "pax"}, claimed
    assert any(r.get("kind") == "daemon" for r in recs), \
        sorted({r.get("kind") for r in recs})
    with open(os.path.join(t1, "hb.json")) as fh:
        hb = json.load(fh)
    assert hb.get("status") == "done", hb.get("status")
    assert hb.get("daemon", {}).get("jobs_done") == 2, hb.get("daemon")
    rows = obs_ls(t1, "--cmd", "serve", "--status", "done")
    assert len(rows) == 1 and " serve " in rows[0], rows
    print("daemon_smoke: OK (2 tenants served bit-exact; SIGTERM "
          "drain: exit 0, heartbeat done, registry cmd=serve)")

    # 2. KILL — chaos fires mid-wave AFTER the wave-state persist;
    # the daemon exits 3 leaving the claimed file + carry on disk
    t2 = os.path.join(top, "t2")
    spool2 = os.path.join(t2, "spool")
    os.makedirs(spool2)
    d2 = start_daemon(spool2, t2, exec_dir,
                      extra=("--chaos", "wave_kill:at=1"))
    submit(spool2, "deep", DEEP_RAFT)
    rc, out, err = wait_exit(d2, "chaos kill")
    assert rc == 3, (rc, out, err)
    waves = os.listdir(os.path.join(spool2, "waves"))
    assert any(nm.endswith(".wave.npz") for nm in waves), \
        f"no wave state persisted before the kill: {waves}"
    assert os.path.exists(os.path.join(spool2, "claimed",
                                       "deep.json")), \
        "claimed file must survive the crash"
    assert not os.listdir(os.path.join(spool2, "done"))
    rows = obs_ls(t2, "--cmd", "serve", "--status", "failed")
    assert len(rows) == 1, rows

    # 3. RESTART — recover the leftover claim, resume mid-BFS from
    # the wave state, finish bit-exact; exec cache warm from run 1
    tl = os.path.join(t2, "tl.json")
    d3 = start_daemon(spool2, t2, exec_dir,
                      extra=("--max-idle-polls", "20",
                             "--trace-timeline", tl))
    rc, out, err = wait_exit(d3, "restart drain")
    assert rc == 0, (rc, out, err)
    got = read_result(spool2, "deep")
    for key in COMPARE_KEYS:
        assert got[key] == ref["deep"][key], \
            (key, got[key], ref["deep"][key])
    recs = ledger_records(t2)
    assert any(r.get("kind") == "intake"
               and r.get("action") == "recovered" for r in recs), \
        sorted({(r.get("kind"), r.get("action")) for r in recs})
    assert any(r.get("kind") == "wave_resume" for r in recs), \
        sorted({r.get("kind") for r in recs})
    stored = [nm for nm in os.listdir(exec_dir)
              if nm.endswith(".exec")] if os.path.isdir(exec_dir) \
        else []
    if not stored:
        print("daemon_smoke: OK (killed mid-wave, restart resumed "
              "bit-exact); SKIPPING zero-compile check — backend "
              "cannot serialize executables (empty exec cache)")
        return
    with open(tl) as fh:
        ncomp = fh.read().count('"name": "bucket_compile"')
    assert ncomp == 0, \
        f"warm restart must compile NOTHING, saw {ncomp} spans"
    print("daemon_smoke: OK (killed mid-wave, restart resumed "
          "bit-exact, 0 bucket compiles on the warm path)")


if __name__ == "__main__":
    main()
