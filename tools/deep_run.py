"""Beyond-the-wall depth-exact runs with the host-spill engine
(VERDICT r3 #1 "Break the exhaustion wall").

Round 3 measured the wall: depth 19 (config #2) / depth 21 (config #1)
are the deepest level-exact runs whose buffers fit single-chip HBM, and
the native CPU checker OOMs the 125 GB host (~650 B/state) even
earlier, so NO checker in this environment can verify deeper counts.
The SpillEngine streams levels through host RAM (engine/spill), so its
depth wall is the visited table (12 B/key fp64, 20 B/key fp128)
instead of the level buffers.

Usage: python tools/deep_run.py CONFIG DEPTH [--spec raft|paxos]
       [--fp128] [--chunk N]
       [--seg N] [--vcap N] [--tag NAME] [--classic] [--lcap N]
       [--fcap N] [--native] [--budget N] [--ckpt FILE]
       [--resume FILE] [--ckpt-every N] [--ckpt-keep K]
       [--retries N] [--backoff S] [--chaos SPEC] [--host-table]
       [--partitions P] [--part-cap N] [--ledger FILE]
       [--heartbeat FILE] [--trace-timeline FILE] [--profile-dir DIR]
       [--registry DIR]

Fault tolerance (round 12, resil/): --retries N wraps the drive loop
in the supervised runner — a dropped tunnel triggers backend reinit +
resume from the newest valid member of the --ckpt chain (last
--ckpt-keep checkpoints, sha256 sidecars) with bounded exponential
backoff; attempts land in the ledger/heartbeat and tools/watch.py
shows the backoff state.  --chaos injects deterministic faults at the
named engine sites for recovery drills.

Observability (obs/): --ledger appends one JSONL record per dispatch
(flushed, so a dropped tunnel keeps the telemetry up to the last
dispatch), --heartbeat atomically rewrites a watchdog file every
dispatch (tools/watch.py tails both), --trace-timeline writes the
host span timeline as Perfetto-loadable Chrome-trace JSON, and
--profile-dir captures an XLA device trace with matching
TraceAnnotation names.  --registry DIR appends one queryable record
per run (counters, span rollups, resource peaks, backend fingerprint)
that ``cli obs ls/show/diff/regress`` reads — the ROADMAP validation
rounds should attach --ledger/--heartbeat/--registry to every TPU run.

--host-table moves the visited set to fingerprint-prefix partitions in
host RAM (engine/host_table), streamed through HBM per level — the
depth wall becomes host RAM instead of the ~2^29-slot HBM table.
Checkpoints then carry the partition images (sparse, exact-image
restore) and --resume must repeat the same --host-table/--partitions;
the engine refuses a mismatched resume rather than drift.

--spec paxos runs the Paxos frontend instead of Raft: CONFIG then
selects a ladder of Paxos models (1 = N3/B2/V2/I1 stock, 2 = N3/B3/V2,
3 = N5/B2/V2, 4 = N3/B2/V2/I2) and --native is unavailable (the native
C++ checker is Raft-only).

--classic uses the in-HBM Engine instead of SpillEngine (for
depth-exact head-to-heads at depths that still fit); --native also
runs the native C++ checker at the same depth/budget and records the
speedup; --budget caps distinct states (level-granular, both engines)
for budget-exact rather than depth-exact comparisons.

Writes/merges baseline_runs/round4_deep.json:
  {"config2_depth21": {...}, "config2_depth21_fp128": {...}, ...}

Honesty note (BASELINE.md): counts at these depths cannot be checked
against the native checker or TLC on this machine — corroboration is a
second run with independent 128-bit fingerprints (--fp128), the same
cross-check round 3 recorded for the depth-19 row.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

OUT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "baseline_runs", "round4_deep.json")


def main():
    from raft_tla_tpu.engine.bfs import Engine
    from raft_tla_tpu.engine.spill import SpillEngine
    from tools.measure_baseline import build_cfg

    args = sys.argv[1:]
    conf_no = int(args.pop(0))
    depth = int(args.pop(0))
    flags = {f: f in args for f in ("--fp128", "--classic", "--native",
                                    "--host-table", "--no-burst",
                                    "--no-guard-matmul",
                                    "--no-delta-matmul")}
    for f, on in flags.items():
        if on:
            args.remove(f)
    fp128 = flags["--fp128"]
    host_table = flags["--host-table"]
    if host_table and flags["--classic"]:
        raise SystemExit("--host-table composes with the spill engine; "
                         "drop --classic")
    opts = dict(zip(args[::2], args[1::2]))
    known = {"--chunk", "--seg", "--vcap", "--budget", "--tag", "--lcap",
             "--fcap", "--ckpt", "--resume", "--ckpt-every",
             "--ckpt-keep", "--retries", "--backoff", "--chaos",
             "--partitions", "--part-cap", "--burst-levels",
             "--ledger", "--heartbeat", "--trace-timeline",
             "--profile-dir", "--registry", "--dedup-kernel",
             "--fam-cap-density", "--spec"}
    bad = set(opts) - known
    if bad or len(args) % 2:
        # fail loud: these depths cannot be cross-checked by any other
        # checker here, so a silently-ignored typo'd flag would record
        # an unverifiable row under the wrong parameters
        raise SystemExit(f"unknown/incomplete options: "
                         f"{sorted(bad) or args[-1:]} (known: "
                         f"{sorted(known)})")
    chunk = int(opts.get("--chunk", 4096))
    seg = int(opts.get("--seg", 1 << 22))
    vcap = int(opts.get("--vcap", 1 << 26))
    burst = not flags["--no-burst"]
    burst_levels = (int(opts["--burst-levels"])
                    if "--burst-levels" in opts else None)
    if burst_levels is not None and burst_levels <= 0:
        raise SystemExit(f"--burst-levels must be positive "
                         f"(got {burst_levels}); use --no-burst to "
                         "disable the fused-level path")
    budget = int(opts.get("--budget", 10 ** 9))
    partitions = int(opts.get("--partitions", 4))
    part_cap = int(opts.get("--part-cap", 1 << 16))
    guard_matmul = not flags["--no-guard-matmul"]
    dedup_kernel = opts.get("--dedup-kernel", "auto")
    if dedup_kernel not in ("auto", "on", "off"):
        raise SystemExit(f"--dedup-kernel must be auto|on|off "
                         f"(got {dedup_kernel})")
    spec = opts.get("--spec", "raft")
    if spec not in ("raft", "paxos"):
        raise SystemExit(f"--spec must be raft|paxos (got {spec})")
    fam_density = None
    if "--fam-cap-density" in opts:
        from raft_tla_tpu.engine.expand import parse_fam_density
        from raft_tla_tpu.spec import get_spec
        try:
            fam_density = parse_fam_density(opts["--fam-cap-density"],
                                            get_spec(spec))
        except ValueError as e:
            raise SystemExit(f"--fam-cap-density: {e}") from None
    mxu_kw = dict(guard_matmul=guard_matmul, dedup_kernel=dedup_kernel,
                  delta_matmul=not flags["--no-delta-matmul"],
                  fam_density=fam_density)
    tag = opts.get("--tag",
                   ("paxos_" if spec == "paxos" else "")
                   + f"config{conf_no}_depth{depth}"
                   + ("_fp128" if fp128 else "")
                   + ("_hosttable" if host_table else ""))

    if spec == "paxos":
        from raft_tla_tpu.spec.paxos.config import PaxosConfig
        ladder = {1: PaxosConfig(),
                  2: PaxosConfig(n_ballots=3),
                  3: PaxosConfig(n_servers=5),
                  4: PaxosConfig(n_instances=2)}
        if conf_no not in ladder:
            raise SystemExit(
                f"--spec paxos CONFIG must be one of "
                f"{sorted(ladder)} (got {conf_no})")
        if flags["--native"]:
            raise SystemExit("--native is raft-only (the native C++ "
                             "checker has no Paxos frontend)")
        cfg = ladder[conf_no]
    else:
        cfg = build_cfg(conf_no)
    if fp128:
        cfg = cfg.with_(fp128=True)
    nat_rec = None
    if flags["--native"]:
        from raft_tla_tpu import native
        nat_cfg = cfg.with_(invariants=()) if conf_no == 5 else cfg
        nat = native.check(nat_cfg, threads=os.cpu_count() or 1,
                           max_depth=depth, max_states=budget)
        nat_rec = {
            "distinct": int(nat.distinct_states),
            "depth": int(nat.depth),
            "seconds": round(nat.seconds, 2),
            "states_per_sec": round(nat.states_per_sec, 1)}
        print(json.dumps({"native": nat_rec}), flush=True)
    retries = int(opts.get("--retries", 0))
    backoff_s = float(opts.get("--backoff", 2.0))
    ckpt_keep = int(opts.get("--ckpt-keep", 2))
    if retries < 0 or backoff_s <= 0 or ckpt_keep < 1:
        raise SystemExit("--retries must be >= 0, --backoff > 0, "
                         "--ckpt-keep >= 1")
    if "--chaos" in opts:
        from raft_tla_tpu.resil.chaos import ChaosSpecError, install
        try:
            install(opts["--chaos"])
        except ChaosSpecError as e:
            raise SystemExit(str(e))

    def build_engine():
        if flags["--classic"]:
            eng = Engine(cfg, chunk=chunk, store_states=False,
                         vcap=vcap,
                         lcap=int(opts.get("--lcap", 1 << 21)),
                         fcap=int(opts["--fcap"]) if "--fcap" in opts
                         else None,
                         burst=burst, burst_levels=burst_levels,
                         **mxu_kw)
        else:
            eng = SpillEngine(cfg, chunk=chunk, store_states=False,
                              seg=seg, vcap=vcap,
                              host_table=host_table,
                              partitions=partitions,
                              part_cap=part_cap,
                              burst=burst, burst_levels=burst_levels,
                              **mxu_kw)
        eng.ckpt_keep = ckpt_keep
        return eng
    eng = build_engine()
    from raft_tla_tpu.obs import from_flags
    obs = from_flags(ledger=opts.get("--ledger"),
                     heartbeat=opts.get("--heartbeat"),
                     timeline=opts.get("--trace-timeline"),
                     profile_dir=opts.get("--profile-dir"),
                     registry=opts.get("--registry"),
                     run_info={"cmd": "deep_run", "cfg": repr(cfg)},
                     meta={"spec": eng.ir.name,
                           "ir_fingerprint": eng.ir.fingerprint()})
    obs.start()
    t0 = time.perf_counter()
    with obs.span("compile"):
        eng.check(max_depth=2)                   # warm the jit caches
    compile_s = time.perf_counter() - t0
    # checkpointing (VERDICT r4 #2): hours-scale runs on the tunneled
    # TPU die to dropped connections, not engine faults — a level-
    # boundary checkpoint + --resume makes the depth-21 fp128
    # corroboration protocol survivable
    ckpt = opts.get("--ckpt")
    resume = opts.get("--resume")
    resume_start = 0
    if resume:
        # the checkpoint's distinct count: post-resume throughput is
        # (delta states)/secs — cumulative/partial would inflate the
        # recorded rate ~10x on a late resume.  Read the same chain
        # member the engine will (a torn head falls back to FILE.1,
        # resil/ckpt_chain) — a bare head read here would traceback on
        # exactly the torn-write case the chain exists for
        from raft_tla_tpu.resil.ckpt_chain import latest_valid
        src = latest_valid(resume) or resume
        meta = json.loads(str(np.load(src)["meta"]))
        resume_start = int(meta["distinct"])
    t0 = time.perf_counter()
    # supervised drive loop (resil/supervisor): the first attempt uses
    # the already-warmed engine; retries rebuild it (backend reinit)
    # and resume from the newest valid member of the --ckpt chain
    from raft_tla_tpu.resil.supervisor import supervised_check
    _warm = [eng]

    def make_engine():
        return _warm.pop() if _warm else build_engine()
    try:
        r, eng, attempts = supervised_check(
            make_engine, retries=retries, backoff=backoff_s, obs=obs,
            checkpoint_path=ckpt, resume_from=resume,
            max_depth=depth, max_states=budget, verbose=True,
            checkpoint_every=int(opts.get("--ckpt-every", 1)))
    except BaseException:
        obs.finish(status="failed")
        raise
    secs = time.perf_counter() - t0
    obs.finish(depth=int(r.depth), states=int(r.distinct_states),
               counters=r.metrics.as_dict(),
               level_sizes=[int(x) for x in r.level_sizes])
    rec = {
        "engine": type(eng).__name__,
        "spec": eng.ir.name,
        "ir_fingerprint": eng.ir.fingerprint(),
        "config": conf_no, "max_depth": depth,
        "fp_bits": 128 if fp128 else 64,
        "distinct": int(r.distinct_states), "depth": int(r.depth),
        "depth_exact": budget >= 10 ** 9,
        # on a resumed run the wall/rate fields cover the POST-RESUME
        # portion only (counts stay cumulative); the row is labeled by
        # resumed_from_checkpoint below so it cannot pass for a
        # single-session wall measurement
        "seconds": round(secs, 2),
        "states_per_sec": round(
            (r.distinct_states - resume_start) / max(secs, 1e-9), 1),
        "compile_seconds": round(compile_s, 1),
        "level_sizes": [int(x) for x in r.level_sizes],
        "violations": len(r.violations),
        "overflow_faults": int(r.overflow_faults),
        "chunk": chunk, "seg": seg, "final_vcap": int(eng.VCAP),
        "host_table": host_table,
        # fused-dispatch telemetry: levels_fused > 0 proves the burst
        # engaged on the tiny early levels instead of silently bailing
        "burst": burst,
        "levels_fused": int(r.levels_fused),
        "burst_dispatches": int(r.burst_dispatches),
        "burst_bailouts": int(r.burst_bailouts),
        # MXU-path mode flags (round 9): which expansion/dedup program
        # produced this row
        "guard_matmul": int(r.guard_matmul),
        "dedup_kernel": int(r.dedup_kernel),
        "delta_matmul": int(r.delta_matmul),
        "resumed_from_checkpoint": bool(resume),
        # supervised-retry provenance (round 12): a row produced over
        # several attempts is labeled; its wall/rate fields cover the
        # whole supervised session including backoff waits
        "retry_attempts": int(attempts),
        "expected_fp_collisions": float(
            r.distinct_states ** 2 /
            2.0 ** ((128 if fp128 else 64) + 1)),
    }
    # spill perf floor (VERDICT r4 #6): the canonical spill probe shape
    # (config #2, depth-exact 19, SpillEngine, single session) guards
    # the spill engine's rate the way bench.py guards the classic one
    if host_table:
        rec["partitions"] = partitions
        rec["host_table_keys"] = int(eng.hpt.n_keys)
        rec["host_table_bytes"] = int(eng.hpt.nbytes)
    # (host-table runs are rate-recorded but never floor-gate: the
    # canonical spill probe guards the default in-HBM-table path)
    if (spec == "raft" and not flags["--classic"] and conf_no == 2
            and depth == 19
            and rec["depth_exact"] and not fp128 and not resume
            and not host_table and attempts == 1):
        import jax

        from bench import perf_floor
        floor_info, _zero = perf_floor(
            rec["states_per_sec"], 0,
            str(jax.devices()[0].device_kind),
            os.path.join(os.path.dirname(os.path.dirname(OUT)),
                         "BENCH_FLOOR.json"),
            gate_ok=rec["violations"] == 0, allow_bump=True,
            key="spill_config2_depth19", headline_depth=0,
            bump_source="deep_run.py spill probe auto-bump")
        rec["perf_floor"] = floor_info
    if nat_rec is not None:
        rec["native"] = nat_rec
        rec["counts_match"] = (
            nat_rec["distinct"] == rec["distinct"]
            and nat_rec["depth"] == rec["depth"])
        rec["speedup"] = round(rec["states_per_sec"] /
                               max(nat_rec["states_per_sec"], 1e-9), 2)
    else:
        rec["note"] = ("no CPU checker on this host can reach this "
                       "depth (native OOMs ~65GB RSS; round3 "
                       "exhaustion probes)")
    data = {}
    if os.path.exists(OUT):
        data = json.load(open(OUT))
    data[tag] = rec
    # write-then-rename: an interrupted dump must not destroy earlier
    # recorded rows (these depths are unreproducible by other checkers)
    tmp = OUT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(data, f, indent=1)
    os.replace(tmp, OUT)
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
