"""Delta-matmul CI smoke (tools/ci_smoke.sh step, round 11).

Depth-capped CLI checks with ``--delta-matmul`` (successor generation
as the group scatter-as-matmul) vs ``--no-delta-matmul`` (the
per-family kernel path) must land on IDENTICAL counts — for the raft
small config AND for the stock paxos model, whose four families run
the delta path with zero hand-written kernels.  Exercises the
end-to-end flag wiring (CLI → engine → Expander) plus the stats mode
flags (delta_matmul 1/0).

Sub-minute on CPU; the full-space duplicates live in
tests/test_delta_matmul.py.  Exits 0 on identity, 1 with a message on
any divergence.
"""

import json
import os
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def fail(msg):
    print(f"delta_smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def run_one(spec_args, flag, stats_path):
    cmd = [sys.executable, "-m", "raft_tla_tpu", "check"] + \
        spec_args + [flag, "--stats-json", stats_path]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(cmd, env=env, cwd=_REPO,
                          capture_output=True, text=True)
    if proc.returncode != 0:
        fail(f"check {' '.join(spec_args[:1])} {flag} failed "
             f"rc={proc.returncode}:\n{proc.stderr}")
    with open(stats_path) as fh:
        return json.load(fh)


def ab(name, spec_args, td):
    on = run_one(spec_args, "--delta-matmul",
                 os.path.join(td, f"{name}_on.json"))
    off = run_one(spec_args, "--no-delta-matmul",
                  os.path.join(td, f"{name}_off.json"))
    if on.get("delta_matmul") != 1 or off.get("delta_matmul") != 0:
        fail(f"{name}: mode flags wrong: on={on.get('delta_matmul')} "
             f"off={off.get('delta_matmul')} — the CLI flag did not "
             "reach the engine")
    for key in ("distinct_states", "generated_states", "depth",
                "dedup_hit_rate", "violations"):
        if on[key] != off[key]:
            fail(f"{name} {key}: delta-matmul {on[key]} != kernel "
                 f"path {off[key]} — the delta path diverged")
    print(f"delta_smoke: {name} ON ≡ OFF at depth {on['depth']} "
          f"({on['distinct_states']} states)")


def main():
    with tempfile.TemporaryDirectory(prefix="delta_smoke_") as td:
        ab("raft", [
            os.path.join(_REPO, "configs", "tlc_membership",
                         "raft.cfg"),
            "--servers", "2", "--init-servers", "2",
            "--max-log-length", "1", "--max-timeouts", "1",
            "--max-client-requests", "1", "--max-depth", "6"], td)
        ab("paxos", ["--spec", "paxos", "--max-depth", "6"], td)
    print("delta_smoke: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
