"""Guard-matmul CI smoke (tools/ci_smoke.sh step, round 9).

Two tiny CLI checks over the repo-local small config — the default
``--guard-matmul`` (MXU path: guard grid as int8 matmul + one-hot
successor einsum) and ``--no-guard-matmul`` (the historical vmapped
lane sweep) — must land on IDENTICAL counts: distinct, generated,
depth, dedup rate.  Exercises the end-to-end flag wiring (CLI →
engine → Expander) plus the stats mode flags (guard_matmul 1/0).

Depth-capped so the pair stays sub-minute on CPU; the full-space
duplicates live in tests/test_guard_matmul.py.  Exits 0 on identity,
1 with a message on any divergence.
"""

import json
import os
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def fail(msg):
    print(f"guard_matmul_smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def run_one(flag, stats_path):
    cmd = [
        sys.executable, "-m", "raft_tla_tpu", "check",
        os.path.join(_REPO, "configs", "tlc_membership", "raft.cfg"),
        "--servers", "2", "--init-servers", "2",
        "--max-log-length", "1", "--max-timeouts", "1",
        "--max-client-requests", "1", "--max-depth", "6",
        flag, "--stats-json", stats_path,
    ]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(cmd, env=env, cwd=_REPO,
                          capture_output=True, text=True)
    if proc.returncode != 0:
        fail(f"check {flag} failed rc={proc.returncode}:\n"
             f"{proc.stderr}")
    return json.load(open(stats_path))


def main():
    td = tempfile.mkdtemp(prefix="guard_matmul_smoke_")
    on = run_one("--guard-matmul", os.path.join(td, "on.json"))
    off = run_one("--no-guard-matmul", os.path.join(td, "off.json"))
    if on.get("guard_matmul") != 1 or off.get("guard_matmul") != 0:
        fail(f"mode flags wrong: on={on.get('guard_matmul')} "
             f"off={off.get('guard_matmul')} — the CLI flag did not "
             "reach the engine")
    for key in ("distinct_states", "generated_states", "depth",
                "dedup_hit_rate", "violations"):
        if on[key] != off[key]:
            fail(f"{key}: guard-matmul {on[key]} != lane path "
                 f"{off[key]} — the MXU path diverged")
    print(f"guard_matmul_smoke: ok — ON ≡ OFF at depth {on['depth']} "
          f"({on['distinct_states']} states) ({td})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
