"""Measure BASELINE.md configs #1-#5: native CPU checker (the machine-
measured TLC stand-in) vs the TPU engine, same counting semantics.

Usage:  python tools/measure_baseline.py [config_no ...]

Writes one JSON file per config under baseline_runs/ so the BASELINE.md
table can be filled incrementally; reruns overwrite.  Budgets keep every
run minutes-scale: configs whose spaces exceed the budget are recorded
with exhausted=false and the rate still holds (level-granular budget,
identical on both engines).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "baseline_runs")
os.makedirs(OUT, exist_ok=True)

TLC_CFG = "/root/reference/tlc_membership/raft.cfg"
APA_CFG = "/root/reference/apalache_no_membership/raft.cfg"


def build_cfg(n):
    from raft_tla_tpu.cfg.parser import load_model
    from raft_tla_tpu.config import NEXT_DYNAMIC, Bounds
    if n == 1:
        # Server=3, MaxTerm=2, MaxLogLen=2 (BASELINE.json config #1)
        return load_model(TLC_CFG, bounds=Bounds.make(
            max_log_length=2, max_timeouts=1, max_client_requests=3))
    if n == 2:
        # headline metric config (bench.py)
        cfg = load_model(TLC_CFG, bounds=Bounds.make(
            max_log_length=3, max_timeouts=2, max_client_requests=3))
        return cfg.with_(invariants=("ElectionSafety",))
    if n == 3:
        # membership workload: Server=4 ⊋ InitServer=3, NextDynamic,
        # + the invariant BASELINE.json names (authored by us — the
        # reference has no such operator, SURVEY preamble)
        cfg = load_model(TLC_CFG, bounds=Bounds.make(
            max_log_length=2, max_timeouts=1, max_client_requests=2,
            max_membership_changes=1))
        return cfg.with_(
            n_servers=4, init_servers=(0, 1, 2),
            next_family=NEXT_DYNAMIC,
            invariants=tuple(cfg.invariants) +
            ("OneAtATimeMembershipChangeOK",))
    if n == 4:
        # apalache_no_membership variant, bounded k=10 as BFS depth
        return load_model(APA_CFG)
    if n == 5:
        # Server=5, MaxTerm=4, MaxLogLen=4, scenario property hunt
        cfg = load_model(TLC_CFG, bounds=Bounds.make(
            max_log_length=4, max_timeouts=3, max_client_requests=3))
        return cfg.with_(n_servers=5, init_servers=(0, 1, 2, 3, 4),
                         invariants=("ConcurrentLeaders",))
    raise SystemExit(f"unknown config {n}")


# budgets keep runs minutes-scale and inside single-chip HBM for the
# engine's level buffers (levels near the budget must fit LCAP without
# growth: a growth's transient old+new buffers are what OOM a chip);
# equal budgets on both engines keep the differential count check
# meaningful even when not exhaustive
BUDGET = {1: 2_000_000, 2: 2_400_000, 3: 1_500_000, 4: 10**9,
          5: 600_000}
DEPTH = {4: 10}
ENGINE_KW = {
    # ocap=2^14 on the S=3 configs: the early nearly-all-fresh levels
    # outgrow the chunk*4 fresh-row default (growth = replay the level)
    1: dict(chunk=2048, lcap=1 << 21, vcap=1 << 24, ocap=1 << 14),
    2: dict(chunk=2048, lcap=1 << 21, vcap=1 << 24, ocap=1 << 14),
    # fcap/ocap/fam_caps pre-sized from measured per-family enabled
    # maxima (tools/tune_config3.py famx_max + 25% headroom): the
    # membership model averages ~20 enabled lanes/parent and its early
    # levels are nearly all-fresh, so the density-table defaults both
    # under-size (mid-run growth = ~100s replay+recompile) and
    # over-size (every phase pays the buffer width) — measured
    # 18.2k -> 31.2k states/s round-over-round on this config
    # lcap=2^23 pre-sizes for depth 17's 2.14M-state level: at 2^21 the
    # first rep pays a grow+recompile+replay (~200s) that the median
    # then hides — measured 12.3k/s rep-1 vs 85.6k/s steady-state
    3: dict(chunk=2048, lcap=1 << 23, vcap=1 << 24, fcap=45056,
            ocap=1 << 14,
            fam_caps=(3584, 512, 3584, 2048, 3072, 2560, 1024, 8192,
                      4608, 8192, 7680, 7680, 2048, 3072)),
    4: dict(chunk=1024, lcap=1 << 17, vcap=1 << 20),
    5: dict(chunk=512, lcap=1 << 20, vcap=1 << 23),
}


from statistics import median as _median


def measure(n, reps=3):
    """Interleaved A/B protocol (VERDICT r4 #7): the recorded ratio is
    median(native)/median(engine) over `reps` alternating same-process
    runs (native, TPU, native, TPU, ...) — the shared single-vCPU host
    measured the SAME native binary at 24k-150k/s across different
    days, so single runs hours apart are not comparable."""
    from raft_tla_tpu import native
    from raft_tla_tpu.engine.bfs import Engine
    cfg = build_cfg(n)
    budget = BUDGET[n]
    depth = DEPTH.get(n, 10**9)
    out = {"config": n, "budget": budget, "max_depth": depth,
           "protocol": f"interleaved median-of-{reps} (same process)"}

    # config 5's target is a scenario property (negated reachability);
    # the native runtime checks safety invariants only, so its rate is
    # measured on the bare state space there
    nat_cfg = cfg.with_(invariants=()) if n == 5 else cfg
    kw = dict(ENGINE_KW[n])
    fam_caps = kw.pop("fam_caps", None)
    eng = Engine(cfg, store_states=False, **kw)
    if fam_caps is not None:
        eng.FAM_CAPS = tuple(fam_caps)
    t0 = time.time()
    eng.check(max_depth=min(2, depth))          # warm the jit caches
    compile_s = time.time() - t0

    nat_rates, eng_rates = [], []
    nat = r = None
    for rep in range(max(1, int(reps))):
        nat = native.check(nat_cfg, threads=os.cpu_count() or 1,
                           max_states=budget, max_depth=depth)
        nat_rates.append(round(nat.states_per_sec, 1))
        t0 = time.time()
        r = eng.check(max_states=budget, max_depth=depth)
        secs = time.time() - t0
        eng_rates.append(round(r.distinct_states / max(secs, 1e-9), 1))
        print(f"config {n} rep {rep}: native {nat_rates[-1]}/s  "
              f"engine {eng_rates[-1]}/s", flush=True)
        # identical counts EVERY rep, not just the last
        assert (r.distinct_states == nat.distinct_states
                or n == 5), (r.distinct_states, nat.distinct_states)

    # both `seconds` fields are MEDIAN-DERIVED (distinct/median rate)
    # so they stay comparable to each other and to states_per_sec; the
    # raw per-rep rates ride in rates[]
    out["native"] = {
        "distinct": int(nat.distinct_states), "depth": int(nat.depth),
        "seconds": round(nat.distinct_states /
                         max(_median(nat_rates), 1e-9), 2),
        "states_per_sec": _median(nat_rates),
        "rates": nat_rates,
        "violations": len(nat.violations),
        "exhausted": bool(nat.distinct_states < budget),
    }
    out["engine"] = {
        "distinct": int(r.distinct_states), "depth": int(r.depth),
        "seconds": round(r.distinct_states / max(_median(eng_rates),
                                                 1e-9), 2),
        "states_per_sec": _median(eng_rates),
        "rates": eng_rates,
        "compile_seconds": round(compile_s, 1),
        "violations": len(r.violations),
        "overflow_faults": int(r.overflow_faults),
        "exhausted": bool(r.distinct_states < budget),
    }
    out["counts_match"] = (
        out["native"]["distinct"] == out["engine"]["distinct"]
        and out["native"]["depth"] == out["engine"]["depth"])
    out["speedup"] = round(out["engine"]["states_per_sec"] /
                           max(out["native"]["states_per_sec"], 1e-9), 2)
    # per-config perf floor (VERDICT r4 #6): the canonical budgeted run
    # checks + ratchets its BENCH_FLOOR row like bench.py's headline
    import jax

    from bench import perf_floor
    floor_info, _zero = perf_floor(
        out["engine"]["states_per_sec"], 0,
        str(jax.devices()[0].device_kind),
        os.path.join(os.path.dirname(OUT), "BENCH_FLOOR.json"),
        gate_ok=out["counts_match"], allow_bump=True,
        key=f"config{n}_budgeted", headline_depth=0,
        bump_source=f"measure_baseline.py config {n} auto-bump")
    out["engine"]["perf_floor"] = floor_info
    print(f"config {n} engine: {out['engine']} "
          f"match={out['counts_match']} speedup={out['speedup']}",
          flush=True)
    with open(os.path.join(OUT, f"config{n}.json"), "w") as f:
        json.dump(out, f, indent=1)
    return out


if __name__ == "__main__":
    args = sys.argv[1:]
    reps = 3
    if "--reps" in args:
        i = args.index("--reps")
        reps = int(args[i + 1])
        del args[i:i + 2]
    if len(args) == 1:
        try:
            measure(int(args[0]), reps=reps)
        except Exception as e:
            print(f"config {args[0]} FAILED: {type(e).__name__}: {e}",
                  flush=True)
            raise SystemExit(1)
    else:
        # one subprocess per config: a failed/OOM'd engine run must not
        # pin HBM (exception tracebacks keep carry buffers alive) or
        # poison later configs
        import subprocess
        for n in [int(a) for a in args] or [1, 2, 3, 4, 5]:
            subprocess.run([sys.executable, os.path.abspath(__file__),
                            str(n), "--reps", str(reps)])
