"""Worker process for tests/test_multihost.py: one controller in a
multi-controller CPU run (gloo collectives = the DCN stand-in).

Usage: python tools/multihost_worker.py <pid> <nproc> <port> [opts-json]
opts (all optional): {"checkpoint": path, "resume": path,
                      "max_depth": int, "lcap": int, "vcap": int,
                      "scap": int, "chunk_mult": int,
                      "invariants": [names], "trace_dir": path,
                      "trace_gid": int, "stop_on_violation": bool}
trace_gid replays one witness chain from the merged archives at run
end (the store_states × checkpoint differential reads it on a resumed
run).
trace_dir enables store_states: each controller writes its archive
shard and the violation-finding controller replays the full witness
trace across the merged per-controller files (multihost_engine).
Caller must set XLA_FLAGS=--xla_force_host_platform_device_count=N and
JAX_PLATFORMS=cpu in the environment BEFORE the interpreter starts.
Tiny lcap/scap force mid-run capacity growth — exercised by the growth
test (every controller takes the identical growth branch from the
replicated scal matrix).
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
opts = json.loads(sys.argv[4]) if len(sys.argv) > 4 else {}

import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")

from raft_tla_tpu.parallel.multihost import init_distributed  # noqa: E402

init_distributed(f"127.0.0.1:{port}", num_processes=nproc,
                 process_id=pid)

# AFTER distributed init: importing the engine initializes XLA
from raft_tla_tpu.parallel.multihost import MultiHostEngine  # noqa: E402

from raft_tla_tpu.config import NEXT_ASYNC, Bounds, ModelConfig  # noqa: E402

cfg = ModelConfig(
    n_servers=2, init_servers=(0, 1), values=(1,),
    next_family=NEXT_ASYNC, symmetry=True, max_inflight_override=4,
    invariants=tuple(opts.get("invariants", ())),
    bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                       max_client_requests=1))

D = len(jax.devices())
trace_dir = opts.get("trace_dir")
eng = MultiHostEngine(cfg, chunk=opts.get("chunk_mult", 4) * D,
                      lcap=opts.get("lcap", 1 << 12),
                      vcap=opts.get("vcap", 1 << 15),
                      scap=opts.get("scap"),
                      store_states=trace_dir is not None,
                      trace_dir=trace_dir)
r = eng.check(max_depth=opts.get("max_depth", 10 ** 9),
              checkpoint_path=opts.get("checkpoint"),
              resume_from=opts.get("resume"),
              stop_on_violation=opts.get("stop_on_violation", False))
traces = []
if trace_dir and opts.get("trace_gid") is not None:
    traces.append([lbl for lbl, _ in eng.trace(int(opts["trace_gid"]))])
if trace_dir and r.violations:
    # mesh-scale witness reconstruction: the controller that holds the
    # violating shard replays the parent chain across every
    # controller's archive file (no single-host re-run)
    for v in r.violations[:2]:
        traces.append([lbl for lbl, _ in eng.trace(v.state_id)])
print("RESULT " + json.dumps(dict(
    pid=pid, n_devices=D,
    distinct=int(r.distinct_states), depth=int(r.depth),
    generated=int(r.generated_states),
    violations=int(r.violations_global),
    # shard-local decoded violating states: a mesh-scale hit is
    # actionable without a single-host re-run (only the parent trace
    # needs one — multihost module docstring)
    viol_local=[[v.invariant, str(v.state)]
                for v in r.violations[:3]],
    traces=traces,
    final_caps=[int(eng.LB), int(eng.SC), int(eng.FC)])),
    flush=True)
