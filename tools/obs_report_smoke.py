"""Run-registry / regression-verdict smoke validation (ISSUE 17;
tools/ci_smoke.sh step).

Three tiny CLI check runs record into one ``--registry`` directory:
two identical (A, B) and one with an injected depth-gate mismatch (C,
``--max-depth 3`` vs 6).  Then the query surface is validated end to
end:

- ``cli obs diff A B`` emits a machine-readable ``verdict: clean``
  (count + level-size parity, no mode-flag drift) and exits 0;
- ``cli obs diff A C`` names the count mismatch and exits 1;
- ``cli obs regress B --against A`` exits 0 (the parity pair passes);
- ``cli obs regress C --against A`` exits 1 (the injected mismatch is
  CAUGHT — the acceptance contract: a regression gate that cannot
  fail is not a gate);
- both parity runs' registry records carry the resource telemetry
  (host RSS peak, compile seconds; device memory only where the
  backend reports it — XLA:CPU does not) and the backend fingerprint,
  and the ledger/heartbeat artifacts cross-link by the same run id.

Exits 0 on success, 1 with a message on any violation.  CPU-only and
reference-free (repo-local configs/ twin), like the other smokes.
"""

import json
import os
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def fail(msg):
    print(f"obs_report_smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def run_cli(args, env):
    proc = subprocess.run([sys.executable, "-m", "raft_tla_tpu"]
                          + args, env=env, cwd=_REPO,
                          capture_output=True, text=True)
    return proc


def check_run(reg, td, tag, max_depth, env):
    """One tiny CLI check into the registry; returns its new run id
    (the registry file that appeared)."""
    before = set(os.listdir(reg)) if os.path.isdir(reg) else set()
    proc = run_cli([
        "check",
        os.path.join(_REPO, "configs", "tlc_membership", "raft.cfg"),
        "--servers", "2", "--init-servers", "2",
        "--max-log-length", "1", "--max-timeouts", "1",
        "--max-client-requests", "1", "--max-depth", str(max_depth),
        "--registry", reg,
        "--ledger", os.path.join(td, f"{tag}.jsonl"),
        "--heartbeat", os.path.join(td, f"{tag}.hb.json"),
    ], env)
    if proc.returncode != 0:
        fail(f"check run {tag} failed rc={proc.returncode}:\n"
             f"{proc.stderr}")
    new = [n for n in set(os.listdir(reg)) - before
           if n.endswith(".json")]
    if len(new) != 1:
        fail(f"run {tag}: expected exactly one new registry record, "
             f"got {sorted(new)}")
    return new[0][:-len(".json")]


def main():
    td = tempfile.mkdtemp(prefix="obs_report_smoke_")
    reg = os.path.join(td, "registry")
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    a = check_run(reg, td, "a", 6, env)
    b = check_run(reg, td, "b", 6, env)
    c = check_run(reg, td, "c", 3, env)   # injected depth-gate drift

    # -- diff: parity pair clean, gated pair a named mismatch -----------
    proc = run_cli(["obs", "diff", "--registry", reg, a, b], env)
    if proc.returncode != 0:
        fail(f"diff A B rc={proc.returncode} (want 0):\n{proc.stderr}")
    rep = json.loads(proc.stdout)
    if rep.get("verdict") != "clean":
        fail(f"diff A B verdict {rep.get('verdict')!r} != 'clean': "
             f"{rep.get('parity')}")
    if rep.get("mode_drift"):
        fail(f"identical runs report mode drift: {rep['mode_drift']}")

    proc = run_cli(["obs", "diff", "--registry", reg, a, c], env)
    if proc.returncode != 1:
        fail(f"diff A C rc={proc.returncode} (want 1 — the depth-"
             f"gated run counts fewer states):\n{proc.stdout}")
    rep = json.loads(proc.stdout)
    if rep.get("verdict") != "mismatch":
        fail(f"diff A C verdict {rep.get('verdict')!r} != 'mismatch'")
    ds = rep.get("parity", {}).get("counts", {}).get("distinct_states")
    if not ds or ds.get("equal"):
        fail(f"diff A C does not name the distinct_states mismatch: "
             f"{rep.get('parity')}")

    # -- regress: the parity pair passes, the injected mismatch trips ---
    proc = run_cli(["obs", "regress", "--registry", reg, b,
                    "--against", a], env)
    if proc.returncode != 0:
        fail(f"regress B vs A rc={proc.returncode} (want 0):\n"
             f"{proc.stdout}\n{proc.stderr}")
    proc = run_cli(["obs", "regress", "--registry", reg, c,
                    "--against", a], env)
    if proc.returncode != 1:
        fail(f"regress C vs A rc={proc.returncode} (want 1 — the "
             f"gate must CATCH the injected mismatch):\n{proc.stdout}")
    rep = json.loads(proc.stdout)
    if not any("mismatch" in f for f in rep.get("failures", [])):
        fail(f"regress C vs A names no mismatch: {rep}")

    # -- resource + identity fields on the parity records ---------------
    for tag, rid in (("a", a), ("b", b)):
        rec = json.load(open(os.path.join(reg, rid + ".json")))
        res = rec.get("resources") or {}
        if not res.get("rss_peak_bytes", 0) > 0:
            fail(f"run {tag}: no host RSS peak in resources: {res}")
        if "compile_seconds" not in res:
            fail(f"run {tag}: no compile_seconds in resources: {res}")
        # device memory appears only where the backend reports it
        # (XLA:CPU does not) — present means positive, absent is fine
        if "device_peak_bytes_in_use" in res \
                and not res["device_peak_bytes_in_use"] > 0:
            fail(f"run {tag}: zero device peak reported: {res}")
        if not (rec.get("backend") or {}).get("platform"):
            fail(f"run {tag}: no backend fingerprint: {rec.get('backend')}")
        # artifacts cross-link by run id: every ledger row and the
        # heartbeat carry the record's id
        rows = [json.loads(x)
                for x in open(os.path.join(td, f"{tag}.jsonl"))]
        if not rows or any(r.get("run_id") != rid for r in rows):
            fail(f"run {tag}: ledger rows not stamped with {rid}")
        seqs = [r.get("seq") for r in rows]
        if seqs != sorted(seqs) or len(set(seqs)) != len(seqs):
            fail(f"run {tag}: ledger seq not strictly increasing: "
                 f"{seqs}")
        hb = json.load(open(os.path.join(td, f"{tag}.hb.json")))
        if hb.get("run_id") != rid:
            fail(f"run {tag}: heartbeat run_id {hb.get('run_id')} != "
                 f"{rid}")

    print(f"obs_report_smoke: ok — parity pair clean, injected "
          f"depth-gate mismatch caught by diff(rc 1) and regress"
          f"(rc 1), resource + identity fields present ({td})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
