"""Ledger/heartbeat/timeline smoke validation (tools/ci_smoke.sh step).

Runs one tiny CLI check with the full observability surface on —
``--ledger --heartbeat --trace-timeline --stats-json`` — then
validates the artifacts against the contracts the obs layer promises:

- the JSONL ledger parses line-by-line, has >= 1 record per burst
  dispatch (every committing burst writes one; a first-level bail is
  immediately followed by a per-level record, so total records >=
  burst_dispatches), and its final record's burst counters equal the
  --stats-json ones (the registry is the single source — any split
  would be the levels_fused drift class);
- the Chrome-trace timeline satisfies the catapult trace_event schema
  Perfetto validates: every event has ph/ts/dur/name, ph == "X",
  no negative timestamps or durations, and events on one (pid, tid)
  nest properly (no partial overlap — every inner span closed inside
  its enclosing span);
- the heartbeat's final depth equals the run's reported depth and its
  status is "finished".

Exits 0 on success, 1 with a message on any violation.  CPU-only and
reference-free (uses the repo-local configs/ twin), so it runs in
every container ci_smoke.sh runs in.
"""

import json
import os
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def fail(msg):
    print(f"obs_smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def load_trace_events(path):
    """Parse a (possibly unclosed — killed-run) trace-event array."""
    text = open(path).read().strip()
    if not text.startswith("["):
        fail(f"{path}: not a JSON array")
    if not text.endswith("]"):
        text = text.rstrip().rstrip(",") + "\n]"
    try:
        return json.loads(text)
    except ValueError as e:
        fail(f"{path}: trace JSON does not parse: {e}")


def validate_spans(events):
    """catapult trace_event schema + proper nesting."""
    if not events:
        fail("timeline has no span events")
    for ev in events:
        for key in ("ph", "ts", "dur", "name"):
            if key not in ev:
                fail(f"trace event missing {key!r}: {ev}")
        if ev["ph"] != "X":
            fail(f"unexpected phase {ev['ph']!r} (complete events "
                 f"only): {ev}")
        if ev["ts"] < 0 or ev["dur"] < 0:
            fail(f"negative ts/dur (non-monotonic clock?): {ev}")
    # nesting: on each (pid, tid) track, sorted by start (ties: longer
    # first — the enclosing span), every span must close before the
    # enclosing one does; a partial overlap means an unmatched
    # begin/end pair
    by_track = {}
    for ev in events:
        by_track.setdefault((ev.get("pid"), ev.get("tid")),
                            []).append(ev)
    eps = 1.0   # us — perf_counter rounding slack
    for track, evs in by_track.items():
        evs.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack = []
        for ev in evs:
            end = ev["ts"] + ev["dur"]
            while stack and ev["ts"] >= stack[-1] - eps:
                stack.pop()
            if stack and end > stack[-1] + eps:
                fail(f"span {ev['name']!r} [{ev['ts']}, {end}] "
                     f"overlaps its enclosing span's end "
                     f"{stack[-1]} on track {track} — unmatched "
                     f"start/end")
            stack.append(end)


def main():
    td = tempfile.mkdtemp(prefix="obs_smoke_")
    ledger = os.path.join(td, "run.jsonl")
    hb = os.path.join(td, "hb.json")
    tl = os.path.join(td, "timeline.json")
    stats = os.path.join(td, "stats.json")
    cmd = [
        sys.executable, "-m", "raft_tla_tpu", "check",
        os.path.join(_REPO, "configs", "tlc_membership", "raft.cfg"),
        "--servers", "2", "--init-servers", "2",
        "--max-log-length", "1", "--max-timeouts", "1",
        "--max-client-requests", "1", "--max-depth", "6",
        "--ledger", ledger, "--heartbeat", hb,
        "--trace-timeline", tl, "--stats-json", stats,
    ]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(cmd, env=env, cwd=_REPO,
                          capture_output=True, text=True)
    if proc.returncode != 0:
        fail(f"check run failed rc={proc.returncode}:\n{proc.stderr}")

    st = json.load(open(stats))

    # -- ledger ---------------------------------------------------------
    recs = []
    for i, line in enumerate(open(ledger)):
        try:
            recs.append(json.loads(line))
        except ValueError as e:
            fail(f"ledger line {i + 1} does not parse: {e}")
    if not recs:
        fail("ledger is empty")
    if len(recs) < st["burst_dispatches"]:
        fail(f"{len(recs)} ledger records < {st['burst_dispatches']} "
             "burst dispatches — a dispatch wrote no record")
    last = recs[-1]
    for key in ("levels_fused", "burst_dispatches", "burst_bailouts",
                "distinct_states", "generated_states"):
        if last.get(key) != st[key]:
            fail(f"ledger final record {key}={last.get(key)} != "
                 f"--stats-json {key}={st[key]} — the registry split")
    for key in ("kind", "depth", "frontier", "rss_bytes", "ts"):
        if key not in last:
            fail(f"ledger record missing {key!r}: {last}")

    # -- timeline -------------------------------------------------------
    validate_spans(load_trace_events(tl))

    # -- heartbeat ------------------------------------------------------
    hb_obj = json.load(open(hb))
    if hb_obj.get("depth") != st["depth"]:
        fail(f"heartbeat depth {hb_obj.get('depth')} != run depth "
             f"{st['depth']}")
    if hb_obj.get("status") != "finished":
        fail(f"heartbeat status {hb_obj.get('status')!r} != "
             "'finished'")
    if hb_obj.get("states_enqueued") != st["distinct_states"]:
        fail(f"heartbeat states {hb_obj.get('states_enqueued')} != "
             f"{st['distinct_states']}")

    print(f"obs_smoke: ok — {len(recs)} ledger records, depth "
          f"{st['depth']}, {st['distinct_states']} states, "
          f"heartbeat+timeline consistent ({td})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
