"""Paxos-frontend CI smoke (tools/ci_smoke.sh step, round 10).

One depth-capped CLI check of the stock Paxos model (``--spec paxos``,
reference-less, CPU) pinned against the plain-Python oracle computed
in-process: distinct / generated / depth / violations must match
bit-for-bit, the stats must stamp the spec name + IR fingerprint, and
the engine-layer import gate must hold (``raft_tla_tpu/engine`` and
``raft_tla_tpu/parallel`` never import ``models.raft`` directly — the
grep-gate satellite of the SpecIR refactor, enforced here so a
regression fails CI before any engine change lands).

Exits 0 on identity, 1 with a message on any divergence.
"""

import json
import os
import re
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

DEPTH = 8


def fail(msg):
    print(f"paxos_smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def import_gate():
    """Spec-agnostic engine layer: no direct models.raft imports."""
    pat = re.compile(r"models\s*\.\s*raft|models\s+import\s+raft|"
                     r"models\.raft\s+import")
    bad = []
    for sub in ("engine", "parallel", "sim"):
        root = os.path.join(_REPO, "raft_tla_tpu", sub)
        for dirp, _dirs, files in os.walk(root):
            for f in files:
                if not f.endswith(".py"):
                    continue
                path = os.path.join(dirp, f)
                for ln, line in enumerate(open(path), 1):
                    if pat.search(line):
                        bad.append(f"{path}:{ln}: {line.strip()}")
    if bad:
        fail("engine layer imports models.raft directly again "
             "(route through the SpecIR handle):\n" + "\n".join(bad))


def main():
    import_gate()
    td = tempfile.mkdtemp(prefix="paxos_smoke_")
    stats_path = os.path.join(td, "paxos.json")
    cmd = [sys.executable, "-m", "raft_tla_tpu", "check",
           "--spec", "paxos", "--max-depth", str(DEPTH),
           "--chunk", "128", "--no-store",
           "--stats-json", stats_path]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(cmd, env=env, cwd=_REPO,
                          capture_output=True, text=True)
    if proc.returncode != 0:
        fail(f"check --spec paxos failed rc={proc.returncode}:\n"
             f"{proc.stderr}")
    got = json.load(open(stats_path))
    if got.get("spec") != "paxos" or not got.get("ir_fingerprint"):
        fail(f"stats not spec-stamped: spec={got.get('spec')!r} "
             f"ir_fingerprint={got.get('ir_fingerprint')!r}")
    from raft_tla_tpu.spec.paxos.config import PaxosConfig
    from raft_tla_tpu.spec.paxos.oracle import explore
    ro = explore(PaxosConfig(), max_depth=DEPTH)
    for key, want in (("distinct_states", ro.distinct_states),
                      ("generated_states", ro.generated_states),
                      ("depth", ro.depth),
                      ("violations", len(ro.violations))):
        if got[key] != want:
            fail(f"{key}: engine {got[key]} != oracle {want}")
    print(f"paxos_smoke: ok — engine ≡ oracle at depth {DEPTH} "
          f"({got['distinct_states']} distinct, spec-stamped "
          f"{got['ir_fingerprint']}), engine-layer import gate clean")


if __name__ == "__main__":
    main()
