"""Pjit-engine CI smoke (tools/ci_smoke.sh step, round 14).

A depth-capped CLI ``check --pjit`` (the whole BFS state under named
shardings — parallel/pjit_mesh) must land on IDENTICAL counts to the
default single-device engine: same program, different partitioning, so
this is reference-less A/B parity, no oracle.  Exercises the
end-to-end flag wiring (CLI → PjitShardedEngine) on whatever devices
the container has (CPU: jax's host platform; the mesh is however many
devices XLA exposes — 1 is a valid degenerate mesh and still runs the
pjit program).

Sub-minute on CPU; the 8-virtual-device and 2-controller reps live in
tests/test_pjit.py.  Exits 0 on identity, 1 with a message.
"""

import json
import os
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SPEC = [os.path.join(_REPO, "configs", "tlc_membership", "raft.cfg"),
        "--servers", "2", "--init-servers", "2",
        "--max-log-length", "1", "--max-timeouts", "1",
        "--max-client-requests", "1", "--max-depth", "6"]


def fail(msg):
    print(f"pjit_smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def run_one(extra, stats_path):
    cmd = [sys.executable, "-m", "raft_tla_tpu", "check"] + SPEC + \
        extra + ["--stats-json", stats_path]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(cmd, env=env, cwd=_REPO,
                          capture_output=True, text=True)
    if proc.returncode != 0:
        fail(f"check {' '.join(extra)} failed rc={proc.returncode}:\n"
             f"{proc.stderr}")
    with open(stats_path) as fh:
        return json.load(fh)


def main():
    with tempfile.TemporaryDirectory(prefix="pjit_smoke_") as td:
        ref = run_one([], os.path.join(td, "ref.json"))
        pj = run_one(["--pjit"], os.path.join(td, "pjit.json"))
        for key in ("distinct_states", "generated_states", "depth",
                    "dedup_hit_rate", "violations"):
            if ref[key] != pj[key]:
                fail(f"{key}: pjit {pj[key]} != default engine "
                     f"{ref[key]} — the sharded program diverged")
        print(f"pjit_smoke: --pjit ≡ default at depth {pj['depth']} "
              f"({pj['distinct_states']} states)")
    print("pjit_smoke: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
