"""Worker process for tests/test_pjit.py: one controller of a
multi-controller PjitShardedEngine run (2 procs × N virtual CPU
devices, gloo collectives = the DCN stand-in).  The whole BFS state
lives under NamedShardings on the process-spanning mesh; both
controllers must land on the oracle's exact counts — and on
bit-identical witness traces, since the pjit program IS the classic
engine's program.

Usage: python tools/pjit_worker.py <pid> <nproc> <port> [opts-json]
opts (all optional): {"max_depth": int, "chunk": int, "lcap": int,
                      "vcap": int, "invariants": [names],
                      "store_states": bool, "trace_gid": int,
                      "checkpoint": path, "resume": path,
                      "resume_portable": path,
                      "stop_on_violation": bool}
resume_portable — a checkpoint path loaded through
resil.portable.load_portable_image and re-partitioned onto this mesh
(the round-12 contract: a mesh/classic checkpoint resumes at pod
shape).  Caller must set
XLA_FLAGS=--xla_force_host_platform_device_count=N and
JAX_PLATFORMS=cpu before the interpreter starts.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
opts = json.loads(sys.argv[4]) if len(sys.argv) > 4 else {}

import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")

from raft_tla_tpu.parallel.multihost import init_distributed  # noqa: E402

init_distributed(f"127.0.0.1:{port}", num_processes=nproc,
                 process_id=pid)

# AFTER distributed init: importing the engine initializes XLA
from raft_tla_tpu.parallel.pjit_mesh import PjitShardedEngine  # noqa: E402
from raft_tla_tpu.config import NEXT_ASYNC, Bounds, ModelConfig  # noqa: E402

cfg = ModelConfig(
    n_servers=2, init_servers=(0, 1), values=(1,),
    next_family=NEXT_ASYNC, symmetry=True, max_inflight_override=4,
    invariants=tuple(opts.get("invariants", ())),
    bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                       max_client_requests=1))

D = len(jax.devices())
store = bool(opts.get("store_states") or opts.get("trace_gid")
             is not None)
eng = PjitShardedEngine(cfg, chunk=opts.get("chunk", 16 * D),
                        lcap=opts.get("lcap", 1 << 12),
                        vcap=opts.get("vcap", 1 << 15),
                        store_states=store)
resume_image = None
if opts.get("resume_portable"):
    from raft_tla_tpu.resil.portable import load_portable_image
    resume_image = load_portable_image(opts["resume_portable"])
r = eng.check(max_depth=opts.get("max_depth", 10 ** 9),
              checkpoint_path=opts.get("checkpoint"),
              resume_from=opts.get("resume"),
              resume_image=resume_image,
              stop_on_violation=opts.get("stop_on_violation", False))
trace = None
if opts.get("trace_gid") is not None:
    # archives are controller-replicated under the pjit gather fns, so
    # EVERY controller can replay any witness chain
    trace = [lbl for lbl, _ in eng.trace(int(opts["trace_gid"]))]
print("RESULT " + json.dumps(dict(
    pid=pid, n_devices=D,
    distinct=int(r.distinct_states), depth=int(r.depth),
    generated=int(r.generated_states),
    level_sizes=[int(x) for x in r.level_sizes],
    violations=int(r.violations_global),
    trace=trace)), flush=True)
